"""Build the native XDR serializer (see native/cxdr.c).

    python setup.py build_ext --inplace

The framework runs without it (pure-Python codec fallback); building it
accelerates the serialization-bound replay path.
"""

from setuptools import Extension, setup

setup(
    name="stellar-core-tpu-native",
    version="2.0.0",
    ext_modules=[Extension(
        "stellar_core_tpu._cxdr",
        sources=["native/cxdr.c"],
        extra_compile_args=["-O2"],
    )],
)
