"""Build the native extensions (see native/cxdr.c, native/cquorum.c).

    python setup.py build_ext --inplace

The framework runs without them (pure-Python fallbacks); building them
accelerates the serialization-bound replay path and the exact
quorum-intersection enumeration.
"""

from setuptools import Extension, setup

setup(
    name="stellar-core-tpu-native",
    version="2.0.0",
    ext_modules=[
        Extension(
            "stellar_core_tpu._cxdr",
            sources=["native/cxdr.c"],
            extra_compile_args=["-O2"],
        ),
        Extension(
            "stellar_core_tpu._cquorum",
            sources=["native/cquorum.c"],
            extra_compile_args=["-O2"],
        ),
        Extension(
            "stellar_core_tpu._capply",
            sources=["native/capply.c"],
            extra_compile_args=["-O2"],
        ),
    ],
)
