"""Build the native extensions (see native/cxdr.c, native/cquorum.c).

    python setup.py build_ext --inplace

The framework runs without them (pure-Python fallbacks); building them
accelerates the serialization-bound replay path and the exact
quorum-intersection enumeration.
"""

from setuptools import Extension, setup

# Default build is warning-clean under -Wall -Wextra (ISSUE 15) and must
# stay that way: the lint/CI path re-compiles with -Werror
# (`python -m stellar_core_tpu._native_build --warn-check`), so a new
# warning fails `make lint` while end-user builds keep plain warnings.
_CFLAGS = ["-O2", "-Wall", "-Wextra"]

setup(
    name="stellar-core-tpu-native",
    version="2.0.0",
    ext_modules=[
        Extension(
            "stellar_core_tpu._cxdr",
            sources=["native/cxdr.c"],
            extra_compile_args=_CFLAGS,
        ),
        Extension(
            "stellar_core_tpu._cquorum",
            sources=["native/cquorum.c"],
            extra_compile_args=_CFLAGS,
        ),
        Extension(
            "stellar_core_tpu._capply",
            sources=["native/capply.c"],
            extra_compile_args=_CFLAGS,
        ),
    ],
)
