"""bench.py — the BASELINE.json measurement matrix on the live chip.

Measures, in order (all on this host / the one visible TPU):
  #2  synthetic Ed25519 batch verify: TPU kernel vs single-core libsodium
  #1  catchup replay, libsodium CPU (ledgers/sec — the metric of record)
  #4  catchup replay, TPU SignatureChecker (identical hashes enforced)
  #3  tier-1-shaped quorum map intersection wall-clock (CPU exact checker)
  #5  adversarial quorum map on the TPU frontier enumerator

Prints ONE JSON line.  Headline: TPU replay ledgers/sec; vs_baseline is the
TPU-vs-CPU replay ratio (BASELINE.json's metric of record; the sub-metrics
ride in "extra").  Replay rates are steady-state: the accel path warms its
jit cache on a prefix replay first, like a long catchup amortizes compiles.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from stellar_core_tpu._native_build import ensure_native  # noqa: E402

ensure_native()


def _stage(msg):
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Global deadline (ISSUE 3 satellite): BENCH_r05 was killed by the driver
# budget mid-quorum-matrix (rc=124) and the JSON line never printed.  ONE
# wall-clock budget threads through every section: a section whose estimate
# no longer fits emits SKIPPED(budget) rows instead of running, and the
# final JSON line is ALWAYS written from whatever was measured (plus
# last-good cache for skipped accel sections).  The watchdog stays as the
# backstop for a section that wedges PAST its estimate.
# ---------------------------------------------------------------------------
_T0 = time.monotonic()
BENCH_BUDGET_S = float(os.environ.get("BENCH_DEADLINE_S", "2100"))


def time_left() -> float:
    return BENCH_BUDGET_S - (time.monotonic() - _T0)


def budget_fits(section: str, estimate_s: float) -> bool:
    """True when `section` still fits the global budget (1.25x slack on
    the estimate); logs the skip decision otherwise."""
    left = time_left()
    if left >= estimate_s * 1.25:
        return True
    _stage(f"SKIPPING '{section}' (needs ~{estimate_s:.0f}s, "
           f"{left:.0f}s of the {BENCH_BUDGET_S:.0f}s budget left)")
    return False


# ---------------------------------------------------------------------------
# Last-good result cache (VERDICT r3 weak #1): the shared tunnel has died
# mid-session twice, erasing a whole round's perf record at driver time.
# Every successful on-chip sub-result is persisted the moment it is
# measured; a degraded run emits the cached numbers with their age and a
# stale flag instead of bare zeros.
# ---------------------------------------------------------------------------
CACHE_PATH = os.environ.get("BENCH_CACHE_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_CACHE.json")


def _cache_load() -> dict:
    try:
        with open(CACHE_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _cache_put(section: str, values: dict, source: str = "bench.py on-chip run"):
    try:
        cache = _cache_load()
        cache[section] = {
            "measured_at_unix": round(time.time(), 1),
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "source": source,
            "values": values,
        }
        tmp = CACHE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(cache, f, indent=2)
        os.replace(tmp, CACHE_PATH)
        _stage(f"cached last-good '{section}' -> {CACHE_PATH}")
    except (OSError, TypeError, ValueError) as e:
        # a cache write must never fail a healthy bench (IO errors, or a
        # non-JSON-serializable value sneaking into a stats dict)
        _stage(f"cache write failed (non-fatal): {e}")


def _merge_last_good(section: str, values: dict) -> dict:
    """Per-ROW last-good: a section whose matrix mixes measured numbers
    with SKIPPED/FAILED marker strings must not cache a marker OVER a
    previously measured number — that would destroy exactly the value the
    stale-fill path exists to preserve (a later degraded run would emit
    the marker as the 'last-good' result).  The returned dict is what
    gets cached: this run's rows, with any skipped/failed row restored to
    the prior cached numeric value.  Restored rows keep honest
    provenance: `restored_rows` maps each such key to the timestamp of
    the run that actually MEASURED it (chained across runs), because the
    section-level measured_at will be re-stamped to this run."""
    got = _cache_load().get(section, {})
    prev = got.get("values", {})
    prev_restored = prev.get("restored_rows")
    if not isinstance(prev_restored, dict):
        prev_restored = {}
    out = dict(values)
    restored = {}
    for k, v in values.items():
        if isinstance(v, str) and (v.startswith("SKIPPED")
                                   or v.startswith("FAILED")) \
                and isinstance(prev.get(k), (int, float)):
            out[k] = prev[k]
            restored[k] = prev_restored.get(k, got.get("measured_at", "?"))
    if restored:
        out["restored_rows"] = restored
    return out


def _degraded_report(detail: str) -> dict:
    """Build the one-line JSON for a run that could not (fully) measure on
    chip: last-good cached numbers, each with its age, stale-flagged —
    never bare zeros while evidence exists."""
    cache = _cache_load()
    now = time.time()
    extra = {"accel_unavailable": True, "stale": True, "detail": detail}
    value = 0.0
    vs = 0.0
    sig = cache.get("sigs")
    if sig:
        value = sig["values"].get("ed25519_tpu_sigs_per_sec", 0.0)
        base = sig["values"].get("ed25519_libsodium_1core_sigs_per_sec", 0.0)
        vs = round(value / base, 2) if base else 0.0
    for section in ("sigs", "replay", "quorum", "bucketlistdb", "chaos",
                    "admission", "catchup_parallel", "catchup_mesh",
                    "native_close", "fleet", "sampleprof", "fleettrace",
                    "telemetry"):
        got = cache.get(section)
        if not got:
            continue
        extra.update({(f"{section}_{k}" if k == "note" else k): v
                      for k, v in got["values"].items()})
        extra[f"{section}_measured_at"] = got["measured_at"]
        extra[f"{section}_age_hours"] = round(
            (now - got["measured_at_unix"]) / 3600.0, 1)
        extra[f"{section}_source"] = got["source"]
    if not any(cache.get(s) for s in ("sigs", "replay", "quorum")):
        extra["detail"] += " (no BENCH_CACHE.json last-good entries exist)"
    return {
        "metric": "ed25519_batch_verify_throughput",
        "value": value,
        "unit": "sigs/s",
        "vs_baseline": vs,
        "extra": extra,
    }


def build_archive(nid, passphrase, path, n_payment_ledgers=110,
                  txs_per_ledger=40, multisig_every=4):
    """Synthetic pubnet-shaped history: account creation burst, then
    payment traffic with a multisig slice (extra signers on every 4th
    account, double-signed txs)."""
    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.history.archive import FileHistoryArchive
    from stellar_core_tpu.history.manager import HistoryManager
    from stellar_core_tpu.ledger.manager import LedgerManager
    from stellar_core_tpu.testutils import (TestAccount, build_tx,
                                            create_account_op,
                                            native_payment_op)
    import random

    mgr = LedgerManager(nid, invariant_manager=None)
    mgr.start_new_ledger()
    archive = FileHistoryArchive(path)
    history = HistoryManager(mgr, passphrase, [archive])
    rng = random.Random(11)

    root_sk = mgr.root_account_secret()
    e = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
        accountID=X.AccountID.ed25519(root_sk.public_key.ed25519))).to_xdr())
    root = TestAccount(mgr, root_sk, e.data.value.seqNum)
    ct = [1_600_000_000]

    def close(frames):
        ct[0] += 5
        history.ledger_closed(mgr.close_ledger(frames, ct[0]))

    n_accounts = 120
    sks = [SecretKey(bytes([1 + (i % 250)]) * 31 + bytes([i // 250]))
           for i in range(n_accounts)]
    for start in range(0, n_accounts, 50):
        ops = [create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10**12)
            for sk in sks[start:start + 50]]
        close([root.tx(ops)])
    accounts = []
    extras = {}
    setopts = []
    for i, sk in enumerate(sks):
        entry = mgr.root.get_entry(X.LedgerKey.account(X.LedgerKeyAccount(
            accountID=X.AccountID.ed25519(sk.public_key.ed25519))).to_xdr())
        acct = TestAccount(mgr, sk, entry.data.value.seqNum)
        accounts.append(acct)
        if i % multisig_every == 0:
            extra = SecretKey(bytes([200 + (i % 50)]) * 31 + bytes([i // 50]))
            extras[i] = extra
            setopts.append(acct.tx([X.Operation(
                body=X.OperationBody.setOptionsOp(X.SetOptionsOp(
                    signer=X.Signer(
                        key=X.SignerKey.ed25519(extra.public_key.ed25519),
                        weight=1))))]))
    for start in range(0, len(setopts), 40):
        close(setopts[start:start + 40])

    for _ in range(n_payment_ledgers):
        frames = []
        for _ in range(txs_per_ledger):
            i = rng.randrange(n_accounts)
            acct = accounts[i]
            op = native_payment_op(
                accounts[rng.randrange(n_accounts)].account_id,
                1000 + rng.randrange(10**6))
            if i in extras:
                frames.append(build_tx(
                    nid, acct.secret, acct.next_seq(), [op],
                    extra_signers=[extras[i]]))   # 2 sigs
            else:
                frames.append(acct.tx([op]))
        close(frames)
    # run empty ledgers until the LCL sits exactly on a published
    # checkpoint boundary: the archive then covers the whole chain and the
    # replay target hash equals mgr.lcl_hash
    while not history.published_checkpoints or \
            history.published_checkpoints[-1] != mgr.last_closed_ledger_seq:
        close([])
    return archive, mgr


def bench_lint():
    """corelint wall time + per-rule counts over the full tree: the
    static-analysis gate runs on every `make test`, so its cost must stay
    a rounding error as the tree grows (ISSUE 4 satellite)."""
    from stellar_core_tpu.lint import (DEFAULT_TARGETS, all_rules,
                                       check_baseline, load_baseline,
                                       run_paths)
    root = os.path.dirname(os.path.abspath(__file__))
    targets = [os.path.join(root, t) for t in DEFAULT_TARGETS]
    t0 = time.perf_counter()
    rep = run_paths(targets, all_rules(), root=root)
    wall = time.perf_counter() - t0
    # parse errors and baseline-ratchet drift fail `make lint` too —
    # count them so this row can never read clean while the gate is red
    ratchet = []
    bl_path = os.path.join(root, "LINT_BASELINE.json")
    if os.path.exists(bl_path):
        ratchet = check_baseline(rep, load_baseline(bl_path))
    # every registered rule appears with an explicit count (zero included)
    # so the native-C pass (ISSUE 15) is visibly part of the gate even on
    # a clean tree; suppressed findings are broken out per rule too
    counts = {r.id: 0 for r in all_rules()}
    counts.update(rep.counts_by_rule())
    suppressed_by_rule = {}
    for v in rep.suppressed:
        suppressed_by_rule[v.rule] = suppressed_by_rule.get(v.rule, 0) + 1
    return {
        "lint_wall_s": round(wall, 3),
        "lint_files": rep.files_scanned,
        "lint_files_per_sec": round(rep.files_scanned / wall, 1)
        if wall > 0 else 0.0,
        "lint_violations": len(rep.violations) + len(rep.parse_errors)
        + len(ratchet),
        "lint_parse_errors": len(rep.parse_errors),
        "lint_ratchet_problems": len(ratchet),
        "lint_suppressed": len(rep.suppressed),
        "lint_rule_counts": counts,
        "lint_suppressed_by_rule": suppressed_by_rule,
    }


def bench_native_asan(time_left_fn):
    """ASan+UBSan differential-tier wall (ISSUE 15): rebuild the C
    engine sanitized (its own .so cache under build/asan) and run the
    native-close differential + fuzz suites with the runtime preloaded
    and halt_on_error=1 — the `make native-asan` tax, measured so the
    sanitizer tier's cost trend rides every report.  Emits
    SKIPPED(no-toolchain) rows when cc/libasan is absent (the tier
    itself degrades identically)."""
    import subprocess
    from stellar_core_tpu import _native_build as nb
    if not nb.sanitizer_available():
        return {"native_asan_wall_s": "SKIPPED(no-toolchain)",
                "native_asan_green": False}
    t0 = time.perf_counter()
    if not nb.ensure_sanitized(quiet=False):
        return {"native_asan_wall_s": "SKIPPED(sanitized-build-failed)",
                "native_asan_green": False}
    build_s = time.perf_counter() - t0
    env = nb.sanitizer_env()
    env["JAX_PLATFORMS"] = "cpu"
    env["NATIVE_CLOSE_DIFFERENTIAL"] = "1"
    root = os.path.dirname(os.path.abspath(__file__))
    t1 = time.perf_counter()
    try:
        res = subprocess.run(
            [sys.executable, "-m", "pytest",
             os.path.join(root, "tests", "test_native_close.py"),
             os.path.join(root, "tests", "test_capply.py"),
             "-q", "-m", "not slow", "-p", "no:cacheprovider",
             "-p", "no:xdist", "-p", "no:randomly"],
            cwd=root, env=env, capture_output=True, text=True,
            timeout=max(120.0, time_left_fn()))
    except subprocess.TimeoutExpired:
        return {"native_asan_wall_s": "SKIPPED(budget, pre-empted)",
                "native_asan_build_s": round(build_s, 2),
                "native_asan_green": False}
    wall = time.perf_counter() - t1
    ok = res.returncode == 0
    vals = {
        "native_asan_wall_s": round(wall, 2) if ok
        else f"FAILED(rc={res.returncode})",
        "native_asan_build_s": round(build_s, 2),
        "native_asan_green": ok,
    }
    if not ok:
        _stage("native-asan tier FAILED:\n" + res.stdout[-2000:]
               + res.stderr[-2000:])
    return vals


def bench_racetrace(n: int = 200_000):
    """Race-sanitizer overhead row (ISSUE 9): µs per tracked attribute
    access with the sanitizer ON vs the identical un-instrumented class,
    plus the on/off ratio — the `make race` tax, reported next to the
    lock-tracer note in PROFILE.md.  Runs in-process with enable()/
    disable() so the rest of the bench stays uninstrumented."""
    from stellar_core_tpu.util import lockorder, racetrace
    from stellar_core_tpu.util.racetrace import race_checked

    class _Plain:
        def __init__(self):
            self.x = 0

    @race_checked
    class _Checked:
        def __init__(self):
            self.x = 0

    def loop(obj):
        t0 = time.perf_counter()
        for _ in range(n):
            obj.x = obj.x + 1        # one read + one write per iteration
        return (time.perf_counter() - t0) / (2 * n) * 1e6

    off_us = loop(_Plain())
    prev_race = racetrace.enabled()
    prev_lock = lockorder.enabled()
    racetrace.enable()
    try:
        on_us = loop(_Checked())
    finally:
        # restore, don't clobber: under STPU_RACE_TRACE=1 the sanitizer
        # must stay armed for the rest of the bench
        if not prev_race:
            racetrace.disable()
        if not prev_lock:
            lockorder.disable()
    return {
        "racetrace_off_us_per_access": round(off_us, 4),
        "racetrace_on_us_per_access": round(on_us, 4),
        "racetrace_overhead_x": round(on_us / off_us, 1)
        if off_us > 0 else 0.0,
    }


def bench_chaos(time_left_fn):
    """Chaos campaign section (ISSUE 6): run the small-topology scenario
    tier — partition/flap/heal, stall+rejoin, corrupted floods, link
    degradation — and report per-scenario ledgers-closed + measured
    virtual recovery times.  Scenarios are attempted smallest-first under
    the remaining global budget; ones that no longer fit emit
    SKIPPED(budget) rows like every other section."""
    import logging as _pylogging

    from stellar_core_tpu.simulation import chaos as chaos_mod

    # the sims log one INFO line per peer auth: thousands of lines at
    # 50 nodes drown the bench stderr, so clamp to WARNING for the section
    prev_level = _pylogging.getLogger("stellar").level
    _pylogging.getLogger("stellar").setLevel(_pylogging.WARNING)
    # the catalogue IS the plan (cheapest first) — the flagship 51-node
    # campaign dominates; its estimate tracks the tier-1 test's runtime
    plan = sorted(chaos_mod.SMALL_SCENARIOS, key=lambda fe: fe[1])
    vals = {"chaos_scenarios": {}}
    total_ledgers = 0
    failures = 0
    try:
        for make, est in plan:
            sc = make()
            if time_left_fn() < est * 1.25 + 30.0:
                vals["chaos_scenarios"][sc.name] = "SKIPPED(budget)"
                continue
            _stage(f"chaos scenario {sc.name}...")
            t0 = time.perf_counter()
            res = chaos_mod.run_scenario(sc)
            row = res.to_report()
            row["wall_s"] = round(time.perf_counter() - t0, 1)
            vals["chaos_scenarios"][sc.name] = row
            total_ledgers += res.ledgers_closed
            if not res.passed:
                failures += 1
        # 300-node soak timing row (ISSUE 12): the headline number for
        # the incremental per-slot quorum state — the campaign that used
        # to be offline-scale.  Attempted only when the remaining global
        # budget clearly covers it; a SKIPPED(budget) marker is resolved
        # back to the last measured wall time by _merge_last_good.
        est300 = 1150.0   # PROFILE round 11: ~19 min with the quorum index
        if time_left_fn() >= est300 * 1.25 + 60.0:
            _stage("chaos 300-node soak (byzantine equivocator armed)...")
            t0 = time.perf_counter()
            res = chaos_mod.run_scenario(chaos_mod.scenario_soak(100, 3))
            vals["chaos_soak300_wall_s"] = round(time.perf_counter() - t0, 1)
            vals["chaos_soak300_ledgers"] = res.ledgers_closed
            if not res.passed:
                failures += 1
        else:
            vals["chaos_soak300_wall_s"] = "SKIPPED(budget)"
    finally:
        _pylogging.getLogger("stellar").setLevel(prev_level)
    vals["chaos_total_ledgers"] = total_ledgers
    vals["chaos_failed_scenarios"] = failures
    recs = [max(r["recovery_s"])
            for r in vals["chaos_scenarios"].values()
            if isinstance(r, dict) and r.get("recovery_s")]
    if recs:
        vals["chaos_recovery_s_max"] = max(recs)
    return vals


def bench_determinism(time_left_fn):
    """Determinism tier (ISSUE 19).  The four consensus-path lint rules
    ride the corelint section automatically (bench_lint enumerates every
    registered rule); this section measures the *dynamic* half:

    - detguard overhead: the Soroban mixed campaign with the guard
      disarmed vs armed in-process (enable()/disable()) — the
      `make determinism` / STPU_DETGUARD=1 tax, reported like the
      racetrace overhead row;
    - the hash-seed differential: the 51-node flagship chaos campaign
      in paired subprocesses under two PYTHONHASHSEED values (children
      detguard-armed), divergence asserted zero — deadline-aware with
      SKIPPED(budget) + last-good semantics like every section."""
    import logging as _pylogging

    from stellar_core_tpu.simulation import hashseed_diff
    from stellar_core_tpu.simulation.loadgen import SorobanMixCampaign
    from stellar_core_tpu.util import detguard

    vals = {}
    prev_level = _pylogging.getLogger("stellar").level
    _pylogging.getLogger("stellar").setLevel(_pylogging.WARNING)
    try:
        n_ledgers = 20
        # untimed warm-up: first campaign pays import/JIT/caches and
        # would inflate whichever arm runs first
        SorobanMixCampaign().run(n_ledgers=5)
        t0 = time.perf_counter()
        SorobanMixCampaign().run(n_ledgers=n_ledgers)
        off_s = time.perf_counter() - t0
        detguard.reset_stats()
        detguard.enable()
        try:
            t0 = time.perf_counter()
            SorobanMixCampaign().run(n_ledgers=n_ledgers)
            on_s = time.perf_counter() - t0
        finally:
            detguard.disable()
        st = detguard.stats()
        vals["detguard_off_wall_s"] = round(off_s, 3)
        vals["detguard_on_wall_s"] = round(on_s, 3)
        vals["detguard_overhead_ratio"] = round(on_s / max(off_s, 1e-9), 3)
        vals["detguard_regions"] = st["regions"]
        vals["detguard_trips"] = st["trips"]
    finally:
        _pylogging.getLogger("stellar").setLevel(prev_level)

    # paired-subprocess flagship differential: the two children run
    # concurrently, so the wall cost is ~one detguard-armed campaign
    est_flagship = 110.0
    if time_left_fn() >= est_flagship * 1.25 + 30.0:
        _stage("hash-seed differential (51-node flagship pair)...")
        rep = hashseed_diff.run_pair(
            "flagship", timeout_s=max(300.0, time_left_fn()))
        vals["hashseed_flagship_wall_s"] = (
            round(rep["wall_s"], 1) if rep["ok"]
            else f"FAILED({rep['divergence'] or rep['errors']})")
        vals["hashseed_flagship_identical"] = rep["identical"]
        vals["hashseed_flagship_trips"] = sum(
            g.get("trips", 0) for g in rep["detguard"]) \
            if rep["detguard"] else None
    else:
        vals["hashseed_flagship_wall_s"] = "SKIPPED(budget)"
    return vals


def bench_transport(time_left_fn):
    """ISSUE 18 acceptance: the batched-authenticated-transport section.
    Rows cheapest first under the global deadline:

    1. MAC+codec µs/message at batch sizes {1,4,16,64} — the pure
       compute saving of one-MAC frames (one HMAC + one splice per run
       instead of one per message).
    2. single-message latency floor — a lone message on a batched link
       rides the run-of-one fast path (classic v0 frame, flushed within
       the same crank): its loopback round trip must not regress vs an
       unbatched link (ASSERTED, like the admission floor).
    3. 51-node flagship campaign wall clock, batched vs unbatched —
       the faster-or-equal headline row.
    4. 150-node soak pair (budget-gated): the >=1.5x wall-clock row
       with both campaigns' safety/liveness verdicts.

    CPU-only: everything here is HMAC + splice + scheduler work."""
    import logging as _pylogging
    import struct

    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.herder.herder import Herder
    from stellar_core_tpu.ledger.manager import LedgerManager
    from stellar_core_tpu.overlay import (OverlayManager, frame_encode,
                                          make_loopback_pair)
    from stellar_core_tpu.overlay.peer_auth import mac_message
    from stellar_core_tpu.simulation import chaos as chaos_mod
    from stellar_core_tpu.simulation.simulation import qset_of
    from stellar_core_tpu.testutils import network_id
    from stellar_core_tpu.util.clock import ClockMode, VirtualClock

    vals = {}

    # --- 1. MAC+codec microbench -------------------------------------
    _stage("transport MAC+codec microbench...")
    key = b"\x5a" * 32
    env = X.SCPEnvelope(
        statement=X.SCPStatement(
            nodeID=X.AccountID.ed25519(b"\x11" * 32),
            slotIndex=12345,
            pledges=X.SCPStatementPledges.nominate(X.SCPNomination(
                quorumSetHash=b"\x22" * 32,
                votes=[b"\x33" * 32], accepted=[b"\x44" * 32]))),
        signature=b"\x55" * 64)
    body = X.StellarMessage.envelope(env).to_xdr()
    reps = 200
    rows = {}
    for bs in (1, 4, 16, 64):
        t0 = time.perf_counter()
        for r in range(reps):
            for i in range(bs):
                mac = mac_message(key, r, body)
                frame_encode(b"\x00\x00\x00\x00" + struct.pack(">Q", r)
                             + body + mac)
        un_us = (time.perf_counter() - t0) / (reps * bs) * 1e6
        t0 = time.perf_counter()
        for r in range(reps):
            payload = struct.pack(">I", bs) + (
                struct.pack(">I", len(body)) + body) * bs
            mac = mac_message(key, r, payload)
            frame_encode(b"\x00\x00\x00\x01" + struct.pack(">Q", r)
                         + payload + mac)
        ba_us = (time.perf_counter() - t0) / (reps * bs) * 1e6
        rows[str(bs)] = {"unbatched_us": round(un_us, 2),
                         "batched_us": round(ba_us, 2),
                         "speedup": round(un_us / ba_us, 2)}
    vals["transport_mac_codec_us_per_msg"] = rows

    # --- 2. single-message latency floor -----------------------------
    _stage("transport single-message floor (loopback)...")
    nid = network_id("transport bench net")

    def loopback_pair(batching):
        clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        sk_a, sk_b = SecretKey(b"\x31" * 32), SecretKey(b"\x32" * 32)
        q = qset_of([sk_a.public_key.ed25519, sk_b.public_key.ed25519], 2)
        overlays = []
        for sk, seed in ((sk_a, b"t" * 32), (sk_b, b"u" * 32)):
            lm = LedgerManager(nid)
            lm.start_new_ledger()
            h = Herder(clock, lm, sk, q)
            overlays.append(OverlayManager(clock, h, nid, sk,
                                           auth_seed=seed,
                                           batching=batching))
        pa, pb = make_loopback_pair(overlays[0], overlays[1])
        for _ in range(50):
            clock.crank()
        assert pa.is_authenticated() and pb.is_authenticated()
        return clock, pa, pb

    # interleaved rounds + min-of-N per arm: the only stable estimator
    # for small effects on this workload (PROFILE round 15) — a
    # sequential cold-first comparison fakes a 1.4x "regression" out of
    # interpreter warmup
    floor_m, rounds = 150, 4
    arms = {}
    for mode, batching in (("batched", True), ("unbatched", False)):
        clock, pa, pb = loopback_pair(batching)
        got = [0]
        orig = pb.overlay._message_received

        def spy(p, m, body=None, _o=orig, _g=got):
            _g[0] += 1
            return _o(p, m, body=body)
        pb.overlay._message_received = spy
        arms[mode] = (clock, pa, got)

    def floor_round(mode):
        clock, pa, got = arms[mode]
        n0 = got[0]
        t0 = time.perf_counter()
        for i in range(floor_m):
            pa.send_message(X.StellarMessage.getSCPLedgerSeq(i + 1))
            clock.crank()
            clock.crank()
        wall = time.perf_counter() - t0
        assert got[0] - n0 >= floor_m, (mode, got[0] - n0)
        return wall / floor_m * 1e6

    for mode in arms:
        floor_round(mode)          # warmup round, discarded
    samples = {m: [] for m in arms}
    for _ in range(rounds):
        for mode in ("batched", "unbatched"):
            samples[mode].append(floor_round(mode))
    floor = {m: min(s) for m, s in samples.items()}
    floor_ratio = floor["batched"] / floor["unbatched"]
    vals["transport_floor_batched_us"] = round(floor["batched"], 1)
    vals["transport_floor_unbatched_us"] = round(floor["unbatched"], 1)
    vals["transport_floor_ratio"] = round(floor_ratio, 3)
    # the no-flush-delay proof is in CRANKS: a lone message on a batched
    # link must reach the partner within at most one extra crank (the
    # posted crank-edge flush), never wait on a timer or more traffic
    cranks = {}
    for mode in ("batched", "unbatched"):
        clock, pa, got = arms[mode]
        n0 = got[0]
        pa.send_message(X.StellarMessage.getSCPLedgerSeq(9999))
        n = 0
        while got[0] == n0 and n < 10:
            clock.crank()
            n += 1
        cranks[mode] = n
    vals["transport_floor_cranks_batched"] = cranks["batched"]
    vals["transport_floor_cranks_unbatched"] = cranks["unbatched"]
    assert cranks["batched"] <= cranks["unbatched"] + 1, cranks
    # the CPU side: run-of-one emits the identical v0 frame, so the only
    # extra work is one posted flush action — 1.5x bounds that plus
    # single-core scheduler noise (measured ~1.1-1.2x)
    assert floor_ratio <= 1.5, (
        f"single-message latency regressed under batching: "
        f"{floor['batched']:.1f}µs vs {floor['unbatched']:.1f}µs "
        f"({floor_ratio:.2f}x > 1.5x)")
    vals["transport_floor_ok"] = True

    # --- 3. 51-node flagship, both transport modes -------------------
    prev_level = _pylogging.getLogger("stellar").level
    _pylogging.getLogger("stellar").setLevel(_pylogging.WARNING)
    try:
        est51 = 60.0
        if time_left_fn() < est51 * 2.5 + 30.0:
            vals["transport_flagship51"] = "SKIPPED(budget)"
        else:
            walls, ok = {}, True
            for mode, batching in (("batched", True),
                                   ("unbatched", False)):
                _stage(f"transport flagship 51-node campaign "
                       f"({mode})...")
                sc = chaos_mod.scenario_partition_flap_heal(17, 3)
                sc.batching = batching
                t0 = time.perf_counter()
                res = chaos_mod.run_scenario(sc)
                walls[mode] = time.perf_counter() - t0
                ok = ok and res.passed
                vals[f"transport_flagship51_{mode}_wall_s"] = round(
                    walls[mode], 1)
                vals[f"transport_flagship51_{mode}_ledgers"] = \
                    res.ledgers_closed
            vals["transport_flagship51_speedup"] = round(
                walls["unbatched"] / walls["batched"], 2)
            vals["transport_flagship51_passed"] = ok

        # --- 4. 150-node soak pair (the >=1.5x acceptance row) -------
        est150 = 240.0
        if time_left_fn() < est150 * 2 * 1.25 + 60.0:
            vals["transport_soak150"] = "SKIPPED(budget)"
        else:
            walls, ok = {}, True
            for mode, batching in (("batched", True),
                                   ("unbatched", False)):
                _stage(f"transport 150-node soak ({mode})...")
                sc = chaos_mod.scenario_soak(50, 3)
                sc.batching = batching
                t0 = time.perf_counter()
                res = chaos_mod.run_scenario(sc)
                walls[mode] = time.perf_counter() - t0
                ok = ok and res.passed
                vals[f"transport_soak150_{mode}_wall_s"] = round(
                    walls[mode], 1)
                vals[f"transport_soak150_{mode}_ledgers"] = \
                    res.ledgers_closed
            vals["transport_soak150_speedup"] = round(
                walls["unbatched"] / walls["batched"], 2)
            vals["transport_soak150_passed"] = ok
    finally:
        _pylogging.getLogger("stellar").setLevel(prev_level)
    return vals


def bench_admission(time_left_fn):
    """ISSUE 7 acceptance: the sustained-ingestion section.  Three
    measurements, cheapest first under the global deadline:

    1. latency floor — at low offered load (sparse arrivals) batched
       admission takes the synchronous single-sig path, so its per-tx
       latency must not regress below a direct ``try_add`` call.  Both
       sides are measured on fresh frames (no verify-cache pollution)
       and the no-regression floor is ASSERTED, not assumed.
    2. sustained throughput — a seed-derived account campaign over
       BucketListDB offered exactly the apply capacity per close.
    3. 2x overload — offered load doubles; the queue must bound itself
       (surge eviction + fee-floor prefilter + try-again-later) and the
       report carries the queue-depth/shedding behavior.

    CPU-only: the batching/back-pressure machinery is identical either
    way and the device's sig throughput is bench_sigs' job, so this
    section stays measurable with the tunnel down."""
    from stellar_core_tpu.herder.tx_queue import AddResult, TransactionQueue
    from stellar_core_tpu.simulation.loadgen import AdmissionCampaign

    vals = {}

    # --- 1. latency floor (in-memory root: the sig verify dominates) ---
    _stage("admission latency floor vs direct try_add...")
    n = 250
    c = AdmissionCampaign(n_accounts=2 * n, workdir=None, install_chunk=500)
    try:
        # distinct account ranges + distinct frames per side: every
        # verify is a genuine libsodium call on both paths
        direct_frames = [c._payment_frame(i, (i + 1) % c.pool.n)
                         for i in range(n)]
        sync_frames = [c._payment_frame(n + i, (n + i + 1) % c.pool.n)
                       for i in range(n)]
        direct_q = TransactionQueue(c.mgr)
        direct_s = []
        for f in direct_frames:
            t0 = time.perf_counter()
            res = direct_q.try_add(f)
            direct_s.append(time.perf_counter() - t0)
            assert res.code == AddResult.STATUS_PENDING, res.code
        sync_s = []
        for f in sync_frames:
            # sparse arrival: advance virtual time past the burst window
            # so the pipeline stays idle and takes the sync path
            c.clock.crank_for(c.admission.flush_delay_s * 2)
            t0 = time.perf_counter()
            res = c.admission.submit(f)
            sync_s.append(time.perf_counter() - t0)
            assert res.code == AddResult.STATUS_PENDING, res.code
        assert c.admission.stats["sync_path"] == n
        direct_s.sort()
        sync_s.sort()
        direct_p50 = direct_s[n // 2]
        sync_p50 = sync_s[n // 2]
        floor_ratio = sync_p50 / direct_p50
        vals["admission_floor_direct_p50_us"] = round(direct_p50 * 1e6, 1)
        vals["admission_floor_batched_p50_us"] = round(sync_p50 * 1e6, 1)
        vals["admission_floor_ratio"] = round(floor_ratio, 3)
        # the sync path is try_add plus a handful of dict ops on a
        # ~60µs signature verify; 1.25x is the noise bound, not a tax
        assert floor_ratio <= 1.25, (
            f"admission latency floor regressed: sync-path p50 "
            f"{sync_p50 * 1e6:.1f}µs vs direct try_add "
            f"{direct_p50 * 1e6:.1f}µs ({floor_ratio:.2f}x > 1.25x)")
        vals["admission_floor_ok"] = True
    finally:
        c.close()

    # --- on-device admission row (ROADMAP 3c): the accel path batch-
    # verifies through AdmissionPipeline's PreverifyPipeline — gated on
    # the same ACCEL switch the node config flips, so CPU-only rigs (and
    # tunnel-down days) emit an explicit SKIPPED row while the sections
    # above stay measurable ---
    if os.environ.get("ACCEL", "").lower() != "tpu":
        vals["admission_accel"] = "SKIPPED(ACCEL!=tpu)"
    elif time_left_fn() < 90.0:
        vals["admission_accel"] = "SKIPPED(budget)"
    else:
        _stage("admission accel campaign (on-device batch verify)...")
        c = AdmissionCampaign(n_accounts=4000, workdir=None, accel=True,
                              batch_size=256, max_tx_set_ops=500,
                              max_backlog=2000)
        try:
            rep = c.run(n_ledgers=3, offered_per_ledger=500)
            stats = rep["admission_stats"]
            vals["admission_accel_sustained_tps"] = rep["sustained_tps"]
            vals["admission_accel_batches"] = rep.get("batches", 0)
            vals["admission_accel_sigs_offloaded"] = \
                stats.get("sigs_offloaded", 0)
            vals["admission_accel_sync_path"] = stats.get("sync_path", 0)
            for q in ("p50", "p99"):
                key = f"admission_{q}_us"
                if key in rep:
                    vals[f"admission_accel_{q}_us"] = rep[key]
        finally:
            c.close()

    # --- 2+3. sustained campaign + 2x overload over BucketListDB ---
    if time_left_fn() < 120.0:
        vals["admission_campaign"] = "SKIPPED(budget)"
        return vals
    accounts = int(os.environ.get("BENCH_ADMISSION_ACCOUNTS", "100000"))
    cap = 500   # ops per close (surge trim limit; queue bounds at 4x)
    _stage(f"admission campaign ({accounts} seed-derived accounts "
           "over BucketListDB)...")
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        c = AdmissionCampaign(n_accounts=accounts, workdir=d,
                              max_tx_set_ops=cap, max_backlog=2000)
        vals["admission_accounts"] = accounts
        vals["admission_install_s"] = round(time.perf_counter() - t0, 2)
        try:
            rep1 = c.run(n_ledgers=4, offered_per_ledger=cap)
            vals["admission_sustained_tps"] = rep1["sustained_tps"]
            shed_before = {k: v for k, v in c.statuses.items()}
            rej_before = c.admission.stats["rejected"]
            pre_before = c.admission.stats["prefiltered"]
            # 2x overload: enough rounds that the queue actually fills
            # (net growth cap/round) and the shedding economics engage
            rep2 = c.run(n_ledgers=6, offered_per_ledger=2 * cap)
            vals["admission_overload_tps"] = rep2["sustained_tps"]
            vals["admission_max_sustained_tps"] = max(
                rep1["sustained_tps"], rep2["sustained_tps"])
            for q in ("p50", "p90", "p99"):
                key = f"admission_{q}_us"
                if key in rep2:
                    vals[key] = rep2[key]
            for key in ("batches", "batch_size_p50", "batch_size_p99",
                        "batch_size_max"):
                if key in rep2:
                    vals[f"admission_{key}"] = rep2[key]
            vals["admission_overload_peak_queue_depth"] = \
                rep2["peak_queue_depth"]
            vals["admission_overload_peak_backlog"] = \
                rep2["peak_admission_depth"]
            vals["admission_overload_queue_bounded"] = \
                rep2["peak_queue_depth"] <= 4 * cap
            assert rep2["peak_queue_depth"] <= 4 * cap, \
                "tx queue grew past its surge bound under 2x overload"
            assert rep2["peak_admission_depth"] <= c.admission.max_backlog, \
                "admission backlog grew past max_backlog under overload"
            # "rejected" already counts the prefiltered txs (the fee-floor
            # path routes through _reject) — no double count
            shed = c.admission.stats["rejected"] - rej_before
            tal = (c.statuses.get(AddResult.STATUS_TRY_AGAIN_LATER, 0)
                   - shed_before.get(AddResult.STATUS_TRY_AGAIN_LATER, 0))
            vals["admission_overload_shed"] = shed
            vals["admission_overload_try_again_later"] = tal
            vals["admission_prefiltered"] = \
                c.admission.stats["prefiltered"] - pre_before
            vals["admission_peak_decoded_entries"] = \
                rep2.get("peak_decoded_entries", 0)
        finally:
            c.close()
    return vals


def bench_catchup_parallel(time_left_fn):
    """ISSUE 10 acceptance: range-parallel catchup wall-clock vs the
    single-stream replay on a multi-thousand-ledger archive.  Both sides
    run through the SAME subprocess-worker machinery (ParallelCatchup with
    workers=1 vs 2/4) so the comparison includes every real cost — worker
    spawn, per-range assume-state (hash-verified HAS + bucket download),
    stitch verification.  Interleaved (single, par4) rounds with
    replay-style mid-section pre-emption; the final ledger hash is
    asserted bit-identical to the archive builder's on EVERY run and every
    boundary stitch is asserted inside the orchestrator (it raises on any
    mismatch).  CPU-only (workers default to the native apply engine)."""
    from stellar_core_tpu.catchup.parallel import ParallelCatchup
    from stellar_core_tpu.testutils import network_id

    passphrase = "catchup parallel bench"
    nid = network_id(passphrase)
    n_pay = int(os.environ.get("BENCH_CATCHUP_PAR_LEDGERS", "2000"))
    rounds = 3
    vals = {}
    with tempfile.TemporaryDirectory() as d:
        _stage(f"catchup_parallel: building archive (~{n_pay} payment "
               "ledgers)...")
        t0 = time.perf_counter()
        archive, mgr = build_archive(
            nid, passphrase, os.path.join(d, "archive"),
            n_payment_ledgers=n_pay,
            txs_per_ledger=int(os.environ.get("BENCH_CATCHUP_PAR_TXS", "20")))
        target = mgr.last_closed_ledger_seq
        expected = mgr.lcl_hash.hex()
        vals["catchup_par_ledgers"] = target
        vals["catchup_par_build_s"] = round(time.perf_counter() - t0, 1)

        run_idx = [0]

        def one_run(workers: int) -> dict:
            import shutil
            run_idx[0] += 1
            workdir = os.path.join(d, f"run-{run_idx[0]:02d}")
            pc = ParallelCatchup(
                os.path.join(d, "archive"), passphrase, workers=workers,
                workdir=workdir)
            report = pc.run()
            assert report["final_hash"] == expected, \
                f"parallel catchup (N={workers}) diverged from the builder"
            assert report["stitches_verified"] == len(report["ranges"]) - 1
            # the persisted final-range state is never adopted here —
            # reclaim per run, or 7 full ledger states pile up under `d`
            shutil.rmtree(workdir, ignore_errors=True)
            return report

        single_s, par4_s, par4_report = [], [], None
        round_cost = None
        rounds_skipped = 0
        for r in range(rounds):
            if round_cost is not None and time_left_fn() < round_cost * 1.25:
                rounds_skipped = rounds - r
                _stage(f"catchup_parallel: PRE-EMPTED after {r}/{rounds} "
                       f"rounds (next needs ~{round_cost:.0f}s, "
                       f"{time_left_fn():.0f}s left)")
                break
            t_round = time.perf_counter()
            _stage(f"catchup_parallel round {r + 1}/{rounds}: "
                   "single stream...")
            single_s.append(one_run(1)["wall_s"])
            _stage(f"catchup_parallel round {r + 1}/{rounds}: N=4...")
            par4_report = one_run(4)
            par4_s.append(par4_report["wall_s"])
            round_cost = time.perf_counter() - t_round
        if not single_s:
            return None   # budget pre-empted before one full round
        med = lambda xs: sorted(xs)[len(xs) // 2]
        vals["catchup_par_single_s"] = med(single_s)
        vals["catchup_par_n4_s"] = med(par4_s)
        vals["catchup_par_speedup_n4"] = round(med(single_s) / med(par4_s),
                                               2)
        vals["catchup_par_single_ledgers_per_s"] = round(
            target / med(single_s), 1)
        vals["catchup_par_n4_ledgers_per_s"] = round(target / med(par4_s), 1)
        vals["catchup_par_n4_stitches"] = \
            par4_report["stitches_verified"]
        vals["catchup_par_n4_range_rates"] = [
            rr["ledgers_per_s"] for rr in par4_report["ranges"]]
        if rounds_skipped:
            vals["catchup_par_rounds_skipped_budget"] = rounds_skipped
        # one N=2 point for the scaling curve when the budget still fits
        if round_cost is not None and time_left_fn() > round_cost:
            _stage("catchup_parallel: N=2...")
            n2 = one_run(2)
            vals["catchup_par_n2_s"] = n2["wall_s"]
            vals["catchup_par_speedup_n2"] = round(
                med(single_s) / n2["wall_s"], 2)
        else:
            vals["catchup_par_n2_s"] = "SKIPPED(budget)"
        vals["catchup_par_hashes_identical"] = True
    return vals


def bench_catchup_mesh(time_left_fn):
    """ISSUE 14 acceptance: the mesh catchup scaling curve.  One >=2000-
    ledger archive; per-N wall clock for N=1/2/4/8 range workers, each
    pinned to one (CPU-simulated) device via the visible-device env the
    real mesh uses, with checkpoint-granular work stealing live; then the
    straggler pair — N=3 with one throttled range, steal OFF vs steal ON
    — proving stealing beats the no-steal curve in wall clock.  Final
    hash asserted bit-identical to the builder's on EVERY run; monotone
    N-scaling asserted (10% tolerance for host noise)."""
    import shutil

    from stellar_core_tpu.catchup.parallel import ParallelCatchup
    from stellar_core_tpu.testutils import network_id

    passphrase = "catchup mesh bench"
    nid = network_id(passphrase)
    n_pay = int(os.environ.get("BENCH_CATCHUP_MESH_LEDGERS", "2000"))
    vals = {}
    with tempfile.TemporaryDirectory() as d:
        _stage(f"catchup_mesh: building archive (~{n_pay} payment "
               "ledgers)...")
        t0 = time.perf_counter()
        archive, mgr = build_archive(
            nid, passphrase, os.path.join(d, "archive"),
            n_payment_ledgers=n_pay,
            txs_per_ledger=int(os.environ.get("BENCH_CATCHUP_MESH_TXS",
                                              "20")))
        target = mgr.last_closed_ledger_seq
        expected = mgr.lcl_hash.hex()
        vals["catchup_mesh_ledgers"] = target
        vals["catchup_mesh_build_s"] = round(time.perf_counter() - t0, 1)

        run_idx = [0]

        def one_run(workers, steal=True, extra_env=None,
                    mesh=True) -> dict:
            run_idx[0] += 1
            workdir = os.path.join(d, f"run-{run_idx[0]:02d}")
            pc = ParallelCatchup(
                os.path.join(d, "archive"), passphrase, workers=workers,
                workdir=workdir, steal=steal,
                mesh_devices=(min(8, workers) if mesh else 0),
                mesh_platform="cpu", extra_env=extra_env)
            report = pc.run()
            assert report["final_hash"] == expected, \
                f"mesh catchup (N={workers}) diverged from the builder"
            assert report["stitches_verified"] == len(report["ranges"]) - 1
            shutil.rmtree(workdir, ignore_errors=True)
            return report

        # -- the scaling curve, N=1/2/4/8, steal on + device pinning ----
        walls = {}
        steals_total = 0
        cost = None
        for n in (1, 2, 4, 8):
            if cost is not None and time_left_fn() < cost * 1.25:
                vals[f"catchup_mesh_n{n}_s"] = "SKIPPED(budget)"
                continue
            _stage(f"catchup_mesh: N={n} (device-pinned, steal on)...")
            t0 = time.perf_counter()
            rep = one_run(n)
            cost = time.perf_counter() - t0
            walls[n] = rep["wall_s"]
            steals_total += rep["steals"]
            vals[f"catchup_mesh_n{n}_s"] = rep["wall_s"]
            vals[f"catchup_mesh_n{n}_ledgers_per_s"] = \
                rep["ledgers_per_s"]
            vals[f"catchup_mesh_n{n}_steals"] = rep["steals"]
        if 1 in walls:
            for n in (2, 4, 8):
                if n in walls:
                    vals[f"catchup_mesh_speedup_n{n}"] = round(
                        walls[1] / walls[n], 2)
        vals["catchup_mesh_steals_total"] = steals_total
        vals["catchup_mesh_hashes_identical"] = True
        # monotone scaling to N=8 (acceptance): each doubling may not
        # LOSE wall clock (10% tolerance: run-to-run noise on a shared
        # host, fixed per-worker spawn costs at the small end)
        ns = sorted(walls)
        for a, b in zip(ns, ns[1:]):
            assert walls[b] <= walls[a] * 1.10, (
                f"mesh scaling NOT monotone: N={b} took {walls[b]}s vs "
                f"N={a} {walls[a]}s")

        # -- straggler pair: steal must beat no-steal -------------------
        if cost is not None and time_left_fn() > 3 * cost + 60:
            throttle = {0: {"STPU_CATCHUP_THROTTLE_S": "0.6"}}
            _stage("catchup_mesh: straggler N=3, steal OFF...")
            no_steal = one_run(3, steal=False, extra_env=throttle,
                               mesh=False)
            _stage("catchup_mesh: straggler N=3, steal ON...")
            with_steal = one_run(3, steal=True, extra_env=throttle,
                                 mesh=False)
            vals["catchup_mesh_straggler_nosteal_s"] = no_steal["wall_s"]
            vals["catchup_mesh_straggler_steal_s"] = with_steal["wall_s"]
            vals["catchup_mesh_straggler_steals"] = with_steal["steals"]
            vals["catchup_mesh_straggler_speedup"] = round(
                no_steal["wall_s"] / with_steal["wall_s"], 2)
            assert with_steal["steals"] >= 1, \
                "straggler run triggered no steals"
            assert with_steal["wall_s"] < no_steal["wall_s"], (
                f"work stealing lost to no-steal: "
                f"{with_steal['wall_s']}s vs {no_steal['wall_s']}s")
        else:
            vals["catchup_mesh_straggler_nosteal_s"] = "SKIPPED(budget)"
            vals["catchup_mesh_straggler_steal_s"] = "SKIPPED(budget)"
    return vals


def bench_fleet(time_left_fn):
    """ISSUE 11: small-fleet short soak — 3 real `run` processes over
    real TCP sustain SeedAccountPool traffic through a SIGKILL +
    `catchup --parallel` rejoin against the fleet's live archive.
    Reports sustained accepted TPS, p99 close time and rejoin-to-
    retracking seconds; zero hash divergence is ASSERTED (a fork fails
    the bench, it does not get reported as a number).  CPU-only like the
    other composition sections.  Returns None when the budget pre-empts
    the soak before it produced a report."""
    import shutil
    import tempfile

    from stellar_core_tpu.simulation.fleet import FleetSLOs, run_fleet_soak

    # the schedule's timeout_s only bounds the event loop; boot
    # (wait_all_healthy, up to 90s) and funding (up to 60s) run BEFORE
    # it — reserve for their worst case too, or a degraded host
    # reintroduces the rc=124 overrun class the deadline work removed
    budget = min(300.0, time_left_fn() - 180.0)
    if budget < 90.0:
        return None
    d = tempfile.mkdtemp(prefix="bench-fleet-")
    schedule = [
        {"kind": "traffic", "rate_per_s": 25.0},
        {"kind": "wait-ledger", "seq": 8},
        {"kind": "kill", "node": 2},
        {"kind": "rejoin", "node": 2, "parallel": 2},
        {"kind": "wait-ledger", "seq": 18},
    ]
    try:
        rep = run_fleet_soak(
            d, n_nodes=3, schedule=schedule, n_accounts=40,
            slos=FleetSLOs(max_p99_close_s=2.0, max_shed_rate=0.5,
                           max_retracking_s=120.0),
            timeout_s=budget)
    except (RuntimeError, OSError, ValueError) as e:
        # boot/funding infrastructure failure on a degraded host: an
        # explicit FAILED row (last-good cache fills the numbers), not a
        # bench-wide crash — only a FORK below is allowed to raise
        _stage(f"fleet soak infrastructure failure: {e}")
        return {"fleet": f"FAILED({type(e).__name__}: {e})"}
    finally:
        shutil.rmtree(d, ignore_errors=True)
    # an actual hash divergence is a correctness claim: fail the bench
    assert not any("DIVERGENCE" in v for v in rep["violations"]), \
        rep["violations"]
    vals = {
        "fleet_passed": rep["passed"],
        "fleet_nodes": rep["nodes"],
        "fleet_ledgers": rep["max_ledger"],
        "fleet_wall_s": rep["wall_s"],
        "fleet_sustained_tps": rep["traffic"].get("accepted_tps", 0.0),
        "fleet_offered": rep["traffic"]["offered"],
        "fleet_shed_rate": rep["traffic"]["shed_rate"],
        "fleet_divergence_seqs_compared": rep["divergence_seqs_compared"],
    }
    if rep.get("p99_close_s") is not None:
        vals["fleet_p99_close_ms"] = round(rep["p99_close_s"] * 1e3, 2)
    for key in ("retracking_s", "kill_to_retracking_s"):
        if key in rep["metrics"]:
            vals[f"fleet_{key}"] = rep["metrics"][key]
    if not rep["passed"]:
        vals["fleet_violations"] = rep["violations"]
    return vals


def bench_native_close(time_left_fn):
    """Native live close section (ISSUE 13): LedgerManager.close driven
    by the C engine (ledger/native_close.py) vs the pure-Python close on
    identical payment traffic, hash-identity asserted.  Deadline-aware:
    the Python side runs first (it is the slow side and its rate decides
    whether the native side still fits); pre-emption reports partial
    results.  Last-good cached like the other CPU sections."""
    import random as _random

    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.ledger.manager import LedgerManager
    from stellar_core_tpu.ledger.native_close import native_close_available
    from stellar_core_tpu.testutils import (TestAccount, create_account_op,
                                            native_payment_op, network_id)

    nid = network_id("native close bench")
    n_ledgers = int(os.environ.get("BENCH_NATIVE_CLOSE_LEDGERS", "200"))
    txs_per_ledger = 10

    def run(native: bool):
        mgr = LedgerManager(nid, invariant_manager=None)
        mgr.start_new_ledger()
        if native:
            assert mgr.attach_native_close(differential=0), \
                "native close attach failed"
        root_sk = mgr.root_account_secret()
        ent = mgr.root.get_entry(
            X.account_key_xdr(root_sk.public_key.ed25519))
        root = TestAccount(mgr, root_sk, ent.data.value.seqNum)
        sks = [SecretKey(bytes([40 + i]) * 32) for i in range(16)]
        mgr.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10 ** 12)
            for sk in sks])], 1_700_000_000)
        accts = []
        for sk in sks:
            e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
            accts.append(TestAccount(mgr, sk, e.data.value.seqNum))
        rng = _random.Random(9)
        ct = 1_700_000_000
        t0 = time.perf_counter()
        for _ in range(n_ledgers):
            ct += 5
            frames = []
            for _ in range(txs_per_ledger):
                a = accts[rng.randrange(len(accts))]
                frames.append(a.tx([native_payment_op(
                    accts[rng.randrange(len(accts))].account_id,
                    1000 + rng.randrange(10 ** 6))]))
            mgr.close_ledger(frames, ct)
        dur = time.perf_counter() - t0
        fallbacks = 0
        if native:
            # a mid-run degrade would silently report PYTHON throughput
            # as the native rate — exactly the regression this section
            # exists to expose
            assert mgr.native_closer.degraded is None, \
                mgr.native_closer.degraded
            fallbacks = mgr.native_closer.fallbacks
            mgr.detach_native_close()
        return n_ledgers / dur, mgr.lcl_hash, fallbacks

    dummy = LedgerManager(nid, invariant_manager=None)
    dummy.start_new_ledger()
    if not native_close_available(dummy):
        return {"native_close": "SKIPPED(_capply not built)"}
    _stage(f"native_close: python side ({n_ledgers} ledgers x "
           f"{txs_per_ledger} txs)...")
    py_rate, py_hash, _ = run(native=False)
    if time_left_fn() < (n_ledgers / py_rate) * 0.6 + 30:
        # the native side is ~3x faster than what just fit — but don't
        # start a side that cannot finish; report the python half only
        return {"native_close": "PARTIAL(budget, python side only)",
                "native_close_python_ledgers_per_sec": round(py_rate, 1),
                "native_close_ledgers": n_ledgers}
    _stage("native_close: native side...")
    c_rate, c_hash, fallbacks = run(native=True)
    assert c_hash == py_hash, "native live close diverged from Python"
    return {
        "native_close_ledgers_per_sec": round(c_rate, 1),
        "native_close_python_ledgers_per_sec": round(py_rate, 1),
        "native_close_vs_python": round(c_rate / py_rate, 3),
        "native_close_ledgers": n_ledgers,
        "native_close_txs_per_ledger": txs_per_ledger,
        "native_close_fallbacks": fallbacks,
        "native_close_hashes_identical": True,
    }


def bench_soroban(time_left_fn):
    """Soroban execution subsystem (ISSUE 17): mixed-phase close
    throughput, footprint-parallel speedup vs serial apply (bucket-hash
    identity asserted), and host metering overhead (metered insns/sec
    through the `burn` built-in).  CPU-only; deadline-aware like the
    other sections."""
    import random as _random

    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.ledger.manager import LedgerManager
    from stellar_core_tpu.testutils import (TestAccount, contract_address,
                                            create_account_op, invoke_op,
                                            make_soroban_data,
                                            native_payment_op, network_id)
    from stellar_core_tpu.soroban.storage import contract_data_key

    nid = network_id("soroban bench")
    n_ledgers = int(os.environ.get("BENCH_SOROBAN_LEDGERS", "30"))
    n_accounts = 12
    classic_per_ledger = 4

    def mk_mgr():
        mgr = LedgerManager(nid, invariant_manager=None)
        mgr.start_new_ledger()
        root_sk = mgr.root_account_secret()
        ent = mgr.root.get_entry(
            X.account_key_xdr(root_sk.public_key.ed25519))
        root = TestAccount(mgr, root_sk, ent.data.value.seqNum)
        sks = [SecretKey(bytes([70 + i]) * 32) for i in range(n_accounts)]
        mgr.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10 ** 12)
            for sk in sks])], 1_700_000_000)
        accts = []
        for sk in sks:
            e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
            accts.append(TestAccount(mgr, sk, e.data.value.seqNum))
        return mgr, accts

    def run(parallel: bool):
        mgr, accts = mk_mgr()
        mgr.soroban_parallel_apply = parallel
        rng = _random.Random(23)
        ct = 1_700_000_000
        t0 = time.perf_counter()
        for ledger in range(n_ledgers):
            ct += 5
            frames = []
            for _ in range(classic_per_ledger):
                a = accts[rng.randrange(len(accts))]
                frames.append(a.tx([native_payment_op(
                    accts[rng.randrange(len(accts))].account_id,
                    1000 + rng.randrange(10 ** 6))]))
            # one invoke per account, each on its own contract: the
            # write sets are disjoint, so every soroban tx is its own
            # cluster and the parallel side fans out fully
            for i, a in enumerate(accts):
                c = contract_address(i + 1)
                key = X.SCVal.sym("v")
                dk = contract_data_key(c, key,
                                       X.ContractDataDurability.PERSISTENT)
                sd = make_soroban_data(read_write=[dk])
                frames.append(a.tx(
                    [invoke_op(c, "put", [key, X.SCVal.u64(ledger),
                                          X.SCVal.sym("persistent")])],
                    fee=1000 + sd.resourceFee, soroban_data=sd))
            mgr.close_ledger(frames, ct)
        return n_ledgers / (time.perf_counter() - t0), mgr.lcl_hash

    _stage(f"soroban: serial apply ({n_ledgers} mixed ledgers x "
           f"{classic_per_ledger}+{n_accounts} txs)...")
    serial_rate, serial_hash = run(parallel=False)
    if time_left_fn() < (n_ledgers / serial_rate) * 1.2 + 30:
        return {"soroban": "PARTIAL(budget, serial side only)",
                "soroban_serial_ledgers_per_sec": round(serial_rate, 1),
                "soroban_ledgers": n_ledgers}
    _stage("soroban: footprint-parallel apply...")
    par_rate, par_hash = run(parallel=True)
    assert par_hash == serial_hash, \
        "footprint-parallel close diverged from serial"

    # metering overhead: one account hammering `burn` — wall time per
    # metered instruction through the bounded host's budget charging
    burn_insns = 2_000_000
    mgr, accts = mk_mgr()
    c = contract_address(99)
    sd = make_soroban_data(instructions=burn_insns + 1_000_000)
    n_burn = 20
    ct = 1_800_000_000
    t0 = time.perf_counter()
    for _ in range(n_burn):
        ct += 5
        mgr.close_ledger([accts[0].tx(
            [invoke_op(c, "burn", [X.SCVal.u64(burn_insns)])],
            fee=1000 + sd.resourceFee, soroban_data=sd)], ct)
    burn_wall = time.perf_counter() - t0
    return {
        "soroban_serial_ledgers_per_sec": round(serial_rate, 1),
        "soroban_parallel_ledgers_per_sec": round(par_rate, 1),
        "soroban_parallel_speedup": round(par_rate / serial_rate, 3),
        "soroban_hashes_identical": True,
        "soroban_ledgers": n_ledgers,
        "soroban_clusters_per_ledger": n_accounts,
        "soroban_metered_insns_per_sec": round(
            n_burn * burn_insns / burn_wall, 0),
        "soroban_metering_us_per_invoke": round(
            burn_wall / n_burn * 1e6, 1),
    }


def bench_sampleprof(time_left_fn):
    """Observability plane (ISSUE 16): the always-on sampling profiler's
    overhead on a replay-shaped CPU microbench (tx apply + ledger close
    loop, the hot path the sampler would ride in production).  Interleaved
    off/on/off/on rounds, best-of each arm to shed scheduler noise; the
    <5% overhead claim is ASSERTED, not just reported — a sampler that
    costs more than its budget must fail the bench before shipping."""
    import random as _random

    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.crypto.keys import SecretKey
    from stellar_core_tpu.ledger.manager import LedgerManager
    from stellar_core_tpu.testutils import (TestAccount, create_account_op,
                                            native_payment_op, network_id)
    from stellar_core_tpu.util.sampleprof import SamplingProfiler

    nid = network_id("sampleprof bench")
    # long enough arms that scheduler noise stays well under the 5%
    # overhead budget being asserted (sub-second arms flap the ratio)
    n_ledgers = int(os.environ.get("BENCH_SAMPLEPROF_LEDGERS", "300"))
    txs_per_ledger = 10

    def run_once():
        mgr = LedgerManager(nid, invariant_manager=None)
        mgr.start_new_ledger()
        root_sk = mgr.root_account_secret()
        ent = mgr.root.get_entry(
            X.account_key_xdr(root_sk.public_key.ed25519))
        root = TestAccount(mgr, root_sk, ent.data.value.seqNum)
        sks = [SecretKey(bytes([60 + i]) * 32) for i in range(8)]
        mgr.close_ledger([root.tx([create_account_op(
            X.AccountID.ed25519(sk.public_key.ed25519), 10 ** 12)
            for sk in sks])], 1_700_000_000)
        accts = []
        for sk in sks:
            e = mgr.root.get_entry(X.account_key_xdr(sk.public_key.ed25519))
            accts.append(TestAccount(mgr, sk, e.data.value.seqNum))
        rng = _random.Random(11)
        ct = 1_700_000_000
        t0 = time.perf_counter()
        for _ in range(n_ledgers):
            ct += 5
            frames = []
            for _ in range(txs_per_ledger):
                a = accts[rng.randrange(len(accts))]
                frames.append(a.tx([native_payment_op(
                    accts[rng.randrange(len(accts))].account_id,
                    1000 + rng.randrange(10 ** 6))]))
            mgr.close_ledger(frames, ct)
        return time.perf_counter() - t0

    prof = SamplingProfiler()
    run_once()    # warmup: first run pays import/jit/page-in costs
    off_s, on_s = [], []
    samples = 0
    for round_ in range(4):
        if time_left_fn() < 30:
            break
        off_s.append(run_once())
        prof.start()
        try:
            on_s.append(run_once())
        finally:
            prof.stop()
        samples = prof.snapshot()["samples"]
    if not on_s:
        return {"sampleprof": "SKIPPED(budget, pre-empted mid-section)"}
    # min-of-N per arm: the sampler's true cost is additive and tiny
    # (~5us/sample), while the workload's run-to-run spread is ~10% —
    # the minima converge to each arm's floor
    base, with_prof = min(off_s), min(on_s)
    overhead = with_prof / base
    vals = {
        "sampleprof_off_s": round(base, 4),
        "sampleprof_on_s": round(with_prof, 4),
        "sampleprof_overhead_ratio": round(overhead, 4),
        "sampleprof_samples": samples,
        "sampleprof_ledgers": n_ledgers,
    }
    # the always-on claim: ride-along cost under 5% on the apply path
    assert overhead < 1.05, (
        f"sampling profiler overhead {overhead:.3f}x exceeds the 5% "
        f"always-on budget (off={base:.3f}s on={with_prof:.3f}s)")
    return vals


def bench_fleettrace(time_left_fn):
    """Observability plane (ISSUE 16): merged cross-node trace cost over
    a synthetic 5-node x 4000-mark collection (a soak's worth of phase
    marks) — merge wall-clock and events/s, so a regression in the
    alignment/merge path shows up as a bench row, not a stuck soak
    teardown."""
    from stellar_core_tpu.util.fleettrace import FleetTraceCollector

    n_nodes = 5
    n_marks = int(os.environ.get("BENCH_FLEETTRACE_MARKS", "4000"))
    if time_left_fn() < 20:
        return {"fleettrace": "SKIPPED(budget, pre-empted mid-section)"}
    coll = FleetTraceCollector()
    phases = ("admission-flush", "tx-flood", "nominate", "externalize",
              "close-seal")
    for i in range(n_nodes):
        skew = (i - 2) * 0.75    # seconds of injected wall skew
        marks = []
        for k in range(n_marks):
            slot = 2 + k // len(phases)
            marks.append({
                "seq": k + 1, "phase": phases[k % len(phases)],
                "slot": slot, "wall_s": 1_700_000_000.0 + slot * 5.0
                + (k % len(phases)) * 0.05 + skew,
                "node": f"node-{i}", "tid": 1, "args": {}})
        coll.ingest(f"node-{i}", {"marks": marks, "next_since": n_marks})
    t0 = time.perf_counter()
    doc = coll.merge_chrome_trace()
    merge_s = time.perf_counter() - t0
    events = len(doc["traceEvents"])
    return {
        "fleettrace_nodes": n_nodes,
        "fleettrace_marks_per_node": n_marks,
        "fleettrace_merge_ms": round(merge_s * 1e3, 2),
        "fleettrace_events": events,
        "fleettrace_events_per_sec": round(events / merge_s, 1),
    }


def bench_telemetry(time_left_fn):
    """Historical telemetry (ISSUE 20), two measurements:

    1. capture ride-along — the wall-cadence TimeSeriesStore thread
       snapshotting the whole process registry while the 51-node flagship
       chaos scenario runs.  Interleaved off/on rounds after a discarded
       warmup, min-of-each arm; the <2% overhead claim is ASSERTED (a
       capture plane that taxes consensus more than its budget fails the
       bench before shipping).  The direct accounting (tick count x mean
       tick cost) rides along for diagnosis when the ratio moves.
    2. close-p99 vs read-QPS — concurrent snapshot bulk readers
       (`load_keys` over pinned disk views) at stepped offered rates
       against live closes over a 100k-account BucketListDB; one curve
       row per step so a read-path contention regression shows up as a
       bent curve, not a vague soak slowdown.

    Deadline-aware at every seam: rounds and steps each check the global
    budget and report partial results with an explicit note."""
    import logging as _pylogging
    import random as _random
    import threading

    from stellar_core_tpu.simulation import chaos as chaos_mod
    from stellar_core_tpu.util.metrics import registry
    from stellar_core_tpu.util.timeseries import TimeSeriesStore

    vals = {}
    rounds = int(os.environ.get("BENCH_TELEMETRY_ROUNDS", "2"))

    # --- 1. capture-thread overhead on the 51-node flagship ----------
    def flagship():
        sc = chaos_mod.scenario_partition_flap_heal(17, 3)
        t0 = time.perf_counter()
        res = chaos_mod.run_scenario(sc)
        return time.perf_counter() - t0, res

    est_run = 60.0
    prev_level = _pylogging.getLogger("stellar").level
    _pylogging.getLogger("stellar").setLevel(_pylogging.WARNING)
    off_s, on_s = [], []
    passed = True
    ticks = 0
    try:
        if time_left_fn() < est_run * 3:
            vals["telemetry_capture"] = \
                "SKIPPED(budget, pre-empted mid-section)"
        else:
            flagship()    # warmup: import/jit/page-in costs, discarded
            for _ in range(rounds):
                if time_left_fn() < est_run * 2.5:
                    break
                w, res = flagship()
                off_s.append(w)
                passed = passed and res.passed
                # production cadence (1s), production payload: the whole
                # registry, which at this point carries all 51 nodes
                ts = TimeSeriesStore(cadence_s=1.0)
                ts.start()
                try:
                    w, res = flagship()
                finally:
                    ts.stop()
                on_s.append(w)
                passed = passed and res.passed
                ticks = ts.seq
    finally:
        _pylogging.getLogger("stellar").setLevel(prev_level)
    if on_s:
        base, with_ts = min(off_s), min(on_s)
        overhead = with_ts / base
        tick = registry().snapshot(prefix="timeseries.").get(
            "timeseries.capture.tick-time", {})
        vals.update({
            "telemetry_capture_off_s": round(base, 2),
            "telemetry_capture_on_s": round(with_ts, 2),
            "telemetry_capture_overhead_ratio": round(overhead, 4),
            "telemetry_capture_rounds": len(on_s),
            "telemetry_capture_ticks": ticks,
            "telemetry_capture_tick_ms": round(
                tick.get("mean_s", 0.0) * 1e3, 3),
            "telemetry_flagship_nodes": 51,
            "telemetry_flagship_passed": passed,
        })
        # the always-on claim: historical capture rides along under 2%
        assert overhead < 1.02, (
            f"telemetry capture overhead {overhead:.3f}x exceeds the 2% "
            f"ride-along budget (off={base:.2f}s on={with_ts:.2f}s)")
    elif "telemetry_capture" not in vals:
        vals["telemetry_capture"] = "SKIPPED(budget, pre-empted mid-section)"

    # --- 2. close-p99 vs read-QPS over a 100k-account BucketListDB ---
    if time_left_fn() < 180.0:
        vals["telemetry_curve"] = "SKIPPED(budget, pre-empted mid-section)"
        return vals
    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.simulation.loadgen import AdmissionCampaign

    accounts = int(os.environ.get("BENCH_TELEMETRY_ACCOUNTS", "100000"))
    cap = 200
    _stage(f"telemetry contention curve ({accounts} accounts over "
           "BucketListDB)...")
    with tempfile.TemporaryDirectory() as d:
        t0 = time.perf_counter()
        c = AdmissionCampaign(n_accounts=accounts, workdir=d,
                              max_tx_set_ops=cap, max_backlog=2000)
        vals["telemetry_curve_accounts"] = accounts
        vals["telemetry_curve_install_s"] = round(
            time.perf_counter() - t0, 1)
        try:
            c.run(n_ledgers=1, offered_per_ledger=cap)   # page-in round
            rng = _random.Random(23)
            keys = [X.account_key_xdr(
                c.pool.secret(rng.randrange(c.pool.n)).public_key.ed25519)
                for _ in range(2048)]
            n_threads, batch = 4, 64
            curve = []
            for target_qps in (0, 5_000, 20_000, 80_000):
                if time_left_fn() < 45.0:
                    vals["telemetry_curve_note"] = \
                        "pre-empted mid-curve (budget); rows above stand"
                    break
                stop = threading.Event()
                reads = []
                threads = []
                snaps = []
                for t in range(n_threads if target_qps else 0):
                    # snapshots built between steps (main thread only);
                    # immutable buckets + store pins make the concurrent
                    # reads safe while closes advance the live list
                    snap = c.mgr.bucket_list.snapshot(
                        c.mgr.last_closed_ledger_seq, store=c.store)
                    snaps.append(snap)
                    box = [0]
                    reads.append(box)
                    trng = _random.Random(100 + t)
                    interval = batch / (target_qps / n_threads)

                    def read_loop(snap=snap, box=box, trng=trng,
                                  interval=interval):
                        nxt = time.perf_counter()
                        while not stop.is_set():
                            snap.load_keys([
                                keys[trng.randrange(len(keys))]
                                for _ in range(batch)])
                            box[0] += batch
                            nxt += interval
                            delay = nxt - time.perf_counter()
                            if delay > 0:
                                time.sleep(delay)
                            else:
                                nxt = time.perf_counter()  # saturated
                    th = threading.Thread(target=read_loop,
                                          name=f"bench-reader-{t}",
                                          daemon=True)
                    threads.append(th)
                    th.start()
                registry().timer("ledger.ledger.close").reset()
                t0 = time.perf_counter()
                c.run(n_ledgers=3, offered_per_ledger=cap)
                step_wall = time.perf_counter() - t0
                stop.set()
                for th in threads:
                    th.join()
                for snap in snaps:
                    snap.release()
                cl = registry().snapshot(prefix="ledger.ledger.").get(
                    "ledger.ledger.close", {})
                curve.append({
                    "target_read_qps": target_qps,
                    "achieved_read_qps": round(
                        sum(b[0] for b in reads) / step_wall, 1),
                    "close_p50_ms": round(cl.get("p50_s", 0.0) * 1e3, 2),
                    "close_p99_ms": round(cl.get("p99_s", 0.0) * 1e3, 2),
                    "ledgers": 3,
                })
            vals["telemetry_curve"] = curve
            if curve:
                vals["telemetry_read_peak_qps"] = max(
                    row["achieved_read_qps"] for row in curve)
                vals["telemetry_curve_baseline_p99_ms"] = \
                    curve[0]["close_p99_ms"]
                vals["telemetry_curve_loaded_p99_ms"] = \
                    curve[-1]["close_p99_ms"]
        finally:
            c.close()
    return vals


def bench_merge_throughput(workdir):
    """ISSUE 3 acceptance: streaming-merge throughput.  Two synthetic
    buckets (disjoint + colliding keys) merged by the decoded path and by
    merge_buckets_raw (file-to-file, decode-free), hash identity asserted,
    entries/s + MB/s reported."""
    from stellar_core_tpu import xdr as X
    from stellar_core_tpu.bucket import (Bucket, BucketListStore,
                                         merge_buckets, merge_buckets_raw)
    from stellar_core_tpu.crypto.keys import SecretKey

    n = int(os.environ.get("BENCH_MERGE_ENTRIES", "20000"))

    def acct(i):
        sk = SecretKey(bytes([i % 251 + 1]) * 28 + i.to_bytes(4, "big"))
        return X.LedgerEntry(
            lastModifiedLedgerSeq=1,
            data=X.LedgerEntryData.account(X.AccountEntry(
                accountID=X.AccountID.ed25519(sk.public_key.ed25519),
                balance=10 ** 9 + i, seqNum=1)))

    old = Bucket.fresh(23, [acct(i) for i in range(n)], [], [])
    new = Bucket.fresh(23, [], [acct(i) for i in range(n // 2, n + n // 2)],
                       [])
    store = BucketListStore(os.path.join(workdir, "merge-bench"))
    # make both inputs disk-resident so the raw pass measures the real
    # deep-level regime: file-to-file, no decoded entries anywhere
    old_d = merge_buckets_raw(old, Bucket.empty(), True, None, store)
    new_d = merge_buckets_raw(new, Bucket.empty(), True, None, store)

    t0 = time.perf_counter()
    mem = merge_buckets(old, new, True)
    mem_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    raw = merge_buckets_raw(old_d, new_d, True, None, store)
    raw_s = time.perf_counter() - t0
    assert mem.hash() == raw.hash(), "streaming merge diverged"
    out_entries = len(raw)
    out_bytes = raw.disk_index()._file_size
    return {
        "merge_entries_in": 2 * n,
        "merge_entries_out": out_entries,
        "merge_raw_entries_per_sec": round(out_entries / raw_s, 1),
        "merge_raw_mb_per_sec": round(out_bytes / raw_s / 1e6, 2),
        "merge_raw_vs_decoded": round(mem_s / raw_s, 3),
        "merge_hashes_identical": True,
    }


def bench_bucketlistdb():
    """ISSUE 2 acceptance: the bench line reports the BucketListDB entry-
    cache hit rate and load-latency percentiles.  ISSUE 3 adds the phase-2
    memory story: peak decoded-entry count under default residency plus
    streaming-merge counters, with disk/memory hash identity ASSERTED
    across the multi-checkpoint replay.  CPU-only (no device): one small
    archive replayed both ways — in-memory dict root vs disk-backed
    BucketListDB root — with the relative replay rate recorded."""
    from stellar_core_tpu.bucket import BucketListStore
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.crypto import keys
    from stellar_core_tpu.testutils import network_id
    from stellar_core_tpu.util.metrics import registry, reset_registry

    passphrase = "bucketlistdb bench"
    nid = network_id(passphrase)
    with tempfile.TemporaryDirectory() as d:
        archive, mgr = build_archive(
            nid, passphrase, os.path.join(d, "archive"),
            n_payment_ledgers=int(os.environ.get(
                "BENCH_BLDB_LEDGERS", "120")), txs_per_ledger=20)
        n = mgr.last_closed_ledger_seq
        keys.clear_verify_cache()
        t0 = time.perf_counter()
        m_mem = CatchupManager(nid, passphrase,
                               native=False).catchup_complete(archive)
        mem_s = time.perf_counter() - t0
        # isolate the bucketlistdb.* metric slice to the disk replay
        reset_registry()
        keys.clear_verify_cache()
        store = BucketListStore(os.path.join(d, "bucketlistdb"))
        cm = CatchupManager(nid, passphrase, native=False,
                            bucket_store=store, entry_cache_size=4096)
        t0 = time.perf_counter()
        m_disk = cm.catchup_complete(archive)
        disk_s = time.perf_counter() - t0
        assert m_disk.lcl_hash == m_mem.lcl_hash == mgr.lcl_hash, \
            "bucketlistdb replay diverged from the in-memory path"
        stats = m_disk.root.cache_stats()
        bl = m_disk.bucket_list
        out = {
            "bucketlistdb_replay_ledgers": n,
            "bucketlistdb_cache_hit_rate": stats.get("hit_rate", 0.0),
            "bucketlistdb_cache_entries": stats.get("size", 0),
            "bucketlistdb_cache_max": stats.get("max_size", 0),
            "bucketlistdb_ledgers_per_sec": round(n / disk_s, 1),
            "bucketlistdb_vs_in_memory": round(mem_s / disk_s, 3),
            "bucketlistdb_hashes_identical": True,
            # phase 2 memory story: peak decoded entries across the whole
            # replay vs the ledger's live-entry count (the old O(ledger))
            "bucketlistdb_resident_levels": bl.resident_levels,
            "bucketlistdb_peak_resident_entries": bl.peak_decoded_entries,
            "bucketlistdb_end_resident_entries": bl.decoded_entry_count(),
            "bucketlistdb_total_live_entries": m_disk.root.entry_count(),
        }
        load = registry().snapshot(prefix="bucketlistdb.").get(
            "bucketlistdb.load")
        if load:
            out["bucketlistdb_loads"] = load["count"]
            for q in ("p50", "p90", "p99"):
                out[f"bucketlistdb_load_{q}_us"] = round(
                    load[f"{q}_s"] * 1e6, 1)
        bsnap = registry().snapshot(prefix="bucket.merge.")
        stream = bsnap.get("bucket.merge.stream")
        if stream:
            out["bucketlistdb_stream_merges"] = stream["count"]
            out["bucketlistdb_stream_merge_p90_ms"] = round(
                stream["p90_s"] * 1e3, 2)
        mbytes = bsnap.get("bucket.merge.bytes")
        if mbytes:
            out["bucketlistdb_stream_merge_bytes"] = mbytes["count"]
        out.update(bench_merge_throughput(d))
    return out


def bench_sigs():
    """Config #2: raw batch-verify throughput vs single-core libsodium."""
    import random
    from stellar_core_tpu.accel.ed25519 import Ed25519BatchVerifier
    from stellar_core_tpu.crypto import sodium

    rng = random.Random(7)
    n_total = 65536
    # round-4 A/B (experiments/sig_chunk_ab.py): the backend pipelines
    # in-flight chunks, so several mid-size dispatches beat one full-width
    # one — 46.2k sigs/s @ chunk 16384 (4 in flight) vs 43.9k @ 32768 vs
    # 38.2k @ 65536.  Round 3's "width is the lever" held only for
    # serial one-chunk-at-a-time dispatch.
    chunk = 16384
    n_base = 3000
    keys = [sodium.sign_seed_keypair(bytes([i]) * 32) for i in range(64)]
    pks, sigs, msgs = [], [], []
    n_bad = 0
    for i in range(n_total):
        pk, sk = keys[i % len(keys)]
        msg = rng.randbytes(120)
        sig = sodium.sign_detached(msg, sk)
        if i % 100 == 99:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            n_bad += 1
        pks.append(pk)
        sigs.append(sig)
        msgs.append(msg)

    v = Ed25519BatchVerifier(chunk_size=chunk)
    v.verify(pks[:chunk], sigs[:chunk], msgs[:chunk])  # compile + warm
    # the shared chip drifts 20-66% minute to minute (r3: 58.3k sigs/s,
    # r4 morning: 35.1k, same code) — interleave (cpu, tpu) x 3 and report
    # medians so one bad minute doesn't become the round's record
    base_rates, tpu_rates = [], []
    for _ in range(3):
        t0 = time.perf_counter()
        acc = 0
        for i in range(n_base):
            acc += sodium.verify_detached(sigs[i], msgs[i], pks[i])
        base_rates.append(n_base / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        verdicts = v.verify(pks, sigs, msgs)
        tpu_rates.append(n_total / (time.perf_counter() - t0))
        assert int(verdicts.sum()) == n_total - n_bad
    med = lambda xs: sorted(xs)[len(xs) // 2]
    return med(tpu_rates), med(base_rates)


def bench_replay(nid, passphrase, archive, expected_hash, rounds=3,
                 time_left_fn=None):
    """Configs #1 + #4: ledgers/sec CPU vs accel.  The rig's shared TPU
    drifts 20-40% run to run, so passes are INTERLEAVED (cpu, accel) x
    `rounds` and the medians reported; identical hashes asserted on every
    pass.  The accel pass reports a per-phase breakdown
    (dispatch host prep / collect sync-stall).

    `time_left_fn` is the global bench deadline (ISSUE 5 satellite: the
    PR 3 budget only gated sections that hadn't STARTED — BENCH_r05 hit
    rc=124 cut mid-replay).  The deadline now pre-empts the replay
    section itself: each completed (cpu, accel) round updates the
    per-round cost estimate, and a next round that no longer fits is
    skipped — partial results (medians over completed rounds) are
    reported instead of the whole run dying.  Returns None when not even
    one round fit."""
    import time as _time

    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.crypto import keys

    has = archive.get_state()
    n_ledgers = has.current_ledger

    if time_left_fn is not None and time_left_fn() < 240:
        _stage("replay: archive build consumed the section budget — "
               "skipping all rounds")
        return None

    _stage("replay: accel warm pass (compiles)...")
    keys.clear_verify_cache()
    # per-key tables (hot_threshold=4): with the native apply engine the
    # device is the replay critical path, and the table kernel's ~2.5x
    # lighter compute is the best accel variant (r5 A/B:
    # experiments/out_replay_tables_ab_r5.txt — tables 215 l/s vs generic
    # 187 l/s vs native-cpu 345 l/s on the same interleaved rounds)
    cm_warm = CatchupManager(nid, passphrase, accel=True, accel_chunk=8192,
                             accel_hot_threshold=4)
    cm_warm.catchup_complete(archive, to_ledger=127)

    cpu_rates, tpu_rates = [], []
    phases = {}
    hit_rate = 0.0
    rounds_skipped = 0
    round_cost_s = None   # measured cost of one full (cpu, accel) round
    for r in range(rounds):
        if time_left_fn is not None and round_cost_s is not None \
                and time_left_fn() < round_cost_s * 1.25:
            rounds_skipped = rounds - r
            _stage(f"replay: PRE-EMPTED after {r}/{rounds} rounds "
                   f"(next round needs ~{round_cost_s:.0f}s, "
                   f"{time_left_fn():.0f}s left)")
            break
        t_round = _time.perf_counter()
        _stage(f"replay round {r + 1}/{rounds}: cpu...")
        keys.clear_verify_cache()
        cm_cpu = CatchupManager(nid, passphrase, accel=False)
        t0 = time.perf_counter()
        m = cm_cpu.catchup_complete(archive)
        cpu_rates.append(n_ledgers / (time.perf_counter() - t0))
        assert m.lcl_hash == expected_hash
        _stage(f"replay round {r + 1}/{rounds}: accel...")
        keys.clear_verify_cache()
        # the registry is process-global and by now holds the archive
        # build + all CPU rounds; reset before EVERY accel pass — not
        # just the planned last one — so the observability snapshot
        # embedded in the bench record describes exactly ONE accel
        # replay even when the deadline pre-empts later rounds
        # (otherwise crypto.verify.recompute is ~all CPU-round libsodium
        # work and the close quantiles blend every phase)
        from stellar_core_tpu.util.metrics import reset_registry
        reset_registry()
        cm_tpu = CatchupManager(nid, passphrase, accel=True,
                                accel_chunk=8192, accel_hot_threshold=4)
        t0 = time.perf_counter()
        m2 = cm_tpu.catchup_complete(archive)
        tpu_rates.append(n_ledgers / (time.perf_counter() - t0))
        assert m2.lcl_hash == expected_hash, "accel replay diverged"
        hit_rate = cm_tpu.offload_hit_rate()
        phases = {k: round(v, 3) if isinstance(v, float) else v
                  for k, v in cm_tpu.stats.items()}
        round_cost_s = _time.perf_counter() - t_round

    if not cpu_rates:
        return None   # budget pre-empted before one full round completed

    med = lambda xs: sorted(xs)[len(xs) // 2]
    # drift-resistant headline (VERDICT r4 item 6): per-round arrays + the
    # ratio as the MEDIAN OF PER-ROUND PAIRS (each pair shares one drift
    # window), min/max recorded alongside
    pair_ratios = [t / c for c, t in zip(cpu_rates, tpu_rates)]
    phases["cpu_rates"] = [round(x, 1) for x in cpu_rates]
    phases["accel_rates"] = [round(x, 1) for x in tpu_rates]
    phases["pair_ratios"] = [round(x, 3) for x in pair_ratios]
    phases["ratio_min"] = round(min(pair_ratios), 3)
    phases["ratio_max"] = round(max(pair_ratios), 3)
    phases["ratio_median_of_pairs"] = round(med(pair_ratios), 3)
    if rounds_skipped:
        phases["rounds_skipped_budget"] = rounds_skipped
    return med(cpu_rates), med(tpu_rates), hit_rate, n_ledgers, phases


def observability_snapshot(hit_rate):
    """The metrics-registry slice that rides along in BENCH_*.json so
    hit-rates, batch-size distributions and stage percentiles are
    comparable round to round (ISSUE 1 exposition: bench embeds the accel
    preverify hit rate and ed25519 batch-size metrics)."""
    from stellar_core_tpu.util.metrics import registry
    out = {"sig_offload_hit_rate": round(hit_rate, 3)}
    out.update(registry().snapshot(prefix="accel."))
    # whole catchup family: download/apply stage timers record on BOTH
    # engines (the native C apply bypasses the Python ledger.ledger.close
    # timer, so that slice alone would be empty on a standard run)
    out.update(registry().snapshot(prefix="catchup."))
    out.update(registry().snapshot(prefix="ledger.ledger.close"))
    return out


def tier1_quorum_map(n_orgs=9):
    """Config #3 shape: orgs x 3 validators, inner-set 2-of-3, top-level
    threshold 2/3 of orgs (the pubnet tier-1 topology shape; answered via
    the symmetric-org contraction in the CPU checker)."""
    from stellar_core_tpu import xdr as X

    per_org = 3
    ids = [bytes([o + 1]) * 31 + bytes([v]) for o in range(n_orgs)
           for v in range(per_org)]
    inner = []
    for o in range(n_orgs):
        inner.append(X.SCPQuorumSet(
            threshold=2,
            validators=[X.NodeID.ed25519(ids[o * per_org + v])
                        for v in range(per_org)],
            innerSets=[]))
    qset = X.SCPQuorumSet(threshold=(2 * n_orgs + 2) // 3,
                          validators=[], innerSets=inner)
    return {nid: qset for nid in ids}


def adversarial_quorum_map(n=16):
    """Config #5 shape (scaled to driver runtime): interlocking rings that
    force deep enumeration."""
    from stellar_core_tpu import xdr as X
    ids = [bytes([i + 1]) * 32 for i in range(n)]
    qmap = {}
    for i in range(n):
        members = [ids[(i + d) % n] for d in range(0, 6)]
        qmap[ids[i]] = X.SCPQuorumSet(
            threshold=4,
            validators=[X.NodeID.ed25519(m) for m in members],
            innerSets=[])
    return qmap


def asym_org_map(n_orgs):
    """Config #5's exponential class (single definition shared with the
    differential tests: stellar_core_tpu.testutils.asym_org_qmap).
    Measured growth per org: CPU ~58x, TPU frontier ~13x (see BASELINE.md
    config 5 crossover table)."""
    from stellar_core_tpu.testutils import asym_org_qmap
    return asym_org_qmap(n_orgs)


def _quorum_map_for(row: str):
    if row == "tier1":
        return tier1_quorum_map()
    if row == "rings16":
        return adversarial_quorum_map()
    if row == "rings12":
        return adversarial_quorum_map(12)
    if row.startswith("asym"):
        return asym_org_map(int(row[len("asym"):]))
    raise ValueError(f"unknown quorum bench row {row!r}")


def _quorum_cell_main(row: str, engine: str) -> int:
    """Body of `python bench.py --quorum-cell ROW ENGINE`: one quorum
    matrix cell in its OWN process, so the parent can pre-empt it with a
    hard kill when it overruns the global deadline (BENCH_r05 died rc=124
    inside an in-process cell no soft check could interrupt).  Prints one
    JSON line: the measured wall-clock of the check itself (imports and
    TPU compile warm excluded, like the old in-process rows)."""
    from stellar_core_tpu.herder.quorum_intersection import (
        QuorumIntersectionChecker, check_intersection, _cquorum)

    qmap = _quorum_map_for(row)
    if engine == "contraction":
        fn = lambda: check_intersection(qmap)
    elif engine == "py":
        # pure-Python enumeration, bypassing the native core AND the
        # symmetric-org contraction (the oracle row of the matrix)
        fn = lambda: QuorumIntersectionChecker(qmap)._check_python()
    elif engine == "c":
        if _cquorum is None:
            # the pure-Python fallback is 14-23x slower and would blow the
            # budget the estimates are calibrated for
            print(json.dumps({"skipped": "no native engine"}))
            return 0
        fn = lambda: QuorumIntersectionChecker(qmap)._check_native()
    elif engine == "tpu":
        from stellar_core_tpu.accel.quorum import check_intersection_tpu
        check_intersection_tpu(adversarial_quorum_map(12))  # compile warm
        fn = lambda: check_intersection_tpu(qmap, batch_size=8192)
    else:
        print(json.dumps({"skipped": f"unknown engine {engine}"}))
        return 2
    t0 = time.perf_counter()
    res = fn()
    print(json.dumps({"s": round(time.perf_counter() - t0, 3),
                      "intersects": bool(res.intersects)}))
    return 0


def _run_quorum_cell(row: str, engine: str, timeout_s: float) -> dict:
    """Run one cell subprocess under a hard kill timeout.  Returns the
    cell's JSON doc, {"preempted": wall_s} on timeout, or
    {"failed": rc, "detail": ...} on an abnormal exit."""
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__),
           "--quorum-cell", row, engine]
    t0 = time.perf_counter()
    try:
        r = subprocess.run(cmd, capture_output=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return {"preempted": round(time.perf_counter() - t0, 1)}
    lines = [ln for ln in r.stdout.decode().splitlines() if ln.strip()]
    if r.returncode != 0 or not lines:
        return {"failed": r.returncode,
                "detail": r.stderr.decode(errors="replace")[-300:]}
    try:
        return json.loads(lines[-1])
    except ValueError:
        return {"failed": r.returncode, "detail": lines[-1][-300:]}


def bench_quorum(time_left_fn, budget_s: float = 700.0):
    """Config 3 + 5 as a CROSSOVER MATRIX (VERDICT r4 item 4): tier-1,
    rings and asym orgs=5..7 across all three engines — pure Python
    enumeration (the semantic oracle), native C (native/cquorum.c) and the
    TPU frontier enumerator — with per-engine wall-clocks in the driver
    record.

    Every cell (one quorum core's check on one engine) runs in its own
    subprocess with a HARD kill timeout bounded by both the matrix budget
    and the remaining global BENCH_DEADLINE_S — the BENCH_r05 rc=124
    post-mortem: an in-process cell that overran its estimate could not be
    interrupted, so the driver's timeout fired before the JSON line.  Now
    an overrunning cell is pre-empted mid-run, emits a SKIPPED row (the
    last-good cache supplies its stale value), and the section ALWAYS
    returns within the deadline.  r4 reference costs (slow-chip day):
    asym5 C 0.3s / TPU 56s; asym6 py 181s / C 9s / TPU 71s; asym7 C 93s /
    TPU 255s; TPU cells re-pay the compile warm per cell (excluded from
    the reported number)."""
    t_start = time.perf_counter()
    matrix = {}
    RESERVE_S = 30.0   # the reporting tail must always fit

    def left():
        return min(budget_s - (time.perf_counter() - t_start),
                   time_left_fn() - RESERVE_S)

    def run(row, engine, estimate_s, expect=None):
        key = f"{row}_{engine}_s"
        lf = left()
        if lf < estimate_s * 1.25:
            matrix[key] = "SKIPPED(budget)"
            return
        # the kill bound: generous vs the estimate (4x) but never past
        # what the global deadline still affords
        cell = _run_quorum_cell(row, engine,
                                timeout_s=max(5.0, min(lf, estimate_s * 4)))
        if "preempted" in cell:
            _stage(f"quorum cell {row}/{engine} PRE-EMPTED after "
                   f"{cell['preempted']}s (estimate {estimate_s}s)")
            matrix[key] = (f"SKIPPED(budget, pre-empted after "
                           f"{cell['preempted']}s)")
        elif "failed" in cell:
            _stage(f"quorum cell {row}/{engine} failed rc={cell['failed']}: "
                   f"{cell.get('detail', '')!r}")
            matrix[key] = f"FAILED(rc={cell['failed']})"
        elif "skipped" in cell:
            matrix[key] = f"SKIPPED({cell['skipped']})"
        else:
            matrix[key] = cell["s"]
            if expect is not None:
                assert cell["intersects"] == expect, (row, engine)

    # tier-1 shape: answered by the symmetric-org contraction (product
    # fast path) in ms — engine-independent; estimates include the cell's
    # interpreter spin-up (and, for tpu, jax import + compile warm)
    run("tier1", "contraction", 3, expect=True)
    run("rings16", "py", 4, expect=True)
    run("rings16", "c", 3, expect=True)
    run("rings16", "tpu", 45, expect=True)
    run("asym5", "py", 10, expect=True)
    run("asym5", "c", 4, expect=True)
    run("asym5", "tpu", 85, expect=True)
    matrix["asym6_py_s"] = "SKIPPED(~180s, over per-row budget)"
    run("asym6", "c", 14, expect=True)
    run("asym6", "tpu", 105, expect=True)
    matrix["asym7_py_s"] = "SKIPPED(>900s measured r3)"
    run("asym7", "c", 115, expect=True)
    run("asym7", "tpu", 275, expect=True)
    matrix["quorum_matrix_budget_s"] = budget_s
    matrix["quorum_matrix_spent_s"] = round(time.perf_counter() - t_start, 1)
    return matrix


def probe_device(timeout_s: float = 120.0, attempts: int = 3) -> bool:
    """The shared tunneled TPU wedges occasionally (observed: RPCs that
    never return, freezing the calling thread).  Probe it in a SUBPROCESS
    with a hard timeout so a sick tunnel fails the bench fast and honestly
    instead of hanging the driver."""
    import subprocess
    code = ("import jax, jax.numpy as jnp, numpy as np;"
            "x = jnp.asarray(np.ones((128, 128), np.float32));"
            "print(int(np.asarray(x @ x)[0, 0]))")
    for i in range(attempts):
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, timeout=timeout_s)
            if r.returncode == 0 and b"128" in r.stdout:
                return True
            _stage(f"device probe attempt {i + 1} failed: "
                   f"{r.stderr[-200:]!r}")
        except subprocess.TimeoutExpired:
            _stage(f"device probe attempt {i + 1} timed out ({timeout_s}s)")
        if i + 1 < attempts:
            time.sleep(30)
    return False


_watchdog_cancel = None


def _arm_watchdog(deadline_s: float = 2100.0):
    """The tunnel can wedge MID-bench (after a healthy probe): a daemon
    watchdog prints the degraded JSON line and hard-exits rather than
    hanging the driver forever.  Normal full runs finish in ~12-18 min;
    the deadline leaves slack.  Returns a cancel() callable; re-arming
    (the retry path) cancels the previous timer first."""
    import threading
    global _watchdog_cancel
    if _watchdog_cancel is not None:
        _watchdog_cancel()

    def fire():
        _stage(f"WATCHDOG: bench exceeded {deadline_s}s — device presumed "
               "wedged mid-run; emitting degraded report")
        print(json.dumps(_degraded_report(
            f"bench watchdog fired after {deadline_s}s (tunnel wedged "
            "mid-run); numbers below are the last good on-chip results, "
            "stale-flagged with their age")), flush=True)
        os._exit(3)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()
    _watchdog_cancel = t.cancel
    return t.cancel


SUMMARY_PATH = os.environ.get("BENCH_SUMMARY_PATH") or os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_SUMMARY.json")

# sections whose cached rows predate the PR-14 never-wait poll profile:
# the r05 bench run was killed by the driver budget (rc=124) before the
# accel sections re-measured, so their last-good rows are older-profile
STALE_AFTER_HOURS = 24.0


def _summary_main() -> int:
    """`bench.py --summary`: render BENCH_CACHE.json's last-good rows
    into BENCH_SUMMARY.json — one section per cached bench section with
    its age and staleness flags — WITHOUT touching the device.  This is
    the driver/reviewer view of 'what numbers do we actually have, and
    how old are they'."""
    cache = _cache_load()
    if not cache:
        print(json.dumps({"error": f"no cache at {CACHE_PATH}"}))
        return 1
    now = time.time()
    sections = {}
    for name in sorted(cache):
        got = cache[name]
        age_h = round(
            (now - got.get("measured_at_unix", 0.0)) / 3600.0, 1)
        vals = got.get("values", {})
        restored = vals.get("restored_rows")
        sections[name] = {
            "measured_at": got.get("measured_at"),
            "age_hours": age_h,
            "stale": age_h > STALE_AFTER_HOURS,
            "partially_restored": bool(restored),
            "restored_rows": restored or {},
            "source": got.get("source"),
            "values": {k: v for k, v in vals.items()
                       if k != "restored_rows"},
        }
    doc = {
        "generated_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime(now)),
        "cache_path": CACHE_PATH,
        "stale_after_hours": STALE_AFTER_HOURS,
        "note": ("last-good rows from BENCH_CACHE.json; 'stale' rows "
                 "were measured more than stale_after_hours ago, "
                 "'partially_restored' sections carry rows restored "
                 "from an even older run (see restored_rows for the "
                 "run that measured each)"),
        "sections": sections,
    }
    tmp = SUMMARY_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2)
    os.replace(tmp, SUMMARY_PATH)
    print(json.dumps({"summary": SUMMARY_PATH,
                      "sections": len(sections),
                      "stale": sorted(n for n, s in sections.items()
                                      if s["stale"])}))
    return 0


def _stale_fill(extra: dict, section: str) -> dict:
    """Pull a skipped section's last-good cached values into `extra`,
    age-stamped and stale-flagged (never bare zeros while evidence
    exists).  Returns the cached values dict ({} when none)."""
    got = _cache_load().get(section)
    if not got:
        return {}
    extra.update(got["values"])
    extra[f"{section}_measured_at"] = got["measured_at"]
    extra[f"{section}_age_hours"] = round(
        (time.time() - got["measured_at_unix"]) / 3600.0, 1)
    extra[f"{section}_stale"] = True
    return got["values"]


def main():
    from stellar_core_tpu.testutils import network_id

    passphrase = "bench network"
    nid = network_id(passphrase)
    extra = {"bench_budget_s": BENCH_BUDGET_S}
    value = vs = 0.0

    # corelint is pure CPU and cheap (~1s for the current tree): measure
    # it first so the gate's cost trend is in every report
    _stage("corelint bench...")
    lint_vals = bench_lint()
    _cache_put("lint", lint_vals)
    extra.update(lint_vals)

    # race-sanitizer overhead: pure CPU, sub-second — alongside corelint
    # so every report carries the `make race` tax (ISSUE 9)
    _stage("racetrace overhead bench...")
    rt_vals = bench_racetrace()
    _cache_put("racetrace", rt_vals)
    extra.update(rt_vals)

    # ASan+UBSan differential tier (ISSUE 15): CPU-only subprocess run of
    # `make native-asan`'s suite — deadline-aware and last-good cached
    # like every section, SKIPPED(no-toolchain) where cc/libasan is absent
    if budget_fits("native_asan", 180):
        _stage("native ASan+UBSan differential tier...")
        asan_vals = bench_native_asan(time_left)
        _cache_put("native_asan", _merge_last_good("native_asan", asan_vals))
        extra.update(asan_vals)
    else:
        extra["native_asan"] = "SKIPPED(budget)"
        _stale_fill(extra, "native_asan")

    # BucketListDB differential runs on CPU — measure it before touching
    # the (occasionally wedged) device so the numbers exist either way
    if budget_fits("bucketlistdb", 240):
        _stage("bucketlistdb bench (CPU-only)...")
        bldb = bench_bucketlistdb()
        _cache_put("bucketlistdb", bldb)
        extra.update(bldb)
    else:
        extra["bucketlistdb"] = "SKIPPED(budget)"
        _stale_fill(extra, "bucketlistdb")

    # chaos campaigns are CPU-only too; the section degrades scenario by
    # scenario under the global deadline (cheapest first)
    if budget_fits("chaos", 150):
        _stage("chaos campaign bench (CPU-only)...")
        chaos_vals = bench_chaos(time_left)
        _cache_put("chaos", _merge_last_good("chaos", chaos_vals))
        extra.update(chaos_vals)
    else:
        extra["chaos"] = "SKIPPED(budget)"
        _stale_fill(extra, "chaos")

    # determinism tier (ISSUE 19): detguard overhead (in-process) + the
    # hash-seed differential flagship pair (subprocesses) — CPU-only
    if budget_fits("determinism", 140):
        _stage("determinism bench (CPU-only)...")
        det_vals = bench_determinism(time_left)
        _cache_put("determinism", _merge_last_good("determinism", det_vals))
        extra.update(det_vals)
    else:
        extra["determinism"] = "SKIPPED(budget)"
        _stale_fill(extra, "determinism")

    # batched authenticated transport (ISSUE 18): MAC/codec microbench,
    # single-message floor, then the flagship/soak campaign pairs —
    # each tier budget-gated inside the section
    if budget_fits("transport", 160):
        _stage("transport bench (CPU-only)...")
        tr_vals = bench_transport(time_left)
        _cache_put("transport", _merge_last_good("transport", tr_vals))
        extra.update(tr_vals)
    else:
        extra["transport"] = "SKIPPED(budget)"
        _stale_fill(extra, "transport")

    # sustained-ingestion section (ISSUE 7): CPU-only like the two above,
    # degrades to floor-only then SKIPPED under the deadline
    if budget_fits("admission", 90):
        _stage("admission bench (CPU-only)...")
        adm_vals = bench_admission(time_left)
        _cache_put("admission", _merge_last_good("admission", adm_vals))
        extra.update(adm_vals)
    else:
        extra["admission"] = "SKIPPED(budget)"
        _stale_fill(extra, "admission")

    # fleet soak (ISSUE 11): 3 real TCP node processes + kill/rejoin —
    # CPU-only composition of overlay/admission/catchup/history
    if budget_fits("fleet", 280):
        _stage("fleet bench (3-node TCP soak, CPU-only)...")
        fleet_vals = bench_fleet(time_left)
        if fleet_vals is None:
            extra["fleet"] = "SKIPPED(budget, pre-empted mid-section)"
            _stale_fill(extra, "fleet")
        else:
            _cache_put("fleet", _merge_last_good("fleet", fleet_vals))
            extra.update(fleet_vals)
    else:
        extra["fleet"] = "SKIPPED(budget)"
        _stale_fill(extra, "fleet")

    # mesh catchup scaling curve (ISSUE 14): N=1/2/4/8 device-pinned
    # range workers + work stealing, hash identity + monotone scaling +
    # steal-beats-straggler asserted
    if budget_fits("catchup_mesh", 300):
        _stage("catchup_mesh bench (CPU-simulated device mesh)...")
        cmesh = bench_catchup_mesh(time_left)
        _cache_put("catchup_mesh", _merge_last_good("catchup_mesh", cmesh))
        extra.update(cmesh)
    else:
        extra["catchup_mesh"] = "SKIPPED(budget)"
        _stale_fill(extra, "catchup_mesh")

    # range-parallel catchup (ISSUE 10): CPU-only subprocess workers —
    # wall-clock single-stream vs N=2/4 with hash identity + stitch proof
    if budget_fits("catchup_parallel", 240):
        _stage("catchup_parallel bench (CPU-only)...")
        cpar = bench_catchup_parallel(time_left)
        if cpar is None:
            extra["catchup_parallel"] = \
                "SKIPPED(budget, pre-empted mid-section)"
            _stale_fill(extra, "catchup_parallel")
        else:
            _cache_put("catchup_parallel",
                       _merge_last_good("catchup_parallel", cpar))
            extra.update(cpar)
    else:
        extra["catchup_parallel"] = "SKIPPED(budget)"
        _stale_fill(extra, "catchup_parallel")

    # native live close (ISSUE 13): CPU-only, live LedgerManager.close
    # through the C engine vs Python on identical traffic
    if budget_fits("native_close", 150):
        _stage("native_close bench (CPU-only)...")
        nc_vals = bench_native_close(time_left)
        _cache_put("native_close", _merge_last_good("native_close", nc_vals))
        extra.update(nc_vals)
    else:
        extra["native_close"] = "SKIPPED(budget)"
        _stale_fill(extra, "native_close")

    # soroban subsystem (ISSUE 17): mixed-phase close throughput,
    # footprint-parallel speedup (hash identity asserted) and host
    # metering overhead — CPU-only
    if budget_fits("soroban", 90):
        _stage("soroban bench (CPU-only)...")
        sb_vals = bench_soroban(time_left)
        _cache_put("soroban", _merge_last_good("soroban", sb_vals))
        extra.update(sb_vals)
    else:
        extra["soroban"] = "SKIPPED(budget)"
        _stale_fill(extra, "soroban")

    # observability plane (ISSUE 16): sampler overhead (<5% asserted on
    # the apply-path microbench) + merged-trace cost — both CPU-only
    if budget_fits("sampleprof", 60):
        _stage("sampleprof overhead bench (CPU-only)...")
        sp_vals = bench_sampleprof(time_left)
        _cache_put("sampleprof", _merge_last_good("sampleprof", sp_vals))
        extra.update(sp_vals)
    else:
        extra["sampleprof"] = "SKIPPED(budget)"
        _stale_fill(extra, "sampleprof")

    if budget_fits("fleettrace", 30):
        _stage("fleettrace merge bench (CPU-only)...")
        ft_vals = bench_fleettrace(time_left)
        _cache_put("fleettrace", _merge_last_good("fleettrace", ft_vals))
        extra.update(ft_vals)
    else:
        extra["fleettrace"] = "SKIPPED(budget)"
        _stale_fill(extra, "fleettrace")

    # historical telemetry (ISSUE 20): capture ride-along on the 51-node
    # flagship (<2% asserted) + close-p99-vs-read-QPS contention curve
    # over a 100k-account BucketListDB — CPU-only
    if budget_fits("telemetry", 420):
        _stage("telemetry capture + read-contention bench (CPU-only)...")
        tl_vals = bench_telemetry(time_left)
        _cache_put("telemetry", _merge_last_good("telemetry", tl_vals))
        extra.update(tl_vals)
    else:
        extra["telemetry"] = "SKIPPED(budget)"
        _stale_fill(extra, "telemetry")

    if not budget_fits("device probe + accel sections", 240):
        # nothing device-side fits anymore: emit what the CPU sections
        # measured plus last-good cache for the rest — never rc=124 with
        # no JSON line
        for section in ("sigs", "replay", "quorum"):
            extra[section] = "SKIPPED(budget)"
            _stale_fill(extra, section)
        sig = _cache_load().get("sigs", {}).get("values", {})
        extra["bench_spent_s"] = round(time.monotonic() - _T0, 1)
        print(json.dumps({
            "metric": "ed25519_batch_verify_throughput",
            "value": sig.get("ed25519_tpu_sigs_per_sec", 0.0),
            "unit": "sigs/s",
            "vs_baseline": sig.get("ed25519_speedup_1chip_vs_1core", 0.0),
            "extra": extra,
        }))
        return

    _stage("probing device health...")
    # the tunnel has come back mid-window after outages before: retry the
    # probe a couple of times across the bench window before giving up
    up = False
    for round_ in range(2):
        if probe_device(timeout_s=min(120.0, max(10.0, time_left() / 4))):
            up = True
            break
        if round_ == 0 and time_left() > 400:
            _stage("device unreachable — waiting 120s and re-probing once")
            time.sleep(120)
    if not up:
        # degraded report: the accel metrics are unmeasurable with the
        # tunnel down — emit the last good on-chip numbers, aged and
        # stale-flagged, rather than zeros (VERDICT r3 weak #1)
        _stage("DEVICE UNREACHABLE — emitting stale last-good report")
        rep = _degraded_report(
            "TPU tunnel unreachable (probes timed out across the bench "
            "window); numbers below are the last good on-chip results, "
            "stale-flagged with their age")
        rep["extra"].update(extra)   # fresh CPU-side rows win over cache
        print(json.dumps(rep))
        return

    # the watchdog backstops a section that WEDGES past its estimate (the
    # deadline checks can only skip sections that haven't started)
    cancel_watchdog = _arm_watchdog(BENCH_BUDGET_S + 240)

    if budget_fits("sigs", 180):
        _stage("sig bench...")
        tpu_sig_rate, cpu_sig_rate = bench_sigs()
        sig_vals = {
            "ed25519_tpu_sigs_per_sec": round(tpu_sig_rate, 1),
            "ed25519_libsodium_1core_sigs_per_sec": round(cpu_sig_rate, 1),
            "ed25519_speedup_1chip_vs_1core":
                round(tpu_sig_rate / cpu_sig_rate, 2),
        }
        _cache_put("sigs", sig_vals)
        extra.update(sig_vals)
        value = round(tpu_sig_rate, 1)
        vs = round(tpu_sig_rate / cpu_sig_rate, 2)
    else:
        extra["sigs"] = "SKIPPED(budget)"
        cached = _stale_fill(extra, "sigs")
        value = cached.get("ed25519_tpu_sigs_per_sec", 0.0)
        vs = cached.get("ed25519_speedup_1chip_vs_1core", 0.0)

    if budget_fits("replay", 900):
        with tempfile.TemporaryDirectory() as d:
            _stage("building archive (~18 checkpoints)...")
            # BASELINE.json configs 1/4 call for thousands of pubnet
            # ledgers; 1100 payment ledgers ≈ 1215 total ≈ 19 checkpoints
            # keeps the steady-state pipeline visible while fitting the
            # driver budget (VERDICT r2 weak #5: 127 ledgers was inside
            # the drift noise).  BENCH_PAYMENT_LEDGERS overrides for
            # offline full-scale runs (VERDICT r3 item 7: the 10k-ledger
            # config-1/4 measurement).
            archive, mgr = build_archive(
                nid, passphrase, os.path.join(d, "archive"),
                n_payment_ledgers=int(os.environ.get(
                    "BENCH_PAYMENT_LEDGERS", "1100")))
            _stage("replay bench...")
            replay = bench_replay(nid, passphrase, archive, mgr.lcl_hash,
                                  time_left_fn=time_left)
        if replay is None:
            # deadline pre-empted the section before one full round
            extra["replay"] = "SKIPPED(budget, pre-empted mid-section)"
            _stale_fill(extra, "replay")
        else:
            cpu_rate, tpu_rate, hit_rate, n_ledgers, phases = replay
            obs = observability_snapshot(hit_rate)
            replay_vals = {
                "replay_accel_ledgers_per_sec": round(tpu_rate, 1),
                "replay_accel_vs_cpu": round(tpu_rate / cpu_rate, 3),
                "replay_ledgers": n_ledgers,
                "replay_cpu_ledgers_per_sec": round(cpu_rate, 1),
                "replay_hashes_identical": True,
                # checkpoint outcome split (ISSUE 13): a silent native
                # fallback regression shows as a nonzero fallback column
                "replay_native_checkpoints":
                    phases.get("native_checkpoints", 0),
                "replay_fallback_checkpoints":
                    phases.get("native_fallback_checkpoints", 0),
                "sig_offload_hit_rate": round(hit_rate, 3),
                # ISSUE 14 satellite: the r03->r05 inversion hid inside
                # replay_phases for two rounds — the stall/offload
                # tells are FIRST-CLASS cached fields now, with the miss
                # causes split (device lost the race vs never dispatched)
                "replay_collect_wait_s":
                    round(phases.get("collect_wait_s", 0.0), 3),
                "replay_race_lost_sigs": phases.get("sigs_race_lost", 0),
                "replay_not_dispatched_sigs":
                    phases.get("sigs_not_dispatched", 0),
                "replay_late_seeded_sigs":
                    phases.get("sigs_late_seeded", 0),
                "replay_phases": phases,
                "metrics": obs,
            }
            # ISSUE 14 acceptance: the never-wait profile means the device
            # can only ADD throughput — an inverted ratio or a visible
            # collect stall is a regression, not a data point, so it must
            # fail the bench BEFORE it can be cached as last-good
            assert replay_vals["replay_accel_vs_cpu"] >= 1.0, (
                f"accel replay INVERTED: "
                f"{replay_vals['replay_accel_vs_cpu']}x CPU "
                f"(never-wait preverify must not lose; phases: {phases})")
            assert replay_vals["replay_collect_wait_s"] < 1.0, (
                f"accel replay spent "
                f"{replay_vals['replay_collect_wait_s']}s blocked in "
                f"collect — the poll profile never waits on the device")
            _cache_put("replay", _merge_last_good("replay", replay_vals))
            extra.update(replay_vals)
    else:
        extra["replay"] = "SKIPPED(budget)"
        _stale_fill(extra, "replay")

    # the quorum matrix already degrades row-by-row under its own budget;
    # hand it whatever wall-clock remains (minus the reporting tail)
    quorum_budget = min(700.0, time_left() - 45.0)
    if quorum_budget > 60.0:
        _stage("quorum bench (crossover matrix)...")
        matrix = bench_quorum(time_left, budget_s=quorum_budget)
        from stellar_core_tpu.herder.quorum_intersection import _cquorum
        matrix["quorum_native_engine"] = _cquorum is not None
        _cache_put("quorum", _merge_last_good("quorum", matrix))
        extra.update(matrix)
    else:
        extra["quorum"] = "SKIPPED(budget)"
        _stale_fill(extra, "quorum")

    extra["bench_spent_s"] = round(time.monotonic() - _T0, 1)
    print(json.dumps({
        "metric": "ed25519_batch_verify_throughput",
        "value": value,
        "unit": "sigs/s",
        "vs_baseline": vs,
        "extra": extra,
    }))
    cancel_watchdog()


if __name__ == "__main__":
    if len(sys.argv) >= 4 and sys.argv[1] == "--quorum-cell":
        # one pre-emptible quorum matrix cell (see bench_quorum)
        sys.exit(_quorum_cell_main(sys.argv[2], sys.argv[3]))
    if len(sys.argv) >= 2 and sys.argv[1] == "--summary":
        # render the last-good cache into BENCH_SUMMARY.json (no device)
        sys.exit(_summary_main())
    try:
        main()
    except AssertionError:
        raise  # correctness claims (identical hashes/verdicts) never retry
    except Exception as e:  # corelint: disable=exception-hygiene -- transient tunnel/compile flake: one retry
        print(f"[bench] retrying after: {e}", file=sys.stderr, flush=True)
        main()
