"""Benchmark: Ed25519 batch verify on TPU vs single-core libsodium.

BASELINE.json config #2 ("1M-sig synthetic Ed25519 batch verify (TPU vmap vs
libsodium)") scaled to a driver-friendly runtime.  Baseline = libsodium
``crypto_sign_verify_detached`` in a single-threaded loop (what the reference
node does inside SignatureChecker during catchup replay, modulo its verify
cache).  Prints ONE JSON line.
"""

import json
import random
import time


def main():
    from stellar_core_tpu.accel.ed25519 import Ed25519BatchVerifier
    from stellar_core_tpu.crypto import sodium

    rng = random.Random(7)
    n_total = 65536
    chunk = 8192
    n_base = 3000

    # Synthetic workload shaped like catchup: few distinct signing accounts,
    # tx-envelope-sized messages, ~1% bad signatures.
    keys = [sodium.sign_seed_keypair(bytes([i]) * 32) for i in range(64)]
    pks, sigs, msgs = [], [], []
    n_bad = 0
    for i in range(n_total):
        pk, sk = keys[i % len(keys)]
        msg = bytes(rng.randrange(256) for _ in range(120))
        sig = sodium.sign_detached(msg, sk)
        if i % 100 == 99:
            sig = bytes([sig[0] ^ 1]) + sig[1:]
            n_bad += 1
        pks.append(pk)
        sigs.append(sig)
        msgs.append(msg)

    # CPU baseline: single-core libsodium loop
    t0 = time.perf_counter()
    acc = 0
    for i in range(n_base):
        acc += sodium.verify_detached(sigs[i], msgs[i], pks[i])
    t_base = time.perf_counter() - t0
    base_rate = n_base / t_base

    v = Ed25519BatchVerifier(chunk_size=chunk)
    # warmup: compile + pk-cache fill
    v.verify(pks[:chunk], sigs[:chunk], msgs[:chunk])
    t0 = time.perf_counter()
    verdicts = v.verify(pks, sigs, msgs)
    t_tpu = time.perf_counter() - t0
    tpu_rate = n_total / t_tpu

    n_accept = int(verdicts.sum())
    assert n_accept == n_total - n_bad, (
        f"verdict mismatch: {n_accept} accepts, expected {n_total - n_bad}")

    print(json.dumps({
        "metric": "ed25519_batch_verify_throughput",
        "value": round(tpu_rate, 1),
        "unit": "sigs/s",
        "vs_baseline": round(tpu_rate / base_rate, 2),
    }))


if __name__ == "__main__":
    main()
