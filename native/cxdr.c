/* cxdr: native XDR serializer for stellar-core-tpu.
 *
 * The reference implements its XDR layer in C++ (xdrpp, generated
 * marshalers); this extension is that native seam for the TPU framework:
 * profiled replay time is dominated by serialization (PROFILE.md), so the
 * pack path — the hot inner loop of hashing, bucket building and history
 * writing — runs in C while the Python codec remains the semantic source
 * of truth (differentially tested, automatic fallback when unbuilt).
 *
 * A type is compiled (once, Python side) into a nested tuple "program":
 *   (OP_U32,) (OP_I32,) (OP_U64,) (OP_I64,) (OP_BOOL,) (OP_ENUM,)
 *   (OP_OPAQUE, n) (OP_VAROPAQUE, max) (OP_STRING, max)
 *   (OP_FIXARRAY, n, elem) (OP_VARARRAY, max, elem)
 *   (OP_OPTIONAL, elem) (OP_VOID,)
 *   (OP_STRUCT, (name0, prog0, name1, prog1, ...))
 *   (OP_UNION, {switch_int: prog_or_None}, default_prog_or_None, has_default)
 * cxdr.pack(program, value) returns the XDR bytes, raising cxdr.Error with
 * the same rejection semantics as the Python codec (range checks, length
 * caps, exact fixed-opaque lengths).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

enum {
    OP_U32 = 1, OP_I32, OP_U64, OP_I64, OP_BOOL, OP_ENUM,
    OP_OPAQUE, OP_VAROPAQUE, OP_STRING,
    OP_FIXARRAY, OP_VARARRAY, OP_OPTIONAL, OP_VOID,
    OP_STRUCT, OP_UNION, OP_PYCALL,
};

static PyObject *CxdrError;

typedef struct {
    char *data;
    Py_ssize_t len;
    Py_ssize_t cap;
} Buf;

static int
buf_reserve(Buf *b, Py_ssize_t extra)
{
    if (b->len + extra <= b->cap)
        return 0;
    Py_ssize_t ncap = b->cap ? b->cap * 2 : 256;
    while (ncap < b->len + extra)
        ncap *= 2;
    char *nd = PyMem_Realloc(b->data, ncap);
    if (!nd) {
        PyErr_NoMemory();
        return -1;
    }
    b->data = nd;
    b->cap = ncap;
    return 0;
}

static int
buf_put(Buf *b, const void *src, Py_ssize_t n)
{
    if (buf_reserve(b, n) < 0)
        return -1;
    memcpy(b->data + b->len, src, n);
    b->len += n;
    return 0;
}

static int
put_u32be(Buf *b, uint32_t v)
{
    unsigned char tmp[4] = {
        (unsigned char)(v >> 24), (unsigned char)(v >> 16),
        (unsigned char)(v >> 8), (unsigned char)v,
    };
    return buf_put(b, tmp, 4);
}

static int
put_u64be(Buf *b, uint64_t v)
{
    unsigned char tmp[8] = {
        (unsigned char)(v >> 56), (unsigned char)(v >> 48),
        (unsigned char)(v >> 40), (unsigned char)(v >> 32),
        (unsigned char)(v >> 24), (unsigned char)(v >> 16),
        (unsigned char)(v >> 8), (unsigned char)v,
    };
    return buf_put(b, tmp, 8);
}

/* Extract an integer with overflow detection; returns 0 on success. */
static int
get_int64(PyObject *val, int64_t *out)
{
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(val, &overflow);
    if (overflow || (v == -1 && PyErr_Occurred())) {
        PyErr_Clear();
        PyErr_Format(CxdrError, "value out of range: %R", val);
        return -1;
    }
    *out = (int64_t)v;
    return 0;
}

static int
get_uint64(PyObject *val, uint64_t *out)
{
    unsigned long long v = PyLong_AsUnsignedLongLong(val);
    if (v == (unsigned long long)-1 && PyErr_Occurred()) {
        PyErr_Clear();
        PyErr_Format(CxdrError, "value out of range: %R", val);
        return -1;
    }
    *out = (uint64_t)v;
    return 0;
}

static int pack_value(PyObject *prog, PyObject *val, Buf *b, int depth);

static int
pack_bytes_body(Buf *b, const char *p, Py_ssize_t n, int with_len)
{
    if (with_len && put_u32be(b, (uint32_t)n) < 0)
        return -1;
    if (buf_put(b, p, n) < 0)
        return -1;
    static const char zeros[4] = {0, 0, 0, 0};
    Py_ssize_t pad = (4 - (n % 4)) % 4;
    if (pad && buf_put(b, zeros, pad) < 0)
        return -1;
    return 0;
}

static int
as_bytes(PyObject *val, PyObject **owned, const char **p, Py_ssize_t *n,
         int allow_str)
{
    /* bytes / bytearray always; str (utf-8) only for OP_STRING, matching
       the Python codec's XdrString-only str acceptance */
    *owned = NULL;
    if (PyBytes_Check(val)) {
        *p = PyBytes_AS_STRING(val);
        *n = PyBytes_GET_SIZE(val);
        return 0;
    }
    if (PyByteArray_Check(val)) {
        *p = PyByteArray_AS_STRING(val);
        *n = PyByteArray_GET_SIZE(val);
        return 0;
    }
    if (allow_str && PyUnicode_Check(val)) {
        PyObject *enc = PyUnicode_AsUTF8String(val);
        if (!enc)
            return -1;
        *owned = enc;
        *p = PyBytes_AS_STRING(enc);
        *n = PyBytes_GET_SIZE(enc);
        return 0;
    }
    PyErr_Format(CxdrError, "expected bytes, got %.80s",
                 Py_TYPE(val)->tp_name);
    return -1;
}

static int
pack_value(PyObject *prog, PyObject *val, Buf *b, int depth)
{
    if (depth > 200) {
        PyErr_SetString(CxdrError, "program too deep");
        return -1;
    }
    long op = PyLong_AsLong(PyTuple_GET_ITEM(prog, 0));
    switch (op) {
    case OP_U32: {
        uint64_t v;
        if (get_uint64(val, &v) < 0 || v > 0xFFFFFFFFULL) {
            if (!PyErr_Occurred())
                PyErr_Format(CxdrError, "value out of range: %R", val);
            return -1;
        }
        return put_u32be(b, (uint32_t)v);
    }
    case OP_I32: {
        int64_t v;
        if (get_int64(val, &v) < 0)
            return -1;
        if (v < INT32_MIN || v > INT32_MAX) {
            PyErr_Format(CxdrError, "value out of range: %R", val);
            return -1;
        }
        return put_u32be(b, (uint32_t)(int32_t)v);
    }
    case OP_ENUM: {
        /* (OP_ENUM, members_dict): membership-checked like the Python
           codec's _EnumAdapter */
        PyObject *members = PyTuple_GET_ITEM(prog, 1);
        PyObject *swint = PyNumber_Index(val);
        if (!swint) {
            PyErr_Clear();
            PyErr_Format(CxdrError, "bad enum value %R", val);
            return -1;
        }
        int contains = PyDict_Contains(members, swint);
        if (contains <= 0) {
            Py_DECREF(swint);
            if (contains == 0)
                PyErr_Format(CxdrError, "bad enum value %R", val);
            return -1;
        }
        int64_t v;
        int rc = get_int64(swint, &v);
        Py_DECREF(swint);
        if (rc < 0)
            return -1;
        return put_u32be(b, (uint32_t)(int32_t)v);
    }
    case OP_U64: {
        uint64_t v;
        if (get_uint64(val, &v) < 0)
            return -1;
        return put_u64be(b, v);
    }
    case OP_I64: {
        int64_t v;
        if (get_int64(val, &v) < 0)
            return -1;
        return put_u64be(b, (uint64_t)v);
    }
    case OP_BOOL: {
        int truth = PyObject_IsTrue(val);
        if (truth < 0)
            return -1;
        return put_u32be(b, (uint32_t)truth);
    }
    case OP_OPAQUE: {
        Py_ssize_t want = PyLong_AsSsize_t(PyTuple_GET_ITEM(prog, 1));
        PyObject *owned;
        const char *p;
        Py_ssize_t n;
        if (as_bytes(val, &owned, &p, &n, 0) < 0)
            return -1;
        if (n != want) {
            Py_XDECREF(owned);
            PyErr_Format(CxdrError, "opaque[%zd]: got %zd bytes", want, n);
            return -1;
        }
        int rc = pack_bytes_body(b, p, n, 0);
        Py_XDECREF(owned);
        return rc;
    }
    case OP_VAROPAQUE:
    case OP_STRING: {
        Py_ssize_t maxlen = PyLong_AsSsize_t(PyTuple_GET_ITEM(prog, 1));
        PyObject *owned;
        const char *p;
        Py_ssize_t n;
        if (as_bytes(val, &owned, &p, &n, op == OP_STRING) < 0)
            return -1;
        if (n > maxlen) {
            Py_XDECREF(owned);
            PyErr_Format(CxdrError, "opaque<%zd>: got %zd bytes", maxlen, n);
            return -1;
        }
        int rc = pack_bytes_body(b, p, n, 1);
        Py_XDECREF(owned);
        return rc;
    }
    case OP_FIXARRAY:
    case OP_VARARRAY: {
        Py_ssize_t bound = PyLong_AsSsize_t(PyTuple_GET_ITEM(prog, 1));
        PyObject *elem = PyTuple_GET_ITEM(prog, 2);
        PyObject *seq = PySequence_Fast(val, "expected a sequence");
        if (!seq)
            return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        if (op == OP_FIXARRAY ? (n != bound) : (n > bound)) {
            Py_DECREF(seq);
            PyErr_Format(CxdrError, "array bound %zd: got %zd", bound, n);
            return -1;
        }
        if (op == OP_VARARRAY && put_u32be(b, (uint32_t)n) < 0) {
            Py_DECREF(seq);
            return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            if (pack_value(elem, PySequence_Fast_GET_ITEM(seq, i), b,
                           depth + 1) < 0) {
                Py_DECREF(seq);
                return -1;
            }
        }
        Py_DECREF(seq);
        return 0;
    }
    case OP_OPTIONAL: {
        if (val == Py_None)
            return put_u32be(b, 0);
        if (put_u32be(b, 1) < 0)
            return -1;
        return pack_value(PyTuple_GET_ITEM(prog, 1), val, b, depth + 1);
    }
    case OP_VOID:
        return 0;
    case OP_STRUCT: {
        /* (OP_STRUCT, fields, cls) */
        PyObject *fields = PyTuple_GET_ITEM(prog, 1);
        PyObject *cls = PyTuple_GET_ITEM(prog, 2);
        int inst = PyObject_IsInstance(val, cls);
        if (inst < 0)
            return -1;
        if (!inst) {
            PyErr_Format(CxdrError, "expected %.80s, got %.80s",
                         ((PyTypeObject *)cls)->tp_name,
                         Py_TYPE(val)->tp_name);
            return -1;
        }
        Py_ssize_t nf = PyTuple_GET_SIZE(fields);
        for (Py_ssize_t i = 0; i < nf; i += 2) {
            PyObject *name = PyTuple_GET_ITEM(fields, i);
            PyObject *sub = PyTuple_GET_ITEM(fields, i + 1);
            PyObject *fv = PyObject_GetAttr(val, name);
            if (!fv)
                return -1;
            int rc = pack_value(sub, fv, b, depth + 1);
            Py_DECREF(fv);
            if (rc < 0)
                return -1;
        }
        return 0;
    }
    case OP_UNION: {
        /* (OP_UNION, arms, defprog, has_default, members_or_None, cls) */
        PyObject *arms = PyTuple_GET_ITEM(prog, 1);
        PyObject *defprog = PyTuple_GET_ITEM(prog, 2);
        int has_default = PyObject_IsTrue(PyTuple_GET_ITEM(prog, 3));
        PyObject *members = PyTuple_GET_ITEM(prog, 4);
        PyObject *cls = PyTuple_GET_ITEM(prog, 5);
        int inst = PyObject_IsInstance(val, cls);
        if (inst < 0)
            return -1;
        if (!inst) {
            PyErr_Format(CxdrError, "expected %.80s, got %.80s",
                         ((PyTypeObject *)cls)->tp_name,
                         Py_TYPE(val)->tp_name);
            return -1;
        }
        PyObject *sw = PyObject_GetAttrString(val, "switch");
        if (!sw)
            return -1;
        PyObject *swint = PyNumber_Index(sw);
        Py_DECREF(sw);
        if (!swint) {
            PyErr_Clear();
            PyErr_SetString(CxdrError, "union switch is not an integer");
            return -1;
        }
        int64_t swv;
        if (get_int64(swint, &swv) < 0 || swv < INT32_MIN ||
            swv > INT32_MAX) {
            Py_DECREF(swint);
            if (!PyErr_Occurred())
                PyErr_SetString(CxdrError, "union switch out of range");
            return -1;
        }
        if (members != Py_None) {
            /* enum-typed switch: membership check like _EnumAdapter */
            int ok = PyDict_Contains(members, swint);
            if (ok <= 0) {
                Py_DECREF(swint);
                if (ok == 0)
                    PyErr_Format(CxdrError, "bad enum value %lld",
                                 (long long)swv);
                return -1;
            }
        }
        PyObject *arm = PyDict_GetItem(arms, swint);  /* borrowed */
        Py_DECREF(swint);
        if (!arm) {
            if (!has_default) {
                PyErr_Format(CxdrError, "no arm for discriminant %lld",
                             (long long)swv);
                return -1;
            }
            arm = defprog;
        }
        if (put_u32be(b, (uint32_t)(int32_t)swv) < 0)
            return -1;
        if (arm == Py_None)
            return 0;
        PyObject *av = PyObject_GetAttrString(val, "value");
        if (!av)
            return -1;
        int rc = pack_value(arm, av, b, depth + 1);
        Py_DECREF(av);
        return rc;
    }
    case OP_PYCALL: {
        /* (OP_PYCALL, xdr_type): recursion/fallback seam — delegate to
           the PYTHON pack path (_pack_py): recursive types render their
           whole subtree in Python, which cannot re-enter this opcode for
           the same value */
        PyObject *t = PyTuple_GET_ITEM(prog, 1);
        PyObject *res = PyObject_CallMethod(t, "_pack_py", "O", val);
        if (!res)
            return -1;
        if (!PyBytes_Check(res)) {
            Py_DECREF(res);
            PyErr_SetString(CxdrError, "pack() did not return bytes");
            return -1;
        }
        int rc = buf_put(b, PyBytes_AS_STRING(res), PyBytes_GET_SIZE(res));
        Py_DECREF(res);
        return rc;
    }
    default:
        PyErr_Format(CxdrError, "bad opcode %ld", op);
        return -1;
    }
}

/* ------------------------------------------------------------------ */
/* unpack: the deserialization mirror (catchup replay's hot loop is   */
/* archive-stream + bucket-entry DECODING — PROFILE.md round 2).      */
/* Same strictness as the Python codec: canonical padding, length     */
/* caps, bool/enum membership, short-buffer errors.                   */
/* ------------------------------------------------------------------ */

typedef struct {
    const unsigned char *data;
    Py_ssize_t len;
    Py_ssize_t off;
    PyObject *src; /* borrowed: the original bytes object (OP_PYCALL) */
} Rdr;

static PyObject *str_switch, *str_value;

static int
rd_need(Rdr *r, Py_ssize_t n, const char *what)
{
    if (r->off + n > r->len) {
        PyErr_Format(CxdrError, "short buffer for %s", what);
        return -1;
    }
    return 0;
}

static int
rd_u32(Rdr *r, uint32_t *out, const char *what)
{
    if (rd_need(r, 4, what) < 0)
        return -1;
    const unsigned char *p = r->data + r->off;
    *out = ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
    r->off += 4;
    return 0;
}

static int
rd_u64(Rdr *r, uint64_t *out, const char *what)
{
    if (rd_need(r, 8, what) < 0)
        return -1;
    const unsigned char *p = r->data + r->off;
    *out = ((uint64_t)p[0] << 56) | ((uint64_t)p[1] << 48) |
           ((uint64_t)p[2] << 40) | ((uint64_t)p[3] << 32) |
           ((uint64_t)p[4] << 24) | ((uint64_t)p[5] << 16) |
           ((uint64_t)p[6] << 8) | (uint64_t)p[7];
    r->off += 8;
    return 0;
}

static int
rd_pad(Rdr *r, Py_ssize_t n)
{
    Py_ssize_t pad = (4 - (n % 4)) % 4;
    if (rd_need(r, pad, "padding") < 0)
        return -1;
    for (Py_ssize_t i = 0; i < pad; i++) {
        if (r->data[r->off + i]) {
            PyErr_SetString(CxdrError, "nonzero padding");
            return -1;
        }
    }
    r->off += pad;
    return 0;
}

static PyObject *unpack_value(PyObject *prog, Rdr *r, int depth);

static PyObject *
alloc_instance(PyObject *cls)
{
    /* __slots__ value classes: allocate without running __init__ */
    PyTypeObject *tp = (PyTypeObject *)cls;
    return tp->tp_alloc(tp, 0);
}

static PyObject *
unpack_value(PyObject *prog, Rdr *r, int depth)
{
    if (depth > 200) {
        PyErr_SetString(CxdrError, "program too deep");
        return NULL;
    }
    long op = PyLong_AsLong(PyTuple_GET_ITEM(prog, 0));
    switch (op) {
    case OP_U32: {
        uint32_t v;
        if (rd_u32(r, &v, "uint32") < 0)
            return NULL;
        return PyLong_FromUnsignedLong(v);
    }
    case OP_I32: {
        uint32_t v;
        if (rd_u32(r, &v, "int32") < 0)
            return NULL;
        return PyLong_FromLong((long)(int32_t)v);
    }
    case OP_ENUM: {
        PyObject *members = PyTuple_GET_ITEM(prog, 1);
        uint32_t v;
        if (rd_u32(r, &v, "enum") < 0)
            return NULL;
        PyObject *key = PyLong_FromLong((long)(int32_t)v);
        if (!key)
            return NULL;
        PyObject *member = PyDict_GetItem(members, key); /* borrowed */
        Py_DECREF(key);
        if (!member) {
            PyErr_Format(CxdrError, "bad enum value %ld",
                         (long)(int32_t)v);
            return NULL;
        }
        Py_INCREF(member);
        return member;
    }
    case OP_U64: {
        uint64_t v;
        if (rd_u64(r, &v, "uint64") < 0)
            return NULL;
        return PyLong_FromUnsignedLongLong(v);
    }
    case OP_I64: {
        uint64_t v;
        if (rd_u64(r, &v, "int64") < 0)
            return NULL;
        return PyLong_FromLongLong((long long)(int64_t)v);
    }
    case OP_BOOL: {
        uint32_t v;
        if (rd_u32(r, &v, "bool") < 0)
            return NULL;
        if (v > 1) {
            PyErr_Format(CxdrError, "bad bool %lu", (unsigned long)v);
            return NULL;
        }
        PyObject *out = v ? Py_True : Py_False;
        Py_INCREF(out);
        return out;
    }
    case OP_OPAQUE: {
        Py_ssize_t n = PyLong_AsSsize_t(PyTuple_GET_ITEM(prog, 1));
        if (rd_need(r, n, "opaque") < 0)
            return NULL;
        PyObject *out = PyBytes_FromStringAndSize(
            (const char *)r->data + r->off, n);
        if (!out)
            return NULL;
        r->off += n;
        if (rd_pad(r, n) < 0) {
            Py_DECREF(out);
            return NULL;
        }
        return out;
    }
    case OP_VAROPAQUE:
    case OP_STRING: {
        Py_ssize_t maxlen = PyLong_AsSsize_t(PyTuple_GET_ITEM(prog, 1));
        uint32_t n;
        if (rd_u32(r, &n, "var opaque length") < 0)
            return NULL;
        if ((Py_ssize_t)n > maxlen) {
            PyErr_Format(CxdrError, "opaque<%zd>: length %lu", maxlen,
                         (unsigned long)n);
            return NULL;
        }
        if (rd_need(r, (Py_ssize_t)n, "var opaque") < 0)
            return NULL;
        PyObject *out = PyBytes_FromStringAndSize(
            (const char *)r->data + r->off, (Py_ssize_t)n);
        if (!out)
            return NULL;
        r->off += (Py_ssize_t)n;
        if (rd_pad(r, (Py_ssize_t)n) < 0) {
            Py_DECREF(out);
            return NULL;
        }
        return out;
    }
    case OP_FIXARRAY:
    case OP_VARARRAY: {
        Py_ssize_t bound = PyLong_AsSsize_t(PyTuple_GET_ITEM(prog, 1));
        PyObject *elem = PyTuple_GET_ITEM(prog, 2);
        Py_ssize_t n;
        if (op == OP_FIXARRAY) {
            n = bound;
        } else {
            uint32_t ln;
            if (rd_u32(r, &ln, "array length") < 0)
                return NULL;
            if ((Py_ssize_t)ln > bound) {
                PyErr_Format(CxdrError, "array<%zd>: length %lu", bound,
                             (unsigned long)ln);
                return NULL;
            }
            n = (Py_ssize_t)ln;
        }
        /* every non-void element consumes >= 4 wire bytes: reject wire
           lengths the remaining buffer cannot possibly satisfy BEFORE
           preallocating (a hostile 4-byte length claiming 2^32-1 elements
           must fail like the Python decoder's short-buffer error, not
           attempt a multi-GB PyList_New) */
        long elem_op = PyLong_AsLong(PyTuple_GET_ITEM(elem, 0));
        if (elem_op != OP_VOID && n > (r->len - r->off) / 4) {
            PyErr_SetString(CxdrError, "short buffer for array");
            return NULL;
        }
        PyObject *lst = PyList_New(n);
        if (!lst)
            return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *v = unpack_value(elem, r, depth + 1);
            if (!v) {
                Py_DECREF(lst);
                return NULL;
            }
            PyList_SET_ITEM(lst, i, v);
        }
        return lst;
    }
    case OP_OPTIONAL: {
        uint32_t flag;
        if (rd_u32(r, &flag, "optional flag") < 0)
            return NULL;
        if (flag > 1) {
            PyErr_Format(CxdrError, "bad bool %lu", (unsigned long)flag);
            return NULL;
        }
        if (!flag)
            Py_RETURN_NONE;
        return unpack_value(PyTuple_GET_ITEM(prog, 1), r, depth + 1);
    }
    case OP_VOID:
        Py_RETURN_NONE;
    case OP_STRUCT: {
        PyObject *fields = PyTuple_GET_ITEM(prog, 1);
        PyObject *cls = PyTuple_GET_ITEM(prog, 2);
        PyObject *obj = alloc_instance(cls);
        if (!obj)
            return NULL;
        Py_ssize_t nf = PyTuple_GET_SIZE(fields);
        for (Py_ssize_t i = 0; i < nf; i += 2) {
            PyObject *name = PyTuple_GET_ITEM(fields, i);
            PyObject *sub = PyTuple_GET_ITEM(fields, i + 1);
            PyObject *v = unpack_value(sub, r, depth + 1);
            if (!v) {
                Py_DECREF(obj);
                return NULL;
            }
            int rc = PyObject_SetAttr(obj, name, v);
            Py_DECREF(v);
            if (rc < 0) {
                Py_DECREF(obj);
                return NULL;
            }
        }
        return obj;
    }
    case OP_UNION: {
        PyObject *arms = PyTuple_GET_ITEM(prog, 1);
        PyObject *defprog = PyTuple_GET_ITEM(prog, 2);
        int has_default = PyObject_IsTrue(PyTuple_GET_ITEM(prog, 3));
        PyObject *members = PyTuple_GET_ITEM(prog, 4);
        PyObject *cls = PyTuple_GET_ITEM(prog, 5);
        uint32_t raw;
        if (rd_u32(r, &raw, "union switch") < 0)
            return NULL;
        PyObject *swint = PyLong_FromLong((long)(int32_t)raw);
        if (!swint)
            return NULL;
        PyObject *sw = swint; /* what .switch will hold */
        if (members != Py_None) {
            PyObject *member = PyDict_GetItem(members, swint); /* borrowed */
            if (!member) {
                Py_DECREF(swint);
                PyErr_Format(CxdrError, "bad enum value %ld",
                             (long)(int32_t)raw);
                return NULL;
            }
            Py_INCREF(member);
            Py_DECREF(swint);
            sw = member;
            swint = NULL;
        }
        /* arm lookup needs the plain int key */
        PyObject *key = swint ? sw : PyLong_FromLong((long)(int32_t)raw);
        if (!key) {
            Py_DECREF(sw);
            return NULL;
        }
        PyObject *arm = PyDict_GetItem(arms, key); /* borrowed */
        int arm_found = (arm != NULL);
        if (key != sw)
            Py_DECREF(key);
        if (!arm_found) {
            if (!has_default) {
                Py_DECREF(sw);
                PyErr_Format(CxdrError, "no arm for discriminant %ld",
                             (long)(int32_t)raw);
                return NULL;
            }
            arm = defprog;
        }
        PyObject *av;
        if (arm == Py_None) {
            av = Py_None;
            Py_INCREF(av);
        } else {
            av = unpack_value(arm, r, depth + 1);
            if (!av) {
                Py_DECREF(sw);
                return NULL;
            }
        }
        PyObject *obj = alloc_instance(cls);
        if (!obj) {
            Py_DECREF(sw);
            Py_DECREF(av);
            return NULL;
        }
        int rc = PyObject_SetAttr(obj, str_switch, sw);
        Py_DECREF(sw);
        if (rc == 0) {
            rc = PyObject_SetAttr(obj, str_value, av);
        }
        Py_DECREF(av);
        if (rc < 0) {
            Py_DECREF(obj);
            return NULL;
        }
        return obj;
    }
    case OP_PYCALL: {
        /* recursion/fallback seam: delegate to the Python unpack_from,
           which returns (value, new_offset) over the ORIGINAL buffer */
        PyObject *t = PyTuple_GET_ITEM(prog, 1);
        PyObject *res = PyObject_CallMethod(t, "unpack_from", "On",
                                            r->src, r->off);
        if (!res)
            return NULL;
        if (!PyTuple_Check(res) || PyTuple_GET_SIZE(res) != 2) {
            Py_DECREF(res);
            PyErr_SetString(CxdrError,
                            "unpack_from() did not return (val, off)");
            return NULL;
        }
        PyObject *val = PyTuple_GET_ITEM(res, 0);
        Py_ssize_t noff = PyLong_AsSsize_t(PyTuple_GET_ITEM(res, 1));
        if (noff == -1 && PyErr_Occurred()) {
            Py_DECREF(res);
            return NULL;
        }
        if (noff < r->off || noff > r->len) {
            Py_DECREF(res);
            PyErr_SetString(CxdrError, "unpack_from() offset out of range");
            return NULL;
        }
        Py_INCREF(val);
        Py_DECREF(res);
        r->off = noff;
        return val;
    }
    default:
        PyErr_Format(CxdrError, "bad opcode %ld", op);
        return NULL;
    }
}

static PyObject *
cxdr_unpack_from(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *prog, *src;
    Py_ssize_t off = 0;
    if (!PyArg_ParseTuple(args, "O!O|n", &PyTuple_Type, &prog, &src, &off))
        return NULL;
    Py_buffer view;
    if (PyObject_GetBuffer(src, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    if (off < 0 || off > view.len) {
        PyBuffer_Release(&view);
        PyErr_SetString(CxdrError, "offset out of range");
        return NULL;
    }
    Rdr r = {(const unsigned char *)view.buf, view.len, off, src};
    PyObject *val = unpack_value(prog, &r, 0);
    Py_ssize_t end = r.off;
    PyBuffer_Release(&view);
    if (!val)
        return NULL;
    PyObject *out = Py_BuildValue("Nn", val, end);
    return out;
}

static PyObject *
cxdr_unpack(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *prog, *src;
    if (!PyArg_ParseTuple(args, "O!O", &PyTuple_Type, &prog, &src))
        return NULL;
    Py_buffer view;
    if (PyObject_GetBuffer(src, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    Rdr r = {(const unsigned char *)view.buf, view.len, 0, src};
    PyObject *val = unpack_value(prog, &r, 0);
    Py_ssize_t end = r.off, total = view.len;
    PyBuffer_Release(&view);
    if (!val)
        return NULL;
    if (end != total) {
        Py_DECREF(val);
        PyErr_Format(CxdrError, "trailing bytes: consumed %zd of %zd",
                     end, total);
        return NULL;
    }
    return val;
}

static PyObject *
cxdr_pack(PyObject *self, PyObject *args)
{
    (void)self;
    PyObject *prog, *val;
    if (!PyArg_ParseTuple(args, "O!O", &PyTuple_Type, &prog, &val))
        return NULL;
    Buf b = {NULL, 0, 0};
    if (pack_value(prog, val, &b, 0) < 0) {
        PyMem_Free(b.data);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize(b.data, b.len);
    PyMem_Free(b.data);
    return out;
}

/* ------------------------------------------------------------------ */
/* deep_copy: generic structural copy of codec values (the LedgerTxn   */
/* copy-out hot path — PROFILE.md round 3: deep_copy chains were the   */
/* single largest replay cost block after the unpack mirror landed).   */
/* Immutable leaves (int/enum/bool/bytes/str/None) are shared; lists   */
/* are rebuilt; struct/union slot objects are tp_alloc'd and filled    */
/* without descriptor or __init__ overhead.  Per-type field layouts    */
/* are cached in a C-side dict: type -> tuple of interned names, or    */
/* None for unions (copied via their fixed switch/value slots).        */
/* ------------------------------------------------------------------ */

static PyObject *deepcopy_layouts;   /* type -> tuple | None (union) */
static PyObject *str_spec, *str_arms, *str_deep_copy;

static PyObject *
layout_for(PyObject *tp)
{
    PyObject *layout = PyDict_GetItem(deepcopy_layouts, tp); /* borrowed */
    if (layout)
        return layout;
    if (PyObject_HasAttr(tp, str_arms)) {
        layout = Py_None;
    } else if (PyObject_HasAttr(tp, str_spec)) {
        PyObject *spec = PyObject_GetAttr(tp, str_spec);
        if (!spec)
            return NULL;
        PyObject *fast = PySequence_Fast(spec, "bad _spec");
        Py_DECREF(spec);
        if (!fast)
            return NULL;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
        PyObject *names = PyTuple_New(n);
        if (!names) {
            Py_DECREF(fast);
            return NULL;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PySequence_Fast_GET_ITEM(fast, i);
            PyObject *name = PySequence_GetItem(pair, 0);
            if (!name) {
                Py_DECREF(fast);
                Py_DECREF(names);
                return NULL;
            }
            PyUnicode_InternInPlace(&name);
            PyTuple_SET_ITEM(names, i, name);
        }
        Py_DECREF(fast);
        layout = names;
        if (PyDict_SetItem(deepcopy_layouts, tp, layout) < 0) {
            Py_DECREF(names);
            return NULL;
        }
        Py_DECREF(names);
        return PyDict_GetItem(deepcopy_layouts, tp);
    } else {
        layout = NULL;  /* unknown: fall back to the Python method */
        return Py_NotImplemented;
    }
    if (PyDict_SetItem(deepcopy_layouts, tp, layout) < 0)
        return NULL;
    return layout;
}

static PyObject *
deep_copy_c(PyObject *val, int depth)
{
    if (depth > 200) {
        PyErr_SetString(CxdrError, "deep_copy too deep");
        return NULL;
    }
    /* immutable leaves shared (PyLong covers bool + IntEnum members) */
    if (val == Py_None || PyLong_Check(val) || PyBytes_Check(val) ||
        PyUnicode_Check(val)) {
        Py_INCREF(val);
        return val;
    }
    if (PyList_Check(val)) {
        Py_ssize_t n = PyList_GET_SIZE(val);
        PyObject *lst = PyList_New(n);
        if (!lst)
            return NULL;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *v = deep_copy_c(PyList_GET_ITEM(val, i), depth + 1);
            if (!v) {
                Py_DECREF(lst);
                return NULL;
            }
            PyList_SET_ITEM(lst, i, v);
        }
        return lst;
    }
    PyObject *tp = (PyObject *)Py_TYPE(val);
    PyObject *layout = layout_for(tp);
    if (!layout)
        return NULL;
    if (layout == Py_NotImplemented)   /* not a codec class */
        return PyObject_CallMethodNoArgs(val, str_deep_copy);
    PyObject *obj = alloc_instance(tp);
    if (!obj)
        return NULL;
    if (layout == Py_None) {           /* union: switch shared, value copied */
        PyObject *sw = PyObject_GetAttr(val, str_switch);
        if (!sw || PyObject_SetAttr(obj, str_switch, sw) < 0) {
            Py_XDECREF(sw);
            Py_DECREF(obj);
            return NULL;
        }
        Py_DECREF(sw);
        PyObject *v = PyObject_GetAttr(val, str_value);
        if (!v) {
            Py_DECREF(obj);
            return NULL;
        }
        PyObject *c = deep_copy_c(v, depth + 1);
        Py_DECREF(v);
        if (!c || PyObject_SetAttr(obj, str_value, c) < 0) {
            Py_XDECREF(c);
            Py_DECREF(obj);
            return NULL;
        }
        Py_DECREF(c);
        return obj;
    }
    Py_ssize_t nf = PyTuple_GET_SIZE(layout);
    for (Py_ssize_t i = 0; i < nf; i++) {
        PyObject *name = PyTuple_GET_ITEM(layout, i);
        PyObject *v = PyObject_GetAttr(val, name);
        if (!v) {
            Py_DECREF(obj);
            return NULL;
        }
        PyObject *c = deep_copy_c(v, depth + 1);
        Py_DECREF(v);
        if (!c || PyObject_SetAttr(obj, name, c) < 0) {
            Py_XDECREF(c);
            Py_DECREF(obj);
            return NULL;
        }
        Py_DECREF(c);
    }
    return obj;
}

static PyObject *
cxdr_deep_copy(PyObject *self, PyObject *val)
{
    (void)self;
    return deep_copy_c(val, 0);
}

static PyMethodDef cxdr_methods[] = {
    {"pack", cxdr_pack, METH_VARARGS,
     "pack(program, value) -> bytes: serialize value per the program."},
    {"unpack", cxdr_unpack, METH_VARARGS,
     "unpack(program, data) -> value: full-consumption deserialize."},
    {"unpack_from", cxdr_unpack_from, METH_VARARGS,
     "unpack_from(program, data, off=0) -> (value, new_off)."},
    {"deep_copy", cxdr_deep_copy, METH_O,
     "deep_copy(value) -> structural copy sharing immutable leaves."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef cxdr_module = {
    PyModuleDef_HEAD_INIT, "_cxdr",
    "Native XDR serializer (see native/cxdr.c).", -1, cxdr_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit__cxdr(void)
{
    PyObject *m = PyModule_Create(&cxdr_module);
    if (!m)
        return NULL;
    CxdrError = PyErr_NewException("_cxdr.Error", NULL, NULL);
    Py_XINCREF(CxdrError);
    if (PyModule_AddObject(m, "Error", CxdrError) < 0) {
        Py_XDECREF(CxdrError);
        Py_DECREF(m);
        return NULL;
    }
    str_switch = PyUnicode_InternFromString("switch");
    str_value = PyUnicode_InternFromString("value");
    str_spec = PyUnicode_InternFromString("_spec");
    str_arms = PyUnicode_InternFromString("_arms");
    str_deep_copy = PyUnicode_InternFromString("deep_copy");
    deepcopy_layouts = PyDict_New();
    if (!str_switch || !str_value || !str_spec || !str_arms ||
        !str_deep_copy || !deepcopy_layouts) {
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
