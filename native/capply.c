/* Native catchup-replay apply core.
 *
 * Reference: the replay hot path of SURVEY.md §3.3 — ApplyCheckpointWork
 * -> LedgerManager apply (src/catchup/ApplyCheckpointWork.cpp,
 * src/ledger/LedgerManagerImpl.cpp, src/transactions/TransactionFrame.cpp,
 * src/bucket/BucketListBase.cpp).  The reference's whole node is native
 * C++; this module is the framework's native equivalent for the apply
 * engine specifically (SURVEY §2.4 "C++ core where perf-critical"),
 * mirroring the PYTHON oracle in stellar_core_tpu (ledger/manager.py,
 * transactions/frame.py, transactions/operations.py, bucket/bucket.py)
 * bit-for-bit: identical result XDR, identical bucket-list hashes,
 * identical header hashes.  The Python engine remains the semantic source
 * of truth; differential tests assert hash equality ledger by ledger, and
 * STELLAR_TPU_NO_CAPPLY forces the Python path.
 *
 * Scope: an engine instance owns the ledger state (entry store + bucket
 * list + header) and applies whole CHECKPOINTS from the raw archive
 * records (no per-ledger Python object traffic).  Transactions whose
 * features fall outside the supported set (probe()) are the caller's cue
 * to fall back to the Python engine for that checkpoint, after an
 * export_state()/import_state() round-trip.
 *
 * Supported tx surface (probe-gated): v0/v1 envelopes AND fee-bump
 * envelopes (outer LOW-threshold auth, inner result embedded verbatim);
 * preconditions NONE/TIME/V2, any memo; ed25519/preauth/hashX signers.
 * ALL 24 classic op types apply natively (round 12 closed the set):
 * CREATE_ACCOUNT, PAYMENT (native + credit), PATH_PAYMENT_STRICT_RECEIVE,
 * PATH_PAYMENT_STRICT_SEND (order book vs CAP-38 pool per hop),
 * MANAGE_SELL_OFFER, MANAGE_BUY_OFFER, CREATE_PASSIVE_SELL_OFFER,
 * SET_OPTIONS, CHANGE_TRUST (classic + pool-share lines), ALLOW_TRUST,
 * ACCOUNT_MERGE, INFLATION, MANAGE_DATA, BUMP_SEQUENCE,
 * BEGIN/END_SPONSORING_FUTURE_RESERVES, REVOKE_SPONSORSHIP (CAP-33
 * sandwiches incl. per-signer slots), CREATE_CLAIMABLE_BALANCE,
 * CLAIM_CLAIMABLE_BALANCE, CLAWBACK, CLAWBACK_CLAIMABLE_BALANCE,
 * SET_TRUST_LINE_FLAGS, LIQUIDITY_POOL_DEPOSIT, LIQUIDITY_POOL_WITHDRAW.
 *
 * Fallback set (probe answers "unsupported"; the caller replays that
 * checkpoint in Python): Soroban ops, soroban-typed RevokeSponsorship
 * keys, and generalized tx sets.
 *
 * Live close (round 12): close_ledger() applies ONE externalized ledger
 * with no archive header to check against — the engine computes the
 * header/results and returns them with the entry delta so the Python
 * manager mirrors its read view (ledger/native_close.py drives it and
 * differentially spot-checks against the Python close).
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <dlfcn.h>
#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;
typedef __int128 i128;

static PyObject *CapplyError;

#define INT64_MAXV 9223372036854775807LL

/* ---- refcounted byte blob -------------------------------------------- */

typedef struct {
    int rc;
    int len;
    uint8_t bytes[];
} RB;

static RB *
rb_new(const uint8_t *data, int len)
{
    RB *b = PyMem_Malloc(sizeof(RB) + len);
    if (!b)
        return NULL;
    b->rc = 1;
    b->len = len;
    if (data)
        memcpy(b->bytes, data, len);
    return b;
}

static RB *
rb_ref(RB *b) { b->rc++; return b; }

static void
rb_unref(RB *b)
{
    if (b && --b->rc == 0)
        PyMem_Free(b);
}

/* bytes compare with Python semantics (lexicographic, shorter first) */
static int
bcmp_py(const uint8_t *a, int alen, const uint8_t *b, int blen)
{
    int n = alen < blen ? alen : blen;
    int c = memcmp(a, b, n);
    if (c)
        return c;
    return alen - blen;
}

/* ---- growable output buffer ------------------------------------------ */

typedef struct {
    uint8_t *p;
    int len, cap;
} Buf;

static int
buf_reserve(Buf *b, int extra)
{
    if (b->len + extra <= b->cap)
        return 0;
    int nc = b->cap ? b->cap * 2 : 256;
    while (nc < b->len + extra)
        nc *= 2;
    uint8_t *np = PyMem_Realloc(b->p, nc);
    if (!np) { PyErr_NoMemory(); return -1; }
    b->p = np;
    b->cap = nc;
    return 0;
}

static int
buf_put(Buf *b, const void *data, int len)
{
    if (len == 0)
        return 0;    /* empty Bufs carry p == NULL: memcpy(NULL) is UB */
    if (buf_reserve(b, len) < 0)
        return -1;
    memcpy(b->p + b->len, data, len);
    b->len += len;
    return 0;
}

static int
buf_u32(Buf *b, uint32_t v)
{
    uint8_t t[4] = { v >> 24, v >> 16, v >> 8, v };
    return buf_put(b, t, 4);
}

static int
buf_i32(Buf *b, int32_t v) { return buf_u32(b, (uint32_t)v); }

static int
buf_u64(Buf *b, uint64_t v)
{
    uint8_t t[8] = { v >> 56, v >> 48, v >> 40, v >> 32,
                     v >> 24, v >> 16, v >> 8, v };
    return buf_put(b, t, 8);
}

static int
buf_i64(Buf *b, int64_t v) { return buf_u64(b, (uint64_t)v); }

static int
buf_varopaque(Buf *b, const uint8_t *data, int len)
{
    static const uint8_t zero[4] = {0, 0, 0, 0};
    if (buf_u32(b, (uint32_t)len) < 0 || buf_put(b, data, len) < 0)
        return -1;
    int pad = (4 - (len & 3)) & 3;
    return pad ? buf_put(b, zero, pad) : 0;
}

/* ---- bounds-checked XDR reader --------------------------------------- */

typedef struct {
    const uint8_t *p;
    int off, len;
    int err;             /* sticky parse error */
} Rd;

static void
rd_init(Rd *r, const uint8_t *p, int len)
{
    r->p = p; r->off = 0; r->len = len; r->err = 0;
}

static const uint8_t *
rd_take(Rd *r, int n)
{
    if (r->err || n < 0 || r->off + n > r->len) {
        r->err = 1;
        return NULL;
    }
    const uint8_t *q = r->p + r->off;
    r->off += n;
    return q;
}

static uint32_t
rd_u32(Rd *r)
{
    const uint8_t *q = rd_take(r, 4);
    if (!q)
        return 0;
    return ((uint32_t)q[0] << 24) | ((uint32_t)q[1] << 16) |
           ((uint32_t)q[2] << 8) | q[3];
}

static int32_t
rd_i32(Rd *r) { return (int32_t)rd_u32(r); }

static uint64_t
rd_u64(Rd *r)
{
    uint64_t hi = rd_u32(r);
    uint64_t lo = rd_u32(r);
    return (hi << 32) | lo;
}

static int64_t
rd_i64(Rd *r) { return (int64_t)rd_u64(r); }

/* var-opaque with max length; returns pointer into the buffer */
static const uint8_t *
rd_varopaque(Rd *r, uint32_t max, uint32_t *out_len)
{
    uint32_t n = rd_u32(r);
    if (r->err)
        return NULL;
    if (n > max) { r->err = 1; return NULL; }
    const uint8_t *q = rd_take(r, (int)n);
    if (!q)
        return NULL;
    int pad = (4 - (n & 3)) & 3;
    if (pad) {
        const uint8_t *z = rd_take(r, pad);
        if (!z)
            return NULL;
        for (int i = 0; i < pad; i++)
            if (z[i]) { r->err = 1; return NULL; }  /* strict padding */
    }
    *out_len = n;
    return q;
}

static int
rd_skip(Rd *r, int n) { return rd_take(r, n) ? 0 : -1; }

/* ---- SHA-256 ---------------------------------------------------------- */

typedef struct {
    uint32_t h[8];
    uint64_t nbytes;
    uint8_t block[64];
    int blen;
} Sha256;

static const uint32_t SHA_K[64] = {
    0x428a2f98,0x71374491,0xb5c0fbcf,0xe9b5dba5,0x3956c25b,0x59f111f1,
    0x923f82a4,0xab1c5ed5,0xd807aa98,0x12835b01,0x243185be,0x550c7dc3,
    0x72be5d74,0x80deb1fe,0x9bdc06a7,0xc19bf174,0xe49b69c1,0xefbe4786,
    0x0fc19dc6,0x240ca1cc,0x2de92c6f,0x4a7484aa,0x5cb0a9dc,0x76f988da,
    0x983e5152,0xa831c66d,0xb00327c8,0xbf597fc7,0xc6e00bf3,0xd5a79147,
    0x06ca6351,0x14292967,0x27b70a85,0x2e1b2138,0x4d2c6dfc,0x53380d13,
    0x650a7354,0x766a0abb,0x81c2c92e,0x92722c85,0xa2bfe8a1,0xa81a664b,
    0xc24b8b70,0xc76c51a3,0xd192e819,0xd6990624,0xf40e3585,0x106aa070,
    0x19a4c116,0x1e376c08,0x2748774c,0x34b0bcb5,0x391c0cb3,0x4ed8aa4a,
    0x5b9cca4f,0x682e6ff3,0x748f82ee,0x78a5636f,0x84c87814,0x8cc70208,
    0x90befffa,0xa4506ceb,0xbef9a3f7,0xc67178f2,
};

#define ROR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))

static void
sha_compress(Sha256 *s, const uint8_t *blk)
{
    uint32_t w[64];
    for (int i = 0; i < 16; i++)
        w[i] = ((uint32_t)blk[4 * i] << 24) | ((uint32_t)blk[4 * i + 1] << 16)
             | ((uint32_t)blk[4 * i + 2] << 8) | blk[4 * i + 3];
    for (int i = 16; i < 64; i++) {
        uint32_t s0 = ROR(w[i - 15], 7) ^ ROR(w[i - 15], 18) ^ (w[i - 15] >> 3);
        uint32_t s1 = ROR(w[i - 2], 17) ^ ROR(w[i - 2], 19) ^ (w[i - 2] >> 10);
        w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = s->h[0], b = s->h[1], c = s->h[2], d = s->h[3];
    uint32_t e = s->h[4], f = s->h[5], g = s->h[6], h = s->h[7];
    for (int i = 0; i < 64; i++) {
        uint32_t S1 = ROR(e, 6) ^ ROR(e, 11) ^ ROR(e, 25);
        uint32_t ch = (e & f) ^ (~e & g);
        uint32_t t1 = h + S1 + ch + SHA_K[i] + w[i];
        uint32_t S0 = ROR(a, 2) ^ ROR(a, 13) ^ ROR(a, 22);
        uint32_t mj = (a & b) ^ (a & c) ^ (b & c);
        uint32_t t2 = S0 + mj;
        h = g; g = f; f = e; e = d + t1;
        d = c; c = b; b = a; a = t1 + t2;
    }
    s->h[0] += a; s->h[1] += b; s->h[2] += c; s->h[3] += d;
    s->h[4] += e; s->h[5] += f; s->h[6] += g; s->h[7] += h;
}

static void
sha_init(Sha256 *s)
{
    static const uint32_t iv[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
    };
    memcpy(s->h, iv, sizeof(iv));
    s->nbytes = 0;
    s->blen = 0;
}

static void
sha_update(Sha256 *s, const uint8_t *data, size_t len)
{
    s->nbytes += len;
    if (s->blen) {
        while (len && s->blen < 64) {
            s->block[s->blen++] = *data++;
            len--;
        }
        if (s->blen == 64) {
            sha_compress(s, s->block);
            s->blen = 0;
        }
    }
    while (len >= 64) {
        sha_compress(s, data);
        data += 64;
        len -= 64;
    }
    while (len--)
        s->block[s->blen++] = *data++;
}

static void
sha_final(Sha256 *s, uint8_t out[32])
{
    uint64_t bits = s->nbytes * 8;
    uint8_t pad = 0x80;
    sha_update(s, &pad, 1);
    static const uint8_t zeros[64] = {0};
    while (s->blen != 56)
        sha_update(s, zeros, (64 + 56 - s->blen) % 64 ? 1 : 1);
    uint8_t lb[8] = { bits >> 56, bits >> 48, bits >> 40, bits >> 32,
                      bits >> 24, bits >> 16, bits >> 8, bits };
    sha_update(s, lb, 8);
    for (int i = 0; i < 8; i++) {
        out[4 * i] = s->h[i] >> 24;
        out[4 * i + 1] = s->h[i] >> 16;
        out[4 * i + 2] = s->h[i] >> 8;
        out[4 * i + 3] = s->h[i];
    }
}

static void
sha256_of(const uint8_t *data, size_t len, uint8_t out[32])
{
    Sha256 s;
    sha_init(&s);
    sha_update(&s, data, len);
    sha_final(&s, out);
}

/* ---- libsodium verify (same verdicts as crypto/sodium.py) ------------- */

static int (*sodium_verify)(const uint8_t *sig, const uint8_t *msg,
                            unsigned long long mlen, const uint8_t *pk);

static void
load_sodium(void)
{
    static const char *names[] = {
        "libsodium.so.23", "libsodium.so", "libsodium.dylib", NULL };
    for (int i = 0; names[i]; i++) {
        void *h = dlopen(names[i], RTLD_NOW | RTLD_GLOBAL);
        if (h) {
            int (*init)(void) = dlsym(h, "sodium_init");
            if (init)
                init();
            sodium_verify = dlsym(h, "crypto_sign_verify_detached");
            if (sodium_verify)
                return;
        }
    }
}

/* ---- open-addressing hashmap: bytes key -> RB* value ------------------ */

static uint64_t
fnv1a(const uint8_t *p, int len)
{
    uint64_t h = 0xcbf29ce484222325ULL;
    for (int i = 0; i < len; i++) {
        h ^= p[i];
        h *= 0x100000001b3ULL;
    }
    return h ? h : 1;
}

typedef struct {
    RB *key;             /* NULL = empty */
    RB *val;             /* NULL with key set = tombstone marker (deleted) */
    uint64_t hash;
    int state;           /* 0 empty, 1 used, 2 erased-slot */
} MapSlot;

typedef struct {
    MapSlot *slots;
    int cap;             /* power of two */
    int n;               /* used (state==1) */
    int fill;            /* used + erased */
} Map;

static int
map_init(Map *m, int cap)
{
    m->slots = PyMem_Calloc(cap, sizeof(MapSlot));
    if (!m->slots) { PyErr_NoMemory(); return -1; }
    m->cap = cap;
    m->n = 0;
    m->fill = 0;
    return 0;
}

static void
map_clear(Map *m)
{
    for (int i = 0; i < m->cap; i++) {
        if (m->slots[i].state == 1) {
            rb_unref(m->slots[i].key);
            rb_unref(m->slots[i].val);
        }
    }
    memset(m->slots, 0, m->cap * sizeof(MapSlot));
    m->n = 0;
    m->fill = 0;
}

static void
map_free(Map *m)
{
    if (!m->slots)
        return;
    map_clear(m);
    PyMem_Free(m->slots);
    m->slots = NULL;
}

static int map_put(Map *m, RB *key, RB *val);   /* takes ownership of refs */

static int
map_grow(Map *m)
{
    MapSlot *old = m->slots;
    int ocap = m->cap;
    if (map_init(m, ocap * 2) < 0) {
        m->slots = old;
        m->cap = ocap;
        return -1;
    }
    for (int i = 0; i < ocap; i++) {
        if (old[i].state == 1) {
            if (map_put(m, old[i].key, old[i].val) < 0)
                return -1;
        }
    }
    PyMem_Free(old);
    return 0;
}

/* find slot index for key; returns -1-able semantics via pointer */
static MapSlot *
map_find(Map *m, const uint8_t *key, int klen, uint64_t h)
{
    uint64_t mask = m->cap - 1;
    uint64_t i = h & mask;
    MapSlot *first_erased = NULL;
    for (;;) {
        MapSlot *s = &m->slots[i];
        if (s->state == 0)
            return first_erased ? first_erased : s;
        if (s->state == 2) {
            if (!first_erased)
                first_erased = s;
        } else if (s->hash == h && s->key->len == klen &&
                   memcmp(s->key->bytes, key, klen) == 0) {
            return s;
        }
        i = (i + 1) & mask;
    }
}

/* takes ownership of both refs; replaces existing value */
static int
map_put(Map *m, RB *key, RB *val)
{
    if ((m->fill + 1) * 3 >= m->cap * 2) {
        if (map_grow(m) < 0)
            return -1;
    }
    uint64_t h = fnv1a(key->bytes, key->len);
    MapSlot *s = map_find(m, key->bytes, key->len, h);
    if (s->state == 1) {
        rb_unref(s->key);
        rb_unref(s->val);
        s->key = key;
        s->val = val;
        s->hash = h;
        return 0;
    }
    if (s->state == 0)
        m->fill++;
    s->state = 1;
    s->key = key;
    s->val = val;
    s->hash = h;
    m->n++;
    return 0;
}

/* returns borrowed RB* or NULL; *present=1 when the key exists */
static RB *
map_get(Map *m, const uint8_t *key, int klen, int *present)
{
    uint64_t h = fnv1a(key, klen);
    MapSlot *s = map_find(m, key, klen, h);
    if (s->state == 1) {
        if (present)
            *present = 1;
        return s->val;
    }
    if (present)
        *present = 0;
    return NULL;
}

static void
map_del(Map *m, const uint8_t *key, int klen)
{
    uint64_t h = fnv1a(key, klen);
    MapSlot *s = map_find(m, key, klen, h);
    if (s->state == 1) {
        rb_unref(s->key);
        rb_unref(s->val);
        s->key = NULL;
        s->val = NULL;
        s->state = 2;
        m->n--;
    }
}

/* ---- AccountEntry parse / serialize ----------------------------------- *
 *
 * Mirrors xdr/ledger_entries.py AccountEntry (+ LedgerEntry wrapper) field
 * for field.  Parse is strict (length caps, zero padding, known union
 * tags) so hostile bytes fail exactly where the Python codec fails.
 */

typedef struct {
    uint32_t key_type;          /* SignerKeyType */
    uint8_t key[32];
    uint8_t payload[64];        /* type 3 only */
    uint32_t payload_len;
    uint32_t weight;
} CSigner;

typedef struct {
    /* LedgerEntry level */
    uint32_t last_modified;
    int entry_ext_v1;           /* 0: ext v0; 1: ext v1 */
    int has_sponsor;
    uint8_t sponsor[32];
    /* AccountEntry */
    uint8_t account_id[32];
    int64_t balance;
    int64_t seq_num;
    uint32_t num_sub;
    int has_inflation_dest;
    uint8_t inflation_dest[32];
    uint32_t flags;
    uint8_t home_domain[32];
    uint32_t home_domain_len;
    uint8_t thresholds[4];
    int n_signers;
    CSigner signers[20];
    /* ext chain: 0 = v0, 1 = v1, 2 = v1+v2, 3 = v1+v2+v3 */
    int ext_level;
    int64_t liab_buying, liab_selling;
    uint32_t num_sponsored, num_sponsoring;
    int n_ssids;
    struct { int present; uint8_t id[32]; } ssids[20];
    uint32_t seq_ledger;
    uint64_t seq_time;
} CAccount;

static int
parse_account_id(Rd *r, uint8_t out[32])
{
    if (rd_u32(r) != 0 || r->err) { r->err = 1; return -1; }  /* PK type */
    const uint8_t *q = rd_take(r, 32);
    if (!q)
        return -1;
    memcpy(out, q, 32);
    return 0;
}

static int
parse_signer_key(Rd *r, CSigner *s)
{
    s->key_type = rd_u32(r);
    if (r->err || s->key_type > 3) { r->err = 1; return -1; }
    const uint8_t *q = rd_take(r, 32);
    if (!q)
        return -1;
    memcpy(s->key, q, 32);
    s->payload_len = 0;
    if (s->key_type == 3) {
        uint32_t plen;
        const uint8_t *p = rd_varopaque(r, 64, &plen);
        if (!p)
            return -1;
        memcpy(s->payload, p, plen);
        s->payload_len = plen;
    }
    return 0;
}

/* signer key XDR bytes (for the SetOptions sort) into out, returns len */
static int
signer_key_xdr(const CSigner *s, uint8_t out[104])
{
    out[0] = 0; out[1] = 0; out[2] = 0; out[3] = (uint8_t)s->key_type;
    memcpy(out + 4, s->key, 32);
    if (s->key_type != 3)
        return 36;
    uint32_t n = s->payload_len;
    out[36] = n >> 24; out[37] = n >> 16; out[38] = n >> 8; out[39] = n;
    memcpy(out + 40, s->payload, n); /* corelint: disable=memcpy-provenance -- payload_len <= 64 by parse_signer_key's rd_varopaque max; 40+64 fits out[104] */
    int pad = (4 - (n & 3)) & 3;
    memset(out + 40 + n, 0, pad);
    return 40 + (int)n + pad;
}

static int
parse_account_entry(const uint8_t *data, int len, CAccount *a)
{
    memset(a, 0, sizeof(*a));
    Rd r;
    rd_init(&r, data, len);
    a->last_modified = rd_u32(&r);
    if (rd_u32(&r) != 0 || r.err) { return -1; }       /* data tag ACCOUNT */
    if (parse_account_id(&r, a->account_id) < 0)
        return -1;
    a->balance = rd_i64(&r);
    a->seq_num = rd_i64(&r);
    a->num_sub = rd_u32(&r);
    uint32_t has_inf = rd_u32(&r);
    if (r.err || has_inf > 1)
        return -1;
    a->has_inflation_dest = (int)has_inf;
    if (has_inf && parse_account_id(&r, a->inflation_dest) < 0)
        return -1;
    a->flags = rd_u32(&r);
    uint32_t hlen;
    const uint8_t *hd = rd_varopaque(&r, 32, &hlen);
    if (!hd)
        return -1;
    memcpy(a->home_domain, hd, hlen);
    a->home_domain_len = hlen;
    const uint8_t *th = rd_take(&r, 4);
    if (!th)
        return -1;
    memcpy(a->thresholds, th, 4);
    uint32_t nsig = rd_u32(&r);
    if (r.err || nsig > 20)
        return -1;
    a->n_signers = (int)nsig;
    for (uint32_t i = 0; i < nsig; i++) {
        if (parse_signer_key(&r, &a->signers[i]) < 0)
            return -1;
        a->signers[i].weight = rd_u32(&r);
    }
    int32_t ext = rd_i32(&r);
    if (r.err || (ext != 0 && ext != 1))
        return -1;
    a->ext_level = 0;
    if (ext == 1) {
        a->ext_level = 1;
        a->liab_buying = rd_i64(&r);
        a->liab_selling = rd_i64(&r);
        int32_t e1 = rd_i32(&r);
        if (r.err || (e1 != 0 && e1 != 2))
            return -1;
        if (e1 == 2) {
            a->ext_level = 2;
            a->num_sponsored = rd_u32(&r);
            a->num_sponsoring = rd_u32(&r);
            uint32_t nss = rd_u32(&r);
            if (r.err || nss > 20)
                return -1;
            a->n_ssids = (int)nss;
            for (uint32_t i = 0; i < nss; i++) {
                uint32_t present = rd_u32(&r);
                if (r.err || present > 1)
                    return -1;
                a->ssids[i].present = (int)present;
                if (present &&
                        parse_account_id(&r, a->ssids[i].id) < 0)
                    return -1;
            }
            int32_t e2 = rd_i32(&r);
            if (r.err || (e2 != 0 && e2 != 3))
                return -1;
            if (e2 == 3) {
                a->ext_level = 3;
                if (rd_i32(&r) != 0 || r.err)     /* ExtensionPoint v0 */
                    return -1;
                a->seq_ledger = rd_u32(&r);
                a->seq_time = rd_u64(&r);
            }
        }
    }
    /* LedgerEntry ext */
    int32_t lext = rd_i32(&r);
    if (r.err || (lext != 0 && lext != 1))
        return -1;
    a->entry_ext_v1 = (int)lext;
    if (lext == 1) {
        uint32_t sp = rd_u32(&r);
        if (r.err || sp > 1)
            return -1;
        a->has_sponsor = (int)sp;
        if (sp && parse_account_id(&r, a->sponsor) < 0)
            return -1;
        if (rd_i32(&r) != 0 || r.err)             /* v1 ext v0 */
            return -1;
    }
    if (r.err || r.off != r.len)
        return -1;
    return 0;
}

static int
write_account_id(Buf *b, const uint8_t id[32])
{
    return buf_u32(b, 0) < 0 || buf_put(b, id, 32) < 0 ? -1 : 0;
}

static int
serialize_account_entry(const CAccount *a, Buf *b)
{
    if (buf_u32(b, a->last_modified) < 0 ||
        buf_u32(b, 0) < 0 ||                          /* ACCOUNT tag */
        write_account_id(b, a->account_id) < 0 ||
        buf_i64(b, a->balance) < 0 ||
        buf_i64(b, a->seq_num) < 0 ||
        buf_u32(b, a->num_sub) < 0 ||
        buf_u32(b, (uint32_t)a->has_inflation_dest) < 0)
        return -1;
    if (a->has_inflation_dest && write_account_id(b, a->inflation_dest) < 0)
        return -1;
    if (buf_u32(b, a->flags) < 0 ||
        buf_varopaque(b, a->home_domain, (int)a->home_domain_len) < 0 ||
        buf_put(b, a->thresholds, 4) < 0 ||
        buf_u32(b, (uint32_t)a->n_signers) < 0)
        return -1;
    for (int i = 0; i < a->n_signers; i++) {
        uint8_t kx[104];
        int klen = signer_key_xdr(&a->signers[i], kx);
        if (buf_put(b, kx, klen) < 0 ||
            buf_u32(b, a->signers[i].weight) < 0)
            return -1;
    }
    if (buf_i32(b, a->ext_level >= 1 ? 1 : 0) < 0)
        return -1;
    if (a->ext_level >= 1) {
        if (buf_i64(b, a->liab_buying) < 0 ||
            buf_i64(b, a->liab_selling) < 0 ||
            buf_i32(b, a->ext_level >= 2 ? 2 : 0) < 0)
            return -1;
        if (a->ext_level >= 2) {
            if (buf_u32(b, a->num_sponsored) < 0 ||
                buf_u32(b, a->num_sponsoring) < 0 ||
                buf_u32(b, (uint32_t)a->n_ssids) < 0)
                return -1;
            for (int i = 0; i < a->n_ssids; i++) {
                if (buf_u32(b, (uint32_t)a->ssids[i].present) < 0)
                    return -1;
                if (a->ssids[i].present &&
                        write_account_id(b, a->ssids[i].id) < 0)
                    return -1;
            }
            if (buf_i32(b, a->ext_level >= 3 ? 3 : 0) < 0)
                return -1;
            if (a->ext_level >= 3) {
                if (buf_i32(b, 0) < 0 ||
                    buf_u32(b, a->seq_ledger) < 0 ||
                    buf_u64(b, a->seq_time) < 0)
                    return -1;
            }
        }
    }
    if (buf_i32(b, a->entry_ext_v1) < 0)
        return -1;
    if (a->entry_ext_v1) {
        if (buf_u32(b, (uint32_t)a->has_sponsor) < 0)
            return -1;
        if (a->has_sponsor && write_account_id(b, a->sponsor) < 0)
            return -1;
        if (buf_i32(b, 0) < 0)
            return -1;
    }
    return 0;
}

/* account LedgerKey XDR: tag ACCOUNT(0) + PublicKey tag(0) + 32 bytes */
static void
account_key_xdr_c(const uint8_t pk[32], uint8_t out[40])
{
    memset(out, 0, 8);
    memcpy(out + 8, pk, 32);
}

/* ---- verify cache + signature checker --------------------------------- *
 *
 * Mirrors crypto/keys.py verify_sig (cache -> libsodium) and
 * transactions/signature_checker.py SignatureChecker exactly.  The cache
 * is identity-keyed by sha256(pk||msg||sig) truncated to 16 bytes —
 * collisions are cryptographically negligible, and a miss only recomputes
 * the same verdict via libsodium, so verdicts never depend on cache
 * behavior (unlike latency).  Seedable from the TPU preverify collector.
 */

#define VCACHE_BITS 18
#define VCACHE_SIZE (1 << VCACHE_BITS)

typedef struct {
    uint8_t digest[16];
    uint8_t state;              /* 0 empty, 1 false, 2 true */
} VSlot;

typedef struct {
    VSlot *slots;
    uint64_t hits, misses, verifies;
} VCache;

static int
vcache_init(VCache *vc)
{
    vc->slots = PyMem_Calloc(VCACHE_SIZE, sizeof(VSlot));
    if (!vc->slots) { PyErr_NoMemory(); return -1; }
    vc->hits = vc->misses = vc->verifies = 0;
    return 0;
}

static void
vcache_key(const uint8_t *pk, const uint8_t *msg, int msg_len,
           const uint8_t *sig, int sig_len, uint8_t out[16])
{
    Sha256 s;
    uint8_t full[32];
    sha_init(&s);
    sha_update(&s, pk, 32);
    sha_update(&s, msg, msg_len);
    sha_update(&s, sig, sig_len);
    sha_final(&s, full);
    memcpy(out, full, 16);
}

static VSlot *
vcache_slot(VCache *vc, const uint8_t digest[16])
{
    uint64_t h;
    memcpy(&h, digest, 8);
    return &vc->slots[h & (VCACHE_SIZE - 1)];
}

static void
vcache_put(VCache *vc, const uint8_t digest[16], int verdict)
{
    VSlot *s = vcache_slot(vc, digest);
    memcpy(s->digest, digest, 16);
    s->state = verdict ? 2 : 1;
}

/* libsodium-exact verdict with cache */
static int
verify_sig_c(VCache *vc, const uint8_t pk[32], const uint8_t *msg,
             int msg_len, const uint8_t *sig, int sig_len)
{
    if (sig_len != 64)
        return 0;               /* crypto/sodium.py: len != 64 -> False */
    uint8_t d[16];
    vcache_key(pk, msg, msg_len, sig, sig_len, d);
    VSlot *s = vcache_slot(vc, d);
    if (s->state && memcmp(s->digest, d, 16) == 0) {
        vc->hits++;
        return s->state == 2;
    }
    vc->misses++;
    vc->verifies++;
    int ok = sodium_verify &&
        sodium_verify(sig, msg, (unsigned long long)msg_len, pk) == 0;
    memcpy(s->digest, d, 16);
    s->state = ok ? 2 : 1;
    return ok;
}

/* decorated signatures of one tx + used flags */
typedef struct {
    const uint8_t *hint;        /* 4 bytes */
    const uint8_t *sig;
    int sig_len;
    int used;
} CDecSig;

typedef struct {
    CDecSig sigs[20];
    int n;
    const uint8_t *content_hash;   /* 32 bytes */
    VCache *vc;
} CChecker;

/* signer view for check_signature: CSigner plus resolved weight */
typedef struct {
    uint32_t key_type;
    const uint8_t *key;
    uint32_t weight;
} CCheckSigner;

/* mirror SignatureChecker.check_signature */
static int
checker_check(CChecker *ck, const CCheckSigner *signers, int n_signers,
              uint32_t needed)
{
    uint64_t total = 0;
    for (int j = 0; j < n_signers; j++) {
        if (signers[j].key_type == 1 &&                /* PRE_AUTH_TX */
            memcmp(signers[j].key, ck->content_hash, 32) == 0) {
            total += signers[j].weight;
            if (total > 0 && total >= needed)
                return 1;
        }
    }
    for (int i = 0; i < ck->n; i++) {
        CDecSig *ds = &ck->sigs[i];
        for (int j = 0; j < n_signers; j++) {
            const CCheckSigner *sg = &signers[j];
            if (sg->key_type == 0) {                   /* ED25519 */
                if (memcmp(ds->hint, sg->key + 28, 4) != 0)
                    continue;
                if (!verify_sig_c(ck->vc, sg->key, ck->content_hash, 32,
                                  ds->sig, ds->sig_len))
                    continue;
            } else if (sg->key_type == 2) {            /* HASH_X */
                if (memcmp(ds->hint, sg->key + 28, 4) != 0)
                    continue;
                uint8_t hx[32];
                sha256_of(ds->sig, ds->sig_len, hx);
                if (memcmp(hx, sg->key, 32) != 0)
                    continue;
            } else {
                continue;        /* preauth handled above; type 3 skipped */
            }
            ds->used = 1;
            total += sg->weight;
            break;
        }
        if (total > 0 && total >= needed)
            return 1;
    }
    return 0;
}

static int
checker_all_used(const CChecker *ck)
{
    for (int i = 0; i < ck->n; i++)
        if (!ck->sigs[i].used)
            return 0;
    return 1;
}

/* check_account_signature: signers list = acc.signers + master (if >0) */
static int
check_account_sig(CChecker *ck, const CAccount *acc, int threshold_level)
{
    CCheckSigner list[21];
    int n = 0;
    for (int i = 0; i < acc->n_signers; i++) {
        list[n].key_type = acc->signers[i].key_type;
        list[n].key = acc->signers[i].key;
        list[n].weight = acc->signers[i].weight;
        n++;
    }
    uint32_t master = acc->thresholds[0];
    if (master > 0) {
        list[n].key_type = 0;
        list[n].key = acc->account_id;
        list[n].weight = master;
        n++;
    }
    uint32_t needed = acc->thresholds[threshold_level];
    return checker_check(ck, list, n, needed);
}

/* ---- transaction views (parsed from raw envelope records) ------------- */

typedef struct {
    int32_t op_type;            /* OperationType, -1 = unparsed */
    int has_source;
    int source_muxed;           /* med25519 */
    uint8_t source[32];
    const uint8_t *body;        /* raw body slice (after the type tag) */
    int body_len;
} COp;

#define MAX_OPS 100

typedef struct CTx_ {
    const uint8_t *env;         /* raw envelope record */
    int env_len;
    int is_v0;
    uint8_t source[32];         /* tx source account id (ed25519) */
    int source_muxed;
    uint32_t fee;
    int64_t seq_num;
    /* preconditions */
    int cond_type;              /* 0 none, 1 time, 2 v2 */
    int has_time_bounds;
    uint64_t min_time, max_time;
    int n_extra_signers;
    CSigner extra_signers[2];
    int has_muxed;              /* any med25519 in tx/op sources or dests */
    int n_ops;
    COp ops[MAX_OPS];
    int n_sigs;
    CDecSig sigs[20];
    uint8_t content_hash[32];
    /* fee bump (reference: FeeBumpTransactionFrame): source is the FEE
     * source, fee64 the Int64 outer bid, inner the wrapped v1 frame */
    int is_feebump;
    int64_t fee64;
    struct CTx_ *inner;
    /* fee phase result */
    int bad_seq;
    int supported;              /* everything parseable by the native ops */
} CTx;

static int skip_predicate(Rd *r, int depth);

/* skip one Asset (native / alphanum4 / alphanum12); returns -1 on
 * malformed bytes */
static int
skip_asset(Rd *r)
{
    uint32_t at = rd_u32(r);
    if (r->err)
        return -1;
    if (at == 0)
        return 0;
    if (at != 1 && at != 2) { r->err = 1; return -1; }
    rd_skip(r, at == 1 ? 4 : 12);
    if (rd_u32(r) != 0) { r->err = 1; return -1; }  /* issuer PK type */
    return rd_skip(r, 32);
}

/* parse one Operation; returns -1 on parse error */
static int
parse_op(Rd *r, COp *op, CTx *tx)
{
    uint32_t has_src = rd_u32(r);
    if (r->err || has_src > 1)
        return -1;
    op->has_source = (int)has_src;
    op->source_muxed = 0;
    if (has_src) {
        uint32_t mt = rd_u32(r);
        if (mt == 0x100) {
            op->source_muxed = 1;
            tx->has_muxed = 1;
            rd_skip(r, 8);
        } else if (mt != 0) {
            r->err = 1;
            return -1;
        }
        const uint8_t *q = rd_take(r, 32);
        if (!q)
            return -1;
        memcpy(op->source, q, 32);
    }
    op->op_type = rd_i32(r);
    if (r->err)
        return -1;
    op->body = r->p + r->off; /* corelint: disable=reader-discipline -- slice handle over the region the walk below bounds-checks via its own Rd */
    /* walk the body to find its length; only supported op types are
     * walked precisely — anything else marks the tx unsupported and
     * aborts the parse (the caller falls back to Python) */
    int start = r->off;
    switch (op->op_type) {
    case 0:                                   /* CREATE_ACCOUNT */
        if (rd_u32(r) != 0) { r->err = 1; return -1; }   /* PK type */
        rd_skip(r, 32 + 8);
        break;
    case 1: {                                 /* PAYMENT */
        uint32_t mt = rd_u32(r);
        if (mt == 0x100) { tx->has_muxed = 1; rd_skip(r, 8); }
        else if (mt != 0) { r->err = 1; return -1; }
        rd_skip(r, 32);
        uint32_t at = rd_u32(r);
        if (at == 0) {
            /* native asset */
        } else if (at == 1) {
            rd_skip(r, 4);
            if (rd_u32(r) != 0) { r->err = 1; return -1; }
            rd_skip(r, 32);
        } else if (at == 2) {
            rd_skip(r, 12);
            if (rd_u32(r) != 0) { r->err = 1; return -1; }
            rd_skip(r, 32);
        } else { r->err = 1; return -1; }
        rd_skip(r, 8);
        break;
    }
    case 3: case 12: {                        /* MANAGE_SELL/BUY_OFFER */
        for (int k = 0; k < 2; k++) {          /* selling + buying */
            uint32_t at = rd_u32(r);
            if (at == 1) { rd_skip(r, 4); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
            else if (at == 2) { rd_skip(r, 12); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
            else if (at != 0) { r->err = 1; return -1; }
        }
        rd_skip(r, 8 + 4 + 4 + 8);             /* amount, price, offerID */
        break;
    }
    case 4: {                                 /* CREATE_PASSIVE_SELL_OFFER */
        for (int k = 0; k < 2; k++) {
            uint32_t at = rd_u32(r);
            if (at == 1) { rd_skip(r, 4); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
            else if (at == 2) { rd_skip(r, 12); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
            else if (at != 0) { r->err = 1; return -1; }
        }
        rd_skip(r, 8 + 4 + 4);                 /* amount, price */
        break;
    }
    case 2: case 13: {            /* PATH_PAYMENT_STRICT_RECEIVE / SEND */
        if (skip_asset(r) < 0)                 /* sendAsset */
            return -1;
        rd_skip(r, 8);                         /* sendMax / sendAmount */
        uint32_t mt = rd_u32(r);
        if (mt == 0x100) { tx->has_muxed = 1; rd_skip(r, 8); }
        else if (mt != 0) { r->err = 1; return -1; }
        rd_skip(r, 32);                        /* destination */
        if (skip_asset(r) < 0)                 /* destAsset */
            return -1;
        rd_skip(r, 8);                         /* destAmount / destMin */
        uint32_t np = rd_u32(r);
        if (r->err || np > 5) { r->err = 1; return -1; }
        for (uint32_t i = 0; i < np; i++)
            if (skip_asset(r) < 0)
                return -1;
        break;
    }
    case 6: {                                 /* CHANGE_TRUST */
        uint32_t lt = rd_u32(r);
        if (lt == 0) {
            /* native line: applies natively (MALFORMED result) */
        } else if (lt == 1 || lt == 2) {
            rd_skip(r, lt == 1 ? 4 : 12);
            if (rd_u32(r) != 0) { r->err = 1; return -1; }
            rd_skip(r, 32);
        } else if (lt == 3) {
            /* pool-share line: LiquidityPoolParameters.constantProduct */
            if (rd_u32(r) != 0) { r->err = 1; return -1; }
            if (skip_asset(r) < 0 || skip_asset(r) < 0)
                return -1;
            rd_skip(r, 4);                     /* fee (i32) */
        } else { r->err = 1; return -1; }
        rd_skip(r, 8);
        break;
    }
    case 16:                                  /* BEGIN_SPONSORING_F_R */
        if (rd_u32(r) != 0) { r->err = 1; return -1; }   /* PK type */
        rd_skip(r, 32);
        break;
    case 17:                                  /* END_SPONSORING (void) */
        break;
    case 18: {                                /* REVOKE_SPONSORSHIP */
        uint32_t arm = rd_u32(r);
        if (r->err)
            return -1;
        if (arm == 0) {                       /* LEDGER_ENTRY: LedgerKey */
            uint32_t kt = rd_u32(r);
            if (r->err)
                return -1;
            switch (kt) {
            case 0:                           /* ACCOUNT */
                if (rd_u32(r) != 0) { r->err = 1; return -1; }
                rd_skip(r, 32);
                break;
            case 1: {                         /* TRUSTLINE */
                if (rd_u32(r) != 0) { r->err = 1; return -1; }
                rd_skip(r, 32);
                uint32_t at = rd_u32(r);
                if (at == 0) {
                    /* native */
                } else if (at == 1 || at == 2) {
                    rd_skip(r, at == 1 ? 4 : 12);
                    if (rd_u32(r) != 0) { r->err = 1; return -1; }
                    rd_skip(r, 32);
                } else if (at == 3) {
                    rd_skip(r, 32);           /* poolID */
                } else { r->err = 1; return -1; }
                break;
            }
            case 2:                           /* OFFER */
                if (rd_u32(r) != 0) { r->err = 1; return -1; }
                rd_skip(r, 32 + 8);
                break;
            case 3: {                         /* DATA */
                if (rd_u32(r) != 0) { r->err = 1; return -1; }
                rd_skip(r, 32);
                uint32_t nl;
                if (!rd_varopaque(r, 64, &nl)) return -1;
                break;
            }
            case 4:                           /* CLAIMABLE_BALANCE */
                if (rd_u32(r) != 0) { r->err = 1; return -1; }
                rd_skip(r, 32);
                break;
            case 5:                           /* LIQUIDITY_POOL */
                rd_skip(r, 32);
                break;
            default:
                return 1;     /* soroban-typed key: fall back to Python */
            }
        } else if (arm == 1) {                /* SIGNER */
            if (rd_u32(r) != 0) { r->err = 1; return -1; }
            rd_skip(r, 32);
            CSigner sg;
            if (parse_signer_key(r, &sg) < 0)
                return -1;
        } else { r->err = 1; return -1; }
        break;
    }
    case 22:                                  /* LIQUIDITY_POOL_DEPOSIT */
        rd_skip(r, 32 + 8 + 8 + 8 + 8);       /* pool, maxA, maxB, 2 prices */
        break;
    case 23:                                  /* LIQUIDITY_POOL_WITHDRAW */
        rd_skip(r, 32 + 8 + 8 + 8);
        break;
    case 7: {                                 /* ALLOW_TRUST */
        if (rd_u32(r) != 0) { r->err = 1; return -1; }   /* PK type */
        rd_skip(r, 32);
        uint32_t at = rd_u32(r);
        if (at == 1) rd_skip(r, 4);
        else if (at == 2) rd_skip(r, 12);
        else { r->err = 1; return -1; }
        rd_skip(r, 4);                         /* authorize */
        break;
    }
    case 8: {                                 /* ACCOUNT_MERGE */
        uint32_t mt = rd_u32(r);
        if (mt == 0x100) { tx->has_muxed = 1; rd_skip(r, 8); }
        else if (mt != 0) { r->err = 1; return -1; }
        rd_skip(r, 32);
        break;
    }
    case 9:                                   /* INFLATION (void body) */
        break;
    case 10: {                                /* MANAGE_DATA */
        uint32_t sl;
        if (!rd_varopaque(r, 64, &sl)) return -1;
        uint32_t hv = rd_u32(r);
        if (hv > 1) { r->err = 1; return -1; }
        if (hv) {
            if (!rd_varopaque(r, 64, &sl)) return -1;
        }
        break;
    }
    case 11:                                  /* BUMP_SEQUENCE */
        rd_skip(r, 8);
        break;
    case 14: {                                /* CREATE_CLAIMABLE_BALANCE */
        uint32_t at = rd_u32(r);
        if (at == 1) { rd_skip(r, 4); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        else if (at == 2) { rd_skip(r, 12); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        else if (at != 0) { r->err = 1; return -1; }
        rd_skip(r, 8);                         /* amount */
        uint32_t nc = rd_u32(r);
        if (r->err || nc > 10) { r->err = 1; return -1; }
        for (uint32_t i = 0; i < nc; i++) {
            if (rd_u32(r) != 0) { r->err = 1; return -1; }  /* CLAIMANT_V0 */
            if (rd_u32(r) != 0) { r->err = 1; return -1; }  /* PK type */
            rd_skip(r, 32);
            if (skip_predicate(r, 0) < 0) { r->err = 1; return -1; }
        }
        break;
    }
    case 15: case 20:                         /* CLAIM / CLAWBACK_CB */
        if (rd_u32(r) != 0) { r->err = 1; return -1; }      /* bid v0 */
        rd_skip(r, 32);
        break;
    case 19: {                                /* CLAWBACK */
        uint32_t at = rd_u32(r);
        if (at == 1) { rd_skip(r, 4); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        else if (at == 2) { rd_skip(r, 12); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        else if (at != 0) { r->err = 1; return -1; }
        uint32_t mt = rd_u32(r);
        if (mt == 0x100) { tx->has_muxed = 1; rd_skip(r, 8); }
        else if (mt != 0) { r->err = 1; return -1; }
        rd_skip(r, 32 + 8);
        break;
    }
    case 21: {                                /* SET_TRUST_LINE_FLAGS */
        if (rd_u32(r) != 0) { r->err = 1; return -1; }
        rd_skip(r, 32);
        uint32_t at = rd_u32(r);
        if (at == 1) { rd_skip(r, 4); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        else if (at == 2) { rd_skip(r, 12); if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        else if (at != 0) { r->err = 1; return -1; }
        rd_skip(r, 8);                         /* clear + set */
        break;
    }
    case 5: {                                 /* SET_OPTIONS */
        /* 4 optionals u32-ish + homeDomain + signer */
        uint32_t p;
        p = rd_u32(r); if (p > 1) { r->err = 1; return -1; }
        if (p) { if (rd_u32(r) != 0) { r->err = 1; return -1; } rd_skip(r, 32); }
        for (int i = 0; i < 6; i++) {         /* clear/set/master/low/med/high */
            p = rd_u32(r); if (p > 1) { r->err = 1; return -1; }
            if (p) rd_skip(r, 4);
        }
        p = rd_u32(r); if (p > 1) { r->err = 1; return -1; }
        if (p) {                              /* homeDomain str<=32 */
            uint32_t sl;
            if (!rd_varopaque(r, 32, &sl)) return -1;
        }
        p = rd_u32(r); if (p > 1) { r->err = 1; return -1; }
        if (p) {                              /* signer */
            CSigner sg;
            if (parse_signer_key(r, &sg) < 0) return -1;
            rd_skip(r, 4);
        }
        break;
    }
    default:
        return 1;               /* unsupported op type: fall back */
    }
    if (r->err)
        return -1;
    op->body_len = r->off - start;
    return 0;
}

/* Parse one TransactionEnvelope from the stream position of `outer`,
 * advancing it; fills tx, computes the content hash.  Returns 0 ok, 1
 * unsupported-but-wellformed-enough-to-skip (fall back to Python for the
 * checkpoint), -1 malformed. */
static int
parse_envelope_rd(Rd *outer, const uint8_t network_id[32], CTx *tx)
{
    memset(tx, 0, sizeof(*tx));
    const uint8_t *env = outer->p + outer->off; /* corelint: disable=reader-discipline -- envelope slice re-read through a fresh bounds-checked Rd below */
    int len = outer->len - outer->off;
    tx->env = env;
    Rd r;
    rd_init(&r, env, len);
    uint32_t etype = rd_u32(&r);
    if (r.err)
        return -1;
    if (etype == 5) {
        /* FeeBumpTransactionEnvelope: feeSource, fee(i64), innerTx
         * (union tag ENVELOPE_TYPE_TX + TransactionV1Envelope — byte-
         * identical to a standalone v1 envelope, so recurse), ext, sigs */
        tx->is_feebump = 1;
        int fb_start = r.off;           /* feeBumpTx slice starts here */
        uint32_t mt = rd_u32(&r);
        if (mt == 0x100) { tx->source_muxed = 1; tx->has_muxed = 1; rd_skip(&r, 8); }
        else if (mt != 0) return -1;
        const uint8_t *q = rd_take(&r, 32);
        if (!q)
            return -1;
        memcpy(tx->source, q, 32);
        tx->fee64 = rd_i64(&r);
        if (r.err)
            return -1;
        /* peek: the innerTx union tag must be ENVELOPE_TYPE_TX */
        if (r.off + 4 > r.len ||
            !(env[r.off] == 0 && env[r.off + 1] == 0 &&
              env[r.off + 2] == 0 && env[r.off + 3] == 2))
            return -1;
        tx->inner = PyMem_Malloc(sizeof(CTx));
        if (!tx->inner) {
            PyErr_NoMemory();
            return -1;
        }
        Rd ir;
        rd_init(&ir, env + r.off, len - r.off);
        int irc = parse_envelope_rd(&ir, network_id, tx->inner);
        if (irc != 0) {
            PyMem_Free(tx->inner);
            tx->inner = NULL;
            return irc;
        }
        r.off += ir.off;
        if (rd_i32(&r) != 0 || r.err)   /* FeeBumpTransactionExt v0 */
            return -1;
        int fb_end = r.off;
        uint32_t n_sigs = rd_u32(&r);
        if (r.err || n_sigs > 20)
            return -1;
        tx->n_sigs = (int)n_sigs;
        for (uint32_t i = 0; i < n_sigs; i++) {
            const uint8_t *hint = rd_take(&r, 4);
            if (!hint)
                return -1;
            uint32_t sl;
            const uint8_t *sig = rd_varopaque(&r, 64, &sl);
            if (!sig)
                return -1;
            tx->sigs[i].hint = hint;
            tx->sigs[i].sig = sig;
            tx->sigs[i].sig_len = (int)sl;
            tx->sigs[i].used = 0;
        }
        if (r.err)
            return -1;
        tx->env_len = r.off;
        outer->off += r.off;
        /* outer hash = sha256(nid || ENVELOPE_TYPE_TX_FEE_BUMP ||
         * feeBumpTx bytes) */
        Sha256 s5;
        sha_init(&s5);
        sha_update(&s5, network_id, 32);
        static const uint8_t tag_fb[4] = {0, 0, 0, 5};
        sha_update(&s5, tag_fb, 4);
        sha_update(&s5, env + fb_start, fb_end - fb_start);
        sha_final(&s5, tx->content_hash);
        /* fee-bump view fields: seq from the inner tx (apply order),
         * ops/conditions live on the inner frame */
        tx->seq_num = tx->inner->seq_num;
        if (tx->inner->has_muxed)
            tx->has_muxed = 1;
        tx->supported = 1;
        return 0;
    }
    if (etype != 0 && etype != 2)
        return -1;
    tx->is_v0 = etype == 0;
    int tx_start = r.off;
    if (tx->is_v0) {
        const uint8_t *q = rd_take(&r, 32);
        if (!q)
            return -1;
        memcpy(tx->source, q, 32);
    } else {
        uint32_t mt = rd_u32(&r);
        if (mt == 0x100) { tx->source_muxed = 1; tx->has_muxed = 1; rd_skip(&r, 8); }
        else if (mt != 0) { return -1; }
        const uint8_t *q = rd_take(&r, 32);
        if (!q)
            return -1;
        memcpy(tx->source, q, 32);
    }
    tx->fee = rd_u32(&r);
    tx->seq_num = rd_i64(&r);
    if (tx->is_v0) {
        uint32_t has_tb = rd_u32(&r);
        if (r.err || has_tb > 1)
            return -1;
        tx->cond_type = has_tb ? 1 : 0;
        tx->has_time_bounds = (int)has_tb;
        if (has_tb) {
            tx->min_time = rd_u64(&r);
            tx->max_time = rd_u64(&r);
        }
    } else {
        uint32_t ct = rd_u32(&r);
        if (r.err || ct > 2)
            return -1;
        tx->cond_type = (int)ct;
        if (ct == 1) {
            tx->has_time_bounds = 1;
            tx->min_time = rd_u64(&r);
            tx->max_time = rd_u64(&r);
        } else if (ct == 2) {
            uint32_t p = rd_u32(&r);
            if (p > 1) return -1;
            if (p) {
                tx->has_time_bounds = 1;
                tx->min_time = rd_u64(&r);
                tx->max_time = rd_u64(&r);
            }
            p = rd_u32(&r);                       /* ledgerBounds */
            if (p > 1) return -1;
            if (p) rd_skip(&r, 8);
            p = rd_u32(&r);                       /* minSeqNum */
            if (p > 1) return -1;
            if (p) rd_skip(&r, 8);
            rd_skip(&r, 8);                       /* minSeqAge */
            rd_skip(&r, 4);                       /* minSeqLedgerGap */
            uint32_t ns = rd_u32(&r);
            if (r.err || ns > 2) return -1;
            tx->n_extra_signers = (int)ns;
            for (uint32_t i = 0; i < ns; i++)
                if (parse_signer_key(&r, &tx->extra_signers[i]) < 0)
                    return -1;
        }
    }
    /* memo */
    uint32_t memo_t = rd_u32(&r);
    if (r.err)
        return -1;
    switch (memo_t) {
    case 0: break;
    case 1: { uint32_t sl; if (!rd_varopaque(&r, 28, &sl)) return -1; break; }
    case 2: rd_skip(&r, 8); break;
    case 3: case 4: rd_skip(&r, 32); break;
    default: return -1;
    }
    /* operations */
    uint32_t n_ops = rd_u32(&r);
    if (r.err || n_ops > MAX_OPS)
        return -1;
    tx->n_ops = (int)n_ops;
    int unsupported = 0;
    for (uint32_t i = 0; i < n_ops; i++) {
        int rc = parse_op(&r, &tx->ops[i], tx);
        if (rc < 0)
            return -1;
        if (rc == 1)
            unsupported = 1;
        if (unsupported)
            return 1;           /* stop early: caller falls back */
    }
    /* ext */
    int32_t ext = rd_i32(&r);
    if (r.err)
        return -1;
    if (ext != 0)
        return 1;               /* soroban tx ext: fall back */
    int tx_end = r.off;
    /* signatures */
    uint32_t n_sigs = rd_u32(&r);
    if (r.err || n_sigs > 20)
        return -1;
    tx->n_sigs = (int)n_sigs;
    for (uint32_t i = 0; i < n_sigs; i++) {
        const uint8_t *hint = rd_take(&r, 4);
        if (!hint)
            return -1;
        uint32_t sl;
        const uint8_t *sig = rd_varopaque(&r, 64, &sl);
        if (!sig)
            return -1;
        tx->sigs[i].hint = hint;
        tx->sigs[i].sig = sig;
        tx->sigs[i].sig_len = (int)sl;
        tx->sigs[i].used = 0;
    }
    if (r.err)
        return -1;
    tx->env_len = r.off;
    outer->off += r.off;
    /* content hash = sha256(network_id || u32(ENVELOPE_TYPE_TX=2) ||
     * v1-tx-bytes).  For v0, the v1 payload equals 00000000 (muxed tag)
     * followed by the raw v0 tx bytes — byte-identical layout (the
     * optional-timeBounds flag doubles as the PRECOND_TIME tag). */
    Sha256 s;
    sha_init(&s);
    sha_update(&s, network_id, 32);
    static const uint8_t tag_tx[4] = {0, 0, 0, 2};
    sha_update(&s, tag_tx, 4);
    if (tx->is_v0) {
        static const uint8_t mux0[4] = {0, 0, 0, 0};
        sha_update(&s, mux0, 4);
    }
    sha_update(&s, env + tx_start, tx_end - tx_start);
    sha_final(&s, tx->content_hash);
    tx->supported = 1;
    return 0;
}

/* ---- buckets (mirror bucket/bucket.py + bucket_list.py exactly) ------- */

typedef struct {
    int n, cap;
    RB **keys;                  /* sort keys (LedgerKey XDR) */
    RB **recs;                  /* full BucketEntry records (tag + body) */
    uint32_t protocol;
    uint8_t hash[32];
    int hash_valid;
    int rc;
} CBucket;

static CBucket *
cbucket_new(int cap)
{
    CBucket *b = PyMem_Calloc(1, sizeof(CBucket));
    if (!b) { PyErr_NoMemory(); return NULL; }
    if (cap > 0) {
        b->keys = PyMem_Malloc(cap * sizeof(RB *));
        b->recs = PyMem_Malloc(cap * sizeof(RB *));
        if (!b->keys || !b->recs) {
            PyMem_Free(b->keys); PyMem_Free(b->recs); PyMem_Free(b);
            PyErr_NoMemory();
            return NULL;
        }
    }
    b->cap = cap;
    b->rc = 1;
    return b;
}

static void
cbucket_unref(CBucket *b)
{
    if (!b || --b->rc > 0)
        return;
    for (int i = 0; i < b->n; i++) {
        rb_unref(b->keys[i]);
        rb_unref(b->recs[i]);
    }
    PyMem_Free(b->keys);
    PyMem_Free(b->recs);
    PyMem_Free(b);
}

static int
rec_type(const RB *rec)
{
    /* BucketEntryType from the record tag (big-endian i32) */
    return (int32_t)(((uint32_t)rec->bytes[0] << 24) |
                     ((uint32_t)rec->bytes[1] << 16) |
                     ((uint32_t)rec->bytes[2] << 8) | rec->bytes[3]);
}

#define BE_LIVE 0
#define BE_DEAD 1
#define BE_INIT 2

static void
cbucket_hash(CBucket *b, uint8_t out[32])
{
    if (b->hash_valid) {
        memcpy(out, b->hash, 32);
        return;
    }
    if (b->n == 0) {
        memset(out, 0, 32);     /* empty bucket hashes to 32 zero bytes */
        memcpy(b->hash, out, 32);
        b->hash_valid = 1;
        return;
    }
    Sha256 s;
    sha_init(&s);
    uint8_t meta[12];
    meta[0] = 0xFF; meta[1] = 0xFF; meta[2] = 0xFF; meta[3] = 0xFF;
    meta[4] = b->protocol >> 24; meta[5] = b->protocol >> 16;
    meta[6] = b->protocol >> 8; meta[7] = b->protocol;
    memset(meta + 8, 0, 4);     /* BucketMetadata ext v0 */
    sha_update(&s, meta, 12);
    for (int i = 0; i < b->n; i++)
        sha_update(&s, b->recs[i]->bytes, b->recs[i]->len);
    sha_final(&s, out);
    memcpy(b->hash, out, 32);
    b->hash_valid = 1;
}

/* CAP-20 pair-rule merge (mirror merge_buckets, protocol >= 12 form) */
static CBucket *
cbucket_merge(CBucket *old, CBucket *new, int keep_tombstones,
              uint32_t protocol)
{
    CBucket *out = cbucket_new(old->n + new->n);
    if (!out)
        return NULL;
    out->protocol = protocol;
    int i = 0, j = 0;

#define EMIT(K, R) do { \
        out->keys[out->n] = rb_ref(K); \
        out->recs[out->n] = rb_ref(R); \
        out->n++; \
    } while (0)

    while (i < old->n || j < new->n) {
        int take_old;
        if (j >= new->n)
            take_old = 1;
        else if (i >= old->n)
            take_old = 0;
        else {
            int c = bcmp_py(old->keys[i]->bytes, old->keys[i]->len,
                            new->keys[j]->bytes, new->keys[j]->len);
            if (c < 0)
                take_old = 1;
            else if (c > 0)
                take_old = 0;
            else {
                /* equal keys: pair rules */
                RB *ok = old->keys[i], *orr = old->recs[i];
                RB *nk = new->keys[j], *nr = new->recs[j];
                int ot = rec_type(orr), nt = rec_type(nr);
                i++; j++;
                (void)ok;
                if (ot == BE_INIT && nt == BE_LIVE) {
                    /* INIT carrying the live value */
                    RB *re = rb_new(nr->bytes, nr->len);
                    if (!re) { cbucket_unref(out); return NULL; }
                    re->bytes[3] = BE_INIT; re->bytes[2] = 0;
                    re->bytes[1] = 0; re->bytes[0] = 0;
                    if (!keep_tombstones) {
                        /* emit() would decay INIT->LIVE */
                        re->bytes[3] = BE_LIVE;
                    }
                    out->keys[out->n] = rb_ref(nk);
                    out->recs[out->n] = re;
                    out->n++;
                } else if (ot == BE_INIT && nt == BE_DEAD) {
                    /* annihilated */
                } else if (ot == BE_DEAD && nt == BE_INIT) {
                    RB *re = rb_new(nr->bytes, nr->len);
                    if (!re) { cbucket_unref(out); return NULL; }
                    re->bytes[0] = 0; re->bytes[1] = 0;
                    re->bytes[2] = 0; re->bytes[3] = BE_LIVE;
                    out->keys[out->n] = rb_ref(nk);
                    out->recs[out->n] = re;
                    out->n++;
                } else {
                    /* newer entry wins, through emit() rules */
                    if (nt == BE_DEAD) {
                        if (keep_tombstones)
                            EMIT(nk, nr);
                    } else if (nt == BE_INIT && !keep_tombstones) {
                        RB *re = rb_new(nr->bytes, nr->len);
                        if (!re) { cbucket_unref(out); return NULL; }
                        re->bytes[0] = 0; re->bytes[1] = 0;
                        re->bytes[2] = 0; re->bytes[3] = BE_LIVE;
                        out->keys[out->n] = rb_ref(nk);
                        out->recs[out->n] = re;
                        out->n++;
                    } else {
                        EMIT(nk, nr);
                    }
                }
                continue;
            }
        }
        RB *k = take_old ? old->keys[i] : new->keys[j];
        RB *rec = take_old ? old->recs[i] : new->recs[j];
        if (take_old) i++; else j++;
        int t = rec_type(rec);
        if (t == BE_DEAD) {
            if (keep_tombstones)
                EMIT(k, rec);
        } else if (t == BE_INIT && !keep_tombstones) {
            RB *re = rb_new(rec->bytes, rec->len);
            if (!re) { cbucket_unref(out); return NULL; }
            re->bytes[0] = 0; re->bytes[1] = 0;
            re->bytes[2] = 0; re->bytes[3] = BE_LIVE;
            out->keys[out->n] = rb_ref(k);
            out->recs[out->n] = re;
            out->n++;
        } else {
            EMIT(k, rec);
        }
    }
#undef EMIT
    return out;
}

#define NUM_LEVELS 11

typedef struct {
    CBucket *curr, *snap;
    CBucket *next_out;          /* resolved pending merge, or NULL */
} CLevel;

typedef struct {
    CLevel levels[NUM_LEVELS];
} CBucketList;

static int64_t
level_half_c(int level)
{
    /* level_size = 4^(level+1); half = size/2 */
    int64_t size = 1;
    for (int i = 0; i <= level; i++)
        size *= 4;
    return size / 2;
}

static int
level_should_spill_c(int64_t ledger, int level)
{
    if (level == NUM_LEVELS - 1)
        return 0;
    int64_t half = level_half_c(level);
    return ledger == (ledger / half) * half;
}

static int
cbl_init(CBucketList *bl)
{
    for (int i = 0; i < NUM_LEVELS; i++) {
        bl->levels[i].curr = cbucket_new(0);
        bl->levels[i].snap = cbucket_new(0);
        bl->levels[i].next_out = NULL;
        if (!bl->levels[i].curr || !bl->levels[i].snap)
            return -1;
    }
    return 0;
}

static void
cbl_free(CBucketList *bl)
{
    for (int i = 0; i < NUM_LEVELS; i++) {
        cbucket_unref(bl->levels[i].curr);
        cbucket_unref(bl->levels[i].snap);
        cbucket_unref(bl->levels[i].next_out);
        bl->levels[i].curr = bl->levels[i].snap = bl->levels[i].next_out = NULL;
    }
}

/* add one ledger's fresh bucket (already sorted) */
static int
cbl_add_batch(CBucketList *bl, int64_t ledger_seq, uint32_t protocol,
              CBucket *fresh)
{
    for (int i = NUM_LEVELS - 1; i >= 1; i--) {
        if (level_should_spill_c(ledger_seq, i - 1)) {
            CLevel *below = &bl->levels[i - 1];
            CLevel *lvl = &bl->levels[i];
            /* snap_curr on the level below */
            cbucket_unref(below->snap);
            below->snap = below->curr;
            below->curr = cbucket_new(0);
            if (!below->curr)
                return -1;
            CBucket *spill = below->snap;
            /* commit the pending merge */
            if (lvl->next_out) {
                cbucket_unref(lvl->curr);
                lvl->curr = lvl->next_out;
                lvl->next_out = NULL;
            }
            /* prepare the next merge (computed eagerly; outputs are pure
             * functions of inputs, so eager == the reference's lazy
             * worker-thread merge, bit for bit) */
            int keep = i < NUM_LEVELS - 1;
            lvl->next_out = cbucket_merge(lvl->curr, spill, keep, protocol);
            if (!lvl->next_out)
                return -1;
        }
    }
    CLevel *l0 = &bl->levels[0];
    CBucket *merged = cbucket_merge(l0->curr, fresh, 1, protocol);
    if (!merged)
        return -1;
    cbucket_unref(l0->curr);
    l0->curr = merged;
    return 0;
}

static void
cbl_hash(CBucketList *bl, uint8_t out[32])
{
    Sha256 s;
    sha_init(&s);
    for (int i = 0; i < NUM_LEVELS; i++) {
        uint8_t ch[32], sh[32], lh[32];
        Sha256 ls;
        cbucket_hash(bl->levels[i].curr, ch);
        cbucket_hash(bl->levels[i].snap, sh);
        sha_init(&ls);
        sha_update(&ls, ch, 32);
        sha_update(&ls, sh, 32);
        sha_final(&ls, lh);
        sha_update(&s, lh, 32);
    }
    sha_final(&s, out);
}

/* ---- ledger header ---------------------------------------------------- */

typedef struct {
    uint32_t ledger_version;
    uint8_t previous_hash[32];
    /* scpValue kept as raw bytes (copied), with parsed fields */
    uint8_t *scp_value;
    int scp_len;
    uint8_t tx_set_hash[32];
    uint64_t close_time;
    /* upgrade slices point into scp_value */
    int n_upgrades;
    struct { const uint8_t *p; int len; } upgrades[6];
    uint8_t tx_set_result_hash[32];
    uint8_t bucket_list_hash[32];
    uint32_t ledger_seq;
    int64_t total_coins;
    int64_t fee_pool;
    uint32_t inflation_seq;
    uint64_t id_pool;
    uint32_t base_fee;
    uint32_t base_reserve;
    uint32_t max_tx_set_size;
    uint8_t skip_list[4][32];
    uint8_t *ext;               /* raw LedgerHeaderExt bytes (copied) */
    int ext_len;
} CHeader;

static void
cheader_clear(CHeader *h)
{
    PyMem_Free(h->scp_value);
    PyMem_Free(h->ext);
    memset(h, 0, sizeof(*h));
}

/* parse a StellarValue, recording the slice boundaries; r advances */
static int
parse_scp_value(Rd *r, CHeader *h)
{
    int start = r->off;
    const uint8_t *tsh = rd_take(r, 32);
    if (!tsh)
        return -1;
    memcpy(h->tx_set_hash, tsh, 32);
    h->close_time = rd_u64(r);
    uint32_t nup = rd_u32(r);
    if (r->err || nup > 6)
        return -1;
    h->n_upgrades = (int)nup;
    int up_offs[6], up_lens[6];
    for (uint32_t i = 0; i < nup; i++) {
        int uo = r->off;
        uint32_t ul;
        if (!rd_varopaque(r, 128, &ul))
            return -1;
        up_offs[i] = uo + 4;     /* past the length word */
        up_lens[i] = (int)ul;
    }
    int32_t vext = rd_i32(r);
    if (r->err)
        return -1;
    if (vext == 1) {             /* LedgerCloseValueSignature */
        if (rd_u32(r) != 0) { r->err = 1; return -1; }  /* NodeID PK type */
        rd_skip(r, 32);
        uint32_t sl;
        if (!rd_varopaque(r, 64, &sl))
            return -1;
    } else if (vext != 0) {
        return -1;
    }
    if (r->err)
        return -1;
    int len = r->off - start;
    h->scp_value = PyMem_Malloc(len);
    if (!h->scp_value) { PyErr_NoMemory(); return -1; }
    memcpy(h->scp_value, r->p + start, len); /* corelint: disable=reader-discipline -- copy of [start, off): every byte already consumed via rd_* above */
    h->scp_len = len;
    for (int i = 0; i < h->n_upgrades; i++) {
        h->upgrades[i].p = h->scp_value + (up_offs[i] - start);
        h->upgrades[i].len = up_lens[i];
    }
    return 0;
}

/* parse a full LedgerHeader from r into h (h cleared first) */
static int
parse_header(Rd *r, CHeader *h)
{
    memset(h, 0, sizeof(*h));
    h->ledger_version = rd_u32(r);
    const uint8_t *ph = rd_take(r, 32);
    if (!ph)
        return -1;
    memcpy(h->previous_hash, ph, 32);
    if (parse_scp_value(r, h) < 0)
        return -1;
    const uint8_t *q;
    if (!(q = rd_take(r, 32))) return -1;
    memcpy(h->tx_set_result_hash, q, 32);
    if (!(q = rd_take(r, 32))) return -1;
    memcpy(h->bucket_list_hash, q, 32);
    h->ledger_seq = rd_u32(r);
    h->total_coins = rd_i64(r);
    h->fee_pool = rd_i64(r);
    h->inflation_seq = rd_u32(r);
    h->id_pool = rd_u64(r);
    h->base_fee = rd_u32(r);
    h->base_reserve = rd_u32(r);
    h->max_tx_set_size = rd_u32(r);
    for (int i = 0; i < 4; i++) {
        if (!(q = rd_take(r, 32)))
            return -1;
        memcpy(h->skip_list[i], q, 32);
    }
    int ext_start = r->off;
    int32_t ext = rd_i32(r);
    if (r->err)
        return -1;
    if (ext == 1) {              /* LedgerHeaderExtensionV1: flags + ext v0 */
        rd_skip(r, 4);
        if (rd_i32(r) != 0 || r->err)
            return -1;
    } else if (ext != 0) {
        return -1;
    }
    int ext_len = r->off - ext_start;
    h->ext = PyMem_Malloc(ext_len);
    if (!h->ext) { PyErr_NoMemory(); return -1; }
    memcpy(h->ext, r->p + ext_start, ext_len); /* corelint: disable=reader-discipline -- copy of [ext_start, off): every byte already consumed via rd_* above */
    h->ext_len = ext_len;
    return r->err ? -1 : 0;
}

/* replace the header's scpValue with the raw slice `p` (parsed fields
 * refreshed) — close_ledger's `header.scpValue = stellar_value` */
static int
cheader_set_scp(CHeader *h, const uint8_t *p, int len)
{
    PyMem_Free(h->scp_value);
    h->scp_value = NULL;
    h->scp_len = 0;
    h->n_upgrades = 0;
    Rd r;
    rd_init(&r, p, len);
    if (parse_scp_value(&r, h) < 0 || r.off != len)
        return -1;
    return 0;
}

static int
serialize_header(const CHeader *h, Buf *b)
{
    if (buf_u32(b, h->ledger_version) < 0 ||
        buf_put(b, h->previous_hash, 32) < 0 ||
        buf_put(b, h->scp_value, h->scp_len) < 0 ||
        buf_put(b, h->tx_set_result_hash, 32) < 0 ||
        buf_put(b, h->bucket_list_hash, 32) < 0 ||
        buf_u32(b, h->ledger_seq) < 0 ||
        buf_i64(b, h->total_coins) < 0 ||
        buf_i64(b, h->fee_pool) < 0 ||
        buf_u32(b, h->inflation_seq) < 0 ||
        buf_u64(b, h->id_pool) < 0 ||
        buf_u32(b, h->base_fee) < 0 ||
        buf_u32(b, h->base_reserve) < 0 ||
        buf_u32(b, h->max_tx_set_size) < 0)
        return -1;
    for (int i = 0; i < 4; i++)
        if (buf_put(b, h->skip_list[i], 32) < 0)
            return -1;
    return buf_put(b, h->ext, h->ext_len);
}

/* voted-upgrade application (mirror herder/upgrades.py apply_to_checked:
 * malformed or invalid-for-apply upgrades are skipped, never fatal) */
#define MAX_SUPPORTED_PROTOCOL 23

static void
apply_upgrades(CHeader *h)
{
    for (int i = 0; i < h->n_upgrades; i++) {
        Rd r;
        rd_init(&r, h->upgrades[i].p, h->upgrades[i].len);
        int32_t t = rd_i32(&r);
        uint32_t v = rd_u32(&r);
        if (r.err || r.off != r.len)
            continue;            /* malformed: skip (logged in Python) */
        switch (t) {
        case 1:                  /* LEDGER_UPGRADE_VERSION */
            if (h->ledger_version < v && v <= MAX_SUPPORTED_PROTOCOL)
                h->ledger_version = v;
            break;
        case 2:                  /* BASE_FEE */
            if (v > 0)
                h->base_fee = v;
            break;
        case 3:                  /* MAX_TX_SET_SIZE */
            if (v > 0)
                h->max_tx_set_size = v;
            break;
        case 4:                  /* BASE_RESERVE */
            if (v > 0)
                h->base_reserve = v;
            break;
        default:
            break;               /* flags/config: unsupported, skip */
        }
    }
}

/* ---- the engine ------------------------------------------------------- */

/* one open Begin/End sponsorship sandwich (CAP-33): `sponsor` covers every
 * reserve created FOR `sponsored` until the matching End op */
typedef struct {
    uint8_t sponsored[32];
    uint8_t sponsor[32];
} Sandwich;

typedef struct {
    PyObject_HEAD
    uint8_t network_id[32];
    int state_loaded;
    int poisoned;               /* failure after the store fold began */
    Map store;                  /* authoritative entries */
    Map ledger_delta;           /* current ledger's changes (NULL = dead) */
    Map tx_delta;               /* current tx's nested overlay */
    Map op_delta;               /* current op's overlay (per-op rollback,
                                   mirror frame.py's per-op LedgerTxn) */
    Map hop_delta;              /* path-payment book-attempt overlay
                                   (mirror _convert_hop's child LedgerTxn) */
    Map *cur;                   /* write target of the active layer */
    int op_active, hop_active;
    /* per-tx Begin/End sandwich state (mirror frame._sponsorship_ctx) */
    Sandwich sandwich[MAX_OPS];
    int n_sandwich;
    CBucketList bl;
    CHeader header;             /* last closed header */
    uint8_t lcl_hash[32];
    VCache vcache;
    /* cumulative SetOptions ed25519-signer harvest for accel pairing
     * (mirrors PreverifyPipeline._harvested_hint; in-order dispatch makes
     * it a superset of every signer the apply will try) */
    uint8_t (*harvest)[32];
    int n_harvest, cap_harvest;
    /* stats */
    uint64_t ledgers_applied, txs_applied;
} Engine;

/* entry lookup through hop_delta -> op_delta -> tx_delta -> ledger_delta
 * -> store.  Returns borrowed RB* (NULL when absent/dead). */
static RB *
eng_get(Engine *e, const uint8_t *key, int klen)
{
    int present;
    RB *v;
    if (e->hop_active) {
        v = map_get(&e->hop_delta, key, klen, &present);
        if (present)
            return v;
    }
    if (e->op_active) {
        v = map_get(&e->op_delta, key, klen, &present);
        if (present)
            return v;
    }
    v = map_get(&e->tx_delta, key, klen, &present);
    if (present)
        return v;
    v = map_get(&e->ledger_delta, key, klen, &present);
    if (present)
        return v;
    return map_get(&e->store, key, klen, &present);
}

/* fold the upper overlay into the lower one (op commit / hop commit) */
static int
eng_fold_overlay(Map *upper, Map *lower)
{
    for (int i = 0; i < upper->cap; i++) {
        MapSlot *s = &upper->slots[i];
        if (s->state != 1)
            continue;
        if (map_put(lower, rb_ref(s->key),
                    s->val ? rb_ref(s->val) : NULL) < 0)
            return -1;
    }
    map_clear(upper);
    return 0;
}

/* write into the CURRENT overlay (tx_delta during tx apply, ledger_delta
 * in fee/bookkeeping phases); val may be NULL (tombstone).  Takes
 * ownership of val's ref; copies the key. */
static int
eng_put(Engine *e, Map *overlay, const uint8_t *key, int klen, RB *val)
{
    (void)e;
    RB *k = rb_new(key, klen);
    if (!k) { rb_unref(val); PyErr_NoMemory(); return -1; }
    return map_put(overlay, k, val);
}

static int
eng_get_account(Engine *e, const uint8_t pk[32], CAccount *out)
{
    uint8_t kx[40];
    account_key_xdr_c(pk, kx);
    RB *rec = eng_get(e, kx, 40);
    if (!rec)
        return 0;
    if (parse_account_entry(rec->bytes, rec->len, out) < 0)
        return -1;               /* corrupt state: fail-stop */
    return 1;
}

static int
eng_put_account(Engine *e, Map *overlay, const CAccount *a)
{
    Buf b = {0};
    if (serialize_account_entry(a, &b) < 0) {
        PyMem_Free(b.p);
        return -1;
    }
    RB *val = rb_new(b.p, b.len);
    PyMem_Free(b.p);
    if (!val) { PyErr_NoMemory(); return -1; }
    uint8_t kx[40];
    account_key_xdr_c(a->account_id, kx);
    return eng_put(e, overlay, kx, 40, val);
}

/* fold tx_delta into ledger_delta (tx commit) */
static int
eng_commit_tx(Engine *e)
{
    Map *td = &e->tx_delta;
    for (int i = 0; i < td->cap; i++) {
        MapSlot *s = &td->slots[i];
        if (s->state != 1)
            continue;
        if (map_put(&e->ledger_delta, rb_ref(s->key),
                    s->val ? rb_ref(s->val) : NULL) < 0)
            return -1;
    }
    map_clear(td);
    return 0;
}

static void
eng_rollback_tx(Engine *e)
{
    map_clear(&e->hop_delta);
    map_clear(&e->op_delta);
    map_clear(&e->tx_delta);
    e->hop_active = 0;
    e->op_active = 0;
    e->cur = &e->tx_delta;
}

/* active-sandwich lookup (mirror sponsorship.active_sponsor): the account
 * sponsoring future reserves of `owner` in this tx, or NULL */
static const uint8_t *
active_sponsor_c(Engine *e, const uint8_t owner[32])
{
    for (int i = 0; i < e->n_sandwich; i++)
        if (memcmp(e->sandwich[i].sponsored, owner, 32) == 0)
            return e->sandwich[i].sponsor;
    return NULL;
}

/* CAP-33 sponsorship core (defined with the round-12 op set below) */
#define SP_SUCCESS 0
#define SP_LOW_RESERVE 1
#define SP_TOO_MANY 2

static int establish_sponsorship_c(Engine *e, const uint8_t sponsor_id[32],
                                   CAccount *owner, int mult);
static int sponsorship_error_c(Buf *rb, int32_t op_type, int32_t low_code,
                               int code);
static void acc_ensure_v2(CAccount *a);

/* reserve math in 128-bit (Python ints are unbounded) ------------------- */

static i128
min_balance_128(const CHeader *h, const CAccount *a)
{
    i128 count = (i128)2 + a->num_sub + a->num_sponsoring - a->num_sponsored;
    return count * (i128)h->base_reserve;
}

/* mirror utils.add_balance */
static int
add_balance_c(const CHeader *h, CAccount *a, int64_t delta, int with_floor)
{
    i128 nb = (i128)a->balance + delta;
    if (nb < 0 || nb > INT64_MAXV)
        return 0;
    if (delta < 0) {
        i128 floor = 0;
        if (with_floor)
            floor = min_balance_128(h, a) + a->liab_selling;
        if (nb < floor)
            return 0;
    } else {
        if (nb > (i128)INT64_MAXV - a->liab_buying)
            return 0;
    }
    a->balance = (int64_t)nb;
    return 1;
}

/* mirror utils.add_num_entries */
static int
add_num_entries_c(const CHeader *h, CAccount *a, int delta)
{
    i128 nc = (i128)a->num_sub + delta;
    if (nc < 0)
        return 0;
    if (delta > 0) {
        i128 need = ((i128)2 + nc + a->num_sponsoring - a->num_sponsored)
                    * (i128)h->base_reserve;
        if ((i128)a->balance < need + a->liab_selling)
            return 0;
    }
    a->num_sub = (uint32_t)nc;
    return 1;
}

/* ---- operation results ------------------------------------------------ */

/* opINNER + op type + inner code (void arm) */
static int
res_inner(Buf *b, int32_t op_type, int32_t code)
{
    return buf_i32(b, 0) < 0 || buf_i32(b, op_type) < 0 ||
           buf_i32(b, code) < 0 ? -1 : 0;
}

/* outer OperationResult code (opBAD_AUTH/opNO_ACCOUNT/...): void arm */
static int
res_outer(Buf *b, int32_t code)
{
    return buf_i32(b, code);
}

/* ---- the three native op frames --------------------------------------- *
 * Each returns 1 (op success), 0 (op failed; result written), -1 (engine
 * error).  All writes go to tx_delta.  Result bytes appended to `rb`.
 */

/* mirror CreateAccountOpFrame (operations.py) */
static int
op_create_account(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
                  Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    rd_skip(&r, 4);                     /* PK type (checked at parse) */
    const uint8_t *dest = rd_take(&r, 32);
    int64_t starting = rd_i64(&r);
    if (!dest || r.err)
        return -1;
    CHeader *h = &e->header;

    /* do_check_valid */
    int min_ok = h->ledger_version >= 14 ? starting >= 0 : starting > 0;
    if (!min_ok || memcmp(dest, src_id, 32) == 0)
        return res_inner(rb, 0, -1) < 0 ? -1 : 0;   /* MALFORMED */

    /* do_apply */
    uint8_t dk[40];
    account_key_xdr_c(dest, dk);
    if (eng_get(e, dk, 40) != NULL)
        return res_inner(rb, 0, -4) < 0 ? -1 : 0;   /* ALREADY_EXIST */
    CAccount na;
    memset(&na, 0, sizeof(na));
    na.last_modified = h->ledger_seq;
    memcpy(na.account_id, dest, 32);
    na.balance = starting;
    na.seq_num = (int64_t)h->ledger_seq << 32;
    na.thresholds[0] = 1;                            /* defaults */
    /* sponsored create (CAP-33 sandwich, v14+): the sponsor's reserve
     * covers the new account's 2 base reserves, checked BEFORE the
     * source pays the starting balance */
    const uint8_t *sponsor = h->ledger_version >= 14
        ? active_sponsor_c(e, dest) : NULL;
    if (sponsor != NULL) {
        int sc = sponsorship_error_c(rb, 0, -3,
            establish_sponsorship_c(e, sponsor, &na, 2));
        if (sc)
            return sc < 0 ? -1 : 0;
        na.entry_ext_v1 = 1;
        na.has_sponsor = 1;
        memcpy(na.sponsor, sponsor, 32);
    } else if (starting < (i128)2 * h->base_reserve) {
        return res_inner(rb, 0, -3) < 0 ? -1 : 0;   /* LOW_RESERVE */
    }
    CAccount src;
    int got = eng_get_account(e, src_id, &src);
    if (got < 0)
        return -1;
    if (!got)
        return -1;                                   /* checked earlier */
    if (!add_balance_c(h, &src, -starting, 1))
        return res_inner(rb, 0, -2) < 0 ? -1 : 0;   /* UNDERFUNDED */
    if (eng_put_account(e, e->cur, &src) < 0)
        return -1;
    if (eng_put_account(e, e->cur, &na) < 0)
        return -1;
    return res_inner(rb, 0, 0) < 0 ? -1 : 1;
}

/* mirror PaymentOpFrame, native-asset arm only (probe gates the rest) */
static int
op_payment(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32], Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t mt = rd_u32(&r);
    if (mt == 0x100)
        rd_skip(&r, 8);
    const uint8_t *dest = rd_take(&r, 32);
    uint32_t asset_t = rd_u32(&r);
    int64_t amount = rd_i64(&r);
    if (!dest || r.err || asset_t != 0)
        return -1;
    CHeader *h = &e->header;

    /* do_check_valid: amount > 0 (native asset is always valid) */
    if (amount <= 0)
        return res_inner(rb, 1, -1) < 0 ? -1 : 0;   /* MALFORMED */

    CAccount dst;
    int got = eng_get_account(e, dest, &dst);
    if (got < 0)
        return -1;
    if (!got)
        return res_inner(rb, 1, -5) < 0 ? -1 : 0;   /* NO_DESTINATION */
    CAccount src;
    got = eng_get_account(e, src_id, &src);
    if (got <= 0)
        return -1;
    if (memcmp(src_id, dest, 32) == 0)
        return res_inner(rb, 1, 0) < 0 ? -1 : 1;    /* self-pay: no-op */
    if (!add_balance_c(h, &src, -amount, 1))
        return res_inner(rb, 1, -2) < 0 ? -1 : 0;   /* UNDERFUNDED */
    if (!add_balance_c(h, &dst, amount, 0))
        return res_inner(rb, 1, -8) < 0 ? -1 : 0;   /* LINE_FULL */
    src.last_modified = h->ledger_seq;
    dst.last_modified = h->ledger_seq;
    if (eng_put_account(e, e->cur, &src) < 0 ||
        eng_put_account(e, e->cur, &dst) < 0)
        return -1;
    return res_inner(rb, 1, 0) < 0 ? -1 : 1;
}

/* mirror SetOptionsOpFrame incl. signerSponsoringIDs alignment (no
 * sandwich can be active natively; sponsored-signer REMOVAL still
 * releases the recorded sponsor) */
static int
op_set_options(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
               Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    CHeader *h = &e->header;

    int has_inf = 0;
    uint8_t inf_dest[32];
    uint32_t p = rd_u32(&r);
    if (p) {
        rd_skip(&r, 4);
        const uint8_t *q = rd_take(&r, 32);
        if (!q) return -1;
        memcpy(inf_dest, q, 32);
        has_inf = 1;
    }
    int has_clear = 0, has_set = 0;
    uint32_t clear_flags = 0, set_flags = 0;
    p = rd_u32(&r); if (p) { has_clear = 1; clear_flags = rd_u32(&r); }
    p = rd_u32(&r); if (p) { has_set = 1; set_flags = rd_u32(&r); }
    int has_thr[4] = {0, 0, 0, 0};
    uint32_t thr[4] = {0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {        /* master, low, med, high */
        p = rd_u32(&r);
        if (p) { has_thr[i] = 1; thr[i] = rd_u32(&r); }
    }
    int has_home = 0;
    const uint8_t *home = NULL;
    uint32_t home_len = 0;
    p = rd_u32(&r);
    if (p) {
        home = rd_varopaque(&r, 32, &home_len);
        if (!home) return -1;
        has_home = 1;
    }
    int has_signer = 0;
    CSigner signer;
    uint32_t signer_weight = 0;
    p = rd_u32(&r);
    if (p) {
        if (parse_signer_key(&r, &signer) < 0) return -1;
        signer_weight = rd_u32(&r);
        signer.weight = signer_weight;
        has_signer = 1;
    }
    if (r.err)
        return -1;

    /* do_check_valid (order mirrors operations.py) */
    for (int i = 0; i < 4; i++)
        if (has_thr[i] && thr[i] > 255)
            return res_inner(rb, 5, -7) < 0 ? -1 : 0;  /* THRESHOLD_OUT_OF_RANGE */
    if (has_set && has_clear && (set_flags & clear_flags))
        return res_inner(rb, 5, -3) < 0 ? -1 : 0;      /* BAD_FLAGS */
    uint32_t mask = 0xF;                               /* MASK_ACCOUNT_FLAGS_V17 */
    if ((has_set && (set_flags & ~mask)) ||
        (has_clear && (clear_flags & ~mask)))
        return res_inner(rb, 5, -6) < 0 ? -1 : 0;      /* UNKNOWN_FLAG */
    if (has_home) {
        for (uint32_t i = 0; i < home_len; i++)
            if (home[i] > 0x7F)
                return res_inner(rb, 5, -9) < 0 ? -1 : 0;  /* INVALID_HOME_DOMAIN */
    }
    if (has_signer) {
        if (signer.key_type == 0 && memcmp(signer.key, src_id, 32) == 0)
            return res_inner(rb, 5, -8) < 0 ? -1 : 0;  /* BAD_SIGNER */
        if (signer_weight > 255)
            return res_inner(rb, 5, -8) < 0 ? -1 : 0;
    }

    /* do_apply */
    CAccount src;
    int got = eng_get_account(e, src_id, &src);
    if (got <= 0)
        return -1;
    if (has_inf) {
        uint8_t ik[40];
        account_key_xdr_c(inf_dest, ik);
        if (eng_get(e, ik, 40) == NULL)
            return res_inner(rb, 5, -4) < 0 ? -1 : 0;  /* INVALID_INFLATION */
        memcpy(src.inflation_dest, inf_dest, 32);
        src.has_inflation_dest = 1;
    }
    if (has_clear) {
        if (src.flags & 0x4)                           /* AUTH_IMMUTABLE */
            return res_inner(rb, 5, -5) < 0 ? -1 : 0;  /* CANT_CHANGE */
        src.flags &= ~clear_flags;
    }
    if (has_set) {
        if (src.flags & 0x4)
            return res_inner(rb, 5, -5) < 0 ? -1 : 0;
        src.flags |= set_flags;
    }
    for (int i = 0; i < 4; i++)
        if (has_thr[i])
            src.thresholds[i] = (uint8_t)thr[i];
    if (has_home) {
        memcpy(src.home_domain, home, home_len);
        src.home_domain_len = home_len;
    }
    if (has_signer) {
        uint8_t new_kx[104];
        int new_klen = signer_key_xdr(&signer, new_kx);
        int idx = -1;
        for (int i = 0; i < src.n_signers; i++) {
            uint8_t kx[104];
            int klen = signer_key_xdr(&src.signers[i], kx);
            if (klen == new_klen && memcmp(kx, new_kx, klen) == 0) {
                idx = i;
                break;
            }
        }
        int has_v2 = src.ext_level >= 2;
        if (signer_weight == 0) {
            if (idx >= 0) {
                int sponsored = has_v2 && idx < src.n_ssids &&
                                src.ssids[idx].present;
                uint8_t sponsor[32];
                if (sponsored)
                    memcpy(sponsor, src.ssids[idx].id, 32);
                /* pop signer idx */
                for (int i = idx; i + 1 < src.n_signers; i++)
                    src.signers[i] = src.signers[i + 1];
                src.n_signers--;
                if (has_v2 && idx < src.n_ssids) {
                    for (int i = idx; i + 1 < src.n_ssids; i++)
                        src.ssids[i] = src.ssids[i + 1];
                    src.n_ssids--;
                }
                if (sponsored) {
                    /* release_signer_sponsorship + numSubEntries -= 1 */
                    CAccount sp;
                    int g = eng_get_account(e, sponsor, &sp);
                    if (g < 0)
                        return -1;
                    if (g) {
                        if (sp.num_sponsoring < 1)
                            return -1;      /* count underflow: fail-stop */
                        sp.num_sponsoring -= 1;
                        if (sp.ext_level < 2)
                            sp.ext_level = 2;
                        sp.last_modified = h->ledger_seq;
                        if (eng_put_account(e, e->cur, &sp) < 0)
                            return -1;
                        /* re-read src if sponsor == src (same account) */
                        if (memcmp(sponsor, src_id, 32) == 0) {
                            if (eng_get_account(e, src_id, &src) <= 0)
                                return -1;
                        }
                    }
                    if (src.num_sponsored < 1)
                        return -1;
                    src.num_sponsored -= 1;
                    if (src.ext_level < 2)
                        src.ext_level = 2;
                    src.num_sub -= 1;
                } else if (!add_num_entries_c(h, &src, -1)) {
                    /* numSubEntries would go negative (corrupt counts):
                     * the oracle reports LOW_RESERVE here */
                    return res_inner(rb, 5, -1) < 0 ? -1 : 0;
                }
            }
        } else if (idx >= 0) {
            src.signers[idx].weight = signer_weight;
        } else {
            if (src.n_signers >= 20)
                return res_inner(rb, 5, -2) < 0 ? -1 : 0;  /* TOO_MANY_SIGNERS */
            /* sponsored signer (CAP-33 sandwich, v14+): the sponsor's
             * reserve covers the new subentry */
            const uint8_t *sp_id = h->ledger_version >= 14
                ? active_sponsor_c(e, src_id) : NULL;
            if (sp_id != NULL) {
                int sc = sponsorship_error_c(rb, 5, -1,
                    establish_sponsorship_c(e, sp_id, &src, 1));
                if (sc)
                    return sc < 0 ? -1 : 0;
                src.num_sub += 1;
            } else if (!add_num_entries_c(h, &src, 1)) {
                return res_inner(rb, 5, -1) < 0 ? -1 : 0;  /* LOW_RESERVE */
            }
            /* sorted insert position by signer-key XDR */
            int pos = src.n_signers;
            for (int i = 0; i < src.n_signers; i++) {
                uint8_t kx[104];
                int klen = signer_key_xdr(&src.signers[i], kx);
                if (bcmp_py(kx, klen, new_kx, new_klen) > 0) {
                    pos = i;
                    break;
                }
            }
            for (int i = src.n_signers; i > pos; i--)
                src.signers[i] = src.signers[i - 1];
            src.signers[pos] = signer;
            src.n_signers++;
            /* record_signer_insert: a sponsored insert materializes the
             * v2 ext; an unsponsored one records only when v2 exists */
            if (sp_id != NULL || src.ext_level >= 2) {
                acc_ensure_v2(&src);
                while (src.n_ssids < src.n_signers) {  /* pad to new count */
                    src.ssids[src.n_ssids].present = 0;
                    src.n_ssids++;
                }
                for (int i = src.n_ssids - 1; i > pos; i--)
                    src.ssids[i] = src.ssids[i - 1];
                src.ssids[pos].present = sp_id != NULL;
                if (sp_id != NULL)
                    memcpy(src.ssids[pos].id, sp_id, 32);
            }
        }
    }
    src.last_modified = h->ledger_seq;
    if (eng_put_account(e, e->cur, &src) < 0)
        return -1;
    return res_inner(rb, 5, 0) < 0 ? -1 : 1;
}

/* ---- transaction-level apply (mirror transactions/frame.py) ----------- */

#define TXC_SUCCESS 0
#define TXC_FAILED (-1)
#define TXC_TOO_EARLY (-2)
#define TXC_TOO_LATE (-3)
#define TXC_MISSING_OPERATION (-4)
#define TXC_BAD_SEQ (-5)
#define TXC_BAD_AUTH (-6)
#define TXC_INSUFFICIENT_BALANCE (-7)
#define TXC_NO_ACCOUNT (-8)
#define TXC_INSUFFICIENT_FEE (-9)
#define TXC_BAD_AUTH_EXTRA (-10)
#define TXC_NOT_SUPPORTED (-12)

static int64_t
fee_charged_c(const CTx *tx, const CHeader *h)
{
    if (tx->is_feebump) {
        /* numOperations = inner ops + 1 (the bump itself) */
        int64_t min_fee = ((int64_t)tx->inner->n_ops + 1) * h->base_fee;
        return tx->fee64 < min_fee ? tx->fee64 : min_fee;
    }
    int64_t min_fee = (int64_t)tx->n_ops * h->base_fee;
    return (int64_t)tx->fee < min_fee ? (int64_t)tx->fee : min_fee;
}

/* mirror TransactionFrame._common_valid with check_seq=False; returns 0
 * (valid) or a TXC code */
static int
common_valid_c(Engine *e, const CTx *tx, uint64_t close_time,
               CAccount *src_out, int *src_found)
{
    const CHeader *h = &e->header;
    *src_found = 0;
    if (tx->n_ops == 0)
        return TXC_MISSING_OPERATION;
    if (tx->n_ops > MAX_OPS)
        return -16;                              /* txMALFORMED */
    if (tx->cond_type == 2 && h->ledger_version < 19)
        return TXC_NOT_SUPPORTED;
    if (tx->has_muxed && h->ledger_version < 13)
        return TXC_NOT_SUPPORTED;
    if (tx->has_time_bounds) {
        if (tx->min_time && close_time < tx->min_time)
            return TXC_TOO_EARLY;
        if (tx->max_time && close_time > tx->max_time)
            return TXC_TOO_LATE;
    }
    if ((int64_t)tx->fee < (int64_t)tx->n_ops * h->base_fee)
        return TXC_INSUFFICIENT_FEE;
    if (tx->seq_num < 0)
        return TXC_BAD_SEQ;
    int got = eng_get_account(e, tx->source, src_out);
    if (got < 0)
        return -128;                             /* engine error marker */
    if (!got)
        return TXC_NO_ACCOUNT;
    *src_found = 1;
    if (src_out->balance < fee_charged_c(tx, h))
        return TXC_INSUFFICIENT_BALANCE;
    return 0;
}

/* fee+seq phase (mirror process_fee_seq_num); writes to ledger_delta */
static int
fee_phase_c(Engine *e, CTx *tx)
{
    CHeader *h = &e->header;
    if (tx->is_feebump) {
        /* fee from the fee source; seq consumed on the INNER source with
         * no chain check (mirror FeeBumpTransactionFrame.
         * process_fee_seq_num; _bad_seq is never set) */
        CAccount fa;
        int got = eng_get_account(e, tx->source, &fa);
        if (got < 0)
            return -1;
        if (!got)
            return 0;
        int64_t fc = fee_charged_c(tx, h);
        int64_t avail = fa.balance > 0 ? fa.balance : 0;
        int64_t fee = fc < avail ? fc : avail;
        fa.balance -= fee;
        h->fee_pool += fee;
        fa.last_modified = h->ledger_seq;
        if (eng_put_account(e, &e->ledger_delta, &fa) < 0)
            return -1;
        CAccount ia;
        got = eng_get_account(e, tx->inner->source, &ia);
        if (got < 0)
            return -1;
        if (got) {
            ia.seq_num = tx->inner->seq_num;
            ia.last_modified = h->ledger_seq;
            if (eng_put_account(e, &e->ledger_delta, &ia) < 0)
                return -1;
        }
        return 0;
    }
    CAccount acc;
    int got = eng_get_account(e, tx->source, &acc);
    if (got < 0)
        return -1;
    if (!got) {
        tx->bad_seq = 1;
        return 0;
    }
    int64_t fc = fee_charged_c(tx, h);
    int64_t avail = acc.balance > 0 ? acc.balance : 0;
    int64_t fee = fc < avail ? fc : avail;
    acc.balance -= fee;
    if (acc.seq_num + 1 == tx->seq_num) {
        acc.seq_num = tx->seq_num;
        tx->bad_seq = 0;
    } else {
        tx->bad_seq = 1;
    }
    h->fee_pool += fee;
    acc.last_modified = h->ledger_seq;
    return eng_put_account(e, &e->ledger_delta, &acc);
}

/* write a void-arm TransactionResult (feeCharged + code + ext) */
static int
tx_result_void(Buf *b, int64_t fee, int32_t code)
{
    return buf_i64(b, fee) < 0 || buf_i32(b, code) < 0 ||
           buf_i32(b, 0) < 0 ? -1 : 0;
}

/* write a results-arm TransactionResult from collected op results */
static int
tx_result_ops(Buf *b, int64_t fee, int32_t code, const Buf *ops, int n_ops)
{
    if (buf_i64(b, fee) < 0 || buf_i32(b, code) < 0 ||
        buf_u32(b, (uint32_t)n_ops) < 0 ||
        buf_put(b, ops->p, ops->len) < 0 ||
        buf_i32(b, 0) < 0)
        return -1;
    return 0;
}

/* one-time preauth signer removal (mirror _remove_used_one_time_signers,
 * incl. sponsored-signer release) */
static int
remove_one_time_signers_c(Engine *e, CTx *tx)
{
    CHeader *h = &e->header;
    /* collect distinct source account ids: tx source + op sources */
    uint8_t ids[1 + MAX_OPS][32];
    int n_ids = 0;
    memcpy(ids[n_ids++], tx->source, 32);
    for (int i = 0; i < tx->n_ops; i++) {
        if (!tx->ops[i].has_source)
            continue;
        int dup = 0;
        for (int j = 0; j < n_ids; j++)
            if (memcmp(ids[j], tx->ops[i].source, 32) == 0) { dup = 1; break; }
        if (!dup)
            memcpy(ids[n_ids++], tx->ops[i].source, 32);
    }
    for (int j = 0; j < n_ids; j++) {
        CAccount acc;
        int got = eng_get_account(e, ids[j], &acc);
        if (got < 0)
            return -1;
        if (!got)
            continue;
        int changed = 0;
        int i = 0;
        while (i < acc.n_signers) {
            CSigner *s = &acc.signers[i];
            if (s->key_type == 1 &&
                memcmp(s->key, tx->content_hash, 32) == 0) {
                int sponsored = acc.ext_level >= 2 && i < acc.n_ssids &&
                                acc.ssids[i].present;
                uint8_t sponsor[32];
                if (sponsored)
                    memcpy(sponsor, acc.ssids[i].id, 32);
                for (int k = i; k + 1 < acc.n_signers; k++)
                    acc.signers[k] = acc.signers[k + 1];
                acc.n_signers--;
                if (acc.ext_level >= 2 && i < acc.n_ssids) {
                    for (int k = i; k + 1 < acc.n_ssids; k++)
                        acc.ssids[k] = acc.ssids[k + 1];
                    acc.n_ssids--;
                }
                if (sponsored) {
                    CAccount sp;
                    int g = eng_get_account(e, sponsor, &sp);
                    if (g < 0)
                        return -1;
                    if (g) {
                        if (sp.num_sponsoring < 1)
                            return -1;
                        sp.num_sponsoring -= 1;
                        sp.last_modified = h->ledger_seq;
                        if (eng_put_account(e, e->cur, &sp) < 0)
                            return -1;
                    }
                    if (acc.num_sponsored < 1)
                        return -1;
                    acc.num_sponsored -= 1;
                }
                acc.num_sub -= 1;
                changed = 1;
            } else {
                i++;
            }
        }
        if (changed) {
            if (eng_put_account(e, e->cur, &acc) < 0)
                return -1;
        }
    }
    return 0;
}

/* round-5 widened op set (defined below the checkpoint machinery) */
static int op_payment_credit(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_change_trust(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_manage_data(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_bump_sequence(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_account_merge(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_allow_trust(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_set_tl_flags(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_clawback(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_manage_offer(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_create_cb(Engine *, CTx *, COp *, int, const uint8_t *, Buf *);
static int op_claim_cb(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_clawback_cb(Engine *, CTx *, COp *, const uint8_t *, Buf *);
/* round-12 full-coverage op set: path payments, sponsorship, pools */
static int op_path_payment(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_begin_sponsoring(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_end_sponsoring(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_revoke_sponsorship(Engine *, CTx *, COp *, const uint8_t *,
                                 Buf *);
static int op_pool_deposit(Engine *, CTx *, COp *, const uint8_t *, Buf *);
static int op_pool_withdraw(Engine *, CTx *, COp *, const uint8_t *, Buf *);

/* apply one NON-fee-bump tx; appends its TransactionResult XDR to
 * `out`.  Mirrors TransactionFrame.apply: all-or-nothing via tx_delta. */
static int
apply_tx_core(Engine *e, CTx *tx, uint64_t close_time, Buf *out)
{
    CHeader *h = &e->header;
    int64_t fee = fee_charged_c(tx, h);
    e->txs_applied++;

    if (tx->bad_seq)
        return tx_result_void(out, fee, TXC_BAD_SEQ);

    map_clear(&e->tx_delta);
    map_clear(&e->op_delta);
    map_clear(&e->hop_delta);
    e->op_active = e->hop_active = 0;
    e->cur = &e->tx_delta;
    e->n_sandwich = 0;          /* fresh Begin/End sandwich state per apply */
    /* header.idPool is bumped by offer creation inside ops; a failed tx
     * rolls it back along with the entry delta (the oracle's inner
     * LedgerTxn holds the header mutation until commit) */
    uint64_t saved_id_pool = h->id_pool;

    CAccount src;
    int src_found;
    int code = common_valid_c(e, tx, close_time, &src, &src_found);
    if (code == -128)
        return -1;
    if (code != 0 && code != TXC_BAD_SEQ) {
        eng_rollback_tx(e);
        return tx_result_void(out, fee, code);
    }

    /* checker over the tx's signatures */
    CChecker ck;
    ck.n = tx->n_sigs;
    memcpy(ck.sigs, tx->sigs, sizeof(CDecSig) * tx->n_sigs);
    ck.content_hash = tx->content_hash;
    ck.vc = &e->vcache;

    /* process_signatures: tx source at LOW threshold */
    if (!src_found || !check_account_sig(&ck, &src, 1)) {
        eng_rollback_tx(e);
        return tx_result_void(out, fee, TXC_BAD_AUTH);
    }

    Buf ops_buf = {0};
    int ok = 1;
    int rc = 0;
    for (int i = 0; i < tx->n_ops; i++) {
        COp *op = &tx->ops[i];
        const uint8_t *op_src = op->has_source ? op->source : tx->source;
        /* op.check_valid: version gate, then signature check at the op's
         * threshold, then static checks + apply fused in the op
         * functions */
        /* version gates run FIRST (mirror OperationFrame.check_valid:
         * MIN_PROTOCOL_VERSION precedes the signature check) —
         * BumpSequence v10+, path strict-send v12+, sponsorship trio +
         * claimable balances v14+, Clawback/SetTrustLineFlags v17+,
         * liquidity pools v18+ */
        if ((op->op_type == 11 && h->ledger_version < 10) ||
            (op->op_type == 12 && h->ledger_version < 11) ||
            (op->op_type == 13 && h->ledger_version < 12) ||
            ((op->op_type == 14 || op->op_type == 15 ||
              op->op_type == 16 || op->op_type == 17 ||
              op->op_type == 18) && h->ledger_version < 14) ||
            ((op->op_type == 19 || op->op_type == 20 ||
              op->op_type == 21) && h->ledger_version < 17) ||
            ((op->op_type == 22 || op->op_type == 23) &&
             h->ledger_version < 18)) {
            if (res_outer(&ops_buf, -3) < 0) { rc = -1; goto done; }
            ok = 0;
            continue;
        }
        CAccount op_acc;
        int got = eng_get_account(e, op_src, &op_acc);
        if (got < 0) { rc = -1; goto done; }
        if (!got) {
            if (res_outer(&ops_buf, -2) < 0) { rc = -1; goto done; }
            ok = 0;
            continue;
        }
        /* thresholds: SetOptions/AccountMerge HIGH, BumpSequence LOW,
         * everything else MED (mirror the op frames' threshold_level) */
        int threshold_level =
            (op->op_type == 5 || op->op_type == 8) ? 3 :
            (op->op_type == 11 || op->op_type == 7 ||
             op->op_type == 21) ? 1 : 2;
        if (!check_account_sig(&ck, &op_acc, threshold_level)) {
            if (res_outer(&ops_buf, -1) < 0) { rc = -1; goto done; }
            ok = 0;
            continue;
        }
        /* each op applies in its OWN overlay, rolled back on op failure
         * (mirror frame.apply's per-op LedgerTxn) — a mutate-then-fail
         * path (RevokeSponsorship transfer, sponsored CreateAccount
         * UNDERFUNDED) must leave no mutations for later ops to see */
        map_clear(&e->op_delta);
        e->op_active = 1;
        e->cur = &e->op_delta;
        uint64_t op_saved_id_pool = h->id_pool;
        int r;
        switch (op->op_type) {
        case 0: r = op_create_account(e, tx, op, op_src, &ops_buf); break;
        case 1: {
            /* dispatch on asset arm (native vs credit) */
            Rd ar;
            rd_init(&ar, op->body, op->body_len);
            uint32_t mt = rd_u32(&ar);
            if (mt == 0x100) rd_skip(&ar, 8);
            rd_skip(&ar, 32);
            uint32_t at = rd_u32(&ar);
            r = at == 0 ? op_payment(e, tx, op, op_src, &ops_buf)
                        : op_payment_credit(e, tx, op, op_src, &ops_buf);
            break;
        }
        case 2: case 13:
            r = op_path_payment(e, tx, op, op_src, &ops_buf);
            break;
        case 3: case 4: case 12:
            r = op_manage_offer(e, tx, op, op_src, &ops_buf);
            break;
        case 5: r = op_set_options(e, tx, op, op_src, &ops_buf); break;
        case 6: r = op_change_trust(e, tx, op, op_src, &ops_buf); break;
        case 7: r = op_allow_trust(e, tx, op, op_src, &ops_buf); break;
        case 8: r = op_account_merge(e, tx, op, op_src, &ops_buf); break;
        case 9:
            /* Inflation: NOT_TIME always (protocol >= 12 semantics) */
            r = res_inner(&ops_buf, 9, -1) < 0 ? -1 : 0;
            break;
        case 10: r = op_manage_data(e, tx, op, op_src, &ops_buf); break;
        case 11: r = op_bump_sequence(e, tx, op, op_src, &ops_buf); break;
        case 14: r = op_create_cb(e, tx, op, i, op_src, &ops_buf); break;
        case 15: r = op_claim_cb(e, tx, op, op_src, &ops_buf); break;
        case 16: r = op_begin_sponsoring(e, tx, op, op_src, &ops_buf); break;
        case 17: r = op_end_sponsoring(e, tx, op, op_src, &ops_buf); break;
        case 18: r = op_revoke_sponsorship(e, tx, op, op_src, &ops_buf); break;
        case 19: r = op_clawback(e, tx, op, op_src, &ops_buf); break;
        case 20: r = op_clawback_cb(e, tx, op, op_src, &ops_buf); break;
        case 21: r = op_set_tl_flags(e, tx, op, op_src, &ops_buf); break;
        case 22: r = op_pool_deposit(e, tx, op, op_src, &ops_buf); break;
        case 23: r = op_pool_withdraw(e, tx, op, op_src, &ops_buf); break;
        default: r = -1; break;
        }
        e->hop_active = 0;
        map_clear(&e->hop_delta);
        if (r > 0) {
            if (eng_fold_overlay(&e->op_delta, &e->tx_delta) < 0)
                r = -1;
        } else {
            map_clear(&e->op_delta);
            h->id_pool = op_saved_id_pool;
        }
        e->op_active = 0;
        e->cur = &e->tx_delta;
        if (r < 0) { rc = -1; goto done; }
        if (r == 0)
            ok = 0;
    }
    if (ok && e->n_sandwich) {
        /* a BeginSponsoringFutureReserves left unclosed at tx end fails
         * the whole tx (mirror frame.apply: txBAD_SPONSORSHIP) */
        eng_rollback_tx(e);
        h->id_pool = saved_id_pool;
        PyMem_Free(ops_buf.p);
        return tx_result_void(out, fee, -14);
    }
    if (ok && tx->n_extra_signers) {
        /* _check_extra_signers: each extra signer as a 1-of-1 set */
        for (int i = 0; i < tx->n_extra_signers; i++) {
            CCheckSigner s = { tx->extra_signers[i].key_type,
                               tx->extra_signers[i].key, 1 };
            if (!checker_check(&ck, &s, 1, 1)) {
                eng_rollback_tx(e);
                h->id_pool = saved_id_pool;
                PyMem_Free(ops_buf.p);
                return tx_result_void(out, fee, TXC_BAD_AUTH_EXTRA);
            }
        }
    }
    if (ok && !checker_all_used(&ck)) {
        eng_rollback_tx(e);
        h->id_pool = saved_id_pool;
        PyMem_Free(ops_buf.p);
        return tx_result_void(out, fee, TXC_BAD_AUTH_EXTRA);
    }
    if (!ok) {
        eng_rollback_tx(e);
        h->id_pool = saved_id_pool;
        rc = tx_result_ops(out, fee, TXC_FAILED, &ops_buf, tx->n_ops);
        PyMem_Free(ops_buf.p);
        return rc;
    }
    if (remove_one_time_signers_c(e, tx) < 0) { rc = -1; goto done; }
    if (eng_commit_tx(e) < 0) { rc = -1; goto done; }
    rc = tx_result_ops(out, fee, TXC_SUCCESS, &ops_buf, tx->n_ops);
    PyMem_Free(ops_buf.p);
    return rc;
done:
    eng_rollback_tx(e);
    PyMem_Free(ops_buf.p);
    return rc;
}

/* fee-bump dispatch (mirror FeeBumpTransactionFrame.apply): the outer
 * envelope authenticates the fee source at LOW; the inner v1 frame then
 * applies with its own signatures.  InnerTransactionResult has the same
 * byte layout as TransactionResult, so the inner core's output embeds
 * verbatim into the txFEE_BUMP_INNER_* pair. */
static int
apply_tx_c(Engine *e, CTx *tx, uint64_t close_time, Buf *out)
{
    if (!tx->is_feebump)
        return apply_tx_core(e, tx, close_time, out);
    CHeader *h = &e->header;
    int64_t fee = fee_charged_c(tx, h);
    if (h->ledger_version < 13)
        return tx_result_void(out, fee, TXC_NOT_SUPPORTED);
    CChecker ck;
    ck.n = tx->n_sigs;
    memcpy(ck.sigs, tx->sigs, sizeof(CDecSig) * tx->n_sigs);
    ck.content_hash = tx->content_hash;
    ck.vc = &e->vcache;
    CAccount fs;
    int got = eng_get_account(e, tx->source, &fs);
    if (got < 0)
        return -1;
    int auth_ok = got == 1 && check_account_sig(&ck, &fs, 1) &&
                  checker_all_used(&ck);
    if (!auth_ok) {
        /* FEE_BUMP_INNER_FAILED wrapping feeCharged=0 txBAD_AUTH */
        if (buf_i64(out, fee) < 0 || buf_i32(out, -13) < 0 ||
            buf_put(out, tx->inner->content_hash, 32) < 0 ||
            buf_i64(out, 0) < 0 || buf_i32(out, TXC_BAD_AUTH) < 0 ||
            buf_i32(out, 0) < 0 ||
            buf_i32(out, 0) < 0)
            return -1;
        return 0;
    }
    Buf ib = {0};
    if (apply_tx_core(e, tx->inner, close_time, &ib) < 0) {
        PyMem_Free(ib.p);
        return -1;
    }
    /* inner result code sits after its i64 feeCharged */
    int32_t icode = (int32_t)(((uint32_t)ib.p[8] << 24) |
                              ((uint32_t)ib.p[9] << 16) |
                              ((uint32_t)ib.p[10] << 8) | ib.p[11]);
    int32_t ocode = icode == 0 ? 1 : -13;
    int rc = 0;
    if (buf_i64(out, fee) < 0 || buf_i32(out, ocode) < 0 ||
        buf_put(out, tx->inner->content_hash, 32) < 0 ||
        buf_put(out, ib.p, ib.len) < 0 ||
        buf_i32(out, 0) < 0)
        rc = -1;
    PyMem_Free(ib.p);
    return rc;
}

/* ---- apply order (mirror LedgerManager.apply_order) ------------------- */

static int
apply_order_c(CTx *txs, int n, int *order_out)
{
    /* per-source queues in seq order; repeatedly pick the head with the
     * smallest content hash.  n <= MAX_TX_PER_LEDGER; simple O(n^2). */
    int *next_in_src = PyMem_Malloc(n * sizeof(int));
    int *head = PyMem_Malloc(n * sizeof(int));
    int *src_of = PyMem_Malloc(n * sizeof(int));
    if (!next_in_src || !head || !src_of) {
        PyMem_Free(next_in_src);
        PyMem_Free(head);
        PyMem_Free(src_of);
        PyErr_NoMemory();
        return -1;
    }
    int n_src = 0;
    /* build per-source chains sorted by seq (insertion into linked list) */
    for (int i = 0; i < n; i++)
        next_in_src[i] = -1;
    for (int i = 0; i < n; i++) {
        int s;
        for (s = 0; s < n_src; s++)
            if (memcmp(txs[head[s]].source, txs[i].source, 32) == 0)
                break;
        if (s == n_src) {
            head[n_src] = i;
            src_of[i] = n_src;
            n_src++;
            continue;
        }
        /* insert i into chain s by seq_num */
        src_of[i] = s;
        int prev = -1, cur = head[s];
        while (cur != -1 && txs[cur].seq_num <= txs[i].seq_num) {
            prev = cur;
            cur = next_in_src[cur];
        }
        if (prev == -1) {
            next_in_src[i] = head[s];
            head[s] = i;
        } else {
            next_in_src[i] = next_in_src[prev];
            next_in_src[prev] = i;
        }
    }
    int emitted = 0;
    while (emitted < n) {
        int best = -1;
        for (int s = 0; s < n_src; s++) {
            if (head[s] == -1)
                continue;
            if (best == -1 ||
                memcmp(txs[head[s]].content_hash,
                       txs[head[best]].content_hash, 32) < 0)
                best = s;
        }
        order_out[emitted++] = head[best];
        head[best] = next_in_src[head[best]];
    }
    PyMem_Free(next_in_src);
    PyMem_Free(head);
    PyMem_Free(src_of);
    return 0;
}

/* ---- ledger close (mirror LedgerManager.close_ledger) ----------------- */

#define MAX_TX_PER_LEDGER 2000

static int
raise_capply(const char *fmt, uint32_t seq)
{
    PyErr_Format(CapplyError, fmt, (unsigned long)seq);
    return -1;
}

/* fee-bump inner frames are heap-allocated per parse; the tx buffers are
 * reused across records/ledgers, so allocators zero the slots once and
 * every re-parse frees the previous generation's inners first. */
static void
zero_tx_inners(CTx *txs)
{
    for (int i = 0; i < MAX_TX_PER_LEDGER; i++)
        txs[i].inner = NULL;
}

static void
free_tx_inners(CTx *txs)
{
    for (int i = 0; i < MAX_TX_PER_LEDGER; i++)
        if (txs[i].inner) {
            PyMem_Free(txs[i].inner);
            txs[i].inner = NULL;
        }
}

/* parse one TransactionHistoryEntry; fills txs/n_txs and records the
 * TransactionSet slice for hashing.  Returns 0 ok / 1 unsupported / -1
 * malformed. */
static int
parse_tx_record(const uint8_t *rec, int len, const uint8_t nid[32],
                CTx *txs, int *n_txs, const uint8_t **set_p, int *set_len,
                uint32_t *rec_seq)
{
    free_tx_inners(txs);
    Rd r;
    rd_init(&r, rec, len);
    *rec_seq = rd_u32(&r);
    int set_start = r.off;
    rd_skip(&r, 32);                     /* previousLedgerHash */
    uint32_t n = rd_u32(&r);
    if (r.err || n > MAX_TX_PER_LEDGER)
        return -1;
    *n_txs = (int)n;
    for (uint32_t i = 0; i < n; i++) {
        int rc = parse_envelope_rd(&r, nid, &txs[i]);
        if (rc)
            return rc;
    }
    int set_end = r.off;
    int32_t ext = rd_i32(&r);
    if (r.err)
        return -1;
    if (ext == 1)
        return 1;                        /* generalized tx set: fall back */
    if (ext != 0 || r.off != r.len)
        return -1;
    *set_p = rec + set_start;
    *set_len = set_end - set_start;
    return 0;
}

/* classify the ledger delta into a fresh bucket + fold it into the store */
static CBucket *
build_fresh_and_fold(Engine *e, uint32_t seq)
{
    Map *d = &e->ledger_delta;
    CBucket *fresh = cbucket_new(d->n);
    if (!fresh)
        return NULL;
    fresh->protocol = e->header.ledger_version;
    for (int i = 0; i < d->cap; i++) {
        MapSlot *s = &d->slots[i];
        if (s->state != 1)
            continue;
        int present;
        RB *pre = map_get(&e->store, s->key->bytes, s->key->len, &present);
        (void)pre;
        if (s->val == NULL) {
            if (!present)
                continue;                /* deleted never-existing: no-op */
            /* DEADENTRY: tag + key; remove from store */
            RB *rec = rb_new(NULL, 4 + s->key->len);
            if (!rec) { PyErr_NoMemory(); goto fail; }
            memset(rec->bytes, 0, 3);
            rec->bytes[3] = BE_DEAD;
            memcpy(rec->bytes + 4, s->key->bytes, s->key->len);
            fresh->keys[fresh->n] = rb_ref(s->key);
            fresh->recs[fresh->n] = rec;
            fresh->n++;
            map_del(&e->store, s->key->bytes, s->key->len);
        } else {
            /* stamp lastModifiedLedgerSeq = seq on the entry */
            RB *entry = rb_new(s->val->bytes, s->val->len);
            if (!entry) { PyErr_NoMemory(); goto fail; }
            entry->bytes[0] = seq >> 24;
            entry->bytes[1] = seq >> 16;
            entry->bytes[2] = seq >> 8;
            entry->bytes[3] = seq;
            RB *rec = rb_new(NULL, 4 + entry->len);
            if (!rec) { rb_unref(entry); PyErr_NoMemory(); goto fail; }
            memset(rec->bytes, 0, 3);
            rec->bytes[3] = present ? BE_LIVE : BE_INIT;
            memcpy(rec->bytes + 4, entry->bytes, entry->len);
            fresh->keys[fresh->n] = rb_ref(s->key);
            fresh->recs[fresh->n] = rec;
            fresh->n++;
            if (map_put(&e->store, rb_ref(s->key), entry) < 0)
                goto fail;
        }
    }
    /* sort fresh by key (Bucket.fresh sorts by sort key) */
    for (int i = 1; i < fresh->n; i++) {
        RB *k = fresh->keys[i], *rec = fresh->recs[i];
        int j = i - 1;
        while (j >= 0 && bcmp_py(fresh->keys[j]->bytes, fresh->keys[j]->len,
                                 k->bytes, k->len) > 0) {
            fresh->keys[j + 1] = fresh->keys[j];
            fresh->recs[j + 1] = fresh->recs[j];
            j--;
        }
        fresh->keys[j + 1] = k;
        fresh->recs[j + 1] = rec;
    }
    map_clear(d);
    return fresh;
fail:
    cbucket_unref(fresh);
    return NULL;
}

/* deep-copy a header (snapshot for live-close rollback) */
static int
cheader_copy(const CHeader *src, CHeader *dst)
{
    *dst = *src;
    dst->scp_value = NULL;
    dst->ext = NULL;
    if (src->scp_value) {
        dst->scp_value = PyMem_Malloc(src->scp_len);
        if (!dst->scp_value) { PyErr_NoMemory(); return -1; }
        memcpy(dst->scp_value, src->scp_value, src->scp_len);
        for (int i = 0; i < src->n_upgrades; i++)
            dst->upgrades[i].p = dst->scp_value +
                (src->upgrades[i].p - src->scp_value);
    }
    if (src->ext) {
        dst->ext = PyMem_Malloc(src->ext_len);
        if (!dst->ext) {
            PyMem_Free(dst->scp_value);
            dst->scp_value = NULL;
            PyErr_NoMemory();
            return -1;
        }
        memcpy(dst->ext, src->ext, src->ext_len);
    }
    return 0;
}

/* shared apply core: fee phase + per-tx apply (in apply order) + voted
 * upgrades.  Appends the TransactionResultSet XDR (count + pairs) to
 * `results`.  Returns 0 / -1 (state may be partially mutated in the
 * delta maps only — callers roll back by clearing them + restoring the
 * header). */
static int
apply_tx_phase(Engine *e, CTx *txs, int n_txs, Buf *results)
{
    CHeader *h = &e->header;
    uint64_t close_time = h->close_time;
    int order[MAX_TX_PER_LEDGER];
    if (n_txs && apply_order_c(txs, n_txs, order) < 0)
        return -1;
    for (int i = 0; i < n_txs; i++)
        if (fee_phase_c(e, &txs[order[i]]) < 0)
            return -1;
    if (buf_u32(results, (uint32_t)n_txs) < 0)
        return -1;
    for (int i = 0; i < n_txs; i++) {
        CTx *tx = &txs[order[i]];
        if (buf_put(results, tx->content_hash, 32) < 0)
            return -1;
        if (apply_tx_c(e, tx, close_time, results) < 0)
            return -1;
    }
    sha256_of(results->p, results->len, h->tx_set_result_hash);
    apply_upgrades(h);
    return 0;
}

/* apply one ledger from its raw records.  Returns 0 / -1 (Python error
 * set). */
static int
close_one_ledger(Engine *e, const uint8_t *hdr_rec, int hdr_len,
                 const uint8_t *tx_rec, int tx_len, CTx *txs)
{
    uint32_t seq = e->header.ledger_seq + 1;

    /* header entry: hash + header + ext */
    Rd hr;
    rd_init(&hr, hdr_rec, hdr_len);
    const uint8_t *expected = rd_take(&hr, 32);
    CHeader hin;
    memset(&hin, 0, sizeof(hin));
    if (!expected || parse_header(&hr, &hin) < 0) {
        cheader_clear(&hin);
        return raise_capply("malformed header record at ledger %lu", seq);
    }
    if (rd_i32(&hr) != 0 || hr.err || hr.off != hr.len) {
        cheader_clear(&hin);
        return raise_capply("malformed header record at ledger %lu", seq);
    }
    if (hin.ledger_seq != seq) {
        cheader_clear(&hin);
        return raise_capply("header gap at ledger %lu", seq);
    }

    /* tx set + its hash check against the externalized value */
    int n_txs = 0;
    uint8_t set_hash[32];
    if (tx_rec) {
        const uint8_t *set_p;
        int set_len;
        uint32_t rec_seq;
        int rc = parse_tx_record(tx_rec, tx_len, e->network_id, txs,
                                 &n_txs, &set_p, &set_len, &rec_seq);
        if (rc) {
            cheader_clear(&hin);
            return raise_capply(rc > 0
                ? "unsupported tx at ledger %lu (native probe miss)"
                : "malformed tx record at ledger %lu", seq);
        }
        if (rec_seq != seq) {
            cheader_clear(&hin);
            return raise_capply("tx record seq mismatch at ledger %lu", seq);
        }
        sha256_of(set_p, set_len, set_hash);
    } else {
        Sha256 s;
        sha_init(&s);
        sha_update(&s, e->lcl_hash, 32);
        static const uint8_t zero4[4] = {0, 0, 0, 0};
        sha_update(&s, zero4, 4);
        sha_final(&s, set_hash);
    }
    if (memcmp(set_hash, hin.tx_set_hash, 32) != 0) {
        cheader_clear(&hin);
        return raise_capply("tx set hash mismatch at ledger %lu", seq);
    }

    /* advance the working header */
    CHeader *h = &e->header;
    h->ledger_seq = seq;
    memcpy(h->previous_hash, e->lcl_hash, 32);
    if (cheader_set_scp(h, hin.scp_value, hin.scp_len) < 0) {
        cheader_clear(&hin);
        return raise_capply("bad scpValue at ledger %lu", seq);
    }
    /* phases 1+2 in apply order, result hash, voted upgrades */
    Buf results = {0};
    if (apply_tx_phase(e, txs, n_txs, &results) < 0)
        goto fail;
    PyMem_Free(results.p);
    results.p = NULL;
    results.len = results.cap = 0;

    CBucket *fresh = build_fresh_and_fold(e, seq);
    if (!fresh)
        goto fail;
    if (cbl_add_batch(&e->bl, seq, h->ledger_version, fresh) < 0) {
        cbucket_unref(fresh);
        goto fail;
    }
    cbucket_unref(fresh);
    cbl_hash(&e->bl, h->bucket_list_hash);

    /* skip list (reference: updateSkipList) */
    static const uint32_t intervals[4] = {50, 5000, 50000, 500000};
    for (int i = 0; i < 4; i++)
        if (seq % intervals[i] == 0)
            memcpy(h->skip_list[i], h->previous_hash, 32);

    /* finalize: header hash must equal the archive's */
    Buf hb = {0};
    if (serialize_header(h, &hb) < 0) {
        PyMem_Free(hb.p);
        goto fail;
    }
    uint8_t got[32];
    sha256_of(hb.p, hb.len, got);
    PyMem_Free(hb.p);
    if (memcmp(got, expected, 32) != 0) {
        cheader_clear(&hin);
        return raise_capply(
            "ledger %lu hash mismatch (native apply diverged)", seq);
    }
    memcpy(e->lcl_hash, got, 32);
    e->ledgers_applied++;
    cheader_clear(&hin);
    return 0;
fail:
    PyMem_Free(results.p);
    cheader_clear(&hin);
    if (!PyErr_Occurred())
        raise_capply("apply failed at ledger %lu", seq);
    return -1;
}

/* ---- Python object glue ----------------------------------------------- */

static void
Engine_dealloc(Engine *self)
{
    PyMem_Free(self->harvest);
    map_free(&self->store);
    map_free(&self->ledger_delta);
    map_free(&self->tx_delta);
    map_free(&self->op_delta);
    map_free(&self->hop_delta);
    cbl_free(&self->bl);
    cheader_clear(&self->header);
    PyMem_Free(self->vcache.slots);
    Py_TYPE(self)->tp_free((PyObject *)self);
}

static PyObject *
Engine_new(PyTypeObject *type, PyObject *args, PyObject *kwds)
{
    (void)kwds;
    const uint8_t *nid;
    Py_ssize_t nid_len;
    if (!PyArg_ParseTuple(args, "y#", &nid, &nid_len))
        return NULL;
    if (nid_len != 32) {
        PyErr_SetString(PyExc_ValueError, "network id must be 32 bytes");
        return NULL;
    }
    Engine *self = (Engine *)type->tp_alloc(type, 0);
    if (!self)
        return NULL;
    memcpy(self->network_id, nid, 32);
    self->state_loaded = 0;
    memset(&self->header, 0, sizeof(self->header));
    self->vcache.slots = NULL;
    if (map_init(&self->store, 1024) < 0 ||
        map_init(&self->ledger_delta, 256) < 0 ||
        map_init(&self->tx_delta, 64) < 0 ||
        map_init(&self->op_delta, 64) < 0 ||
        map_init(&self->hop_delta, 64) < 0 ||
        cbl_init(&self->bl) < 0 ||
        vcache_init(&self->vcache) < 0) {
        Py_DECREF(self);
        return NULL;
    }
    self->cur = &self->tx_delta;
    self->op_active = self->hop_active = 0;
    self->n_sandwich = 0;
    self->poisoned = 0;
    return (PyObject *)self;
}

/* build one CBucket from (keys_list, recs_list, protocol) */
static CBucket *
bucket_from_py(PyObject *tup)
{
    PyObject *keys, *recs;
    unsigned int proto;
    if (!PyArg_ParseTuple(tup, "OOI", &keys, &recs, &proto))
        return NULL;
    Py_ssize_t n = PyList_Size(keys);
    if (n < 0 || PyList_Size(recs) != n) {
        PyErr_SetString(PyExc_ValueError, "bucket keys/recs mismatch");
        return NULL;
    }
    CBucket *b = cbucket_new((int)n);
    if (!b)
        return NULL;
    b->protocol = proto;
    for (Py_ssize_t i = 0; i < n; i++) {
        char *kp, *rp;
        Py_ssize_t kl, rl;
        if (PyBytes_AsStringAndSize(PyList_GetItem(keys, i), &kp, &kl) < 0 ||
            PyBytes_AsStringAndSize(PyList_GetItem(recs, i), &rp, &rl) < 0) {
            cbucket_unref(b);
            return NULL;
        }
        RB *k = rb_new((uint8_t *)kp, (int)kl);
        RB *r = rb_new((uint8_t *)rp, (int)rl);
        if (!k || !r) {
            rb_unref(k); rb_unref(r);
            cbucket_unref(b);
            PyErr_NoMemory();
            return NULL;
        }
        b->keys[b->n] = k;
        b->recs[b->n] = r;
        b->n++;
    }
    return b;
}

static PyObject *
Engine_import_state(Engine *self, PyObject *args)
{
    const uint8_t *hdr;
    Py_ssize_t hdr_len;
    PyObject *entries, *buckets, *nexts;
    const uint8_t *lcl;
    Py_ssize_t lcl_len;
    if (!PyArg_ParseTuple(args, "y#y#OOO", &hdr, &hdr_len, &lcl, &lcl_len,
                          &entries, &buckets, &nexts))
        return NULL;
    if (lcl_len != 32) {
        PyErr_SetString(PyExc_ValueError, "lcl hash must be 32 bytes");
        return NULL;
    }
    Rd r;
    rd_init(&r, hdr, (int)hdr_len);
    cheader_clear(&self->header);
    if (parse_header(&r, &self->header) < 0 || r.off != r.len) {
        PyErr_SetString(CapplyError, "malformed header");
        return NULL;
    }
    memcpy(self->lcl_hash, lcl, 32);
    map_clear(&self->store);
    map_clear(&self->ledger_delta);
    map_clear(&self->tx_delta);
    map_clear(&self->op_delta);
    map_clear(&self->hop_delta);
    self->cur = &self->tx_delta;
    self->op_active = self->hop_active = 0;
    self->poisoned = 0;
    PyObject *it = PyObject_GetIter(entries);
    if (!it)
        return NULL;
    PyObject *item;
    while ((item = PyIter_Next(it)) != NULL) {
        const uint8_t *kp, *vp;
        Py_ssize_t kl, vl;
        if (!PyArg_ParseTuple(item, "y#y#", &kp, &kl, &vp, &vl)) {
            Py_DECREF(item);
            Py_DECREF(it);
            return NULL;
        }
        RB *k = rb_new(kp, (int)kl);
        RB *v = rb_new(vp, (int)vl);
        Py_DECREF(item);
        if (!k || !v || map_put(&self->store, k, v) < 0) {
            rb_unref(k); rb_unref(v);
            Py_DECREF(it);
            return PyErr_NoMemory();
        }
    }
    Py_DECREF(it);
    if (PyErr_Occurred())
        return NULL;
    if (PyList_Size(buckets) != NUM_LEVELS * 2 ||
        PyList_Size(nexts) != NUM_LEVELS) {
        PyErr_SetString(PyExc_ValueError, "need 22 buckets / 11 nexts");
        return NULL;
    }
    for (int i = 0; i < NUM_LEVELS; i++) {
        CBucket *curr = bucket_from_py(PyList_GetItem(buckets, 2 * i));
        CBucket *snap = bucket_from_py(PyList_GetItem(buckets, 2 * i + 1));
        if (!curr || !snap) {
            cbucket_unref(curr);
            return NULL;
        }
        CLevel *lvl = &self->bl.levels[i];
        cbucket_unref(lvl->curr);
        cbucket_unref(lvl->snap);
        cbucket_unref(lvl->next_out);
        lvl->curr = curr;
        lvl->snap = snap;
        lvl->next_out = NULL;
        PyObject *nx = PyList_GetItem(nexts, i);
        if (nx != Py_None) {
            lvl->next_out = bucket_from_py(nx);
            if (!lvl->next_out)
                return NULL;
        }
    }
    self->state_loaded = 1;
    Py_RETURN_NONE;
}

static PyObject *
bucket_stream_py(CBucket *b)
{
    if (b->n == 0)
        return PyBytes_FromStringAndSize("", 0);
    Buf out = {0};
    uint8_t meta[12];
    meta[0] = meta[1] = meta[2] = meta[3] = 0xFF;
    meta[4] = b->protocol >> 24; meta[5] = b->protocol >> 16;
    meta[6] = b->protocol >> 8; meta[7] = b->protocol;
    memset(meta + 8, 0, 4);
    if (buf_put(&out, meta, 12) < 0) {
        PyMem_Free(out.p);
        return NULL;
    }
    for (int i = 0; i < b->n; i++)
        if (buf_put(&out, b->recs[i]->bytes, b->recs[i]->len) < 0) {
            PyMem_Free(out.p);
            return NULL;
        }
    PyObject *res = PyBytes_FromStringAndSize((char *)out.p, out.len);
    PyMem_Free(out.p);
    return res;
}

static PyObject *
Engine_export_state(Engine *self, PyObject *args)
{
    (void)args;
    if (self->poisoned) {
        /* a post-fold close failure left the store/header torn —
         * exporting it would hand the caller silently-diverged state */
        PyErr_SetString(CapplyError,
                        "engine poisoned by a failed close; state is "
                        "unrecoverable");
        return NULL;
    }
    Buf hb = {0};
    if (serialize_header(&self->header, &hb) < 0) {
        PyMem_Free(hb.p);
        return NULL;
    }
    PyObject *hdr = PyBytes_FromStringAndSize((char *)hb.p, hb.len);
    PyMem_Free(hb.p);
    if (!hdr)
        return NULL;
    PyObject *entries = PyList_New(0);
    PyObject *buckets = NULL, *nexts = NULL;
    for (int i = 0; i < self->store.cap; i++) {
        MapSlot *s = &self->store.slots[i];
        if (s->state != 1)
            continue;
        PyObject *pair = Py_BuildValue(
            "(y#y#)", s->key->bytes, (Py_ssize_t)s->key->len,
            s->val->bytes, (Py_ssize_t)s->val->len);
        if (!pair || PyList_Append(entries, pair) < 0) {
            Py_XDECREF(pair);
            goto fail;
        }
        Py_DECREF(pair);
    }
    buckets = PyList_New(0);
    nexts = PyList_New(0);
    if (!buckets || !nexts)
        goto fail;
    for (int i = 0; i < NUM_LEVELS; i++) {
        CLevel *lvl = &self->bl.levels[i];
        PyObject *c = bucket_stream_py(lvl->curr);
        PyObject *sn = bucket_stream_py(lvl->snap);
        if (!c || !sn || PyList_Append(buckets, c) < 0 ||
            PyList_Append(buckets, sn) < 0) {
            Py_XDECREF(c); Py_XDECREF(sn);
            goto fail;
        }
        Py_DECREF(c); Py_DECREF(sn);
        if (lvl->next_out) {
            PyObject *nx = bucket_stream_py(lvl->next_out);
            if (!nx || PyList_Append(nexts, nx) < 0) {
                Py_XDECREF(nx);
                goto fail;
            }
            Py_DECREF(nx);
        } else {
            if (PyList_Append(nexts, Py_None) < 0)
                goto fail;
        }
    }
    return Py_BuildValue("(Ny#NNN)", hdr, self->lcl_hash, (Py_ssize_t)32,
                         entries, buckets, nexts);
fail:
    Py_XDECREF(hdr);
    Py_XDECREF(entries);
    Py_XDECREF(buckets);
    Py_XDECREF(nexts);
    return NULL;
}

/* header + serialized buckets only — the checkpoint-boundary sync seam
 * of native live close.  export_state() additionally materializes a
 * Python pair per store entry; boundaries need none of that. */
static PyObject *
Engine_export_buckets(Engine *self, PyObject *args)
{
    (void)args;
    if (self->poisoned) {
        PyErr_SetString(CapplyError,
                        "engine poisoned by a failed close; state is "
                        "unrecoverable");
        return NULL;
    }
    Buf hb = {0};
    if (serialize_header(&self->header, &hb) < 0) {
        PyMem_Free(hb.p);
        return NULL;
    }
    PyObject *hdr = PyBytes_FromStringAndSize((char *)hb.p, hb.len);
    PyMem_Free(hb.p);
    if (!hdr)
        return NULL;
    PyObject *buckets = PyList_New(0);
    PyObject *nexts = PyList_New(0);
    if (!buckets || !nexts)
        goto fail;
    for (int i = 0; i < NUM_LEVELS; i++) {
        CLevel *lvl = &self->bl.levels[i];
        PyObject *c = bucket_stream_py(lvl->curr);
        PyObject *sn = bucket_stream_py(lvl->snap);
        if (!c || !sn || PyList_Append(buckets, c) < 0 ||
            PyList_Append(buckets, sn) < 0) {
            Py_XDECREF(c); Py_XDECREF(sn);
            goto fail;
        }
        Py_DECREF(c); Py_DECREF(sn);
        if (lvl->next_out) {
            PyObject *nx = bucket_stream_py(lvl->next_out);
            if (!nx || PyList_Append(nexts, nx) < 0) {
                Py_XDECREF(nx);
                goto fail;
            }
            Py_DECREF(nx);
        } else if (PyList_Append(nexts, Py_None) < 0) {
            goto fail;
        }
    }
    return Py_BuildValue("(NNN)", hdr, buckets, nexts);
fail:
    Py_XDECREF(hdr);
    Py_XDECREF(buckets);
    Py_XDECREF(nexts);
    return NULL;
}

static PyObject *
Engine_probe(Engine *self, PyObject *args)
{
    PyObject *tx_recs;
    if (!PyArg_ParseTuple(args, "O", &tx_recs))
        return NULL;
    CTx *txs = PyMem_Malloc(sizeof(CTx) * MAX_TX_PER_LEDGER);
    if (!txs)
        return PyErr_NoMemory();
    zero_tx_inners(txs);
    Py_ssize_t n = PyList_Size(tx_recs);
    int ok = 1;
    for (Py_ssize_t i = 0; ok && i < n; i++) {
        PyObject *item = PyList_GetItem(tx_recs, i);
        if (item == Py_None)
            continue;
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0) {
            free_tx_inners(txs);
            PyMem_Free(txs);
            return NULL;
        }
        int n_txs, set_len;
        const uint8_t *set_p;
        uint32_t rec_seq;
        if (parse_tx_record((uint8_t *)p, (int)len, self->network_id,
                            txs, &n_txs, &set_p, &set_len, &rec_seq) != 0)
            ok = 0;
    }
    free_tx_inners(txs);
    PyMem_Free(txs);
    return PyBool_FromLong(ok);
}

static PyObject *
Engine_apply_checkpoint(Engine *self, PyObject *args)
{
    PyObject *hdr_recs, *tx_recs;
    unsigned long max_seq;
    if (!PyArg_ParseTuple(args, "OOk", &hdr_recs, &tx_recs, &max_seq))
        return NULL;
    if (!self->state_loaded) {
        PyErr_SetString(CapplyError, "no state imported");
        return NULL;
    }
    Py_ssize_t n = PyList_Size(hdr_recs);
    if (PyList_Size(tx_recs) != n) {
        PyErr_SetString(PyExc_ValueError, "header/tx record count mismatch");
        return NULL;
    }
    CTx *txs = PyMem_Malloc(sizeof(CTx) * MAX_TX_PER_LEDGER);
    if (!txs)
        return PyErr_NoMemory();
    zero_tx_inners(txs);
    long applied = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        /* peek the header seq (first 32 bytes are the entry hash) */
        char *hp;
        Py_ssize_t hl;
        if (PyBytes_AsStringAndSize(PyList_GetItem(hdr_recs, i),
                                    &hp, &hl) < 0) {
            free_tx_inners(txs);
            PyMem_Free(txs);
            return NULL;
        }
        if (hl < 36 + 32) {
            free_tx_inners(txs);
            PyMem_Free(txs);
            PyErr_SetString(CapplyError, "truncated header record");
            return NULL;
        }
        /* header.ledgerSeq sits after hash(32) + ledgerVersion(4) +
         * previousLedgerHash(32) + scpValue(variable) — cheaper to just
         * compare against the engine's next seq after a skip check via
         * the parse inside close_one_ledger; only skip/stop decisions
         * need the seq, which IS parsed there.  To skip already-applied
         * ledgers (ApplyCheckpointWork resume semantics) we parse the
         * minimal prefix here. */
        Rd r;
        rd_init(&r, (uint8_t *)hp, (int)hl);
        rd_skip(&r, 32);
        CHeader peek;
        memset(&peek, 0, sizeof(peek));
        if (parse_header(&r, &peek) < 0) {
            cheader_clear(&peek);
            free_tx_inners(txs);
            PyMem_Free(txs);
            PyErr_SetString(CapplyError, "malformed header record");
            return NULL;
        }
        uint32_t seq = peek.ledger_seq;
        cheader_clear(&peek);
        if (seq <= self->header.ledger_seq)
            continue;
        if (seq > max_seq)
            break;
        PyObject *txo = PyList_GetItem(tx_recs, i);
        char *tp = NULL;
        Py_ssize_t tl = 0;
        if (txo != Py_None &&
            PyBytes_AsStringAndSize(txo, &tp, &tl) < 0) {
            free_tx_inners(txs);
            PyMem_Free(txs);
            return NULL;
        }
        if (close_one_ledger(self, (uint8_t *)hp, (int)hl,
                             (uint8_t *)tp, (int)tl, txs) < 0) {
            free_tx_inners(txs);
            PyMem_Free(txs);
            return NULL;
        }
        applied++;
    }
    free_tx_inners(txs);
    PyMem_Free(txs);
    return PyLong_FromLong(applied);
}

static PyObject *
Engine_lcl(Engine *self, PyObject *args)
{
    (void)args;
    return Py_BuildValue("(ky#)", (unsigned long)self->header.ledger_seq,
                         self->lcl_hash, (Py_ssize_t)32);
}

/* Live ledger close (round 12): apply ONE ledger from the externalized
 * StellarValue + the tx record (a TransactionHistoryEntry, None for an
 * empty set).  Unlike apply_checkpoint there is no archive header to
 * verify against — the engine COMPUTES the header and returns it with
 * the result set and the ledger's entry delta, so the Python manager can
 * mirror its read view.  All failures before the store fold roll the
 * header and delta maps back (the engine stays usable — degrade to the
 * Python close); a failure after the fold poisons the engine. */
static PyObject *
Engine_close_ledger(Engine *self, PyObject *args)
{
    PyObject *tx_rec_obj;
    const uint8_t *scp;
    Py_ssize_t scp_len;
    if (!PyArg_ParseTuple(args, "Oy#", &tx_rec_obj, &scp, &scp_len))
        return NULL;
    if (!self->state_loaded) {
        PyErr_SetString(CapplyError, "no state imported");
        return NULL;
    }
    if (self->poisoned) {
        PyErr_SetString(CapplyError, "engine poisoned by an earlier "
                        "failed close");
        return NULL;
    }
    uint32_t seq = self->header.ledger_seq + 1;
    CTx *txs = PyMem_Malloc(sizeof(CTx) * MAX_TX_PER_LEDGER);
    if (!txs)
        return PyErr_NoMemory();
    zero_tx_inners(txs);
    int n_txs = 0;
    uint8_t set_hash[32];
    if (tx_rec_obj != Py_None) {
        char *tp;
        Py_ssize_t tl;
        if (PyBytes_AsStringAndSize(tx_rec_obj, &tp, &tl) < 0)
            goto fail_free;
        const uint8_t *set_p;
        int set_len;
        uint32_t rec_seq;
        int rc = parse_tx_record((uint8_t *)tp, (int)tl, self->network_id,
                                 txs, &n_txs, &set_p, &set_len, &rec_seq);
        if (rc) {
            raise_capply(rc > 0
                ? "unsupported tx at ledger %lu (native probe miss)"
                : "malformed tx record at ledger %lu", seq);
            goto fail_free;
        }
        if (rec_seq != seq) {
            raise_capply("tx record seq mismatch at ledger %lu", seq);
            goto fail_free;
        }
        sha256_of(set_p, set_len, set_hash);
    } else {
        Sha256 s;
        sha_init(&s);
        sha_update(&s, self->lcl_hash, 32);
        static const uint8_t zero4[4] = {0, 0, 0, 0};
        sha_update(&s, zero4, 4);
        sha_final(&s, set_hash);
    }
    /* the externalized value must name the tx set being applied */
    {
        CHeader probe;
        memset(&probe, 0, sizeof(probe));
        Rd sr;
        rd_init(&sr, scp, (int)scp_len);
        if (parse_scp_value(&sr, &probe) < 0 || sr.off != sr.len) {
            cheader_clear(&probe);
            raise_capply("bad scpValue at ledger %lu", seq);
            goto fail_free;
        }
        int match = memcmp(probe.tx_set_hash, set_hash, 32) == 0;
        cheader_clear(&probe);
        if (!match) {
            raise_capply(
                "externalized value names a different tx set at %lu", seq);
            goto fail_free;
        }
    }
    /* header snapshot for rollback (store untouched until the fold) */
    CHeader saved;
    if (cheader_copy(&self->header, &saved) < 0)
        goto fail_free;
    CHeader *h = &self->header;
    h->ledger_seq = seq;
    memcpy(h->previous_hash, self->lcl_hash, 32);
    Buf results = {0};
    if (cheader_set_scp(h, scp, (int)scp_len) < 0 ||
        apply_tx_phase(self, txs, n_txs, &results) < 0) {
        /* clean rollback: restore the header, drop the deltas */
        cheader_clear(&self->header);
        self->header = saved;
        map_clear(&self->ledger_delta);
        eng_rollback_tx(self);
        PyMem_Free(results.p);
        if (!PyErr_Occurred())
            raise_capply("apply failed at ledger %lu", seq);
        goto fail_free;
    }
    cheader_clear(&saved);
    /* seal: from here a failure poisons the engine (store mutated) */
    CBucket *fresh = build_fresh_and_fold(self, seq);
    if (!fresh || cbl_add_batch(&self->bl, seq, h->ledger_version,
                                fresh) < 0) {
        cbucket_unref(fresh);
        PyMem_Free(results.p);
        self->poisoned = 1;
        if (!PyErr_Occurred())
            raise_capply("seal failed at ledger %lu", seq);
        goto fail_free;
    }
    cbl_hash(&self->bl, h->bucket_list_hash);
    static const uint32_t intervals[4] = {50, 5000, 50000, 500000};
    for (int i = 0; i < 4; i++)
        if (seq % intervals[i] == 0)
            memcpy(h->skip_list[i], h->previous_hash, 32);
    Buf hb = {0};
    PyObject *delta = NULL, *out = NULL;
    if (serialize_header(h, &hb) < 0)
        goto fail_sealed;
    sha256_of(hb.p, hb.len, self->lcl_hash);
    self->ledgers_applied++;
    /* the ledger's entry delta, for the Python manager's read mirror */
    delta = PyList_New(0);
    if (!delta)
        goto fail_sealed;
    for (int i = 0; i < fresh->n; i++) {
        RB *k = fresh->keys[i], *rec = fresh->recs[i];
        PyObject *pair;
        if (rec_type(rec) == BE_DEAD)
            pair = Py_BuildValue("(y#O)", k->bytes, (Py_ssize_t)k->len,
                                 Py_None);
        else
            pair = Py_BuildValue("(y#y#)", k->bytes, (Py_ssize_t)k->len,
                                 rec->bytes + 4, (Py_ssize_t)(rec->len - 4));
        if (!pair || PyList_Append(delta, pair) < 0) {
            Py_XDECREF(pair);
            goto fail_sealed;
        }
        Py_DECREF(pair);
    }
    out = Py_BuildValue("(ky#y#y#N)", (unsigned long)seq,
                        self->lcl_hash, (Py_ssize_t)32,
                        hb.p, (Py_ssize_t)hb.len,
                        results.p, (Py_ssize_t)results.len, delta);
    delta = NULL;                   /* N stole the reference */
    PyMem_Free(hb.p);
    PyMem_Free(results.p);
    cbucket_unref(fresh);
    free_tx_inners(txs);
    PyMem_Free(txs);
    if (!out)
        self->poisoned = 1;         /* close happened; result lost (OOM) */
    return out;
fail_sealed:
    Py_XDECREF(delta);
    PyMem_Free(hb.p);
    PyMem_Free(results.p);
    cbucket_unref(fresh);
    self->poisoned = 1;
fail_free:
    free_tx_inners(txs);
    PyMem_Free(txs);
    return NULL;
}

static PyObject *
Engine_seed_verdicts(Engine *self, PyObject *args)
{
    PyObject *pks, *msgs, *sigs, *verdicts;
    if (!PyArg_ParseTuple(args, "OOOO", &pks, &sigs, &msgs, &verdicts))
        return NULL;
    Py_ssize_t n = PyList_Size(pks);
    if (PyList_Size(sigs) != n || PyList_Size(msgs) != n ||
        PyList_Size(verdicts) != n) {
        PyErr_SetString(PyExc_ValueError, "length mismatch");
        return NULL;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        char *pk, *sig, *msg;
        Py_ssize_t pkl, sigl, msgl;
        if (PyBytes_AsStringAndSize(PyList_GetItem(pks, i), &pk, &pkl) < 0 ||
            PyBytes_AsStringAndSize(PyList_GetItem(sigs, i), &sig, &sigl) < 0 ||
            PyBytes_AsStringAndSize(PyList_GetItem(msgs, i), &msg, &msgl) < 0)
            return NULL;
        if (pkl != 32)
            continue;
        int v = PyObject_IsTrue(PyList_GetItem(verdicts, i));
        if (v < 0)
            return NULL;
        if (sigl != 64)
            continue;            /* verify_sig_c short-circuits those */
        uint8_t d[16];
        vcache_key((uint8_t *)pk, (uint8_t *)msg, (int)msgl,
                   (uint8_t *)sig, (int)sigl, d);
        vcache_put(&self->vcache, d, v);
    }
    Py_RETURN_NONE;
}

static int
harvest_add(Engine *e, const uint8_t pk[32])
{
    for (int i = 0; i < e->n_harvest; i++)
        if (memcmp(e->harvest[i], pk, 32) == 0)
            return 0;
    if (e->n_harvest == e->cap_harvest) {
        int nc = e->cap_harvest ? e->cap_harvest * 2 : 64;
        void *np = PyMem_Realloc(e->harvest, nc * 32);
        if (!np) { PyErr_NoMemory(); return -1; }
        e->harvest = np;
        e->cap_harvest = nc;
    }
    memcpy(e->harvest[e->n_harvest++], pk, 32);
    return 0;
}

/* Accel pairing extraction (mirrors PreverifyPipeline.dispatch pairing):
 * for each tx of the given raw records, candidates are the tx/op source
 * account ids, those accounts' ed25519 signers in the engine state, and
 * the cumulative SetOptions harvest; every decorated signature pairs with
 * every distinct hint-matching candidate.  Returns (pks, sigs, msgs,
 * total_sigs) — msgs are the 32-byte content hashes. */
static PyObject *
Engine_extract_pairs(Engine *self, PyObject *args)
{
    PyObject *tx_recs;
    if (!PyArg_ParseTuple(args, "O", &tx_recs))
        return NULL;
    CTx *txs = PyMem_Malloc(sizeof(CTx) * MAX_TX_PER_LEDGER);
    if (!txs)
        return PyErr_NoMemory();
    zero_tx_inners(txs);
    PyObject *pks = PyList_New(0), *sigs = PyList_New(0),
             *msgs = PyList_New(0);
    long total = 0;
    if (!pks || !sigs || !msgs)
        goto fail;
    Py_ssize_t n_recs = PyList_Size(tx_recs);
    /* pass 1: harvest SetOptions signers from the whole group */
    for (Py_ssize_t ri = 0; ri < n_recs; ri++) {
        PyObject *item = PyList_GetItem(tx_recs, ri);
        if (item == Py_None)
            continue;
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0)
            goto fail;
        int n_txs, set_len;
        const uint8_t *set_p;
        uint32_t rec_seq;
        if (parse_tx_record((uint8_t *)p, (int)len, self->network_id, txs,
                            &n_txs, &set_p, &set_len, &rec_seq) != 0)
            continue;            /* unsupported/malformed: python pairs it */
        for (int t = 0; t < n_txs; t++) {
            CTx *hb = txs[t].is_feebump ? txs[t].inner : &txs[t];
            for (int oi = 0; oi < hb->n_ops; oi++) {
                COp *op = &hb->ops[oi];
                if (op->op_type != 5)
                    continue;
                /* walk the SetOptions body to the optional signer */
                Rd r;
                rd_init(&r, op->body, op->body_len);
                uint32_t pr = rd_u32(&r);
                if (pr) { rd_skip(&r, 36); }
                for (int k = 0; k < 6; k++) {
                    pr = rd_u32(&r);
                    if (pr) rd_skip(&r, 4);
                }
                pr = rd_u32(&r);
                if (pr) {
                    uint32_t sl;
                    if (!rd_varopaque(&r, 32, &sl))
                        continue;
                }
                pr = rd_u32(&r);
                if (pr && !r.err) {
                    CSigner sg;
                    if (parse_signer_key(&r, &sg) == 0 &&
                        sg.key_type == 0) {
                        if (harvest_add(self, sg.key) < 0)
                            goto fail;
                    }
                }
            }
        }
    }
    /* pass 2: pair */
    for (Py_ssize_t ri = 0; ri < n_recs; ri++) {
        PyObject *item = PyList_GetItem(tx_recs, ri);
        if (item == Py_None)
            continue;
        char *p;
        Py_ssize_t len;
        if (PyBytes_AsStringAndSize(item, &p, &len) < 0)
            goto fail;
        int n_txs, set_len;
        const uint8_t *set_p;
        uint32_t rec_seq;
        if (parse_tx_record((uint8_t *)p, (int)len, self->network_id, txs,
                            &n_txs, &set_p, &set_len, &rec_seq) != 0)
            continue;
        for (int t = 0; t < n_txs; t++) {
            CTx *tx = &txs[t];
            CTx *body = tx->is_feebump ? tx->inner : tx;
            total += tx->n_sigs;
            /* candidate pks: sources' masters + their state signers
             * (fee bumps add the inner source; ops live on the inner) */
            uint8_t cand[2 + MAX_OPS + 21 * (2 + MAX_OPS)][32];
            int n_cand = 0;
            uint8_t srcs[2 + MAX_OPS][32];
            int n_srcs = 0;
            memcpy(srcs[n_srcs++], tx->source, 32);
            if (tx->is_feebump &&
                memcmp(tx->inner->source, tx->source, 32) != 0)
                memcpy(srcs[n_srcs++], tx->inner->source, 32);
            for (int oi = 0; oi < body->n_ops; oi++)
                if (body->ops[oi].has_source) {
                    int dup = 0;
                    for (int k = 0; k < n_srcs; k++)
                        if (memcmp(srcs[k], body->ops[oi].source, 32) == 0) {
                            dup = 1;
                            break;
                        }
                    if (!dup)
                        memcpy(srcs[n_srcs++], body->ops[oi].source, 32);
                }
            for (int k = 0; k < n_srcs; k++) {
                memcpy(cand[n_cand++], srcs[k], 32);
                CAccount acc;
                int got = eng_get_account(self, srcs[k], &acc);
                if (got > 0) {
                    for (int si = 0; si < acc.n_signers; si++)
                        if (acc.signers[si].key_type == 0)
                            memcpy(cand[n_cand++], acc.signers[si].key, 32);
                }
            }
            /* pair the outer signatures against the outer hash, and —
             * for fee bumps — the inner signatures against the inner
             * hash (the Python frames pipeline only pairs the outer
             * ones; preverifying both is strictly better and verdicts
             * are identical either way) */
            int n_total_sigs = tx->n_sigs +
                (tx->is_feebump ? tx->inner->n_sigs : 0);
            if (tx->is_feebump)
                total += tx->inner->n_sigs;
            for (int di = 0; di < n_total_sigs; di++) {
                CDecSig *ds = di < tx->n_sigs
                    ? &tx->sigs[di]
                    : &tx->inner->sigs[di - tx->n_sigs];
                const uint8_t *msg_hash = di < tx->n_sigs
                    ? tx->content_hash : tx->inner->content_hash;
                uint8_t seen[64][32];
                int n_seen = 0;
#define EMIT_PAIR(PK) do {                     int dup = 0;                     for (int z = 0; z < n_seen; z++)                         if (memcmp(seen[z], (PK), 32) == 0) { dup = 1; break; }                     if (!dup && n_seen < 64) {                         memcpy(seen[n_seen++], (PK), 32);                         PyObject *o1 = PyBytes_FromStringAndSize((const char *)(PK), 32);                         PyObject *o2 = PyBytes_FromStringAndSize((const char *)ds->sig, ds->sig_len);                         PyObject *o3 = PyBytes_FromStringAndSize((const char *)msg_hash, 32);                         if (!o1 || !o2 || !o3 ||                             PyList_Append(pks, o1) < 0 ||                             PyList_Append(sigs, o2) < 0 ||                             PyList_Append(msgs, o3) < 0) {                             Py_XDECREF(o1); Py_XDECREF(o2); Py_XDECREF(o3);                             goto fail;                         }                         Py_DECREF(o1); Py_DECREF(o2); Py_DECREF(o3);                     }                 } while (0)
                for (int k = 0; k < n_cand; k++)
                    if (memcmp(ds->hint, cand[k] + 28, 4) == 0)
                        EMIT_PAIR(cand[k]);
                for (int k = 0; k < self->n_harvest; k++)
                    if (memcmp(ds->hint, self->harvest[k] + 28, 4) == 0)
                        EMIT_PAIR(self->harvest[k]);
#undef EMIT_PAIR
            }
        }
    }
    free_tx_inners(txs);
    PyMem_Free(txs);
    return Py_BuildValue("(NNNl)", pks, sigs, msgs, total);
fail:
    free_tx_inners(txs);
    PyMem_Free(txs);
    Py_XDECREF(pks);
    Py_XDECREF(sigs);
    Py_XDECREF(msgs);
    return NULL;
}

static PyObject *
Engine_stats(Engine *self, PyObject *args)
{
    (void)args;
    return Py_BuildValue(
        "{s:K,s:K,s:K,s:K,s:K}",
        "ledgers_applied", (unsigned long long)self->ledgers_applied,
        "txs_applied", (unsigned long long)self->txs_applied,
        "verify_cache_hits", (unsigned long long)self->vcache.hits,
        "verify_cache_misses", (unsigned long long)self->vcache.misses,
        "libsodium_verifies", (unsigned long long)self->vcache.verifies);
}

static PyMethodDef Engine_methods[] = {
    {"import_state", (PyCFunction)Engine_import_state, METH_VARARGS,
     "import_state(header_xdr, lcl_hash, entries[(key,rec)], "
     "buckets[22 x (keys, recs, proto)], nexts[11 x None|(keys,recs,proto)])"},
    {"export_state", (PyCFunction)Engine_export_state, METH_NOARGS,
     "-> (header_xdr, lcl_hash, entries, bucket_streams[22], "
     "next_streams[11])"},
    {"export_buckets", (PyCFunction)Engine_export_buckets, METH_NOARGS,
     "-> (header_xdr, bucket_streams[22], next_streams[11]) — no entry "
     "materialization (checkpoint-boundary sync)"},
    {"probe", (PyCFunction)Engine_probe, METH_VARARGS,
     "probe(tx_recs) -> bool: every tx natively applicable?"},
    {"apply_checkpoint", (PyCFunction)Engine_apply_checkpoint, METH_VARARGS,
     "apply_checkpoint(header_recs, tx_recs, max_seq) -> n_applied"},
    {"close_ledger", (PyCFunction)Engine_close_ledger, METH_VARARGS,
     "close_ledger(tx_rec|None, scp_value_xdr) -> (seq, lcl_hash, "
     "header_xdr, result_set_xdr, delta[(key, entry|None)])"},
    {"lcl", (PyCFunction)Engine_lcl, METH_NOARGS, "-> (seq, hash)"},
    {"seed_verdicts", (PyCFunction)Engine_seed_verdicts, METH_VARARGS,
     "seed_verdicts(pks, sigs, msgs, verdicts)"},
    {"extract_pairs", (PyCFunction)Engine_extract_pairs, METH_VARARGS,
     "extract_pairs(tx_recs) -> (pks, sigs, msgs, total_sigs)"},
    {"stats", (PyCFunction)Engine_stats, METH_NOARGS, "-> dict"},
    {NULL, NULL, 0, NULL},
};

static PyTypeObject EngineType = {
    PyVarObject_HEAD_INIT(NULL, 0)
    .tp_name = "_capply.Engine",
    .tp_basicsize = sizeof(Engine),
    .tp_dealloc = (destructor)Engine_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_new = Engine_new,
    .tp_methods = Engine_methods,
    .tp_doc = "Native ledger-apply engine (catchup replay hot path)",
};

/* debug/differential helper: parse + reserialize an account LedgerEntry */
static PyObject *
capply_roundtrip_account(PyObject *self, PyObject *args)
{
    (void)self;
    const uint8_t *p;
    Py_ssize_t len;
    if (!PyArg_ParseTuple(args, "y#", &p, &len))
        return NULL;
    CAccount a;
    if (parse_account_entry(p, (int)len, &a) < 0) {
        PyErr_SetString(CapplyError, "account parse failed");
        return NULL;
    }
    Buf b = {0};
    if (serialize_account_entry(&a, &b) < 0) {
        PyMem_Free(b.p);
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize((char *)b.p, b.len);
    PyMem_Free(b.p);
    return res;
}

/* stateless strict scan of one TransactionHistoryEntry: returns
 * (rc, n_sigs) with rc 0 = natively supported / 1 = unsupported (fall
 * back to Python); raises on malformed framing — lets the download work
 * keep its retry-with-backoff contract for corrupt archives without
 * decoding in Python, and gives the pipeline a pair-free signature count
 * (n_sigs is partial for rc=1: the parse stops at the unsupported
 * feature; the fallback path re-counts from decoded frames). */
static PyObject *
capply_scan_tx_record(PyObject *self, PyObject *args)
{
    (void)self;
    const uint8_t *nid, *rec;
    Py_ssize_t nid_len, rec_len;
    if (!PyArg_ParseTuple(args, "y#y#", &nid, &nid_len, &rec, &rec_len))
        return NULL;
    if (nid_len != 32) {
        PyErr_SetString(PyExc_ValueError, "network id must be 32 bytes");
        return NULL;
    }
    CTx *txs = PyMem_Malloc(sizeof(CTx) * MAX_TX_PER_LEDGER);
    if (!txs)
        return PyErr_NoMemory();
    zero_tx_inners(txs);
    int n_txs = 0, set_len;
    const uint8_t *set_p;
    uint32_t rec_seq;
    int rc = parse_tx_record(rec, (int)rec_len, nid, txs, &n_txs,
                             &set_p, &set_len, &rec_seq);
    long n_sigs = 0;
    if (rc >= 0)
        for (int i = 0; i < n_txs; i++)
            if (txs[i].supported)
                n_sigs += txs[i].n_sigs;
    free_tx_inners(txs);
    PyMem_Free(txs);
    if (rc < 0) {
        PyErr_SetString(CapplyError, "malformed tx record");
        return NULL;
    }
    return Py_BuildValue("(il)", rc, n_sigs);
}

static PyMethodDef capply_methods[] = {
    {"roundtrip_account", capply_roundtrip_account, METH_VARARGS,
     "parse+reserialize an account LedgerEntry (differential tests)"},
    {"scan_tx_record", capply_scan_tx_record, METH_VARARGS,
     "scan_tx_record(network_id, rec) -> 0 supported / 1 unsupported; "
     "raises _capply.Error on malformed framing"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef capply_module = {
    PyModuleDef_HEAD_INIT, "_capply",
    "Native catchup-replay apply core", -1, capply_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit__capply(void)
{
    PyObject *m = PyModule_Create(&capply_module);
    if (!m)
        return NULL;
    if (PyType_Ready(&EngineType) < 0)
        return NULL;
    Py_INCREF(&EngineType);
    PyModule_AddObject(m, "Engine", (PyObject *)&EngineType);
    CapplyError = PyErr_NewException("_capply.Error", NULL, NULL);
    Py_INCREF(CapplyError);
    PyModule_AddObject(m, "Error", CapplyError);
    load_sodium();
    return m;
}

/* ---- TrustLine / Data entries (round-5 widening) ---------------------- */

typedef struct {
    /* LedgerEntry level */
    uint32_t last_modified;
    int entry_ext_v1;
    int has_sponsor;
    uint8_t sponsor[32];
    /* TrustLineEntry */
    uint8_t account_id[32];
    uint32_t asset_type;        /* 1 alphanum4 / 2 alphanum12 / 3 pool
                                   share (native never stored) */
    uint8_t asset_code[12];
    uint8_t issuer[32];
    uint8_t pool_id[32];        /* asset_type == 3 */
    int64_t balance;
    int64_t limit;
    uint32_t flags;
    int ext_level;              /* 0 v0; 1 v1; 2 v1+v2 */
    int64_t liab_buying, liab_selling;
    int32_t pool_use_count;
} CTrustLine;

static int
parse_trustline_entry(const uint8_t *data, int len, CTrustLine *t)
{
    memset(t, 0, sizeof(*t));
    Rd r;
    rd_init(&r, data, len);
    t->last_modified = rd_u32(&r);
    if (rd_u32(&r) != 1 || r.err)       /* data tag TRUSTLINE */
        return -1;
    if (parse_account_id(&r, t->account_id) < 0)
        return -1;
    t->asset_type = rd_u32(&r);
    if (r.err)
        return -1;
    if (t->asset_type == 1) {
        const uint8_t *c = rd_take(&r, 4);
        if (!c) return -1;
        memcpy(t->asset_code, c, 4);
        if (parse_account_id(&r, t->issuer) < 0) return -1;
    } else if (t->asset_type == 2) {
        const uint8_t *c = rd_take(&r, 12);
        if (!c) return -1;
        memcpy(t->asset_code, c, 12);
        if (parse_account_id(&r, t->issuer) < 0) return -1;
    } else if (t->asset_type == 3) {
        const uint8_t *c = rd_take(&r, 32);   /* liquidityPoolID */
        if (!c) return -1;
        memcpy(t->pool_id, c, 32);
    } else {
        return -1;              /* native: never stored as a trustline */
    }
    t->balance = rd_i64(&r);
    t->limit = rd_i64(&r);
    t->flags = rd_u32(&r);
    int32_t ext = rd_i32(&r);
    if (r.err || (ext != 0 && ext != 1))
        return -1;
    if (ext == 1) {
        t->ext_level = 1;
        t->liab_buying = rd_i64(&r);
        t->liab_selling = rd_i64(&r);
        int32_t e1 = rd_i32(&r);
        if (r.err || (e1 != 0 && e1 != 2))
            return -1;
        if (e1 == 2) {
            t->ext_level = 2;
            t->pool_use_count = rd_i32(&r);
            if (rd_i32(&r) != 0 || r.err)
                return -1;
        }
    }
    int32_t lext = rd_i32(&r);
    if (r.err || (lext != 0 && lext != 1))
        return -1;
    t->entry_ext_v1 = (int)lext;
    if (lext == 1) {
        uint32_t sp = rd_u32(&r);
        if (r.err || sp > 1)
            return -1;
        t->has_sponsor = (int)sp;
        if (sp && parse_account_id(&r, t->sponsor) < 0)
            return -1;
        if (rd_i32(&r) != 0 || r.err)
            return -1;
    }
    return (r.err || r.off != r.len) ? -1 : 0;
}

static int
write_tl_asset(Buf *b, uint32_t asset_type, const uint8_t code[12],
               const uint8_t issuer[32])
{
    if (buf_u32(b, asset_type) < 0)
        return -1;
    if (buf_put(b, code, asset_type == 1 ? 4 : 12) < 0)
        return -1;
    return write_account_id(b, issuer);
}

/* TrustLineAsset for a pool-share line: tag 3 + liquidityPoolID */
static int
write_tl_pool_asset(Buf *b, const uint8_t pool_id[32])
{
    return buf_u32(b, 3) < 0 || buf_put(b, pool_id, 32) < 0 ? -1 : 0;
}

static int
serialize_trustline_entry(const CTrustLine *t, Buf *b)
{
    if (buf_u32(b, t->last_modified) < 0 ||
        buf_u32(b, 1) < 0 ||
        write_account_id(b, t->account_id) < 0 ||
        (t->asset_type == 3
         ? write_tl_pool_asset(b, t->pool_id)
         : write_tl_asset(b, t->asset_type, t->asset_code, t->issuer)) < 0 ||
        buf_i64(b, t->balance) < 0 ||
        buf_i64(b, t->limit) < 0 ||
        buf_u32(b, t->flags) < 0 ||
        buf_i32(b, t->ext_level >= 1 ? 1 : 0) < 0)
        return -1;
    if (t->ext_level >= 1) {
        if (buf_i64(b, t->liab_buying) < 0 ||
            buf_i64(b, t->liab_selling) < 0 ||
            buf_i32(b, t->ext_level >= 2 ? 2 : 0) < 0)
            return -1;
        if (t->ext_level >= 2) {
            if (buf_i32(b, t->pool_use_count) < 0 || buf_i32(b, 0) < 0)
                return -1;
        }
    }
    if (buf_i32(b, t->entry_ext_v1) < 0)
        return -1;
    if (t->entry_ext_v1) {
        if (buf_u32(b, (uint32_t)t->has_sponsor) < 0)
            return -1;
        if (t->has_sponsor && write_account_id(b, t->sponsor) < 0)
            return -1;
        if (buf_i32(b, 0) < 0)
            return -1;
    }
    return 0;
}

/* trustline LedgerKey XDR: tag 1 + accountID + TrustLineAsset */
static int
trustline_key_xdr_c(const uint8_t acc[32], uint32_t asset_type,
                    const uint8_t code[12], const uint8_t issuer[32],
                    Buf *b)
{
    if (buf_u32(b, 1) < 0 || write_account_id(b, acc) < 0)
        return -1;
    return write_tl_asset(b, asset_type, code, issuer);
}

/* pool-share trustline LedgerKey: tag 1 + accountID + (tag 3 + poolID) */
static int
pool_trustline_key_xdr_c(const uint8_t acc[32], const uint8_t pool_id[32],
                         Buf *b)
{
    if (buf_u32(b, 1) < 0 || write_account_id(b, acc) < 0)
        return -1;
    return write_tl_pool_asset(b, pool_id);
}

/* mirror utils.add_trustline_balance */
static int
add_tl_balance_c(CTrustLine *t, int64_t delta)
{
    i128 nb = (i128)t->balance + delta;
    if (nb < 0 || nb > t->limit)
        return 0;
    if (delta < 0 && nb < t->liab_selling)
        return 0;
    if (delta > 0 && nb > (i128)t->limit - t->liab_buying)
        return 0;
    t->balance = (int64_t)nb;
    return 1;
}

/* parse an alphanum4/12 Asset arm: type already read as `at`.  Fills
 * code (zero-padded 12) + issuer.  Returns -1 on malformed. */
static int
parse_alphanum(Rd *r, uint32_t at, uint8_t code[12], uint8_t issuer[32])
{
    memset(code, 0, 12);
    const uint8_t *c = rd_take(r, at == 1 ? 4 : 12);
    if (!c)
        return -1;
    memcpy(code, c, at == 1 ? 4 : 12);
    if (rd_u32(r) != 0)                       /* PK type */
        { r->err = 1; return -1; }
    const uint8_t *iq = rd_take(r, 32);
    if (!iq)
        return -1;
    memcpy(issuer, iq, 32);
    return 0;
}

/* stamp + serialize + store a trustline under key `kb` (which is freed).
 * Returns the op-function contract: 1 stored+success-result written,
 * -1 engine error. */
static int
store_trustline(Engine *e, Buf *kb, CTrustLine *tl, Buf *rb,
                int32_t op_type)
{
    tl->last_modified = e->header.ledger_seq;
    Buf eb = {0};
    int rc = -1;
    if (serialize_trustline_entry(tl, &eb) < 0)
        goto out;
    RB *val = rb_new(eb.p, eb.len);
    if (!val || eng_put(e, e->cur, kb->p, kb->len, val) < 0)
        goto out;
    rc = res_inner(rb, op_type, 0) < 0 ? -1 : 1;
out:
    PyMem_Free(eb.p);
    PyMem_Free(kb->p);
    kb->p = NULL;
    return rc;
}

/* mirror utils.asset_valid for alphanum codes */
static int
asset_code_valid(uint32_t asset_type, const uint8_t *code)
{
    int maxlen = asset_type == 1 ? 4 : 12;
    int n = maxlen;
    while (n > 0 && code[n - 1] == 0)
        n--;
    if (n == 0)
        return 0;
    for (int i = 0; i < n; i++) {
        uint8_t c = code[i];
        if (c == 0)
            return 0;                   /* embedded NUL before padding */
        if (!((c >= '0' && c <= '9') || (c >= 'A' && c <= 'Z') ||
              (c >= 'a' && c <= 'z')))
            return 0;
    }
    if (asset_type == 1)
        return n <= 4;
    return n >= 5;
}

/* ---- round-5 widened op set ------------------------------------------- */

/* shared: release a sponsored entry's reserve units from its sponsor
 * (mirror sponsorship.release_entry_sponsorship sponsor side; the owner
 * side (numSponsored) is the caller's CAccount). Returns -1 on count
 * underflow (fail-stop, like the oracle's RuntimeError). */
static int
release_entry_sponsor(Engine *e, const uint8_t sponsor[32], int mult,
                      CAccount *owner)
{
    CAccount sp;
    int g = eng_get_account(e, sponsor, &sp);
    if (g < 0)
        return -1;
    if (g) {
        if ((int)sp.num_sponsoring < mult)
            return -1;
        sp.num_sponsoring -= (uint32_t)mult;
        sp.last_modified = e->header.ledger_seq;
        if (eng_put_account(e, e->cur, &sp) < 0)
            return -1;
    }
    if (owner != NULL) {
        if ((int)owner->num_sponsored < mult)
            return -1;
        owner->num_sponsored -= (uint32_t)mult;
    }
    return 0;
}

static int
is_issuer_c(const uint8_t acc[32], uint32_t asset_type,
            const uint8_t issuer[32])
{
    (void)asset_type;
    return memcmp(acc, issuer, 32) == 0;
}

/* one side of a credit payment: load/auth/adjust/store the trustline of
 * `acc`.  Returns 1 ok, 0 failed (fail_code written as the op result),
 * -1 engine error.  no_trust/not_auth/balance_fail are the side's result
 * codes (src: -3/-4/-2; dest: -6/-7/-8). */
static int
payment_tl_side(Engine *e, Buf *rb, const uint8_t acc[32],
                uint32_t asset_type, const uint8_t code[12],
                const uint8_t issuer[32], int64_t delta,
                int no_trust, int not_auth, int balance_fail)
{
    Buf kb = {0};
    if (trustline_key_xdr_c(acc, asset_type, code, issuer, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    int rc = -1;
    RB *rec = eng_get(e, kb.p, kb.len);
    if (!rec) {
        rc = res_inner(rb, 1, no_trust) < 0 ? -1 : 0;
        goto out;
    }
    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0)
        goto out;
    if (!(tl.flags & 1)) {                        /* AUTHORIZED */
        rc = res_inner(rb, 1, not_auth) < 0 ? -1 : 0;
        goto out;
    }
    if (!add_tl_balance_c(&tl, delta)) {
        rc = res_inner(rb, 1, balance_fail) < 0 ? -1 : 0;
        goto out;
    }
    tl.last_modified = e->header.ledger_seq;
    Buf eb = {0};
    if (serialize_trustline_entry(&tl, &eb) < 0) {
        PyMem_Free(eb.p);
        goto out;
    }
    RB *val = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    if (!val || eng_put(e, e->cur, kb.p, kb.len, val) < 0)
        goto out;
    rc = 1;                      /* caller writes the shared success result */
out:
    PyMem_Free(kb.p);
    return rc;
}

/* credit-asset arm of PaymentOpFrame (native arm lives in op_payment) */
static int
op_payment_credit(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
                  Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t mt = rd_u32(&r);
    if (mt == 0x100)
        rd_skip(&r, 8);
    const uint8_t *dest = rd_take(&r, 32);
    uint32_t at = rd_u32(&r);
    uint8_t code[12], issuer[32];
    if (r.err || parse_alphanum(&r, at, code, issuer) < 0)
        return -1;
    int64_t amount = rd_i64(&r);
    if (!dest || r.err)
        return -1;

    /* do_check_valid: amount > 0, asset code valid */
    if (amount <= 0 || !asset_code_valid(at, code))
        return res_inner(rb, 1, -1) < 0 ? -1 : 0;    /* MALFORMED */

    CAccount dst_acc;
    int got = eng_get_account(e, dest, &dst_acc);
    if (got < 0)
        return -1;
    if (!got)
        return res_inner(rb, 1, -5) < 0 ? -1 : 0;    /* NO_DESTINATION */

    /* source side (SRC_NO_TRUST/SRC_NOT_AUTHORIZED/UNDERFUNDED) */
    if (!is_issuer_c(src_id, at, issuer)) {
        int rc2 = payment_tl_side(e, rb, src_id, at, code, issuer, -amount,
                                  -3, -4, -2);
        if (rc2 <= 0)
            return rc2;
    }
    /* destination side (NO_TRUST/NOT_AUTHORIZED/LINE_FULL) */
    if (!is_issuer_c(dest, at, issuer)) {
        int rc2 = payment_tl_side(e, rb, dest, at, code, issuer, amount,
                                  -6, -7, -8);
        if (rc2 <= 0)
            return rc2;
    }
    return res_inner(rb, 1, 0) < 0 ? -1 : 1;
}

/* CAP-38 pool-share trustline arm (defined with the pool machinery) */
static int apply_pool_share_ct(Engine *, CTx *, COp *, const uint8_t *,
                               Buf *);

/* mirror ChangeTrustOpFrame (classic assets + CAP-38 pool shares) */
static int
op_change_trust(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
                Buf *rb)
{
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t lt = rd_u32(&r);
    uint8_t code[12] = {0};
    uint8_t issuer[32] = {0};
    if (lt == 3)
        return apply_pool_share_ct(e, tx, op, src_id, rb);
    if (lt == 1 || lt == 2) {
        if (parse_alphanum(&r, lt, code, issuer) < 0)
            return -1;
    } else if (lt != 0) {
        return -1;
    }
    int64_t limit = rd_i64(&r);
    if (r.err)
        return -1;
    CHeader *h = &e->header;

    /* do_check_valid */
    if (lt == 0)
        return res_inner(rb, 6, -1) < 0 ? -1 : 0;   /* native: MALFORMED */
    if (!asset_code_valid(lt, code) || limit < 0)
        return res_inner(rb, 6, -1) < 0 ? -1 : 0;
    if (is_issuer_c(src_id, lt, issuer))
        return res_inner(rb, 6, -5) < 0 ? -1 : 0;   /* SELF_NOT_ALLOWED */

    Buf kb = {0};
    if (trustline_key_xdr_c(src_id, lt, code, issuer, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    CAccount src;
    if (eng_get_account(e, src_id, &src) <= 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    uint8_t ik[40];
    account_key_xdr_c(issuer, ik);

#define CT_FAIL(code_) do { \
        int rr = res_inner(rb, 6, (code_)); \
        PyMem_Free(kb.p); \
        return rr < 0 ? -1 : 0; \
    } while (0)

    if (rec == NULL) {
        if (limit == 0)
            CT_FAIL(-3);                             /* INVALID_LIMIT */
        RB *issuer_rec = eng_get(e, ik, 40);
        if (issuer_rec == NULL)
            CT_FAIL(-2);                             /* NO_ISSUER */
        CAccount iss;
        if (parse_account_entry(issuer_rec->bytes, issuer_rec->len,
                                &iss) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        uint32_t flags = 0;
        if (!(iss.flags & 0x1))                      /* AUTH_REQUIRED */
            flags |= 1;                              /* AUTHORIZED */
        if (iss.flags & 0x8)                         /* CLAWBACK_ENABLED */
            flags |= 4;                              /* TL_CLAWBACK */
        CTrustLine tl;
        memset(&tl, 0, sizeof(tl));
        memcpy(tl.account_id, src_id, 32);
        tl.asset_type = lt;
        memcpy(tl.asset_code, code, 12);
        memcpy(tl.issuer, issuer, 32);
        tl.limit = limit;
        tl.flags = flags;
        const uint8_t *sp_id = h->ledger_version >= 14
            ? active_sponsor_c(e, src_id) : NULL;
        if (sp_id != NULL) {
            int sc = sponsorship_error_c(rb, 6, -4,
                establish_sponsorship_c(e, sp_id, &src, 1));
            if (sc) {
                PyMem_Free(kb.p);
                return sc < 0 ? -1 : 0;
            }
            tl.entry_ext_v1 = 1;
            tl.has_sponsor = 1;
            memcpy(tl.sponsor, sp_id, 32);
            src.num_sub += 1;
        } else if (!add_num_entries_c(h, &src, 1)) {
            CT_FAIL(-4);                             /* LOW_RESERVE */
        }
        if (eng_put_account(e, e->cur, &src) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        return store_trustline(e, &kb, &tl, rb, 6);
    }

    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    if (limit == 0) {
        if (tl.balance != 0)
            CT_FAIL(-3);                             /* INVALID_LIMIT */
        if (tl.liab_buying || tl.liab_selling)
            CT_FAIL(-7);                             /* CANNOT_DELETE */
        if (eng_put(e, e->cur, kb.p, kb.len, NULL) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        if (tl.has_sponsor) {
            if (release_entry_sponsor(e, tl.sponsor, 1, &src) < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
            src.num_sub -= 1;
        } else {
            add_num_entries_c(h, &src, -1);
        }
        int rc2 = eng_put_account(e, e->cur, &src);
        PyMem_Free(kb.p);
        if (rc2 < 0)
            return -1;
        return res_inner(rb, 6, 0) < 0 ? -1 : 1;
    }
    if ((i128)limit < (i128)tl.balance + tl.liab_buying)
        CT_FAIL(-3);                                 /* INVALID_LIMIT */
    if (eng_get(e, ik, 40) == NULL)
        CT_FAIL(-2);                                 /* NO_ISSUER */
    tl.limit = limit;
    return store_trustline(e, &kb, &tl, rb, 6);
#undef CT_FAIL
}

/* mirror ManageDataOpFrame */
static int
op_manage_data(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
               Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t name_len;
    const uint8_t *name = rd_varopaque(&r, 64, &name_len);
    if (!name)
        return -1;
    uint32_t has_val = rd_u32(&r);
    if (r.err || has_val > 1)
        return -1;
    const uint8_t *val = NULL;
    uint32_t val_len = 0;
    if (has_val) {
        val = rd_varopaque(&r, 64, &val_len);
        if (!val)
            return -1;
    }
    CHeader *h = &e->header;

    /* do_check_valid: 1..64 ascii bytes */
    if (name_len == 0)
        return res_inner(rb, 10, -4) < 0 ? -1 : 0;   /* INVALID_NAME */
    for (uint32_t i = 0; i < name_len; i++)
        if (name[i] > 0x7F)
            return res_inner(rb, 10, -4) < 0 ? -1 : 0;

    /* data LedgerKey: tag 3 + accountID + string64 name */
    Buf kb = {0};
    if (buf_u32(&kb, 3) < 0 || write_account_id(&kb, src_id) < 0 ||
        buf_varopaque(&kb, name, (int)name_len) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    CAccount src;
    if (eng_get_account(e, src_id, &src) <= 0) {
        PyMem_Free(kb.p);
        return -1;
    }

    if (!has_val) {                                  /* delete */
        if (rec == NULL) {
            PyMem_Free(kb.p);
            return res_inner(rb, 10, -2) < 0 ? -1 : 0;  /* NAME_NOT_FOUND */
        }
        /* entry-level sponsor lives in the LedgerEntry ext: parse the
         * tail.  DataEntry layout: lastMod + tag3 + acct + name + value
         * + ext0 + entry-ext.  Walk it. */
        Rd dr;
        rd_init(&dr, rec->bytes, rec->len);
        rd_skip(&dr, 8);                             /* lastMod + tag */
        rd_skip(&dr, 36);                            /* accountID */
        uint32_t nl, vl;
        if (!rd_varopaque(&dr, 64, &nl) || !rd_varopaque(&dr, 64, &vl) ||
            rd_i32(&dr) != 0 || dr.err) {
            PyMem_Free(kb.p);
            return -1;
        }
        int32_t lext = rd_i32(&dr);
        int sponsored = 0;
        uint8_t sponsor[32];
        if (dr.err || (lext != 0 && lext != 1)) {
            PyMem_Free(kb.p);
            return -1;           /* corrupt stored entry: fail-stop */
        }
        if (lext == 1) {
            uint32_t sp = rd_u32(&dr);
            if (dr.err || sp > 1) {
                PyMem_Free(kb.p);
                return -1;
            }
            if (sp == 1) {
                if (rd_u32(&dr) != 0) {           /* PK type */
                    PyMem_Free(kb.p);
                    return -1;
                }
                const uint8_t *q = rd_take(&dr, 32);
                if (!q || rd_i32(&dr) != 0 || dr.err) {
                    PyMem_Free(kb.p);
                    return -1;
                }
                memcpy(sponsor, q, 32);
                sponsored = 1;
            } else if (rd_i32(&dr) != 0 || dr.err) {
                PyMem_Free(kb.p);
                return -1;
            }
        }
        if (eng_put(e, e->cur, kb.p, kb.len, NULL) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        if (sponsored) {
            if (release_entry_sponsor(e, sponsor, 1, &src) < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
            src.num_sub -= 1;
        } else {
            add_num_entries_c(h, &src, -1);
        }
        int rc2 = eng_put_account(e, e->cur, &src);
        PyMem_Free(kb.p);
        if (rc2 < 0)
            return -1;
        return res_inner(rb, 10, 0) < 0 ? -1 : 1;
    }

    Buf eb = {0};
    int rc2;
    const uint8_t *md_sponsor = NULL;
    if (rec == NULL) {                               /* create */
        md_sponsor = h->ledger_version >= 14
            ? active_sponsor_c(e, src_id) : NULL;
        if (md_sponsor != NULL) {
            int sc = sponsorship_error_c(rb, 10, -3,
                establish_sponsorship_c(e, md_sponsor, &src, 1));
            if (sc) {
                PyMem_Free(kb.p);
                return sc < 0 ? -1 : 0;
            }
            src.num_sub += 1;
        } else if (!add_num_entries_c(h, &src, 1)) {
            PyMem_Free(kb.p);
            return res_inner(rb, 10, -3) < 0 ? -1 : 0;  /* LOW_RESERVE */
        }
        if (eng_put_account(e, e->cur, &src) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
    } else {
        /* update: preserve the entry-level ext (sponsorship) */
        Rd dr;
        rd_init(&dr, rec->bytes, rec->len);
        rd_skip(&dr, 8 + 36);
        uint32_t nl, vl;
        if (!rd_varopaque(&dr, 64, &nl) || !rd_varopaque(&dr, 64, &vl) ||
            rd_i32(&dr) != 0 || dr.err) {
            PyMem_Free(kb.p);
            return -1;
        }
        int ext_off = dr.off;
        if (buf_u32(&eb, h->ledger_seq) < 0 || buf_u32(&eb, 3) < 0 ||
            write_account_id(&eb, src_id) < 0 ||
            buf_varopaque(&eb, name, (int)name_len) < 0 ||
            buf_varopaque(&eb, val, (int)val_len) < 0 ||
            buf_i32(&eb, 0) < 0 ||
            buf_put(&eb, rec->bytes + ext_off, rec->len - ext_off) < 0) {
            PyMem_Free(kb.p); PyMem_Free(eb.p);
            return -1;
        }
        RB *v = rb_new(eb.p, eb.len);
        PyMem_Free(eb.p);
        rc2 = v ? eng_put(e, e->cur, kb.p, kb.len, v) : -1;
        PyMem_Free(kb.p);
        if (rc2 < 0)
            return -1;
        return res_inner(rb, 10, 0) < 0 ? -1 : 1;
    }
    if (buf_u32(&eb, h->ledger_seq) < 0 || buf_u32(&eb, 3) < 0 ||
        write_account_id(&eb, src_id) < 0 ||
        buf_varopaque(&eb, name, (int)name_len) < 0 ||
        buf_varopaque(&eb, val, (int)val_len) < 0 ||
        buf_i32(&eb, 0) < 0) {
        PyMem_Free(kb.p); PyMem_Free(eb.p);
        return -1;
    }
    /* LedgerEntry ext: v1(sponsoringID) on a sandwich-sponsored create */
    if (md_sponsor != NULL
        ? (buf_i32(&eb, 1) < 0 || buf_u32(&eb, 1) < 0 ||
           write_account_id(&eb, md_sponsor) < 0 || buf_i32(&eb, 0) < 0)
        : buf_i32(&eb, 0) < 0) {
        PyMem_Free(kb.p); PyMem_Free(eb.p);
        return -1;
    }
    RB *v = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    rc2 = v ? eng_put(e, e->cur, kb.p, kb.len, v) : -1;
    PyMem_Free(kb.p);
    if (rc2 < 0)
        return -1;
    return res_inner(rb, 10, 0) < 0 ? -1 : 1;
}

/* mirror BumpSequenceOpFrame (LOW threshold, v10+) */
static int
op_bump_sequence(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
                 Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    int64_t bump_to = rd_i64(&r);
    if (r.err)
        return -1;
    if (bump_to < 0)
        return res_inner(rb, 11, -1) < 0 ? -1 : 0;   /* BAD_SEQ */
    CAccount src;
    if (eng_get_account(e, src_id, &src) <= 0)
        return -1;
    if (bump_to > src.seq_num) {
        src.seq_num = bump_to;
        src.last_modified = e->header.ledger_seq;
        if (eng_put_account(e, e->cur, &src) < 0)
            return -1;
    }
    return res_inner(rb, 11, 0) < 0 ? -1 : 1;
}

/* mirror AccountMergeOpFrame (HIGH threshold); success carries i64 */
static int
op_account_merge(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
                 Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t mt = rd_u32(&r);
    if (mt == 0x100)
        rd_skip(&r, 8);
    else if (mt != 0)
        return -1;
    const uint8_t *dest = rd_take(&r, 32);
    if (!dest || r.err)
        return -1;
    CHeader *h = &e->header;

    if (memcmp(dest, src_id, 32) == 0)
        return res_inner(rb, 8, -1) < 0 ? -1 : 0;    /* MALFORMED */
    CAccount dst;
    int got = eng_get_account(e, dest, &dst);
    if (got < 0)
        return -1;
    if (!got)
        return res_inner(rb, 8, -2) < 0 ? -1 : 0;    /* NO_ACCOUNT */
    CAccount src;
    if (eng_get_account(e, src_id, &src) <= 0)
        return -1;
    if (src.flags & 0x4)
        return res_inner(rb, 8, -3) < 0 ? -1 : 0;    /* IMMUTABLE_SET */
    if (h->ledger_version >= 14) {
        /* a party to an OPEN Begin/End sandwich — sponsored account OR
         * sponsor — cannot merge away mid-tx (mirror MergeOpFrame) */
        for (int i = 0; i < e->n_sandwich; i++)
            if (memcmp(e->sandwich[i].sponsored, src_id, 32) == 0 ||
                memcmp(e->sandwich[i].sponsor, src_id, 32) == 0)
                return res_inner(rb, 8, -7) < 0 ? -1 : 0;  /* IS_SPONSOR */
    }
    if (src.num_sub != 0)
        return res_inner(rb, 8, -4) < 0 ? -1 : 0;    /* HAS_SUB_ENTRIES */
    if (src.num_sponsoring != 0)
        return res_inner(rb, 8, -7) < 0 ? -1 : 0;    /* IS_SPONSOR */
    if (src.seq_num >= (((int64_t)h->ledger_seq + 1) << 32) - 1 &&
        src.seq_num == INT64_MAXV)
        return res_inner(rb, 8, -5) < 0 ? -1 : 0;    /* SEQNUM_TOO_FAR */
    int64_t balance = src.balance;
    if (!add_balance_c(h, &dst, balance, 0))
        return res_inner(rb, 8, -6) < 0 ? -1 : 0;    /* DEST_FULL */
    dst.last_modified = h->ledger_seq;
    if (eng_put_account(e, e->cur, &dst) < 0)
        return -1;
    if (src.entry_ext_v1 && src.has_sponsor) {
        /* the dying account's entry releases its sponsor's 2 units */
        if (release_entry_sponsor(e, src.sponsor, 2, NULL) < 0)
            return -1;
    }
    uint8_t kx[40];
    account_key_xdr_c(src_id, kx);
    if (eng_put(e, e->cur, kx, 40, NULL) < 0)
        return -1;
    /* success arm carries sourceAccountBalance (i64) */
    if (buf_i32(rb, 0) < 0 || buf_i32(rb, 8) < 0 ||
        buf_i32(rb, 0) < 0 || buf_i64(rb, balance) < 0)
        return -1;
    return 1;
}

/* mirror AllowTrustOpFrame (LOW threshold; issuer = op source) */
static int
op_allow_trust(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
               Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint8_t trustor[32];
    if (parse_account_id(&r, trustor) < 0)
        return -1;
    uint32_t at = rd_u32(&r);
    if (r.err || (at != 1 && at != 2))
        return -1;
    uint8_t code[12] = {0};
    const uint8_t *c = rd_take(&r, at == 1 ? 4 : 12);
    if (!c)
        return -1;
    memcpy(code, c, at == 1 ? 4 : 12);  /* AssetCode union: code only */
    uint32_t authorize = rd_u32(&r);
    if (r.err)
        return -1;

    /* do_check_valid */
    if (authorize > 3 || (authorize & 1 && authorize & 2))
        return res_inner(rb, 7, -1) < 0 ? -1 : 0;    /* MALFORMED */
    if (!asset_code_valid(at, code))
        return res_inner(rb, 7, -1) < 0 ? -1 : 0;
    if (memcmp(trustor, src_id, 32) == 0)
        return res_inner(rb, 7, -5) < 0 ? -1 : 0;    /* SELF_NOT_ALLOWED */

    CAccount src;
    if (eng_get_account(e, src_id, &src) <= 0)
        return -1;
    if (!(src.flags & 0x2) && authorize != 1)        /* AUTH_REVOCABLE */
        return res_inner(rb, 7, -4) < 0 ? -1 : 0;    /* CANT_REVOKE */
    Buf kb = {0};
    if (trustline_key_xdr_c(trustor, at, code, src_id, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    if (!rec) {
        PyMem_Free(kb.p);
        return res_inner(rb, 7, -2) < 0 ? -1 : 0;    /* NO_TRUST_LINE */
    }
    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    tl.flags = (tl.flags & ~3u) | authorize;
    return store_trustline(e, &kb, &tl, rb, 7);
}

/* mirror SetTrustLineFlagsOpFrame (v17+, LOW threshold) */
static int
op_set_tl_flags(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32],
                Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint8_t trustor[32];
    if (parse_account_id(&r, trustor) < 0)
        return -1;
    uint32_t at = rd_u32(&r);
    uint8_t code[12] = {0};
    uint8_t issuer[32] = {0};
    if (at == 1 || at == 2) {
        if (parse_alphanum(&r, at, code, issuer) < 0)
            return -1;
    } else if (at != 0) {
        return -1;
    }
    uint32_t clear_flags = rd_u32(&r);
    uint32_t set_flags = rd_u32(&r);
    if (r.err)
        return -1;

    /* do_check_valid */
    if (at == 0 || !asset_code_valid(at, code) ||
        !is_issuer_c(src_id, at, issuer) ||
        memcmp(trustor, src_id, 32) == 0 ||
        (set_flags & clear_flags) ||
        ((set_flags | clear_flags) & ~7u) ||
        (set_flags & 4u) ||
        ((set_flags & 1) && (set_flags & 2)))
        return res_inner(rb, 21, -1) < 0 ? -1 : 0;   /* MALFORMED */

    CAccount src;
    if (eng_get_account(e, src_id, &src) <= 0)
        return -1;
    int revoking = (clear_flags & 3u) != 0;
    if (revoking && !(src.flags & 0x2))
        return res_inner(rb, 21, -3) < 0 ? -1 : 0;   /* CANT_REVOKE */
    Buf kb = {0};
    if (trustline_key_xdr_c(trustor, at, code, issuer, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    if (!rec) {
        PyMem_Free(kb.p);
        return res_inner(rb, 21, -2) < 0 ? -1 : 0;   /* NO_TRUST_LINE */
    }
    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    uint32_t new_flags = (tl.flags & ~clear_flags) | set_flags;
    if ((new_flags & 3u) == 3u) {
        PyMem_Free(kb.p);
        return res_inner(rb, 21, -4) < 0 ? -1 : 0;   /* INVALID_STATE */
    }
    tl.flags = new_flags;
    return store_trustline(e, &kb, &tl, rb, 21);
}

/* mirror ClawbackOpFrame (v17+, MED threshold) */
static int
op_clawback(Engine *e, CTx *tx, COp *op, const uint8_t src_id[32], Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t at = rd_u32(&r);
    uint8_t code[12] = {0};
    uint8_t issuer[32] = {0};
    if (at == 1 || at == 2) {
        if (parse_alphanum(&r, at, code, issuer) < 0)
            return -1;
    } else if (at != 0) {
        return -1;
    }
    uint32_t mt = rd_u32(&r);
    if (mt == 0x100)
        rd_skip(&r, 8);
    else if (mt != 0)
        return -1;
    const uint8_t *from = rd_take(&r, 32);
    int64_t amount = rd_i64(&r);
    if (!from || r.err)
        return -1;

    /* do_check_valid */
    if (amount <= 0 || at == 0 || !asset_code_valid(at, code) ||
        !is_issuer_c(src_id, at, issuer))
        return res_inner(rb, 19, -1) < 0 ? -1 : 0;   /* MALFORMED */

    Buf kb = {0};
    if (trustline_key_xdr_c(from, at, code, issuer, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    if (!rec) {
        PyMem_Free(kb.p);
        return res_inner(rb, 19, -3) < 0 ? -1 : 0;   /* NO_TRUST */
    }
    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    if (!(tl.flags & 4u)) {
        PyMem_Free(kb.p);
        return res_inner(rb, 19, -2) < 0 ? -1 : 0;   /* NOT_CLAWBACK_ENABLED */
    }
    if (!add_tl_balance_c(&tl, -amount)) {
        PyMem_Free(kb.p);
        return res_inner(rb, 19, -4) < 0 ? -1 : 0;   /* UNDERFUNDED */
    }
    return store_trustline(e, &kb, &tl, rb, 19);
}

/* ---- offers: entries, exchange math, liabilities (round 5) ------------ *
 *
 * Mirrors transactions/offer_exchange.py exactly: exchangeV10 rounding,
 * the 1% price-error thresholds, adjustOffer, liabilities bookkeeping and
 * the convertWithOffers sweep.  All amount math in __int128 (the oracle
 * uses python ints; products are <= 2^94, bound sums <= 2^101).
 */

typedef struct {
    uint32_t type;              /* 0 native, 1 alphanum4, 2 alphanum12 */
    uint8_t code[12];
    uint8_t issuer[32];
} CAssetC;

static int
parse_asset(Rd *r, CAssetC *a)
{
    memset(a, 0, sizeof(*a));
    a->type = rd_u32(r);
    if (r->err)
        return -1;
    if (a->type == 0)
        return 0;
    if (a->type != 1 && a->type != 2) {
        r->err = 1;
        return -1;
    }
    return parse_alphanum(r, a->type, a->code, a->issuer);
}

static int
write_asset(Buf *b, const CAssetC *a)
{
    if (buf_u32(b, a->type) < 0)
        return -1;
    if (a->type == 0)
        return 0;
    if (buf_put(b, a->code, a->type == 1 ? 4 : 12) < 0)
        return -1;
    return write_account_id(b, a->issuer);
}

static int
asset_eq(const CAssetC *a, const CAssetC *b)
{
    if (a->type != b->type)
        return 0;
    if (a->type == 0)
        return 1;
    return memcmp(a->code, b->code, 12) == 0 &&
           memcmp(a->issuer, b->issuer, 32) == 0;
}

static int
asset_valid_c(const CAssetC *a)
{
    if (a->type == 0)
        return 1;
    return asset_code_valid(a->type, a->code);
}

static int
is_issuer_asset(const uint8_t acc[32], const CAssetC *a)
{
    return a->type != 0 && memcmp(a->issuer, acc, 32) == 0;
}

typedef struct {
    uint32_t last_modified;
    int entry_ext_v1;
    int has_sponsor;
    uint8_t sponsor[32];
    uint8_t seller[32];
    int64_t offer_id;
    CAssetC selling, buying;
    int64_t amount;
    int32_t price_n, price_d;
    uint32_t flags;
} COffer;

static int
parse_offer_entry(const uint8_t *data, int len, COffer *o)
{
    memset(o, 0, sizeof(*o));
    Rd r;
    rd_init(&r, data, len);
    o->last_modified = rd_u32(&r);
    if (rd_u32(&r) != 2 || r.err)       /* data tag OFFER */
        return -1;
    if (parse_account_id(&r, o->seller) < 0)
        return -1;
    o->offer_id = rd_i64(&r);
    if (parse_asset(&r, &o->selling) < 0 || parse_asset(&r, &o->buying) < 0)
        return -1;
    o->amount = rd_i64(&r);
    o->price_n = rd_i32(&r);
    o->price_d = rd_i32(&r);
    o->flags = rd_u32(&r);
    if (rd_i32(&r) != 0 || r.err)       /* OfferEntry ext v0 */
        return -1;
    int32_t lext = rd_i32(&r);
    if (r.err || (lext != 0 && lext != 1))
        return -1;
    o->entry_ext_v1 = (int)lext;
    if (lext == 1) {
        uint32_t sp = rd_u32(&r);
        if (r.err || sp > 1)
            return -1;
        o->has_sponsor = (int)sp;
        if (sp && parse_account_id(&r, o->sponsor) < 0)
            return -1;
        if (rd_i32(&r) != 0 || r.err)
            return -1;
    }
    return (r.err || r.off != r.len) ? -1 : 0;
}

/* serialize just the OfferEntry body (shared by the ledger entry and the
 * ManageOfferSuccessResult offer arm) */
static int
write_offer_body(const COffer *o, Buf *b)
{
    if (write_account_id(b, o->seller) < 0 ||
        buf_i64(b, o->offer_id) < 0 ||
        write_asset(b, &o->selling) < 0 ||
        write_asset(b, &o->buying) < 0 ||
        buf_i64(b, o->amount) < 0 ||
        buf_i32(b, o->price_n) < 0 ||
        buf_i32(b, o->price_d) < 0 ||
        buf_u32(b, o->flags) < 0 ||
        buf_i32(b, 0) < 0)
        return -1;
    return 0;
}

static int
serialize_offer_entry(const COffer *o, Buf *b)
{
    if (buf_u32(b, o->last_modified) < 0 || buf_u32(b, 2) < 0 ||
        write_offer_body(o, b) < 0 ||
        buf_i32(b, o->entry_ext_v1) < 0)
        return -1;
    if (o->entry_ext_v1) {
        if (buf_u32(b, (uint32_t)o->has_sponsor) < 0)
            return -1;
        if (o->has_sponsor && write_account_id(b, o->sponsor) < 0)
            return -1;
        if (buf_i32(b, 0) < 0)
            return -1;
    }
    return 0;
}

/* offer LedgerKey XDR: tag 2 + sellerID + offerID */
static void
offer_key_xdr_c(const uint8_t seller[32], int64_t offer_id, uint8_t out[48])
{
    out[0] = 0; out[1] = 0; out[2] = 0; out[3] = 2;
    out[4] = 0; out[5] = 0; out[6] = 0; out[7] = 0;
    memcpy(out + 8, seller, 32);
    uint64_t v = (uint64_t)offer_id;
    for (int i = 0; i < 8; i++)
        out[40 + i] = (uint8_t)(v >> (56 - 8 * i));
}

/* ---- exchangeV10 (exact integer crossing math) ------------------------ */

#define RND_NORMAL 0
#define RND_PATH_STRICT_RECEIVE 1
#define RND_PATH_STRICT_SEND 2

typedef struct {
    int wheat_stays;
    int64_t wheat_received;
    int64_t sheep_send;
} CExchange;

static i128
i128_min(i128 a, i128 b) { return a < b ? a : b; }

static int64_t
div_round_128(i128 num, i128 den, int round_up)
{
    i128 q = num / den;
    if (round_up && num % den)
        q += 1;
    return (int64_t)q;
}

static int
check_price_error_bound_c(int32_t n, int32_t d, int64_t wheat_receive,
                          int64_t sheep_send, int can_favor_wheat)
{
    i128 k = (i128)wheat_receive * n;
    i128 v = (i128)sheep_send * d;
    if (100 * v < 99 * k)
        return 0;
    if (!can_favor_wheat && 100 * v > 101 * k)
        return 0;
    return 1;
}

static CExchange
apply_price_error_thresholds_c(int32_t n, int32_t d, int64_t wheat_receive,
                               int64_t sheep_send, int wheat_stays,
                               int rounding)
{
    if (wheat_receive > 0 && sheep_send > 0) {
        if (rounding == RND_NORMAL &&
            !check_price_error_bound_c(n, d, wheat_receive, sheep_send, 0))
            wheat_receive = sheep_send = 0;
        else if (rounding == RND_PATH_STRICT_RECEIVE &&
                 !check_price_error_bound_c(n, d, wheat_receive, sheep_send,
                                            1))
            wheat_receive = sheep_send = 0;
    }
    if (wheat_receive == 0 || sheep_send == 0)
        wheat_receive = sheep_send = 0;
    CExchange ex = { wheat_stays, wheat_receive, sheep_send };
    return ex;
}

static CExchange
exchange_v10_c(int32_t n, int32_t d, int64_t max_wheat_send,
               int64_t max_wheat_receive, int64_t max_sheep_send,
               int64_t max_sheep_receive, int rounding)
{
    i128 wheat_value = i128_min((i128)max_wheat_send * n,
                                (i128)max_sheep_receive * d);
    i128 sheep_value = i128_min((i128)max_sheep_send * d,
                                (i128)max_wheat_receive * n);
    if (wheat_value <= 0 || sheep_value <= 0) {
        CExchange ex = { wheat_value > 0, 0, 0 };
        return ex;
    }
    int wheat_stays = wheat_value > sheep_value;
    int64_t wheat_receive, sheep_send;
    if (wheat_stays) {
        wheat_receive = div_round_128(sheep_value, n, 0);
        if (rounding == RND_PATH_STRICT_SEND)
            sheep_send = max_sheep_send;
        else
            sheep_send = div_round_128((i128)wheat_receive * n, d, 1);
    } else {
        wheat_receive = div_round_128(wheat_value, n, 0);
        sheep_send = div_round_128(wheat_value, d, 1);
    }
    return apply_price_error_thresholds_c(n, d, wheat_receive, sheep_send,
                                          wheat_stays, rounding);
}

static int64_t
adjust_offer_c(int32_t n, int32_t d, int64_t max_wheat_send,
               int64_t max_sheep_receive)
{
    CExchange ex = exchange_v10_c(n, d, max_wheat_send, INT64_MAXV,
                                  INT64_MAXV, max_sheep_receive, RND_NORMAL);
    return ex.wheat_received;
}

static int64_t
offer_selling_liab_c(int32_t n, int32_t d, int64_t amount)
{
    return adjust_offer_c(n, d, amount, INT64_MAXV);
}

static int64_t
offer_buying_liab_c(int32_t n, int32_t d, int64_t amount)
{
    CExchange ex = exchange_v10_c(n, d, amount, INT64_MAXV, INT64_MAXV,
                                  INT64_MAXV, RND_NORMAL);
    return ex.sheep_send;
}

/* ---- liabilities bookkeeping + transfers ------------------------------ */

/* mirror _add_liab for the native-asset (account) arm; mutates acc */
static int
account_add_liab(const CHeader *h, CAccount *acc, int64_t d_buying,
                 int64_t d_selling)
{
    i128 nb = (i128)acc->liab_buying + d_buying;
    i128 ns = (i128)acc->liab_selling + d_selling;
    if (nb < 0 || ns < 0)
        return 0;
    if (ns > (i128)acc->balance - min_balance_128(h, acc))
        return 0;
    if (nb > (i128)INT64_MAXV - acc->balance)
        return 0;
    acc->liab_buying = (int64_t)nb;
    acc->liab_selling = (int64_t)ns;
    if (acc->ext_level < 1)
        acc->ext_level = 1;
    return 1;
}

/* mirror _add_liab for the trustline arm */
static int
tl_add_liab(CTrustLine *tl, int64_t d_buying, int64_t d_selling)
{
    i128 nb = (i128)tl->liab_buying + d_buying;
    i128 ns = (i128)tl->liab_selling + d_selling;
    if (nb < 0 || ns < 0)
        return 0;
    if (ns > tl->balance)
        return 0;
    if (nb > (i128)tl->limit - tl->balance)
        return 0;
    tl->liab_buying = (int64_t)nb;
    tl->liab_selling = (int64_t)ns;
    if (tl->ext_level < 1)
        tl->ext_level = 1;
    return 1;
}

/* load+mutate+store one liability adjustment for `acc`'s side of `asset`.
 * Returns 1 ok, 0 constraint violated, -1 engine error, 2 = trustline
 * missing (caller decides).  Issuers carry no liabilities. */
static int
adjust_side_liab(Engine *e, const uint8_t acc[32], const CAssetC *asset,
                 int64_t d_buying, int64_t d_selling)
{
    if (asset->type == 0) {
        CAccount a;
        int got = eng_get_account(e, acc, &a);
        if (got < 0)
            return -1;
        if (!got)
            return 0;
        if (!account_add_liab(&e->header, &a, d_buying, d_selling))
            return 0;
        return eng_put_account(e, e->cur, &a) < 0 ? -1 : 1;
    }
    if (is_issuer_asset(acc, asset))
        return 1;
    Buf kb = {0};
    if (trustline_key_xdr_c(acc, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    if (!rec) {
        PyMem_Free(kb.p);
        return 2;
    }
    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    if (!tl_add_liab(&tl, d_buying, d_selling)) {
        PyMem_Free(kb.p);
        return 0;
    }
    Buf eb = {0};
    int rc = -1;
    if (serialize_trustline_entry(&tl, &eb) == 0) {
        RB *val = rb_new(eb.p, eb.len);
        rc = (val && eng_put(e, e->cur, kb.p, kb.len, val) == 0)
             ? 1 : -1;
    }
    PyMem_Free(eb.p);
    PyMem_Free(kb.p);
    return rc;
}

/* mirror acquire_or_release_offer_liabilities: 1 ok / 0 failed / -1 err */
static int
offer_liabilities(Engine *e, const COffer *o, int acquire)
{
    int sign = acquire ? 1 : -1;
    int64_t selling_liab = offer_selling_liab_c(o->price_n, o->price_d,
                                                o->amount);
    int64_t buying_liab = offer_buying_liab_c(o->price_n, o->price_d,
                                              o->amount);
    int rc = adjust_side_liab(e, o->seller, &o->selling, 0,
                              sign * selling_liab);
    if (rc == 2)
        return 0;              /* missing non-issuer trustline */
    if (rc != 1)
        return rc;
    rc = adjust_side_liab(e, o->seller, &o->buying, sign * buying_liab, 0);
    if (rc == 2)
        return 0;
    return rc;
}

/* mirror _can_sell_at_most */
static int64_t
can_sell_at_most_c(Engine *e, const uint8_t acc[32], const CAssetC *asset)
{
    if (asset->type == 0) {
        CAccount a;
        if (eng_get_account(e, acc, &a) != 1)
            return 0;
        i128 avail = (i128)a.balance - min_balance_128(&e->header, &a)
                     - a.liab_selling;
        return avail > 0 ? (int64_t)avail : 0;
    }
    if (is_issuer_asset(acc, asset))
        return INT64_MAXV;
    Buf kb = {0};
    if (trustline_key_xdr_c(acc, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return 0;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int64_t out = 0;
    if (rec) {
        CTrustLine tl;
        if (parse_trustline_entry(rec->bytes, rec->len, &tl) == 0 &&
            (tl.flags & 1)) {
            int64_t v = tl.balance - tl.liab_selling;
            out = v > 0 ? v : 0;
        }
    }
    PyMem_Free(kb.p);
    return out;
}

/* mirror _can_buy_at_most */
static int64_t
can_buy_at_most_c(Engine *e, const uint8_t acc[32], const CAssetC *asset)
{
    if (asset->type == 0) {
        CAccount a;
        if (eng_get_account(e, acc, &a) != 1)
            return 0;
        i128 cap = (i128)INT64_MAXV - a.balance - a.liab_buying;
        return cap > 0 ? (int64_t)cap : 0;
    }
    if (is_issuer_asset(acc, asset))
        return INT64_MAXV;
    Buf kb = {0};
    if (trustline_key_xdr_c(acc, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return 0;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int64_t out = 0;
    if (rec) {
        CTrustLine tl;
        if (parse_trustline_entry(rec->bytes, rec->len, &tl) == 0 &&
            (tl.flags & 1)) {
            i128 v = (i128)tl.limit - tl.balance - tl.liab_buying;
            out = v > 0 ? (int64_t)v : 0;
        }
    }
    PyMem_Free(kb.p);
    return out;
}

/* mirror _transfer: 1 ok / 0 failed / -1 err */
static int
transfer_c(Engine *e, const uint8_t acc[32], const CAssetC *asset,
           int64_t delta)
{
    if (asset->type != 0 && is_issuer_asset(acc, asset))
        return 1;
    if (asset->type == 0) {
        CAccount a;
        int got = eng_get_account(e, acc, &a);
        if (got < 0)
            return -1;
        if (!got)
            return 0;
        if (!add_balance_c(&e->header, &a, delta, 1))
            return 0;
        return eng_put_account(e, e->cur, &a) < 0 ? -1 : 1;
    }
    Buf kb = {0};
    if (trustline_key_xdr_c(acc, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    if (!rec) {
        PyMem_Free(kb.p);
        return 0;
    }
    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    if (!add_tl_balance_c(&tl, delta)) {
        PyMem_Free(kb.p);
        return 0;
    }
    Buf eb = {0};
    int rc = -1;
    if (serialize_trustline_entry(&tl, &eb) == 0) {
        RB *val = rb_new(eb.p, eb.len);
        rc = (val && eng_put(e, e->cur, kb.p, kb.len, val) == 0)
             ? 1 : -1;
    }
    PyMem_Free(eb.p);
    PyMem_Free(kb.p);
    return rc;
}

/* ---- book scan + convertWithOffers ------------------------------------ */

typedef struct {
    COffer *offers;
    int n, cap;
} CBook;

static int
book_push(CBook *bk, const COffer *o)
{
    if (bk->n == bk->cap) {
        int nc = bk->cap ? bk->cap * 2 : 16;
        COffer *np = PyMem_Realloc(bk->offers, nc * sizeof(COffer));
        if (!np) { PyErr_NoMemory(); return -1; }
        bk->offers = np;
        bk->cap = nc;
    }
    bk->offers[bk->n++] = *o;
    return 0;
}

static int
offer_cmp(const void *pa, const void *pb)
{
    const COffer *a = pa, *b = pb;
    i128 lhs = (i128)a->price_n * b->price_d;
    i128 rhs = (i128)b->price_n * a->price_d;
    if (lhs != rhs)
        return lhs < rhs ? -1 : 1;
    if (a->offer_id != b->offer_id)
        return a->offer_id < b->offer_id ? -1 : 1;
    return 0;
}

/* all current offers selling `wheat` for `sheep`, sorted by (price,
 * offerID) — mirror load_best_offers over the 3-level overlay.  Caller
 * frees bk->offers. */
static int
scan_book(Engine *e, const CAssetC *wheat, const CAssetC *sheep, CBook *bk)
{
    memset(bk, 0, sizeof(*bk));
    Map seen;
    if (map_init(&seen, 256) < 0)
        return -1;
    Map *layers[5];
    int n_layers = 0;
    if (e->hop_active)
        layers[n_layers++] = &e->hop_delta;
    if (e->op_active)
        layers[n_layers++] = &e->op_delta;
    layers[n_layers++] = &e->tx_delta;
    layers[n_layers++] = &e->ledger_delta;
    layers[n_layers++] = &e->store;
    for (int li = 0; li < n_layers; li++) {
        Map *m = layers[li];
        for (int i = 0; i < m->cap; i++) {
            MapSlot *s = &m->slots[i];
            if (s->state != 1)
                continue;
            if (s->key->len < 4 || s->key->bytes[0] != 0 ||
                s->key->bytes[1] != 0 || s->key->bytes[2] != 0 ||
                s->key->bytes[3] != 2)
                continue;       /* not an OFFER key */
            int present;
            map_get(&seen, s->key->bytes, s->key->len, &present);
            if (present)
                continue;
            if (map_put(&seen, rb_ref(s->key), NULL) < 0)
                goto fail;
            RB *rec = eng_get(e, s->key->bytes, s->key->len);
            if (!rec)
                continue;       /* deleted in an upper layer */
            COffer o;
            if (parse_offer_entry(rec->bytes, rec->len, &o) < 0)
                goto fail;      /* corrupt stored offer: fail-stop */
            if (asset_eq(&o.selling, wheat) && asset_eq(&o.buying, sheep)) {
                if (book_push(bk, &o) < 0)
                    goto fail;
            }
        }
    }
    map_free(&seen);
    if (bk->n)
        qsort(bk->offers, bk->n, sizeof(COffer), offer_cmp);
    return 0;
fail:
    map_free(&seen);
    PyMem_Free(bk->offers);
    memset(bk, 0, sizeof(*bk));
    return -1;
}

/* erase an offer + subentry/sponsorship bookkeeping (mirror _erase_offer) */
static int
erase_offer_c(Engine *e, const COffer *o)
{
    uint8_t kx[48];
    offer_key_xdr_c(o->seller, o->offer_id, kx);
    /* re-read the CURRENT entry for its sponsor (o may be a snapshot) */
    RB *rec = eng_get(e, kx, 48);
    int sponsored = 0;
    uint8_t sponsor[32];
    if (rec) {
        COffer cur;
        if (parse_offer_entry(rec->bytes, rec->len, &cur) < 0)
            return -1;
        if (cur.entry_ext_v1 && cur.has_sponsor) {
            sponsored = 1;
            memcpy(sponsor, cur.sponsor, 32);
        }
    }
    if (eng_put(e, e->cur, kx, 48, NULL) < 0)
        return -1;
    CAccount acc;
    if (eng_get_account(e, o->seller, &acc) <= 0)
        return -1;
    if (sponsored) {
        if (release_entry_sponsor(e, sponsor, 1, &acc) < 0)
            return -1;
    }
    acc.num_sub -= 1;
    return eng_put_account(e, e->cur, &acc);
}

typedef struct {
    int result;                 /* CONVERT_OK/PARTIAL/FILTER_STOP */
    int self_cross;
    int64_t wheat_received;
    int64_t sheep_sent;
    Buf claims;                 /* concatenated ClaimAtom XDR */
    int n_claims;
} CCross;

#define CVT_OK 0
#define CVT_PARTIAL 1
#define CVT_FILTER_STOP 2

/* price_bound for manage-offer crossing (mirror `crossable`): maker.n *
 * price.n <= maker.d * price.d, strict when passive */
static int
crossable_c(const COffer *maker, int32_t pn, int32_t pd, int passive)
{
    i128 lhs = (i128)maker->price_n * pn;
    i128 rhs = (i128)maker->price_d * pd;
    if (lhs < rhs)
        return 1;
    return lhs == rhs && !passive;
}

/* mirror convert_with_offers.  bound_pn/pd < 0 disables the price bound.
 * Returns 0 ok / -1 engine error; *cr filled. */
static int
convert_with_offers_c(Engine *e, const CAssetC *sheep, const CAssetC *wheat,
                      int64_t max_wheat_receive, int64_t max_sheep_send,
                      const uint8_t taker[32], int rounding,
                      int32_t bound_pn, int32_t bound_pd, int passive,
                      CCross *cr)
{
    memset(cr, 0, sizeof(*cr));
    cr->result = CVT_OK;
    int64_t need_wheat = max_wheat_receive;
    int64_t have_sheep = max_sheep_send;
    CBook bk;
    if (scan_book(e, wheat, sheep, &bk) < 0)
        return -1;
    int rc = 0;
    for (int i = 0; i < bk.n; i++) {
        COffer *o = &bk.offers[i];
        if (need_wheat <= 0 || have_sheep <= 0)
            break;
        if (bound_pn >= 0 &&
            !crossable_c(o, bound_pn, bound_pd, passive)) {
            cr->result = CVT_FILTER_STOP;
            break;
        }
        if (memcmp(o->seller, taker, 32) == 0) {
            cr->self_cross = 1;
            cr->result = CVT_FILTER_STOP;
            break;
        }
        int lr = offer_liabilities(e, o, 0);     /* release */
        if (lr < 0) { rc = -1; break; }
        if (lr == 0)
            continue;          /* inconsistent offer: skip defensively */
        int64_t mws = can_sell_at_most_c(e, o->seller, wheat);
        if (o->amount < mws)
            mws = o->amount;
        int64_t msr = can_buy_at_most_c(e, o->seller, sheep);
        CExchange ex = exchange_v10_c(o->price_n, o->price_d, mws,
                                      need_wheat, have_sheep, msr,
                                      rounding);
        if (ex.wheat_received > 0) {
            if (transfer_c(e, o->seller, wheat, -ex.wheat_received) != 1 ||
                transfer_c(e, o->seller, sheep, ex.sheep_send) != 1) {
                rc = -1;       /* oracle asserts here: fail-stop */
                break;
            }
            /* ClaimAtom.orderBook */
            if (buf_u32(&cr->claims, 1) < 0 ||
                write_account_id(&cr->claims, o->seller) < 0 ||
                buf_i64(&cr->claims, o->offer_id) < 0 ||
                write_asset(&cr->claims, wheat) < 0 ||
                buf_i64(&cr->claims, ex.wheat_received) < 0 ||
                write_asset(&cr->claims, sheep) < 0 ||
                buf_i64(&cr->claims, ex.sheep_send) < 0) {
                rc = -1;
                break;
            }
            cr->n_claims++;
            cr->wheat_received += ex.wheat_received;
            cr->sheep_sent += ex.sheep_send;
            need_wheat -= ex.wheat_received;
            have_sheep -= ex.sheep_send;
        }
        if (ex.wheat_stays) {
            int64_t rem = o->amount - ex.wheat_received;
            int64_t cs = can_sell_at_most_c(e, o->seller, wheat);
            if (cs < rem)
                rem = cs;
            int64_t new_amount = adjust_offer_c(
                o->price_n, o->price_d, rem,
                can_buy_at_most_c(e, o->seller, sheep));
            if (new_amount > 0) {
                uint8_t kx[48];
                offer_key_xdr_c(o->seller, o->offer_id, kx);
                RB *rec = eng_get(e, kx, 48);
                if (!rec) { rc = -1; break; }
                COffer cur;
                if (parse_offer_entry(rec->bytes, rec->len, &cur) < 0) {
                    rc = -1;
                    break;
                }
                cur.amount = new_amount;
                Buf eb = {0};
                if (serialize_offer_entry(&cur, &eb) < 0) {
                    PyMem_Free(eb.p);
                    rc = -1;
                    break;
                }
                RB *val = rb_new(eb.p, eb.len);
                PyMem_Free(eb.p);
                if (!val || eng_put(e, e->cur, kx, 48, val) < 0) {
                    rc = -1;
                    break;
                }
                if (offer_liabilities(e, &cur, 1) != 1) {
                    rc = -1;   /* oracle asserts re-acquire succeeds */
                    break;
                }
            } else {
                if (erase_offer_c(e, o) < 0) { rc = -1; break; }
            }
            break;             /* taker exhausted */
        } else {
            if (erase_offer_c(e, o) < 0) { rc = -1; break; }
        }
    }
    PyMem_Free(bk.offers);
    if (rc < 0) {
        PyMem_Free(cr->claims.p);
        memset(cr, 0, sizeof(*cr));
        return -1;
    }
    if (need_wheat > 0 && have_sheep > 0 && cr->result == CVT_OK)
        cr->result = CVT_PARTIAL;
    return 0;
}

/* ---- manage-offer op family (mirror offer_ops._apply_manage) ---------- */

/* write the op-success result: opINNER + op_type + code0 +
 * ManageOfferSuccessResult{claims, offer-union} */
static int
manage_success(Buf *rb, int32_t op_type, const CCross *cr, int effect,
               const COffer *offer_body)
{
    if (buf_i32(rb, 0) < 0 || buf_i32(rb, op_type) < 0 ||
        buf_i32(rb, 0) < 0 ||
        buf_u32(rb, (uint32_t)cr->n_claims) < 0 ||
        buf_put(rb, cr->claims.p, cr->claims.len) < 0 ||
        buf_i32(rb, effect) < 0)
        return -1;
    if (effect != 2 && write_offer_body(offer_body, rb) < 0)
        return -1;
    return 0;
}

/* one _check_offer_valid side; returns 0 ok, else the failure already
 * written (1) or engine error (-1) */
static int
offer_side_valid(Engine *e, Buf *rb, int32_t op_type, const uint8_t src[32],
                 const CAssetC *asset, int no_trust, int not_auth)
{
    if (asset->type == 0 || is_issuer_asset(src, asset))
        return 0;
    Buf kb = {0};
    if (trustline_key_xdr_c(src, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int rc = 0;
    if (!rec) {
        rc = res_inner(rb, op_type, no_trust) < 0 ? -1 : 1;
    } else {
        CTrustLine tl;
        if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0)
            rc = -1;
        else if (!(tl.flags & 1))
            rc = res_inner(rb, op_type, not_auth) < 0 ? -1 : 1;
    }
    PyMem_Free(kb.p);
    return rc;
}

/* the shared create/update/delete + crossing flow.  is_buy carries the
 * ManageBuyOffer amount semantics (buy_amount + original buy price);
 * `pn/pd` is the STORED price (inverted for buy offers). */
static int
apply_manage_c(Engine *e, Buf *rb, int32_t op_type,
               const uint8_t src[32], const CAssetC *selling,
               const CAssetC *buying, int32_t pn, int32_t pd,
               int64_t offer_id, int64_t sell_amount, int passive,
               int is_buy, int64_t buy_amount, int32_t buy_pn,
               int32_t buy_pd)
{
    CHeader *h = &e->header;
    int rc = offer_side_valid(e, rb, op_type, src, selling, -2, -4);
    if (rc)
        return rc < 0 ? -1 : 0;
    rc = offer_side_valid(e, rb, op_type, src, buying, -3, -5);
    if (rc)
        return rc < 0 ? -1 : 0;

    int creating = offer_id == 0;
    COffer old;
    int old_ext_v1 = 0, old_sponsored = 0;
    uint8_t old_sponsor[32];
    uint8_t kx[48];
    if (!creating) {
        offer_key_xdr_c(src, offer_id, kx);
        RB *rec = eng_get(e, kx, 48);
        if (!rec)
            return res_inner(rb, op_type, -11) < 0 ? -1 : 0;  /* NOT_FOUND */
        if (parse_offer_entry(rec->bytes, rec->len, &old) < 0)
            return -1;
        old_ext_v1 = old.entry_ext_v1;
        old_sponsored = old.entry_ext_v1 && old.has_sponsor;
        if (old_sponsored)
            memcpy(old_sponsor, old.sponsor, 32);
        if (offer_liabilities(e, &old, 0) != 1)
            return -1;          /* oracle asserts the release succeeds */
        if (eng_put(e, e->cur, kx, 48, NULL) < 0)
            return -1;
        if (sell_amount == 0) {
            CAccount acc;
            if (eng_get_account(e, src, &acc) <= 0)
                return -1;
            if (old_sponsored) {
                if (release_entry_sponsor(e, old_sponsor, 1, &acc) < 0)
                    return -1;
            }
            acc.num_sub -= 1;
            if (eng_put_account(e, e->cur, &acc) < 0)
                return -1;
            CCross none;
            memset(&none, 0, sizeof(none));
            return manage_success(rb, op_type, &none, 2, NULL) < 0 ? -1 : 1;
        }
    }

    int64_t max_sheep = can_sell_at_most_c(e, src, selling);
    if (sell_amount < max_sheep)
        max_sheep = sell_amount;
    int64_t max_wheat;
    if (is_buy) {
        max_wheat = can_buy_at_most_c(e, src, buying);
        if (buy_amount < max_wheat)
            max_wheat = buy_amount;
    } else {
        max_wheat = can_buy_at_most_c(e, src, buying);
    }
    CCross cross;
    if (convert_with_offers_c(e, selling, buying, max_wheat, max_sheep,
                              src, RND_NORMAL, pn, pd, passive,
                              &cross) < 0)
        return -1;

#define MG_FAIL(code_) do { \
        int rr = res_inner(rb, op_type, (code_)); \
        PyMem_Free(cross.claims.p); \
        return rr < 0 ? -1 : 0; \
    } while (0)

    if (cross.self_cross)
        MG_FAIL(-8);                                  /* CROSS_SELF */
    rc = transfer_c(e, src, selling, -cross.sheep_sent);
    if (rc < 0) { PyMem_Free(cross.claims.p); return -1; }
    if (rc == 0)
        MG_FAIL(-7);                                  /* UNDERFUNDED */
    rc = transfer_c(e, src, buying, cross.wheat_received);
    if (rc < 0) { PyMem_Free(cross.claims.p); return -1; }
    if (rc == 0)
        MG_FAIL(-6);                                  /* LINE_FULL */

    i128 residual;
    if (is_buy) {
        i128 left = (i128)buy_amount - cross.wheat_received;
        residual = left <= 0 ? 0
            : ((left * buy_pn) + buy_pd - 1) / buy_pd;  /* ceil */
    } else {
        residual = (i128)sell_amount - cross.sheep_sent;
    }
    int effect = creating ? 0 : 1;                    /* CREATED : UPDATED */
    int64_t cs = can_sell_at_most_c(e, src, selling);
    int64_t bounded = residual < cs ? (int64_t)residual : cs;
    int64_t new_amount = adjust_offer_c(pn, pd, bounded,
                                        can_buy_at_most_c(e, src, buying));
    if (new_amount <= 0) {
        if (!creating) {
            CAccount acc;
            if (eng_get_account(e, src, &acc) <= 0) {
                PyMem_Free(cross.claims.p);
                return -1;
            }
            if (old_sponsored) {
                if (release_entry_sponsor(e, old_sponsor, 1, &acc) < 0) {
                    PyMem_Free(cross.claims.p);
                    return -1;
                }
            }
            acc.num_sub -= 1;
            if (eng_put_account(e, e->cur, &acc) < 0) {
                PyMem_Free(cross.claims.p);
                return -1;
            }
        }
        int rr = manage_success(rb, op_type, &cross, 2, NULL);
        PyMem_Free(cross.claims.p);
        return rr < 0 ? -1 : 1;
    }

    COffer off;
    memset(&off, 0, sizeof(off));
    off.last_modified = h->ledger_seq;
    memcpy(off.seller, src, 32);
    off.offer_id = offer_id;
    off.selling = *selling;
    off.buying = *buying;
    off.amount = new_amount;
    off.price_n = pn;
    off.price_d = pd;
    off.flags = passive ? 1 : 0;
    if (creating) {
        CAccount acc;
        if (eng_get_account(e, src, &acc) <= 0) {
            PyMem_Free(cross.claims.p);
            return -1;
        }
        const uint8_t *sp_id = h->ledger_version >= 14
            ? active_sponsor_c(e, src) : NULL;
        if (sp_id != NULL) {
            int sc = sponsorship_error_c(rb, op_type, -12,
                establish_sponsorship_c(e, sp_id, &acc, 1));
            if (sc) {
                PyMem_Free(cross.claims.p);
                return sc < 0 ? -1 : 0;
            }
            off.entry_ext_v1 = 1;
            off.has_sponsor = 1;
            memcpy(off.sponsor, sp_id, 32);
            acc.num_sub += 1;
        } else if (!add_num_entries_c(h, &acc, 1)) {
            MG_FAIL(-12);                             /* LOW_RESERVE */
        }
        if (eng_put_account(e, e->cur, &acc) < 0) {
            PyMem_Free(cross.claims.p);
            return -1;
        }
        h->id_pool += 1;
        off.offer_id = (int64_t)h->id_pool;
    } else if (old_ext_v1) {
        /* the oracle carries existing.ext VERBATIM (incl. a v1 ext with
         * a null sponsoringID) */
        off.entry_ext_v1 = 1;
        off.has_sponsor = old_sponsored;
        if (old_sponsored)
            memcpy(off.sponsor, old_sponsor, 32);
    }
    Buf eb = {0};
    if (serialize_offer_entry(&off, &eb) < 0) {
        PyMem_Free(eb.p);
        PyMem_Free(cross.claims.p);
        return -1;
    }
    offer_key_xdr_c(off.seller, off.offer_id, kx);
    RB *val = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    if (!val || eng_put(e, e->cur, kx, 48, val) < 0) {
        PyMem_Free(cross.claims.p);
        return -1;
    }
    rc = offer_liabilities(e, &off, 1);
    if (rc < 0) { PyMem_Free(cross.claims.p); return -1; }
    if (rc == 0)
        MG_FAIL(-6);                                  /* LINE_FULL */
    int rr = manage_success(rb, op_type, &cross, effect, &off);
    PyMem_Free(cross.claims.p);
    return rr < 0 ? -1 : 1;
#undef MG_FAIL
}

/* op frames: ManageSellOffer (3) / CreatePassiveSellOffer (4) /
 * ManageBuyOffer (12) */
static int
op_manage_offer(Engine *e, CTx *tx, COp *op, const uint8_t src[32],
                Buf *rb)
{
    (void)tx;
    int32_t op_type = op->op_type;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    CAssetC selling, buying;
    if (parse_asset(&r, &selling) < 0 || parse_asset(&r, &buying) < 0)
        return -1;
    int64_t amount = rd_i64(&r);
    int32_t pn = rd_i32(&r);
    int32_t pd = rd_i32(&r);
    int64_t offer_id = 0;
    if (op_type != 4)
        offer_id = rd_i64(&r);
    if (r.err)
        return -1;
    int passive = op_type == 4;
    int is_buy = op_type == 12;

    /* do_check_valid (per frame) */
    int price_ok = pn > 0 && pd > 0;
    int assets_ok = asset_valid_c(&selling) && asset_valid_c(&buying) &&
                    !asset_eq(&selling, &buying);
    int malformed;
    if (op_type == 4)
        malformed = amount <= 0 || !price_ok || !assets_ok;
    else
        malformed = amount < 0 || !price_ok || !assets_ok ||
                    offer_id < 0 || (amount == 0 && offer_id == 0);
    if (malformed)
        return res_inner(rb, op_type, -1) < 0 ? -1 : 0;

    int64_t sell_amount = amount;
    int32_t use_pn = pn, use_pd = pd;
    int64_t buy_amount = 0;
    if (is_buy) {
        buy_amount = amount;
        use_pn = pd;                      /* stored price is inverted */
        use_pd = pn;
        if (buy_amount == 0) {
            sell_amount = 0;
        } else {
            i128 sa = ((i128)buy_amount * pn + pd - 1) / pd;  /* ceil */
            if (sa > INT64_MAXV)
                return res_inner(rb, op_type, -1) < 0 ? -1 : 0;
            sell_amount = (int64_t)sa;
        }
    }
    return apply_manage_c(e, rb, op_type, src, &selling, &buying,
                          use_pn, use_pd, offer_id, sell_amount, passive,
                          is_buy, buy_amount, pn, pd);
}

/* ---- claimable balances (round 5) ------------------------------------- */

/* recursive ClaimPredicate walk: skip + structural bounds (depth <= 4,
 * AND/OR arity == 2 mirrors _predicate_valid; rel/abs >= 0 checked at
 * CREATE time only).  Returns 0 ok / -1 malformed. */
static int
skip_predicate(Rd *r, int depth)
{
    if (depth > 4)
        return -1;
    int32_t t = rd_i32(r);
    if (r->err)
        return -1;
    switch (t) {
    case 0:                                   /* UNCONDITIONAL */
        return 0;
    case 1: case 2: {                         /* AND / OR: vec<=2 */
        uint32_t n = rd_u32(r);
        if (r->err || n > 2)
            return -1;
        for (uint32_t i = 0; i < n; i++)
            if (skip_predicate(r, depth + 1) < 0)
                return -1;
        return 0;
    }
    case 3: {                                 /* NOT: optional */
        uint32_t p = rd_u32(r);
        if (r->err || p > 1)
            return -1;
        return p ? skip_predicate(r, depth + 1) : 0;
    }
    case 4: case 5:                           /* abs/rel before */
        rd_skip(r, 8);
        return r->err ? -1 : 0;
    default:
        return -1;
    }
}

/* _predicate_valid: structural rules for CREATE (arity exactly 2,
 * NOT non-null, times >= 0) */
static int
predicate_valid_c(Rd *r, int depth)
{
    if (depth > 4)
        return 0;
    int32_t t = rd_i32(r);
    if (r->err)
        return 0;
    switch (t) {
    case 0:
        return 1;
    case 1: case 2: {
        uint32_t n = rd_u32(r);
        if (r->err || n != 2)
            return 0;
        for (uint32_t i = 0; i < 2; i++)
            if (!predicate_valid_c(r, depth + 1))
                return 0;
        return 1;
    }
    case 3: {
        uint32_t p = rd_u32(r);
        if (r->err || p != 1)
            return 0;
        return predicate_valid_c(r, depth + 1);
    }
    case 4: case 5: {
        int64_t v = rd_i64(r);
        return !r->err && v >= 0;
    }
    default:
        return 0;
    }
}

/* predicate_satisfied(pred, close_time, created_time=0) */
static int
predicate_satisfied_c(Rd *r, uint64_t close_time)
{
    int32_t t = rd_i32(r);
    if (r->err)
        return 0;
    switch (t) {
    case 0:
        return 1;
    case 1: {                                 /* AND */
        uint32_t n = rd_u32(r);
        int ok = 1;
        for (uint32_t i = 0; i < n && !r->err; i++)
            if (!predicate_satisfied_c(r, close_time))
                ok = 0;
        return ok && !r->err;
    }
    case 2: {                                 /* OR */
        uint32_t n = rd_u32(r);
        int ok = 0;
        for (uint32_t i = 0; i < n && !r->err; i++)
            if (predicate_satisfied_c(r, close_time))
                ok = 1;
        return ok && !r->err;
    }
    case 3: {                                 /* NOT */
        uint32_t p = rd_u32(r);
        if (r->err || !p)
            return 0;          /* oracle: not predicate_satisfied(None) is
                                  unreachable for valid stored predicates */
        return !predicate_satisfied_c(r, close_time) && !r->err;
    }
    case 4: {                                 /* BEFORE_ABSOLUTE_TIME */
        int64_t v = rd_i64(r);
        return !r->err && (int64_t)close_time < v;
    }
    case 5: {                                 /* BEFORE_RELATIVE_TIME:
                                  created_time approximated as 0 */
        int64_t v = rd_i64(r);
        return !r->err && (int64_t)close_time < v;
    }
    default:
        return 0;
    }
}

/* mirror utils.add_num_sponsoring (incl. v2 materialization with padded
 * signerSponsoringIDs).  Returns 1 ok / 0 reserve-or-underflow fail. */
static int
add_num_sponsoring_c(const CHeader *h, CAccount *a, int delta)
{
    i128 nc = (i128)a->num_sponsoring + delta;
    if (nc < 0)
        return 0;
    if (delta > 0) {
        i128 need = ((i128)2 + a->num_sub + nc - a->num_sponsored)
                    * (i128)h->base_reserve;
        if ((i128)a->balance < need + a->liab_selling)
            return 0;
    }
    if (a->ext_level < 2) {
        a->ext_level = 2;
        while (a->n_ssids < a->n_signers) {
            a->ssids[a->n_ssids].present = 0;
            a->n_ssids++;
        }
    }
    a->num_sponsoring = (uint32_t)nc;
    return 1;
}

/* release the CB's per-claimant reserve from its recorded sponsor
 * (mirror _release_claimable_balance_reserve) — the shared
 * release_entry_sponsor already implements the load / missing-no-op /
 * underflow-fail-stop / decrement / store sequence. */
static int
release_cb_reserve(Engine *e, const uint8_t sponsor[32], int n_claimants)
{
    return release_entry_sponsor(e, sponsor, n_claimants, NULL) < 0
        ? -1 : 1;
}

/* parsed view of a stored ClaimableBalanceEntry (claimant slices kept
 * raw; asset parsed; ext sponsor from the LedgerEntry wrapper) */
typedef struct {
    uint8_t balance_id[32];
    int n_claimants;
    struct { uint8_t dest[32]; const uint8_t *pred; int pred_len; }
        claimants[10];
    CAssetC asset;
    int64_t amount;
    uint32_t cb_flags;          /* ext v1 flags, 0 when v0 */
    int has_sponsor;
    uint8_t sponsor[32];
} CClaimable;

static int
parse_cb_entry(const uint8_t *data, int len, CClaimable *cb)
{
    memset(cb, 0, sizeof(*cb));
    Rd r;
    rd_init(&r, data, len);
    rd_skip(&r, 4);                           /* lastModified */
    if (rd_u32(&r) != 4 || r.err)             /* data tag CLAIMABLE_BALANCE */
        return -1;
    if (rd_u32(&r) != 0 || r.err)             /* balanceID v0 */
        return -1;
    const uint8_t *bid = rd_take(&r, 32);
    if (!bid)
        return -1;
    memcpy(cb->balance_id, bid, 32);
    uint32_t nc = rd_u32(&r);
    if (r.err || nc > 10)
        return -1;
    cb->n_claimants = (int)nc;
    for (uint32_t i = 0; i < nc; i++) {
        if (rd_u32(&r) != 0 || r.err)         /* CLAIMANT_TYPE_V0 */
            return -1;
        if (parse_account_id(&r, cb->claimants[i].dest) < 0)
            return -1;
        int pstart = r.off;
        if (skip_predicate(&r, 0) < 0)
            return -1;
        cb->claimants[i].pred = data + pstart;
        cb->claimants[i].pred_len = r.off - pstart;
    }
    if (parse_asset(&r, &cb->asset) < 0)
        return -1;
    cb->amount = rd_i64(&r);
    int32_t ext = rd_i32(&r);
    if (r.err || (ext != 0 && ext != 1))
        return -1;
    if (ext == 1) {
        if (rd_i32(&r) != 0 || r.err)         /* v1 ext v0 */
            return -1;
        cb->cb_flags = rd_u32(&r);
    }
    int32_t lext = rd_i32(&r);
    if (r.err || (lext != 0 && lext != 1))
        return -1;
    if (lext == 1) {
        uint32_t sp = rd_u32(&r);
        if (r.err || sp > 1)
            return -1;
        cb->has_sponsor = (int)sp;
        if (sp && parse_account_id(&r, cb->sponsor) < 0)
            return -1;
        if (rd_i32(&r) != 0 || r.err)
            return -1;
    }
    return (r.err || r.off != r.len) ? -1 : 0;
}

/* cb LedgerKey: tag 4 + ClaimableBalanceID (tag 0 + hash) */
static void
cb_key_xdr_c(const uint8_t bid[32], uint8_t out[40])
{
    memset(out, 0, 8);
    out[3] = 4;
    memcpy(out + 8, bid, 32);
}

/* mirror CreateClaimableBalanceOpFrame (v14+, MED threshold) */
static int
op_create_cb(Engine *e, CTx *tx, COp *op, int op_index,
             const uint8_t src[32], Buf *rb)
{
    CHeader *h = &e->header;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    CAssetC asset;
    if (parse_asset(&r, &asset) < 0)
        return -1;
    int64_t amount = rd_i64(&r);
    uint32_t nc = rd_u32(&r);
    if (r.err || nc > 10)
        return -1;
    uint8_t dests[10][32];
    const uint8_t *claimants_start = op->body + r.off;
    int preds_valid = 1;
    for (uint32_t i = 0; i < nc; i++) {
        if (rd_u32(&r) != 0 || r.err)
            return -1;
        if (parse_account_id(&r, dests[i]) < 0)
            return -1;
        Rd pr = r;                        /* validate from here */
        if (!predicate_valid_c(&pr, 0))
            preds_valid = 0;
        if (skip_predicate(&r, 0) < 0)
            return -1;
    }
    int claimants_len = (int)(op->body + r.off - claimants_start);
    if (r.err)
        return -1;

    /* do_check_valid: amount>0, asset valid, claimants nonempty+unique,
     * predicates structurally valid */
    int malformed = amount <= 0 || !asset_valid_c(&asset) || nc == 0 ||
                    !preds_valid;
    for (uint32_t i = 0; !malformed && i < nc; i++)
        for (uint32_t j = i + 1; j < nc; j++)
            if (memcmp(dests[i], dests[j], 32) == 0) {
                malformed = 1;
                break;
            }
    if (malformed)
        return res_inner(rb, 14, -1) < 0 ? -1 : 0;   /* MALFORMED */

    /* reserve for claimants is a sponsored reserve: the sandwich sponsor
     * takes it when one is active for the source, else the source
     * sponsors its own creation (mirror CreateClaimableBalanceOpFrame) */
    const uint8_t *cb_sponsor = active_sponsor_c(e, src);
    if (cb_sponsor != NULL) {
        int sc = sponsorship_error_c(rb, 14, -2,
            establish_sponsorship_c(e, cb_sponsor, NULL, (int)nc));
        if (sc)
            return sc < 0 ? -1 : 0;
    }
    CAccount srca;
    if (eng_get_account(e, src, &srca) <= 0)
        return -1;
    if (cb_sponsor == NULL) {
        cb_sponsor = src;
        if (!add_num_sponsoring_c(h, &srca, (int)nc))
            return res_inner(rb, 14, -2) < 0 ? -1 : 0;   /* LOW_RESERVE */
    }
    if (asset.type == 0) {
        if (!add_balance_c(h, &srca, -amount, 1))
            return res_inner(rb, 14, -5) < 0 ? -1 : 0;  /* UNDERFUNDED */
    } else if (!is_issuer_asset(src, &asset)) {
        /* write the sponsoring-count change FIRST so the trustline arm's
         * failure codes match the oracle's sequencing (oracle mutates the
         * same src_e object; both sides commit only on success) */
        Buf kb = {0};
        if (trustline_key_xdr_c(src, asset.type, asset.code, asset.issuer,
                                &kb) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        RB *rec = eng_get(e, kb.p, kb.len);
        if (!rec) {
            PyMem_Free(kb.p);
            return res_inner(rb, 14, -3) < 0 ? -1 : 0;  /* NO_TRUST */
        }
        CTrustLine tl;
        if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        if (!(tl.flags & 1)) {
            PyMem_Free(kb.p);
            return res_inner(rb, 14, -4) < 0 ? -1 : 0;  /* NOT_AUTHORIZED */
        }
        if (!add_tl_balance_c(&tl, -amount)) {
            PyMem_Free(kb.p);
            return res_inner(rb, 14, -5) < 0 ? -1 : 0;  /* UNDERFUNDED */
        }
        int rc = store_trustline(e, &kb, &tl, rb, 14);
        if (rc < 0)
            return -1;
        /* store_trustline wrote a success result we don't want yet —
         * rewind it (12 bytes: opINNER + type + code); the clawback flag
         * comes from the re-probe below, mirroring the oracle's second
         * load_trustline */
        rb->len -= 12;
    }

    /* balanceID = sha256(HashIDPreimage.operationID) with the TX source */
    Buf pre = {0};
    uint8_t bid[32];
    if (buf_u32(&pre, 6) < 0 ||                   /* ENVELOPE_TYPE_OP_ID */
        write_account_id(&pre, tx->source) < 0 ||
        buf_i64(&pre, tx->seq_num) < 0 ||
        buf_u32(&pre, (uint32_t)op_index) < 0) {
        PyMem_Free(pre.p);
        return -1;
    }
    sha256_of(pre.p, pre.len, bid);
    PyMem_Free(pre.p);

    /* clawback flag propagates from the source trustline (re-probe the
     * CURRENT state, as the oracle does) */
    uint32_t cb_flags = 0;
    if (asset.type != 0 && !is_issuer_asset(src, &asset)) {
        Buf kb = {0};
        if (trustline_key_xdr_c(src, asset.type, asset.code, asset.issuer,
                                &kb) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        RB *rec = eng_get(e, kb.p, kb.len);
        PyMem_Free(kb.p);
        if (rec) {
            CTrustLine tl;
            if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0)
                return -1;
            if (tl.flags & 4u)
                cb_flags = 1;      /* CLAIMABLE_BALANCE_CLAWBACK_ENABLED */
        }
    }
    srca.last_modified = h->ledger_seq;
    if (eng_put_account(e, e->cur, &srca) < 0)
        return -1;
    /* build the CB LedgerEntry */
    Buf eb = {0};
    if (buf_u32(&eb, h->ledger_seq) < 0 || buf_u32(&eb, 4) < 0 ||
        buf_u32(&eb, 0) < 0 || buf_put(&eb, bid, 32) < 0 ||
        buf_u32(&eb, nc) < 0 ||
        buf_put(&eb, claimants_start, claimants_len) < 0 ||
        write_asset(&eb, &asset) < 0 ||
        buf_i64(&eb, amount) < 0) {
        PyMem_Free(eb.p);
        return -1;
    }
    if (cb_flags) {
        if (buf_i32(&eb, 1) < 0 || buf_i32(&eb, 0) < 0 ||
            buf_u32(&eb, cb_flags) < 0) {
            PyMem_Free(eb.p);
            return -1;
        }
    } else if (buf_i32(&eb, 0) < 0) {
        PyMem_Free(eb.p);
        return -1;
    }
    /* LedgerEntry ext v1 with sponsoringID = sandwich sponsor or source */
    if (buf_i32(&eb, 1) < 0 || buf_u32(&eb, 1) < 0 ||
        write_account_id(&eb, cb_sponsor) < 0 || buf_i32(&eb, 0) < 0) {
        PyMem_Free(eb.p);
        return -1;
    }
    uint8_t kx[40];
    cb_key_xdr_c(bid, kx);
    RB *val = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    if (!val || eng_put(e, e->cur, kx, 40, val) < 0)
        return -1;
    /* success carries the balance id */
    if (buf_i32(rb, 0) < 0 || buf_i32(rb, 14) < 0 || buf_i32(rb, 0) < 0 ||
        buf_u32(rb, 0) < 0 || buf_put(rb, bid, 32) < 0)
        return -1;
    return 1;
}

/* mirror ClaimClaimableBalanceOpFrame (v14+) */
static int
op_claim_cb(Engine *e, CTx *tx, COp *op, const uint8_t src[32], Buf *rb)
{
    (void)tx;
    CHeader *h = &e->header;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    if (rd_u32(&r) != 0 || r.err)             /* balanceID v0 */
        return -1;
    const uint8_t *bid = rd_take(&r, 32);
    if (!bid || r.err)
        return -1;
    uint8_t kx[40];
    cb_key_xdr_c(bid, kx);
    RB *rec = eng_get(e, kx, 40);
    if (!rec)
        return res_inner(rb, 15, -1) < 0 ? -1 : 0;  /* DOES_NOT_EXIST */
    CClaimable cb;
    if (parse_cb_entry(rec->bytes, rec->len, &cb) < 0)
        return -1;
    int ci = -1;
    for (int i = 0; i < cb.n_claimants; i++)
        if (memcmp(cb.claimants[i].dest, src, 32) == 0) {
            ci = i;
            break;
        }
    int satisfied = 0;
    if (ci >= 0) {
        Rd pr;
        rd_init(&pr, cb.claimants[ci].pred, cb.claimants[ci].pred_len);
        satisfied = predicate_satisfied_c(&pr, h->close_time);
    }
    if (ci < 0 || !satisfied)
        return res_inner(rb, 15, -2) < 0 ? -1 : 0;  /* CANNOT_CLAIM */
    if (cb.asset.type == 0) {
        CAccount acc;
        if (eng_get_account(e, src, &acc) <= 0)
            return -1;
        if (!add_balance_c(h, &acc, cb.amount, 0))
            return res_inner(rb, 15, -3) < 0 ? -1 : 0;  /* LINE_FULL */
        acc.last_modified = h->ledger_seq;
        if (eng_put_account(e, e->cur, &acc) < 0)
            return -1;
    } else if (!is_issuer_asset(src, &cb.asset)) {
        Buf kb = {0};
        if (trustline_key_xdr_c(src, cb.asset.type, cb.asset.code,
                                cb.asset.issuer, &kb) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        RB *trec = eng_get(e, kb.p, kb.len);
        if (!trec) {
            PyMem_Free(kb.p);
            return res_inner(rb, 15, -4) < 0 ? -1 : 0;  /* NO_TRUST */
        }
        CTrustLine tl;
        if (parse_trustline_entry(trec->bytes, trec->len, &tl) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        if (!(tl.flags & 1)) {
            PyMem_Free(kb.p);
            return res_inner(rb, 15, -5) < 0 ? -1 : 0;  /* NOT_AUTHORIZED */
        }
        if (!add_tl_balance_c(&tl, cb.amount)) {
            PyMem_Free(kb.p);
            return res_inner(rb, 15, -3) < 0 ? -1 : 0;  /* LINE_FULL */
        }
        int rc = store_trustline(e, &kb, &tl, rb, 15);
        if (rc < 0)
            return -1;
        rb->len -= 12;            /* rewind the helper's success result */
    }
    if (cb.has_sponsor) {
        if (release_cb_reserve(e, cb.sponsor, cb.n_claimants) < 0)
            return -1;
    }
    if (eng_put(e, e->cur, kx, 40, NULL) < 0)
        return -1;
    return res_inner(rb, 15, 0) < 0 ? -1 : 1;
}

/* mirror ClawbackClaimableBalanceOpFrame (v17+) */
static int
op_clawback_cb(Engine *e, CTx *tx, COp *op, const uint8_t src[32], Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    if (rd_u32(&r) != 0 || r.err)
        return -1;
    const uint8_t *bid = rd_take(&r, 32);
    if (!bid || r.err)
        return -1;
    uint8_t kx[40];
    cb_key_xdr_c(bid, kx);
    RB *rec = eng_get(e, kx, 40);
    if (!rec)
        return res_inner(rb, 20, -1) < 0 ? -1 : 0;  /* DOES_NOT_EXIST */
    CClaimable cb;
    if (parse_cb_entry(rec->bytes, rec->len, &cb) < 0)
        return -1;
    if (!is_issuer_asset(src, &cb.asset))
        return res_inner(rb, 20, -2) < 0 ? -1 : 0;  /* NOT_ISSUER */
    if (!(cb.cb_flags & 1u))
        return res_inner(rb, 20, -3) < 0 ? -1 : 0;  /* NOT_CLAWBACK_ENABLED */
    if (cb.has_sponsor) {
        if (release_cb_reserve(e, cb.sponsor, cb.n_claimants) < 0)
            return -1;
    }
    if (eng_put(e, e->cur, kx, 40, NULL) < 0)
        return -1;
    return res_inner(rb, 20, 0) < 0 ? -1 : 1;
}

/* ---- CAP-33 sponsorship core (round 12) -------------------------------- *
 *
 * Mirrors transactions/sponsorship.py: establish/release move the
 * sponsor's numSponsoring (sponsor loaded and stored HERE — callers must
 * not hold a copy of it across the call) and the owner's numSponsored
 * (mutated in the caller's CAccount, stored by the caller), exactly the
 * load/update sequencing of the oracle.
 */

/* materialize the v1+v2 extension chain (mirror _ensure_acc_ext_v2):
 * signerSponsoringIDs padded to the signer count on v2 materialization */
static void
acc_ensure_v2(CAccount *a)
{
    if (a->ext_level < 1)
        a->ext_level = 1;               /* liabilities start zeroed */
    if (a->ext_level < 2) {
        a->ext_level = 2;
        while (a->n_ssids < a->n_signers) {
            a->ssids[a->n_ssids].present = 0;
            a->n_ssids++;
        }
    }
}

/* mirror establish_sponsorship: SP_SUCCESS / SP_LOW_RESERVE / SP_TOO_MANY
 * or -1 on engine error (missing sponsor = corrupt state, like the
 * oracle's RuntimeError) */
static int
establish_sponsorship_c(Engine *e, const uint8_t sponsor_id[32],
                        CAccount *owner, int mult)
{
    CHeader *h = &e->header;
    CAccount sp;
    int got = eng_get_account(e, sponsor_id, &sp);
    if (got <= 0)
        return -1;
    if (sp.num_sponsoring > 0xFFFFFFFFu - (uint32_t)mult)
        return SP_TOO_MANY;
    i128 need = ((i128)2 + sp.num_sub + sp.num_sponsoring + mult
                 - sp.num_sponsored) * (i128)h->base_reserve;
    if ((i128)sp.balance < need + sp.liab_selling)
        return SP_LOW_RESERVE;
    if (owner != NULL &&
        owner->num_sponsored > 0xFFFFFFFFu - (uint32_t)mult)
        return SP_TOO_MANY;
    acc_ensure_v2(&sp);
    sp.num_sponsoring += (uint32_t)mult;
    sp.last_modified = h->ledger_seq;
    if (eng_put_account(e, e->cur, &sp) < 0)
        return -1;
    if (owner != NULL) {
        acc_ensure_v2(owner);
        owner->num_sponsored += (uint32_t)mult;
    }
    return SP_SUCCESS;
}

/* map a SponsorshipResult into the op result stream: 0 = success
 * (nothing written), 1 = failure result written, -1 = engine error.
 * TOO_MANY maps to the outer opTOO_MANY_SPONSORING (mirror
 * OperationFrame.sponsorship_error). */
static int
sponsorship_error_c(Buf *rb, int32_t op_type, int32_t low_code, int code)
{
    (void)op_type;
    if (code < 0)
        return -1;
    if (code == SP_SUCCESS)
        return 0;
    if (code == SP_LOW_RESERVE)
        return res_inner(rb, op_type, low_code) < 0 ? -1 : 1;
    return res_outer(rb, -6) < 0 ? -1 : 1;   /* opTOO_MANY_SPONSORING */
}

/* mirror owner_can_afford: after taking back `mult` reserve units, does
 * the owner's balance still cover its minimum? */
static int
owner_can_afford_c(const CHeader *h, const CAccount *a, int mult)
{
    i128 need = ((i128)2 + a->num_sub + a->num_sponsoring
                 - ((i128)a->num_sponsored - mult)) * (i128)h->base_reserve;
    return (i128)a->balance >= need + a->liab_selling;
}

/* ---- Begin/End/RevokeSponsorship op frames ----------------------------- */

/* mirror BeginSponsoringFutureReservesOpFrame (v14+, MED threshold) */
static int
op_begin_sponsoring(Engine *e, CTx *tx, COp *op, const uint8_t src[32],
                    Buf *rb)
{
    (void)tx;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    rd_skip(&r, 4);                           /* PK type */
    const uint8_t *sponsored = rd_take(&r, 32);
    if (!sponsored || r.err)
        return -1;
    /* do_check_valid */
    if (memcmp(sponsored, src, 32) == 0)
        return res_inner(rb, 16, -1) < 0 ? -1 : 0;      /* MALFORMED */
    /* do_apply (ctx mutations only on success — no rollback needed) */
    for (int i = 0; i < e->n_sandwich; i++)
        if (memcmp(e->sandwich[i].sponsored, sponsored, 32) == 0)
            return res_inner(rb, 16, -2) < 0 ? -1 : 0;  /* ALREADY_SPONSORED */
    for (int i = 0; i < e->n_sandwich; i++)
        if (memcmp(e->sandwich[i].sponsored, src, 32) == 0)
            return res_inner(rb, 16, -3) < 0 ? -1 : 0;  /* RECURSIVE */
    for (int i = 0; i < e->n_sandwich; i++)
        if (memcmp(e->sandwich[i].sponsor, sponsored, 32) == 0)
            return res_inner(rb, 16, -3) < 0 ? -1 : 0;  /* RECURSIVE */
    if (e->n_sandwich >= MAX_OPS)
        return -1;                     /* unreachable: one Begin per op */
    memcpy(e->sandwich[e->n_sandwich].sponsored, sponsored, 32);
    memcpy(e->sandwich[e->n_sandwich].sponsor, src, 32);
    e->n_sandwich++;
    return res_inner(rb, 16, 0) < 0 ? -1 : 1;
}

/* mirror EndSponsoringFutureReservesOpFrame (v14+) */
static int
op_end_sponsoring(Engine *e, CTx *tx, COp *op, const uint8_t src[32],
                  Buf *rb)
{
    (void)tx;
    (void)op;
    for (int i = 0; i < e->n_sandwich; i++) {
        if (memcmp(e->sandwich[i].sponsored, src, 32) == 0) {
            for (int j = i; j + 1 < e->n_sandwich; j++)
                e->sandwich[j] = e->sandwich[j + 1];
            e->n_sandwich--;
            return res_inner(rb, 17, 0) < 0 ? -1 : 1;
        }
    }
    return res_inner(rb, 17, -1) < 0 ? -1 : 0;   /* NOT_SPONSORED */
}

/* Walk a stored LedgerEntry record to its LedgerEntry-level ext (the
 * suffix).  Fills ext_off / has_sponsor / sponsor; returns the entry
 * type, or -1 on malformed bytes (fail-stop: stored state is trusted). */
static int
walk_entry_ext(const uint8_t *rec, int len, int *ext_off,
               int *has_sponsor, uint8_t sponsor[32])
{
    Rd r;
    rd_init(&r, rec, len);
    rd_skip(&r, 4);                           /* lastModified */
    int32_t t = rd_i32(&r);
    if (r.err)
        return -1;
    switch (t) {
    case 0: {                                 /* ACCOUNT */
        rd_skip(&r, 36 + 8 + 8 + 4);
        uint32_t hi = rd_u32(&r);
        if (r.err || hi > 1) return -1;
        if (hi) rd_skip(&r, 36);
        rd_skip(&r, 4);                       /* flags */
        uint32_t hl;
        if (!rd_varopaque(&r, 32, &hl)) return -1;
        rd_skip(&r, 4);                       /* thresholds */
        uint32_t ns = rd_u32(&r);
        if (r.err || ns > 20) return -1;
        for (uint32_t i = 0; i < ns; i++) {
            CSigner sg;
            if (parse_signer_key(&r, &sg) < 0) return -1;
            rd_skip(&r, 4);
        }
        int32_t ext = rd_i32(&r);
        if (r.err || (ext != 0 && ext != 1)) return -1;
        if (ext == 1) {
            rd_skip(&r, 16);                  /* liabilities */
            int32_t e1 = rd_i32(&r);
            if (r.err || (e1 != 0 && e1 != 2)) return -1;
            if (e1 == 2) {
                rd_skip(&r, 8);               /* numSponsored/ing */
                uint32_t nss = rd_u32(&r);
                if (r.err || nss > 20) return -1;
                for (uint32_t i = 0; i < nss; i++) {
                    uint32_t p = rd_u32(&r);
                    if (r.err || p > 1) return -1;
                    if (p) rd_skip(&r, 36);
                }
                int32_t e2 = rd_i32(&r);
                if (r.err || (e2 != 0 && e2 != 3)) return -1;
                if (e2 == 3) rd_skip(&r, 4 + 4 + 8);
            }
        }
        break;
    }
    case 1: {                                 /* TRUSTLINE */
        rd_skip(&r, 36);
        uint32_t at = rd_u32(&r);
        if (r.err) return -1;
        if (at == 1 || at == 2) {
            rd_skip(&r, at == 1 ? 4 : 12);
            if (rd_u32(&r) != 0) return -1;
            rd_skip(&r, 32);
        } else if (at == 3) {
            rd_skip(&r, 32);
        } else
            return -1;
        rd_skip(&r, 8 + 8 + 4);
        int32_t ext = rd_i32(&r);
        if (r.err || (ext != 0 && ext != 1)) return -1;
        if (ext == 1) {
            rd_skip(&r, 16);
            int32_t e1 = rd_i32(&r);
            if (r.err || (e1 != 0 && e1 != 2)) return -1;
            if (e1 == 2) {
                rd_skip(&r, 4);
                if (rd_i32(&r) != 0 || r.err) return -1;
            }
        }
        break;
    }
    case 2: {                                 /* OFFER */
        rd_skip(&r, 36 + 8);
        if (skip_asset(&r) < 0 || skip_asset(&r) < 0) return -1;
        rd_skip(&r, 8 + 8 + 4);
        if (rd_i32(&r) != 0 || r.err) return -1;
        break;
    }
    case 3: {                                 /* DATA */
        rd_skip(&r, 36);
        uint32_t nl, vl;
        if (!rd_varopaque(&r, 64, &nl) || !rd_varopaque(&r, 64, &vl))
            return -1;
        if (rd_i32(&r) != 0 || r.err) return -1;
        break;
    }
    case 4: {                                 /* CLAIMABLE_BALANCE */
        if (rd_u32(&r) != 0 || r.err) return -1;   /* bid v0 */
        rd_skip(&r, 32);
        uint32_t nc = rd_u32(&r);
        if (r.err || nc > 10) return -1;
        for (uint32_t i = 0; i < nc; i++) {
            if (rd_u32(&r) != 0 || r.err) return -1;
            if (rd_u32(&r) != 0 || r.err) return -1;
            rd_skip(&r, 32);
            if (skip_predicate(&r, 0) < 0) return -1;
        }
        if (skip_asset(&r) < 0) return -1;
        rd_skip(&r, 8);
        int32_t ext = rd_i32(&r);
        if (r.err || (ext != 0 && ext != 1)) return -1;
        if (ext == 1) {
            if (rd_i32(&r) != 0 || r.err) return -1;
            rd_skip(&r, 4);                   /* flags */
        }
        break;
    }
    case 5: {                                 /* LIQUIDITY_POOL */
        rd_skip(&r, 32);
        if (rd_u32(&r) != 0 || r.err) return -1;
        if (skip_asset(&r) < 0 || skip_asset(&r) < 0) return -1;
        rd_skip(&r, 4 + 8 + 8 + 8 + 8);
        break;
    }
    default:
        return -1;
    }
    *ext_off = r.off;
    int32_t lext = rd_i32(&r);
    if (r.err || (lext != 0 && lext != 1)) return -1;
    *has_sponsor = 0;
    if (lext == 1) {
        uint32_t sp = rd_u32(&r);
        if (r.err || sp > 1) return -1;
        if (sp) {
            if (rd_u32(&r) != 0 || r.err) return -1;
            const uint8_t *q = rd_take(&r, 32);
            if (!q) return -1;
            memcpy(sponsor, q, 32);
            *has_sponsor = 1;
        }
        if (rd_i32(&r) != 0 || r.err) return -1;
    }
    return r.off == len ? t : -1;
}

/* store a copy of `rec` with lastModified = seq and the LedgerEntry-level
 * ext replaced */
static int
store_entry_with_ext(Engine *e, const uint8_t *key, int klen,
                     const RB *rec, int ext_off, int has_sponsor,
                     const uint8_t sponsor[32])
{
    CHeader *h = &e->header;
    Buf b = {0};
    if (buf_u32(&b, h->ledger_seq) < 0 ||
        buf_put(&b, rec->bytes + 4, ext_off - 4) < 0)
        goto fail;
    if (has_sponsor) {
        if (buf_i32(&b, 1) < 0 || buf_u32(&b, 1) < 0 ||
            write_account_id(&b, sponsor) < 0 || buf_i32(&b, 0) < 0)
            goto fail;
    } else if (buf_i32(&b, 0) < 0)
        goto fail;
    RB *val = rb_new(b.p, b.len);
    PyMem_Free(b.p);
    if (!val || eng_put(e, e->cur, key, klen, val) < 0)
        return -1;
    return 0;
fail:
    PyMem_Free(b.p);
    return -1;
}

/* reserve units a stored entry pins (mirror compute_multiplier): 2 for
 * an account, #claimants for a claimable balance, 2 for a pool-share
 * trustline, 1 otherwise */
static int
entry_multiplier(const RB *rec, int type)
{
    if (type == 0)
        return 2;
    if (type == 1) {
        /* TrustLineAsset tag sits after lastMod(4)+tag(4)+accountID(36) */
        if (rec->len >= 48 && rec->bytes[47] == 3 && rec->bytes[46] == 0 &&
            rec->bytes[45] == 0 && rec->bytes[44] == 0)
            return 2;                         /* pool share */
        return 1;
    }
    if (type == 4) {
        /* claimant count after lastMod(4)+tag(4)+bidV0(4)+hash(32) */
        if (rec->len < 48)
            return 1;
        return (int)(((uint32_t)rec->bytes[44] << 24) |
                     ((uint32_t)rec->bytes[45] << 16) |
                     ((uint32_t)rec->bytes[46] << 8) | rec->bytes[47]);
    }
    return 1;
}

/* mirror RevokeSponsorshipOpFrame (v14+, MED threshold) */
static int
op_revoke_sponsorship(Engine *e, CTx *tx, COp *op, const uint8_t src[32],
                      Buf *rb)
{
    (void)tx;
    CHeader *h = &e->header;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    uint32_t arm = rd_u32(&r);
    if (r.err)
        return -1;

    if (arm == 1) {                           /* SIGNER arm */
        uint8_t acc_id[32];
        if (parse_account_id(&r, acc_id) < 0)
            return -1;
        CSigner want;
        if (parse_signer_key(&r, &want) < 0 || r.err)
            return -1;
        CAccount acc;
        int got = eng_get_account(e, acc_id, &acc);
        if (got < 0)
            return -1;
        if (!got)
            return res_inner(rb, 18, -1) < 0 ? -1 : 0;  /* DOES_NOT_EXIST */
        uint8_t want_kx[104];
        int want_klen = signer_key_xdr(&want, want_kx);
        int idx = -1;
        for (int i = 0; i < acc.n_signers; i++) {
            uint8_t kx[104];
            int klen = signer_key_xdr(&acc.signers[i], kx);
            if (klen == want_klen && memcmp(kx, want_kx, klen) == 0) {
                idx = i;
                break;
            }
        }
        if (idx < 0)
            return res_inner(rb, 18, -1) < 0 ? -1 : 0;  /* DOES_NOT_EXIST */
        int old_sp = acc.ext_level >= 2 && idx < acc.n_ssids &&
                     acc.ssids[idx].present;
        uint8_t old_sponsor[32];
        if (old_sp)
            memcpy(old_sponsor, acc.ssids[idx].id, 32);
        const uint8_t *new_sp = active_sponsor_c(e, src);
        if (new_sp != NULL && memcmp(new_sp, acc_id, 32) == 0)
            new_sp = NULL;          /* owner reclaiming its own reserve */
        if (old_sp) {
            if (memcmp(src, old_sponsor, 32) != 0)
                return res_inner(rb, 18, -2) < 0 ? -1 : 0;  /* NOT_SPONSOR */
        } else if (memcmp(src, acc_id, 32) != 0) {
            return res_inner(rb, 18, -2) < 0 ? -1 : 0;      /* NOT_SPONSOR */
        }
        if ((old_sp && new_sp != NULL &&
             memcmp(old_sponsor, new_sp, 32) == 0) ||
            (!old_sp && new_sp == NULL))
            return res_inner(rb, 18, 0) < 0 ? -1 : 1;       /* no-op */
        if (old_sp) {
            if (new_sp == NULL && !owner_can_afford_c(h, &acc, 1))
                return res_inner(rb, 18, -3) < 0 ? -1 : 0;  /* LOW_RESERVE */
            /* release_signer_sponsorship */
            CAccount sp;
            int g = eng_get_account(e, old_sponsor, &sp);
            if (g < 0)
                return -1;
            if (g) {
                if (sp.num_sponsoring < 1)
                    return -1;
                acc_ensure_v2(&sp);
                sp.num_sponsoring -= 1;
                sp.last_modified = h->ledger_seq;
                if (eng_put_account(e, e->cur, &sp) < 0)
                    return -1;
            }
            if (acc.num_sponsored < 1)
                return -1;
            acc_ensure_v2(&acc);
            acc.num_sponsored -= 1;
        }
        if (new_sp != NULL) {
            int sc = sponsorship_error_c(rb, 18, -3,
                establish_sponsorship_c(e, new_sp, &acc, 1));
            if (sc)
                return sc < 0 ? -1 : 0;
        }
        /* aligned sponsoring-slot write */
        acc_ensure_v2(&acc);
        while (acc.n_ssids < acc.n_signers) {
            acc.ssids[acc.n_ssids].present = 0;
            acc.n_ssids++;
        }
        acc.ssids[idx].present = new_sp != NULL;
        if (new_sp != NULL)
            memcpy(acc.ssids[idx].id, new_sp, 32);
        acc.last_modified = h->ledger_seq;
        if (eng_put_account(e, e->cur, &acc) < 0)
            return -1;
        return res_inner(rb, 18, 0) < 0 ? -1 : 1;
    }
    if (arm != 0)
        return -1;

    /* LEDGER_ENTRY arm: the raw LedgerKey is the body slice after the
     * arm tag (XDR is canonical, so the slice IS the lookup key) */
    const uint8_t *key = op->body + r.off;
    uint32_t kt = rd_u32(&r);
    if (r.err)
        return -1;
    if (kt > 4)
        return res_inner(rb, 18, -5) < 0 ? -1 : 0;  /* MALFORMED */
    /* walk the key to find its length (parse_op validated the shape) */
    switch (kt) {
    case 0: rd_skip(&r, 36); break;
    case 1: {
        rd_skip(&r, 36);
        uint32_t at = rd_u32(&r);
        if (at == 1 || at == 2) { rd_skip(&r, at == 1 ? 4 : 12);
                                  rd_skip(&r, 36); }
        else if (at == 3) rd_skip(&r, 32);
        else if (at != 0) { r.err = 1; }
        break;
    }
    case 2: rd_skip(&r, 36 + 8); break;
    case 3: {
        rd_skip(&r, 36);
        uint32_t nl;
        if (!rd_varopaque(&r, 64, &nl)) return -1;
        break;
    }
    case 4: rd_skip(&r, 4 + 32); break;
    }
    if (r.err)
        return -1;
    int klen = (int)(op->body + r.off - key);
    RB *rec = eng_get(e, key, klen);
    if (!rec)
        return res_inner(rb, 18, -1) < 0 ? -1 : 0;  /* DOES_NOT_EXIST */

    /* owner of the reserve (NULL for claimable balances) */
    const uint8_t *owner_id = NULL;
    if (kt == 0 || kt == 1 || kt == 3)
        owner_id = key + 8;                   /* tag + PK type, then id */
    else if (kt == 2)
        owner_id = key + 8;                   /* sellerID */

    int ext_off, old_sp;
    uint8_t old_sponsor[32];
    int etype = walk_entry_ext(rec->bytes, rec->len, &ext_off, &old_sp,
                               old_sponsor);
    if (etype < 0)
        return -1;
    const uint8_t *new_sp = active_sponsor_c(e, src);
    if (new_sp != NULL && owner_id != NULL &&
        memcmp(new_sp, owner_id, 32) == 0)
        new_sp = NULL;              /* owner reclaiming its own reserve */
    if (old_sp) {
        if (memcmp(src, old_sponsor, 32) != 0)
            return res_inner(rb, 18, -2) < 0 ? -1 : 0;      /* NOT_SPONSOR */
    } else if (owner_id == NULL || memcmp(src, owner_id, 32) != 0) {
        return res_inner(rb, 18, -2) < 0 ? -1 : 0;          /* NOT_SPONSOR */
    }
    if ((old_sp && new_sp != NULL && memcmp(old_sponsor, new_sp, 32) == 0)
        || (!old_sp && new_sp == NULL))
        return res_inner(rb, 18, 0) < 0 ? -1 : 1;           /* no-op */
    int mult = entry_multiplier(rec, (int)kt);
    int own_is_entry = kt == 0;
    CAccount owner;
    int have_owner = 0;
    if (own_is_entry) {
        if (parse_account_entry(rec->bytes, rec->len, &owner) < 0)
            return -1;
        have_owner = 1;
    } else if (owner_id != NULL) {
        int g = eng_get_account(e, owner_id, &owner);
        if (g <= 0)
            return -1;              /* owner must exist: corrupt state */
        have_owner = 1;
    }
    int entry_has_sponsor = old_sp;
    uint8_t entry_sponsor[32];
    if (old_sp) {
        if (new_sp == NULL && owner_id == NULL)
            return res_inner(rb, 18, -4) < 0 ? -1 : 0;  /* ONLY_TRANSFERABLE */
        if (new_sp == NULL && have_owner &&
            !owner_can_afford_c(h, &owner, mult))
            return res_inner(rb, 18, -3) < 0 ? -1 : 0;  /* LOW_RESERVE */
        /* release_entry_sponsorship: sponsor side + owner side */
        CAccount sp;
        int g = eng_get_account(e, old_sponsor, &sp);
        if (g < 0)
            return -1;
        if (g) {
            if ((int)sp.num_sponsoring < mult)
                return -1;
            acc_ensure_v2(&sp);
            sp.num_sponsoring -= (uint32_t)mult;
            sp.last_modified = h->ledger_seq;
            if (eng_put_account(e, e->cur, &sp) < 0)
                return -1;
        }
        if (have_owner) {
            if ((int)owner.num_sponsored < mult)
                return -1;
            acc_ensure_v2(&owner);
            owner.num_sponsored -= (uint32_t)mult;
        }
        entry_has_sponsor = 0;
    }
    if (new_sp != NULL) {
        int sc = sponsorship_error_c(rb, 18, -3,
            establish_sponsorship_c(e, new_sp,
                                    have_owner ? &owner : NULL, mult));
        if (sc)
            return sc < 0 ? -1 : 0;
        entry_has_sponsor = 1;
        memcpy(entry_sponsor, new_sp, 32);
    }
    if (own_is_entry) {
        /* the entry IS the owner account: one serialize carries both the
         * counter changes and the rewritten ext */
        owner.entry_ext_v1 = entry_has_sponsor ? 1 : 0;
        owner.has_sponsor = entry_has_sponsor;
        if (entry_has_sponsor)
            memcpy(owner.sponsor, entry_sponsor, 32);
        owner.last_modified = h->ledger_seq;
        if (eng_put_account(e, e->cur, &owner) < 0)
            return -1;
    } else {
        if (store_entry_with_ext(e, key, klen, rec, ext_off,
                                 entry_has_sponsor, entry_sponsor) < 0)
            return -1;
        if (have_owner) {
            owner.last_modified = h->ledger_seq;
            if (eng_put_account(e, e->cur, &owner) < 0)
                return -1;
        }
    }
    return res_inner(rb, 18, 0) < 0 ? -1 : 1;
}

/* ---- liquidity pools (CAP-38 constant product, round 12) --------------- */

#define POOL_FEE_BPS_C 30

typedef struct {
    uint32_t last_modified;
    int entry_ext_v1, has_sponsor;
    uint8_t sponsor[32];
    uint8_t pool_id[32];
    CAssetC asset_a, asset_b;
    int32_t fee;
    int64_t reserve_a, reserve_b, total_shares, tl_count;
} CPoolEntry;

static int
parse_pool_entry(const uint8_t *data, int len, CPoolEntry *p)
{
    memset(p, 0, sizeof(*p));
    Rd r;
    rd_init(&r, data, len);
    p->last_modified = rd_u32(&r);
    if (rd_u32(&r) != 5 || r.err)       /* data tag LIQUIDITY_POOL */
        return -1;
    const uint8_t *pid = rd_take(&r, 32);
    if (!pid)
        return -1;
    memcpy(p->pool_id, pid, 32);
    if (rd_u32(&r) != 0 || r.err)       /* body tag constantProduct */
        return -1;
    if (parse_asset(&r, &p->asset_a) < 0 || parse_asset(&r, &p->asset_b) < 0)
        return -1;
    p->fee = rd_i32(&r);
    p->reserve_a = rd_i64(&r);
    p->reserve_b = rd_i64(&r);
    p->total_shares = rd_i64(&r);
    p->tl_count = rd_i64(&r);
    int32_t lext = rd_i32(&r);
    if (r.err || (lext != 0 && lext != 1))
        return -1;
    p->entry_ext_v1 = (int)lext;
    if (lext == 1) {
        uint32_t sp = rd_u32(&r);
        if (r.err || sp > 1)
            return -1;
        p->has_sponsor = (int)sp;
        if (sp && parse_account_id(&r, p->sponsor) < 0)
            return -1;
        if (rd_i32(&r) != 0 || r.err)
            return -1;
    }
    return (r.err || r.off != r.len) ? -1 : 0;
}

static int
serialize_pool_entry(const CPoolEntry *p, Buf *b)
{
    if (buf_u32(b, p->last_modified) < 0 || buf_u32(b, 5) < 0 ||
        buf_put(b, p->pool_id, 32) < 0 ||
        buf_u32(b, 0) < 0 ||
        write_asset(b, &p->asset_a) < 0 ||
        write_asset(b, &p->asset_b) < 0 ||
        buf_i32(b, p->fee) < 0 ||
        buf_i64(b, p->reserve_a) < 0 ||
        buf_i64(b, p->reserve_b) < 0 ||
        buf_i64(b, p->total_shares) < 0 ||
        buf_i64(b, p->tl_count) < 0 ||
        buf_i32(b, p->entry_ext_v1) < 0)
        return -1;
    if (p->entry_ext_v1) {
        if (buf_u32(b, (uint32_t)p->has_sponsor) < 0)
            return -1;
        if (p->has_sponsor && write_account_id(b, p->sponsor) < 0)
            return -1;
        if (buf_i32(b, 0) < 0)
            return -1;
    }
    return 0;
}

/* pool LedgerKey XDR: tag LIQUIDITY_POOL(5) + PoolID */
static void
pool_key_xdr_c(const uint8_t pool_id[32], uint8_t out[36])
{
    memset(out, 0, 4);
    out[3] = 5;
    memcpy(out + 4, pool_id, 32);
}

/* PoolID = SHA256(xdr(LiquidityPoolParameters)) (mirror pool_id_for) */
static int
pool_id_for_c(const CAssetC *a, const CAssetC *b, int32_t fee,
              uint8_t out[32])
{
    Buf pb = {0};
    if (buf_u32(&pb, 0) < 0 || write_asset(&pb, a) < 0 ||
        write_asset(&pb, b) < 0 || buf_i32(&pb, fee) < 0) {
        PyMem_Free(pb.p);
        return -1;
    }
    sha256_of(pb.p, pb.len, out);
    PyMem_Free(pb.p);
    return 0;
}

/* canonical asset ordering = lexicographic XDR compare (mirror
 * asset_order); *err set on allocation failure */
static int
asset_order_c(const CAssetC *a, const CAssetC *b, int *err)
{
    Buf ba = {0}, bb = {0};
    int c = 0;
    *err = 0;
    if (write_asset(&ba, a) < 0 || write_asset(&bb, b) < 0)
        *err = 1;
    else
        c = bcmp_py(ba.p, ba.len, bb.p, bb.len);
    PyMem_Free(ba.p);
    PyMem_Free(bb.p);
    return c;
}

/* floor((a * m) / d) without overflowing 128 bits (a <= 2^126, m <= 10^4,
 * d <= 2^78): decompose a = q*d + r.  rem_nonzero reports whether the
 * true quotient had a remainder (for ceil). */
static u128
muldiv_u128(u128 a, uint64_t m, u128 d, int *rem_nonzero)
{
    u128 q = a / d, r = a % d;
    u128 low = r * (u128)m;
    if (rem_nonzero)
        *rem_nonzero = (low % d) != 0;
    return q * (u128)m + low / d;
}

/* strict-send disbursement y = floor(Y*x*(1-F) / (X + x*(1-F))) exactly
 * in basis points (mirror pool_swap_out_given_in) */
static int64_t
pool_swap_out_given_in_c(int64_t rin, int64_t rout, int64_t in_amt)
{
    u128 den = (u128)rin * 10000 +
               (u128)in_amt * (10000 - POOL_FEE_BPS_C);
    if (den == 0)
        return 0;
    u128 q = muldiv_u128((u128)rout * (u128)in_amt,
                         10000 - POOL_FEE_BPS_C, den, NULL);
    return (int64_t)q;
}

/* strict-receive charge x = ceil(X*y / ((Y-y)*(1-F))); -1 = the pool
 * cannot disburse amount_out (mirror pool_swap_in_given_out's None) */
static int64_t
pool_swap_in_given_out_c(int64_t rin, int64_t rout, int64_t out_amt)
{
    if (out_amt >= rout)
        return -1;
    u128 den = (u128)(rout - out_amt) * (10000 - POOL_FEE_BPS_C);
    int rem;
    u128 q = muldiv_u128((u128)rin * (u128)out_amt, 10000, den, &rem);
    if (rem)
        q += 1;
    if (q > (u128)INT64_MAXV)
        return -1;
    return (int64_t)q;
}

/* floor(sqrt(n)) by integer Newton iteration */
static u128
isqrt_u128(u128 n)
{
    if (n == 0)
        return 0;
    u128 x = n, y = (x + 1) / 2;
    while (y < x) {
        x = y;
        y = (x + n / x) / 2;
    }
    return x;
}

/* one side of a pool deposit (mirror LiquidityPoolDepositOpFrame._spend):
 * 1 ok / 0 failed / -1 engine error */
static int
pool_spend_c(Engine *e, const uint8_t src[32], const CAssetC *asset,
             int64_t amount)
{
    if (asset->type == 0) {
        CAccount a;
        int got = eng_get_account(e, src, &a);
        if (got < 0)
            return -1;
        if (!got || !add_balance_c(&e->header, &a, -amount, 1))
            return 0;
        return eng_put_account(e, e->cur, &a) < 0 ? -1 : 1;
    }
    if (is_issuer_asset(src, asset))
        return 1;
    Buf kb = {0};
    if (trustline_key_xdr_c(src, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int rc = 0;
    CTrustLine tl;
    if (rec != NULL &&
        parse_trustline_entry(rec->bytes, rec->len, &tl) == 0 &&
        (tl.flags & 1) && add_tl_balance_c(&tl, -amount)) {
        Buf eb = {0};
        rc = -1;
        if (serialize_trustline_entry(&tl, &eb) == 0) {
            RB *val = rb_new(eb.p, eb.len);
            rc = (val && eng_put(e, e->cur, kb.p, kb.len, val) == 0)
                 ? 1 : -1;
        }
        PyMem_Free(eb.p);
    } else if (rec != NULL &&
               parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        rc = -1;
    }
    PyMem_Free(kb.p);
    return rc;
}

/* mirror LiquidityPoolWithdrawOpFrame._receive (no auth check) */
static int
pool_receive_c(Engine *e, const uint8_t src[32], const CAssetC *asset,
               int64_t amount)
{
    if (asset->type == 0) {
        CAccount a;
        int got = eng_get_account(e, src, &a);
        if (got < 0)
            return -1;
        if (!got || !add_balance_c(&e->header, &a, amount, 1))
            return 0;
        return eng_put_account(e, e->cur, &a) < 0 ? -1 : 1;
    }
    if (is_issuer_asset(src, asset))
        return 1;
    Buf kb = {0};
    if (trustline_key_xdr_c(src, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int rc = 0;
    CTrustLine tl;
    if (rec != NULL) {
        if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0)
            rc = -1;
        else if (add_tl_balance_c(&tl, amount)) {
            Buf eb = {0};
            rc = -1;
            if (serialize_trustline_entry(&tl, &eb) == 0) {
                RB *val = rb_new(eb.p, eb.len);
                rc = (val && eng_put(e, e->cur, kb.p, kb.len, val) == 0)
                     ? 1 : -1;
            }
            PyMem_Free(eb.p);
        }
    }
    PyMem_Free(kb.p);
    return rc;
}

/* mirror LiquidityPoolDepositOpFrame (v18+, MED threshold) */
static int
op_pool_deposit(Engine *e, CTx *tx, COp *op, const uint8_t src[32], Buf *rb)
{
    (void)tx;
    CHeader *h = &e->header;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    const uint8_t *pid = rd_take(&r, 32);
    int64_t max_a = rd_i64(&r);
    int64_t max_b = rd_i64(&r);
    int32_t min_n = rd_i32(&r), min_d = rd_i32(&r);
    int32_t max_n = rd_i32(&r), max_d = rd_i32(&r);
    if (!pid || r.err)
        return -1;

    /* do_check_valid */
    if (max_a <= 0 || max_b <= 0 || min_n <= 0 || min_d <= 0 ||
        max_n <= 0 || max_d <= 0 ||
        (i128)min_n * max_d > (i128)max_n * min_d)
        return res_inner(rb, 22, -1) < 0 ? -1 : 0;   /* MALFORMED */

    Buf kb = {0};
    if (pool_trustline_key_xdr_c(src, pid, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *tl_rec = eng_get(e, kb.p, kb.len);
    if (!tl_rec) {
        PyMem_Free(kb.p);
        return res_inner(rb, 22, -2) < 0 ? -1 : 0;   /* NO_TRUST */
    }
    CTrustLine tl;
    if (parse_trustline_entry(tl_rec->bytes, tl_rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    uint8_t pk[36];
    pool_key_xdr_c(pid, pk);
    RB *prec = eng_get(e, pk, 36);
    if (!prec) {
        PyMem_Free(kb.p);
        return res_inner(rb, 22, -2) < 0 ? -1 : 0;   /* NO_TRUST */
    }
    CPoolEntry pool;
    if (parse_pool_entry(prec->bytes, prec->len, &pool) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }

#define PD_FAIL(code_) do { \
        int rr = res_inner(rb, 22, (code_)); \
        PyMem_Free(kb.p); \
        return rr < 0 ? -1 : 0; \
    } while (0)

    i128 amount_a, amount_b, shares;
    if (pool.total_shares == 0) {
        amount_a = max_a;
        amount_b = max_b;
        /* deposit price a/b must lie within [minPrice, maxPrice] */
        if (amount_a * min_d < amount_b * min_n ||
            amount_a * max_d > amount_b * max_n)
            PD_FAIL(-6);                             /* BAD_PRICE */
        shares = (i128)isqrt_u128((u128)amount_a * (u128)amount_b);
    } else {
        i128 shares_a = (i128)pool.total_shares * max_a / pool.reserve_a;
        i128 shares_b = (i128)pool.total_shares * max_b / pool.reserve_b;
        shares = shares_a < shares_b ? shares_a : shares_b;
        amount_a = (shares * pool.reserve_a + pool.total_shares - 1)
                   / pool.total_shares;
        amount_b = (shares * pool.reserve_b + pool.total_shares - 1)
                   / pool.total_shares;
        if (amount_a > max_a || amount_b > max_b) {
            shares -= 1;
            amount_a = (shares * pool.reserve_a + pool.total_shares - 1)
                       / pool.total_shares;
            amount_b = (shares * pool.reserve_b + pool.total_shares - 1)
                       / pool.total_shares;
        }
        if (shares <= 0 || amount_a <= 0 || amount_b <= 0)
            PD_FAIL(-4);                             /* UNDERFUNDED */
        /* pool price must lie within bounds */
        if ((i128)pool.reserve_a * min_d < (i128)pool.reserve_b * min_n ||
            (i128)pool.reserve_a * max_d > (i128)pool.reserve_b * max_n)
            PD_FAIL(-6);                             /* BAD_PRICE */
    }
    if (pool.total_shares > (i128)INT64_MAXV - shares ||
        pool.reserve_a > (i128)INT64_MAXV - amount_a ||
        pool.reserve_b > (i128)INT64_MAXV - amount_b)
        PD_FAIL(-7);                                 /* POOL_FULL */
    int rc = pool_spend_c(e, src, &pool.asset_a, (int64_t)amount_a);
    if (rc < 0) { PyMem_Free(kb.p); return -1; }
    if (rc == 0)
        PD_FAIL(-4);                                 /* UNDERFUNDED */
    rc = pool_spend_c(e, src, &pool.asset_b, (int64_t)amount_b);
    if (rc < 0) { PyMem_Free(kb.p); return -1; }
    if (rc == 0)
        PD_FAIL(-4);                                 /* UNDERFUNDED */
    if (!add_tl_balance_c(&tl, (int64_t)shares))
        PD_FAIL(-5);                                 /* LINE_FULL */
#undef PD_FAIL
    tl.last_modified = h->ledger_seq;
    Buf eb = {0};
    if (serialize_trustline_entry(&tl, &eb) < 0) {
        PyMem_Free(eb.p); PyMem_Free(kb.p);
        return -1;
    }
    RB *val = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    int st = val ? eng_put(e, e->cur, kb.p, kb.len, val) : -1;
    PyMem_Free(kb.p);
    if (st < 0)
        return -1;
    pool.reserve_a += (int64_t)amount_a;
    pool.reserve_b += (int64_t)amount_b;
    pool.total_shares += (int64_t)shares;
    pool.last_modified = h->ledger_seq;
    Buf pb = {0};
    if (serialize_pool_entry(&pool, &pb) < 0) {
        PyMem_Free(pb.p);
        return -1;
    }
    RB *pval = rb_new(pb.p, pb.len);
    PyMem_Free(pb.p);
    if (!pval || eng_put(e, e->cur, pk, 36, pval) < 0)
        return -1;
    return res_inner(rb, 22, 0) < 0 ? -1 : 1;
}

/* mirror LiquidityPoolWithdrawOpFrame (v18+, MED threshold) */
static int
op_pool_withdraw(Engine *e, CTx *tx, COp *op, const uint8_t src[32],
                 Buf *rb)
{
    (void)tx;
    CHeader *h = &e->header;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    const uint8_t *pid = rd_take(&r, 32);
    int64_t amount = rd_i64(&r);
    int64_t min_a = rd_i64(&r);
    int64_t min_b = rd_i64(&r);
    if (!pid || r.err)
        return -1;

    if (amount <= 0 || min_a < 0 || min_b < 0)
        return res_inner(rb, 23, -1) < 0 ? -1 : 0;   /* MALFORMED */

    Buf kb = {0};
    if (pool_trustline_key_xdr_c(src, pid, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *tl_rec = eng_get(e, kb.p, kb.len);
    if (!tl_rec) {
        PyMem_Free(kb.p);
        return res_inner(rb, 23, -2) < 0 ? -1 : 0;   /* NO_TRUST */
    }
    CTrustLine tl;
    if (parse_trustline_entry(tl_rec->bytes, tl_rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    if (tl.balance < amount) {
        PyMem_Free(kb.p);
        return res_inner(rb, 23, -3) < 0 ? -1 : 0;   /* UNDERFUNDED */
    }
    uint8_t pk[36];
    pool_key_xdr_c(pid, pk);
    RB *prec = eng_get(e, pk, 36);
    CPoolEntry pool;
    if (!prec || parse_pool_entry(prec->bytes, prec->len, &pool) < 0) {
        PyMem_Free(kb.p);
        return -1;              /* pool missing under a live share: corrupt */
    }
    int64_t amount_a = (int64_t)((i128)amount * pool.reserve_a
                                 / pool.total_shares);
    int64_t amount_b = (int64_t)((i128)amount * pool.reserve_b
                                 / pool.total_shares);
    if (amount_a < min_a || amount_b < min_b) {
        PyMem_Free(kb.p);
        return res_inner(rb, 23, -5) < 0 ? -1 : 0;   /* UNDER_MINIMUM */
    }
    int rc = pool_receive_c(e, src, &pool.asset_a, amount_a);
    if (rc < 0) { PyMem_Free(kb.p); return -1; }
    if (rc == 0) {
        PyMem_Free(kb.p);
        return res_inner(rb, 23, -4) < 0 ? -1 : 0;   /* LINE_FULL */
    }
    rc = pool_receive_c(e, src, &pool.asset_b, amount_b);
    if (rc < 0) { PyMem_Free(kb.p); return -1; }
    if (rc == 0) {
        PyMem_Free(kb.p);
        return res_inner(rb, 23, -4) < 0 ? -1 : 0;   /* LINE_FULL */
    }
    if (!add_tl_balance_c(&tl, -amount)) {
        PyMem_Free(kb.p);
        return -1;              /* oracle asserts this succeeds */
    }
    tl.last_modified = h->ledger_seq;
    Buf eb = {0};
    if (serialize_trustline_entry(&tl, &eb) < 0) {
        PyMem_Free(eb.p); PyMem_Free(kb.p);
        return -1;
    }
    RB *val = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    int st = val ? eng_put(e, e->cur, kb.p, kb.len, val) : -1;
    PyMem_Free(kb.p);
    if (st < 0)
        return -1;
    pool.reserve_a -= amount_a;
    pool.reserve_b -= amount_b;
    pool.total_shares -= amount;
    pool.last_modified = h->ledger_seq;
    Buf pb = {0};
    if (serialize_pool_entry(&pool, &pb) < 0) {
        PyMem_Free(pb.p);
        return -1;
    }
    RB *pval = rb_new(pb.p, pb.len);
    PyMem_Free(pb.p);
    if (!pval || eng_put(e, e->cur, pk, 36, pval) < 0)
        return -1;
    return res_inner(rb, 23, 0) < 0 ? -1 : 1;
}

/* adjust a constituent trustline's liquidityPoolUseCount (mirror
 * ChangeTrustOpFrame._bump_pool_use) and store it */
static int
bump_pool_use_c(Engine *e, const uint8_t *key, int klen, CTrustLine *tl,
                int delta)
{
    if (tl->ext_level < 1)
        tl->ext_level = 1;
    if (tl->ext_level < 2) {
        tl->ext_level = 2;
        tl->pool_use_count = 0;
    }
    tl->pool_use_count += delta;
    Buf eb = {0};
    if (serialize_trustline_entry(tl, &eb) < 0) {
        PyMem_Free(eb.p);
        return -1;
    }
    RB *val = rb_new(eb.p, eb.len);
    PyMem_Free(eb.p);
    return (!val || eng_put(e, e->cur, key, klen, val) < 0) ? -1 : 0;
}

/* CAP-38 pool-share ChangeTrust arm (mirror _apply_pool_share) */
static int
apply_pool_share_ct(Engine *e, CTx *tx, COp *op, const uint8_t src[32],
                    Buf *rb)
{
    (void)tx;
    CHeader *h = &e->header;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    if (rd_u32(&r) != 3 || r.err)             /* ChangeTrustAsset tag */
        return -1;
    if (rd_u32(&r) != 0 || r.err)             /* params: constantProduct */
        return -1;
    CAssetC asset_a, asset_b;
    if (parse_asset(&r, &asset_a) < 0 || parse_asset(&r, &asset_b) < 0)
        return -1;
    int32_t fee = rd_i32(&r);
    int64_t limit = rd_i64(&r);
    if (r.err)
        return -1;

    /* do_check_valid */
    int err = 0;
    int ord = asset_order_c(&asset_a, &asset_b, &err);
    if (err)
        return -1;
    if (!asset_valid_c(&asset_a) || !asset_valid_c(&asset_b) ||
        ord >= 0 || fee != POOL_FEE_BPS_C || limit < 0)
        return res_inner(rb, 6, -1) < 0 ? -1 : 0;    /* MALFORMED */

    uint8_t pool_id[32];
    if (pool_id_for_c(&asset_a, &asset_b, fee, pool_id) < 0)
        return -1;
    Buf kb = {0};
    if (pool_trustline_key_xdr_c(src, pool_id, &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    CAccount srca;
    if (eng_get_account(e, src, &srca) <= 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    uint8_t pk[36];
    pool_key_xdr_c(pool_id, pk);

#define PS_FAIL(code_) do { \
        int rr = res_inner(rb, 6, (code_)); \
        PyMem_Free(kb.p); \
        return rr < 0 ? -1 : 0; \
    } while (0)

    if (rec == NULL) {                        /* create */
        if (limit == 0)
            PS_FAIL(-3);                             /* INVALID_LIMIT */
        /* constituents: credit assets need an authorized-enough line,
         * whose pool-use count is bumped */
        const CAssetC *consts[2] = { &asset_a, &asset_b };
        for (int ci = 0; ci < 2; ci++) {
            const CAssetC *as = consts[ci];
            if (as->type == 0 || is_issuer_asset(src, as))
                continue;
            Buf ck = {0};
            if (trustline_key_xdr_c(src, as->type, as->code, as->issuer,
                                    &ck) < 0) {
                PyMem_Free(ck.p); PyMem_Free(kb.p);
                return -1;
            }
            RB *crec = eng_get(e, ck.p, ck.len);
            if (!crec) {
                PyMem_Free(ck.p);
                PS_FAIL(-6);                         /* TRUST_LINE_MISSING */
            }
            CTrustLine ctl;
            if (parse_trustline_entry(crec->bytes, crec->len, &ctl) < 0) {
                PyMem_Free(ck.p); PyMem_Free(kb.p);
                return -1;
            }
            if (!(ctl.flags & 3u)) {                 /* maintain-liab OK */
                PyMem_Free(ck.p);
                PS_FAIL(-8);            /* NOT_AUTH_MAINTAIN_LIABILITIES */
            }
            int brc = bump_pool_use_c(e, ck.p, ck.len, &ctl, 1);
            PyMem_Free(ck.p);
            if (brc < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
        }
        CTrustLine ntl;
        memset(&ntl, 0, sizeof(ntl));
        memcpy(ntl.account_id, src, 32);
        ntl.asset_type = 3;
        memcpy(ntl.pool_id, pool_id, 32);
        ntl.limit = limit;
        ntl.flags = 1;                               /* AUTHORIZED */
        /* pool-share lines pin 2 reserve units (CAP-38 double subentry) */
        const uint8_t *sp_id = h->ledger_version >= 18
            ? active_sponsor_c(e, src) : NULL;
        if (sp_id != NULL) {
            int sc = sponsorship_error_c(rb, 6, -4,
                establish_sponsorship_c(e, sp_id, &srca, 2));
            if (sc) {
                PyMem_Free(kb.p);
                return sc < 0 ? -1 : 0;
            }
            ntl.entry_ext_v1 = 1;
            ntl.has_sponsor = 1;
            memcpy(ntl.sponsor, sp_id, 32);
            srca.num_sub += 2;
        } else if (!add_num_entries_c(h, &srca, 2)) {
            PS_FAIL(-4);                             /* LOW_RESERVE */
        }
        if (eng_put_account(e, e->cur, &srca) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        /* pool entry: create on first trustline, else count up */
        RB *prec = eng_get(e, pk, 36);
        CPoolEntry pool;
        if (prec == NULL) {
            memset(&pool, 0, sizeof(pool));
            memcpy(pool.pool_id, pool_id, 32);
            pool.asset_a = asset_a;
            pool.asset_b = asset_b;
            pool.fee = fee;
            pool.tl_count = 1;
        } else {
            if (parse_pool_entry(prec->bytes, prec->len, &pool) < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
            pool.tl_count += 1;
        }
        pool.last_modified = h->ledger_seq;
        Buf pb = {0};
        if (serialize_pool_entry(&pool, &pb) < 0) {
            PyMem_Free(pb.p); PyMem_Free(kb.p);
            return -1;
        }
        RB *pval = rb_new(pb.p, pb.len);
        PyMem_Free(pb.p);
        if (!pval || eng_put(e, e->cur, pk, 36, pval) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        return store_trustline(e, &kb, &ntl, rb, 6);
    }

    CTrustLine tl;
    if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    if (limit == 0) {                         /* delete */
        if (tl.balance != 0)
            PS_FAIL(-3);                             /* INVALID_LIMIT */
        if (eng_put(e, e->cur, kb.p, kb.len, NULL) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        if (tl.entry_ext_v1 && tl.has_sponsor) {
            if (release_entry_sponsor(e, tl.sponsor, 2, &srca) < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
            srca.num_sub -= 2;
        } else {
            add_num_entries_c(h, &srca, -2);
        }
        if (eng_put_account(e, e->cur, &srca) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        RB *prec = eng_get(e, pk, 36);
        CPoolEntry pool;
        if (!prec || parse_pool_entry(prec->bytes, prec->len, &pool) < 0) {
            PyMem_Free(kb.p);
            return -1;
        }
        pool.tl_count -= 1;
        if (pool.tl_count == 0) {
            if (eng_put(e, e->cur, pk, 36, NULL) < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
        } else {
            pool.last_modified = h->ledger_seq;
            Buf pb = {0};
            if (serialize_pool_entry(&pool, &pb) < 0) {
                PyMem_Free(pb.p); PyMem_Free(kb.p);
                return -1;
            }
            RB *pval = rb_new(pb.p, pb.len);
            PyMem_Free(pb.p);
            if (!pval || eng_put(e, e->cur, pk, 36, pval) < 0) {
                PyMem_Free(kb.p);
                return -1;
            }
        }
        const CAssetC *consts[2] = { &asset_a, &asset_b };
        for (int ci = 0; ci < 2; ci++) {
            const CAssetC *as = consts[ci];
            if (as->type == 0 || is_issuer_asset(src, as))
                continue;
            Buf ck = {0};
            if (trustline_key_xdr_c(src, as->type, as->code, as->issuer,
                                    &ck) < 0) {
                PyMem_Free(ck.p); PyMem_Free(kb.p);
                return -1;
            }
            RB *crec = eng_get(e, ck.p, ck.len);
            if (crec != NULL) {
                CTrustLine ctl;
                if (parse_trustline_entry(crec->bytes, crec->len,
                                          &ctl) < 0 ||
                    bump_pool_use_c(e, ck.p, ck.len, &ctl, -1) < 0) {
                    PyMem_Free(ck.p); PyMem_Free(kb.p);
                    return -1;
                }
            }
            PyMem_Free(ck.p);
        }
        PyMem_Free(kb.p);
        return res_inner(rb, 6, 0) < 0 ? -1 : 1;
    }
    if (limit < tl.balance)
        PS_FAIL(-3);                                 /* INVALID_LIMIT */
#undef PS_FAIL
    tl.limit = limit;
    return store_trustline(e, &kb, &tl, rb, 6);
}

/* ---- path payments (round 12) ------------------------------------------ *
 *
 * Mirrors offer_ops._PathPaymentBase: each hop crosses the order book
 * (in a child overlay, rolled back if the pool wins) or the CAP-38
 * constant-product pool — whichever converts at the better rate.
 */

typedef struct {
    uint8_t pool_id[32];
    int64_t amount_in, amount_out;
    int flip;
    int usable;
} CPoolQuote;

/* mirror _pool_quote; returns -1 on engine error, else 0 (pq->usable) */
static int
pool_quote_c(Engine *e, const CAssetC *from, const CAssetC *to,
             int64_t wheat_target, int64_t sheep_budget, int rounding,
             CPoolQuote *pq)
{
    memset(pq, 0, sizeof(*pq));
    int err = 0;
    int ord = asset_order_c(from, to, &err);
    if (err)
        return -1;
    const CAssetC *a = ord < 0 ? from : to;
    const CAssetC *b = ord < 0 ? to : from;
    if (pool_id_for_c(a, b, POOL_FEE_BPS_C, pq->pool_id) < 0)
        return -1;
    uint8_t pk[36];
    pool_key_xdr_c(pq->pool_id, pk);
    RB *rec = eng_get(e, pk, 36);
    if (!rec)
        return 0;
    CPoolEntry pool;
    if (parse_pool_entry(rec->bytes, rec->len, &pool) < 0)
        return -1;
    pq->flip = asset_eq(from, &pool.asset_b);
    int64_t r_in = pq->flip ? pool.reserve_b : pool.reserve_a;
    int64_t r_out = pq->flip ? pool.reserve_a : pool.reserve_b;
    if (r_in <= 0 || r_out <= 0)
        return 0;
    if (rounding == RND_PATH_STRICT_RECEIVE) {
        pq->amount_out = wheat_target;
        pq->amount_in = pool_swap_in_given_out_c(r_in, r_out, wheat_target);
        if (pq->amount_in < 0)
            return 0;
    } else {
        pq->amount_in = sheep_budget;
        pq->amount_out = pool_swap_out_given_in_c(r_in, r_out, sheep_budget);
        if (pq->amount_out <= 0)
            return 0;
    }
    /* skip the pool rather than overflow its post-swap reserve */
    if ((u128)r_in + (u128)pq->amount_in > (u128)INT64_MAXV)
        return 0;
    pq->usable = 1;
    return 0;
}

/* one hop (mirror _convert_hop): 0 ok (amounts + claims filled), 1 op
 * failure (result written to rb), -1 engine error.  Claims append to
 * claims_out as raw ClaimAtom XDR. */
static int
convert_hop_c(Engine *e, int32_t op_type, const uint8_t taker[32],
              const CAssetC *from, const CAssetC *to,
              int64_t wheat_target, int64_t sheep_budget, int rounding,
              int64_t *wheat_out, int64_t *sheep_out, Buf *claims_out,
              int *n_claims_out, Buf *rb)
{
    /* order-book attempt in the hop overlay (child LedgerTxn) */
    map_clear(&e->hop_delta);
    e->hop_active = 1;
    e->cur = &e->hop_delta;
    CCross book;
    int rc = convert_with_offers_c(e, from, to, wheat_target, sheep_budget,
                                   taker, rounding, -1, -1, 0, &book);
    if (rc < 0) {
        e->hop_active = 0;
        map_clear(&e->hop_delta);
        e->cur = &e->op_delta;
        return -1;
    }
    if (book.self_cross) {
        map_clear(&e->hop_delta);
        e->hop_active = 0;
        e->cur = &e->op_delta;
        PyMem_Free(book.claims.p);
        return res_inner(rb, op_type, -11) < 0 ? -1 : 1; /* OFFER_CROSS_SELF */
    }
    /* pool quote: book crossing cannot touch pool entries, so reading
     * through the hop overlay sees the oracle's outer-ltx values */
    CPoolQuote pq;
    if (pool_quote_c(e, from, to, wheat_target, sheep_budget, rounding,
                     &pq) < 0) {
        e->hop_active = 0;
        map_clear(&e->hop_delta);
        e->cur = &e->op_delta;
        PyMem_Free(book.claims.p);
        return -1;
    }
    int book_filled =
        (rounding == RND_PATH_STRICT_RECEIVE &&
         book.wheat_received >= wheat_target) ||
        (rounding == RND_PATH_STRICT_SEND &&
         book.sheep_sent >= sheep_budget);
    int use_pool = 0;
    if (pq.usable) {
        if (rounding == RND_PATH_STRICT_RECEIVE)
            /* pool can deliver the full target; better price == less in */
            use_pool = pq.amount_out >= wheat_target &&
                       (!book_filled || pq.amount_in < book.sheep_sent);
        else
            use_pool = pq.amount_in <= sheep_budget &&
                       pq.amount_out > book.wheat_received;
    }
    if (use_pool) {
        /* roll the book attempt back; swap through the pool */
        map_clear(&e->hop_delta);
        e->hop_active = 0;
        e->cur = &e->op_delta;
        PyMem_Free(book.claims.p);
        uint8_t pk[36];
        pool_key_xdr_c(pq.pool_id, pk);
        RB *rec = eng_get(e, pk, 36);
        CPoolEntry pool;
        if (!rec || parse_pool_entry(rec->bytes, rec->len, &pool) < 0)
            return -1;
        if (pq.flip) {
            pool.reserve_b += pq.amount_in;
            pool.reserve_a -= pq.amount_out;
        } else {
            pool.reserve_a += pq.amount_in;
            pool.reserve_b -= pq.amount_out;
        }
        pool.last_modified = e->header.ledger_seq;
        Buf pb = {0};
        if (serialize_pool_entry(&pool, &pb) < 0) {
            PyMem_Free(pb.p);
            return -1;
        }
        RB *pval = rb_new(pb.p, pb.len);
        PyMem_Free(pb.p);
        if (!pval || eng_put(e, e->cur, pk, 36, pval) < 0)
            return -1;
        /* ClaimAtom.liquidityPool */
        if (buf_u32(claims_out, 2) < 0 ||
            buf_put(claims_out, pq.pool_id, 32) < 0 ||
            write_asset(claims_out, to) < 0 ||
            buf_i64(claims_out, pq.amount_out) < 0 ||
            write_asset(claims_out, from) < 0 ||
            buf_i64(claims_out, pq.amount_in) < 0)
            return -1;
        *n_claims_out = 1;
        *wheat_out = pq.amount_out;
        *sheep_out = pq.amount_in;
        return 0;
    }
    /* commit the book attempt into the op overlay */
    e->hop_active = 0;
    if (eng_fold_overlay(&e->hop_delta, &e->op_delta) < 0) {
        e->cur = &e->op_delta;
        PyMem_Free(book.claims.p);
        return -1;
    }
    e->cur = &e->op_delta;
    if ((rounding == RND_PATH_STRICT_RECEIVE &&
         book.wheat_received < wheat_target) ||
        (rounding == RND_PATH_STRICT_SEND &&
         book.sheep_sent < sheep_budget)) {
        PyMem_Free(book.claims.p);
        return res_inner(rb, op_type, -10) < 0 ? -1 : 1; /* TOO_FEW_OFFERS */
    }
    if (buf_put(claims_out, book.claims.p, book.claims.len) < 0) {
        PyMem_Free(book.claims.p);
        return -1;
    }
    PyMem_Free(book.claims.p);
    *n_claims_out = book.n_claims;
    *wheat_out = book.wheat_received;
    *sheep_out = book.sheep_sent;
    return 0;
}

/* credit destAsset to the destination (mirror _credit_dest): 0 ok,
 * 1 failure written, -1 engine error */
static int
pp_credit_dest(Engine *e, int32_t ot, const uint8_t dest[32],
               const CAssetC *asset, int64_t amount, Buf *rb)
{
    CHeader *h = &e->header;
    if (asset->type == 0) {
        CAccount a;
        int got = eng_get_account(e, dest, &a);
        if (got < 0)
            return -1;
        if (!got)
            return res_inner(rb, ot, -5) < 0 ? -1 : 1;  /* NO_DESTINATION */
        if (!add_balance_c(h, &a, amount, 1))
            return res_inner(rb, ot, -8) < 0 ? -1 : 1;  /* LINE_FULL */
        return eng_put_account(e, e->cur, &a) < 0 ? -1 : 0;
    }
    uint8_t dk[40];
    account_key_xdr_c(dest, dk);
    if (eng_get(e, dk, 40) == NULL)
        return res_inner(rb, ot, -5) < 0 ? -1 : 1;      /* NO_DESTINATION */
    if (is_issuer_asset(dest, asset))
        return 0;                                       /* burn at issuer */
    Buf kb = {0};
    if (trustline_key_xdr_c(dest, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int rc;
    CTrustLine tl;
    if (!rec) {
        rc = res_inner(rb, ot, -6) < 0 ? -1 : 1;        /* NO_TRUST */
    } else if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        rc = -1;
    } else if (!(tl.flags & 1)) {
        rc = res_inner(rb, ot, -7) < 0 ? -1 : 1;        /* NOT_AUTHORIZED */
    } else if (!add_tl_balance_c(&tl, amount)) {
        rc = res_inner(rb, ot, -8) < 0 ? -1 : 1;        /* LINE_FULL */
    } else {
        tl.last_modified = h->ledger_seq;
        Buf eb = {0};
        rc = -1;
        if (serialize_trustline_entry(&tl, &eb) == 0) {
            RB *val = rb_new(eb.p, eb.len);
            rc = (val && eng_put(e, e->cur, kb.p, kb.len, val) == 0)
                 ? 0 : -1;
        }
        PyMem_Free(eb.p);
    }
    PyMem_Free(kb.p);
    return rc;
}

/* debit sendAsset from the source (mirror _debit_source) */
static int
pp_debit_source(Engine *e, int32_t ot, const uint8_t src[32],
                const CAssetC *asset, int64_t amount, Buf *rb)
{
    CHeader *h = &e->header;
    if (asset->type == 0) {
        CAccount a;
        if (eng_get_account(e, src, &a) <= 0)
            return -1;
        if (!add_balance_c(h, &a, -amount, 1))
            return res_inner(rb, ot, -2) < 0 ? -1 : 1;  /* UNDERFUNDED */
        return eng_put_account(e, e->cur, &a) < 0 ? -1 : 0;
    }
    if (is_issuer_asset(src, asset))
        return 0;                                       /* mint at issuer */
    Buf kb = {0};
    if (trustline_key_xdr_c(src, asset->type, asset->code, asset->issuer,
                            &kb) < 0) {
        PyMem_Free(kb.p);
        return -1;
    }
    RB *rec = eng_get(e, kb.p, kb.len);
    int rc;
    CTrustLine tl;
    if (!rec) {
        rc = res_inner(rb, ot, -3) < 0 ? -1 : 1;        /* SRC_NO_TRUST */
    } else if (parse_trustline_entry(rec->bytes, rec->len, &tl) < 0) {
        rc = -1;
    } else if (!(tl.flags & 1)) {
        rc = res_inner(rb, ot, -4) < 0 ? -1 : 1;        /* SRC_NOT_AUTH */
    } else if (!add_tl_balance_c(&tl, -amount)) {
        rc = res_inner(rb, ot, -2) < 0 ? -1 : 1;        /* UNDERFUNDED */
    } else {
        tl.last_modified = h->ledger_seq;
        Buf eb = {0};
        rc = -1;
        if (serialize_trustline_entry(&tl, &eb) == 0) {
            RB *val = rb_new(eb.p, eb.len);
            rc = (val && eng_put(e, e->cur, kb.p, kb.len, val) == 0)
                 ? 0 : -1;
        }
        PyMem_Free(eb.p);
    }
    PyMem_Free(kb.p);
    return rc;
}

/* mirror PathPaymentStrictReceiveOpFrame (op 2) and
 * PathPaymentStrictSendOpFrame (op 13, v12+) */
static int
op_path_payment(Engine *e, CTx *tx, COp *op, const uint8_t src[32], Buf *rb)
{
    (void)tx;
    int strict_send = op->op_type == 13;
    int32_t ot = op->op_type;
    Rd r;
    rd_init(&r, op->body, op->body_len);
    CAssetC chain[7];
    if (parse_asset(&r, &chain[0]) < 0)               /* sendAsset */
        return -1;
    int64_t amt1 = rd_i64(&r);              /* sendMax / sendAmount */
    uint32_t mt = rd_u32(&r);
    if (mt == 0x100)
        rd_skip(&r, 8);
    else if (mt != 0)
        return -1;
    const uint8_t *dest = rd_take(&r, 32);
    if (!dest)
        return -1;
    CAssetC dest_asset;
    if (parse_asset(&r, &dest_asset) < 0)
        return -1;
    int64_t amt2 = rd_i64(&r);              /* destAmount / destMin */
    uint32_t np = rd_u32(&r);
    if (r.err || np > 5)
        return -1;
    for (uint32_t i = 0; i < np; i++)
        if (parse_asset(&r, &chain[1 + i]) < 0)
            return -1;
    if (r.err)
        return -1;
    int n_chain = (int)np + 2;
    chain[n_chain - 1] = dest_asset;

    /* do_check_valid */
    int bad = strict_send ? (amt1 <= 0 || amt2 <= 0)
                          : (amt2 <= 0 || amt1 <= 0);
    for (int i = 0; !bad && i < n_chain; i++)
        if (!asset_valid_c(&chain[i]))
            bad = 1;
    if (bad)
        return res_inner(rb, ot, -1) < 0 ? -1 : 0;    /* MALFORMED */

    Buf claims = {0};
    int n_claims = 0;
    int64_t wheat = 0, sheep = 0;
    int64_t last_amount;
    int rc;
    if (!strict_send) {
        int64_t dest_amount = amt2, send_max = amt1;
        rc = pp_credit_dest(e, ot, dest, &dest_asset, dest_amount, rb);
        if (rc) {
            PyMem_Free(claims.p);
            return rc < 0 ? -1 : 0;
        }
        int64_t need = dest_amount;
        /* walk back from the destination: each hop buys `need` of the
         * next asset with the previous one */
        for (int i = n_chain - 1; i >= 1; i--) {
            if (asset_eq(&chain[i], &chain[i - 1]))
                continue;
            Buf hop = {0};
            int hn = 0;
            rc = convert_hop_c(e, ot, src, &chain[i - 1], &chain[i], need,
                               INT64_MAXV, RND_PATH_STRICT_RECEIVE,
                               &wheat, &sheep, &hop, &hn, rb);
            if (rc) {
                PyMem_Free(hop.p);
                PyMem_Free(claims.p);
                return rc < 0 ? -1 : 0;
            }
            /* claims = hop_claims + claims (prepend) */
            if (buf_put(&hop, claims.p, claims.len) < 0) {
                PyMem_Free(hop.p);
                PyMem_Free(claims.p);
                return -1;
            }
            PyMem_Free(claims.p);
            claims = hop;
            n_claims += hn;
            need = sheep;
        }
        if (need > send_max) {
            PyMem_Free(claims.p);
            return res_inner(rb, ot, -12) < 0 ? -1 : 0; /* OVER_SENDMAX */
        }
        rc = pp_debit_source(e, ot, src, &chain[0], need, rb);
        if (rc) {
            PyMem_Free(claims.p);
            return rc < 0 ? -1 : 0;
        }
        last_amount = dest_amount;
    } else {
        int64_t send_amount = amt1, dest_min = amt2;
        rc = pp_debit_source(e, ot, src, &chain[0], send_amount, rb);
        if (rc) {
            PyMem_Free(claims.p);
            return rc < 0 ? -1 : 0;
        }
        int64_t have = send_amount;
        for (int i = 0; i + 1 < n_chain; i++) {
            if (asset_eq(&chain[i], &chain[i + 1]))
                continue;
            Buf hop = {0};
            int hn = 0;
            rc = convert_hop_c(e, ot, src, &chain[i], &chain[i + 1],
                               INT64_MAXV, have, RND_PATH_STRICT_SEND,
                               &wheat, &sheep, &hop, &hn, rb);
            if (rc) {
                PyMem_Free(hop.p);
                PyMem_Free(claims.p);
                return rc < 0 ? -1 : 0;
            }
            if (buf_put(&claims, hop.p, hop.len) < 0) {
                PyMem_Free(hop.p);
                PyMem_Free(claims.p);
                return -1;
            }
            PyMem_Free(hop.p);
            n_claims += hn;
            have = wheat;
        }
        if (have < dest_min) {
            PyMem_Free(claims.p);
            return res_inner(rb, ot, -12) < 0 ? -1 : 0; /* UNDER_DESTMIN */
        }
        rc = pp_credit_dest(e, ot, dest, &dest_asset, have, rb);
        if (rc) {
            PyMem_Free(claims.p);
            return rc < 0 ? -1 : 0;
        }
        last_amount = have;
    }
    /* success arm: claims vec + SimplePaymentResult */
    if (buf_i32(rb, 0) < 0 || buf_i32(rb, ot) < 0 || buf_i32(rb, 0) < 0 ||
        buf_u32(rb, (uint32_t)n_claims) < 0 ||
        buf_put(rb, claims.p, claims.len) < 0 ||
        write_account_id(rb, dest) < 0 ||
        write_asset(rb, &dest_asset) < 0 ||
        buf_i64(rb, last_amount) < 0) {
        PyMem_Free(claims.p);
        return -1;
    }
    PyMem_Free(claims.p);
    return 1;
}
