/* Native quorum-intersection enumeration core.
 *
 * Reference: src/herder/QuorumIntersectionCheckerImpl.{h,cpp} —
 * QuorumIntersectionCheckerImpl, MinQuorumEnumerator, QBitSet;
 * src/util/TarjanSCCCalculator.  The reference's exact checker is native
 * C++ (and its v2 a Rust crate); this module is the framework's native
 * equivalent (SURVEY §2.4 row "quorum checker"), a faithful port of the
 * pure-Python oracle in herder/quorum_intersection.py: same branch-and-
 * bound over minimal quorums, same max-quorum-contraction pruning, same
 * split heuristic and traversal order, so verdicts, split witnesses AND
 * the max_quorums_found diagnostic are bit-identical to the Python
 * checker (differentially tested).  Node sets are unsigned __int128
 * bitmasks (n <= 128; the Python wrapper falls back to the Python
 * checker beyond that).
 *
 * Input blob (little-endian), built by the Python wrapper:
 *   u32 n                      -- node count
 *   n serialized qset trees, each:
 *     u32 threshold; u8 nodes[16] (LE mask); u32 n_inner; children...
 *
 * check(blob, interrupt_or_None) ->
 *   (code, split_a: bytes|None, split_b: bytes|None,
 *    main_scc_size, max_quorums)
 *   code: 1 = intersects, 0 = split found, -1 = interrupted
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

typedef unsigned __int128 u128;

typedef struct {
    uint32_t thr;
    uint32_t n_inner;
    uint32_t first;     /* index of first child id in the kids array */
    u128 nodes;
    u128 succ;
} QB;

typedef struct {
    QB *qbs;
    uint32_t *kids;
    int qb_len, qb_cap;
    int kids_len, kids_cap;
    int n;
    uint32_t *roots;          /* per-node root qset index */
    int *indegree;
    PyObject *interrupt;      /* borrowed; NULL or a callable */
    unsigned long long calls; /* interrupt poll counter */
    int interrupted;          /* set when interrupt fired or error pending */
    unsigned long long max_quorums;
    PyThreadState *ts;        /* saved thread state while the GIL is
                                 released during enumeration (NULL when
                                 the GIL is held) */
} Ctx;

static int
popcount128(u128 x)
{
    return __builtin_popcountll((uint64_t)x) +
           __builtin_popcountll((uint64_t)(x >> 64));
}

static int
ctz128(u128 x)
{
    uint64_t lo = (uint64_t)x;
    if (lo)
        return __builtin_ctzll(lo);
    return 64 + __builtin_ctzll((uint64_t)(x >> 64));
}

/* ---- blob parsing ---------------------------------------------------- */

static int
ensure_qb(Ctx *c)
{
    if (c->qb_len < c->qb_cap)
        return 0;
    int ncap = c->qb_cap ? c->qb_cap * 2 : 64;
    QB *nq = PyMem_Realloc(c->qbs, ncap * sizeof(QB));
    if (!nq) { PyErr_NoMemory(); return -1; }
    c->qbs = nq; c->qb_cap = ncap;
    return 0;
}

static int
ensure_kids(Ctx *c, int extra)
{
    if (c->kids_len + extra <= c->kids_cap)
        return 0;
    int ncap = c->kids_cap ? c->kids_cap * 2 : 64;
    while (ncap < c->kids_len + extra) ncap *= 2;
    uint32_t *nk = PyMem_Realloc(c->kids, ncap * sizeof(uint32_t));
    if (!nk) { PyErr_NoMemory(); return -1; }
    c->kids = nk; c->kids_cap = ncap;
    return 0;
}

static u128
read_mask(const unsigned char *p)
{
    u128 m = 0;
    for (int i = 15; i >= 0; i--)
        m = (m << 8) | p[i];
    return m;
}

/* returns qset index or -1 on error; advances *pp */
static int
parse_qset(Ctx *c, const unsigned char **pp, const unsigned char *end)
{
    if (end - *pp < 4 + 16 + 4) {
        PyErr_SetString(PyExc_ValueError, "truncated qset blob");
        return -1;
    }
    uint32_t thr, n_inner;
    memcpy(&thr, *pp, 4); *pp += 4;
    u128 nodes = read_mask(*pp); *pp += 16;
    memcpy(&n_inner, *pp, 4); *pp += 4;
    if (n_inner > 4096) {
        PyErr_SetString(PyExc_ValueError, "absurd inner count");
        return -1;
    }
    if (ensure_qb(c) < 0)
        return -1;
    int idx = c->qb_len++;
    c->qbs[idx].thr = thr;
    c->qbs[idx].nodes = nodes;
    c->qbs[idx].n_inner = n_inner;
    c->qbs[idx].first = 0;

    uint32_t stack_kids[64];
    uint32_t *mykids = stack_kids;
    if (n_inner > 64) {
        mykids = PyMem_Malloc(n_inner * sizeof(uint32_t));
        if (!mykids) { PyErr_NoMemory(); return -1; }
    }
    u128 succ = nodes;
    for (uint32_t i = 0; i < n_inner; i++) {
        int ch = parse_qset(c, pp, end);
        if (ch < 0) {
            if (mykids != stack_kids) PyMem_Free(mykids);
            return -1;
        }
        mykids[i] = (uint32_t)ch;
        succ |= c->qbs[ch].succ;
    }
    if (ensure_kids(c, (int)n_inner) < 0) {
        if (mykids != stack_kids) PyMem_Free(mykids);
        return -1;
    }
    c->qbs[idx].first = (uint32_t)c->kids_len;
    memcpy(c->kids + c->kids_len, mykids, n_inner * sizeof(uint32_t));
    c->kids_len += (int)n_inner;
    c->qbs[idx].succ = succ;
    if (mykids != stack_kids) PyMem_Free(mykids);
    return idx;
}

/* ---- quorum primitives (mirror the Python oracle exactly) ------------ */

static int
slice_satisfied(Ctx *c, uint32_t qi, u128 mask)
{
    QB *q = &c->qbs[qi];
    /* count is non-negative and bounded by n + n_inner; compare unsigned so
     * a hostile threshold >= 2^31 (valid XDR uint32 in a never-sanity-checked
     * qmap) cannot wrap negative and satisfy the slice unconditionally. */
    uint32_t count = (uint32_t)popcount128(q->nodes & mask);
    if (count >= q->thr)
        return 1;
    for (uint32_t i = 0; i < q->n_inner; i++) {
        if (slice_satisfied(c, c->kids[q->first + i], mask)) {
            if (++count >= q->thr)
                return 1;
        }
    }
    return 0;
}

static u128
contract_to_max_quorum(Ctx *c, u128 mask)
{
    for (;;) {
        u128 new = 0, m = mask;
        while (m) {
            int i = ctz128(m);
            u128 bit = (u128)1 << i;
            if (slice_satisfied(c, c->roots[i], mask))
                new |= bit;
            m ^= bit;
        }
        if (new == mask)
            return mask;
        mask = new;
    }
}

static int
is_quorum(Ctx *c, u128 mask)
{
    return mask != 0 && contract_to_max_quorum(c, mask) == mask;
}

static int
is_minimal_quorum(Ctx *c, u128 mask)
{
    u128 m = mask;
    while (m) {
        int i = ctz128(m);
        u128 bit = (u128)1 << i;
        if (contract_to_max_quorum(c, mask & ~bit))
            return 0;
        m ^= bit;
    }
    return 1;
}

/* ---- Tarjan SCC (iterative, same visit order as the Python one) ------ */

static int
tarjan_sccs(Ctx *c, u128 *sccs_out, int max_sccs)
{
    int n = c->n;
    int *indexv = PyMem_Calloc(n, sizeof(int));
    int *low = PyMem_Calloc(n, sizeof(int));
    char *on_stack = PyMem_Calloc(n, 1);
    char *visited = PyMem_Calloc(n, 1);
    int *stack = PyMem_Malloc(n * sizeof(int));
    int *work_v = PyMem_Malloc((n + 1) * sizeof(int));
    int *work_pi = PyMem_Malloc((n + 1) * sizeof(int));
    if (!indexv || !low || !on_stack || !visited || !stack || !work_v ||
        !work_pi) {
        PyErr_NoMemory();
        goto fail;
    }
    int sp = 0, n_sccs = 0, counter = 1;
    for (int root = 0; root < n; root++) {
        if (visited[root])
            continue;
        int wp = 0;
        work_v[0] = root; work_pi[0] = 0;
        while (wp >= 0) {
            int v = work_v[wp], pi = work_pi[wp];
            if (pi == 0) {
                visited[v] = 1;
                indexv[v] = low[v] = counter++;
                stack[sp++] = v;
                on_stack[v] = 1;
            }
            int advanced = 0;
            /* pi can reach 128 when the last-visited child is node 127;
             * a >>128 on u128 is UB, so clamp to an empty mask */
            u128 m = pi < 128 ? c->qbs[c->roots[v]].succ >> pi : (u128)0;
            while (m) {
                if (m & 1) {
                    int w = pi;
                    if (!visited[w]) {
                        work_pi[wp] = pi + 1;
                        wp++;
                        work_v[wp] = w; work_pi[wp] = 0;
                        advanced = 1;
                        break;
                    } else if (on_stack[w]) {
                        if (indexv[w] < low[v]) low[v] = indexv[w];
                    }
                }
                m >>= 1;
                pi++;
            }
            if (advanced)
                continue;
            wp--;
            if (low[v] == indexv[v]) {
                u128 scc = 0;
                for (;;) {
                    int w = stack[--sp];
                    on_stack[w] = 0;
                    scc |= (u128)1 << w;
                    if (w == v)
                        break;
                }
                if (n_sccs < max_sccs)
                    sccs_out[n_sccs] = scc;
                n_sccs++;
            }
            if (wp >= 0) {
                int p = work_v[wp];
                if (low[v] < low[p]) low[p] = low[v];
            }
        }
    }
    PyMem_Free(indexv); PyMem_Free(low); PyMem_Free(on_stack);
    PyMem_Free(visited); PyMem_Free(stack); PyMem_Free(work_v);
    PyMem_Free(work_pi);
    return n_sccs;
fail:
    PyMem_Free(indexv); PyMem_Free(low); PyMem_Free(on_stack);
    PyMem_Free(visited); PyMem_Free(stack); PyMem_Free(work_v);
    PyMem_Free(work_pi);
    return -1;
}

/* ---- enumeration ------------------------------------------------------ */

static int
poll_interrupt(Ctx *c)
{
    /* polls on the very first enumeration call (so an already-raised
     * interrupt flag stops even tiny maps, matching the per-call polling
     * of the Python enumeration) and every 65536 calls thereafter.
     * Enumeration runs with the GIL RELEASED (a hard map enumerates for
     * minutes; other threads — herder, http admin, the flag-setting
     * interrupter — must keep running); the poll briefly re-acquires it
     * for the Python calls. */
    if ((c->calls++ & 0xFFFF) != 0)
        return 0;
    if (c->ts)
        PyEval_RestoreThread(c->ts);
    if (PyErr_CheckSignals() < 0)
        c->interrupted = 1;
    if (!c->interrupted && c->interrupt && c->interrupt != Py_None) {
        PyObject *r = PyObject_CallNoArgs(c->interrupt);
        if (!r) {
            c->interrupted = 1;
        } else {
            if (PyObject_IsTrue(r))
                c->interrupted = 1;
            Py_DECREF(r);
        }
    }
    if (c->ts)
        c->ts = PyEval_SaveThread();
    return c->interrupted;
}

static u128
pick_split_node(Ctx *c, u128 remaining)
{
    u128 best = 0, m = remaining;
    int best_deg = -1;
    while (m) {
        int i = ctz128(m);
        u128 bit = (u128)1 << i;
        if (c->indegree[i] > best_deg) {
            best = bit;
            best_deg = c->indegree[i];
        }
        m ^= bit;
    }
    return best;
}

/* returns 1 if a split was found (out params set), 0 otherwise; sets
 * c->interrupted on interrupt/error. */
static int
enumerate(Ctx *c, u128 committed, u128 remaining, u128 scc,
          u128 *out_minq, u128 *out_disj)
{
    if (c->interrupted || poll_interrupt(c))
        return 0;
    u128 perimeter = committed | remaining;
    u128 mq = contract_to_max_quorum(c, perimeter);
    if (committed & ~mq)
        return 0;
    if (!mq)
        return 0;
    if (committed && is_quorum(c, committed)) {
        c->max_quorums++;
        if (is_minimal_quorum(c, committed)) {
            u128 disjoint = contract_to_max_quorum(c, scc & ~committed);
            if (disjoint) {
                *out_minq = committed;
                *out_disj = disjoint;
                return 1;
            }
        }
        return 0;
    }
    if (!remaining)
        return 0;
    u128 bit = pick_split_node(c, remaining);
    u128 rest = remaining & ~bit;
    if (enumerate(c, committed, rest, scc, out_minq, out_disj))
        return 1;
    if (c->interrupted)
        return 0;
    return enumerate(c, committed | bit, rest, scc, out_minq, out_disj);
}

/* ---- module ----------------------------------------------------------- */

static PyObject *
mask_to_bytes(u128 m)
{
    unsigned char buf[16];
    for (int i = 0; i < 16; i++) {
        buf[i] = (unsigned char)(m & 0xFF);
        m >>= 8;
    }
    return PyBytes_FromStringAndSize((const char *)buf, 16);
}

static PyObject *
build_result(int code, u128 a, u128 b, int main_scc_size,
             unsigned long long max_q)
{
    PyObject *pa = Py_None, *pb = Py_None;
    if (code == 0) {
        pa = mask_to_bytes(a);
        pb = mask_to_bytes(b);
        if (!pa || !pb) {
            Py_XDECREF(pa == Py_None ? NULL : pa);
            return NULL;
        }
    } else {
        Py_INCREF(Py_None);
        Py_INCREF(Py_None);
    }
    return Py_BuildValue("(iNNiK)", code, pa, pb, main_scc_size,
                         (unsigned long long)max_q);
}

static PyObject *
cquorum_check(PyObject *self, PyObject *args)
{
    (void)self;
    Py_buffer blob;
    PyObject *interrupt = Py_None;
    if (!PyArg_ParseTuple(args, "y*|O", &blob, &interrupt))
        return NULL;

    Ctx c;
    memset(&c, 0, sizeof(c));
    c.interrupt = interrupt;
    PyObject *result = NULL;
    u128 *sccs = NULL;

    const unsigned char *p = blob.buf;
    const unsigned char *end = p + blob.len;
    if (end - p < 4) {
        PyErr_SetString(PyExc_ValueError, "truncated blob");
        goto done;
    }
    uint32_t n;
    memcpy(&n, p, 4); p += 4;
    if (n > 128) {
        PyErr_SetString(PyExc_ValueError, "n > 128 (python fallback)");
        goto done;
    }
    c.n = (int)n;
    if (n == 0) {
        result = build_result(1, 0, 0, 0, 0);
        goto done;
    }
    c.roots = PyMem_Malloc(n * sizeof(uint32_t));
    c.indegree = PyMem_Calloc(n, sizeof(int));
    if (!c.roots || !c.indegree) { PyErr_NoMemory(); goto done; }
    for (uint32_t i = 0; i < n; i++) {
        int r = parse_qset(&c, &p, end);
        if (r < 0)
            goto done;
        c.roots[i] = (uint32_t)r;
    }
    if (p != end) {
        PyErr_SetString(PyExc_ValueError, "trailing bytes in blob");
        goto done;
    }

    /* in-degree over successors (the split heuristic) */
    for (uint32_t i = 0; i < n; i++) {
        u128 m = c.qbs[c.roots[i]].succ;
        while (m) {
            int j = ctz128(m);
            c.indegree[j]++;
            m ^= (u128)1 << j;
        }
    }

    sccs = PyMem_Malloc(n * sizeof(u128));
    if (!sccs) { PyErr_NoMemory(); goto done; }
    int n_sccs = tarjan_sccs(&c, sccs, (int)n);
    if (n_sccs < 0)
        goto done;

    /* quorum-bearing SCCs, in Tarjan emission order (matches Python) */
    u128 q1 = 0, q2 = 0, main_scc = 0;
    int n_quorum_sccs = 0;
    for (int i = 0; i < n_sccs; i++) {
        u128 mq = contract_to_max_quorum(&c, sccs[i]);
        if (mq) {
            if (n_quorum_sccs == 0) { q1 = mq; main_scc = sccs[i]; }
            else if (n_quorum_sccs == 1) q2 = mq;
            n_quorum_sccs++;
        }
    }
    if (n_quorum_sccs == 0) {
        result = build_result(1, 0, 0, 0, 0);
        goto done;
    }
    if (n_quorum_sccs > 1) {
        result = build_result(0, q1, q2, 0, 0);
        goto done;
    }

    u128 minq = 0, disj = 0;
    c.ts = PyEval_SaveThread();          /* GIL released for the search */
    int found = enumerate(&c, 0, main_scc, main_scc, &minq, &disj);
    PyEval_RestoreThread(c.ts);
    c.ts = NULL;
    if (c.interrupted) {
        if (PyErr_Occurred())
            goto done;               /* propagate callback exception */
        result = build_result(-1, 0, 0, popcount128(main_scc),
                              c.max_quorums);
        goto done;
    }
    result = build_result(found ? 0 : 1, minq, disj,
                          popcount128(main_scc), c.max_quorums);

done:
    PyBuffer_Release(&blob);
    PyMem_Free(c.qbs);
    PyMem_Free(c.kids);
    PyMem_Free(c.roots);
    PyMem_Free(c.indegree);
    PyMem_Free(sccs);
    return result;
}

static PyMethodDef cquorum_methods[] = {
    {"check", cquorum_check, METH_VARARGS,
     "check(blob, interrupt=None) -> (code, split_a, split_b, "
     "main_scc_size, max_quorums)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef cquorum_module = {
    PyModuleDef_HEAD_INIT, "_cquorum",
    "Native quorum-intersection enumeration core", -1, cquorum_methods,
    NULL, NULL, NULL, NULL,
};

PyMODINIT_FUNC
PyInit__cquorum(void)
{
    return PyModule_Create(&cquorum_module);
}
