"""Config 1/4 at their stated scale (10k ledgers) under the round-5
native engine: one interleaved cpu/accel pair + a python-engine pass."""
import sys, tempfile, time
sys.path.insert(0, "/root/repo")
import bench
from stellar_core_tpu.catchup.catchup import CatchupManager
from stellar_core_tpu.crypto import keys
from stellar_core_tpu.testutils import network_id

if not bench.probe_device(timeout_s=120, attempts=2):
    print("DEVICE DOWN"); sys.exit(1)
nid = network_id("bench network")
with tempfile.TemporaryDirectory() as d:
    t0 = time.perf_counter()
    archive, mgr = bench.build_archive(nid, "bench network", d + "/a",
                                       n_payment_ledgers=10000)
    n = mgr.last_closed_ledger_seq
    print(f"archive {n} ledgers built in {time.perf_counter()-t0:.0f}s",
          flush=True)
    keys.clear_verify_cache()
    cmw = CatchupManager(nid, "bench network", accel=True, accel_chunk=8192,
                         accel_hot_threshold=4)
    cmw.catchup_complete(archive, to_ledger=127)
    print("warmed", flush=True)
    for name, kw in (("native-cpu", dict(accel=False)),
                     ("native-accel", dict(accel=True, accel_chunk=8192,
                                           accel_hot_threshold=4)),
                     ("python-cpu", dict(accel=False, native=False))):
        keys.clear_verify_cache()
        cm = CatchupManager(nid, "bench network", **kw)
        t0 = time.perf_counter()
        m = cm.catchup_complete(archive)
        dt = time.perf_counter() - t0
        assert m.lcl_hash == mgr.lcl_hash, name + " diverged"
        extra = ""
        if "accel" in name:
            extra = (f" hit={cm.offload_hit_rate():.3f}"
                     f" wait={cm.stats.get('collect_wait_s', 0):.1f}"
                     f" losses={cm.stats.get('race_losses', 0)}"
                     f" sodium={cm.stats.get('native_libsodium_verifies')}")
        print(f"{name}: {n/dt:.1f} l/s ({dt:.1f}s){extra}", flush=True)
