"""Round-5 on-chip: native-engine replay, CPU vs accel vs python-cpu,
interleaved rounds (the rig drifts 20-66%; only interleaved medians are
valid — see BASELINE.md)."""

import sys
import tempfile
import time

sys.path.insert(0, "/root/repo")
import bench  # noqa: E402
from stellar_core_tpu.catchup.catchup import CatchupManager  # noqa: E402
from stellar_core_tpu.crypto import keys  # noqa: E402
from stellar_core_tpu.testutils import network_id  # noqa: E402


def main():
    if not bench.probe_device(timeout_s=120, attempts=2):
        print("DEVICE DOWN")
        sys.exit(1)
    nid = network_id("bench network")
    with tempfile.TemporaryDirectory() as d:
        archive, mgr = bench.build_archive(
            nid, "bench network", d + "/a", n_payment_ledgers=1100)
        n = mgr.last_closed_ledger_seq
        print("archive ledgers:", n, flush=True)
        keys.clear_verify_cache()
        cmw = CatchupManager(nid, "bench network", accel=True,
                             accel_chunk=8192, accel_hot_threshold=4)
        cmw.catchup_complete(archive, to_ledger=127)
        print("warmed", flush=True)
        rates = {"cpu": [], "accel": [], "py_cpu": []}
        for r in range(3):
            for name, kw in (("cpu", dict(accel=False)),
                             ("accel", dict(accel=True, accel_chunk=8192,
                                            accel_hot_threshold=4)),
                             ("py_cpu", dict(accel=False, native=False))):
                keys.clear_verify_cache()
                cm = CatchupManager(nid, "bench network", **kw)
                t0 = time.perf_counter()
                m = cm.catchup_complete(archive)
                dt = time.perf_counter() - t0
                assert m.lcl_hash == mgr.lcl_hash, name + " diverged"
                rates[name].append(n / dt)
                extra = ""
                if name == "accel":
                    extra = (
                        f" hit={cm.offload_hit_rate():.3f}"
                        f" collect_wait="
                        f"{cm.stats.get('collect_wait_s', 0):.2f}"
                        f" dispatch={cm.stats.get('dispatch_s', 0):.2f}"
                        f" sodium="
                        f"{cm.stats.get('native_libsodium_verifies')}"
                        f" losses={cm.stats.get('race_losses', 0)}")
                print(f"round {r} {name}: {n/dt:.1f} l/s ({dt:.2f}s){extra}",
                      flush=True)
        med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
        c, a, p = (med(rates["cpu"]), med(rates["accel"]),
                   med(rates["py_cpu"]))
        print(f"MEDIANS: native-cpu {c:.1f} l/s, native-accel {a:.1f} l/s, "
              f"python-cpu {p:.1f} l/s")
        print(f"accel vs native-cpu: {a/c:.3f}x; "
              f"accel vs python-cpu: {a/p:.3f}x; "
              f"native-cpu vs python-cpu: {c/p:.3f}x")


if __name__ == "__main__":
    main()
