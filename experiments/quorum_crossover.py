"""Re-time the asym-org quorum crossover on the device-resident frontier
enumerator (VERDICT r3 item 4: orgs=7 inside the 900 s budget; round-3
chunked path took 1815 s vs CPU TIMEOUT>900 s).

Runs orgs=min_orgs..max_orgs with a wall-clock printout per map.
Verdicts cross-checked against the exact CPU checker where it answers
inside its budget (orgs<=6).

Run ON THE REAL CHIP:
    python experiments/quorum_crossover.py [max_orgs] [min_orgs]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(max_orgs=7, min_orgs=5):
    from stellar_core_tpu.accel.quorum import check_intersection_tpu
    from stellar_core_tpu.herder.quorum_intersection import check_intersection
    from stellar_core_tpu.testutils import asym_org_qmap

    # warm the capacity-bucket compiles on a small map first so orgs>=6
    # timings are execution, not compilation
    print("warm (orgs=4)...", flush=True)
    t0 = time.perf_counter()
    check_intersection_tpu(asym_org_qmap(4))
    print(f"  warm took {time.perf_counter()-t0:.1f}s (incl. compiles)",
          flush=True)

    cpu_budget_s = 900.0
    for n_orgs in range(min_orgs, max_orgs + 1):
        qmap = asym_org_qmap(n_orgs)
        t0 = time.perf_counter()
        tres = check_intersection_tpu(qmap)
        t_tpu = time.perf_counter() - t0
        print(f"orgs={n_orgs}: TPU resident-frontier {t_tpu:8.1f}s  "
              f"intersects={tres.intersects} "
              f"(max_quorums={tres.max_quorums_found})", flush=True)
        if n_orgs <= 6:    # CPU answers 5 (3s) and 6 (~190s); 7 times out
            t0 = time.perf_counter()
            cres = check_intersection(qmap)
            t_cpu = time.perf_counter() - t0
            print(f"          CPU exact checker     {t_cpu:8.1f}s  "
                  f"intersects={cres.intersects}", flush=True)
            assert cres.intersects == tres.intersects, n_orgs
        else:
            print(f"          CPU: skipped (round-3 measured TIMEOUT "
                  f"> {cpu_budget_s:.0f}s)", flush=True)
        if t_tpu > cpu_budget_s:
            print(f"          NOTE: above the {cpu_budget_s:.0f}s "
                  "operational budget", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 7,
         int(sys.argv[2]) if len(sys.argv) > 2 else 5)
