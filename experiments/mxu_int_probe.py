"""MXU int32-limb probe (VERDICT r5 item 5, SURVEY §7 hard-parts).

Question: can the field mults inside the Ed25519 scan use the MXU?

Algebra first: a general batched field mul c[n] = a[n]*b[n] is a
per-element limb convolution — BILINEAR in two per-element operands, so
it cannot be phrased as X @ W with a shared W (the MXU contract).  The
one shape that CAN: multiplying every element by a SHARED constant p
(e.g. one base/table point coordinate): c[n,k] = sum_i a[n,i] * p[k-i]
is (N,L) @ Toeplitz(p) — a real matmul.  Exactness bounds the operand
radix: int8 limbs (radix 2^8, 32 limbs) keep products in int16 and a
63-column accumulation under 2^21 « int32.

So the question reduces to: does THIS backend run int8xint8->int32
matmuls at MXU rate?  This probe measures 32-step dependent chains
inside ONE jit dispatch (the tunnel's ~0.3s launch latency would swamp
per-matmul timing otherwise):
  1. (32768,64) int8 @ (64,64) const int8 -> int32 -> re-narrowed int8
  2. same chain in bf16 (MXU reference rate)
  3. the production VPU int64 radix-16 field mul, 32 dependent muls
and reports chain-steps/s for each route, interleaved medians of 5.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

N = 32768
STEPS = 32


def main():
    import jax
    import jax.numpy as jnp

    from stellar_core_tpu.accel import field as F

    rng = np.random.default_rng(5)
    a8 = jnp.asarray(rng.integers(0, 127, (N, 64), dtype=np.int8))
    t8 = jnp.asarray(rng.integers(0, 127, (64, 64), dtype=np.int8))
    abf = a8.astype(jnp.bfloat16)
    tbf = t8.astype(jnp.bfloat16)

    @jax.jit
    def chain_int8(x, w):
        def step(i, acc):
            prod = jax.lax.dot_general(
                acc, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            return (prod & 0x7F).astype(jnp.int8)   # renarrow: stay integer
        return jax.lax.fori_loop(0, STEPS, step, x)

    @jax.jit
    def chain_bf16(x, w):
        def step(i, acc):
            prod = jax.lax.dot_general(
                acc, w, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return (prod % 127.0).astype(jnp.bfloat16)
        return jax.lax.fori_loop(0, STEPS, step, x)

    av = jnp.asarray(rng.integers(0, 1 << 16, (N, F.NLIMB), dtype=np.int64))
    bv = jnp.asarray(rng.integers(0, 1 << 16, (N, F.NLIMB), dtype=np.int64))

    @jax.jit
    def chain_vpu(x, y):
        def step(i, acc):
            return F.fe_mul(acc, y)
        return jax.lax.fori_loop(0, STEPS, step, x)

    # one-shot exactness check of the int8->int32 matmul
    one = jax.jit(lambda x, w: jax.lax.dot_general(
        x, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32))
    got = np.asarray(one(a8, t8))
    want = np.asarray(a8, dtype=np.int32) @ np.asarray(t8, dtype=np.int32)
    print(f"int8->int32 matmul exact: {bool((got == want).all())}",
          flush=True)

    np.asarray(chain_int8(a8, t8))     # compiles + warm
    np.asarray(chain_bf16(abf, tbf))
    np.asarray(chain_vpu(av, bv))

    reps = {"int8_chain": [], "bf16_chain": [], "vpu_int64_chain": []}
    for r in range(5):
        t0 = time.perf_counter()
        np.asarray(chain_int8(a8, t8))
        reps["int8_chain"].append(STEPS * N / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        np.asarray(chain_bf16(abf, tbf))
        reps["bf16_chain"].append(STEPS * N / (time.perf_counter() - t0))
        t0 = time.perf_counter()
        np.asarray(chain_vpu(av, bv))
        reps["vpu_int64_chain"].append(STEPS * N / (time.perf_counter() - t0))
        print(f"round {r}: " + "  ".join(
            f"{k}={v[-1]/1e6:.2f}M steps/s" for k, v in reps.items()),
            flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]  # noqa: E731
    print("MEDIANS (chain steps/s; one step = one 'mul by shared const'):")
    for k, v in reps.items():
        print(f"  {k}: {med(v)/1e6:.2f}M/s")
    print(f"int8 vs vpu: "
          f"{med(reps['int8_chain'])/med(reps['vpu_int64_chain']):.2f}x "
          f"(applies to shared-constant muls only; the general a*b muls "
          f"of the double-scalarmult are bilinear and stay on the VPU)")


if __name__ == "__main__":
    main()
