"""Split one sig-kernel dispatch into host-prep / transfer / on-chip
compute (VERDICT r3 item 3: substantiate or correct the co-located
projection with MEASURED device time, not "would shed that overhead").

Method (the axon backend exposes no profiler; the split is derived from
three timed materializations, each of which is what actually executes
work on this lazy backend):

  prep     = wall time of the host-side numpy/SHA-512 pairing section
             (timed directly inside verify_async's phases)
  transfer = materialize a TRIVIAL reduction of the uploaded byte
             matrices (sum) — pays H2D transfer + dispatch + D2H of a
             scalar, but ~zero compute
  full     = materialize the real verify kernel on the same inputs
  compute ~= full - transfer        (on-chip kernel time)

Co-located projection printed with its arithmetic: a local chip pays
~PCIe/ICI transfer (>10 GB/s) instead of the ~14 MB/s tunnel, so
projected sigs/s = n / (compute + n_bytes / 10 GB/s + ~1 ms launch).

Run ON THE REAL CHIP (no JAX_PLATFORMS=cpu):
    python experiments/device_time_split.py [--tables]
(--tables measures the per-key-table path instead of the generic one.)
"""

import sys
import os
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main(n=32768, rounds=5, tables=False):
    import jax.numpy as jnp

    from stellar_core_tpu.accel import ed25519 as E
    from stellar_core_tpu.crypto import sodium

    print(f"building {n} signatures (path: "
          f"{'tables' if tables else 'generic'})...", flush=True)
    keys = [sodium.sign_seed_keypair(bytes([i]) * 32) for i in range(64)]
    pks, sigs, msgs = [], [], []
    import random
    rng = random.Random(5)
    for i in range(n):
        pk, sk = keys[i % 64]
        msg = rng.randbytes(120)
        pks.append(pk)
        sigs.append(sodium.sign_detached(msg, sk))
        msgs.append(msg)

    v = E.Ed25519BatchVerifier(chunk_size=n, tail_floor=n,
                               hot_threshold=4 if tables else 1 << 62)

    # -- host prep: time the numpy/SHA section by running verify_async and
    # subtracting nothing — the call itself IS the prep + enqueue (enqueue
    # returns instantly on this backend)
    v.verify(pks, sigs, msgs)   # compile + warm both paths
    t0 = time.perf_counter()
    collector = v.verify_async(pks, sigs, msgs)
    prep_s = time.perf_counter() - t0
    collector()                 # drain

    # -- transfer probe: upload the same byte volume, materialize a sum.
    # 96 B/sig ship for the generic path (s_raw 32 + h_raw 32 + r 32) +
    # 4 B key index
    sig_mat = np.zeros((n, 64), dtype=np.uint8)
    for i, s in enumerate(sigs):
        sig_mat[i] = np.frombuffer(s, dtype=np.uint8)
    payload = np.concatenate(
        [sig_mat[:, 32:], sig_mat[:, :32],
         np.zeros((n, 32), np.uint8)], axis=1)   # 96 B/sig
    n_bytes = payload.nbytes

    import jax

    @jax.jit
    def echo(x):
        return jnp.sum(x.astype(jnp.int32))

    echo_np = np.asarray(echo(jnp.asarray(payload)))  # compile warm

    transfer_rounds = []
    full_rounds = []
    for r in range(rounds):
        t0 = time.perf_counter()
        np.asarray(echo(jnp.asarray(payload)))
        transfer_rounds.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        collector = v.verify_async(pks, sigs, msgs)
        out = collector()
        full_rounds.append(time.perf_counter() - t0)
        assert int(out.sum()) == n

    med = lambda xs: sorted(xs)[len(xs) // 2]
    transfer_s = med(transfer_rounds)
    full_s = med(full_rounds)
    # full includes the host prep re-done inside verify_async
    device_total_s = full_s - prep_s
    compute_s = max(device_total_s - transfer_s, 0.0)

    print(f"\n=== device-time split (batch {n}, medians of {rounds}) ===")
    print(f"host prep (pairing, SHA-512, numpy):  {prep_s*1e3:9.1f} ms")
    print(f"transfer+launch probe ({n_bytes/1e6:.1f} MB):"
          f"   {transfer_s*1e3:9.1f} ms")
    print(f"full verify wall:                     {full_s*1e3:9.1f} ms")
    print(f"=> on-chip compute ~= full-prep-xfer: {compute_s*1e3:9.1f} ms")
    print(f"tunnel sigs/s: {n/full_s:,.0f}")

    # co-located projection WITH ARITHMETIC
    colo_xfer = n_bytes / 10e9
    colo_launch = 0.001
    colo_wall = prep_s + compute_s + colo_xfer + colo_launch
    print(f"\nco-located projection: prep {prep_s*1e3:.1f} ms "
          f"+ compute {compute_s*1e3:.1f} ms "
          f"+ xfer {n_bytes/1e6:.1f}MB/10GBps = {colo_xfer*1e3:.2f} ms "
          f"+ launch ~1 ms = {colo_wall*1e3:.1f} ms "
          f"=> {n/colo_wall:,.0f} sigs/s")
    print(f"(device-only, prep pipelined away: "
          f"{n/(compute_s + colo_xfer + colo_launch):,.0f} sigs/s)")


if __name__ == "__main__":
    main(tables="--tables" in sys.argv)
