"""Isolate the accel replay pipeline's HOST-side overhead from device
speed (round-4: the fresh interleaved bench measured accel 0.881x CPU
after the pack-cut sped CPU replay up — where do the accel pass's extra
seconds actually go?).

Method: run the full CatchupManager accel path, but monkeypatch
`verify_batch_async` so the "device job" verifies with libsodium ON THE
WORKER THREAD (ctypes releases the GIL, so the main thread's apply
proceeds — an idealized infinitely-overlappable device with CPU-core
throughput).  Compare, interleaved:

  cpu     : accel=False                       (baseline)
  fakedev : accel=True + libsodium worker     (pipeline overhead +
                                               perfectly hidden verify)
  seednop : like fakedev but seeding verdicts is skipped and collect
            returns instantly (measures dispatch-prep + bookkeeping
            alone; apply re-verifies on CPU, so NOT a correctness run —
            hash still asserted since verdicts recompute identically)

If the pipeline is sound, fakedev ≈ cpu − (libsodium verify share) and
seednop ≈ cpu + dispatch_prep.  Gaps between theory and measurement are
the host overhead to hunt.  Runs entirely on CPU JAX (no tunnel).
"""

import os
import sys
import time
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def fake_verify_batch_async(pks, sigs, msgs, **kw):
    """Stand-in device job: verify on the calling (worker) thread with
    libsodium; ctypes releases the GIL per call."""
    from stellar_core_tpu.crypto import sodium

    def collect():
        out = np.zeros(len(pks), dtype=np.int32)
        for i in range(len(pks)):
            out[i] = sodium.verify_detached(sigs[i], msgs[i], pks[i])
        return out
    return collect


def main(rounds=2, n_payment_ledgers=1100):
    import bench
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.crypto import keys
    from stellar_core_tpu.testutils import network_id
    from stellar_core_tpu.accel import ed25519 as accel_ed

    passphrase = "bench network"
    nid = network_id(passphrase)

    with tempfile.TemporaryDirectory() as d:
        print(f"building archive ({n_payment_ledgers} payment ledgers)...",
              flush=True)
        t0 = time.perf_counter()
        archive, mgr = bench.build_archive(
            nid, passphrase, os.path.join(d, "archive"),
            n_payment_ledgers=n_payment_ledgers)
        print(f"  built in {time.perf_counter()-t0:.1f}s", flush=True)
        has = archive.get_state()
        n_ledgers = has.current_ledger
        expected = mgr.lcl_hash

        real_async = accel_ed.verify_batch_async
        results = {"cpu": [], "fakedev": []}
        phase_snap = {}

        for r in range(rounds):
            # --- cpu baseline ---
            keys.clear_verify_cache()
            cm = CatchupManager(nid, passphrase, accel=False)
            t0 = time.perf_counter()
            m = cm.catchup_complete(archive)
            dt = time.perf_counter() - t0
            assert m.lcl_hash == expected
            results["cpu"].append(n_ledgers / dt)
            print(f"round {r+1} cpu    : {n_ledgers/dt:7.1f} l/s "
                  f"({dt:.1f}s)", flush=True)

            # --- fake-device accel ---
            accel_ed.verify_batch_async = fake_verify_batch_async
            try:
                keys.clear_verify_cache()
                cm = CatchupManager(nid, passphrase, accel=True,
                                    accel_chunk=8192)
                t0 = time.perf_counter()
                m = cm.catchup_complete(archive)
                dt = time.perf_counter() - t0
                assert m.lcl_hash == expected, "fakedev replay diverged"
                results["fakedev"].append(n_ledgers / dt)
                phase_snap = dict(cm.stats)
                print(f"round {r+1} fakedev: {n_ledgers/dt:7.1f} l/s "
                      f"({dt:.1f}s)  hit={cm.offload_hit_rate():.3f}",
                      flush=True)
            finally:
                accel_ed.verify_batch_async = real_async

        med = lambda xs: sorted(xs)[len(xs) // 2]
        cpu_r, fake_r = med(results["cpu"]), med(results["fakedev"])
        t_cpu, t_fake = n_ledgers / cpu_r, n_ledgers / fake_r
        sigs_total = phase_snap.get("sigs_total", 0)

        # measure this host's libsodium rate for the theory line
        from stellar_core_tpu.crypto import sodium
        pk, sk = sodium.sign_seed_keypair(b"\x07" * 32)
        msg = b"m" * 120
        sig = sodium.sign_detached(msg, sk)
        t0 = time.perf_counter()
        for _ in range(3000):
            sodium.verify_detached(sig, msg, pk)
        libsodium_rate = 3000 / (time.perf_counter() - t0)
        verify_share_s = sigs_total / libsodium_rate

        print(f"\n=== medians over {rounds} interleaved rounds "
              f"({n_ledgers} ledgers, {sigs_total} sigs) ===")
        print(f"cpu      : {cpu_r:7.1f} l/s  ({t_cpu:.2f}s)")
        print(f"fakedev  : {fake_r:7.1f} l/s  ({t_fake:.2f}s)")
        print(f"libsodium: {libsodium_rate:,.0f} sigs/s "
              f"=> verify share ~{verify_share_s:.2f}s of the cpu pass")
        print(f"theory fakedev floor = cpu - verify = "
              f"{t_cpu - verify_share_s:.2f}s "
              f"({n_ledgers/(t_cpu-verify_share_s):.1f} l/s)")
        print(f"pipeline host overhead = fakedev - floor = "
              f"{t_fake - (t_cpu - verify_share_s):+.2f}s")
        print(f"phases: dispatch_s={phase_snap.get('dispatch_s', 0):.3f} "
              f"collect_wait_s={phase_snap.get('collect_wait_s', 0):.3f} "
              f"groups={phase_snap.get('dispatch_groups', 0)} "
              f"shipped={phase_snap.get('sigs_shipped', 0)}")


if __name__ == "__main__":
    main()
