"""Config-1/4 replay at (near) stated scale (VERDICT r3 item 7: the
bench archive was a 1151-ledger proxy for configs that call for ~10k
pubnet-shaped ledgers; the scale-up had never been attempted).

Builds a BENCH_PAYMENT_LEDGERS-shaped archive once (default 10000
payment ledgers ≈ 10.1k total), then one interleaved (cpu, accel) replay
pair with identical-hash assertion, reporting per-phase pipeline stats.
One pair, not medians: a ~10x-longer pass averages over the drift that
the short bench needs interleaved medians for.

Run ON THE REAL CHIP:  python experiments/replay_at_scale.py [n_payment]
"""

import os
import sys
import time
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(n_payment_ledgers=10000):
    import bench
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.crypto import keys
    from stellar_core_tpu.testutils import network_id

    passphrase = "bench network"
    nid = network_id(passphrase)

    with tempfile.TemporaryDirectory() as d:
        print(f"building archive ({n_payment_ledgers} payment ledgers)...",
              flush=True)
        t0 = time.perf_counter()
        archive, mgr = bench.build_archive(
            nid, passphrase, os.path.join(d, "archive"),
            n_payment_ledgers=n_payment_ledgers)
        print(f"  built in {time.perf_counter()-t0:.1f}s", flush=True)
        has = archive.get_state()
        n_ledgers = has.current_ledger
        expected = mgr.lcl_hash

        print("accel warm pass (compiles)...", flush=True)
        keys.clear_verify_cache()
        CatchupManager(nid, passphrase, accel=True,
                       accel_chunk=8192).catchup_complete(archive,
                                                          to_ledger=127)

        keys.clear_verify_cache()
        cm = CatchupManager(nid, passphrase, accel=False)
        t0 = time.perf_counter()
        m = cm.catchup_complete(archive)
        t_cpu = time.perf_counter() - t0
        assert m.lcl_hash == expected
        print(f"cpu  : {n_ledgers/t_cpu:7.1f} l/s ({t_cpu:.1f}s, "
              f"{n_ledgers} ledgers)", flush=True)

        keys.clear_verify_cache()
        cm = CatchupManager(nid, passphrase, accel=True, accel_chunk=8192)
        t0 = time.perf_counter()
        m = cm.catchup_complete(archive)
        t_acc = time.perf_counter() - t0
        assert m.lcl_hash == expected, "accel replay diverged at scale"
        print(f"accel: {n_ledgers/t_acc:7.1f} l/s ({t_acc:.1f}s)  "
              f"ratio {t_cpu/t_acc:.3f}x  "
              f"hit={cm.offload_hit_rate():.3f}", flush=True)
        st = cm.stats
        print(f"phases: dispatch_s={st.get('dispatch_s', 0):.2f} "
              f"collect_wait_s={st.get('collect_wait_s', 0):.2f} "
              f"groups={st.get('dispatch_groups', 0)} "
              f"sigs={st.get('sigs_shipped', 0)}/{st.get('sigs_total', 0)} "
              f"fallbacks={st.get('collect_fallbacks', 0)}", flush=True)


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 10000)
