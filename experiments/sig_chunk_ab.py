"""Chunk-width A/B for the sig bench config (table path, 64 hot keys,
n=65536) in light of the round-4 in-flight discovery: the backend
pipelines enqueued chunks (+91% allfirst vs serial), so several mid-size
chunks in flight may now match or beat one full-width dispatch that the
round-3 width study (single-chunk-at-a-time) favored.

Run ON THE REAL CHIP:  python experiments/sig_chunk_ab.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(n_total=65536, rounds=4):
    import random
    from stellar_core_tpu.accel.ed25519 import Ed25519BatchVerifier
    from stellar_core_tpu.crypto import sodium

    rng = random.Random(7)
    keys = [sodium.sign_seed_keypair(bytes([i]) * 32) for i in range(64)]
    pks, sigs, msgs = [], [], []
    for i in range(n_total):
        pk, sk = keys[i % 64]
        msg = rng.randbytes(120)
        pks.append(pk)
        sigs.append(sodium.sign_detached(msg, sk))
        msgs.append(msg)

    widths = (8192, 16384, 32768, 65536)
    vs = {}
    for w in widths:
        print(f"warm chunk {w}...", flush=True)
        v = Ed25519BatchVerifier(chunk_size=w)
        v.verify(pks[:w], sigs[:w], msgs[:w])
        vs[w] = v

    results = {w: [] for w in widths}
    for r in range(rounds):
        for w in widths:                      # interleaved within a round
            t0 = time.perf_counter()
            out = vs[w].verify(pks, sigs, msgs)
            dt = time.perf_counter() - t0
            assert int(out.sum()) == n_total
            results[w].append(n_total / dt)
            print(f"round {r+1} chunk {w:6d}: {n_total/dt:8,.0f} sigs/s",
                  flush=True)

    med = lambda xs: sorted(xs)[len(xs) // 2]
    print(f"\n=== medians over {rounds} interleaved rounds (n={n_total}) ===")
    for w in widths:
        print(f"chunk {w:6d}: {med(results[w]):8,.0f} sigs/s")


if __name__ == "__main__":
    main()
