"""Measure the NATIVE C enumerator on the asym orgs=8 map with a hard
interrupt cap (VERDICT r4 item 2: the promised native-C orgs=8 number was
never recorded — record it, or an honest TIMEOUT).

CPU-only: safe to run while the chip is busy elsewhere, but keep other
host load off (1-core host).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(cap_s=1200.0):
    from stellar_core_tpu.herder.quorum_intersection import (
        InterruptedError_, QuorumIntersectionChecker, _cquorum)
    from stellar_core_tpu.testutils import asym_org_qmap

    assert _cquorum is not None, "build the native engine first"
    qmap = asym_org_qmap(8)
    t0 = time.perf_counter()

    def interrupt():
        return time.perf_counter() - t0 > cap_s

    checker = QuorumIntersectionChecker(qmap, interrupt=interrupt)
    try:
        res = checker.check()
        dt = time.perf_counter() - t0
        print(f"orgs=8 native C: {dt:.1f}s intersects={res.intersects} "
              f"max_quorums={res.max_quorums_found}", flush=True)
        if dt > 900.0:
            print("NOTE: above the 900s operational budget", flush=True)
    except InterruptedError_:
        dt = time.perf_counter() - t0
        print(f"orgs=8 native C: TIMEOUT > {cap_s:.0f}s "
              f"(interrupted at {dt:.1f}s; 900s operational budget blown)",
              flush=True)


if __name__ == "__main__":
    main(float(sys.argv[1]) if len(sys.argv) > 1 else 1200.0)
