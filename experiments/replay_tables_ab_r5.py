"""r5: with the native engine, the device is the replay bottleneck
(collect_wait ~4s, accel 0.51x cpu).  A/B the per-key table path
(hot_threshold=4) against generic under the new regime."""
import sys, tempfile, time
sys.path.insert(0, "/root/repo")
import bench
from stellar_core_tpu.catchup.catchup import CatchupManager
from stellar_core_tpu.crypto import keys
from stellar_core_tpu.testutils import network_id

if not bench.probe_device(timeout_s=120, attempts=2):
    print("DEVICE DOWN"); sys.exit(1)
nid = network_id("bench network")
with tempfile.TemporaryDirectory() as d:
    archive, mgr = bench.build_archive(nid, "bench network", d + "/a",
                                       n_payment_ledgers=1100)
    n = mgr.last_closed_ledger_seq
    keys.clear_verify_cache()
    cmw = CatchupManager(nid, "bench network", accel=True, accel_chunk=8192,
                         accel_hot_threshold=4)
    cmw.catchup_complete(archive, to_ledger=127)
    cmw2 = CatchupManager(nid, "bench network", accel=True, accel_chunk=8192)
    cmw2.catchup_complete(archive, to_ledger=127)
    print("warmed", flush=True)
    variants = {
        "cpu": dict(accel=False),
        "accel_generic": dict(accel=True, accel_chunk=8192),
        "accel_tables": dict(accel=True, accel_chunk=8192,
                             accel_hot_threshold=4),
        "accel_tables_c16": dict(accel=True, accel_chunk=16384,
                                 accel_hot_threshold=4),
    }
    rates = {k: [] for k in variants}
    for r in range(3):
        for name, kw in variants.items():
            keys.clear_verify_cache()
            cm = CatchupManager(nid, "bench network", **kw)
            t0 = time.perf_counter()
            m = cm.catchup_complete(archive)
            dt = time.perf_counter() - t0
            assert m.lcl_hash == mgr.lcl_hash, name
            rates[name].append(n / dt)
            print(f"round {r} {name}: {n/dt:.1f} l/s ({dt:.2f}s) "
                  f"wait={cm.stats.get('collect_wait_s', 0):.2f} "
                  f"disp={cm.stats.get('dispatch_s', 0):.2f}", flush=True)
    med = lambda xs: sorted(xs)[len(xs) // 2]
    base = med(rates["cpu"])
    for k in variants:
        print(f"MEDIAN {k}: {med(rates[k]):.1f} l/s "
              f"({med(rates[k])/base:.3f}x vs cpu)")
