"""A/B: per-key window tables on the replay pre-verify path.

Round-3 disabled tables for replay ("install dispatches cost more than
they save at replay batch sizes") — but the verifier and its installed
tables persist across every dispatch group of a catchup, and the bench
archive has only ~150 distinct signing keys, so the install cost is paid
once while the ~2.5x fewer field mults repay it on all ~55k signatures.
Re-test the r3 conclusion, interleaved on the real chip:

  cpu      : accel=False
  generic  : accel=True, hot_threshold=1<<62   (r3 default)
  tables   : accel=True, hot_threshold=4       (tables after 4 sightings)

Run ON THE REAL CHIP:  python experiments/replay_tables_ab.py [rounds]
"""

import os
import sys
import time
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(rounds=3, n_payment_ledgers=1100):
    import bench
    from stellar_core_tpu.catchup.catchup import CatchupManager
    from stellar_core_tpu.crypto import keys
    from stellar_core_tpu.testutils import network_id

    passphrase = "bench network"
    nid = network_id(passphrase)

    with tempfile.TemporaryDirectory() as d:
        print(f"building archive ({n_payment_ledgers} payment ledgers)...",
              flush=True)
        archive, mgr = bench.build_archive(
            nid, passphrase, os.path.join(d, "archive"),
            n_payment_ledgers=n_payment_ledgers)
        has = archive.get_state()
        n_ledgers = has.current_ledger
        expected = mgr.lcl_hash

        variants = [
            ("cpu", dict(accel=False)),
            ("generic", dict(accel=True, accel_chunk=8192)),
            ("tables", dict(accel=True, accel_chunk=8192,
                            accel_hot_threshold=4)),
        ]

        print("warm passes (compiles both accel paths)...", flush=True)
        for name, kw in variants[1:]:
            keys.clear_verify_cache()
            CatchupManager(nid, passphrase, **kw).catchup_complete(
                archive, to_ledger=127)

        results = {name: [] for name, _ in variants}
        stats_snap = {}
        for r in range(rounds):
            for name, kw in variants:
                keys.clear_verify_cache()
                cm = CatchupManager(nid, passphrase, **kw)
                t0 = time.perf_counter()
                m = cm.catchup_complete(archive)
                dt = time.perf_counter() - t0
                assert m.lcl_hash == expected, name
                results[name].append(n_ledgers / dt)
                if name != "cpu":
                    stats_snap[name] = dict(cm.stats)
                print(f"round {r+1} {name:8s}: {n_ledgers/dt:7.1f} l/s "
                      f"({dt:.1f}s)", flush=True)

        med = lambda xs: sorted(xs)[len(xs) // 2]
        base = med(results["cpu"])
        print(f"\n=== medians over {rounds} interleaved rounds "
              f"({n_ledgers} ledgers) ===")
        for name, _ in variants:
            m = med(results[name])
            print(f"{name:8s}: {m:7.1f} l/s  ({m/base:5.3f}x vs cpu)")
        for name, st in stats_snap.items():
            print(f"{name} phases: "
                  f"dispatch_s={st.get('dispatch_s', 0):.3f} "
                  f"collect_wait_s={st.get('collect_wait_s', 0):.3f} "
                  f"groups={st.get('dispatch_groups', 0)} "
                  f"shipped={st.get('sigs_shipped', 0)}"
                  f"/{st.get('sigs_total', 0)}")


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 3)
