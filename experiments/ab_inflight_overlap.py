"""A/B: can successive sig-kernel chunks overlap on the tunneled backend?

VERDICT r3 item 3: verify_async enqueues all chunks then collects once;
on this lazily-executing backend it is unknown whether materializing
chunk k also advances chunk k+1's transfer/compute.  Three variants, all
SINGLE-THREADED (concurrent tunnel calls wedge the client — rig hazard):

  serial   : enqueue chunk k, materialize chunk k      (zero in flight)
  window2  : enqueue k+1 BEFORE materializing k        (one extra in flight)
  allfirst : enqueue every chunk, then materialize all (current verify_async)

If the backend pipelines at all, window2/allfirst beat serial; if it
executes strictly at materialization with no read-ahead, all three tie
(the round-3 hypothesis).  Interleaved in-process rounds — cross-process
A/B is useless on this drifting shared chip (PROFILE.md).

Run ON THE REAL CHIP:  python experiments/ab_inflight_overlap.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_batch(n):
    import random

    from stellar_core_tpu.crypto import sodium
    keys = [sodium.sign_seed_keypair(bytes([i]) * 32) for i in range(64)]
    rng = random.Random(5)
    pks, sigs, msgs = [], [], []
    for i in range(n):
        pk, sk = keys[i % 64]
        msg = rng.randbytes(120)
        pks.append(pk)
        sigs.append(sodium.sign_detached(msg, sk))
        msgs.append(msg)
    return pks, sigs, msgs


def main(chunk=8192, n_chunks=8, rounds=4):
    from stellar_core_tpu.accel import ed25519 as E

    n = chunk * n_chunks
    print(f"building {n} signatures ({n_chunks} chunks of {chunk})...",
          flush=True)
    pks, sigs, msgs = build_batch(n)
    v = E.Ed25519BatchVerifier(chunk_size=chunk, tail_floor=chunk,
                               hot_threshold=1 << 62)
    v.verify(pks[:chunk], sigs[:chunk], msgs[:chunk])   # compile warm

    def chunks():
        for k in range(n_chunks):
            lo = k * chunk
            yield pks[lo:lo + chunk], sigs[lo:lo + chunk], msgs[lo:lo + chunk]

    def run_serial():
        total = 0
        for p, s, m in chunks():
            total += int(v.verify_async(p, s, m)().sum())
        return total

    def run_window2():
        total = 0
        prev = None
        for p, s, m in chunks():
            cur = v.verify_async(p, s, m)      # enqueue k+1 ...
            if prev is not None:
                total += int(prev().sum())     # ... before materializing k
            prev = cur
        total += int(prev().sum())
        return total

    def run_allfirst():
        collectors = [v.verify_async(p, s, m) for p, s, m in chunks()]
        return sum(int(c().sum()) for c in collectors)

    variants = [("serial", run_serial), ("window2", run_window2),
                ("allfirst", run_allfirst)]
    results = {name: [] for name, _ in variants}
    for r in range(rounds):
        for name, fn in variants:             # interleaved within a round
            t0 = time.perf_counter()
            total = fn()
            dt = time.perf_counter() - t0
            assert total == n, (name, total)
            results[name].append(n / dt)
            print(f"round {r+1} {name:9s}: {n/dt:,.0f} sigs/s", flush=True)

    print(f"\n=== medians over {rounds} interleaved rounds ===")
    med = lambda xs: sorted(xs)[len(xs) // 2]
    base = med(results["serial"])
    for name, _ in variants:
        m = med(results[name])
        print(f"{name:9s}: {m:,.0f} sigs/s  ({m/base - 1:+.1%} vs serial)")


if __name__ == "__main__":
    main()
