.PHONY: native test clean

native:
	python setup.py build_ext --inplace

test:
	python -m pytest tests/ -q

clean:
	rm -rf build stellar_core_tpu/_cxdr*.so stellar_core_tpu/_cquorum*.so
