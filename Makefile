.PHONY: native native-live native-asan test lint race metrics obs bucketdb \
	bucketdb-slow chaos chaos-byz chaos-soak loadgen loadgen-slow \
	catchup-par catchup-mesh fleet fleet-soak soroban determinism clean

native:
	python setup.py build_ext --inplace

# native live-close differential tier (ISSUE 13): the 24/24 op-frame
# fuzz corpus + the live-close suite with EVERY close spot-checked
# against the Python oracle (NATIVE_CLOSE_DIFFERENTIAL=1 — results,
# fees, header hash and bucket hashes compared per close; any
# divergence fail-stops with a crash bundle naming the op/ledger)
native-live: native
	env JAX_PLATFORMS=cpu NATIVE_CLOSE_DIFFERENTIAL=1 python -m pytest \
		tests/test_native_close.py tests/test_capply.py -q \
		-p no:cacheprovider -p no:xdist -p no:randomly

# sanitizer tier (ISSUE 15): rebuild the engine with
# -fsanitize=address,undefined (own .so cache under build/asan/, never
# shadowing the regular build) and run the native-close differential
# tier plus the three test_native_close fuzz suites (24-op corpus,
# path-payment/pool, sponsorship sandwich) with the ASan runtime
# LD_PRELOADed and halt_on_error=1 — any out-of-bounds read, UB, or
# heap misuse in the C engine fail-stops the suite.  SKIPs cleanly
# (exit 0, notice printed) when cc/libasan is absent.
native-asan:
	env JAX_PLATFORMS=cpu NATIVE_CLOSE_DIFFERENTIAL=1 \
		python -m stellar_core_tpu._native_build --asan-exec \
		python -m pytest tests/test_native_close.py tests/test_capply.py \
		-q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# corelint: project-native static analysis (clock discipline, LedgerTxn
# paths, decode-free seam, exception hygiene, metric registry, lock
# order — plus the native-C pass over native/*.c: reader-discipline,
# memcpy-provenance, unchecked-alloc, handler-result-discipline,
# overlay-pairing).  LINT_BASELINE.json ratchets the explicit
# suppressions (Python AND C): new violations OR new suppressions fail;
# regenerate the baseline with
# `python -m stellar_core_tpu.lint --write-baseline LINT_BASELINE.json`
# only after justifying the new suppression in review.  The second step
# re-compiles native/*.c with -Wall -Wextra -Werror (syntax-only) so a
# new C warning fails the gate here while end-user builds merely warn;
# it exits 0 with a notice when no compiler exists (fallback intact).
lint:
	env JAX_PLATFORMS=cpu python -m stellar_core_tpu.lint \
		--baseline LINT_BASELINE.json
	python -m stellar_core_tpu._native_build --warn-check

test: lint determinism
	python -m pytest tests/ -q

# determinism tier (ISSUE 19): (1) the four consensus-path determinism
# rules alone, tree-wide (iteration-order / float-discipline /
# hash-order / rng-discipline — also part of `make lint` via the full
# rule set); (2) the chaos small tier with the detguard runtime guard
# armed (STPU_DETGUARD=1): any wall-clock read, unseeded RNG draw or
# str/bytes hash() inside a guarded consensus region — ledger close,
# nomination, Soroban apply — fail-stops with DeterminismError + crash
# bundle; (3) the hash-seed divergence differential: the 51-node
# flagship chaos campaign AND the Soroban mixed campaign in paired
# subprocesses under two different PYTHONHASHSEED values, canonical
# slot→hash tables and bucket hashes asserted byte-identical, detguard
# armed in every child with zero trips.
determinism:
	env JAX_PLATFORMS=cpu python -m stellar_core_tpu.lint --rules \
		iteration-order,float-discipline,hash-order,rng-discipline
	env JAX_PLATFORMS=cpu STPU_DETGUARD=1 python -m pytest \
		tests/test_chaos.py -q -m 'not slow' \
		-p no:cacheprovider -p no:xdist -p no:randomly
	env JAX_PLATFORMS=cpu python -m stellar_core_tpu.simulation.hashseed_diff

# race-sanitizer soak (ISSUE 9): the threaded test subset — admission
# (incl. the loopback-flood hysteresis soak and the http-style marshalled
# submission test), the thread-safety suite itself, and the chaos
# scenario tier (INCLUDING the ISSUE 12 byzantine tier: equivocation
# campaigns + the in-sim archive-recovery handoff run with the sanitizer
# armed) — with STPU_RACE_TRACE=1 so every @race_checked class is
# instrumented and every make_lock lock feeds the per-field locksets.
# An unguarded cross-thread write fail-stops with DataRaceError + crash
# bundle.  Overhead: ~1.1µs per tracked access (PROFILE.md round 8).
race:
	env JAX_PLATFORMS=cpu STPU_RACE_TRACE=1 python -m pytest \
		tests/test_thread_safety.py tests/test_admission.py \
		tests/test_chaos.py -q -m 'not slow' \
		-p no:cacheprovider -p no:xdist -p no:randomly

# BucketListDB differential suite: on-disk index round-trip + corruption
# fail-stop, snapshot consistency across closes, LRU bound, the
# dict-vs-disk multi-checkpoint replay hash identity, plus phase 2 —
# randomized merge_buckets vs merge_buckets_raw differentials and the
# disk-resident RSS regression guard (deep randomized chains are -m slow;
# run with `make bucketdb-slow` to include them)
bucketdb:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_bucketlistdb.py \
		tests/test_bucket_streaming.py -q -m 'not slow' \
		-p no:cacheprovider -p no:xdist -p no:randomly

bucketdb-slow:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_bucketlistdb.py \
		tests/test_bucket_streaming.py -q \
		-p no:cacheprovider -p no:xdist -p no:randomly

# incident-observability suite: flight recorder + crash bundles, /health
# + StatusManager, trace-correlated JSON logging, admin error paths, the
# metrics/trace exposition surface, and the fleet observability plane
# (cross-node trace merge, sampling profiler, SLO burn tracking,
# historical time-series store + anomaly detection)
obs:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_observability.py \
		tests/test_eventlog.py tests/test_fleettrace.py \
		tests/test_sampleprof.py tests/test_slo.py \
		tests/test_timeseries.py tests/test_anomaly.py \
		-q -m 'not slow' \
		-p no:cacheprovider -p no:xdist -p no:randomly

# chaos campaigns: the small-topology scenario tier (12-51 nodes —
# partition/flap/heal, stall+rejoin, corrupted floods, link-fault ramps,
# the quorum-split liveness-detection proof) plus the scheduler/replay/
# health unit tests.  `chaos-soak` adds the -m slow 100- and 300-node
# campaigns.
chaos:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
		-m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# byzantine chaos tier (ISSUE 12): equivocation / conflicting-nomination
# / stale-replay campaigns from SIGNING validators, the generated
# intersection-violation fork-detection proof, and the in-sim
# out-of-sync -> archive -> re-tracking handoff (single-stream AND
# range-parallel catchup).  The same tests run sanitizer-armed in
# `make race`.
chaos-byz:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py \
		-k 'Byzantine or ArchiveRecovery' -q -m 'not slow' \
		-p no:cacheprovider -p no:xdist -p no:randomly

chaos-soak:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q \
		-p no:cacheprovider -p no:xdist -p no:randomly

# sustained-ingestion suite: AdmissionPipeline latency floor + batching +
# overload semantics through the admission path, back-pressure into
# overlay flow control and /health, and the small-tier (60k-account)
# load campaign over BucketListDB.  `loadgen-slow` adds the -m slow
# million-account campaign (RSS-guarded).
loadgen:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_admission.py -q \
		-m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

loadgen-slow:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_admission.py -q \
		-p no:cacheprovider -p no:xdist -p no:randomly

# range-parallel catchup suite (ISSUE 10): plan/stitch units, real
# subprocess-worker e2e hash identity vs the single-stream replay,
# per-range retry-with-backoff, and the fail-stop discipline — tampered
# interior ranges (corrupt assumed bucket, forged stitch record) must
# crash-bundle and leave the authoritative ledger dir untouched.
catchup-par:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_catchup_parallel.py \
		-q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# mesh + work-stealing suite (ISSUE 14): steal-plan units, the
# limit/ack handshake, forged-steal-seam fail-stop, the straggler-
# injected e2e (steal beats no-steal in wall clock), and the
# device-pinning path over the CPU-SIMULATED 8-device mesh
# (--xla_force_host_platform_device_count) — so per-worker visible-
# device threading runs in every verify, not only on-chip.
catchup-mesh:
	env JAX_PLATFORMS=cpu \
		XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		python -m pytest tests/test_catchup_mesh.py \
		-q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# fleet harness suite (ISSUE 11): provisioning/schedule/SLO units plus
# the 5-node real-process TCP soak — kill + `catchup --parallel` rejoin,
# overlay partition + heal, rolling config change, zero hash divergence,
# SLOs asserted.  `fleet-soak` adds the -m slow long campaign (overload
# burst + extended partition).
fleet:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q \
		-m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

fleet-soak:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_fleet.py -q \
		-p no:cacheprovider -p no:xdist -p no:randomly

# Soroban execution subsystem (ISSUE 17): bounded-host metering
# (budget-exceeded differential: fee charged, state untouched),
# footprint enforcement fail-stop, TTL extend/restore/eviction,
# generalized tx sets through nomination and the wire, and the
# footprint-scheduled parallel-apply campaign — >=50 mixed ledgers with
# byte-identical bucket-list hashes serial vs parallel, >=4 disjoint
# clusters applied concurrently in at least one ledger.
soroban:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_soroban.py -q \
		-m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

# metric-name lint: every name recorded by a simulated ledger close must
# match layer.subsystem.event and appear in the documented canonical list
metrics:
	env JAX_PLATFORMS=cpu python -m pytest tests/test_observability.py -q \
		-m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly \
		-k 'MetricNameLint or prometheus'

clean:
	rm -rf build stellar_core_tpu/_cxdr*.so stellar_core_tpu/_cquorum*.so
