"""Test/loadgen helpers: tx builders and TestAccount.

Reference: src/test/TxTests.{h,cpp} and src/test/TestAccount.{h,cpp} —
the fixtures every reference test suite builds on (SURVEY.md §4).
Lives in the package (not tests/) because LoadGenerator and Simulation
reuse it, mirroring the reference layout.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import xdr as X
from .crypto.keys import SecretKey
from .crypto.sha import sha256
from .transactions.frame import TransactionFrame


def native_payment_op(dest: X.AccountID, amount: int,
                      source: Optional[X.AccountID] = None) -> X.Operation:
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.paymentOp(X.PaymentOp(
            destination=X.muxed_from_account_id(dest),
            asset=X.Asset.native(), amount=amount)))


def create_account_op(dest: X.AccountID, starting_balance: int,
                      source: Optional[X.AccountID] = None) -> X.Operation:
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.createAccountOp(X.CreateAccountOp(
            destination=dest, startingBalance=starting_balance)))


def build_tx(network_id: bytes, source: SecretKey, seq_num: int,
             ops: Sequence[X.Operation], fee: Optional[int] = None,
             memo: Optional[X.Memo] = None,
             time_bounds: Optional[X.TimeBounds] = None,
             extra_signers: Sequence[SecretKey] = ()) -> TransactionFrame:
    """Build + sign a v1 envelope (reference: TxTests — transactionFromOps)."""
    tx = X.Transaction(
        sourceAccount=X.MuxedAccount.ed25519(source.public_key.ed25519),
        fee=fee if fee is not None else 100 * len(ops),
        seqNum=seq_num,
        cond=(X.Preconditions.timeBounds(time_bounds)
              if time_bounds is not None else X.Preconditions.none()),
        memo=memo if memo is not None else X.Memo.none(),
        operations=list(ops))
    env = X.TransactionEnvelope.v1(
        X.TransactionV1Envelope(tx=tx, signatures=[]))
    frame = TransactionFrame(network_id, env)
    payload_hash = frame.content_hash()
    for signer in (source, *extra_signers):
        env.value.signatures.append(X.DecoratedSignature(
            hint=signer.public_key.hint(),
            signature=signer.sign(payload_hash)))
    return frame


class TestAccount:
    """Sequence-tracking account handle (reference: src/test/TestAccount.h)."""

    __test__ = False  # not a pytest collection target

    def __init__(self, mgr, secret: SecretKey, seq_num: int):
        self.mgr = mgr
        self.secret = secret
        self.seq_num = seq_num

    @property
    def account_id(self) -> X.AccountID:
        return X.AccountID.ed25519(self.secret.public_key.ed25519)

    def next_seq(self) -> int:
        self.seq_num += 1
        return self.seq_num

    def tx(self, ops: Sequence[X.Operation], **kwargs) -> TransactionFrame:
        return build_tx(self.mgr.network_id, self.secret, self.next_seq(),
                        ops, **kwargs)


def network_id(passphrase: str) -> bytes:
    """networkID = SHA256(passphrase) (reference: src/main/Config.cpp)."""
    return sha256(passphrase.encode())


TESTNET_PASSPHRASE = "Test SDF Network ; September 2015"
