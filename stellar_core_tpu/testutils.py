"""Test/loadgen helpers: tx builders and TestAccount.

Reference: src/test/TxTests.{h,cpp} and src/test/TestAccount.{h,cpp} —
the fixtures every reference test suite builds on (SURVEY.md §4).
Lives in the package (not tests/) because LoadGenerator and Simulation
reuse it, mirroring the reference layout.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from . import xdr as X
from .crypto.keys import SecretKey
from .crypto.sha import sha256
from .transactions.frame import TransactionFrame


def native_payment_op(dest: X.AccountID, amount: int,
                      source: Optional[X.AccountID] = None) -> X.Operation:
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.paymentOp(X.PaymentOp(
            destination=X.muxed_from_account_id(dest),
            asset=X.Asset.native(), amount=amount)))


def create_account_op(dest: X.AccountID, starting_balance: int,
                      source: Optional[X.AccountID] = None) -> X.Operation:
    return X.Operation(
        sourceAccount=(X.muxed_from_account_id(source)
                       if source is not None else None),
        body=X.OperationBody.createAccountOp(X.CreateAccountOp(
            destination=dest, startingBalance=starting_balance)))


def build_tx(network_id: bytes, source: SecretKey, seq_num: int,
             ops: Sequence[X.Operation], fee: Optional[int] = None,
             memo: Optional[X.Memo] = None,
             time_bounds: Optional[X.TimeBounds] = None,
             extra_signers: Sequence[SecretKey] = (),
             signers: Optional[Sequence[SecretKey]] = None,
             soroban_data=None) -> TransactionFrame:
    """Build + sign a v1 envelope (reference: TxTests — transactionFromOps).
    `signers` overrides the signing set entirely (e.g. a multisig tx signed
    only by an added signer, not the master key)."""
    tx = X.Transaction(
        sourceAccount=X.MuxedAccount.ed25519(source.public_key.ed25519),
        fee=fee if fee is not None else 100 * len(ops),
        seqNum=seq_num,
        cond=(X.Preconditions.timeBounds(time_bounds)
              if time_bounds is not None else X.Preconditions.none()),
        memo=memo if memo is not None else X.Memo.none(),
        operations=list(ops))
    if soroban_data is not None:
        tx.ext = X.TransactionExt.sorobanData(soroban_data)
    env = X.TransactionEnvelope.v1(
        X.TransactionV1Envelope(tx=tx, signatures=[]))
    frame = TransactionFrame(network_id, env)
    payload_hash = frame.content_hash()
    signing_set = (tuple(signers) if signers is not None
                   else (source, *extra_signers))
    for signer in signing_set:
        env.value.signatures.append(X.DecoratedSignature(
            hint=signer.public_key.hint(),
            signature=signer.sign(payload_hash)))
    return frame


class TestAccount:
    """Sequence-tracking account handle (reference: src/test/TestAccount.h)."""

    __test__ = False  # not a pytest collection target

    def __init__(self, mgr, secret: SecretKey, seq_num: int):
        self.mgr = mgr
        self.secret = secret
        self.seq_num = seq_num

    @property
    def account_id(self) -> X.AccountID:
        return X.AccountID.ed25519(self.secret.public_key.ed25519)

    def next_seq(self) -> int:
        self.seq_num += 1
        return self.seq_num

    def tx(self, ops: Sequence[X.Operation], **kwargs) -> TransactionFrame:
        return build_tx(self.mgr.network_id, self.secret, self.next_seq(),
                        ops, **kwargs)


def network_id(passphrase: str) -> bytes:
    """networkID = SHA256(passphrase) (reference: src/main/Config.cpp)."""
    return sha256(passphrase.encode())


TESTNET_PASSPHRASE = "Test SDF Network ; September 2015"


# --- offer / trust / path-payment / pool op builders ----------------------

def make_asset(code: str, issuer: X.AccountID) -> X.Asset:
    raw = code.encode()
    if len(raw) <= 4:
        return X.Asset.alphaNum4(X.AlphaNum4(
            assetCode=raw.ljust(4, b"\x00"), issuer=issuer))
    return X.Asset.alphaNum12(X.AlphaNum12(
        assetCode=raw.ljust(12, b"\x00"), issuer=issuer))


def _src(source):
    return (X.muxed_from_account_id(source) if source is not None else None)


def change_trust_op(asset: X.Asset, limit: int = 2**63 - 1,
                    source=None) -> X.Operation:
    line = X.ChangeTrustAsset(asset.switch, asset.value)
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.changeTrustOp(
                           X.ChangeTrustOp(line=line, limit=limit)))


def change_trust_pool_op(asset_a: X.Asset, asset_b: X.Asset,
                         limit: int = 2**63 - 1, fee: int = 30,
                         source=None) -> X.Operation:
    params = X.LiquidityPoolParameters.constantProduct(
        X.LiquidityPoolConstantProductParameters(
            assetA=asset_a, assetB=asset_b, fee=fee))
    line = X.ChangeTrustAsset.liquidityPool(params)
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.changeTrustOp(
                           X.ChangeTrustOp(line=line, limit=limit)))


def payment_op(dest: X.AccountID, asset: X.Asset, amount: int,
               source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.paymentOp(X.PaymentOp(
                           destination=X.muxed_from_account_id(dest),
                           asset=asset, amount=amount)))


def manage_sell_offer_op(selling: X.Asset, buying: X.Asset, amount: int,
                         price_n: int, price_d: int, offer_id: int = 0,
                         source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.manageSellOfferOp(
                           X.ManageSellOfferOp(
                               selling=selling, buying=buying, amount=amount,
                               price=X.Price(n=price_n, d=price_d),
                               offerID=offer_id)))


def manage_buy_offer_op(selling: X.Asset, buying: X.Asset, buy_amount: int,
                        price_n: int, price_d: int, offer_id: int = 0,
                        source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.manageBuyOfferOp(
                           X.ManageBuyOfferOp(
                               selling=selling, buying=buying,
                               buyAmount=buy_amount,
                               price=X.Price(n=price_n, d=price_d),
                               offerID=offer_id)))


def create_passive_sell_offer_op(selling: X.Asset, buying: X.Asset,
                                 amount: int, price_n: int, price_d: int,
                                 source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.createPassiveSellOfferOp(
                           X.CreatePassiveSellOfferOp(
                               selling=selling, buying=buying, amount=amount,
                               price=X.Price(n=price_n, d=price_d))))


def path_payment_strict_receive_op(send_asset: X.Asset, send_max: int,
                                   dest: X.AccountID, dest_asset: X.Asset,
                                   dest_amount: int, path=(),
                                   source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.pathPaymentStrictReceiveOp(
                           X.PathPaymentStrictReceiveOp(
                               sendAsset=send_asset, sendMax=send_max,
                               destination=X.muxed_from_account_id(dest),
                               destAsset=dest_asset, destAmount=dest_amount,
                               path=list(path))))


def path_payment_strict_send_op(send_asset: X.Asset, send_amount: int,
                                dest: X.AccountID, dest_asset: X.Asset,
                                dest_min: int, path=(),
                                source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.pathPaymentStrictSendOp(
                           X.PathPaymentStrictSendOp(
                               sendAsset=send_asset, sendAmount=send_amount,
                               destination=X.muxed_from_account_id(dest),
                               destAsset=dest_asset, destMin=dest_min,
                               path=list(path))))


def liquidity_pool_deposit_op(pool_id: bytes, max_a: int, max_b: int,
                              min_price=(1, 10**7), max_price=(10**7, 1),
                              source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.liquidityPoolDepositOp(
                           X.LiquidityPoolDepositOp(
                               liquidityPoolID=pool_id,
                               maxAmountA=max_a, maxAmountB=max_b,
                               minPrice=X.Price(n=min_price[0], d=min_price[1]),
                               maxPrice=X.Price(n=max_price[0], d=max_price[1]))))


def liquidity_pool_withdraw_op(pool_id: bytes, amount: int, min_a: int = 0,
                               min_b: int = 0, source=None) -> X.Operation:
    return X.Operation(sourceAccount=_src(source),
                       body=X.OperationBody.liquidityPoolWithdrawOp(
                           X.LiquidityPoolWithdrawOp(
                               liquidityPoolID=pool_id, amount=amount,
                               minAmountA=min_a, minAmountB=min_b)))


# --- protocol version sweep (reference: src/test/TxTests — for_all_versions)

SUPPORTED_PROTOCOL_RANGE = range(10, 24)   # earliest gated .. current


def for_all_versions(network_id: bytes, body, versions=None) -> None:
    """Run `body(mgr, version)` against a fresh genesis ledger at every
    protocol level (reference: for_all_versions in TxTests — apply-time
    behavior must be checked under each gated protocol)."""
    from .ledger.manager import LedgerManager
    for version in (versions or SUPPORTED_PROTOCOL_RANGE):
        mgr = LedgerManager(network_id)
        mgr.start_new_ledger(protocol_version=version)
        body(mgr, version)


# --- quorum map generators (shared by bench.py config 5 and the accel
# quorum differential tests — one definition so the bench and the tests
# always exercise the same contraction-proof family)

def asym_org_qmap(n_orgs: int):
    """Config 5's exponential class: org sizes cycle 3/4/5 (majority inner
    thresholds) and each org's nodes carry a byte-distinct qset (org list
    rotated per org), so the symmetric-org contraction cannot apply and the
    exact checker must enumerate."""
    sizes = [3 + (i % 3) for i in range(n_orgs)]
    orgs = []
    for o, sz in enumerate(sizes):
        orgs.append([bytes([o + 1]) * 31 + bytes([v]) for v in range(sz)])

    def inner(o):
        return X.SCPQuorumSet(
            threshold=sizes[o] // 2 + 1,
            validators=[X.NodeID.ed25519(m) for m in orgs[o]],
            innerSets=[])

    qmap = {}
    thr = (2 * n_orgs + 2) // 3
    for o in range(n_orgs):
        rotated = [inner((o + j) % n_orgs) for j in range(n_orgs)]
        q = X.SCPQuorumSet(threshold=thr, validators=[], innerSets=rotated)
        for m in orgs[o]:
            qmap[m] = q
    return qmap


# --- Soroban tx builders (reference: src/test/TxTests — sorobanTransactionFrameFromOps)

def contract_address(tag: int) -> "X.SCAddress":
    """Deterministic contract address from a small integer tag."""
    return X.SCAddress.contractId(bytes([tag]) * 32)


def invoke_op(contract: "X.SCAddress", fname: str,
              args: Sequence["X.SCVal"], source=None) -> X.Operation:
    return X.Operation(
        sourceAccount=_src(source),
        body=X.OperationBody.invokeHostFunctionOp(X.InvokeHostFunctionOp(
            hostFunction=X.HostFunction.invokeContract(X.InvokeContractArgs(
                contractAddress=contract, functionName=fname,
                args=list(args))))))


def extend_ttl_op(extend_to: int, source=None) -> X.Operation:
    return X.Operation(
        sourceAccount=_src(source),
        body=X.OperationBody.extendFootprintTTLOp(X.ExtendFootprintTTLOp(
            ext=X.ExtensionPoint.v0(), extendTo=extend_to)))


def restore_footprint_op(source=None) -> X.Operation:
    return X.Operation(
        sourceAccount=_src(source),
        body=X.OperationBody.restoreFootprintOp(X.RestoreFootprintOp(
            ext=X.ExtensionPoint.v0())))


def make_soroban_data(read_only: Sequence["X.LedgerKey"] = (),
                      read_write: Sequence["X.LedgerKey"] = (),
                      instructions: int = 1_000_000,
                      read_bytes: int = 10_000, write_bytes: int = 10_000,
                      resource_fee: Optional[int] = None
                      ) -> "X.SorobanTransactionData":
    """Resource declaration with a fee that (by default) meets the network
    minimum for the declared resources."""
    resources = X.SorobanResources(
        footprint=X.LedgerFootprint(readOnly=list(read_only),
                                    readWrite=list(read_write)),
        instructions=instructions, readBytes=read_bytes,
        writeBytes=write_bytes)
    if resource_fee is None:
        from .soroban import network_config
        resource_fee = network_config().min_resource_fee(resources)
    return X.SorobanTransactionData(
        ext=X.ExtensionPoint.v0(), resources=resources,
        resourceFee=resource_fee)
