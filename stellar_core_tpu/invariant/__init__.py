"""Invariant framework (reference: src/invariant/)."""

from .invariants import (ALL_INVARIANTS, AccountSubEntriesCountIsValid,
                         BucketListIsConsistentWithDatabase,
                         ConservationOfLumens, ConstantProductInvariant,
                         Invariant, InvariantDoesNotHold, InvariantManager,
                         LedgerCloseContext, LedgerEntryIsValid,
                         LiabilitiesMatchOffers, SponsorshipCountIsValid)

__all__ = [
    "ALL_INVARIANTS", "AccountSubEntriesCountIsValid",
    "BucketListIsConsistentWithDatabase", "ConservationOfLumens",
    "ConstantProductInvariant", "Invariant", "InvariantDoesNotHold",
    "InvariantManager", "LedgerCloseContext", "LedgerEntryIsValid",
    "LiabilitiesMatchOffers", "SponsorshipCountIsValid",
]
