"""Invariant framework: optional fail-stop consistency checks on ledger close.

Reference: src/invariant/ — InvariantManagerImpl::{checkOnOperationApply,
checkOnBucketApply}, ConservationOfLumens, AccountSubEntriesCountIsValid,
LiabilitiesMatchOffers, BucketListIsConsistentWithDatabase,
LedgerEntryIsValid.  A violated invariant throws InvariantDoesNotHold and
the node crashes (fail-stop), same as the reference.

Design difference, deliberate: the reference hooks every operation apply
with a per-op LedgerTxnDelta; here the LedgerManager hands the whole
ledger-close delta (pre/post entry pairs + pre/post headers) to the manager
once per close.  Same invariants, coarser granularity — a violation names
the ledger, the tests bisect the op.  This keeps the apply path free of
per-op callback plumbing.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, Optional, Tuple

from .. import xdr as X


class InvariantDoesNotHold(Exception):
    """Fail-stop: raised out of close_ledger, never caught internally."""


def _fail_invariant(msg: str) -> None:
    """Record the violation as a flight event and write a post-mortem
    bundle (util/eventlog → $STPU_CRASH_DIR) before the fail-stop —
    the crash artifact is what the operator reads instead of a bare
    traceback."""
    from ..util import eventlog
    eventlog.record("Invariant", "ERROR", "invariant does not hold",
                    detail=msg)
    eventlog.write_crash_bundle(f"InvariantDoesNotHold: {msg}")
    raise InvariantDoesNotHold(msg)


class LedgerCloseContext:
    """Everything an invariant may inspect for one close.

    pre / post map delta key-bytes -> entry-or-None (None = absent).  Keys
    not in the delta were untouched; `post_state(kb)` falls back to the
    authoritative store for those.
    """

    def __init__(self, pre: Dict[bytes, Optional[X.LedgerEntry]],
                 post: Dict[bytes, Optional[X.LedgerEntry]],
                 pre_header: X.LedgerHeader, post_header: X.LedgerHeader,
                 root_get: Callable[[bytes], Optional[X.LedgerEntry]],
                 all_keys: Callable[[], "list[bytes]"],
                 bucket_list=None):
        self.pre = pre
        self.post = post
        self.pre_header = pre_header
        self.post_header = post_header
        self._root_get = root_get
        self._all_keys = all_keys
        self.bucket_list = bucket_list

    def post_state(self, kb: bytes) -> Optional[X.LedgerEntry]:
        if kb in self.post:
            return self.post[kb]
        return self._root_get(kb)

    def iter_post_keys(self):
        seen = set()
        for kb in self._all_keys():
            seen.add(kb)
            if self.post_state(kb) is not None:
                yield kb
        for kb, e in self.post.items():
            if kb not in seen and e is not None:
                yield kb


class Invariant:
    NAME = "?"
    # invariants that read the bucket list run after add_batch; the rest run
    # before it, so their failure leaves the LedgerManager un-torn (neither
    # root store nor bucket list has advanced)
    NEEDS_BUCKETS = False

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        """Return an error message, or None if the invariant holds."""
        raise NotImplementedError

    def check_on_bucket_apply(self, entry: X.BucketEntry, level: int,
                              header_seq: int) -> Optional[str]:
        """Per-entry check while assuming state from bucket files
        (reference: InvariantManagerImpl::checkOnBucketApply).  Default:
        nothing to check."""
        return None


# ---------------------------------------------------------------------------

def _native_held(entry: Optional[X.LedgerEntry]) -> int:
    """Stroops of native XLM held inside a ledger entry (reference:
    ConservationOfLumens sums balances across accounts, native claimable
    balances and native pool reserves)."""
    if entry is None:
        return 0
    d = entry.data
    t = d.switch
    if t == X.LedgerEntryType.ACCOUNT:
        return d.value.balance
    if t == X.LedgerEntryType.CLAIMABLE_BALANCE:
        cb = d.value
        if cb.asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            return cb.amount
        return 0
    if t == X.LedgerEntryType.LIQUIDITY_POOL:
        cp = d.value.body.value
        held = 0
        if cp.params.assetA.switch == X.AssetType.ASSET_TYPE_NATIVE:
            held += cp.reserveA
        if cp.params.assetB.switch == X.AssetType.ASSET_TYPE_NATIVE:
            held += cp.reserveB
        return held
    return 0


class ConservationOfLumens(Invariant):
    """Σ native held + feePool is constant except for explicit totalCoins
    changes (inflation).  Reference: src/invariant/ConservationOfLumens.cpp."""
    NAME = "ConservationOfLumens"

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        d_held = 0
        for kb in set(ctx.pre) | set(ctx.post):
            d_held += _native_held(ctx.post.get(kb)) \
                - _native_held(ctx.pre.get(kb))
        d_fee = ctx.post_header.feePool - ctx.pre_header.feePool
        d_total = ctx.post_header.totalCoins - ctx.pre_header.totalCoins
        if d_held + d_fee != d_total:
            return (f"lumens not conserved: Δheld={d_held} ΔfeePool={d_fee} "
                    f"ΔtotalCoins={d_total}")
        return None


def _subentry_owner(kb: bytes) -> Optional[Tuple[bytes, int]]:
    """(owner AccountID xdr, subentry weight) for subentry-type keys."""
    key = X.LedgerKey.from_xdr(kb)
    t = key.switch
    if t == X.LedgerEntryType.TRUSTLINE:
        w = 2 if key.value.asset.switch == \
            X.AssetType.ASSET_TYPE_POOL_SHARE else 1
        return key.value.accountID.to_xdr(), w
    if t == X.LedgerEntryType.OFFER:
        return key.value.sellerID.to_xdr(), 1
    if t == X.LedgerEntryType.DATA:
        return key.value.accountID.to_xdr(), 1
    return None


class AccountSubEntriesCountIsValid(Invariant):
    """Δ numSubEntries of each touched account equals the Δ of subentries it
    owns (signers + trustlines [pool share = 2] + offers + data); a deleted
    account owns none afterwards.  Reference:
    src/invariant/AccountSubEntriesCountIsValid.cpp."""
    NAME = "AccountSubEntriesCountIsValid"

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        d_sub: Dict[bytes, int] = {}      # owner -> subentry count delta
        d_declared: Dict[bytes, int] = {}  # owner -> numSubEntries delta
        for kb in set(ctx.pre) | set(ctx.post):
            pre_e, post_e = ctx.pre.get(kb), ctx.post.get(kb)
            owner = _subentry_owner(kb)
            if owner is not None:
                aid, w = owner
                d_sub[aid] = d_sub.get(aid, 0) \
                    + w * ((post_e is not None) - (pre_e is not None))
                continue
            key = X.LedgerKey.from_xdr(kb)
            if key.switch != X.LedgerEntryType.ACCOUNT:
                continue
            aid = key.value.accountID.to_xdr()
            pre_n = pre_e.data.value.numSubEntries if pre_e else 0
            post_n = post_e.data.value.numSubEntries if post_e else 0
            pre_s = len(pre_e.data.value.signers) if pre_e else 0
            post_s = len(post_e.data.value.signers) if post_e else 0
            d_declared[aid] = d_declared.get(aid, 0) + (post_n - pre_n)
            d_sub[aid] = d_sub.get(aid, 0) + (post_s - pre_s)
        # a deleted account needs no special case: merge requires
        # numSubEntries == 0 first, so Δdeclared == Δowned holds uniformly
        # (orphaned subentries left behind would break the equality here)
        for aid in set(d_sub) | set(d_declared):
            if d_sub.get(aid, 0) != d_declared.get(aid, 0):
                return (f"numSubEntries delta {d_declared.get(aid, 0)} != "
                        f"owned subentry delta {d_sub.get(aid, 0)} for "
                        f"account {aid.hex()[:16]}")
        return None


class LiabilitiesMatchOffers(Invariant):
    """For every account/trustline touched this ledger, recorded
    buying/selling liabilities equal the aggregate over that owner's resting
    offers in post state (issuers carry none in their own asset).
    Reference: src/invariant/LiabilitiesMatchOffers.cpp."""
    NAME = "LiabilitiesMatchOffers"

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        from ..transactions.offer_exchange import (
            offer_buying_liabilities, offer_selling_liabilities)
        from ..transactions.utils import is_issuer

        # aggregate liabilities per (owner, asset) over ALL post-state offers
        agg: Dict[Tuple[bytes, bytes], List[int]] = {}  # -> [buying, selling]
        tag = int(X.LedgerEntryType.OFFER).to_bytes(4, "big")
        for kb in ctx.iter_post_keys():
            if not kb.startswith(tag):
                continue
            offer = ctx.post_state(kb).data.value
            sid = offer.sellerID
            if not is_issuer(sid, offer.selling):
                k = (sid.to_xdr(), offer.selling.to_xdr())
                agg.setdefault(k, [0, 0])[1] += \
                    offer_selling_liabilities(offer.price, offer.amount)
            if not is_issuer(sid, offer.buying):
                k = (sid.to_xdr(), offer.buying.to_xdr())
                agg.setdefault(k, [0, 0])[0] += \
                    offer_buying_liabilities(offer.price, offer.amount)

        native = X.Asset(X.AssetType.ASSET_TYPE_NATIVE, None).to_xdr()
        for kb in set(ctx.pre) | set(ctx.post):
            e = ctx.post.get(kb)
            if e is None:
                continue
            t = e.data.switch
            if t == X.LedgerEntryType.ACCOUNT:
                acc = e.data.value
                if acc.ext.switch == 0:
                    rec_b = rec_s = 0
                else:
                    li = acc.ext.value.liabilities
                    rec_b, rec_s = li.buying, li.selling
                want = agg.get((acc.accountID.to_xdr(), native), [0, 0])
                if [rec_b, rec_s] != want:
                    return (f"native liabilities ({rec_b},{rec_s}) != offer "
                            f"aggregate ({want[0]},{want[1]}) for account "
                            f"{acc.accountID.to_xdr().hex()[:16]}")
            elif t == X.LedgerEntryType.TRUSTLINE:
                tl = e.data.value
                if tl.asset.switch == X.AssetType.ASSET_TYPE_POOL_SHARE:
                    continue
                if tl.ext.switch == 0:
                    rec_b = rec_s = 0
                else:
                    li = tl.ext.value.liabilities
                    rec_b, rec_s = li.buying, li.selling
                asset = X.Asset(tl.asset.switch, tl.asset.value).to_xdr()
                want = agg.get((tl.accountID.to_xdr(), asset), [0, 0])
                if [rec_b, rec_s] != want:
                    return (f"trustline liabilities ({rec_b},{rec_s}) != "
                            f"offer aggregate ({want[0]},{want[1]}) for "
                            f"{tl.accountID.to_xdr().hex()[:16]}")
        return None


class BucketListIsConsistentWithDatabase(Invariant):
    """Every key this close touched must read back from the bucket list as
    exactly the post-state entry (or be absent/dead when deleted).
    Reference: src/invariant/BucketListIsConsistentWithDatabase.cpp.

    NB: a violation here means the bucket list itself is corrupt; the
    LedgerManager must be discarded (fail-stop), not reused."""
    NAME = "BucketListIsConsistentWithDatabase"
    NEEDS_BUCKETS = True

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        if ctx.bucket_list is None:
            return None
        for kb in set(ctx.pre) | set(ctx.post):
            want = ctx.post.get(kb)
            got = ctx.bucket_list.lookup_latest(kb)
            if want is None:
                if got is not None:
                    return f"deleted key {kb.hex()[:16]} still live in buckets"
            elif got is None or got.to_xdr() != want.to_xdr():
                return f"bucket entry for {kb.hex()[:16]} != ledger state"
        return None


class LedgerEntryIsValid(Invariant):
    """Structural sanity of written entries (reference:
    src/invariant/LedgerEntryIsValid.cpp — subset: non-negative balances /
    amounts, balance <= limit, lastModified == closing seq)."""
    NAME = "LedgerEntryIsValid"

    @staticmethod
    def _entry_struct_error(e: X.LedgerEntry) -> Optional[str]:
        """Shared per-type structural checks (one source of truth for the
        ledger-close and bucket-apply hooks — the two paths must never
        diverge on what a valid entry is)."""
        t = e.data.switch
        if t == X.LedgerEntryType.ACCOUNT:
            acc = e.data.value
            if acc.balance < 0:
                return "negative account balance"
            if acc.seqNum < 0:
                return "negative seqNum"
        elif t == X.LedgerEntryType.TRUSTLINE:
            tl = e.data.value
            if tl.balance < 0 or tl.limit <= 0 or tl.balance > tl.limit:
                return f"trustline balance {tl.balance} outside [0, {tl.limit}]"
        elif t == X.LedgerEntryType.OFFER:
            off = e.data.value
            if off.amount <= 0 or off.price.n <= 0 or off.price.d <= 0:
                return "non-positive offer amount/price"
        return None

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        seq = ctx.post_header.ledgerSeq
        for kb, e in ctx.post.items():
            if e is None:
                continue
            if e.lastModifiedLedgerSeq != seq:
                return (f"lastModifiedLedgerSeq {e.lastModifiedLedgerSeq} != "
                        f"closing seq {seq} for {kb.hex()[:16]}")
            msg = self._entry_struct_error(e)
            if msg is not None:
                return msg
        return None

    def check_on_bucket_apply(self, entry: X.BucketEntry, level: int,
                              header_seq: int) -> Optional[str]:
        """Structural sanity of entries assumed from an archive's buckets
        (reference: LedgerEntryIsValid under checkOnBucketApply): same
        per-type checks, but lastModified may be any ledger <= the header
        being assumed."""
        if entry.switch in (X.BucketEntryType.DEADENTRY,
                            X.BucketEntryType.METAENTRY):
            return None
        e = entry.value
        where = f"level {level} bucket entry"
        if e.lastModifiedLedgerSeq > header_seq:
            return (f"{where}: lastModifiedLedgerSeq "
                    f"{e.lastModifiedLedgerSeq} is after the assumed "
                    f"header seq {header_seq}")
        msg = self._entry_struct_error(e)
        if msg is not None:
            return f"{where}: {msg}"
        return None


def _sponsorship_units(entry: Optional[X.LedgerEntry]
                       ) -> Optional[Tuple[bytes, int]]:
    """(sponsor AccountID xdr, reserve units) when the entry carries a
    sponsoringID (2 for an account entry, one per claimant for claimable
    balances, 2 for pool-share trustlines, else 1).  Reference:
    computeMultiplier in SponsorshipUtils."""
    if entry is None or entry.ext.switch != 1 \
            or entry.ext.value.sponsoringID is None:
        return None
    from ..transactions.sponsorship import compute_multiplier
    return entry.ext.value.sponsoringID.to_xdr(), compute_multiplier(entry)


def _entry_owner_units(entry: Optional[X.LedgerEntry]
                       ) -> Optional[Tuple[bytes, int]]:
    """(owner AccountID xdr, units) for a SPONSORED entry whose reserve is
    counted in an owner account's numSponsored — accounts own themselves,
    trustlines/data/offers their account; claimable balances are
    owner-less."""
    su = _sponsorship_units(entry)
    if su is None:
        return None
    d = entry.data
    t = d.switch
    if t == X.LedgerEntryType.ACCOUNT:
        return d.value.accountID.to_xdr(), su[1]
    if t in (X.LedgerEntryType.TRUSTLINE, X.LedgerEntryType.DATA):
        return d.value.accountID.to_xdr(), su[1]
    if t == X.LedgerEntryType.OFFER:
        return d.value.sellerID.to_xdr(), su[1]
    return None


def _signer_sponsor_counts(entry: Optional[X.LedgerEntry],
                           sign: int, by_sponsor: Dict[bytes, int],
                           by_owner: Dict[bytes, int]) -> None:
    """Accumulate one account entry's sponsored-signer units into both the
    per-sponsor and per-owner tallies."""
    if entry is None or entry.data.switch != X.LedgerEntryType.ACCOUNT:
        return
    from ..transactions.sponsorship import signer_sponsoring_ids
    ids = signer_sponsoring_ids(entry.data.value)
    if not ids:
        return
    aid = entry.data.value.accountID.to_xdr()
    for sp in ids:
        if sp is not None:
            sb = sp.to_xdr()
            by_sponsor[sb] = by_sponsor.get(sb, 0) + sign
            by_owner[aid] = by_owner.get(aid, 0) + sign


class SponsorshipCountIsValid(Invariant):
    """Δ numSponsoring of each account equals the Δ of reserve units it
    sponsors (entries AND signers), and Δ numSponsored of each account
    equals the Δ of sponsored units it owns.  Reference:
    src/invariant/SponsorshipCountIsValid.cpp."""
    NAME = "SponsorshipCountIsValid"

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        from ..transactions.utils import num_sponsored, num_sponsoring
        d_units: Dict[bytes, int] = {}          # sponsored units BY sponsor
        d_owned: Dict[bytes, int] = {}          # sponsored units ON owner
        d_declared: Dict[bytes, int] = {}       # numSponsoring deltas
        d_declared_ed: Dict[bytes, int] = {}    # numSponsored deltas
        for kb in set(ctx.pre) | set(ctx.post):
            pre_e, post_e = ctx.pre.get(kb), ctx.post.get(kb)
            for e, sign in ((pre_e, -1), (post_e, +1)):
                su = _sponsorship_units(e)
                if su is not None:
                    d_units[su[0]] = d_units.get(su[0], 0) + sign * su[1]
                ou = _entry_owner_units(e)
                if ou is not None:
                    d_owned[ou[0]] = d_owned.get(ou[0], 0) + sign * ou[1]
                _signer_sponsor_counts(e, sign, d_units, d_owned)
            key = X.LedgerKey.from_xdr(kb)
            if key.switch == X.LedgerEntryType.ACCOUNT:
                aid = key.value.accountID.to_xdr()
                pre_n = num_sponsoring(pre_e.data.value) if pre_e else 0
                post_n = num_sponsoring(post_e.data.value) if post_e else 0
                d_declared[aid] = d_declared.get(aid, 0) + post_n - pre_n
                pre_d = num_sponsored(pre_e.data.value) if pre_e else 0
                post_d = num_sponsored(post_e.data.value) if post_e else 0
                d_declared_ed[aid] = d_declared_ed.get(aid, 0) + post_d - pre_d
        for aid in set(d_units) | set(d_declared):
            if d_units.get(aid, 0) != d_declared.get(aid, 0):
                return (f"numSponsoring delta {d_declared.get(aid, 0)} != "
                        f"sponsored-unit delta {d_units.get(aid, 0)} for "
                        f"account {aid.hex()[:16]}")
        for aid in set(d_owned) | set(d_declared_ed):
            if d_owned.get(aid, 0) != d_declared_ed.get(aid, 0):
                return (f"numSponsored delta {d_declared_ed.get(aid, 0)} != "
                        f"owned sponsored-unit delta {d_owned.get(aid, 0)} "
                        f"for account {aid.hex()[:16]}")
        return None


class ConstantProductInvariant(Invariant):
    """Liquidity-pool reserve/share safety (reference:
    src/invariant/ConstantProductInvariant.cpp), guarding pool deposits,
    withdrawals and path payments routed through pools:

    * swaps (total shares unchanged) must not shrink the constant product
      reserveA*reserveB — the 30bp fee makes it grow;
    * deposits (shares up) must not take from either reserve, and must not
      dilute existing holders (minted shares are floored, so the
      per-share value of each reserve never decreases);
    * withdrawals (shares down) must not add to a reserve, and the
      per-share value of each reserve must not decrease (the floor in
      amount = reserve*shares/totalShares favors the pool);
    * a pool leaves the ledger only once empty (no shares, no reserves).
    """
    NAME = "ConstantProductInvariant"

    @staticmethod
    def _cp(entry: Optional[X.LedgerEntry]):
        if entry is None:
            return None
        return entry.data.value.body.value   # LiquidityPoolEntryConstantProduct

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        tag = int(X.LedgerEntryType.LIQUIDITY_POOL).to_bytes(4, "big")
        for kb in set(ctx.pre) | set(ctx.post):
            if not kb.startswith(tag):
                continue
            pre = self._cp(ctx.pre.get(kb))
            post = self._cp(ctx.post.get(kb))
            pid = kb.hex()[8:24]
            if post is not None and (
                    post.reserveA < 0 or post.reserveB < 0
                    or post.totalPoolShares < 0
                    or post.poolSharesTrustLineCount < 0):
                return f"pool {pid}: negative reserve/share/trustline count"
            if pre is None or post is None:
                if post is None and pre is not None and (
                        pre.totalPoolShares != 0 or pre.reserveA != 0
                        or pre.reserveB != 0):
                    return (f"pool {pid} deleted while holding "
                            f"{pre.totalPoolShares} shares / "
                            f"({pre.reserveA},{pre.reserveB}) reserves")
                continue
            ds = post.totalPoolShares - pre.totalPoolShares
            da = post.reserveA - pre.reserveA
            db = post.reserveB - pre.reserveB
            if ds == 0:
                if post.reserveA * post.reserveB \
                        < pre.reserveA * pre.reserveB:
                    return (f"pool {pid}: constant product shrank on swap "
                            f"({pre.reserveA}*{pre.reserveB} -> "
                            f"{post.reserveA}*{post.reserveB})")
            elif ds > 0:
                if da < 0 or db < 0:
                    return (f"pool {pid}: deposit drained a reserve "
                            f"(ΔA={da}, ΔB={db})")
                if post.reserveA * pre.totalPoolShares \
                        < pre.reserveA * post.totalPoolShares \
                        or post.reserveB * pre.totalPoolShares \
                        < pre.reserveB * post.totalPoolShares:
                    return (f"pool {pid}: deposit minted shares worth more "
                            f"than the contributed reserves (dilution)")
            else:
                if da > 0 or db > 0:
                    return (f"pool {pid}: withdrawal grew a reserve "
                            f"(ΔA={da}, ΔB={db})")
                if post.reserveA * pre.totalPoolShares \
                        < pre.reserveA * post.totalPoolShares \
                        or post.reserveB * pre.totalPoolShares \
                        < pre.reserveB * post.totalPoolShares:
                    return (f"pool {pid}: withdrawal paid out more than "
                            f"the burned shares' value")
        return None


class SorobanStateIsValid(Invariant):
    """Contract-state/TTL pairing (ISSUE 17): every CONTRACT_DATA or
    CONTRACT_CODE entry alive after a close must have a live TTL entry
    (keyHash = sha256 of the data key's XDR), and deleting the data entry
    must delete its TTL in the same close — a dangling TTL would survive
    in buckets forever, and a TTL-less entry could never expire."""
    NAME = "SorobanStateIsValid"

    _DATA_TYPES = (X.LedgerEntryType.CONTRACT_DATA,
                   X.LedgerEntryType.CONTRACT_CODE)

    def check_on_ledger_close(self, ctx: LedgerCloseContext) -> Optional[str]:
        from ..crypto.sha import sha256
        tags = tuple(int(t).to_bytes(4, "big") for t in self._DATA_TYPES)
        for kb in set(ctx.pre) | set(ctx.post):
            if not kb.startswith(tags):
                continue
            ttl_kb = X.LedgerKey.ttl(X.LedgerKeyTtl(
                keyHash=sha256(kb))).to_xdr()
            post = ctx.post.get(kb, ctx.pre.get(kb))
            ttl = ctx.post_state(ttl_kb)
            label = kb.hex()[:16]
            if kb in ctx.post and ctx.post[kb] is None:
                if ttl is not None:
                    return (f"contract entry {label} deleted but its TTL "
                            f"entry survives (liveUntil="
                            f"{ttl.data.value.liveUntilLedgerSeq})")
            elif post is not None:
                if ttl is None:
                    return f"live contract entry {label} has no TTL entry"
                if ttl.data.value.liveUntilLedgerSeq <= 0:
                    return (f"contract entry {label} has non-positive "
                            f"liveUntilLedgerSeq")
        return None


ALL_INVARIANTS = (LedgerEntryIsValid, AccountSubEntriesCountIsValid,
                  ConservationOfLumens, LiabilitiesMatchOffers,
                  SponsorshipCountIsValid, ConstantProductInvariant,
                  SorobanStateIsValid, BucketListIsConsistentWithDatabase)


class InvariantManager:
    """Holds enabled invariants; LedgerManager calls check_on_ledger_close
    once per close.  Reference: InvariantManagerImpl (enabled by the
    INVARIANT_CHECKS config regex list)."""

    def __init__(self, invariants: Optional[List[Invariant]] = None):
        self.invariants: List[Invariant] = (
            [cls() for cls in ALL_INVARIANTS]
            if invariants is None else list(invariants))

    @classmethod
    def from_patterns(cls, patterns: List[str]) -> "InvariantManager":
        """INVARIANT_CHECKS semantics: enable invariants whose name matches
        any regex (the reference config default is [\"(?!.*)\"]=none; tests
        and configs usually pass [\".*\"])."""
        enabled = [c() for c in ALL_INVARIANTS
                   if any(re.fullmatch(p, c.NAME) for p in patterns)]
        return cls(enabled)

    def check_on_ledger_close(self, ctx: LedgerCloseContext,
                              needs_buckets: Optional[bool] = None) -> None:
        """needs_buckets: None = run all; False/True = only the pre-bucket /
        post-bucket phase (LedgerManager runs the two phases around
        add_batch so a pre-bucket violation leaves clean state)."""
        for inv in self.invariants:
            if needs_buckets is not None \
                    and inv.NEEDS_BUCKETS is not needs_buckets:
                continue
            msg = inv.check_on_ledger_close(ctx)
            if msg is not None:
                _fail_invariant(f"{inv.NAME}: {msg}")

    def check_on_bucket_apply(self, bucket, level: int,
                              header_seq: int) -> None:
        """Run per-entry bucket-apply checks over one assumed bucket
        (reference: InvariantManagerImpl::checkOnBucketApply — catchup's
        assume-state path; the hash chain detects corruption, the
        invariant LOCALIZES it to an entry with a message).  Only
        invariants that override the hook walk the entries — bucket lists
        are millions of entries, base-class no-ops are not free."""
        active = [inv for inv in self.invariants
                  if type(inv).check_on_bucket_apply
                  is not Invariant.check_on_bucket_apply]
        for inv in active:
            for be in bucket.entries:
                msg = inv.check_on_bucket_apply(be, level, header_seq)
                if msg is not None:
                    _fail_invariant(f"{inv.NAME}: {msg}")
