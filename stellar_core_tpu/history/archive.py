"""History archives: the checkpoint file store.

Reference: src/history/HistoryArchive.{h,cpp} (HistoryArchiveState — the
`.well-known/stellar-history.json` HAS document), FileTransferInfo.h (path
scheme `category/ww/xx/yy/category-<hex8>.xdr.gz`), and the XDR file stream
record framing from xdrpp (util/XDRStream.h — XDRInputFileStream): each
record is a 4-byte big-endian header whose MSB marks the final fragment and
low 31 bits carry the length, followed by the XDR body.

Archives are dumb file stores; the reference drives them with configured
get/put shell commands (cp/curl).  Here an archive is a directory with the
same layout, and the command indirection arrives with ProcessManager.
"""

from __future__ import annotations

import gzip
import json
import os
import re
import struct
from typing import Iterator, List, Optional

from .. import xdr as X
from ..bucket.bucket import Bucket

CHECKPOINT_FREQUENCY = 64
HAS_CURRENT_VERSION = 1


def checkpoint_frequency() -> int:
    """The process-wide checkpoint cadence.  64 on real networks; test
    fleets shrink it (reference: HistoryManager::getCheckpointFrequency
    returns 8 under ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING) so archives
    publish — and rejoining nodes can catch up — within seconds.  Callers
    that do checkpoint arithmetic must read it through this accessor (or
    the helpers below), never bind the constant at import time."""
    return CHECKPOINT_FREQUENCY


def set_checkpoint_frequency(n: int) -> None:
    """Set the process-wide checkpoint cadence.  Every node of a network
    and every catchup worker replaying its archives must agree on this
    number — it is part of the archive format, which is why it travels in
    node configs (Config.CHECKPOINT_FREQUENCY) and in the catchup-range
    worker command line rather than being flipped ad hoc."""
    global CHECKPOINT_FREQUENCY
    if n < 2:
        raise ValueError(f"checkpoint frequency must be >= 2, got {n}")
    CHECKPOINT_FREQUENCY = n

CATEGORY_LEDGER = "ledger"
CATEGORY_TRANSACTIONS = "transactions"
CATEGORY_RESULTS = "results"
CATEGORY_SCP = "scp"
CATEGORY_BUCKET = "bucket"


def is_checkpoint_boundary(ledger_seq: int) -> bool:
    """Checkpoints close at seq ≡ 63 (mod 64) (reference:
    HistoryManager::isLastLedgerInCheckpoint; first checkpoint is 1..63)."""
    return (ledger_seq + 1) % CHECKPOINT_FREQUENCY == 0


def checkpoint_containing(ledger_seq: int) -> int:
    """The checkpoint ledger (its last seq) that contains ledger_seq."""
    return ((ledger_seq // CHECKPOINT_FREQUENCY) + 1) * CHECKPOINT_FREQUENCY - 1


def first_ledger_in_checkpoint(checkpoint: int) -> int:
    return max(1, checkpoint + 1 - CHECKPOINT_FREQUENCY)


# -- XDR record-mark stream framing (xdrpp compatible) ----------------------

def pack_xdr_stream(records: List[bytes]) -> bytes:
    out = bytearray()
    for rec in records:
        out += struct.pack(">I", len(rec) | 0x80000000)
        out += rec
    return bytes(out)


def unpack_xdr_stream(data: bytes) -> Iterator[bytes]:
    off = 0
    while off < len(data):
        if off + 4 > len(data):
            raise ValueError("truncated record mark")
        (mark,) = struct.unpack_from(">I", data, off)
        length = mark & 0x7FFFFFFF
        off += 4
        if off + length > len(data):
            raise ValueError("truncated record body")
        yield data[off:off + length]
        off += length


# -- path scheme ------------------------------------------------------------

def _hex8(n: int) -> str:
    return f"{n:08x}"


def category_path(category: str, checkpoint: int, suffix: str = ".xdr.gz") -> str:
    h = _hex8(checkpoint)
    return f"{category}/{h[0:2]}/{h[2:4]}/{h[4:6]}/{category}-{h}{suffix}"


_HEX256_RE = re.compile(r"[0-9a-f]{64}")


def require_hex256(hash_hex: str) -> str:
    """Strict SHA-256 hex validation (reference: hexToBin256).  HAS files
    come from untrusted archives and their hashes are interpolated into
    filesystem paths and shell command templates — anything that is not
    exactly 64 lowercase hex chars is rejected before it gets near either.
    """
    if not isinstance(hash_hex, str) or _HEX256_RE.fullmatch(hash_hex) is None:
        raise ValueError(f"invalid bucket hash in archive data: {hash_hex!r}")
    return hash_hex


def bucket_path(hash_hex: str) -> str:
    require_hex256(hash_hex)
    return (f"bucket/{hash_hex[0:2]}/{hash_hex[2:4]}/{hash_hex[4:6]}/"
            f"bucket-{hash_hex}.xdr.gz")


# -- HistoryArchiveState ----------------------------------------------------

class HistoryArchiveState:
    """The HAS JSON: current ledger + the bucket hash list per level."""

    def __init__(self, current_ledger: int, network_passphrase: str,
                 level_hashes: List[dict], server: str = "stellar-core-tpu"):
        self.version = HAS_CURRENT_VERSION
        self.server = server
        self.current_ledger = current_ledger
        self.network_passphrase = network_passphrase
        # [{"curr": hex, "snap": hex, "next": <dict|None>}, ...] — "next" is
        # the level's pending merge (reference: FutureBucket::save):
        # {"state": 1, "output": hex} once resolved (FB_HASH_OUTPUT) or
        # {"state": 2, "curr": hex, "snap": hex, keepTombstones,
        # outputProtocol} while running (FB_HASH_INPUTS).  Restart/catchup
        # must restore it to reproduce later bucket hashes.
        self.level_hashes = level_hashes

    @staticmethod
    def from_bucket_list(current_ledger: int, network_passphrase: str,
                         bucket_list,
                         resolve: bool = True) -> "HistoryArchiveState":
        """Snapshot a live bucket list.  resolve=True (publish path) blocks
        until merges finish — the reference requires resolved futures in
        published HAS files; resolve=False (per-close durable HAS) never
        blocks and serializes running merges as inputs."""
        if resolve:
            bucket_list.resolve_all_merges()
        level_hashes = [
            {"curr": lvl.curr.hash().hex(), "snap": lvl.snap.hash().hex(),
             "next": (lvl.next.serialize() if lvl.next is not None
                      else None)}
            for lvl in bucket_list.levels]
        return HistoryArchiveState(current_ledger, network_passphrase,
                                   level_hashes)

    def to_json(self) -> str:
        return json.dumps({
            "version": self.version,
            "server": self.server,
            "currentLedger": self.current_ledger,
            "networkPassphrase": self.network_passphrase,
            "currentBuckets": [
                {"curr": lh["curr"], "snap": lh["snap"],
                 "next": lh.get("next") or {"state": 0}}
                for lh in self.level_hashes],
        }, indent=2)

    @staticmethod
    def from_json(text: str) -> "HistoryArchiveState":
        """Parse an UNTRUSTED archive's HAS; every malformation (bad json,
        missing keys, wrong types, invalid hashes) raises ValueError so
        callers fail-stop with one localized error class."""
        try:
            d = json.loads(text)
            levels = []
            for b in d["currentBuckets"]:
                nxt = b.get("next")
                if nxt is not None and nxt.get("state", 0) == 0:
                    nxt = None
                if nxt is not None:
                    for key in ("output", "curr", "snap"):
                        if key in nxt and nxt[key] is not None:
                            require_hex256(nxt[key])
                levels.append({"curr": require_hex256(b["curr"]),
                               "snap": require_hex256(b["snap"]),
                               "next": nxt})
            return HistoryArchiveState(
                current_ledger=int(d["currentLedger"]),
                network_passphrase=d.get("networkPassphrase", ""),
                level_hashes=levels,
                server=d.get("server", ""))
        except (KeyError, TypeError, AttributeError) as e:
            raise ValueError(f"malformed HAS json: {e!r}") from e

    def bucket_hashes(self) -> List[str]:
        """curr/snap hashes, 2 per level (positional: level*2 + {0,1})."""
        out = []
        for lh in self.level_hashes:
            out.append(lh["curr"])
            out.append(lh["snap"])
        return out

    def next_states(self) -> List[Optional[dict]]:
        """Per-level pending-merge record, or None when clear."""
        return [lh.get("next") for lh in self.level_hashes]

    def rehydrate_next(self, level: int, bucket_source):
        """Rebuild a level's FutureBucket from its serialized form
        (reference: FutureBucket::makeLive).  bucket_source(hex) -> Bucket
        must raise or return None for missing buckets; the all-zero hash is
        the (perfectly valid) empty bucket."""
        from ..bucket.bucket import Bucket
        from ..bucket.future import FutureBucket

        nxt = self.level_hashes[level].get("next")
        if nxt is None:
            return None

        def load(hh: str) -> Bucket:
            if hh == "0" * 64:
                return Bucket.empty()
            try:
                b = bucket_source(hh)
            except (ValueError, OSError) as e:   # hash mismatch / hostile
                raise RuntimeError(str(e)) from e   # gzip / file IO fault
            if b is None:
                raise RuntimeError(f"missing bucket {hh}")
            return b

        # the HAS comes from an untrusted archive: a `next` record that
        # lies about its own shape (unknown state, missing/garbage fields)
        # must fail-stop as a localized archive error, not a KeyError
        try:
            state = int(nxt["state"])
            if state == 1:
                spec = ("output",)
            elif state == 2:
                spec = ("curr", "snap", "keepTombstones", "outputProtocol")
            else:
                raise RuntimeError(
                    f"HAS level {level} next has invalid state {state}")
            fields = {k: nxt[k] for k in spec}
            for k in spec:
                if k in ("output", "curr", "snap"):
                    require_hex256(fields[k])
            if state == 2:
                fields["outputProtocol"] = int(fields["outputProtocol"])
        except (KeyError, TypeError, ValueError) as e:
            raise RuntimeError(
                f"HAS level {level} next record malformed: {e!r}") from e
        if state == 1:
            return FutureBucket.from_output(load(fields["output"]))
        # state 2: re-run the merge from inputs (synchronously — restart
        # is not the hot path)
        return FutureBucket(load(fields["curr"]), load(fields["snap"]),
                            bool(fields["keepTombstones"]),
                            fields["outputProtocol"])

    def all_bucket_hashes(self) -> List[str]:
        """Every referenced bucket incl. next outputs/inputs (what catchup
        must download and what GC must keep — reference:
        HistoryArchiveState::differingBuckets scope)."""
        out = self.bucket_hashes()
        for nxt in self.next_states():
            if nxt is None:
                continue
            if nxt["state"] == 1:
                out.append(nxt["output"])
            else:
                out.extend((nxt["curr"], nxt["snap"]))
        return out


# -- archives ---------------------------------------------------------------

class HistoryArchiveBase:
    """Category/HAS/bucket layer shared by every archive transport; the
    transport provides get_bytes/put_bytes/exists (reference: the archive
    itself is a dumb blob store — HistoryArchive only knows paths)."""

    WELL_KNOWN = ".well-known/stellar-history.json"

    def put_bytes(self, rel: str, data: bytes) -> None:
        raise NotImplementedError

    def get_bytes(self, rel: str) -> Optional[bytes]:
        raise NotImplementedError

    def exists(self, rel: str) -> bool:
        return self.get_bytes(rel) is not None

    # Memory bound for one decompressed history object (checkpoint files
    # are a few MB in practice; a hostile archive can serve a tiny .gz
    # that inflates without limit — decompression is CAPPED so parsing
    # stays memory-bound, reference fail-stop discipline SURVEY §5.3)
    MAX_DECOMPRESSED_BYTES = 256 * 1024 * 1024

    @classmethod
    def _bounded_gunzip(cls, raw: bytes, what: str) -> bytes:
        import zlib
        try:
            d = zlib.decompressobj(wbits=31)   # gzip container
            out = d.decompress(raw, cls.MAX_DECOMPRESSED_BYTES)
            if d.unconsumed_tail:
                raise ValueError(
                    f"{what} inflates past the "
                    f"{cls.MAX_DECOMPRESSED_BYTES}-byte cap")
            out += d.flush()
            if len(out) > cls.MAX_DECOMPRESSED_BYTES:
                raise ValueError(
                    f"{what} inflates past the "
                    f"{cls.MAX_DECOMPRESSED_BYTES}-byte cap")
            if not d.eof:
                # a stream cut at a deflate-block boundary decompresses
                # without error but never reaches the gzip trailer (CRC) —
                # gzip.decompress rejected this and so must we
                raise ValueError(f"{what} is a truncated gzip stream")
            if d.unused_data:
                raise ValueError(f"{what} has trailing data after the "
                                 "gzip stream")
            return out
        except zlib.error as e:
            raise ValueError(f"{what} is not valid gzip data: {e}") from e

    # gzip'd XDR streams
    def put_xdr_file(self, rel: str, records: List[bytes]) -> None:
        self.put_bytes(rel, gzip.compress(pack_xdr_stream(records)))

    def get_xdr_file(self, rel: str) -> Optional[List[bytes]]:
        raw = self.get_bytes(rel)
        if raw is None:
            return None
        return list(unpack_xdr_stream(self._bounded_gunzip(raw, rel)))

    # HAS
    def put_state(self, has: HistoryArchiveState) -> None:
        data = has.to_json().encode()
        self.put_bytes(self.WELL_KNOWN, data)
        self.put_bytes(category_path("history", has.current_ledger,
                                     suffix=".json"), data)

    def get_state(self, checkpoint: Optional[int] = None
                  ) -> Optional[HistoryArchiveState]:
        if checkpoint is None:
            raw = self.get_bytes(self.WELL_KNOWN)
        else:
            raw = self.get_bytes(category_path("history", checkpoint,
                                               suffix=".json"))
        return HistoryArchiveState.from_json(raw.decode()) if raw else None

    # buckets
    def put_bucket(self, bucket: Bucket) -> None:
        if bucket.is_empty():
            return
        self.put_bytes(bucket_path(bucket.hash().hex()),
                       gzip.compress(bucket.serialize()))

    def get_bucket(self, hash_hex: str) -> Optional[Bucket]:
        raw = self.get_bytes(bucket_path(hash_hex))
        if raw is None:
            return None
        b = Bucket.deserialize(
            self._bounded_gunzip(raw, f"bucket {hash_hex}"))
        if b.hash().hex() != hash_hex:
            raise ValueError(f"bucket hash mismatch for {hash_hex}")
        return b


class FileHistoryArchive(HistoryArchiveBase):
    """Local directory archive (the TmpDirHistoryConfigurator analog used by
    every reference history test — SURVEY.md §4 fixtures)."""

    def __init__(self, root: str):
        self.root = root

    def _full(self, rel: str) -> str:
        return os.path.join(self.root, rel)

    # a .tmp.<pid> this old is litter from a publisher that died
    # mid-write (fleet kills do this by design).  The window is an hour:
    # generous enough that even a pathologically descheduled live writer
    # has long since replaced its tmp, and the retry below makes an
    # over-eager reap a rewrite, never a crash.
    STALE_TMP_S = 3600.0

    def put_bytes(self, rel: str, data: bytes) -> None:
        path = self._full(rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        # per-process tmp name: two node processes publishing the same
        # object (a shared fleet archive) must not interleave writes into
        # one tmp file — each writes its own and the os.replace is atomic,
        # so readers only ever see a complete object
        tmp = f"{path}.tmp.{os.getpid()}"
        for attempt in range(2):
            with open(tmp, "wb") as f:
                f.write(data)
            try:
                os.replace(tmp, path)
                break
            except FileNotFoundError:
                # another publisher's reaper mistook our tmp for litter
                # (clock skew / extreme descheduling): rewrite once
                if attempt:
                    raise
        # self-heal: a publisher SIGKILLed between open and replace left
        # its tmp behind; reap aged ones so a long-lived shared archive
        # doesn't accumulate torn litter across soaks
        import glob
        from ..util.clock import wall_now
        for stale in glob.glob(path + ".tmp.*"):
            if stale == tmp:
                continue
            try:
                if wall_now() - os.path.getmtime(stale) > self.STALE_TMP_S:
                    os.unlink(stale)
            except OSError:
                pass

    def get_bytes(self, rel: str) -> Optional[bytes]:
        try:
            with open(self._full(rel), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def exists(self, rel: str) -> bool:
        return os.path.exists(self._full(rel))


class CommandHistoryArchive(HistoryArchiveBase):
    """Shell-command archive transport (reference: HistoryArchive
    get=/put=/mkdir= templates in [HISTORY.<name>], run as subprocesses —
    `curl -sf {0} -o {1}`, `aws s3 cp {0} {1}`, `cp {0} {1}` ...).

    Templates use `{0}`/`{1}` exactly like the reference: for *get*,
    {0} = remote path, {1} = local destination file; for *put*,
    {0} = local source file, {1} = remote path.  Commands run
    synchronously here; the historywork units add pipelining above this
    layer (reference: ProcessManager runs N gets concurrently — the Work
    DAG achieves the overlap in this framework)."""

    def __init__(self, get_template: str = "", put_template: str = "",
                 mkdir_template: str = ""):
        import tempfile
        self.get_template = get_template
        self.put_template = put_template
        self.mkdir_template = mkdir_template
        self._tmp = tempfile.mkdtemp(prefix="sctpu-archive-")
        self._made_dirs: set = set()

    @staticmethod
    def _q(path: str) -> str:
        # Paths reaching the shell are archive-derived (category_path /
        # bucket_path, both strictly validated) — quoting is defense in
        # depth against any future caller passing raw remote data.
        import shlex
        return shlex.quote(path)

    def _run(self, cmdline: str) -> bool:
        import subprocess
        from ..util import logging as slog
        res = subprocess.run(cmdline, shell=True, capture_output=True)
        if res.returncode != 0:
            slog.get("History").warning(
                "archive command failed (%d): %s", res.returncode, cmdline)
        return res.returncode == 0

    def _mkdir_remote(self, rel: str) -> None:
        if not self.mkdir_template:
            return
        d = os.path.dirname(rel)
        if d and d not in self._made_dirs:
            # cache only on success — a transient mkdir failure must be
            # retried by the next put, not poisoned into the cache
            if self._run(self.mkdir_template.format(self._q(d))):
                self._made_dirs.add(d)

    def put_bytes(self, rel: str, data: bytes) -> None:
        if not self.put_template:
            raise RuntimeError("archive has no put command")
        local = os.path.join(self._tmp, "put.tmp")
        with open(local, "wb") as f:
            f.write(data)
        self._mkdir_remote(rel)
        if not self._run(self.put_template.format(self._q(local), self._q(rel))):
            raise RuntimeError(f"archive put failed for {rel}")

    def get_bytes(self, rel: str) -> Optional[bytes]:
        if not self.get_template:
            raise RuntimeError("archive has no get command")
        local = os.path.join(self._tmp, "get.tmp")
        try:
            os.unlink(local)
        except FileNotFoundError:
            pass
        if not self._run(self.get_template.format(self._q(rel), self._q(local))):
            return None
        try:
            with open(local, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None


def make_archive(get_spec: str = "", put_spec: str = "",
                 mkdir_spec: str = ""):
    """Config → archive: specs containing `{0}` are command templates
    (reference semantics); a bare path is a local directory archive."""
    if "{0}" in get_spec or "{0}" in put_spec:
        return CommandHistoryArchive(get_template=get_spec,
                                     put_template=put_spec,
                                     mkdir_template=mkdir_spec)
    return FileHistoryArchive(put_spec or get_spec)
