"""HistoryManager: checkpoint building + publishing.

Reference: src/history/HistoryManagerImpl.{h,cpp} (queueCurrentHistory /
publishQueuedHistory), src/history/CheckpointBuilder.* (incremental append of
ledger headers / tx sets / results as ledgers close), src/history/
StateSnapshot.* (what gets written per checkpoint).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import xdr as X
from ..ledger.manager import ClosedLedgerArtifacts, LedgerManager
from ..util import logging as slog
from .archive import (CATEGORY_LEDGER, CATEGORY_RESULTS, CATEGORY_TRANSACTIONS,
                      CHECKPOINT_FREQUENCY, FileHistoryArchive,
                      HistoryArchiveState, category_path,
                      is_checkpoint_boundary)

log = slog.get("History")

_LHHE = X.LedgerHeaderHistoryEntry._xdr_adapter()
_THE = X.TransactionHistoryEntry._xdr_adapter()
_THRE = X.TransactionHistoryResultEntry._xdr_adapter()


class HistoryManager:
    """Accumulates per-ledger artifacts and publishes checkpoints to the
    configured archives as boundaries are crossed."""

    def __init__(self, ledger_mgr: LedgerManager, network_passphrase: str,
                 archives: Optional[List[FileHistoryArchive]] = None):
        self.ledger_mgr = ledger_mgr
        self.network_passphrase = network_passphrase
        self.archives = archives or []
        self._pending: List[ClosedLedgerArtifacts] = []
        self.published_checkpoints: List[int] = []

    def ledger_closed(self, arts: ClosedLedgerArtifacts) -> None:
        """Call after every close (reference: CheckpointBuilder::appendLedger
        + HistoryManager::maybeQueueHistoryCheckpoint)."""
        self._pending.append(arts)
        seq = arts.header_entry.header.ledgerSeq
        if is_checkpoint_boundary(seq):
            self.publish_checkpoint(seq)

    def publish_checkpoint(self, checkpoint_seq: int) -> None:
        """Write ledger/transactions/results streams, bucket files and the
        HAS for this checkpoint to every archive."""
        headers = [a.header_entry for a in self._pending]
        txs = [a.tx_entry for a in self._pending
               if a.tx_entry.txSet.txs]
        results = [a.result_entry for a in self._pending
                   if a.result_entry.txResultSet.results]
        level_hashes = [
            {"curr": lvl.curr.hash().hex(), "snap": lvl.snap.hash().hex()}
            for lvl in self.ledger_mgr.bucket_list.levels]
        has = HistoryArchiveState(checkpoint_seq, self.network_passphrase,
                                  level_hashes)
        for archive in self.archives:
            archive.put_xdr_file(
                category_path(CATEGORY_LEDGER, checkpoint_seq),
                [_LHHE.pack(h) for h in headers])
            archive.put_xdr_file(
                category_path(CATEGORY_TRANSACTIONS, checkpoint_seq),
                [_THE.pack(t) for t in txs])
            archive.put_xdr_file(
                category_path(CATEGORY_RESULTS, checkpoint_seq),
                [_THRE.pack(r) for r in results])
            for bucket in self.ledger_mgr.bucket_list.buckets():
                if not bucket.is_empty():
                    archive.put_bucket(bucket)
            archive.put_state(has)
        self.published_checkpoints.append(checkpoint_seq)
        self._pending.clear()
        log.info("published checkpoint %d (%d headers, %d tx entries)",
                 checkpoint_seq, len(headers), len(txs))
