"""HistoryManager: checkpoint building + publishing.

Reference: src/history/HistoryManagerImpl.{h,cpp} (queueCurrentHistory /
publishQueuedHistory), src/history/CheckpointBuilder.* (incremental append of
ledger headers / tx sets / results as ledgers close), src/history/
StateSnapshot.* (what gets written per checkpoint).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import xdr as X
from ..ledger.manager import ClosedLedgerArtifacts, LedgerManager
from ..util import eventlog, tracing
from ..util import logging as slog
from .archive import (CATEGORY_LEDGER, CATEGORY_RESULTS, CATEGORY_TRANSACTIONS,
                      FileHistoryArchive, HistoryArchiveState, category_path,
                      checkpoint_frequency, is_checkpoint_boundary)

log = slog.get("History")

_LHHE = X.LedgerHeaderHistoryEntry._xdr_adapter()
_THE = X.TransactionHistoryEntry._xdr_adapter()
_THRE = X.TransactionHistoryResultEntry._xdr_adapter()


class HistoryManager:
    """Accumulates per-ledger artifacts and publishes checkpoints to the
    configured archives as boundaries are crossed."""

    def __init__(self, ledger_mgr: LedgerManager, network_passphrase: str,
                 archives: Optional[List[FileHistoryArchive]] = None,
                 database=None):
        """With `database`, per-ledger artifacts and the publish queue are
        durable: a node killed mid-checkpoint republishes after restart
        (reference: CheckpointBuilder's on-disk .dirty streams + the
        publishqueue table)."""
        self.ledger_mgr = ledger_mgr
        self.network_passphrase = network_passphrase
        self.archives = archives or []
        self.db = database
        self._pending: List[ClosedLedgerArtifacts] = []
        self.published_checkpoints: List[int] = []
        # first ledger this manager has CONTIGUOUS artifacts from; see
        # resume_from
        self._publish_floor = 0

    def ledger_closed(self, arts: ClosedLedgerArtifacts) -> None:
        """Call after every close (reference: CheckpointBuilder::appendLedger
        + HistoryManager::maybeQueueHistoryCheckpoint)."""
        self._pending.append(arts)
        seq = arts.header_entry.header.ledgerSeq
        if self.db is not None:
            self.db.save_tx_history(seq, _THE.pack(arts.tx_entry),
                                    _THRE.pack(arts.result_entry))
            self.db.commit()
        self.maybe_queue_and_publish(seq)

    def _artifacts_from_db(self, checkpoint_seq: int):
        """Rebuild the checkpoint's streams from durable state (survives a
        crash that wiped the in-memory pending list)."""
        lo = max(2, checkpoint_seq - checkpoint_frequency() + 1)
        headers, txs, results = [], [], []
        for seq in range(lo, checkpoint_seq + 1):
            got = self.db.load_header_by_seq(seq)
            if got is None:
                # publishing a checkpoint with holes would poison every
                # node that later catches up from this archive — fail-stop
                raise RuntimeError(
                    f"header {seq} missing from DB while publishing "
                    f"checkpoint {checkpoint_seq}")
            h, header = got
            headers.append(X.LedgerHeaderHistoryEntry(hash=h, header=header))
        for seq, te, re_ in self.db.load_tx_history(lo, checkpoint_seq):
            tx_entry = _THE.unpack(te)
            result_entry = _THRE.unpack(re_)
            if tx_entry.txSet.txs:
                txs.append(tx_entry)
            if result_entry.txResultSet.results:
                results.append(result_entry)
        return headers, txs, results

    def publish_checkpoint(self, checkpoint_seq: int) -> None:
        """Write ledger/transactions/results streams, bucket files and the
        HAS for this checkpoint to every archive."""
        if self.db is not None:
            headers, txs, results = self._artifacts_from_db(checkpoint_seq)
        else:
            headers = [a.header_entry for a in self._pending]
            txs = [a.tx_entry for a in self._pending
                   if a.tx_entry.txSet.txs]
            results = [a.result_entry for a in self._pending
                       if a.result_entry.txResultSet.results]
        bl = self.ledger_mgr.bucket_list
        has = HistoryArchiveState.from_bucket_list(
            checkpoint_seq, self.network_passphrase, bl)
        pending = [lvl.next.resolve() for lvl in bl.levels
                   if lvl.next is not None]
        for archive in self.archives:
            archive.put_xdr_file(
                category_path(CATEGORY_LEDGER, checkpoint_seq),
                [_LHHE.pack(h) for h in headers])
            archive.put_xdr_file(
                category_path(CATEGORY_TRANSACTIONS, checkpoint_seq),
                [_THE.pack(t) for t in txs])
            archive.put_xdr_file(
                category_path(CATEGORY_RESULTS, checkpoint_seq),
                [_THRE.pack(r) for r in results])
            for bucket in bl.buckets() + pending:
                if not bucket.is_empty():
                    archive.put_bucket(bucket)
            archive.put_state(has)
        self.published_checkpoints.append(checkpoint_seq)
        self._pending.clear()
        if self.db is not None:
            self.db.dequeue_publish(checkpoint_seq)
            # retain two checkpoint windows of artifacts + headers (the
            # reference's maintenance keeps a sliding window too)
            keep_from = checkpoint_seq - 2 * checkpoint_frequency()
            self.db.prune_tx_history(keep_from)
            self.db.delete_old_headers(keep_from)
            self.db.commit()
        eventlog.record("History", "INFO", "checkpoint published",
                        checkpoint=checkpoint_seq, headers=len(headers),
                        txs=len(txs))
        tracing.mark_phase("checkpoint-publish", checkpoint_seq,
                           headers=len(headers), txs=len(txs))
        log.info("published checkpoint %d (%d headers, %d tx entries)",
                 checkpoint_seq, len(headers), len(txs))

    def resume_from(self, seq: int) -> None:
        """A node that adopted state from catchup (archive rejoin) has no
        artifacts for the ledgers it skipped — publishing the checkpoint
        window that straddles the adoption would write a stream with
        holes and poison every node that later catches up from this
        archive.  Drop the stale pending list and skip any boundary whose
        window starts before `seq`; healthy peers publish the identical
        bytes for it."""
        self._pending.clear()
        self._publish_floor = seq

    def maybe_queue_and_publish(self, seq: int) -> None:
        """Durable two-step publish: enqueue the boundary, then publish and
        dequeue — a crash between the two republishes at startup
        (reference: queueCurrentHistory + publishQueuedHistory)."""
        boundary = is_checkpoint_boundary(seq)
        if boundary and \
                max(2, seq - checkpoint_frequency() + 1) < self._publish_floor:
            # incomplete window after a catchup adoption (see resume_from)
            self._pending.clear()
            boundary = False
        if self.db is None:
            if boundary:
                self.publish_checkpoint(seq)
            return
        if boundary:
            self.db.queue_publish(seq, "")
            self.db.commit()
        self.publish_queued_history()

    def publish_queued_history(self) -> int:
        """Publish every queued checkpoint (startup recovery path).
        Returns the number published."""
        if self.db is None:
            return 0
        done = 0
        for seq, _state in self.db.publish_queue():
            self.publish_checkpoint(seq)
            done += 1
        return done
