"""Work framework: retryable async task DAGs on the VirtualClock.

Reference: src/work/ — BasicWork (state machine), Work (children),
WorkScheduler (root, cranked by the clock), WorkSequence, BatchWork
(bounded-concurrency fan-out), WorkWithCallback, ConditionalWork.
"""

from .work import (BasicWork, BatchWork, ConditionalWork, State, Work,
                   WorkScheduler, WorkSequence, WorkWithCallback,
                   function_work)

__all__ = ["BasicWork", "BatchWork", "ConditionalWork", "State", "Work",
           "WorkScheduler", "WorkSequence", "WorkWithCallback",
           "function_work"]
