"""The Work framework: cooperative, retryable task DAGs on the main thread.

Reference: src/work/BasicWork.{h,cpp} (state machine: PENDING/RUNNING/
WAITING/SUCCESS/FAILURE_RETRY/FAILURE_RAISE/ABORTED, retry with exponential
backoff), Work.{h,cpp} (works with children), WorkScheduler.{h,cpp} (the
root work cranked via the clock), WorkSequence.cpp, BatchWork.cpp
(bounded-concurrency fan-out), ConditionalWork.cpp, WorkWithCallback.cpp.

Redesign notes: the reference wakes works via asio handlers on the
VirtualClock; here a Work posts its crank steps as clock actions, giving
the same cooperative single-threaded semantics under virtual time (the
determinism backbone per SURVEY.md §4).  A work signals WAITING and is
woken by `wake_up()` (timers, children completing, external events).
"""

from __future__ import annotations

import enum
from typing import Callable, Iterator, List, Optional

from ..util import logging as slog
from ..util.clock import VirtualClock, VirtualTimer

log = slog.get("Work")

RETRY_NEVER = 0
RETRY_ONCE = 1
RETRY_A_FEW = 5
RETRY_A_LOT = 32
RETRY_FOREVER = 0xFFFFFFFF


class State(enum.Enum):
    # Reference: BasicWork::State / InternalState
    PENDING = "pending"
    RUNNING = "running"
    WAITING = "waiting"
    SUCCESS = "success"
    FAILURE = "failure"
    RETRYING = "retrying"
    ABORTING = "aborting"
    ABORTED = "aborted"


# onRun return values (reference: BasicWork::State returned by onRun)
RUN_SUCCESS = State.SUCCESS
RUN_FAILURE = State.FAILURE
RUN_RUNNING = State.RUNNING
RUN_WAITING = State.WAITING


def _is_done(state: State) -> bool:
    return state in (State.SUCCESS, State.FAILURE, State.ABORTED)


class BasicWork:
    """A unit of cooperative async work with retry semantics."""

    MAX_BACKOFF_EXPONENT = 5  # reference: BasicWork.cpp

    def __init__(self, clock: VirtualClock, name: str,
                 max_retries: int = RETRY_A_FEW):
        self.clock = clock
        self.name = name
        self.max_retries = max_retries
        self.state = State.PENDING
        self.retries = 0
        self._retry_timer: Optional[VirtualTimer] = None
        self._scheduled = False
        self._notify_parent: Optional[Callable[[], None]] = None

    # -- subclass interface ----------------------------------------------
    def on_run(self) -> State:
        raise NotImplementedError

    def on_reset(self) -> None:
        """Called when (re)starting, including before each retry."""

    def on_success(self) -> None:
        pass

    def on_failure_retry(self) -> None:
        pass

    def on_failure_raise(self) -> None:
        pass

    def on_aborted(self) -> None:
        pass

    # -- lifecycle --------------------------------------------------------
    def start(self, notify_parent: Optional[Callable[[], None]] = None) -> None:
        assert _is_done(self.state) or self.state == State.PENDING
        self._notify_parent = notify_parent
        self.state = State.RUNNING
        self.retries = 0
        self.on_reset()
        self._schedule_run()

    def shutdown(self) -> None:
        """Request abort.  Reference: BasicWork::shutdown."""
        if _is_done(self.state):
            return
        if self._retry_timer is not None:
            self._retry_timer.cancel()
            self._retry_timer = None
        self.state = State.ABORTING
        self._schedule_run()

    def wake_up(self) -> None:
        """Wake a WAITING work (timer fired, child finished, event arrived)."""
        if self.state == State.WAITING:
            self.state = State.RUNNING
            self._schedule_run()

    # -- internals --------------------------------------------------------
    def _schedule_run(self) -> None:
        if self._scheduled:
            return
        self._scheduled = True
        self.clock.post_action(self._crank, name=f"work:{self.name}")

    def _crank(self) -> None:
        self._scheduled = False
        if self.state == State.ABORTING:
            self._finish(State.ABORTED)
            return
        if self.state != State.RUNNING:
            return
        try:
            res = self.on_run()
        except Exception as e:  # a raising work is a failing work
            log.error("work %s raised: %s", self.name, e)
            res = State.FAILURE
        if res == State.RUNNING:
            self._schedule_run()
        elif res == State.WAITING:
            self.state = State.WAITING
        elif res == State.SUCCESS:
            self._finish(State.SUCCESS)
        elif res == State.FAILURE:
            self._maybe_retry()
        else:
            raise AssertionError(f"bad on_run result: {res}")

    def _maybe_retry(self) -> None:
        if self.retries >= self.max_retries:
            self._finish(State.FAILURE)
            return
        self.retries += 1
        self.state = State.RETRYING
        self.on_failure_retry()
        delay = self._retry_delay()
        log.debug("work %s retry %d/%s in %.1fs", self.name, self.retries,
                  self.max_retries, delay)
        self._retry_timer = VirtualTimer(self.clock)
        self._retry_timer.expires_from_now(delay, self._do_retry)

    def _retry_delay(self) -> float:
        # truncated binary exponential backoff, base 1s
        e = min(self.retries - 1, self.MAX_BACKOFF_EXPONENT)
        return float(1 << e)

    def _do_retry(self) -> None:
        self._retry_timer = None
        if self.state != State.RETRYING:
            return
        self.state = State.RUNNING
        self.on_reset()
        self._schedule_run()

    def _finish(self, state: State) -> None:
        self.state = state
        if state == State.SUCCESS:
            self.on_success()
        elif state == State.FAILURE:
            self.on_failure_raise()
        elif state == State.ABORTED:
            self.on_aborted()
        if self._notify_parent is not None:
            self._notify_parent()

    # -- status -----------------------------------------------------------
    @property
    def done(self) -> bool:
        return _is_done(self.state)

    @property
    def succeeded(self) -> bool:
        return self.state == State.SUCCESS

    @property
    def failed(self) -> bool:
        return self.state in (State.FAILURE, State.ABORTED)

    def status(self) -> str:
        return f"{self.name}: {self.state.value}"


class Work(BasicWork):
    """A work with children: runs children to completion (concurrently, as
    cooperative cranks), then runs its own on_run body via do_work().

    Reference: src/work/Work.{h,cpp} — addWork, yieldNextRunningChild,
    checkChildrenStatus.
    """

    def __init__(self, clock: VirtualClock, name: str,
                 max_retries: int = RETRY_A_FEW):
        super().__init__(clock, name, max_retries)
        self.children: List[BasicWork] = []
        self._any_child_failed = False

    def add_work(self, child: BasicWork) -> BasicWork:
        assert not self.done
        self.children.append(child)
        child.start(notify_parent=self._on_child_done)
        if self.state == State.WAITING:
            self.wake_up()
        return child

    def _on_child_done(self) -> None:
        self.wake_up()

    def on_reset(self) -> None:
        for c in self.children:
            if not c.done:
                c.shutdown()
        self.children = []
        self._any_child_failed = False
        self.do_reset()

    def do_reset(self) -> None:
        pass

    def do_work(self) -> State:
        """Run after all current children are done (and none failed)."""
        return State.SUCCESS

    def on_run(self) -> State:
        pending = [c for c in self.children if not c.done]
        if any(c.failed for c in self.children):
            return State.FAILURE
        if pending:
            return State.WAITING
        return self.do_work()

    def shutdown(self) -> None:
        for c in self.children:
            if not c.done:
                c.shutdown()
        super().shutdown()


class WorkScheduler(Work):
    """The root of the work DAG, owned by the Application.

    Reference: src/work/WorkScheduler.{h,cpp} — scheduleWork / executeWork.
    Children added here run until done; crank the clock to make progress.
    """

    def __init__(self, clock: VirtualClock):
        super().__init__(clock, "work-scheduler", max_retries=RETRY_NEVER)
        self.state = State.RUNNING  # always-on root

    def on_run(self) -> State:
        # the root never completes; it just keeps serving children
        if any(not c.done for c in self.children):
            return State.WAITING
        return State.WAITING

    def schedule(self, work: BasicWork) -> BasicWork:
        return self.add_work(work)

    def execute(self, work: BasicWork, timeout: float = 300.0) -> bool:
        """Blocking convenience: crank the clock until `work` finishes.
        Reference: WorkScheduler::executeWork."""
        self.schedule(work)
        self.clock.crank_until(lambda: work.done, timeout)
        return work.succeeded

    def _on_child_done(self) -> None:
        self.children = [c for c in self.children if not c.done]


class WorkSequence(BasicWork):
    """Runs a list of works strictly in order; fails on first failure.
    Reference: src/work/WorkSequence.{h,cpp}."""

    def __init__(self, clock: VirtualClock, name: str,
                 sequence: List[BasicWork],
                 max_retries: int = RETRY_NEVER):
        super().__init__(clock, name, max_retries)
        self.sequence = sequence
        self._idx = 0
        self._started_current = False

    def on_reset(self) -> None:
        self._idx = 0
        self._started_current = False

    def on_run(self) -> State:
        if self._idx >= len(self.sequence):
            return State.SUCCESS
        cur = self.sequence[self._idx]
        if not self._started_current:
            self._started_current = True
            cur.start(notify_parent=self.wake_up)
            return State.WAITING
        if not cur.done:
            return State.WAITING
        if cur.failed:
            return State.FAILURE
        self._idx += 1
        self._started_current = False
        return State.RUNNING

    def shutdown(self) -> None:
        if self._idx < len(self.sequence):
            cur = self.sequence[self._idx]
            if self._started_current and not cur.done:
                cur.shutdown()
        super().shutdown()


class BatchWork(Work):
    """Fan-out with bounded concurrency: pulls works from an iterator,
    keeping at most `max_concurrency` in flight.

    Reference: src/work/BatchWork.{h,cpp} (concurrency bound =
    MAX_CONCURRENT_SUBPROCESSES in the reference's download use).
    """

    def __init__(self, clock: VirtualClock, name: str,
                 iterator: Iterator[BasicWork], max_concurrency: int = 8,
                 max_retries: int = RETRY_NEVER):
        super().__init__(clock, name, max_retries)
        self._iter = iterator
        self.max_concurrency = max_concurrency
        self._exhausted = False

    def do_reset(self) -> None:
        self._exhausted = False

    def on_run(self) -> State:
        if any(c.failed for c in self.children):
            return State.FAILURE
        self.children = [c for c in self.children if not c.done]
        while not self._exhausted and len(self.children) < self.max_concurrency:
            try:
                nxt = next(self._iter)
            except StopIteration:
                self._exhausted = True
                break
            self.add_work(nxt)
        if self.children:
            return State.WAITING
        return State.SUCCESS


class ConditionalWork(BasicWork):
    """Waits for `condition()` then runs the wrapped work.
    Reference: src/work/ConditionalWork.{h,cpp} (polls the condition)."""

    POLL_INTERVAL = 0.5

    def __init__(self, clock: VirtualClock, name: str,
                 condition: Callable[[], bool], wrapped: BasicWork):
        super().__init__(clock, name, max_retries=RETRY_NEVER)
        self.condition = condition
        self.wrapped = wrapped
        self._started = False
        self._timer: Optional[VirtualTimer] = None

    def on_reset(self) -> None:
        self._started = False

    def on_run(self) -> State:
        if not self._started:
            if not self.condition():
                self._timer = VirtualTimer(self.clock)
                self._timer.expires_from_now(self.POLL_INTERVAL, self.wake_up)
                return State.WAITING
            self._started = True
            self.wrapped.start(notify_parent=self.wake_up)
            return State.WAITING
        if not self.wrapped.done:
            return State.WAITING
        return State.SUCCESS if self.wrapped.succeeded else State.FAILURE

    def shutdown(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
        if self._started and not self.wrapped.done:
            self.wrapped.shutdown()
        super().shutdown()


class WorkWithCallback(BasicWork):
    """Runs a one-shot callback as a work step.
    Reference: src/work/WorkWithCallback.{h,cpp} (callback returns success)."""

    def __init__(self, clock: VirtualClock, name: str,
                 callback: Callable[[], bool],
                 max_retries: int = RETRY_NEVER):
        super().__init__(clock, name, max_retries)
        self.callback = callback

    def on_run(self) -> State:
        return State.SUCCESS if self.callback() else State.FAILURE


def function_work(clock: VirtualClock, name: str, fn: Callable[[], bool],
                  max_retries: int = RETRY_NEVER) -> WorkWithCallback:
    """Helper: wrap a bool-returning function as a schedulable work."""
    return WorkWithCallback(clock, name, fn, max_retries)
