"""Stellar-ledger-entries.x equivalents (reference:
src/protocol-curr/xdr/Stellar-ledger-entries.x): assets, the six classic
ledger-entry types (+ Soroban contract data/code, config, TTL), LedgerEntry,
LedgerKey."""

from .codec import (Bool, Int32, Int64, Opaque, Optional, Uint32, Uint64,
                    VarArray, VarOpaque, Void, XdrString, xdr_enum, xdr_struct,
                    xdr_union)
from .types import (AccountID, AssetCode4, AssetCode12, DataValue, ExtensionPoint,
                    Hash, Liabilities, PoolID, Price, SequenceNumber, SignerKey,
                    String32, String64, Thresholds, TimePoint, Uint256)

MASK_ACCOUNT_FLAGS_V17 = 0xF
MAX_SIGNERS = 20

AssetType = xdr_enum("AssetType", {
    "ASSET_TYPE_NATIVE": 0,
    "ASSET_TYPE_CREDIT_ALPHANUM4": 1,
    "ASSET_TYPE_CREDIT_ALPHANUM12": 2,
    "ASSET_TYPE_POOL_SHARE": 3,
})

AlphaNum4 = xdr_struct("AlphaNum4", [
    ("assetCode", AssetCode4),
    ("issuer", AccountID),
])

AlphaNum12 = xdr_struct("AlphaNum12", [
    ("assetCode", AssetCode12),
    ("issuer", AccountID),
])

Asset = xdr_union("Asset", AssetType, {
    AssetType.ASSET_TYPE_NATIVE: ("native", None),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
})

TrustLineAsset = xdr_union("TrustLineAsset", AssetType, {
    AssetType.ASSET_TYPE_NATIVE: ("native", None),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
    AssetType.ASSET_TYPE_POOL_SHARE: ("liquidityPoolID", PoolID),
})

LedgerEntryType = xdr_enum("LedgerEntryType", {
    "ACCOUNT": 0,
    "TRUSTLINE": 1,
    "OFFER": 2,
    "DATA": 3,
    "CLAIMABLE_BALANCE": 4,
    "LIQUIDITY_POOL": 5,
    "CONTRACT_DATA": 6,
    "CONTRACT_CODE": 7,
    "CONFIG_SETTING": 8,
    "TTL": 9,
})

Signer = xdr_struct("Signer", [
    ("key", SignerKey),
    ("weight", Uint32),
])

AccountFlags = xdr_enum("AccountFlags", {
    "AUTH_REQUIRED_FLAG": 0x1,
    "AUTH_REVOCABLE_FLAG": 0x2,
    "AUTH_IMMUTABLE_FLAG": 0x4,
    "AUTH_CLAWBACK_ENABLED_FLAG": 0x8,
})

SponsorshipDescriptor = Optional(AccountID)

AccountEntryExtensionV3 = xdr_struct("AccountEntryExtensionV3", [
    ("ext", ExtensionPoint),
    ("seqLedger", Uint32),
    ("seqTime", TimePoint),
], defaults={"ext": lambda: ExtensionPoint.v0()})

AccountEntryExtensionV2Ext = xdr_union("AccountEntryExtensionV2Ext", Int32, {
    0: ("v0", None),
    3: ("v3", AccountEntryExtensionV3),
})

AccountEntryExtensionV2 = xdr_struct("AccountEntryExtensionV2", [
    ("numSponsored", Uint32),
    ("numSponsoring", Uint32),
    ("signerSponsoringIDs", VarArray(SponsorshipDescriptor, MAX_SIGNERS)),
    ("ext", AccountEntryExtensionV2Ext),
], defaults={"numSponsored": 0, "numSponsoring": 0, "signerSponsoringIDs": list,
             "ext": lambda: AccountEntryExtensionV2Ext.v0()})

AccountEntryExtensionV1Ext = xdr_union("AccountEntryExtensionV1Ext", Int32, {
    0: ("v0", None),
    2: ("v2", AccountEntryExtensionV2),
})

AccountEntryExtensionV1 = xdr_struct("AccountEntryExtensionV1", [
    ("liabilities", Liabilities),
    ("ext", AccountEntryExtensionV1Ext),
], defaults={"ext": lambda: AccountEntryExtensionV1Ext.v0()})

AccountEntryExt = xdr_union("AccountEntryExt", Int32, {
    0: ("v0", None),
    1: ("v1", AccountEntryExtensionV1),
})

AccountEntry = xdr_struct("AccountEntry", [
    ("accountID", AccountID),
    ("balance", Int64),
    ("seqNum", SequenceNumber),
    ("numSubEntries", Uint32),
    ("inflationDest", Optional(AccountID)),
    ("flags", Uint32),
    ("homeDomain", String32),
    ("thresholds", Thresholds),
    ("signers", VarArray(Signer, MAX_SIGNERS)),
    ("ext", AccountEntryExt),
], defaults={
    "numSubEntries": 0, "inflationDest": None, "flags": 0,
    "homeDomain": b"", "thresholds": b"\x01\x00\x00\x00",
    "signers": list, "ext": lambda: AccountEntryExt.v0(),
})

TrustLineFlags = xdr_enum("TrustLineFlags", {
    "AUTHORIZED_FLAG": 1,
    "AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG": 2,
    "TRUSTLINE_CLAWBACK_ENABLED_FLAG": 4,
})

_TLEv2Ext = xdr_union("TrustLineEntryExtensionV2Ext", Int32, {0: ("v0", None)})

TrustLineEntryExtensionV2 = xdr_struct("TrustLineEntryExtensionV2", [
    ("liquidityPoolUseCount", Int32),
    ("ext", _TLEv2Ext),
], defaults={"liquidityPoolUseCount": 0, "ext": lambda: _TLEv2Ext.v0()})

TrustLineEntryV1Ext = xdr_union("TrustLineEntryV1Ext", Int32, {
    0: ("v0", None),
    2: ("v2", TrustLineEntryExtensionV2),
})

TrustLineEntryV1 = xdr_struct("TrustLineEntryV1", [
    ("liabilities", Liabilities),
    ("ext", TrustLineEntryV1Ext),
], defaults={"ext": lambda: TrustLineEntryV1Ext.v0()})

TrustLineEntryExt = xdr_union("TrustLineEntryExt", Int32, {
    0: ("v0", None),
    1: ("v1", TrustLineEntryV1),
})

TrustLineEntry = xdr_struct("TrustLineEntry", [
    ("accountID", AccountID),
    ("asset", TrustLineAsset),
    ("balance", Int64),
    ("limit", Int64),
    ("flags", Uint32),
    ("ext", TrustLineEntryExt),
], defaults={"balance": 0, "flags": 0, "ext": lambda: TrustLineEntryExt.v0()})

OfferEntryFlags = xdr_enum("OfferEntryFlags", {"PASSIVE_FLAG": 1})

_OfferEntryExt = xdr_union("OfferEntryExt", Int32, {0: ("v0", None)})

OfferEntry = xdr_struct("OfferEntry", [
    ("sellerID", AccountID),
    ("offerID", Int64),
    ("selling", Asset),
    ("buying", Asset),
    ("amount", Int64),
    ("price", Price),
    ("flags", Uint32),
    ("ext", _OfferEntryExt),
], defaults={"flags": 0, "ext": lambda: _OfferEntryExt.v0()})

_DataEntryExt = xdr_union("DataEntryExt", Int32, {0: ("v0", None)})

DataEntry = xdr_struct("DataEntry", [
    ("accountID", AccountID),
    ("dataName", String64),
    ("dataValue", DataValue),
    ("ext", _DataEntryExt),
], defaults={"ext": lambda: _DataEntryExt.v0()})

ClaimPredicateType = xdr_enum("ClaimPredicateType", {
    "CLAIM_PREDICATE_UNCONDITIONAL": 0,
    "CLAIM_PREDICATE_AND": 1,
    "CLAIM_PREDICATE_OR": 2,
    "CLAIM_PREDICATE_NOT": 3,
    "CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME": 4,
    "CLAIM_PREDICATE_BEFORE_RELATIVE_TIME": 5,
})


from .codec import XdrType as _XdrType  # noqa: E402


class _ClaimPredicateFwd(_XdrType):
    """Recursive type: resolved after ClaimPredicate is defined."""
    _target = None

    def pack_into(self, val, out):
        self._target.pack_into(val, out)

    def unpack_from(self, buf, off):
        return self._target.unpack_from(buf, off)


_cp_fwd = _ClaimPredicateFwd()

ClaimPredicate = xdr_union("ClaimPredicate", ClaimPredicateType, {
    ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL: ("unconditional", None),
    ClaimPredicateType.CLAIM_PREDICATE_AND: ("andPredicates", VarArray(_cp_fwd, 2)),
    ClaimPredicateType.CLAIM_PREDICATE_OR: ("orPredicates", VarArray(_cp_fwd, 2)),
    ClaimPredicateType.CLAIM_PREDICATE_NOT: ("notPredicate", Optional(_cp_fwd)),
    ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME: ("absBefore", Int64),
    ClaimPredicateType.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME: ("relBefore", Int64),
})
_ClaimPredicateFwd._target = ClaimPredicate._xdr_adapter()

ClaimantType = xdr_enum("ClaimantType", {"CLAIMANT_TYPE_V0": 0})

ClaimantV0 = xdr_struct("ClaimantV0", [
    ("destination", AccountID),
    ("predicate", ClaimPredicate),
])

Claimant = xdr_union("Claimant", ClaimantType, {
    ClaimantType.CLAIMANT_TYPE_V0: ("v0", ClaimantV0),
})

ClaimableBalanceIDType = xdr_enum("ClaimableBalanceIDType", {
    "CLAIMABLE_BALANCE_ID_TYPE_V0": 0,
})

ClaimableBalanceID = xdr_union("ClaimableBalanceID", ClaimableBalanceIDType, {
    ClaimableBalanceIDType.CLAIMABLE_BALANCE_ID_TYPE_V0: ("v0", Hash),
})

ClaimableBalanceFlags = xdr_enum("ClaimableBalanceFlags", {
    "CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG": 1,
})

ClaimableBalanceEntryExtensionV1Ext = xdr_union(
    "ClaimableBalanceEntryExtensionV1Ext", Int32, {0: ("v0", None)})

ClaimableBalanceEntryExtensionV1 = xdr_struct("ClaimableBalanceEntryExtensionV1", [
    ("ext", ClaimableBalanceEntryExtensionV1Ext),
    ("flags", Uint32),
], defaults={"ext": lambda: ClaimableBalanceEntryExtensionV1Ext.v0()})

ClaimableBalanceEntryExt = xdr_union("ClaimableBalanceEntryExt", Int32, {
    0: ("v0", None),
    1: ("v1", ClaimableBalanceEntryExtensionV1),
})

ClaimableBalanceEntry = xdr_struct("ClaimableBalanceEntry", [
    ("balanceID", ClaimableBalanceID),
    ("claimants", VarArray(Claimant, 10)),
    ("asset", Asset),
    ("amount", Int64),
    ("ext", ClaimableBalanceEntryExt),
], defaults={"ext": lambda: ClaimableBalanceEntryExt.v0()})

LiquidityPoolType = xdr_enum("LiquidityPoolType", {
    "LIQUIDITY_POOL_CONSTANT_PRODUCT": 0,
})

LiquidityPoolConstantProductParameters = xdr_struct(
    "LiquidityPoolConstantProductParameters", [
        ("assetA", Asset),
        ("assetB", Asset),
        ("fee", Int32),
    ])

LIQUIDITY_POOL_FEE_V18 = 30

LiquidityPoolEntryConstantProduct = xdr_struct(
    "LiquidityPoolEntryConstantProduct", [
        ("params", LiquidityPoolConstantProductParameters),
        ("reserveA", Int64),
        ("reserveB", Int64),
        ("totalPoolShares", Int64),
        ("poolSharesTrustLineCount", Int64),
    ],
    defaults={"reserveA": 0, "reserveB": 0, "totalPoolShares": 0,
              "poolSharesTrustLineCount": 0})
_LPConstantProduct = LiquidityPoolEntryConstantProduct

LiquidityPoolEntryBody = xdr_union("LiquidityPoolEntryBody", LiquidityPoolType, {
    LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
        ("constantProduct", _LPConstantProduct),
})

LiquidityPoolEntry = xdr_struct("LiquidityPoolEntry", [
    ("liquidityPoolID", PoolID),
    ("body", LiquidityPoolEntryBody),
])

# --- Soroban entries (storage shape only; host execution is out of scope,
# see SURVEY.md §2.4 — soroban-env-host capability gap) ---

ContractDataDurability = xdr_enum("ContractDataDurability", {
    "TEMPORARY": 0,
    "PERSISTENT": 1,
})

from .contract import SCAddress, SCVal, _AssetFwd  # noqa: E402

# tie the contract-module's Asset forward reference (ContractIDPreimage
# FROM_ASSET) now that Asset exists
_AssetFwd._target = Asset._xdr_adapter()

ContractDataEntry = xdr_struct("ContractDataEntry", [
    ("ext", ExtensionPoint),
    ("contract", SCAddress),
    ("key", SCVal),
    ("durability", ContractDataDurability),
    ("val", SCVal),
])

ContractCodeEntry = xdr_struct("ContractCodeEntry", [
    ("ext", ExtensionPoint),
    ("hash", Hash),
    ("code", VarOpaque()),
])

# Real ConfigSettingEntry is a union over ConfigSettingID with ~15 typed arms;
# until the Soroban config layer lands we keep the leading discriminant (so
# ledger keys derive correctly) and carry the body opaquely.  Same wire-compat
# caveat as the Soroban ops in transaction.py.
ConfigSettingEntry = xdr_struct("ConfigSettingEntry", [
    ("configSettingID", Int32),
    ("raw", VarOpaque()),
])

TTLEntry = xdr_struct("TTLEntry", [
    ("keyHash", Hash),
    ("liveUntilLedgerSeq", Uint32),
])

LedgerEntryData = xdr_union("LedgerEntryData", LedgerEntryType, {
    LedgerEntryType.ACCOUNT: ("account", AccountEntry),
    LedgerEntryType.TRUSTLINE: ("trustLine", TrustLineEntry),
    LedgerEntryType.OFFER: ("offer", OfferEntry),
    LedgerEntryType.DATA: ("data", DataEntry),
    LedgerEntryType.CLAIMABLE_BALANCE: ("claimableBalance", ClaimableBalanceEntry),
    LedgerEntryType.LIQUIDITY_POOL: ("liquidityPool", LiquidityPoolEntry),
    LedgerEntryType.CONTRACT_DATA: ("contractData", ContractDataEntry),
    LedgerEntryType.CONTRACT_CODE: ("contractCode", ContractCodeEntry),
    LedgerEntryType.CONFIG_SETTING: ("configSetting", ConfigSettingEntry),
    LedgerEntryType.TTL: ("ttl", TTLEntry),
})

LedgerEntryExtensionV1Ext = xdr_union("LedgerEntryExtensionV1Ext", Int32,
                                      {0: ("v0", None)})

LedgerEntryExtensionV1 = xdr_struct("LedgerEntryExtensionV1", [
    ("sponsoringID", SponsorshipDescriptor),
    ("ext", LedgerEntryExtensionV1Ext),
], defaults={"ext": lambda: LedgerEntryExtensionV1Ext.v0()})

LedgerEntryExt = xdr_union("LedgerEntryExt", Int32, {
    0: ("v0", None),
    1: ("v1", LedgerEntryExtensionV1),
})

LedgerEntry = xdr_struct("LedgerEntry", [
    ("lastModifiedLedgerSeq", Uint32),
    ("data", LedgerEntryData),
    ("ext", LedgerEntryExt),
], defaults={"lastModifiedLedgerSeq": 0, "ext": lambda: LedgerEntryExt.v0()})

# --- LedgerKey ---

_LKAccount = xdr_struct("LedgerKeyAccount", [("accountID", AccountID)])
_LKTrustLine = xdr_struct("LedgerKeyTrustLine", [
    ("accountID", AccountID), ("asset", TrustLineAsset)])
_LKOffer = xdr_struct("LedgerKeyOffer", [
    ("sellerID", AccountID), ("offerID", Int64)])
_LKData = xdr_struct("LedgerKeyData", [
    ("accountID", AccountID), ("dataName", String64)])
_LKClaimableBalance = xdr_struct("LedgerKeyClaimableBalance", [
    ("balanceID", ClaimableBalanceID)])
_LKLiquidityPool = xdr_struct("LedgerKeyLiquidityPool", [
    ("liquidityPoolID", PoolID)])
_LKContractData = xdr_struct("LedgerKeyContractData", [
    ("contract", SCAddress), ("key", SCVal),
    ("durability", ContractDataDurability)])
_LKContractCode = xdr_struct("LedgerKeyContractCode", [("hash", Hash)])
_LKConfigSetting = xdr_struct("LedgerKeyConfigSetting", [("configSettingID", Int32)])
_LKTtl = xdr_struct("LedgerKeyTtl", [("keyHash", Hash)])

LedgerKey = xdr_union("LedgerKey", LedgerEntryType, {
    LedgerEntryType.ACCOUNT: ("account", _LKAccount),
    LedgerEntryType.TRUSTLINE: ("trustLine", _LKTrustLine),
    LedgerEntryType.OFFER: ("offer", _LKOffer),
    LedgerEntryType.DATA: ("data", _LKData),
    LedgerEntryType.CLAIMABLE_BALANCE: ("claimableBalance", _LKClaimableBalance),
    LedgerEntryType.LIQUIDITY_POOL: ("liquidityPool", _LKLiquidityPool),
    LedgerEntryType.CONTRACT_DATA: ("contractData", _LKContractData),
    LedgerEntryType.CONTRACT_CODE: ("contractCode", _LKContractCode),
    LedgerEntryType.CONFIG_SETTING: ("configSetting", _LKConfigSetting),
    LedgerEntryType.TTL: ("ttl", _LKTtl),
})


def ledger_entry_key(entry: "LedgerEntry") -> "LedgerKey":
    """Derive the LedgerKey identifying a LedgerEntry (reference:
    src/ledger/LedgerTxn.cpp — LedgerEntryKey)."""
    d = entry.data
    t = d.switch
    if t == LedgerEntryType.ACCOUNT:
        return LedgerKey.account(_LKAccount(accountID=d.value.accountID))
    if t == LedgerEntryType.TRUSTLINE:
        return LedgerKey.trustLine(_LKTrustLine(
            accountID=d.value.accountID, asset=d.value.asset))
    if t == LedgerEntryType.OFFER:
        return LedgerKey.offer(_LKOffer(
            sellerID=d.value.sellerID, offerID=d.value.offerID))
    if t == LedgerEntryType.DATA:
        return LedgerKey.data(_LKData(
            accountID=d.value.accountID, dataName=d.value.dataName))
    if t == LedgerEntryType.CLAIMABLE_BALANCE:
        return LedgerKey.claimableBalance(_LKClaimableBalance(
            balanceID=d.value.balanceID))
    if t == LedgerEntryType.LIQUIDITY_POOL:
        return LedgerKey.liquidityPool(_LKLiquidityPool(
            liquidityPoolID=d.value.liquidityPoolID))
    if t == LedgerEntryType.CONTRACT_DATA:
        return LedgerKey.contractData(_LKContractData(
            contract=d.value.contract, key=d.value.key,
            durability=d.value.durability))
    if t == LedgerEntryType.CONTRACT_CODE:
        return LedgerKey.contractCode(_LKContractCode(hash=d.value.hash))
    if t == LedgerEntryType.CONFIG_SETTING:
        return LedgerKey.configSetting(_LKConfigSetting(
            configSettingID=d.value.configSettingID))
    if t == LedgerEntryType.TTL:
        return LedgerKey.ttl(_LKTtl(keyHash=d.value.keyHash))
    raise ValueError(f"no key for entry type {t}")


# Account LedgerKey XDR memo: the replay loop derives an account's key
# bytes on every load/update; the encoding is a pure function of the
# 32-byte public key, so memoize it (bounded — pubnet has ~10M accounts,
# a replay touches far fewer at once).
_ACCOUNT_KEY_XDR: dict = {}


def account_key_xdr(pk: bytes) -> bytes:
    kb = _ACCOUNT_KEY_XDR.get(pk)
    if kb is None:
        kb = LedgerKey.account(_LKAccount(
            accountID=AccountID.ed25519(pk))).to_xdr()
        if len(_ACCOUNT_KEY_XDR) < 1_000_000:
            _ACCOUNT_KEY_XDR[pk] = kb
    return kb


def ledger_entry_key_xdr(entry: "LedgerEntry") -> bytes:
    """ledger_entry_key(entry).to_xdr() with the account fast path."""
    d = entry.data
    if d.switch == LedgerEntryType.ACCOUNT:
        return account_key_xdr(d.value.accountID.value)
    return ledger_entry_key(entry).to_xdr()


# public aliases for the per-type LedgerKey structs (used by upper layers)
LedgerKeyAccount = _LKAccount
LedgerKeyTrustLine = _LKTrustLine
LedgerKeyOffer = _LKOffer
LedgerKeyData = _LKData
LedgerKeyClaimableBalance = _LKClaimableBalance
LedgerKeyLiquidityPool = _LKLiquidityPool
LedgerKeyContractData = _LKContractData
LedgerKeyContractCode = _LKContractCode
LedgerKeyConfigSetting = _LKConfigSetting
LedgerKeyTtl = _LKTtl
