"""Byte-exact XDR (RFC 4506) codec — combinator style.

Reference: the reference uses xdrpp-generated C++ from src/protocol-curr/xdr/*.x
(SURVEY.md §2.1 "XDR protocol defs"). We implement our own declarative codec:
types are combinator objects with pack_into/unpack_from; generated struct/union
classes double as value holders AND as field types, so nested declarations read
like the .x files.

Ledger hashes depend on byte-exact encoding, so this module is tested with
exhaustive round-trip + adversarial truncation tests (tests/test_xdr.py).
"""

from __future__ import annotations

import enum
import struct as _struct
import sys
from typing import Any, Dict, List, Optional as Opt, Sequence, Tuple

_U32 = _struct.Struct(">I")
_I32 = _struct.Struct(">i")
_U64 = _struct.Struct(">Q")
_I64 = _struct.Struct(">q")


class XdrError(ValueError):
    pass


def _pad(n: int) -> int:
    return (4 - n % 4) % 4


class XdrType:
    """Protocol: pack_into(val, out: bytearray); unpack_from(buf, off) -> (val, off)."""

    _cxdr_prog = None

    def pack(self, val: Any) -> bytes:
        if _cxdr is not None:
            prog = self._cxdr_prog
            if prog is None:
                prog = self._cxdr_prog = compile_program(self)
            try:
                return _cxdr.pack(prog, val)
            except _cxdr.Error as e:
                raise XdrError(str(e)) from None
        return self._pack_py(val)

    def _pack_py(self, val: Any) -> bytes:
        out = bytearray()
        self.pack_into(val, out)
        return bytes(out)

    def unpack(self, data: bytes) -> Any:
        if _cxdr_unpack is not None:
            prog = self._cxdr_prog
            if prog is None:
                prog = self._cxdr_prog = compile_program(self)
            try:
                return _cxdr.unpack(prog, data)
            except _cxdr.Error as e:
                raise XdrError(str(e)) from None
        val, off = self.unpack_from(data, 0)
        if off != len(data):
            raise XdrError(f"trailing bytes: consumed {off} of {len(data)}")
        return val

    def unpack_from_fast(self, buf: bytes, off: int = 0) -> Tuple[Any, int]:
        """Native-accelerated unpack_from when the extension is built
        (stream decoding — the catchup-replay hot loop); falls back to the
        pure-Python recursion otherwise."""
        if _cxdr_unpack is not None:
            prog = self._cxdr_prog
            if prog is None:
                prog = self._cxdr_prog = compile_program(self)
            try:
                return _cxdr.unpack_from(prog, buf, off)
            except _cxdr.Error as e:
                raise XdrError(str(e)) from None
        return self.unpack_from(buf, off)

    def pack_into(self, val: Any, out: bytearray) -> None:  # pragma: no cover
        raise NotImplementedError

    def unpack_from(self, buf: bytes, off: int) -> Tuple[Any, int]:  # pragma: no cover
        raise NotImplementedError


def _pack_prim(packer, val) -> bytes:
    try:
        return packer.pack(val)
    except (_struct.error, TypeError) as e:
        raise XdrError(f"value out of range: {val!r} ({e})") from None


class _Int32(XdrType):
    def pack_into(self, val, out):
        out += _pack_prim(_I32, val)

    def unpack_from(self, buf, off):
        if off + 4 > len(buf):
            raise XdrError("short buffer for int32")
        return _I32.unpack_from(buf, off)[0], off + 4


class _Uint32(XdrType):
    def pack_into(self, val, out):
        out += _pack_prim(_U32, val)

    def unpack_from(self, buf, off):
        if off + 4 > len(buf):
            raise XdrError("short buffer for uint32")
        return _U32.unpack_from(buf, off)[0], off + 4


class _Int64(XdrType):
    def pack_into(self, val, out):
        out += _pack_prim(_I64, val)

    def unpack_from(self, buf, off):
        if off + 8 > len(buf):
            raise XdrError("short buffer for int64")
        return _I64.unpack_from(buf, off)[0], off + 8


class _Uint64(XdrType):
    def pack_into(self, val, out):
        out += _pack_prim(_U64, val)

    def unpack_from(self, buf, off):
        if off + 8 > len(buf):
            raise XdrError("short buffer for uint64")
        return _U64.unpack_from(buf, off)[0], off + 8


class _Bool(XdrType):
    def pack_into(self, val, out):
        out += _U32.pack(1 if val else 0)

    def unpack_from(self, buf, off):
        v, off = Uint32.unpack_from(buf, off)
        if v not in (0, 1):
            raise XdrError(f"bad bool {v}")
        return bool(v), off


Int32 = _Int32()
Uint32 = _Uint32()
Int64 = _Int64()
Uint64 = _Uint64()
Bool = _Bool()


class Opaque(XdrType):
    """Fixed-length opaque[n], zero-padded to 4."""

    def __init__(self, n: int) -> None:
        self.n = n

    def pack_into(self, val: bytes, out):
        if len(val) != self.n:
            raise XdrError(f"opaque[{self.n}]: got {len(val)} bytes")
        out += val
        out += b"\x00" * _pad(self.n)

    def unpack_from(self, buf, off):
        end = off + self.n + _pad(self.n)
        if end > len(buf):
            raise XdrError(f"short buffer for opaque[{self.n}]")
        if any(buf[off + self.n:end]):
            raise XdrError("nonzero padding")
        return bytes(buf[off:off + self.n]), end


class VarOpaque(XdrType):
    """Variable opaque<max>: u32 length + data + padding."""

    def __init__(self, max_len: int = 0xFFFFFFFF) -> None:
        self.max_len = max_len

    def pack_into(self, val: bytes, out):
        if len(val) > self.max_len:
            raise XdrError(f"opaque<{self.max_len}>: got {len(val)} bytes")
        out += _U32.pack(len(val))
        out += val
        out += b"\x00" * _pad(len(val))

    def unpack_from(self, buf, off):
        n, off = Uint32.unpack_from(buf, off)
        if n > self.max_len:
            raise XdrError(f"opaque<{self.max_len}>: length {n}")
        end = off + n + _pad(n)
        if end > len(buf):
            raise XdrError("short buffer for var opaque")
        if any(buf[off + n:end]):
            raise XdrError("nonzero padding")
        return bytes(buf[off:off + n]), end


class XdrString(XdrType):
    """string<max> — stored as bytes (stellar strings are ASCII-checked upstream)."""

    def __init__(self, max_len: int = 0xFFFFFFFF) -> None:
        self._op = VarOpaque(max_len)

    def pack_into(self, val, out):
        if isinstance(val, str):
            val = val.encode("utf-8")
        self._op.pack_into(val, out)

    def unpack_from(self, buf, off):
        return self._op.unpack_from(buf, off)


class FixedArray(XdrType):
    def __init__(self, elem: "XdrType", n: int) -> None:
        self.elem, self.n = _as_type(elem), n

    def pack_into(self, val: Sequence, out):
        if len(val) != self.n:
            raise XdrError(f"array[{self.n}]: got {len(val)}")
        for v in val:
            self.elem.pack_into(v, out)

    def unpack_from(self, buf, off):
        vals = []
        for _ in range(self.n):
            v, off = self.elem.unpack_from(buf, off)
            vals.append(v)
        return vals, off


class VarArray(XdrType):
    def __init__(self, elem: "XdrType", max_len: int = 0xFFFFFFFF) -> None:
        self.elem, self.max_len = _as_type(elem), max_len

    def pack_into(self, val: Sequence, out):
        if len(val) > self.max_len:
            raise XdrError(f"array<{self.max_len}>: got {len(val)}")
        out += _U32.pack(len(val))
        for v in val:
            self.elem.pack_into(v, out)

    def unpack_from(self, buf, off):
        n, off = Uint32.unpack_from(buf, off)
        if n > self.max_len:
            raise XdrError(f"array<{self.max_len}>: length {n}")
        vals = []
        for _ in range(n):
            v, off = self.elem.unpack_from(buf, off)
            vals.append(v)
        return vals, off


class Optional(XdrType):
    """T* — bool presence + value."""

    def __init__(self, elem: "XdrType") -> None:
        self.elem = _as_type(elem)

    def pack_into(self, val, out):
        if val is None:
            out += _U32.pack(0)
        else:
            out += _U32.pack(1)
            self.elem.pack_into(val, out)

    def unpack_from(self, buf, off):
        present, off = Bool.unpack_from(buf, off)
        if not present:
            return None, off
        return self.elem.unpack_from(buf, off)


class _Void(XdrType):
    def pack_into(self, val, out):
        pass

    def unpack_from(self, buf, off):
        return None, off


Void = _Void()


class _EnumAdapter(XdrType):
    def __init__(self, enum_cls) -> None:
        self.enum_cls = enum_cls

    def pack_into(self, val, out):
        try:
            val = self.enum_cls(val)
        except ValueError:
            raise XdrError(
                f"bad {self.enum_cls.__name__} value {val!r}") from None
        out += _pack_prim(_I32, int(val))

    def unpack_from(self, buf, off):
        v, off = Int32.unpack_from(buf, off)
        try:
            return self.enum_cls(v), off
        except ValueError:
            raise XdrError(f"bad {self.enum_cls.__name__} value {v}") from None


def _as_type(t) -> XdrType:
    """Accept XdrType instances, struct/union classes, and IntEnum classes."""
    if isinstance(t, XdrType):
        return t
    if isinstance(t, type) and issubclass(t, enum.IntEnum):
        return _EnumAdapter(t)
    if isinstance(t, type) and hasattr(t, "_xdr_adapter"):
        return t._xdr_adapter()
    raise TypeError(f"not an XDR type: {t!r}")


def xdr_enum(name: str, values: Dict[str, int]):
    """Declare an XDR enum as an IntEnum (packed as signed int32)."""
    return enum.IntEnum(name, values)


class _StructAdapter(XdrType):
    def __init__(self, cls) -> None:
        self.cls = cls

    def pack_into(self, val, out):
        if not isinstance(val, self.cls):
            raise XdrError(f"expected {self.cls.__name__}, got {type(val).__name__}")
        for fname, ftype in self.cls._spec:
            ftype.pack_into(getattr(val, fname), out)

    def unpack_from(self, buf, off):
        kwargs = {}
        for fname, ftype in self.cls._spec:
            kwargs[fname], off = ftype.unpack_from(buf, off)
        return self.cls(**kwargs), off


_MISSING = object()


def _compile_struct_init(name, field_names, defaults):
    """exec-generate a flat __init__ (no kwargs dict walking) — struct
    construction is a replay-loop hot spot (profile: ~4 µs/call with the
    generic loop, ~1 µs compiled)."""
    ns = {"_MISSING": _MISSING}
    params = []
    body = []
    for f in field_names:
        params.append(f"{f}=_MISSING")
        if f in defaults:
            d = defaults[f]
            ns[f"_d_{f}"] = d
            if callable(d):
                body.append(f"    self.{f} = _d_{f}() "
                            f"if {f} is _MISSING else {f}")
            else:
                body.append(f"    self.{f} = _d_{f} "
                            f"if {f} is _MISSING else {f}")
        else:
            ns[f"_m_{f}"] = f"{name}: missing field {f!r}"
            body.append(f"    if {f} is _MISSING:")
            body.append(f"        raise TypeError(_m_{f})")
            body.append(f"    self.{f} = {f}")
    src = f"def __init__(self, *, {', '.join(params)}):\n" + "\n".join(body)
    exec(src, ns)  # noqa: S102 — trusted, generated from declared schema
    return ns["__init__"]


def xdr_struct(name: str, fields: List[Tuple[str, Any]], defaults: Opt[Dict[str, Any]] = None):
    """Declare an XDR struct; returns a value class usable as a field type."""
    spec = [(fname, _as_type(ftype)) for fname, ftype in fields]
    field_names = [f for f, _ in spec]
    defaults = defaults or {}

    class Struct:
        _spec = spec
        __slots__ = tuple(field_names)

        __init__ = _compile_struct_init(name, field_names, defaults)

        @classmethod
        def _xdr_adapter(cls):
            a = cls.__dict__.get("_cached_adapter")
            if a is None:
                a = _StructAdapter(cls)
                cls._cached_adapter = a
            return a

        def to_xdr(self) -> bytes:
            return self._xdr_adapter().pack(self)

        @classmethod
        def from_xdr(cls, data: bytes):
            return cls._xdr_adapter().unpack(data)

        def __eq__(self, other):
            return type(other) is type(self) and all(
                getattr(self, f) == getattr(other, f) for f in field_names)

        def __hash__(self):
            return hash(self.to_xdr())

        def __repr__(self):
            parts = ", ".join(f"{f}={getattr(self, f)!r}" for f in field_names)
            return f"{name}({parts})"

        def copy(self, **overrides):
            kw = {f: getattr(self, f) for f in field_names}
            kw.update(overrides)
            return type(self)(**kw)

        def deep_copy(self):
            """Recursive structural copy, ~10x faster than the XDR
            pack/unpack round-trip (the LedgerTxn copy-out hot path).
            Runs natively when the extension is built."""
            if _cxdr_deep_copy is not None:
                return _cxdr_deep_copy(self)
            new = object.__new__(type(self))
            for f in field_names:
                setattr(new, f, _deep_copy_py(getattr(self, f)))
            return new

    Struct.__name__ = Struct.__qualname__ = name
    return Struct


def _deep_copy_py(val):
    """Pure-Python deep copy of any XDR value: primitives are immutable
    and shared; lists are rebuilt; structs/unions copy field-wise."""
    if val is None or isinstance(val, (int, bytes, str, bool)):
        return val
    if isinstance(val, list):
        return [_deep_copy_py(v) for v in val]
    return val.deep_copy()


def deep_copy_value(val):
    """Deep copy of any XDR value (native when the extension is built)."""
    if _cxdr_deep_copy is not None:
        return _cxdr_deep_copy(val)
    return _deep_copy_py(val)


class _UnionAdapter(XdrType):
    def __init__(self, cls) -> None:
        self.cls = cls

    def pack_into(self, val, out):
        if not isinstance(val, self.cls):
            raise XdrError(f"expected {self.cls.__name__}, got {type(val).__name__}")
        arm = self.cls._arm_for(val.switch)
        if arm is None:
            raise XdrError(
                f"{self.cls.__name__}: no arm for discriminant {val.switch!r}")
        self.cls._switch_type.pack_into(val.switch, out)
        if arm[1] is not None:
            arm[1].pack_into(val.value, out)

    def unpack_from(self, buf, off):
        sw, off = self.cls._switch_type.unpack_from(buf, off)
        arm = self.cls._arm_for(sw)
        if arm is None:
            raise XdrError(f"{self.cls.__name__}: no arm for discriminant {sw!r}")
        value = None
        if arm[1] is not None:
            value, off = arm[1].unpack_from(buf, off)
        return self.cls(sw, value), off


def xdr_union(name: str, switch_type, arms: Dict[Any, Tuple[str, Any]],
              default: Opt[Tuple[str, Any]] = None):
    """Declare an XDR union.

    arms: {discriminant: (arm_name, arm_type_or_None)}.  Value class exposes
    .switch, .value, and a classmethod constructor per named arm.
    """
    sw_t = _as_type(switch_type)
    resolved = {k: (an, _as_type(at) if at is not None else None)
                for k, (an, at) in arms.items()}
    default_arm = (default[0], _as_type(default[1]) if default[1] is not None else None) \
        if default else None

    class Union:
        _switch_type = sw_t
        _arms = resolved
        _default = default_arm
        __slots__ = ("switch", "value")

        def __init__(self, switch, value=None):
            self.switch = switch
            self.value = value

        @classmethod
        def _arm_for(cls, sw):
            arm = cls._arms.get(sw)
            if arm is None:
                return cls._default
            return arm

        @property
        def arm(self) -> Opt[str]:
            a = self._arm_for(self.switch)
            return a[0] if a else None

        @classmethod
        def _xdr_adapter(cls):
            a = cls.__dict__.get("_cached_adapter")
            if a is None:
                a = _UnionAdapter(cls)
                cls._cached_adapter = a
            return a

        def to_xdr(self) -> bytes:
            return self._xdr_adapter().pack(self)

        @classmethod
        def from_xdr(cls, data: bytes):
            return cls._xdr_adapter().unpack(data)

        def __eq__(self, other):
            return (type(other) is type(self) and self.switch == other.switch
                    and self.value == other.value)

        def __hash__(self):
            return hash(self.to_xdr())

        def __repr__(self):
            return f"{name}({self.switch!r}, {self.value!r})"

        def deep_copy(self):
            if _cxdr_deep_copy is not None:
                return _cxdr_deep_copy(self)
            new = object.__new__(type(self))
            new.switch = self.switch
            new.value = _deep_copy_py(self.value)
            return new

        @property
        def type(self):
            """Alias for the discriminant (reads like the reference's
            `pledges.type()` accessor)."""
            return self.switch

    class _ArmDescriptor:
        """Class access → constructor; instance access → the arm's value
        (raises if the union currently holds a different arm).  The
        constructor closure is built once and memoized — class-level arm
        access is a construction hot spot (profile: ~46k closures per
        apply-load run before memoization)."""

        __slots__ = ("disc", "arm_name", "has_value", "_made")

        def __init__(self, disc, arm_name, has_value):
            self.disc = disc
            self.arm_name = arm_name
            self.has_value = has_value
            self._made = None

        def __get__(self, obj, objtype=None):
            if obj is None:
                make = self._made
                if make is None:
                    disc, has_value = self.disc, self.has_value
                    if has_value:
                        def make(value):
                            return objtype(disc, value)
                    else:
                        def make():
                            return objtype(disc)
                    make.__name__ = self.arm_name
                    self._made = make
                return make
            # match by arm NAME, not discriminant: several discriminants may
            # share an arm name (e.g. SCError's SCE_VALUE/SCE_AUTH `code`),
            # and instance access must work for all of them
            if obj.arm != self.arm_name:
                raise AttributeError(
                    f"{name} holds arm {obj.arm!r}, not {self.arm_name!r}")
            return obj.value

    for disc, (arm_name, arm_type) in resolved.items():
        if not arm_name.isidentifier() or hasattr(Union, arm_name):
            continue
        setattr(Union, arm_name, _ArmDescriptor(disc, arm_name,
                                                arm_type is not None))

    Union.__name__ = Union.__qualname__ = name
    return Union


def xdr_typedef(t) -> XdrType:
    return _as_type(t)


def pack(t, val) -> bytes:
    return _as_type(t).pack(val)


def unpack(t, data: bytes):
    return _as_type(t).unpack(data)


# ---------------------------------------------------------------------------
# Native serializer integration (native/cxdr.c).  The Python pack_into
# implementations above stay the semantic source of truth; compile_program
# lowers a type to the C interpreter's tuple program, with OP_PYCALL as the
# graceful degradation for recursive/unknown types.  Set STELLAR_TPU_NO_CXDR
# to force the pure-Python path (the differential test does).

import os as _os

try:
    if _os.environ.get("STELLAR_TPU_NO_CXDR"):
        raise ImportError("cxdr disabled by STELLAR_TPU_NO_CXDR")
    from stellar_core_tpu import _cxdr  # built via `make native`
except ImportError:
    _cxdr = None

# unpack/deep_copy arrived after pack; tolerate a stale built extension
_cxdr_unpack = getattr(_cxdr, "unpack", None)
_cxdr_deep_copy = getattr(_cxdr, "deep_copy", None)


def compile_program(t) -> tuple:
    t = _as_type(t)
    if isinstance(t, _Uint32):
        return (1,)
    if isinstance(t, _Int32):
        return (2,)
    if isinstance(t, _Uint64):
        return (3,)
    if isinstance(t, _Int64):
        return (4,)
    if isinstance(t, _Bool):
        return (5,)
    if isinstance(t, _EnumAdapter):
        # values are the member objects: pack only membership-checks the
        # keys; unpack returns the member (same as _EnumAdapter)
        return (6, {int(m): m for m in t.enum_cls})
    if isinstance(t, Opaque):
        return (7, t.n)
    if isinstance(t, VarOpaque):
        return (8, t.max_len)
    if isinstance(t, XdrString):
        return (9, t._op.max_len)
    if isinstance(t, FixedArray):
        return (10, t.n, compile_program(t.elem))
    if isinstance(t, VarArray):
        return (11, t.max_len, compile_program(t.elem))
    if isinstance(t, Optional):
        return (12, compile_program(t.elem))
    if isinstance(t, _Void):
        return (13,)
    if isinstance(t, _StructAdapter):
        parts = []
        for fname, ftype in t.cls._spec:
            parts.append(sys.intern(fname))
            parts.append(compile_program(ftype))
        return (14, tuple(parts), t.cls)
    if isinstance(t, _UnionAdapter):
        arms = {}
        for k, (_an, at) in t.cls._arms.items():
            arms[int(k)] = compile_program(at) if at is not None else None
        default = t.cls._default
        defprog = (compile_program(default[1])
                   if default is not None and default[1] is not None else None)
        # enum-typed switches carry the member dict (None for plain
        # int switches): pack membership-checks the keys, unpack maps the
        # wire int back to the member object for `.switch`
        sw_t = t.cls._switch_type
        members = ({int(m): m for m in sw_t.enum_cls}
                   if isinstance(sw_t, _EnumAdapter) else None)
        return (15, arms, defprog, default is not None, members, t.cls)
    # recursive forward refs and anything unknown: Python-callback seam
    return (16, t)
