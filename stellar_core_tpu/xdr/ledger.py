"""Stellar-ledger.x equivalents (reference: src/protocol-curr/xdr/Stellar-ledger.x):
LedgerHeader, StellarValue, upgrades, transaction sets (classic + generalized),
history entries, bucket entries, ledger close meta."""

from .codec import (FixedArray, Int32, Int64, Opaque, Optional, Uint32, Uint64,
                    VarArray, VarOpaque, XdrString, xdr_enum, xdr_struct,
                    xdr_union)
from .types import (ExtensionPoint, Hash, NodeID, PoolID, SequenceNumber,
                    Signature, TimePoint, Uint256)
from .ledger_entries import LedgerEntry, LedgerKey
from .transaction import (TransactionEnvelope, TransactionResultPair,
                          TransactionResultCode)

MAX_TX_PER_LEDGER = 2000

UpgradeType = VarOpaque(128)

StellarValueType = xdr_enum("StellarValueType", {
    "STELLAR_VALUE_BASIC": 0,
    "STELLAR_VALUE_SIGNED": 1,
})

LedgerCloseValueSignature = xdr_struct("LedgerCloseValueSignature", [
    ("nodeID", NodeID),
    ("signature", Signature),
])

_StellarValueExt = xdr_union("StellarValueExt", StellarValueType, {
    StellarValueType.STELLAR_VALUE_BASIC: ("basic", None),
    StellarValueType.STELLAR_VALUE_SIGNED: ("lcValueSignature", LedgerCloseValueSignature),
})

StellarValue = xdr_struct("StellarValue", [
    ("txSetHash", Hash),
    ("closeTime", TimePoint),
    ("upgrades", VarArray(UpgradeType, 6)),
    ("ext", _StellarValueExt),
], defaults={"upgrades": list, "ext": lambda: _StellarValueExt.basic()})

LedgerHeaderFlags = xdr_enum("LedgerHeaderFlags", {
    "DISABLE_LIQUIDITY_POOL_TRADING_FLAG": 0x1,
    "DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG": 0x2,
    "DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG": 0x4,
})

LedgerHeaderExtensionV1 = xdr_struct("LedgerHeaderExtensionV1", [
    ("flags", Uint32),
    ("ext", xdr_union("LedgerHeaderExtensionV1Ext", Int32, {0: ("v0", None)})),
])

_LedgerHeaderExt = xdr_union("LedgerHeaderExt", Int32, {
    0: ("v0", None),
    1: ("v1", LedgerHeaderExtensionV1),
})

LedgerHeader = xdr_struct("LedgerHeader", [
    ("ledgerVersion", Uint32),
    ("previousLedgerHash", Hash),
    ("scpValue", StellarValue),
    ("txSetResultHash", Hash),
    ("bucketListHash", Hash),
    ("ledgerSeq", Uint32),
    ("totalCoins", Int64),
    ("feePool", Int64),
    ("inflationSeq", Uint32),
    ("idPool", Uint64),
    ("baseFee", Uint32),
    ("baseReserve", Uint32),
    ("maxTxSetSize", Uint32),
    ("skipList", FixedArray(Hash, 4)),
    ("ext", _LedgerHeaderExt),
], defaults={"ext": lambda: _LedgerHeaderExt.v0()})

LedgerUpgradeType = xdr_enum("LedgerUpgradeType", {
    "LEDGER_UPGRADE_VERSION": 1,
    "LEDGER_UPGRADE_BASE_FEE": 2,
    "LEDGER_UPGRADE_MAX_TX_SET_SIZE": 3,
    "LEDGER_UPGRADE_BASE_RESERVE": 4,
    "LEDGER_UPGRADE_FLAGS": 5,
    "LEDGER_UPGRADE_CONFIG": 6,
    "LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE": 7,
})

ConfigUpgradeSetKey = xdr_struct("ConfigUpgradeSetKey", [
    ("contractID", Hash),
    ("contentHash", Hash),
])

LedgerUpgrade = xdr_union("LedgerUpgrade", LedgerUpgradeType, {
    LedgerUpgradeType.LEDGER_UPGRADE_VERSION: ("newLedgerVersion", Uint32),
    LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE: ("newBaseFee", Uint32),
    LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE: ("newMaxTxSetSize", Uint32),
    LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE: ("newBaseReserve", Uint32),
    LedgerUpgradeType.LEDGER_UPGRADE_FLAGS: ("newFlags", Uint32),
    LedgerUpgradeType.LEDGER_UPGRADE_CONFIG: ("newConfig", ConfigUpgradeSetKey),
    LedgerUpgradeType.LEDGER_UPGRADE_MAX_SOROBAN_TX_SET_SIZE:
        ("newMaxSorobanTxSetSize", Uint32),
})

# --- transaction sets ---

TransactionSet = xdr_struct("TransactionSet", [
    ("previousLedgerHash", Hash),
    ("txs", VarArray(TransactionEnvelope)),
])

# Generalized tx set (protocol 20+): phases of components with optional
# discounted base fee.
TxSetComponentType = xdr_enum("TxSetComponentType", {
    "TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE": 0,
})

_TxsMaybeDiscountedFee = xdr_struct("TxSetComponentTxsMaybeDiscountedFee", [
    ("baseFee", Optional(Int64)),
    ("txs", VarArray(TransactionEnvelope)),
])

TxSetComponent = xdr_union("TxSetComponent", TxSetComponentType, {
    TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE:
        ("txsMaybeDiscountedFee", _TxsMaybeDiscountedFee),
})

TransactionPhase = xdr_union("TransactionPhase", Int32, {
    0: ("v0Components", VarArray(TxSetComponent)),
})

TransactionSetV1 = xdr_struct("TransactionSetV1", [
    ("previousLedgerHash", Hash),
    ("phases", VarArray(TransactionPhase)),
])

GeneralizedTransactionSet = xdr_union("GeneralizedTransactionSet", Int32, {
    1: ("v1TxSet", TransactionSetV1),
})

# public aliases (the soroban tx-set builder constructs components, the
# ledger manager builds generalized history-entry exts)
TxSetComponentTxsMaybeDiscountedFee = _TxsMaybeDiscountedFee

# --- history entries ---

_THEExt = xdr_union("TransactionHistoryEntryExt", Int32, {
    0: ("v0", None),
    1: ("generalizedTxSet", GeneralizedTransactionSet),
})

TransactionHistoryEntry = xdr_struct("TransactionHistoryEntry", [
    ("ledgerSeq", Uint32),
    ("txSet", TransactionSet),
    ("ext", _THEExt),
], defaults={"ext": lambda: _THEExt.v0()})

TransactionHistoryEntryExt = _THEExt

TransactionResultSet = xdr_struct("TransactionResultSet", [
    ("results", VarArray(TransactionResultPair)),
])

_THREExt = xdr_union("TransactionHistoryResultEntryExt", Int32, {0: ("v0", None)})

TransactionHistoryResultEntry = xdr_struct("TransactionHistoryResultEntry", [
    ("ledgerSeq", Uint32),
    ("txResultSet", TransactionResultSet),
    ("ext", _THREExt),
], defaults={"ext": lambda: _THREExt.v0()})

LedgerHeaderHistoryEntryExt = xdr_union("LedgerHeaderHistoryEntryExt", Int32,
                                        {0: ("v0", None)})

LedgerHeaderHistoryEntry = xdr_struct("LedgerHeaderHistoryEntry", [
    ("hash", Hash),
    ("header", LedgerHeader),
    ("ext", LedgerHeaderHistoryEntryExt),
], defaults={"ext": lambda: LedgerHeaderHistoryEntryExt.v0()})

# --- SCP history ---

from .scp import SCPEnvelope, SCPQuorumSet  # noqa: E402

LedgerSCPMessages = xdr_struct("LedgerSCPMessages", [
    ("ledgerSeq", Uint32),
    ("messages", VarArray(SCPEnvelope)),
])

SCPHistoryEntryV0 = xdr_struct("SCPHistoryEntryV0", [
    ("quorumSets", VarArray(SCPQuorumSet)),
    ("ledgerMessages", LedgerSCPMessages),
])

SCPHistoryEntry = xdr_union("SCPHistoryEntry", Int32, {
    0: ("v0", SCPHistoryEntryV0),
})

# --- bucket entries ---

BucketEntryType = xdr_enum("BucketEntryType", {
    "METAENTRY": -1,
    "LIVEENTRY": 0,
    "DEADENTRY": 1,
    "INITENTRY": 2,
})

BucketListType = xdr_enum("BucketListType", {
    "LIVE": 0,
    "HOT_ARCHIVE": 1,
})

_BucketMetadataExt = xdr_union("BucketMetadataExt", Int32, {
    0: ("v0", None),
    1: ("bucketListType", BucketListType),
})

BucketMetadata = xdr_struct("BucketMetadata", [
    ("ledgerVersion", Uint32),
    ("ext", _BucketMetadataExt),
], defaults={"ext": lambda: _BucketMetadataExt.v0()})

BucketEntry = xdr_union("BucketEntry", BucketEntryType, {
    BucketEntryType.LIVEENTRY: ("liveEntry", LedgerEntry),
    BucketEntryType.INITENTRY: ("initEntry", LedgerEntry),
    BucketEntryType.DEADENTRY: ("deadEntry", LedgerKey),
    BucketEntryType.METAENTRY: ("metaEntry", BucketMetadata),
})

# --- ledger close meta (observability firehose; simplified v0 shape) ---

TransactionResultMeta = xdr_struct("TransactionResultMeta", [
    ("result", TransactionResultPair),
    ("feeProcessing", VarOpaque()),     # LedgerEntryChanges carried opaque for now
    ("txApplyProcessing", VarOpaque()),
])

UpgradeEntryMeta = xdr_struct("UpgradeEntryMeta", [
    ("upgrade", LedgerUpgrade),
    ("changes", VarOpaque()),
])

LedgerCloseMetaV0 = xdr_struct("LedgerCloseMetaV0", [
    ("ledgerHeader", LedgerHeaderHistoryEntry),
    ("txSet", TransactionSet),
    ("txProcessing", VarArray(TransactionResultMeta)),
    ("upgradesProcessing", VarArray(UpgradeEntryMeta)),
    ("scpInfo", VarArray(SCPHistoryEntry)),
])

LedgerCloseMeta = xdr_union("LedgerCloseMeta", Int32, {
    0: ("v0", LedgerCloseMetaV0),
})
