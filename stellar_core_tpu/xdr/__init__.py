"""XDR protocol layer: byte-exact codec + Stellar protocol types.

Reference: src/protocol-curr/xdr/*.x compiled by xdrpp (SURVEY.md §2.1); here
the types are declared directly in Python combinators (codec.py).
"""

from .codec import (Bool, FixedArray, Int32, Int64, Opaque, Optional, Uint32,
                    Uint64, VarArray, VarOpaque, Void, XdrError, XdrString,
                    deep_copy_value, pack, unpack, xdr_enum, xdr_struct,
                    xdr_union)
from .types import *      # noqa: F401,F403
from .contract import *        # noqa: F401,F403
from .ledger_entries import *  # noqa: F401,F403
from .transaction import *     # noqa: F401,F403
from .scp import *             # noqa: F401,F403
from .ledger import *          # noqa: F401,F403
from .overlay import *       # noqa: F401,F403
