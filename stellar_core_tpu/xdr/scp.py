"""Stellar-SCP.x equivalents (reference: src/protocol-curr/xdr/Stellar-SCP.x)."""

from .codec import (Int32, Opaque, Optional, Uint32, Uint64, VarArray,
                    VarOpaque, xdr_enum, xdr_struct, xdr_union)
from .types import Hash, NodeID, Signature

Value = VarOpaque()

SCPBallot = xdr_struct("SCPBallot", [
    ("counter", Uint32),
    ("value", Value),
])

SCPStatementType = xdr_enum("SCPStatementType", {
    "SCP_ST_PREPARE": 0,
    "SCP_ST_CONFIRM": 1,
    "SCP_ST_EXTERNALIZE": 2,
    "SCP_ST_NOMINATE": 3,
})

SCPNomination = xdr_struct("SCPNomination", [
    ("quorumSetHash", Hash),
    ("votes", VarArray(Value)),
    ("accepted", VarArray(Value)),
])

SCPPrepare = xdr_struct("SCPPrepare", [
    ("quorumSetHash", Hash),
    ("ballot", SCPBallot),
    ("prepared", Optional(SCPBallot)),
    ("preparedPrime", Optional(SCPBallot)),
    ("nC", Uint32),
    ("nH", Uint32),
], defaults={"prepared": None, "preparedPrime": None, "nC": 0, "nH": 0})

SCPConfirm = xdr_struct("SCPConfirm", [
    ("ballot", SCPBallot),
    ("nPrepared", Uint32),
    ("nCommit", Uint32),
    ("nH", Uint32),
    ("quorumSetHash", Hash),
])

SCPExternalize = xdr_struct("SCPExternalize", [
    ("commit", SCPBallot),
    ("nH", Uint32),
    ("commitQuorumSetHash", Hash),
])

SCPStatementPledges = xdr_union("SCPStatementPledges", SCPStatementType, {
    SCPStatementType.SCP_ST_PREPARE: ("prepare", SCPPrepare),
    SCPStatementType.SCP_ST_CONFIRM: ("confirm", SCPConfirm),
    SCPStatementType.SCP_ST_EXTERNALIZE: ("externalize", SCPExternalize),
    SCPStatementType.SCP_ST_NOMINATE: ("nominate", SCPNomination),
})

SCPStatement = xdr_struct("SCPStatement", [
    ("nodeID", NodeID),
    ("slotIndex", Uint64),
    ("pledges", SCPStatementPledges),
])

SCPEnvelope = xdr_struct("SCPEnvelope", [
    ("statement", SCPStatement),
    ("signature", Signature),
])


from .codec import XdrType as _XdrType  # noqa: E402


class _SCPQuorumSetFwd(_XdrType):
    _target = None

    def pack_into(self, val, out):
        self._target.pack_into(val, out)

    def unpack_from(self, buf, off):
        return self._target.unpack_from(buf, off)


_qs_fwd = _SCPQuorumSetFwd()

SCPQuorumSet = xdr_struct("SCPQuorumSet", [
    ("threshold", Uint32),
    ("validators", VarArray(NodeID)),
    ("innerSets", VarArray(_qs_fwd)),
], defaults={"validators": list, "innerSets": list})
_SCPQuorumSetFwd._target = SCPQuorumSet._xdr_adapter()
