"""Stellar-transaction.x equivalents (reference:
src/protocol-curr/xdr/Stellar-transaction.x): MuxedAccount, the 27 operation
bodies (24 classic + 3 Soroban, SURVEY.md §2.2), Transaction v0/v1, fee-bump,
envelopes, signature payloads, and the full result-code hierarchy."""

from .codec import (Bool, Int32, Int64, Opaque, Optional, Uint32, Uint64,
                    VarArray, VarOpaque, Void, XdrString, xdr_enum, xdr_struct,
                    xdr_union)
from .types import (AccountID, Duration, ExtensionPoint, Hash, Liabilities,
                    PoolID, Price, SequenceNumber, Signature, SignatureHint,
                    SignerKey, String32, String64, TimePoint, Uint256)
from .ledger_entries import (Asset, AssetCode4, AssetCode12, ClaimableBalanceID,
                             Claimant, DataValue, LedgerEntry, LedgerKey,
                             Signer, TrustLineAsset)

MAX_OPS_PER_TX = 100

from .types import CryptoKeyType  # noqa: E402

_CKT = CryptoKeyType

_MuxedAccountMed25519 = xdr_struct("MuxedAccountMed25519", [
    ("id", Uint64),
    ("ed25519", Uint256),
])

MuxedAccount = xdr_union("MuxedAccount", _CKT, {
    _CKT.KEY_TYPE_ED25519: ("ed25519", Uint256),
    _CKT.KEY_TYPE_MUXED_ED25519: ("med25519", _MuxedAccountMed25519),
})


def muxed_from_account_id(acc: "AccountID") -> "MuxedAccount":
    return MuxedAccount.ed25519(acc.value)


def muxed_to_account_id(m: "MuxedAccount") -> "AccountID":
    if m.switch == _CKT.KEY_TYPE_ED25519:
        return AccountID.ed25519(m.value)
    return AccountID.ed25519(m.value.ed25519)


DecoratedSignature = xdr_struct("DecoratedSignature", [
    ("hint", SignatureHint),
    ("signature", Signature),
])

OperationType = xdr_enum("OperationType", {
    "CREATE_ACCOUNT": 0,
    "PAYMENT": 1,
    "PATH_PAYMENT_STRICT_RECEIVE": 2,
    "MANAGE_SELL_OFFER": 3,
    "CREATE_PASSIVE_SELL_OFFER": 4,
    "SET_OPTIONS": 5,
    "CHANGE_TRUST": 6,
    "ALLOW_TRUST": 7,
    "ACCOUNT_MERGE": 8,
    "INFLATION": 9,
    "MANAGE_DATA": 10,
    "BUMP_SEQUENCE": 11,
    "MANAGE_BUY_OFFER": 12,
    "PATH_PAYMENT_STRICT_SEND": 13,
    "CREATE_CLAIMABLE_BALANCE": 14,
    "CLAIM_CLAIMABLE_BALANCE": 15,
    "BEGIN_SPONSORING_FUTURE_RESERVES": 16,
    "END_SPONSORING_FUTURE_RESERVES": 17,
    "REVOKE_SPONSORSHIP": 18,
    "CLAWBACK": 19,
    "CLAWBACK_CLAIMABLE_BALANCE": 20,
    "SET_TRUST_LINE_FLAGS": 21,
    "LIQUIDITY_POOL_DEPOSIT": 22,
    "LIQUIDITY_POOL_WITHDRAW": 23,
    "INVOKE_HOST_FUNCTION": 24,
    "EXTEND_FOOTPRINT_TTL": 25,
    "RESTORE_FOOTPRINT": 26,
})

# --- operation bodies (classic) ---

CreateAccountOp = xdr_struct("CreateAccountOp", [
    ("destination", AccountID),
    ("startingBalance", Int64),
])

PaymentOp = xdr_struct("PaymentOp", [
    ("destination", MuxedAccount),
    ("asset", Asset),
    ("amount", Int64),
])

PathPaymentStrictReceiveOp = xdr_struct("PathPaymentStrictReceiveOp", [
    ("sendAsset", Asset),
    ("sendMax", Int64),
    ("destination", MuxedAccount),
    ("destAsset", Asset),
    ("destAmount", Int64),
    ("path", VarArray(Asset, 5)),
])

PathPaymentStrictSendOp = xdr_struct("PathPaymentStrictSendOp", [
    ("sendAsset", Asset),
    ("sendAmount", Int64),
    ("destination", MuxedAccount),
    ("destAsset", Asset),
    ("destMin", Int64),
    ("path", VarArray(Asset, 5)),
])

ManageSellOfferOp = xdr_struct("ManageSellOfferOp", [
    ("selling", Asset),
    ("buying", Asset),
    ("amount", Int64),
    ("price", Price),
    ("offerID", Int64),
])

ManageBuyOfferOp = xdr_struct("ManageBuyOfferOp", [
    ("selling", Asset),
    ("buying", Asset),
    ("buyAmount", Int64),
    ("price", Price),
    ("offerID", Int64),
])

CreatePassiveSellOfferOp = xdr_struct("CreatePassiveSellOfferOp", [
    ("selling", Asset),
    ("buying", Asset),
    ("amount", Int64),
    ("price", Price),
])

SetOptionsOp = xdr_struct("SetOptionsOp", [
    ("inflationDest", Optional(AccountID)),
    ("clearFlags", Optional(Uint32)),
    ("setFlags", Optional(Uint32)),
    ("masterWeight", Optional(Uint32)),
    ("lowThreshold", Optional(Uint32)),
    ("medThreshold", Optional(Uint32)),
    ("highThreshold", Optional(Uint32)),
    ("homeDomain", Optional(String32)),
    ("signer", Optional(Signer)),
], defaults={f: None for f in ("inflationDest", "clearFlags", "setFlags",
                               "masterWeight", "lowThreshold", "medThreshold",
                               "highThreshold", "homeDomain", "signer")})

from .ledger_entries import (AssetType, AlphaNum4, AlphaNum12, OfferEntry,
                             LiquidityPoolConstantProductParameters,
                             LiquidityPoolType)  # noqa: E402

LiquidityPoolParameters = xdr_union("LiquidityPoolParameters", LiquidityPoolType, {
    LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
        ("constantProduct", LiquidityPoolConstantProductParameters),
})

ChangeTrustAsset = xdr_union("ChangeTrustAsset", AssetType, {
    AssetType.ASSET_TYPE_NATIVE: ("native", None),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
    AssetType.ASSET_TYPE_POOL_SHARE: ("liquidityPool", LiquidityPoolParameters),
})

ChangeTrustOp = xdr_struct("ChangeTrustOp", [
    ("line", ChangeTrustAsset),
    ("limit", Int64),
])

AssetCode = xdr_union("AssetCode", AssetType, {
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("assetCode4", AssetCode4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("assetCode12", AssetCode12),
})

AllowTrustOp = xdr_struct("AllowTrustOp", [
    ("trustor", AccountID),
    ("asset", AssetCode),
    ("authorize", Uint32),
])

ManageDataOp = xdr_struct("ManageDataOp", [
    ("dataName", String64),
    ("dataValue", Optional(DataValue)),
])

BumpSequenceOp = xdr_struct("BumpSequenceOp", [
    ("bumpTo", SequenceNumber),
])

CreateClaimableBalanceOp = xdr_struct("CreateClaimableBalanceOp", [
    ("asset", Asset),
    ("amount", Int64),
    ("claimants", VarArray(Claimant, 10)),
])

ClaimClaimableBalanceOp = xdr_struct("ClaimClaimableBalanceOp", [
    ("balanceID", ClaimableBalanceID),
])

BeginSponsoringFutureReservesOp = xdr_struct("BeginSponsoringFutureReservesOp", [
    ("sponsoredID", AccountID),
])

RevokeSponsorshipType = xdr_enum("RevokeSponsorshipType", {
    "REVOKE_SPONSORSHIP_LEDGER_ENTRY": 0,
    "REVOKE_SPONSORSHIP_SIGNER": 1,
})

_RevokeSponsorshipSigner = xdr_struct("RevokeSponsorshipOpSigner", [
    ("accountID", AccountID),
    ("signerKey", SignerKey),
])

RevokeSponsorshipOp = xdr_union("RevokeSponsorshipOp", RevokeSponsorshipType, {
    RevokeSponsorshipType.REVOKE_SPONSORSHIP_LEDGER_ENTRY: ("ledgerKey", LedgerKey),
    RevokeSponsorshipType.REVOKE_SPONSORSHIP_SIGNER: ("signer", _RevokeSponsorshipSigner),
})

ClawbackOp = xdr_struct("ClawbackOp", [
    ("asset", Asset),
    ("from_", MuxedAccount),
    ("amount", Int64),
])

ClawbackClaimableBalanceOp = xdr_struct("ClawbackClaimableBalanceOp", [
    ("balanceID", ClaimableBalanceID),
])

SetTrustLineFlagsOp = xdr_struct("SetTrustLineFlagsOp", [
    ("trustor", AccountID),
    ("asset", Asset),
    ("clearFlags", Uint32),
    ("setFlags", Uint32),
])

LiquidityPoolDepositOp = xdr_struct("LiquidityPoolDepositOp", [
    ("liquidityPoolID", PoolID),
    ("maxAmountA", Int64),
    ("maxAmountB", Int64),
    ("minPrice", Price),
    ("maxPrice", Price),
])

LiquidityPoolWithdrawOp = xdr_struct("LiquidityPoolWithdrawOp", [
    ("liquidityPoolID", PoolID),
    ("amount", Int64),
    ("minAmountA", Int64),
    ("minAmountB", Int64),
])

# Soroban ops.  The wasm HOST is out of scope (SURVEY.md §2.4 capability
# gap — apply yields opNOT_SUPPORTED), but the schema is real: HostFunction,
# SCVal and the auth tree live in contract.py, so Soroban-carrying envelopes
# decode and round-trip byte-exactly.
from .contract import HostFunction, SorobanAuthorizationEntry  # noqa: E402

InvokeHostFunctionOp = xdr_struct("InvokeHostFunctionOp", [
    ("hostFunction", HostFunction),
    ("auth", VarArray(SorobanAuthorizationEntry)),
], defaults={"auth": list})
ExtendFootprintTTLOp = xdr_struct("ExtendFootprintTTLOp", [
    ("ext", ExtensionPoint),
    ("extendTo", Uint32),
])
RestoreFootprintOp = xdr_struct("RestoreFootprintOp", [
    ("ext", ExtensionPoint),
])

OperationBody = xdr_union("OperationBody", OperationType, {
    OperationType.CREATE_ACCOUNT: ("createAccountOp", CreateAccountOp),
    OperationType.PAYMENT: ("paymentOp", PaymentOp),
    OperationType.PATH_PAYMENT_STRICT_RECEIVE:
        ("pathPaymentStrictReceiveOp", PathPaymentStrictReceiveOp),
    OperationType.MANAGE_SELL_OFFER: ("manageSellOfferOp", ManageSellOfferOp),
    OperationType.CREATE_PASSIVE_SELL_OFFER:
        ("createPassiveSellOfferOp", CreatePassiveSellOfferOp),
    OperationType.SET_OPTIONS: ("setOptionsOp", SetOptionsOp),
    OperationType.CHANGE_TRUST: ("changeTrustOp", ChangeTrustOp),
    OperationType.ALLOW_TRUST: ("allowTrustOp", AllowTrustOp),
    OperationType.ACCOUNT_MERGE: ("destination", MuxedAccount),
    OperationType.INFLATION: ("inflation", None),
    OperationType.MANAGE_DATA: ("manageDataOp", ManageDataOp),
    OperationType.BUMP_SEQUENCE: ("bumpSequenceOp", BumpSequenceOp),
    OperationType.MANAGE_BUY_OFFER: ("manageBuyOfferOp", ManageBuyOfferOp),
    OperationType.PATH_PAYMENT_STRICT_SEND:
        ("pathPaymentStrictSendOp", PathPaymentStrictSendOp),
    OperationType.CREATE_CLAIMABLE_BALANCE:
        ("createClaimableBalanceOp", CreateClaimableBalanceOp),
    OperationType.CLAIM_CLAIMABLE_BALANCE:
        ("claimClaimableBalanceOp", ClaimClaimableBalanceOp),
    OperationType.BEGIN_SPONSORING_FUTURE_RESERVES:
        ("beginSponsoringFutureReservesOp", BeginSponsoringFutureReservesOp),
    OperationType.END_SPONSORING_FUTURE_RESERVES:
        ("endSponsoringFutureReserves", None),
    OperationType.REVOKE_SPONSORSHIP: ("revokeSponsorshipOp", RevokeSponsorshipOp),
    OperationType.CLAWBACK: ("clawbackOp", ClawbackOp),
    OperationType.CLAWBACK_CLAIMABLE_BALANCE:
        ("clawbackClaimableBalanceOp", ClawbackClaimableBalanceOp),
    OperationType.SET_TRUST_LINE_FLAGS: ("setTrustLineFlagsOp", SetTrustLineFlagsOp),
    OperationType.LIQUIDITY_POOL_DEPOSIT:
        ("liquidityPoolDepositOp", LiquidityPoolDepositOp),
    OperationType.LIQUIDITY_POOL_WITHDRAW:
        ("liquidityPoolWithdrawOp", LiquidityPoolWithdrawOp),
    OperationType.INVOKE_HOST_FUNCTION: ("invokeHostFunctionOp", InvokeHostFunctionOp),
    OperationType.EXTEND_FOOTPRINT_TTL: ("extendFootprintTTLOp", ExtendFootprintTTLOp),
    OperationType.RESTORE_FOOTPRINT: ("restoreFootprintOp", RestoreFootprintOp),
})

Operation = xdr_struct("Operation", [
    ("sourceAccount", Optional(MuxedAccount)),
    ("body", OperationBody),
], defaults={"sourceAccount": None})

MemoType = xdr_enum("MemoType", {
    "MEMO_NONE": 0,
    "MEMO_TEXT": 1,
    "MEMO_ID": 2,
    "MEMO_HASH": 3,
    "MEMO_RETURN": 4,
})

Memo = xdr_union("Memo", MemoType, {
    MemoType.MEMO_NONE: ("none", None),
    MemoType.MEMO_TEXT: ("text", XdrString(28)),
    MemoType.MEMO_ID: ("id", Uint64),
    MemoType.MEMO_HASH: ("hash", Hash),
    MemoType.MEMO_RETURN: ("retHash", Hash),
})

TimeBounds = xdr_struct("TimeBounds", [
    ("minTime", TimePoint),
    ("maxTime", TimePoint),
])

LedgerBounds = xdr_struct("LedgerBounds", [
    ("minLedger", Uint32),
    ("maxLedger", Uint32),
])

PreconditionsV2 = xdr_struct("PreconditionsV2", [
    ("timeBounds", Optional(TimeBounds)),
    ("ledgerBounds", Optional(LedgerBounds)),
    ("minSeqNum", Optional(SequenceNumber)),
    ("minSeqAge", Duration),
    ("minSeqLedgerGap", Uint32),
    ("extraSigners", VarArray(SignerKey, 2)),
], defaults={"timeBounds": None, "ledgerBounds": None, "minSeqNum": None,
             "minSeqAge": 0, "minSeqLedgerGap": 0, "extraSigners": list})

PreconditionType = xdr_enum("PreconditionType", {
    "PRECOND_NONE": 0,
    "PRECOND_TIME": 1,
    "PRECOND_V2": 2,
})

Preconditions = xdr_union("Preconditions", PreconditionType, {
    PreconditionType.PRECOND_NONE: ("none", None),
    PreconditionType.PRECOND_TIME: ("timeBounds", TimeBounds),
    PreconditionType.PRECOND_V2: ("v2", PreconditionsV2),
})

# Soroban resource declaration (protocol 20+): Transaction.ext v1.
LedgerFootprint = xdr_struct("LedgerFootprint", [
    ("readOnly", VarArray(LedgerKey)),
    ("readWrite", VarArray(LedgerKey)),
], defaults={"readOnly": list, "readWrite": list})

SorobanResources = xdr_struct("SorobanResources", [
    ("footprint", LedgerFootprint),
    ("instructions", Uint32),
    ("readBytes", Uint32),
    ("writeBytes", Uint32),
])

SorobanTransactionData = xdr_struct("SorobanTransactionData", [
    ("ext", ExtensionPoint),
    ("resources", SorobanResources),
    ("resourceFee", Int64),
])

_TxExt = xdr_union("TransactionExt", Int32, {
    0: ("v0", None),
    1: ("sorobanData", SorobanTransactionData),
})
TransactionExt = _TxExt

Transaction = xdr_struct("Transaction", [
    ("sourceAccount", MuxedAccount),
    ("fee", Uint32),
    ("seqNum", SequenceNumber),
    ("cond", Preconditions),
    ("memo", Memo),
    ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
    ("ext", _TxExt),
], defaults={"cond": lambda: Preconditions.none(),
             "memo": lambda: Memo.none(),
             "ext": lambda: _TxExt.v0()})

TransactionV0 = xdr_struct("TransactionV0", [
    ("sourceAccountEd25519", Uint256),
    ("fee", Uint32),
    ("seqNum", SequenceNumber),
    ("timeBounds", Optional(TimeBounds)),
    ("memo", Memo),
    ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
    ("ext", xdr_union("TransactionV0Ext", Int32, {0: ("v0", None)})),
])

TransactionV0Envelope = xdr_struct("TransactionV0Envelope", [
    ("tx", TransactionV0),
    ("signatures", VarArray(DecoratedSignature, 20)),
])

TransactionV1Envelope = xdr_struct("TransactionV1Envelope", [
    ("tx", Transaction),
    ("signatures", VarArray(DecoratedSignature, 20)),
])

EnvelopeType = xdr_enum("EnvelopeType", {
    "ENVELOPE_TYPE_TX_V0": 0,
    "ENVELOPE_TYPE_SCP": 1,
    "ENVELOPE_TYPE_TX": 2,
    "ENVELOPE_TYPE_AUTH": 3,
    "ENVELOPE_TYPE_SCPVALUE": 4,
    "ENVELOPE_TYPE_TX_FEE_BUMP": 5,
    "ENVELOPE_TYPE_OP_ID": 6,
    "ENVELOPE_TYPE_POOL_REVOKE_OP_ID": 7,
    "ENVELOPE_TYPE_CONTRACT_ID": 8,
    "ENVELOPE_TYPE_SOROBAN_AUTHORIZATION": 9,
})

_FeeBumpInnerTx = xdr_union("FeeBumpInnerTx", EnvelopeType, {
    EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope),
})

FeeBumpTransaction = xdr_struct("FeeBumpTransaction", [
    ("feeSource", MuxedAccount),
    ("fee", Int64),
    ("innerTx", _FeeBumpInnerTx),
    ("ext", xdr_union("FeeBumpTransactionExt", Int32, {0: ("v0", None)})),
])

FeeBumpTransactionEnvelope = xdr_struct("FeeBumpTransactionEnvelope", [
    ("tx", FeeBumpTransaction),
    ("signatures", VarArray(DecoratedSignature, 20)),
])

TransactionEnvelope = xdr_union("TransactionEnvelope", EnvelopeType, {
    EnvelopeType.ENVELOPE_TYPE_TX_V0: ("v0", TransactionV0Envelope),
    EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope),
    EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP: ("feeBump", FeeBumpTransactionEnvelope),
})

_TSPTaggedTx = xdr_union("TransactionSignaturePayloadTaggedTransaction", EnvelopeType, {
    EnvelopeType.ENVELOPE_TYPE_TX: ("tx", Transaction),
    EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP: ("feeBump", FeeBumpTransaction),
})

TransactionSignaturePayload = xdr_struct("TransactionSignaturePayload", [
    ("networkId", Hash),
    ("taggedTransaction", _TSPTaggedTx),
])

# --- operation id preimages (for claimable balance ids etc.) ---

_OperationIDId = xdr_struct("OperationIDId", [
    ("sourceAccount", AccountID),
    ("seqNum", SequenceNumber),
    ("opNum", Uint32),
])

HashIDPreimage = xdr_union("HashIDPreimage", EnvelopeType, {
    EnvelopeType.ENVELOPE_TYPE_OP_ID: ("operationID", _OperationIDId),
})

# --- results ---

TransactionResultCode = xdr_enum("TransactionResultCode", {
    "txFEE_BUMP_INNER_SUCCESS": 1,
    "txSUCCESS": 0,
    "txFAILED": -1,
    "txTOO_EARLY": -2,
    "txTOO_LATE": -3,
    "txMISSING_OPERATION": -4,
    "txBAD_SEQ": -5,
    "txBAD_AUTH": -6,
    "txINSUFFICIENT_BALANCE": -7,
    "txNO_ACCOUNT": -8,
    "txINSUFFICIENT_FEE": -9,
    "txBAD_AUTH_EXTRA": -10,
    "txINTERNAL_ERROR": -11,
    "txNOT_SUPPORTED": -12,
    "txFEE_BUMP_INNER_FAILED": -13,
    "txBAD_SPONSORSHIP": -14,
    "txBAD_MIN_SEQ_AGE_OR_GAP": -15,
    "txMALFORMED": -16,
    "txSOROBAN_INVALID": -17,
})

OperationResultCode = xdr_enum("OperationResultCode", {
    "opINNER": 0,
    "opBAD_AUTH": -1,
    "opNO_ACCOUNT": -2,
    "opNOT_SUPPORTED": -3,
    "opTOO_MANY_SUBENTRIES": -4,
    "opEXCEEDED_WORK_LIMIT": -5,
    "opTOO_MANY_SPONSORING": -6,
})


def _simple_result(name: str, codes: dict, success_arms: dict = None):
    """Most op results are enum + void arms (success sometimes carries data)."""
    enum_t = xdr_enum(name + "Code", codes)
    arms = {}
    for cname, cval in codes.items():
        payload = (success_arms or {}).get(cval)
        arms[enum_t(cval)] = (cname, payload)
    return enum_t, xdr_union(name, enum_t, arms, default=("unknown", None))


CreateAccountResultCode, CreateAccountResult = _simple_result(
    "CreateAccountResult", {
        "CREATE_ACCOUNT_SUCCESS": 0,
        "CREATE_ACCOUNT_MALFORMED": -1,
        "CREATE_ACCOUNT_UNDERFUNDED": -2,
        "CREATE_ACCOUNT_LOW_RESERVE": -3,
        "CREATE_ACCOUNT_ALREADY_EXIST": -4,
    })

PaymentResultCode, PaymentResult = _simple_result(
    "PaymentResult", {
        "PAYMENT_SUCCESS": 0,
        "PAYMENT_MALFORMED": -1,
        "PAYMENT_UNDERFUNDED": -2,
        "PAYMENT_SRC_NO_TRUST": -3,
        "PAYMENT_SRC_NOT_AUTHORIZED": -4,
        "PAYMENT_NO_DESTINATION": -5,
        "PAYMENT_NO_TRUST": -6,
        "PAYMENT_NOT_AUTHORIZED": -7,
        "PAYMENT_LINE_FULL": -8,
        "PAYMENT_NO_ISSUER": -9,
    })

# Offer results carry structured success payloads.
ClaimAtomType = xdr_enum("ClaimAtomType", {
    "CLAIM_ATOM_TYPE_V0": 0,
    "CLAIM_ATOM_TYPE_ORDER_BOOK": 1,
    "CLAIM_ATOM_TYPE_LIQUIDITY_POOL": 2,
})

ClaimOfferAtomV0 = xdr_struct("ClaimOfferAtomV0", [
    ("sellerEd25519", Uint256),
    ("offerID", Int64),
    ("assetSold", Asset),
    ("amountSold", Int64),
    ("assetBought", Asset),
    ("amountBought", Int64),
])

ClaimOfferAtom = xdr_struct("ClaimOfferAtom", [
    ("sellerID", AccountID),
    ("offerID", Int64),
    ("assetSold", Asset),
    ("amountSold", Int64),
    ("assetBought", Asset),
    ("amountBought", Int64),
])

ClaimLiquidityAtom = xdr_struct("ClaimLiquidityAtom", [
    ("liquidityPoolID", PoolID),
    ("assetSold", Asset),
    ("amountSold", Int64),
    ("assetBought", Asset),
    ("amountBought", Int64),
])

ClaimAtom = xdr_union("ClaimAtom", ClaimAtomType, {
    ClaimAtomType.CLAIM_ATOM_TYPE_V0: ("v0", ClaimOfferAtomV0),
    ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK: ("orderBook", ClaimOfferAtom),
    ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL: ("liquidityPool", ClaimLiquidityAtom),
})

ManageOfferEffect = xdr_enum("ManageOfferEffect", {
    "MANAGE_OFFER_CREATED": 0,
    "MANAGE_OFFER_UPDATED": 1,
    "MANAGE_OFFER_DELETED": 2,
})

_ManageOfferSuccessOffer = xdr_union("ManageOfferSuccessResultOffer", ManageOfferEffect, {
    ManageOfferEffect.MANAGE_OFFER_CREATED: ("offer", OfferEntry),
    ManageOfferEffect.MANAGE_OFFER_UPDATED: ("offer_updated", OfferEntry),
    ManageOfferEffect.MANAGE_OFFER_DELETED: ("deleted", None),
})

ManageOfferSuccessResult = xdr_struct("ManageOfferSuccessResult", [
    ("offersClaimed", VarArray(ClaimAtom)),
    ("offer", _ManageOfferSuccessOffer),
])

ManageSellOfferResultCode = xdr_enum("ManageSellOfferResultCode", {
    "MANAGE_SELL_OFFER_SUCCESS": 0,
    "MANAGE_SELL_OFFER_MALFORMED": -1,
    "MANAGE_SELL_OFFER_SELL_NO_TRUST": -2,
    "MANAGE_SELL_OFFER_BUY_NO_TRUST": -3,
    "MANAGE_SELL_OFFER_SELL_NOT_AUTHORIZED": -4,
    "MANAGE_SELL_OFFER_BUY_NOT_AUTHORIZED": -5,
    "MANAGE_SELL_OFFER_LINE_FULL": -6,
    "MANAGE_SELL_OFFER_UNDERFUNDED": -7,
    "MANAGE_SELL_OFFER_CROSS_SELF": -8,
    "MANAGE_SELL_OFFER_SELL_NO_ISSUER": -9,
    "MANAGE_SELL_OFFER_BUY_NO_ISSUER": -10,
    "MANAGE_SELL_OFFER_NOT_FOUND": -11,
    "MANAGE_SELL_OFFER_LOW_RESERVE": -12,
})

ManageSellOfferResult = xdr_union("ManageSellOfferResult", ManageSellOfferResultCode, {
    ManageSellOfferResultCode.MANAGE_SELL_OFFER_SUCCESS:
        ("success", ManageOfferSuccessResult),
}, default=("failed", None))

ManageBuyOfferResultCode = xdr_enum("ManageBuyOfferResultCode", {
    "MANAGE_BUY_OFFER_SUCCESS": 0,
    "MANAGE_BUY_OFFER_MALFORMED": -1,
    "MANAGE_BUY_OFFER_SELL_NO_TRUST": -2,
    "MANAGE_BUY_OFFER_BUY_NO_TRUST": -3,
    "MANAGE_BUY_OFFER_SELL_NOT_AUTHORIZED": -4,
    "MANAGE_BUY_OFFER_BUY_NOT_AUTHORIZED": -5,
    "MANAGE_BUY_OFFER_LINE_FULL": -6,
    "MANAGE_BUY_OFFER_UNDERFUNDED": -7,
    "MANAGE_BUY_OFFER_CROSS_SELF": -8,
    "MANAGE_BUY_OFFER_SELL_NO_ISSUER": -9,
    "MANAGE_BUY_OFFER_BUY_NO_ISSUER": -10,
    "MANAGE_BUY_OFFER_NOT_FOUND": -11,
    "MANAGE_BUY_OFFER_LOW_RESERVE": -12,
})

ManageBuyOfferResult = xdr_union("ManageBuyOfferResult", ManageBuyOfferResultCode, {
    ManageBuyOfferResultCode.MANAGE_BUY_OFFER_SUCCESS:
        ("success", ManageOfferSuccessResult),
}, default=("failed", None))

SetOptionsResultCode, SetOptionsResult = _simple_result(
    "SetOptionsResult", {
        "SET_OPTIONS_SUCCESS": 0,
        "SET_OPTIONS_LOW_RESERVE": -1,
        "SET_OPTIONS_TOO_MANY_SIGNERS": -2,
        "SET_OPTIONS_BAD_FLAGS": -3,
        "SET_OPTIONS_INVALID_INFLATION": -4,
        "SET_OPTIONS_CANT_CHANGE": -5,
        "SET_OPTIONS_UNKNOWN_FLAG": -6,
        "SET_OPTIONS_THRESHOLD_OUT_OF_RANGE": -7,
        "SET_OPTIONS_BAD_SIGNER": -8,
        "SET_OPTIONS_INVALID_HOME_DOMAIN": -9,
        "SET_OPTIONS_AUTH_REVOCABLE_REQUIRED": -10,
    })

ChangeTrustResultCode, ChangeTrustResult = _simple_result(
    "ChangeTrustResult", {
        "CHANGE_TRUST_SUCCESS": 0,
        "CHANGE_TRUST_MALFORMED": -1,
        "CHANGE_TRUST_NO_ISSUER": -2,
        "CHANGE_TRUST_INVALID_LIMIT": -3,
        "CHANGE_TRUST_LOW_RESERVE": -4,
        "CHANGE_TRUST_SELF_NOT_ALLOWED": -5,
        "CHANGE_TRUST_TRUST_LINE_MISSING": -6,
        "CHANGE_TRUST_CANNOT_DELETE": -7,
        "CHANGE_TRUST_NOT_AUTH_MAINTAIN_LIABILITIES": -8,
    })

AllowTrustResultCode, AllowTrustResult = _simple_result(
    "AllowTrustResult", {
        "ALLOW_TRUST_SUCCESS": 0,
        "ALLOW_TRUST_MALFORMED": -1,
        "ALLOW_TRUST_NO_TRUST_LINE": -2,
        "ALLOW_TRUST_TRUST_NOT_REQUIRED": -3,
        "ALLOW_TRUST_CANT_REVOKE": -4,
        "ALLOW_TRUST_SELF_NOT_ALLOWED": -5,
        "ALLOW_TRUST_LOW_RESERVE": -6,
    })

AccountMergeResultCode = xdr_enum("AccountMergeResultCode", {
    "ACCOUNT_MERGE_SUCCESS": 0,
    "ACCOUNT_MERGE_MALFORMED": -1,
    "ACCOUNT_MERGE_NO_ACCOUNT": -2,
    "ACCOUNT_MERGE_IMMUTABLE_SET": -3,
    "ACCOUNT_MERGE_HAS_SUB_ENTRIES": -4,
    "ACCOUNT_MERGE_SEQNUM_TOO_FAR": -5,
    "ACCOUNT_MERGE_DEST_FULL": -6,
    "ACCOUNT_MERGE_IS_SPONSOR": -7,
})

AccountMergeResult = xdr_union("AccountMergeResult", AccountMergeResultCode, {
    AccountMergeResultCode.ACCOUNT_MERGE_SUCCESS: ("sourceAccountBalance", Int64),
}, default=("failed", None))

InflationPayout = xdr_struct("InflationPayout", [
    ("destination", AccountID),
    ("amount", Int64),
])

InflationResultCode = xdr_enum("InflationResultCode", {
    "INFLATION_SUCCESS": 0,
    "INFLATION_NOT_TIME": -1,
})

InflationResult = xdr_union("InflationResult", InflationResultCode, {
    InflationResultCode.INFLATION_SUCCESS: ("payouts", VarArray(InflationPayout)),
}, default=("failed", None))

ManageDataResultCode, ManageDataResult = _simple_result(
    "ManageDataResult", {
        "MANAGE_DATA_SUCCESS": 0,
        "MANAGE_DATA_NOT_SUPPORTED_YET": -1,
        "MANAGE_DATA_NAME_NOT_FOUND": -2,
        "MANAGE_DATA_LOW_RESERVE": -3,
        "MANAGE_DATA_INVALID_NAME": -4,
    })

BumpSequenceResultCode, BumpSequenceResult = _simple_result(
    "BumpSequenceResult", {
        "BUMP_SEQUENCE_SUCCESS": 0,
        "BUMP_SEQUENCE_BAD_SEQ": -1,
    })

PathPaymentStrictReceiveResultCode = xdr_enum("PathPaymentStrictReceiveResultCode", {
    "PATH_PAYMENT_STRICT_RECEIVE_SUCCESS": 0,
    "PATH_PAYMENT_STRICT_RECEIVE_MALFORMED": -1,
    "PATH_PAYMENT_STRICT_RECEIVE_UNDERFUNDED": -2,
    "PATH_PAYMENT_STRICT_RECEIVE_SRC_NO_TRUST": -3,
    "PATH_PAYMENT_STRICT_RECEIVE_SRC_NOT_AUTHORIZED": -4,
    "PATH_PAYMENT_STRICT_RECEIVE_NO_DESTINATION": -5,
    "PATH_PAYMENT_STRICT_RECEIVE_NO_TRUST": -6,
    "PATH_PAYMENT_STRICT_RECEIVE_NOT_AUTHORIZED": -7,
    "PATH_PAYMENT_STRICT_RECEIVE_LINE_FULL": -8,
    "PATH_PAYMENT_STRICT_RECEIVE_NO_ISSUER": -9,
    "PATH_PAYMENT_STRICT_RECEIVE_TOO_FEW_OFFERS": -10,
    "PATH_PAYMENT_STRICT_RECEIVE_OFFER_CROSS_SELF": -11,
    "PATH_PAYMENT_STRICT_RECEIVE_OVER_SENDMAX": -12,
})

SimplePaymentResult = xdr_struct("SimplePaymentResult", [
    ("destination", AccountID),
    ("asset", Asset),
    ("amount", Int64),
])

_PPSRSuccess = xdr_struct("PathPaymentStrictReceiveResultSuccess", [
    ("offers", VarArray(ClaimAtom)),
    ("last", SimplePaymentResult),
])

PathPaymentStrictReceiveResult = xdr_union(
    "PathPaymentStrictReceiveResult", PathPaymentStrictReceiveResultCode, {
        PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_SUCCESS:
            ("success", _PPSRSuccess),
        PathPaymentStrictReceiveResultCode.PATH_PAYMENT_STRICT_RECEIVE_NO_ISSUER:
            ("noIssuer", Asset),
    }, default=("failed", None))

PathPaymentStrictSendResultCode = xdr_enum("PathPaymentStrictSendResultCode", {
    "PATH_PAYMENT_STRICT_SEND_SUCCESS": 0,
    "PATH_PAYMENT_STRICT_SEND_MALFORMED": -1,
    "PATH_PAYMENT_STRICT_SEND_UNDERFUNDED": -2,
    "PATH_PAYMENT_STRICT_SEND_SRC_NO_TRUST": -3,
    "PATH_PAYMENT_STRICT_SEND_SRC_NOT_AUTHORIZED": -4,
    "PATH_PAYMENT_STRICT_SEND_NO_DESTINATION": -5,
    "PATH_PAYMENT_STRICT_SEND_NO_TRUST": -6,
    "PATH_PAYMENT_STRICT_SEND_NOT_AUTHORIZED": -7,
    "PATH_PAYMENT_STRICT_SEND_LINE_FULL": -8,
    "PATH_PAYMENT_STRICT_SEND_NO_ISSUER": -9,
    "PATH_PAYMENT_STRICT_SEND_TOO_FEW_OFFERS": -10,
    "PATH_PAYMENT_STRICT_SEND_OFFER_CROSS_SELF": -11,
    "PATH_PAYMENT_STRICT_SEND_UNDER_DESTMIN": -12,
})

_PPSSSuccess = xdr_struct("PathPaymentStrictSendResultSuccess", [
    ("offers", VarArray(ClaimAtom)),
    ("last", SimplePaymentResult),
])

PathPaymentStrictSendResult = xdr_union(
    "PathPaymentStrictSendResult", PathPaymentStrictSendResultCode, {
        PathPaymentStrictSendResultCode.PATH_PAYMENT_STRICT_SEND_SUCCESS:
            ("success", _PPSSSuccess),
        PathPaymentStrictSendResultCode.PATH_PAYMENT_STRICT_SEND_NO_ISSUER:
            ("noIssuer", Asset),
    }, default=("failed", None))

CreateClaimableBalanceResultCode = xdr_enum("CreateClaimableBalanceResultCode", {
    "CREATE_CLAIMABLE_BALANCE_SUCCESS": 0,
    "CREATE_CLAIMABLE_BALANCE_MALFORMED": -1,
    "CREATE_CLAIMABLE_BALANCE_LOW_RESERVE": -2,
    "CREATE_CLAIMABLE_BALANCE_NO_TRUST": -3,
    "CREATE_CLAIMABLE_BALANCE_NOT_AUTHORIZED": -4,
    "CREATE_CLAIMABLE_BALANCE_UNDERFUNDED": -5,
})

CreateClaimableBalanceResult = xdr_union(
    "CreateClaimableBalanceResult", CreateClaimableBalanceResultCode, {
        CreateClaimableBalanceResultCode.CREATE_CLAIMABLE_BALANCE_SUCCESS:
            ("balanceID", ClaimableBalanceID),
    }, default=("failed", None))

ClaimClaimableBalanceResultCode, ClaimClaimableBalanceResult = _simple_result(
    "ClaimClaimableBalanceResult", {
        "CLAIM_CLAIMABLE_BALANCE_SUCCESS": 0,
        "CLAIM_CLAIMABLE_BALANCE_DOES_NOT_EXIST": -1,
        "CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM": -2,
        "CLAIM_CLAIMABLE_BALANCE_LINE_FULL": -3,
        "CLAIM_CLAIMABLE_BALANCE_NO_TRUST": -4,
        "CLAIM_CLAIMABLE_BALANCE_NOT_AUTHORIZED": -5,
    })

BeginSponsoringFutureReservesResultCode, BeginSponsoringFutureReservesResult = \
    _simple_result("BeginSponsoringFutureReservesResult", {
        "BEGIN_SPONSORING_FUTURE_RESERVES_SUCCESS": 0,
        "BEGIN_SPONSORING_FUTURE_RESERVES_MALFORMED": -1,
        "BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED": -2,
        "BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE": -3,
    })

EndSponsoringFutureReservesResultCode, EndSponsoringFutureReservesResult = \
    _simple_result("EndSponsoringFutureReservesResult", {
        "END_SPONSORING_FUTURE_RESERVES_SUCCESS": 0,
        "END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED": -1,
    })

RevokeSponsorshipResultCode, RevokeSponsorshipResult = _simple_result(
    "RevokeSponsorshipResult", {
        "REVOKE_SPONSORSHIP_SUCCESS": 0,
        "REVOKE_SPONSORSHIP_DOES_NOT_EXIST": -1,
        "REVOKE_SPONSORSHIP_NOT_SPONSOR": -2,
        "REVOKE_SPONSORSHIP_LOW_RESERVE": -3,
        "REVOKE_SPONSORSHIP_ONLY_TRANSFERABLE": -4,
        "REVOKE_SPONSORSHIP_MALFORMED": -5,
    })

ClawbackResultCode, ClawbackResult = _simple_result(
    "ClawbackResult", {
        "CLAWBACK_SUCCESS": 0,
        "CLAWBACK_MALFORMED": -1,
        "CLAWBACK_NOT_CLAWBACK_ENABLED": -2,
        "CLAWBACK_NO_TRUST": -3,
        "CLAWBACK_UNDERFUNDED": -4,
    })

ClawbackClaimableBalanceResultCode, ClawbackClaimableBalanceResult = _simple_result(
    "ClawbackClaimableBalanceResult", {
        "CLAWBACK_CLAIMABLE_BALANCE_SUCCESS": 0,
        "CLAWBACK_CLAIMABLE_BALANCE_DOES_NOT_EXIST": -1,
        "CLAWBACK_CLAIMABLE_BALANCE_NOT_ISSUER": -2,
        "CLAWBACK_CLAIMABLE_BALANCE_NOT_CLAWBACK_ENABLED": -3,
    })

SetTrustLineFlagsResultCode, SetTrustLineFlagsResult = _simple_result(
    "SetTrustLineFlagsResult", {
        "SET_TRUST_LINE_FLAGS_SUCCESS": 0,
        "SET_TRUST_LINE_FLAGS_MALFORMED": -1,
        "SET_TRUST_LINE_FLAGS_NO_TRUST_LINE": -2,
        "SET_TRUST_LINE_FLAGS_CANT_REVOKE": -3,
        "SET_TRUST_LINE_FLAGS_INVALID_STATE": -4,
        "SET_TRUST_LINE_FLAGS_LOW_RESERVE": -5,
    })

LiquidityPoolDepositResultCode, LiquidityPoolDepositResult = _simple_result(
    "LiquidityPoolDepositResult", {
        "LIQUIDITY_POOL_DEPOSIT_SUCCESS": 0,
        "LIQUIDITY_POOL_DEPOSIT_MALFORMED": -1,
        "LIQUIDITY_POOL_DEPOSIT_NO_TRUST": -2,
        "LIQUIDITY_POOL_DEPOSIT_NOT_AUTHORIZED": -3,
        "LIQUIDITY_POOL_DEPOSIT_UNDERFUNDED": -4,
        "LIQUIDITY_POOL_DEPOSIT_LINE_FULL": -5,
        "LIQUIDITY_POOL_DEPOSIT_BAD_PRICE": -6,
        "LIQUIDITY_POOL_DEPOSIT_POOL_FULL": -7,
    })

LiquidityPoolWithdrawResultCode, LiquidityPoolWithdrawResult = _simple_result(
    "LiquidityPoolWithdrawResult", {
        "LIQUIDITY_POOL_WITHDRAW_SUCCESS": 0,
        "LIQUIDITY_POOL_WITHDRAW_MALFORMED": -1,
        "LIQUIDITY_POOL_WITHDRAW_NO_TRUST": -2,
        "LIQUIDITY_POOL_WITHDRAW_UNDERFUNDED": -3,
        "LIQUIDITY_POOL_WITHDRAW_LINE_FULL": -4,
        "LIQUIDITY_POOL_WITHDRAW_UNDER_MINIMUM": -5,
    })

InvokeHostFunctionResultCode, InvokeHostFunctionResult = _simple_result(
    "InvokeHostFunctionResult", {
        "INVOKE_HOST_FUNCTION_SUCCESS": 0,
        "INVOKE_HOST_FUNCTION_MALFORMED": -1,
        "INVOKE_HOST_FUNCTION_TRAPPED": -2,
        "INVOKE_HOST_FUNCTION_RESOURCE_LIMIT_EXCEEDED": -3,
        "INVOKE_HOST_FUNCTION_ENTRY_ARCHIVED": -4,
        "INVOKE_HOST_FUNCTION_INSUFFICIENT_REFUNDABLE_FEE": -5,
    }, success_arms={0: Hash})

ExtendFootprintTTLResultCode, ExtendFootprintTTLResult = _simple_result(
    "ExtendFootprintTTLResult", {
        "EXTEND_FOOTPRINT_TTL_SUCCESS": 0,
        "EXTEND_FOOTPRINT_TTL_MALFORMED": -1,
        "EXTEND_FOOTPRINT_TTL_RESOURCE_LIMIT_EXCEEDED": -2,
        "EXTEND_FOOTPRINT_TTL_INSUFFICIENT_REFUNDABLE_FEE": -3,
    })

RestoreFootprintResultCode, RestoreFootprintResult = _simple_result(
    "RestoreFootprintResult", {
        "RESTORE_FOOTPRINT_SUCCESS": 0,
        "RESTORE_FOOTPRINT_MALFORMED": -1,
        "RESTORE_FOOTPRINT_RESOURCE_LIMIT_EXCEEDED": -2,
        "RESTORE_FOOTPRINT_INSUFFICIENT_REFUNDABLE_FEE": -3,
    })

_OpResultTr = xdr_union("OperationResultTr", OperationType, {
    OperationType.CREATE_ACCOUNT: ("createAccountResult", CreateAccountResult),
    OperationType.PAYMENT: ("paymentResult", PaymentResult),
    OperationType.PATH_PAYMENT_STRICT_RECEIVE:
        ("pathPaymentStrictReceiveResult", PathPaymentStrictReceiveResult),
    OperationType.MANAGE_SELL_OFFER: ("manageSellOfferResult", ManageSellOfferResult),
    OperationType.CREATE_PASSIVE_SELL_OFFER:
        ("createPassiveSellOfferResult", ManageSellOfferResult),
    OperationType.SET_OPTIONS: ("setOptionsResult", SetOptionsResult),
    OperationType.CHANGE_TRUST: ("changeTrustResult", ChangeTrustResult),
    OperationType.ALLOW_TRUST: ("allowTrustResult", AllowTrustResult),
    OperationType.ACCOUNT_MERGE: ("accountMergeResult", AccountMergeResult),
    OperationType.INFLATION: ("inflationResult", InflationResult),
    OperationType.MANAGE_DATA: ("manageDataResult", ManageDataResult),
    OperationType.BUMP_SEQUENCE: ("bumpSeqResult", BumpSequenceResult),
    OperationType.MANAGE_BUY_OFFER: ("manageBuyOfferResult", ManageBuyOfferResult),
    OperationType.PATH_PAYMENT_STRICT_SEND:
        ("pathPaymentStrictSendResult", PathPaymentStrictSendResult),
    OperationType.CREATE_CLAIMABLE_BALANCE:
        ("createClaimableBalanceResult", CreateClaimableBalanceResult),
    OperationType.CLAIM_CLAIMABLE_BALANCE:
        ("claimClaimableBalanceResult", ClaimClaimableBalanceResult),
    OperationType.BEGIN_SPONSORING_FUTURE_RESERVES:
        ("beginSponsoringFutureReservesResult", BeginSponsoringFutureReservesResult),
    OperationType.END_SPONSORING_FUTURE_RESERVES:
        ("endSponsoringFutureReservesResult", EndSponsoringFutureReservesResult),
    OperationType.REVOKE_SPONSORSHIP:
        ("revokeSponsorshipResult", RevokeSponsorshipResult),
    OperationType.CLAWBACK: ("clawbackResult", ClawbackResult),
    OperationType.CLAWBACK_CLAIMABLE_BALANCE:
        ("clawbackClaimableBalanceResult", ClawbackClaimableBalanceResult),
    OperationType.SET_TRUST_LINE_FLAGS:
        ("setTrustLineFlagsResult", SetTrustLineFlagsResult),
    OperationType.LIQUIDITY_POOL_DEPOSIT:
        ("liquidityPoolDepositResult", LiquidityPoolDepositResult),
    OperationType.LIQUIDITY_POOL_WITHDRAW:
        ("liquidityPoolWithdrawResult", LiquidityPoolWithdrawResult),
    OperationType.INVOKE_HOST_FUNCTION:
        ("invokeHostFunctionResult", InvokeHostFunctionResult),
    OperationType.EXTEND_FOOTPRINT_TTL:
        ("extendFootprintTTLResult", ExtendFootprintTTLResult),
    OperationType.RESTORE_FOOTPRINT:
        ("restoreFootprintResult", RestoreFootprintResult),
})

OperationResultTr = _OpResultTr

OperationResult = xdr_union("OperationResult", OperationResultCode, {
    OperationResultCode.opINNER: ("tr", _OpResultTr),
}, default=("failed", None))

_InnerTransactionResultResult = xdr_union(
    "InnerTransactionResultResult", TransactionResultCode, {
        TransactionResultCode.txSUCCESS: ("results", VarArray(OperationResult)),
        TransactionResultCode.txFAILED: ("results_failed", VarArray(OperationResult)),
    }, default=("void", None))

InnerTransactionResultExt = xdr_union("InnerTransactionResultExt", Int32,
                                      {0: ("v0", None)})

InnerTransactionResult = xdr_struct("InnerTransactionResult", [
    ("feeCharged", Int64),
    ("result", _InnerTransactionResultResult),
    ("ext", InnerTransactionResultExt),
], defaults={"ext": lambda: InnerTransactionResultExt.v0()})

InnerTransactionResultPair = xdr_struct("InnerTransactionResultPair", [
    ("transactionHash", Hash),
    ("result", InnerTransactionResult),
])

TransactionResultResult = xdr_union(
    "TransactionResultResult", TransactionResultCode, {
        TransactionResultCode.txFEE_BUMP_INNER_SUCCESS:
            ("innerResultPair", InnerTransactionResultPair),
        TransactionResultCode.txFEE_BUMP_INNER_FAILED:
            ("innerResultPair_failed", InnerTransactionResultPair),
        TransactionResultCode.txSUCCESS: ("results", VarArray(OperationResult)),
        TransactionResultCode.txFAILED: ("results_failed", VarArray(OperationResult)),
    }, default=("void", None))

TransactionResultExt = xdr_union("TransactionResultExt", Int32, {0: ("v0", None)})

TransactionResult = xdr_struct("TransactionResult", [
    ("feeCharged", Int64),
    ("result", TransactionResultResult),
    ("ext", TransactionResultExt),
], defaults={"ext": lambda: TransactionResultExt.v0()})

TransactionResultPair = xdr_struct("TransactionResultPair", [
    ("transactionHash", Hash),
    ("result", TransactionResult),
])


# public aliases (used by the transaction frames)
TransactionSignaturePayloadTaggedTransaction = _TSPTaggedTx
InnerTransactionResultResult = _InnerTransactionResultResult
FeeBumpInnerTx = _FeeBumpInnerTx
ManageOfferSuccessResultOffer = _ManageOfferSuccessOffer
PathPaymentStrictReceiveResultSuccess = _PPSRSuccess
PathPaymentStrictSendResultSuccess = _PPSSSuccess
OperationIDId = _OperationIDId
RevokeSponsorshipOpSigner = _RevokeSponsorshipSigner
