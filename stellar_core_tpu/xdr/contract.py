"""Stellar-contract.x equivalents: the Soroban value and host-function
type system.

Reference: src/protocol-curr/xdr/Stellar-contract.x (SCVal and friends) +
the InvokeHostFunctionOp half of Stellar-transaction.x.  The wasm HOST is
out of scope (SURVEY.md §2.4 — no Rust toolchain; ops apply as
opNOT_SUPPORTED), but the SCHEMA is first-class: network envelopes and
ledger entries carrying Soroban payloads decode, round-trip byte-exactly
and content-address correctly, which is what catchup/history fidelity
needs even with a stubbed host.

SCVal is recursive (vectors/maps of SCVal); like SCPQuorumSet the knots are
tied with forward-reference adapters resolved after declaration.
"""

from .codec import (Bool, Int32, Int64, Uint32, Uint64, VarArray,
                    VarOpaque, XdrString, XdrType, xdr_enum, xdr_struct,
                    xdr_union)
from .codec import Optional as XOptional
from .types import AccountID, Hash, Uint256

# -- error values -----------------------------------------------------------

SCErrorType = xdr_enum("SCErrorType", {
    "SCE_CONTRACT": 0,
    "SCE_WASM_VM": 1,
    "SCE_CONTEXT": 2,
    "SCE_STORAGE": 3,
    "SCE_OBJECT": 4,
    "SCE_CRYPTO": 5,
    "SCE_EVENTS": 6,
    "SCE_BUDGET": 7,
    "SCE_VALUE": 8,
    "SCE_AUTH": 9,
})

SCErrorCode = xdr_enum("SCErrorCode", {
    "SCEC_ARITH_DOMAIN": 0,
    "SCEC_INDEX_BOUNDS": 1,
    "SCEC_INVALID_INPUT": 2,
    "SCEC_MISSING_VALUE": 3,
    "SCEC_EXISTING_VALUE": 4,
    "SCEC_EXCEEDED_LIMIT": 5,
    "SCEC_INVALID_ACTION": 6,
    "SCEC_INTERNAL_ERROR": 7,
    "SCEC_UNEXPECTED_TYPE": 8,
    "SCEC_UNEXPECTED_SIZE": 9,
})

# Upstream Stellar-contract.x: only SCE_CONTRACT carries contractCode and
# only SCE_VALUE / SCE_AUTH carry an SCErrorCode; the remaining arms are
# void.  Distinct arm names per void arm — the union machinery installs one
# constructor per name, so sharing "void" would pin it to the first arm.
SCError = xdr_union("SCError", SCErrorType, {
    SCErrorType.SCE_CONTRACT: ("contractCode", Uint32),
    SCErrorType.SCE_WASM_VM: ("wasmVm", None),
    SCErrorType.SCE_CONTEXT: ("context", None),
    SCErrorType.SCE_STORAGE: ("storage", None),
    SCErrorType.SCE_OBJECT: ("object", None),
    SCErrorType.SCE_CRYPTO: ("crypto", None),
    SCErrorType.SCE_EVENTS: ("events", None),
    SCErrorType.SCE_BUDGET: ("budget", None),
    SCErrorType.SCE_VALUE: ("code", SCErrorCode),
    SCErrorType.SCE_AUTH: ("code", SCErrorCode),
})

# -- multi-word integers ----------------------------------------------------

UInt128Parts = xdr_struct("UInt128Parts", [
    ("hi", Uint64), ("lo", Uint64)])

Int128Parts = xdr_struct("Int128Parts", [
    ("hi", Int64), ("lo", Uint64)])

UInt256Parts = xdr_struct("UInt256Parts", [
    ("hi_hi", Uint64), ("hi_lo", Uint64),
    ("lo_hi", Uint64), ("lo_lo", Uint64)])

Int256Parts = xdr_struct("Int256Parts", [
    ("hi_hi", Int64), ("hi_lo", Uint64),
    ("lo_hi", Uint64), ("lo_lo", Uint64)])

# -- addresses --------------------------------------------------------------

SCAddressType = xdr_enum("SCAddressType", {
    "SC_ADDRESS_TYPE_ACCOUNT": 0,
    "SC_ADDRESS_TYPE_CONTRACT": 1,
})

SCAddress = xdr_union("SCAddress", SCAddressType, {
    SCAddressType.SC_ADDRESS_TYPE_ACCOUNT: ("accountId", AccountID),
    SCAddressType.SC_ADDRESS_TYPE_CONTRACT: ("contractId", Hash),
})

# -- leaf payloads ----------------------------------------------------------

SCSYMBOL_LIMIT = 32
SCBytes = VarOpaque()
SCString = XdrString()
SCSymbol = XdrString(SCSYMBOL_LIMIT)

SCNonceKey = xdr_struct("SCNonceKey", [("nonce", Int64)])

ContractExecutableType = xdr_enum("ContractExecutableType", {
    "CONTRACT_EXECUTABLE_WASM": 0,
    "CONTRACT_EXECUTABLE_STELLAR_ASSET": 1,
})

ContractExecutable = xdr_union(
    "ContractExecutable", ContractExecutableType, {
        ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
            ("wasm_hash", Hash),
        ContractExecutableType.CONTRACT_EXECUTABLE_STELLAR_ASSET:
            ("void", None),
    })

# -- the recursive SCVal ----------------------------------------------------

SCValType = xdr_enum("SCValType", {
    "SCV_BOOL": 0,
    "SCV_VOID": 1,
    "SCV_ERROR": 2,
    "SCV_U32": 3,
    "SCV_I32": 4,
    "SCV_U64": 5,
    "SCV_I64": 6,
    "SCV_TIMEPOINT": 7,
    "SCV_DURATION": 8,
    "SCV_U128": 9,
    "SCV_I128": 10,
    "SCV_U256": 11,
    "SCV_I256": 12,
    "SCV_BYTES": 13,
    "SCV_STRING": 14,
    "SCV_SYMBOL": 15,
    "SCV_VEC": 16,
    "SCV_MAP": 17,
    "SCV_ADDRESS": 18,
    "SCV_CONTRACT_INSTANCE": 19,
    "SCV_LEDGER_KEY_CONTRACT_INSTANCE": 20,
    "SCV_LEDGER_KEY_NONCE": 21,
})


class _SCValFwd(XdrType):
    """Forward reference breaking the SCVal ↔ SCVec/SCMap cycle (same
    pattern as the SCPQuorumSet knot in scp.py)."""
    _target = None

    def pack_into(self, val, out):
        self._target.pack_into(val, out)

    def unpack_from(self, buf, off):
        return self._target.unpack_from(buf, off)


_scval_fwd = _SCValFwd()

SCVec = XOptional(VarArray(_scval_fwd))        # SCVal vector, nullable Vec*
SCMapEntry = xdr_struct("SCMapEntry", [
    ("key", _scval_fwd), ("val", _scval_fwd)])
SCMap = XOptional(VarArray(SCMapEntry))

SCContractInstance = xdr_struct("SCContractInstance", [
    ("executable", ContractExecutable),
    ("storage", SCMap),
], defaults={"storage": None})

SCVal = xdr_union("SCVal", SCValType, {
    SCValType.SCV_BOOL: ("b", Bool),
    SCValType.SCV_VOID: ("void", None),
    SCValType.SCV_ERROR: ("error", SCError),
    SCValType.SCV_U32: ("u32", Uint32),
    SCValType.SCV_I32: ("i32", Int32),
    SCValType.SCV_U64: ("u64", Uint64),
    SCValType.SCV_I64: ("i64", Int64),
    SCValType.SCV_TIMEPOINT: ("timepoint", Uint64),
    SCValType.SCV_DURATION: ("duration", Uint64),
    SCValType.SCV_U128: ("u128", UInt128Parts),
    SCValType.SCV_I128: ("i128", Int128Parts),
    SCValType.SCV_U256: ("u256", UInt256Parts),
    SCValType.SCV_I256: ("i256", Int256Parts),
    SCValType.SCV_BYTES: ("bytes", SCBytes),
    SCValType.SCV_STRING: ("str", SCString),
    SCValType.SCV_SYMBOL: ("sym", SCSymbol),
    SCValType.SCV_VEC: ("vec", SCVec),
    SCValType.SCV_MAP: ("map", SCMap),
    SCValType.SCV_ADDRESS: ("address", SCAddress),
    SCValType.SCV_CONTRACT_INSTANCE: ("instance", SCContractInstance),
    SCValType.SCV_LEDGER_KEY_CONTRACT_INSTANCE:
        ("ledger_key_contract_instance", None),
    SCValType.SCV_LEDGER_KEY_NONCE: ("nonce_key", SCNonceKey),
})

_SCValFwd._target = SCVal._xdr_adapter()

# -- host functions (Stellar-transaction.x Soroban half) --------------------

ContractIDPreimageType = xdr_enum("ContractIDPreimageType", {
    "CONTRACT_ID_PREIMAGE_FROM_ADDRESS": 0,
    "CONTRACT_ID_PREIMAGE_FROM_ASSET": 1,
})


class _AssetFwd(XdrType):
    """Asset lives in ledger_entries, which imports this module for
    SCVal/SCAddress — ledger_entries ties this knot after defining Asset."""
    _target = None

    def pack_into(self, val, out):
        self._target.pack_into(val, out)

    def unpack_from(self, buf, off):
        return self._target.unpack_from(buf, off)


_asset_fwd = _AssetFwd()

ContractIDPreimage = xdr_union("ContractIDPreimage", ContractIDPreimageType, {
    ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS:
        ("fromAddress", xdr_struct("ContractIDPreimageFromAddress", [
            ("address", SCAddress),
            ("salt", Uint256)])),
    ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET:
        ("fromAsset", _asset_fwd),
})

CreateContractArgs = xdr_struct("CreateContractArgs", [
    ("contractIDPreimage", ContractIDPreimage),
    ("executable", ContractExecutable),
])

CreateContractArgsV2 = xdr_struct("CreateContractArgsV2", [
    ("contractIDPreimage", ContractIDPreimage),
    ("executable", ContractExecutable),
    ("constructorArgs", VarArray(SCVal)),
], defaults={"constructorArgs": list})

InvokeContractArgs = xdr_struct("InvokeContractArgs", [
    ("contractAddress", SCAddress),
    ("functionName", SCSymbol),
    ("args", VarArray(SCVal)),
], defaults={"args": list})

HostFunctionType = xdr_enum("HostFunctionType", {
    "HOST_FUNCTION_TYPE_INVOKE_CONTRACT": 0,
    "HOST_FUNCTION_TYPE_CREATE_CONTRACT": 1,
    "HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM": 2,
    "HOST_FUNCTION_TYPE_CREATE_CONTRACT_V2": 3,
})

HostFunction = xdr_union("HostFunction", HostFunctionType, {
    HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT:
        ("invokeContract", InvokeContractArgs),
    HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT:
        ("createContract", CreateContractArgs),
    HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM:
        ("wasm", VarOpaque()),
    HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT_V2:
        ("createContractV2", CreateContractArgsV2),
})

# -- authorization ----------------------------------------------------------

SorobanAuthorizedFunctionType = xdr_enum("SorobanAuthorizedFunctionType", {
    "SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN": 0,
    "SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN": 1,
    "SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_V2_HOST_FN": 2,
})

SorobanAuthorizedFunction = xdr_union(
    "SorobanAuthorizedFunction", SorobanAuthorizedFunctionType, {
        SorobanAuthorizedFunctionType
        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN:
            ("contractFn", InvokeContractArgs),
        SorobanAuthorizedFunctionType
        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN:
            ("createContractHostFn", CreateContractArgs),
        SorobanAuthorizedFunctionType
        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_V2_HOST_FN:
            ("createContractV2HostFn", CreateContractArgsV2),
    })


class _AuthInvocationFwd(XdrType):
    _target = None

    def pack_into(self, val, out):
        self._target.pack_into(val, out)

    def unpack_from(self, buf, off):
        return self._target.unpack_from(buf, off)


_auth_inv_fwd = _AuthInvocationFwd()

SorobanAuthorizedInvocation = xdr_struct("SorobanAuthorizedInvocation", [
    ("function", SorobanAuthorizedFunction),
    ("subInvocations", VarArray(_auth_inv_fwd)),
], defaults={"subInvocations": list})

_AuthInvocationFwd._target = SorobanAuthorizedInvocation._xdr_adapter()

SorobanCredentialsType = xdr_enum("SorobanCredentialsType", {
    "SOROBAN_CREDENTIALS_SOURCE_ACCOUNT": 0,
    "SOROBAN_CREDENTIALS_ADDRESS": 1,
})

SorobanAddressCredentials = xdr_struct("SorobanAddressCredentials", [
    ("address", SCAddress),
    ("nonce", Int64),
    ("signatureExpirationLedger", Uint32),
    ("signature", SCVal),
])

SorobanCredentials = xdr_union(
    "SorobanCredentials", SorobanCredentialsType, {
        SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT:
            ("void", None),
        SorobanCredentialsType.SOROBAN_CREDENTIALS_ADDRESS:
            ("address", SorobanAddressCredentials),
    })

SorobanAuthorizationEntry = xdr_struct("SorobanAuthorizationEntry", [
    ("credentials", SorobanCredentials),
    ("rootInvocation", SorobanAuthorizedInvocation),
])
