"""Stellar-overlay.x equivalents (reference: src/protocol-curr/xdr/
Stellar-overlay.x) — the P2P wire protocol: HELLO/AUTH handshake types,
flood adverts/demands, item fetch, flow control and the authenticated
message envelope."""

from .codec import (Int32, Opaque, Uint32, Uint64, VarArray, VarOpaque,
                    XdrString, xdr_enum, xdr_struct, xdr_union)
from .types import Hash, NodeID, Signature, Uint256

ErrorCode = xdr_enum("ErrorCode", {
    "ERR_MISC": 0,
    "ERR_DATA": 1,
    "ERR_CONF": 2,
    "ERR_AUTH": 3,
    "ERR_LOAD": 4,
})

Error = xdr_struct("Error", [
    ("code", ErrorCode),
    ("msg", XdrString(100)),
])

Curve25519Public = xdr_struct("Curve25519Public", [
    ("key", Opaque(32)),
])

HmacSha256Mac = xdr_struct("HmacSha256Mac", [
    ("mac", Opaque(32)),
])

AuthCert = xdr_struct("AuthCert", [
    ("pubkey", Curve25519Public),
    ("expiration", Uint64),
    ("sig", Signature),
])

Hello = xdr_struct("Hello", [
    ("ledgerVersion", Uint32),
    ("overlayVersion", Uint32),
    ("overlayMinVersion", Uint32),
    ("networkID", Hash),
    ("versionStr", XdrString(100)),
    ("listeningPort", Int32),
    ("peerID", NodeID),
    ("cert", AuthCert),
    ("nonce", Uint256),
])

# AUTH_MSG_FLAG_FLOW_CONTROL_BYTES_REQUESTED = 200 in the reference; we
# always speak flow control so the flag is informational.
# AUTH_FLAG_BATCH is a TPU extension bit: a node that sets it in its own
# AUTH accepts (and, if the remote also set it, emits) BATCHED_AUTH
# frames — AuthenticatedMessage arm 1 below.  Peers that never sent the
# flag never see arm-1 frames, so flags=0 links stay byte-compatible
# with the per-message wire format.
AUTH_FLAG_BATCH = 1

Auth = xdr_struct("Auth", [
    ("flags", Int32),
], defaults={"flags": 0})

IPAddrType = xdr_enum("IPAddrType", {"IPv4": 0, "IPv6": 1})

PeerAddressIp = xdr_union("PeerAddressIp", IPAddrType, {
    IPAddrType.IPv4: ("ipv4", Opaque(4)),
    IPAddrType.IPv6: ("ipv6", Opaque(16)),
})

PeerAddress = xdr_struct("PeerAddress", [
    ("ip", PeerAddressIp),
    ("port", Uint32),
    ("numFailures", Uint32),
], defaults={"numFailures": 0})

MessageType = xdr_enum("MessageType", {
    "ERROR_MSG": 0,
    "AUTH": 2,
    "DONT_HAVE": 3,
    "GET_PEERS": 4,
    "PEERS": 5,
    "GET_TX_SET": 6,
    "TX_SET": 7,
    "TRANSACTION": 8,
    "GET_SCP_QUORUMSET": 9,
    "SCP_QUORUMSET": 10,
    "SCP_MESSAGE": 11,
    "GET_SCP_STATE": 12,
    "HELLO": 13,
    "SEND_MORE": 16,
    "GENERALIZED_TX_SET": 17,
    "FLOOD_ADVERT": 18,
    "FLOOD_DEMAND": 19,
    "SEND_MORE_EXTENDED": 20,
    "TIME_SLICED_SURVEY_REQUEST": 21,
    "TIME_SLICED_SURVEY_RESPONSE": 22,
    "TIME_SLICED_SURVEY_START_COLLECTING": 23,
    "TIME_SLICED_SURVEY_STOP_COLLECTING": 24,
})

DontHave = xdr_struct("DontHave", [
    ("type", MessageType),
    ("reqHash", Uint256),
])

SendMore = xdr_struct("SendMore", [
    ("numMessages", Uint32),
])

SendMoreExtended = xdr_struct("SendMoreExtended", [
    ("numMessages", Uint32),
    ("numBytes", Uint32),
])

TX_ADVERT_VECTOR_MAX_SIZE = 1000
TX_DEMAND_VECTOR_MAX_SIZE = 1000

FloodAdvert = xdr_struct("FloodAdvert", [
    ("txHashes", VarArray(Hash, TX_ADVERT_VECTOR_MAX_SIZE)),
])

FloodDemand = xdr_struct("FloodDemand", [
    ("txHashes", VarArray(Hash, TX_DEMAND_VECTOR_MAX_SIZE)),
])


# -- time-sliced network survey (reference: Stellar-overlay.x survey types +
# src/overlay/SurveyManager) -------------------------------------------------

SurveyMessageCommandType = xdr_enum("SurveyMessageCommandType", {
    "TIME_SLICED_SURVEY_TOPOLOGY": 1,
})

SurveyMessageResponseType = xdr_enum("SurveyMessageResponseType", {
    "SURVEY_TOPOLOGY_RESPONSE_V2": 2,
})

SurveyRequestMessage = xdr_struct("SurveyRequestMessage", [
    ("surveyorPeerID", NodeID),
    ("surveyedPeerID", NodeID),
    ("ledgerNum", Uint32),
    ("encryptionKey", Curve25519Public),
    ("commandType", SurveyMessageCommandType),
], defaults={"commandType":
             SurveyMessageCommandType.TIME_SLICED_SURVEY_TOPOLOGY})

TimeSlicedSurveyRequestMessage = xdr_struct("TimeSlicedSurveyRequestMessage", [
    ("request", SurveyRequestMessage),
    ("nonce", Uint32),
    ("inboundPeersIndex", Uint32),
    ("outboundPeersIndex", Uint32),
], defaults={"inboundPeersIndex": 0, "outboundPeersIndex": 0})

SignedTimeSlicedSurveyRequestMessage = xdr_struct(
    "SignedTimeSlicedSurveyRequestMessage", [
        ("requestSignature", Signature),
        ("request", TimeSlicedSurveyRequestMessage),
    ])

EncryptedBody = VarOpaque(64000)

SurveyResponseMessage = xdr_struct("SurveyResponseMessage", [
    ("surveyorPeerID", NodeID),
    ("surveyedPeerID", NodeID),
    ("ledgerNum", Uint32),
    ("commandType", SurveyMessageCommandType),
    ("encryptedBody", EncryptedBody),
], defaults={"commandType":
             SurveyMessageCommandType.TIME_SLICED_SURVEY_TOPOLOGY})

TimeSlicedSurveyResponseMessage = xdr_struct(
    "TimeSlicedSurveyResponseMessage", [
        ("response", SurveyResponseMessage),
        ("nonce", Uint32),
    ])

SignedTimeSlicedSurveyResponseMessage = xdr_struct(
    "SignedTimeSlicedSurveyResponseMessage", [
        ("responseSignature", Signature),
        ("response", TimeSlicedSurveyResponseMessage),
    ])

TimeSlicedSurveyStartCollectingMessage = xdr_struct(
    "TimeSlicedSurveyStartCollectingMessage", [
        ("surveyorID", NodeID),
        ("nonce", Uint32),
        ("ledgerNum", Uint32),
    ])

SignedTimeSlicedSurveyStartCollectingMessage = xdr_struct(
    "SignedTimeSlicedSurveyStartCollectingMessage", [
        ("signature", Signature),
        ("startCollecting", TimeSlicedSurveyStartCollectingMessage),
    ])

TimeSlicedSurveyStopCollectingMessage = xdr_struct(
    "TimeSlicedSurveyStopCollectingMessage", [
        ("surveyorID", NodeID),
        ("nonce", Uint32),
        ("ledgerNum", Uint32),
    ])

SignedTimeSlicedSurveyStopCollectingMessage = xdr_struct(
    "SignedTimeSlicedSurveyStopCollectingMessage", [
        ("signature", Signature),
        ("stopCollecting", TimeSlicedSurveyStopCollectingMessage),
    ])

PeerStats = xdr_struct("PeerStats", [
    ("id", NodeID),
    ("versionStr", XdrString(100)),
    ("messagesRead", Uint64),
    ("messagesWritten", Uint64),
    ("bytesRead", Uint64),
    ("bytesWritten", Uint64),
    ("secondsConnected", Uint64),
    ("uniqueFloodBytesRecv", Uint64),
    ("duplicateFloodBytesRecv", Uint64),
    ("uniqueFetchBytesRecv", Uint64),
    ("duplicateFetchBytesRecv", Uint64),
    ("uniqueFloodMessageRecv", Uint64),
    ("duplicateFloodMessageRecv", Uint64),
    ("uniqueFetchMessageRecv", Uint64),
    ("duplicateFetchMessageRecv", Uint64),
], defaults={k: 0 for k in (
    "messagesRead", "messagesWritten", "bytesRead", "bytesWritten",
    "secondsConnected", "uniqueFloodBytesRecv", "duplicateFloodBytesRecv",
    "uniqueFetchBytesRecv", "duplicateFetchBytesRecv",
    "uniqueFloodMessageRecv", "duplicateFloodMessageRecv",
    "uniqueFetchMessageRecv", "duplicateFetchMessageRecv")})

TimeSlicedPeerData = xdr_struct("TimeSlicedPeerData", [
    ("peerStats", PeerStats),
    ("averageLatencyMs", Uint32),
], defaults={"averageLatencyMs": 0})

TimeSlicedNodeData = xdr_struct("TimeSlicedNodeData", [
    ("addedAuthenticatedPeers", Uint32),
    ("droppedAuthenticatedPeers", Uint32),
    ("totalInboundPeerCount", Uint32),
    ("totalOutboundPeerCount", Uint32),
    ("p75SCPFirstToSelfLatencyMs", Uint32),
    ("p75SCPSelfToOtherLatencyMs", Uint32),
    ("lostSyncCount", Uint32),
    ("isValidator", Uint32),
    ("maxInboundPeerCount", Uint32),
    ("maxOutboundPeerCount", Uint32),
], defaults={k: 0 for k in (
    "addedAuthenticatedPeers", "droppedAuthenticatedPeers",
    "totalInboundPeerCount", "totalOutboundPeerCount",
    "p75SCPFirstToSelfLatencyMs", "p75SCPSelfToOtherLatencyMs",
    "lostSyncCount", "isValidator", "maxInboundPeerCount",
    "maxOutboundPeerCount")})

TopologyResponseBodyV2 = xdr_struct("TopologyResponseBodyV2", [
    ("inboundPeers", VarArray(TimeSlicedPeerData, 25)),
    ("outboundPeers", VarArray(TimeSlicedPeerData, 25)),
    ("nodeData", TimeSlicedNodeData),
])

SurveyResponseBody = xdr_union("SurveyResponseBody", SurveyMessageResponseType, {
    SurveyMessageResponseType.SURVEY_TOPOLOGY_RESPONSE_V2:
        ("topologyResponseBodyV2", TopologyResponseBodyV2),
})


def _build_stellar_message():
    # deferred imports dodge a cycle: transaction.py imports nothing from
    # here, but xdr/__init__ imports both
    from .scp import SCPEnvelope, SCPQuorumSet
    from .transaction import TransactionEnvelope
    from .ledger import GeneralizedTransactionSet, TransactionSet

    return xdr_union("StellarMessage", MessageType, {
        MessageType.ERROR_MSG: ("error", Error),
        MessageType.HELLO: ("hello", Hello),
        MessageType.AUTH: ("auth", Auth),
        MessageType.DONT_HAVE: ("dontHave", DontHave),
        MessageType.GET_PEERS: ("getPeers", None),
        MessageType.PEERS: ("peers", VarArray(PeerAddress, 100)),
        MessageType.GET_TX_SET: ("txSetHash", Uint256),
        MessageType.TX_SET: ("txSet", TransactionSet),
        MessageType.GENERALIZED_TX_SET:
            ("generalizedTxSet", GeneralizedTransactionSet),
        MessageType.TRANSACTION: ("transaction", TransactionEnvelope),
        MessageType.GET_SCP_QUORUMSET: ("qSetHash", Uint256),
        MessageType.SCP_QUORUMSET: ("qSet", SCPQuorumSet),
        MessageType.SCP_MESSAGE: ("envelope", SCPEnvelope),
        MessageType.GET_SCP_STATE: ("getSCPLedgerSeq", Uint32),
        MessageType.SEND_MORE: ("sendMoreMessage", SendMore),
        MessageType.SEND_MORE_EXTENDED: ("sendMoreExtendedMessage",
                                         SendMoreExtended),
        MessageType.FLOOD_ADVERT: ("floodAdvert", FloodAdvert),
        MessageType.FLOOD_DEMAND: ("floodDemand", FloodDemand),
        MessageType.TIME_SLICED_SURVEY_REQUEST:
            ("signedTimeSlicedSurveyRequestMessage",
             SignedTimeSlicedSurveyRequestMessage),
        MessageType.TIME_SLICED_SURVEY_RESPONSE:
            ("signedTimeSlicedSurveyResponseMessage",
             SignedTimeSlicedSurveyResponseMessage),
        MessageType.TIME_SLICED_SURVEY_START_COLLECTING:
            ("signedTimeSlicedSurveyStartCollectingMessage",
             SignedTimeSlicedSurveyStartCollectingMessage),
        MessageType.TIME_SLICED_SURVEY_STOP_COLLECTING:
            ("signedTimeSlicedSurveyStopCollectingMessage",
             SignedTimeSlicedSurveyStopCollectingMessage),
    })


StellarMessage = _build_stellar_message()

AuthenticatedMessageV0 = xdr_struct("AuthenticatedMessageV0", [
    ("sequence", Uint64),
    ("message", StellarMessage),
    ("mac", HmacSha256Mac),
])

# BATCHED_AUTH (TPU extension, negotiated via AUTH_FLAG_BATCH): one
# sequence number + one MAC authenticate a packed run of StellarMessage
# encodings.  Each element of `messages` is one message's own XDR bytes
# (already 4-aligned, so the var-opaque padding is empty and the wire
# layout is exactly count + N x (u32 length + body)); the MAC covers
# everything between the sequence and the MAC itself.  The overlay
# splices these frames from pre-encoded bodies (overlay/peer.py) — this
# codec type exists for layout tests and debugging tools.
BATCH_WIRE_MAX_MESSAGES = 4096

BatchedAuthenticatedMessage = xdr_struct("BatchedAuthenticatedMessage", [
    ("sequence", Uint64),
    ("messages", VarArray(VarOpaque(0x7FFFFFFF), BATCH_WIRE_MAX_MESSAGES)),
    ("mac", HmacSha256Mac),
])

AuthenticatedMessage = xdr_union("AuthenticatedMessage", Uint32, {
    0: ("v0", AuthenticatedMessageV0),
    1: ("batch", BatchedAuthenticatedMessage),
})
