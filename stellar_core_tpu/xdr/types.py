"""Stellar-types.x equivalents (reference: src/protocol-curr/xdr/Stellar-types.x)."""

from .codec import (Int32, Int64, Opaque, Optional, Uint32, Uint64, VarOpaque,
                    Void, XdrString, xdr_enum, xdr_struct, xdr_union)

# typedefs
Hash = Opaque(32)
Uint256 = Opaque(32)
TimePoint = Uint64
Duration = Uint64
SequenceNumber = Int64
DataValue = VarOpaque(64)
Signature = VarOpaque(64)
SignatureHint = Opaque(4)
Thresholds = Opaque(4)
String32 = XdrString(32)
String64 = XdrString(64)
PoolID = Opaque(32)
AssetCode4 = Opaque(4)
AssetCode12 = Opaque(12)

CryptoKeyType = xdr_enum("CryptoKeyType", {
    "KEY_TYPE_ED25519": 0,
    "KEY_TYPE_PRE_AUTH_TX": 1,
    "KEY_TYPE_HASH_X": 2,
    "KEY_TYPE_ED25519_SIGNED_PAYLOAD": 3,
    "KEY_TYPE_MUXED_ED25519": 0x100,
})

PublicKeyType = xdr_enum("PublicKeyType", {
    "PUBLIC_KEY_TYPE_ED25519": 0,
})

SignerKeyType = xdr_enum("SignerKeyType", {
    "SIGNER_KEY_TYPE_ED25519": 0,
    "SIGNER_KEY_TYPE_PRE_AUTH_TX": 1,
    "SIGNER_KEY_TYPE_HASH_X": 2,
    "SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD": 3,
})

PublicKey = xdr_union("PublicKey", PublicKeyType, {
    PublicKeyType.PUBLIC_KEY_TYPE_ED25519: ("ed25519", Uint256),
})

NodeID = PublicKey
AccountID = PublicKey

SignerKeyEd25519SignedPayload = xdr_struct("SignerKeyEd25519SignedPayload", [
    ("ed25519", Uint256),
    ("payload", VarOpaque(64)),
])

SignerKey = xdr_union("SignerKey", SignerKeyType, {
    SignerKeyType.SIGNER_KEY_TYPE_ED25519: ("ed25519", Uint256),
    SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX: ("pre_auth_tx", Uint256),
    SignerKeyType.SIGNER_KEY_TYPE_HASH_X: ("hash_x", Uint256),
    SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD:
        ("ed25519_signed_payload", SignerKeyEd25519SignedPayload),
})

Curve25519Secret = xdr_struct("Curve25519Secret", [("key", Opaque(32))])
Curve25519Public = xdr_struct("Curve25519Public", [("key", Opaque(32))])
HmacSha256Key = xdr_struct("HmacSha256Key", [("key", Opaque(32))])
HmacSha256Mac = xdr_struct("HmacSha256Mac", [("mac", Opaque(32))])

# ExtensionPoint: union switch (int v) { case 0: void; }
ExtensionPoint = xdr_union("ExtensionPoint", Int32, {0: ("v0", None)})

Price = xdr_struct("Price", [("n", Int32), ("d", Int32)])
Liabilities = xdr_struct("Liabilities", [("buying", Int64), ("selling", Int64)])


def account_id(ed25519: bytes) -> "AccountID":
    return AccountID.ed25519(ed25519)


def node_id(ed25519: bytes) -> "NodeID":
    return NodeID.ed25519(ed25519)
