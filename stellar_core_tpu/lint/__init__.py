"""corelint — project-native static analysis for stellar-core-tpu.

Encodes the repo's cross-PR invariants as AST checks (see rules/):

  clock-discipline   VirtualClock-only time outside util/clock, util/perf
  ledger-txn-paths   every LedgerTxn reaches commit()/rollback()
  decode-free-seam   the raw-record path never rehydrates BucketEntry
  exception-hygiene  no silently swallowed `except Exception`
  metric-registry    static layer.subsystem.event + canonical-list check
  lock-order         cycle-free static lock-acquisition graph
  thread-safety      cross-thread fields lock-guarded or owned-by annotated
  raw-lock           threading.Lock/RLock only via util.lockorder.make_lock
  iteration-order    no set/dict-view iteration into order-sensitive sinks
                     (XDR, hashing, escaping lists, broadcast) unsorted
  float-discipline   no floats/true division on protocol-visible values
  hash-order         no builtin hash() / id()-keyed ordering in consensus
  rng-discipline     randomness only via an injected seeded random.Random

Run `python -m stellar_core_tpu.lint` (or `make lint`); suppress a
finding with `# corelint: disable=<rule> -- reason` — suppressions are
ratcheted by LINT_BASELINE.json.  The thread-safety rule's runtime twin
is util/racetrace.py (`make race`); the determinism rules' runtime twin
is util/detguard.py and their differential proof is
simulation/hashseed_diff.py (both under `make determinism`).
"""

from .core import (FileContext, LintReport, Rule, Violation,  # noqa: F401
                   check_baseline, load_baseline, render_human,
                   render_json, run_paths, write_baseline)
from .rules import ALL_RULE_CLASSES, all_rules, rules_by_id  # noqa: F401

DEFAULT_TARGETS = ("stellar_core_tpu", "bench.py", "native")
