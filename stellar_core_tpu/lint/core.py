"""corelint framework: AST rule registry, suppressions, reporters, ratchet.

Reference shape: the reference codebase's invariant/SelfCheck machinery
applied at *compile* time — each Rule encodes one repo discipline (clock,
LedgerTxn hygiene, the decode-free seam, lock order, metric naming) and
the runner turns a source tree into a machine-checkable report.

Suppressions:
  ``# corelint: disable=<rule>[,<rule>...] [-- reason]`` on the flagged
  line suppresses those rules for that line;
  ``# corelint: disable-file=<rule>[,...]`` anywhere in a file suppresses
  the rules for the whole file.
Suppressed findings are not dropped — they are reported in a separate
``suppressed`` list and ratcheted by the committed baseline, so adding a
new suppression is as visible as adding a violation.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*corelint:\s*(disable(?:-file)?)\s*=\s*([a-z0-9_,\s-]+?)"
    r"(?:\s*--.*)?$")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str            # repo-root-relative, posix separators
    line: int
    col: int
    message: str
    suppressed: bool = False

    def format(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] " \
               f"{self.message}{tag}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


class FileContext:
    """One parsed source file plus its suppression tables."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=path)
        self.line_suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
                if m.group(1) == "disable-file":
                    self.file_suppressions |= rules
                else:
                    self.line_suppressions.setdefault(
                        tok.start[0], set()).update(rules)
        except tokenize.TokenError:
            pass  # ast.parse already succeeded; comments best-effort

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions:
            return True
        return rule in self.line_suppressions.get(line, set())


class Rule:
    """One invariant. Subclasses set `id`/`description` and implement
    `check(ctx)`; cross-file rules may also implement `finalize(ctxs)`,
    called once after every file has been visited.  `language` routes
    dispatch: "py" rules see FileContext (Python AST), "c" rules see
    clex.CFileContext (token/function repr of native/*.c)."""

    id: str = ""
    description: str = ""
    language: str = "py"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        return iter(())

    def finalize(self, ctxs: List[FileContext]) -> Iterator[Violation]:
        return iter(())


@dataclass
class LintReport:
    violations: List[Violation] = field(default_factory=list)
    suppressed: List[Violation] = field(default_factory=list)
    files_scanned: int = 0
    parse_errors: List[str] = field(default_factory=list)

    def counts_by_rule(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for v in self.violations:
            out[v.rule] = out.get(v.rule, 0) + 1
        return out

    def suppression_counts(self) -> Dict[str, int]:
        """``"<path>:<rule>" -> count`` for the baseline ratchet."""
        out: Dict[str, int] = {}
        for v in self.suppressed:
            k = f"{v.path}:{v.rule}"
            out[k] = out.get(k, 0) + 1
        return out

    def to_dict(self) -> dict:
        return {
            "files_scanned": self.files_scanned,
            "counts": self.counts_by_rule(),
            "violations": [v.to_dict() for v in self.violations],
            "suppressed": [v.to_dict() for v in self.suppressed],
            "parse_errors": self.parse_errors,
        }


_SOURCE_EXTS = (".py", ".c")


def iter_source_files(paths: Iterable[str],
                      exts: Tuple[str, ...] = _SOURCE_EXTS) -> Iterator[str]:
    skip_dirs = {"__pycache__", ".git", "build", "node_modules"}
    seen: Set[str] = set()  # overlapping args must not lint a file twice

    def emit(path: str) -> Iterator[str]:
        ap = os.path.abspath(path)
        if ap not in seen:
            seen.add(ap)
            yield path

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(exts):
                yield from emit(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames if d not in skip_dirs)
            for fn in sorted(filenames):
                if fn.endswith(exts):
                    yield from emit(os.path.join(dirpath, fn))




def run_paths(paths: Iterable[str], rules: Iterable[Rule],
              root: Optional[str] = None) -> LintReport:
    """Lint every .py under `paths`. Relative paths in the report are
    computed against `root` (default: cwd) — rule scoping (allowed files,
    raw-path seams) keys off these relpaths."""
    from .clex import CFileContext
    root = os.path.abspath(root or os.getcwd())
    rules = list(rules)
    report = LintReport()
    ctxs: List[FileContext] = []
    for path in iter_source_files(paths):
        ap = os.path.abspath(path)
        rel = os.path.relpath(ap, root)
        try:
            with open(ap, "r", encoding="utf-8") as f:
                src = f.read()
            if ap.endswith(".c"):
                ctx = CFileContext(ap, rel, src)
            else:
                ctx = FileContext(ap, rel, src)
        except (SyntaxError, ValueError, UnicodeDecodeError, OSError) as e:
            # ValueError: ast.parse rejects NUL bytes with it (< 3.12);
            # CParseError (brace-unbalanced C) IS-A ValueError
            report.parse_errors.append(f"{rel}: {e}")
            continue
        ctxs.append(ctx)
        report.files_scanned += 1
        lang = getattr(ctx, "language", "py")
        for rule in rules:
            if rule.language != lang:
                continue
            for v in rule.check(ctx):
                _file_violation(report, ctx, v)
    by_rel = {c.relpath: c for c in ctxs}
    for rule in rules:
        lang_ctxs = [c for c in ctxs
                     if getattr(c, "language", "py") == rule.language]
        for v in rule.finalize(lang_ctxs):
            ctx = by_rel.get(v.path)
            if ctx is not None:
                _file_violation(report, ctx, v)
            else:
                report.violations.append(v)
    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    report.suppressed.sort(key=lambda v: (v.path, v.line, v.rule))
    return report


def _file_violation(report: LintReport, ctx: FileContext,
                    v: Violation) -> None:
    if ctx.is_suppressed(v.rule, v.line):
        report.suppressed.append(Violation(
            v.rule, v.path, v.line, v.col, v.message, suppressed=True))
    else:
        report.violations.append(v)


# ---------------------------------------------------------------------------
# Baseline ratchet
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> dict:
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f)


def write_baseline(path: str, report: LintReport) -> None:
    doc = {
        "version": 1,
        "comment": "corelint suppression ratchet — regenerate with "
                   "`python -m stellar_core_tpu.lint --write-baseline`",
        "suppressions": report.suppression_counts(),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")


def check_baseline(report: LintReport,
                   baseline: dict) -> List[str]:
    """Ratchet check: fail messages when the suppression set drifts from
    the committed baseline in EITHER direction.  Growth means a new,
    unreviewed suppression; shrinkage means the baseline is stale and
    must be regenerated — otherwise the removed entry's headroom would
    let a later unreviewed suppression in the same file slip through."""
    allowed: Dict[str, int] = baseline.get("suppressions", {})
    current = report.suppression_counts()
    problems: List[str] = []
    for key in sorted(set(current) | set(allowed)):
        n, cap = current.get(key, 0), allowed.get(key, 0)
        if n > cap:
            problems.append(
                f"suppression ratchet: {key} has {n} suppressed finding(s), "
                f"baseline allows {cap} — justify and regenerate the "
                f"baseline if intentional")
        elif n < cap:
            problems.append(
                f"suppression ratchet: {key} has {n} suppressed finding(s) "
                f"but the baseline still lists {cap} — ratchet down by "
                f"regenerating the baseline (--write-baseline)")
    return problems


# ---------------------------------------------------------------------------
# Reporters
# ---------------------------------------------------------------------------

def render_human(report: LintReport, verbose_suppressed: bool = False) -> str:
    lines: List[str] = []
    for v in report.violations:
        lines.append(v.format())
    if verbose_suppressed:
        for v in report.suppressed:
            lines.append(v.format())
    counts = report.counts_by_rule()
    summary = ", ".join(f"{r}={n}" for r, n in sorted(counts.items())) \
        or "clean"
    lines.append(
        f"corelint: {report.files_scanned} files, "
        f"{len(report.violations)} violation(s) [{summary}], "
        f"{len(report.suppressed)} suppressed")
    for e in report.parse_errors:
        lines.append(f"parse error: {e}")
    return "\n".join(lines)


def render_json(report: LintReport) -> str:
    return json.dumps(report.to_dict(), indent=2, sort_keys=True)


# ---------------------------------------------------------------------------
# Shared helpers used by several rules
# ---------------------------------------------------------------------------

def path_is(relpath: str, suffix: str) -> bool:
    """Path-segment-aware suffix match, robust to a --root above the repo
    root (relpaths then carry extra leading segments) without matching
    mere filename collisions ('workbench.py' is not 'bench.py')."""
    return relpath == suffix or relpath.endswith("/" + suffix)


def dotted_name(node: ast.AST) -> Optional[str]:
    """`a.b.c` Attribute/Name chain as a dotted string, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def import_aliases(tree: ast.Module) -> Dict[str, str]:
    """Map local name -> canonical dotted origin for every import in the
    module (`import time as _t` -> {"_t": "time"}; `from datetime import
    datetime as dt` -> {"dt": "datetime.datetime"})."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out
