"""corelint CLI: `python -m stellar_core_tpu.lint [paths...]`.

Exit status: 0 clean (all findings suppressed and within the baseline),
1 violations or suppression-ratchet growth, 2 usage errors.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import (DEFAULT_TARGETS, all_rules, check_baseline, load_baseline,
               render_human, render_json, rules_by_id, run_paths,
               write_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m stellar_core_tpu.lint",
        description="corelint: project-native static analysis")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_TARGETS})")
    ap.add_argument("--json", action="store_true",
                    help="emit the JSON report instead of human output")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--baseline", default=None, metavar="FILE",
                    help="suppression-ratchet file to enforce")
    ap.add_argument("--write-baseline", default=None, metavar="FILE",
                    help="write the current suppression set as the "
                         "new baseline and exit")
    ap.add_argument("--root", default=None,
                    help="repo root for relative paths (default: cwd)")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="list suppressed findings in human output")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id:18s} {r.description}")
        return 0

    try:
        rules = rules_by_id(args.rules.split(",")) if args.rules \
            else all_rules()
    except KeyError as e:
        print(f"corelint: {e}", file=sys.stderr)
        return 2

    nondefault_root = args.root is not None \
        and os.path.abspath(args.root) != os.getcwd()
    if (args.baseline or args.write_baseline) \
            and (args.rules or args.paths or nondefault_root):
        # the suppression baseline is defined over the FULL default scope
        # keyed by cwd-relative paths; a partial run or a different
        # --root would fail a clean tree (or write mis-keyed entries
        # that fail every run after)
        print("corelint: --baseline/--write-baseline require the default "
              "full scope (no --rules, no explicit paths, no --root)",
              file=sys.stderr)
        return 2

    missing = [p for p in (args.paths or []) if not os.path.exists(p)]
    if missing:
        # a typo'd CI path must not lint zero files and report green
        print(f"corelint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2
    targets = args.paths or [p for p in DEFAULT_TARGETS if os.path.exists(p)]
    if not targets:
        print("corelint: no lint targets found", file=sys.stderr)
        return 2
    report = run_paths(targets, rules, root=args.root)

    if args.write_baseline:
        write_baseline(args.write_baseline, report)
        print(f"corelint: wrote baseline "
              f"({len(report.suppression_counts())} suppression keys) "
              f"to {args.write_baseline}")
        if report.violations or report.parse_errors:
            # the baseline only covers suppressions — live violations
            # must not hide behind a green-looking regen
            print(render_human(report))
            return 1
        return 0

    failures = len(report.violations) > 0 or bool(report.parse_errors)
    ratchet_problems = []
    if args.baseline:
        try:
            baseline = load_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"corelint: cannot read baseline: {e}", file=sys.stderr)
            return 2
        ratchet_problems = check_baseline(report, baseline)
        failures = failures or bool(ratchet_problems)

    if args.json:
        print(render_json(report))
    else:
        print(render_human(report, verbose_suppressed=args.show_suppressed))
    for p in ratchet_problems:
        print(p, file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
