"""Hand-rolled C lexer for corelint's native-C rules (no pycparser).

The 10.4k-line native engine (native/*.c) is load-bearing for live close
and replay, so the same static-analysis bar the Python tree has must
cover it.  Full C parsing is out of scope (and pycparser is not in the
image); the rules in rules/native_c.py only need:

  - a token stream with line numbers (comments/strings/char literals
    stripped into single tokens, preprocessor directives skipped),
  - brace-matched top-level function extraction (name, parameter tokens,
    body token slice),
  - the corelint suppression grammar in C comments:
        /* corelint: disable=<rule>[,<rule>...] -- reason */
        /* corelint: disable-file=<rule>[,...] -- reason */

Deliberately NOT handled: K&R definitions, digraphs/trigraphs, nested
function-type declarators in parameter lists beyond what the engine
uses.  A brace-unbalanced file raises CParseError, which the runner
reports as a parse error (fail-stop, never a silent green).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

_C_SUPPRESS_RE = re.compile(
    r"corelint:\s*(disable(?:-file)?)\s*=\s*([a-z0-9_,\s-]+?)"
    r"(?:\s*--.*)?$", re.DOTALL)

# longest-match punctuation (3-char before 2-char before 1-char)
_PUNCT3 = ("<<=", ">>=", "...")
_PUNCT2 = ("->", "<<", ">>", "<=", ">=", "==", "!=", "&&", "||",
           "+=", "-=", "*=", "/=", "%=", "&=", "|=", "^=", "++", "--")

_NAME_START = set("abcdefghijklmnopqrstuvwxyz"
                  "ABCDEFGHIJKLMNOPQRSTUVWXYZ_")
_NAME_CONT = _NAME_START | set("0123456789")
_DIGITS = set("0123456789")


class CParseError(ValueError):
    """Lexing/brace-matching failure — reported as a lint parse error."""

    def __init__(self, msg: str, line: int):
        super().__init__(f"line {line}: {msg}")
        self.line = line


@dataclass(frozen=True)
class Tok:
    kind: str       # "name" | "num" | "str" | "char" | "punct"
    text: str
    line: int
    col: int

    def __repr__(self) -> str:  # compact for test failure output
        return f"{self.kind}:{self.text}@{self.line}"


@dataclass
class CFunction:
    """One brace-matched function definition."""
    name: str
    line: int                   # line of the function name token
    params: List[Tok]           # tokens inside the parameter parens
    body: List[Tok]             # tokens inside the outermost braces
                                # (braces themselves excluded)

    def param_names_of_type(self, type_name: str) -> Set[str]:
        """Names of parameters declared with `type_name` (pointer or
        value, const-qualified or not): `Rd *r`, `const Rd *outer`."""
        out: Set[str] = set()
        toks = self.params
        for i, t in enumerate(toks):
            if t.kind == "name" and t.text == type_name:
                j = i + 1
                while j < len(toks) and toks[j].text in ("*", "const"):
                    j += 1
                if j < len(toks) and toks[j].kind == "name":
                    out.add(toks[j].text)
        return out

    def local_names_of_type(self, type_name: str) -> Set[str]:
        """Names declared in the body as `type_name x;` / `type_name *x`
        (comma lists included: `Rd a, b;`)."""
        out: Set[str] = set()
        toks = self.body
        i = 0
        while i < len(toks):
            t = toks[i]
            if t.kind == "name" and t.text == type_name and \
                    (i == 0 or toks[i - 1].text in (";", "{", "}")
                     or toks[i - 1].text in ("const", "static")):
                j = i + 1
                while j < len(toks) and toks[j].text != ";":
                    while j < len(toks) and toks[j].text in ("*", "const"):
                        j += 1
                    if j < len(toks) and toks[j].kind == "name":
                        out.add(toks[j].text)
                        j += 1
                    # skip to next ',' or ';' (array dims, initializers)
                    depth = 0
                    while j < len(toks):
                        x = toks[j].text
                        if x in ("(", "["):
                            depth += 1
                        elif x in (")", "]"):
                            depth -= 1
                        elif depth == 0 and x in (",", ";"):
                            break
                        j += 1
                    if j < len(toks) and toks[j].text == ",":
                        j += 1
                        continue
                    break
                i = j
            i += 1
        return out


def tokenize(source: str) -> Tuple[List[Tok], List[Tuple[int, str]]]:
    """Return (tokens, comments) where comments is [(start_line, text)].
    Preprocessor directives (with backslash continuations) are skipped;
    string/char literals become single tokens."""
    toks: List[Tok] = []
    comments: List[Tuple[int, str]] = []
    i, n = 0, len(source)
    line, col = 1, 1
    at_line_start = True

    def adv(k: int) -> None:
        nonlocal i, line, col
        for _ in range(k):
            if i < n and source[i] == "\n":
                line += 1
                col = 1
            else:
                col += 1
            i += 1

    while i < n:
        c = source[i]
        if c in " \t\r":
            adv(1)
            continue
        if c == "\n":
            adv(1)
            at_line_start = True
            continue
        if at_line_start and c == "#":
            # preprocessor directive: consume to EOL, honoring \-continuations
            while i < n:
                if source[i] == "\\" and i + 1 < n and source[i + 1] == "\n":
                    adv(2)
                    continue
                if source[i] == "\n":
                    break
                adv(1)
            continue
        at_line_start = False
        if c == "/" and i + 1 < n and source[i + 1] == "*":
            start_line = line
            j = source.find("*/", i + 2)
            if j < 0:
                raise CParseError("unterminated block comment", start_line)
            comments.append((start_line, source[i + 2:j]))
            adv(j + 2 - i)
            continue
        if c == "/" and i + 1 < n and source[i + 1] == "/":
            start_line = line
            j = source.find("\n", i)
            j = n if j < 0 else j
            comments.append((start_line, source[i + 2:j]))
            adv(j - i)
            continue
        if c in ('"', "'"):
            quote, start_line, start_col = c, line, col
            j = i + 1
            while j < n:
                if source[j] == "\\":
                    j += 2
                    continue
                if source[j] == quote:
                    break
                if source[j] == "\n" and quote == '"':
                    raise CParseError("unterminated string literal",
                                      start_line)
                j += 1
            if j >= n:
                raise CParseError("unterminated literal", start_line)
            text = source[i:j + 1]
            toks.append(Tok("str" if quote == '"' else "char", text,
                            start_line, start_col))
            adv(j + 1 - i)
            continue
        if c in _NAME_START:
            j = i + 1
            while j < n and source[j] in _NAME_CONT:
                j += 1
            toks.append(Tok("name", source[i:j], line, col))
            adv(j - i)
            continue
        if c in _DIGITS:
            j = i + 1
            if c == "0" and j < n and source[j] in "xX":
                j += 1
                while j < n and source[j] in "0123456789abcdefABCDEF":
                    j += 1
            else:
                while j < n and source[j] in "0123456789.":
                    j += 1
            while j < n and source[j] in "uUlLfF":
                j += 1
            toks.append(Tok("num", source[i:j], line, col))
            adv(j - i)
            continue
        three, two = source[i:i + 3], source[i:i + 2]
        if three in _PUNCT3:
            toks.append(Tok("punct", three, line, col))
            adv(3)
            continue
        if two in _PUNCT2:
            toks.append(Tok("punct", two, line, col))
            adv(2)
            continue
        toks.append(Tok("punct", c, line, col))
        adv(1)
    return toks, comments


def extract_functions(toks: List[Tok]) -> List[CFunction]:
    """Brace-matched top-level function extraction.  A `{` at file scope
    whose previous token is `)` opens a function body; any other
    file-scope brace group (initializer, struct/enum/union definition)
    is skipped wholesale."""
    funcs: List[CFunction] = []
    i, n = 0, len(toks)
    while i < n:
        if toks[i].text != "{" or toks[i].kind != "punct":
            i += 1
            continue
        # match the brace group first (shared by both arms)
        depth, j = 1, i + 1
        while j < n and depth:
            if toks[j].kind == "punct":
                if toks[j].text == "{":
                    depth += 1
                elif toks[j].text == "}":
                    depth -= 1
            j += 1
        if depth:
            raise CParseError("unbalanced braces", toks[i].line)
        prev = toks[i - 1] if i > 0 else None
        if prev is not None and prev.kind == "punct" and prev.text == ")":
            # walk back to the matching '(' for the parameter list
            pdepth, k = 1, i - 2
            while k >= 0 and pdepth:
                if toks[k].kind == "punct":
                    if toks[k].text == ")":
                        pdepth += 1
                    elif toks[k].text == "(":
                        pdepth -= 1
                if pdepth:
                    k -= 1
            name_tok = toks[k - 1] if k > 0 else None
            if name_tok is not None and name_tok.kind == "name":
                funcs.append(CFunction(
                    name=name_tok.text,
                    line=name_tok.line,
                    params=toks[k + 1:i - 1],
                    body=toks[i + 1:j - 1]))
        i = j
    return funcs


class CFileContext:
    """C analogue of lint.core.FileContext: one lexed source file plus
    its suppression tables.  `language` routes rule dispatch; the
    suppression protocol (is_suppressed) matches FileContext exactly so
    reporting and the baseline ratchet are shared."""

    language = "c"
    tree = None     # no Python AST

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tokens, self.comments = tokenize(source)
        self.functions = extract_functions(self.tokens)
        self.line_suppressions: Dict[int, Set[str]] = {}
        self.file_suppressions: Set[str] = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        for start_line, text in self.comments:
            m = _C_SUPPRESS_RE.search(text.strip())
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self.file_suppressions |= rules
            else:
                self.line_suppressions.setdefault(
                    start_line, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        if rule in self.file_suppressions:
            return True
        return rule in self.line_suppressions.get(line, set())


# ---------------------------------------------------------------------------
# Token-slice helpers shared by the native-C rules
# ---------------------------------------------------------------------------

def find_calls(toks: List[Tok], names: Set[str]) -> List[Tuple[int, str]]:
    """Indexes (into toks) of call sites `name (` for any name in
    `names`.  Declarations are excluded by requiring the previous token
    not to be a type-ish name is NOT attempted — the engine never
    declares functions with these names locally."""
    out: List[Tuple[int, str]] = []
    for i, t in enumerate(toks):
        if t.kind == "name" and t.text in names and i + 1 < len(toks) \
                and toks[i + 1].text == "(":
            out.append((i, t.text))
    return out


def call_args(toks: List[Tok], open_paren: int) -> List[List[Tok]]:
    """Split the argument tokens of a call whose '(' is at `open_paren`
    into top-level comma-separated slices."""
    args: List[List[Tok]] = []
    cur: List[Tok] = []
    depth = 1
    i = open_paren + 1
    while i < len(toks) and depth:
        t = toks[i]
        if t.kind == "punct":
            if t.text in ("(", "["):
                depth += 1
            elif t.text in (")", "]"):
                depth -= 1
                if depth == 0:
                    break
            elif t.text == "," and depth == 1:
                args.append(cur)
                cur = []
                i += 1
                continue
        cur.append(t)
        i += 1
    if cur or args:
        args.append(cur)
    return args


def text_of(toks: List[Tok]) -> str:
    return " ".join(t.text for t in toks)
