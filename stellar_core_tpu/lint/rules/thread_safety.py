"""thread-safety rule family: lock COVERAGE, not just lock order.

``thread-safety`` — discover every thread entry point in the tree
(``threading.Thread(target=...)`` spawns, including targets reached
through closures and ``functools.partial``; every method of a
``BaseHTTPRequestHandler`` subclass, which the stdlib server runs on
admin worker threads; worker bodies like PreverifyPipeline's device
thread) and build a call-graph reachability map from each entry point to
the instance fields it reads/writes.  Callbacks registered through
``clock.post_action``/``VirtualTimer.expires_from_now`` are re-rooted at
the MAIN role — posting is cross-thread, running is not.  A field
reachable from two or more thread roles, with at least one write outside
``__init__``, must have every post-init access inside a ``with
<lock>``-style guard, or carry an explicit ownership annotation::

    # corelint: owned-by=<thread-role> -- reason

on one of its access/declaration lines.  Fields written only in
``__init__`` are init-then-publish immutable and exempt (the runtime
sanitizer's Exclusive state is the dynamic twin of this rule — see
util/racetrace.py).  The static guard check is coverage-only (SOME lock
is held); whether it is the RIGHT lock is the runtime lockset's job.

``raw-lock`` — ``threading.Lock()`` / ``threading.RLock()`` may only be
constructed inside util/lockorder.py: every lock in the tree goes through
``make_lock``/``make_rlock`` so it is nameable, order-traced, and visible
to the race sanitizer's lockset.

Resolution honesty (same stance as the lock-order rule): receivers
resolve through explicit evidence only — ``self``, ``x = self``,
constructor assignments, ``Name`` annotations on params/locals, and
relative/absolute imports.  An unresolvable callee is dropped, never
guessed; the runtime layer covers what statics cannot see.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import FileContext, Rule, Violation, path_is

_OWNED_RE = re.compile(
    r"#\s*corelint:\s*owned-by\s*=\s*([A-Za-z0-9_.-]+)\s*(--\s*\S.*)?$")

MAIN_ROLE = "main"

# call-shapes that re-root their function argument onto the main role
# (the clock loop runs them), and the positional index of that argument
_MAIN_CALLBACK_REGS = {"post_action": 0, "expires_from_now": 1,
                       "crank_until": 0}
_HTTP_BASE = "BaseHTTPRequestHandler"


def _is_lock_name(name: str) -> bool:
    low = name.lower()
    return low == "lock" or low.endswith("_lock")


ClassKey = Tuple[str, str]        # (dotted module, ClassName)


class _ClassInfo:
    __slots__ = ("key", "bases", "attr_types", "decl_lines", "is_http")

    def __init__(self, key: ClassKey, bases: List[str], is_http: bool):
        self.key = key
        self.bases = bases                      # dotted/raw base names
        self.attr_types: Dict[str, str] = {}    # attr -> dotted class name
        self.decl_lines: Dict[str, List[int]] = {}  # attr -> class-body lines
        self.is_http = is_http


class _FuncUnit:
    __slots__ = ("uid", "module", "relpath", "cls", "name", "parent",
                 "children", "var_types", "accesses", "calls", "spawns",
                 "cb_targets")

    def __init__(self, uid: str, module: str, relpath: str,
                 cls: Optional[ClassKey], name: str,
                 parent: Optional["_FuncUnit"]):
        self.uid = uid
        self.module = module
        self.relpath = relpath
        self.cls = cls                 # owning class for `self` accesses
        self.name = name
        self.parent = parent
        self.children: Dict[str, "_FuncUnit"] = {}
        self.var_types: Dict[str, str] = {}   # local name -> dotted class
        # (attr, is_write, guarded, lineno) for `self.attr`
        self.accesses: List[Tuple[str, bool, bool, int]] = []
        self.calls: List[tuple] = []          # descriptors, see _Scan
        self.spawns: List[Tuple[tuple, str, int]] = []  # (target, role, line)
        self.cb_targets: List[tuple] = []     # re-rooted to MAIN_ROLE


def _module_of(relpath: str) -> str:
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    return mod.replace("/", ".")


def _resolve_imports(tree: ast.Module, module: str) -> Dict[str, str]:
    """Local name -> dotted origin, RELATIVE imports included (the tree
    imports almost everything relatively, unlike core.import_aliases)."""
    out: Dict[str, str] = {}
    pkg_parts = module.split(".")[:-1]
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            if node.level == 0:
                base = node.module or ""
            else:
                up = pkg_parts[:len(pkg_parts) - (node.level - 1)]
                base = ".".join(up + ([node.module] if node.module else []))
            for a in node.names:
                if a.name == "*":
                    continue
                out[a.asname or a.name] = f"{base}.{a.name}" if base \
                    else a.name
    return out


class _Scan(ast.NodeVisitor):
    """One file -> FuncUnits, class table, ownership annotations."""

    def __init__(self, ctx: FileContext):
        self.ctx = ctx
        self.module = _module_of(ctx.relpath)
        self.imports = _resolve_imports(ctx.tree, self.module)
        self.classes: Dict[ClassKey, _ClassInfo] = {}
        self.units: Dict[str, _FuncUnit] = {}
        self.cls_stack: List[ClassKey] = []
        self.lock_depth = 0
        self.owned_lines = self._scan_owned_comments()
        # the module-level pseudo-unit anchors top-level code and nesting
        self.mod_unit = self._new_unit(None, "<module>", None)
        self.unit_stack: List[_FuncUnit] = [self.mod_unit]

    # -- comments -----------------------------------------------------------
    def _scan_owned_comments(self) -> Dict[int, Tuple[str, bool]]:
        """line -> (role, has_reason) for every owned-by annotation."""
        out: Dict[int, Tuple[str, bool]] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.ctx.source).readline):
                if tok.type != tokenize.COMMENT:
                    continue
                m = _OWNED_RE.search(tok.string)
                if m:
                    out[tok.start[0]] = (m.group(1), bool(m.group(2)))
        except tokenize.TokenError:
            pass
        return out

    # -- structure ----------------------------------------------------------
    def _new_unit(self, cls: Optional[ClassKey], name: str,
                  parent: Optional[_FuncUnit],
                  is_method: bool = False) -> _FuncUnit:
        qual = f"{parent.name}.{name}" if parent is not None \
            and parent.name != "<module>" else name
        uid = f"{self.module}::{qual}"
        n = 2
        while uid in self.units:      # same-named siblings stay distinct
            uid = f"{self.module}::{qual}#{n}"
            n += 1
        u = _FuncUnit(uid, self.module, self.ctx.relpath, cls, qual, parent)
        self.units[uid] = u
        # a class METHOD is a class attribute, NOT a lexical name in the
        # enclosing function/module scope — registering it as a child
        # would let a bare `name()` call resolve to a same-named method
        # of an unrelated class and fabricate cross-thread reach
        if parent is not None and not is_method:
            parent.children[name] = u
        return u

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            d = _dotted(b)
            if d is not None:
                bases.append(self.imports.get(d.split(".")[0], d)
                             if "." not in d else d)
        is_http = any(b.split(".")[-1] == _HTTP_BASE for b in bases)
        key = (self.module, node.name)
        info = _ClassInfo(key, bases, is_http)
        self.classes[key] = info
        # class-body declarations (annotation anchor points)
        for st in node.body:
            tgt = None
            if isinstance(st, ast.AnnAssign) and isinstance(st.target,
                                                            ast.Name):
                tgt = st.target.id
            elif isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name):
                tgt = st.targets[0].id
            if tgt is not None:
                info.decl_lines.setdefault(tgt, []).append(st.lineno)
        self.cls_stack.append(key)
        # direct FunctionDef children are METHODS (class attributes, not
        # lexical names); everything else visits normally
        for st in node.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit_fn(st, is_method=True)
            else:
                self.visit(st)
        self.cls_stack.pop()

    def _visit_fn(self, node, is_method: bool = False) -> None:
        cls = self.cls_stack[-1] if self.cls_stack else None
        # a method's immediate parent scope for closures is the enclosing
        # FUNCTION (class bodies don't capture), so walk past a parent
        # whose unit is the class's method container: unit_stack top is it
        u = self._new_unit(cls, node.name, self.unit_stack[-1],
                           is_method=is_method)
        self._infer_param_types(node, u)
        outer_depth = self.lock_depth
        self.lock_depth = 0          # a lock held at def-time is not held at call-time
        self.unit_stack.append(u)
        for st in node.body:
            self.visit(st)
        self.unit_stack.pop()
        self.lock_depth = outer_depth

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node: ast.Lambda) -> None:
        cls = self.cls_stack[-1] if self.cls_stack else None
        u = self._new_unit(cls, f"<lambda@{node.lineno}>",
                           self.unit_stack[-1])
        outer_depth = self.lock_depth
        self.lock_depth = 0
        self.unit_stack.append(u)
        self.visit(node.body)
        self.unit_stack.pop()
        self.lock_depth = outer_depth

    def _infer_param_types(self, fn, u: _FuncUnit) -> None:
        args = fn.args
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None and isinstance(a.annotation,
                                                       ast.Name):
                u.var_types[a.arg] = self.imports.get(
                    a.annotation.id, f"{self.module}.{a.annotation.id}")

    # -- guards -------------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        n_locks = 0
        for item in node.items:
            ce = item.context_expr
            name = ce.attr if isinstance(ce, ast.Attribute) else (
                ce.id if isinstance(ce, ast.Name) else None)
            if name is not None and _is_lock_name(name):
                n_locks += 1
            self.visit(ce)
        self.lock_depth += n_locks
        for st in node.body:
            self.visit(st)
        self.lock_depth -= n_locks

    visit_AsyncWith = visit_With

    # -- accesses -----------------------------------------------------------
    def _record_access(self, attr: str, is_write: bool, line: int) -> None:
        self.unit_stack[-1].accesses.append(
            (attr, is_write, self.lock_depth > 0, line))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if self.unit_stack[-1].cls is not None:
            if isinstance(node.value, ast.Name) \
                    and node.value.id == "self":
                self._record_access(
                    node.attr, isinstance(node.ctx, (ast.Store, ast.Del)),
                    node.lineno)
            elif isinstance(node.ctx, (ast.Store, ast.Del)) \
                    and isinstance(node.value, ast.Attribute) \
                    and isinstance(node.value.value, ast.Name) \
                    and node.value.value.id == "self":
                # self.a.b = ... mutates the object self.a refers to
                self._record_access(node.value.attr, True, node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # self.d[k] = v / del self.d[k] / self.d[k] += v mutate the
        # container the field refers to: a WRITE for sharing purposes
        # (the binding itself is only read — same view the runtime
        # sanitizer has, so the static layer must model it explicitly)
        if isinstance(node.ctx, (ast.Store, ast.Del)) \
                and self.unit_stack[-1].cls is not None \
                and isinstance(node.value, ast.Attribute) \
                and isinstance(node.value.value, ast.Name) \
                and node.value.value.id == "self":
            self._record_access(node.value.attr, True, node.lineno)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        u = self.unit_stack[-1]
        # local type evidence: x = self / x = ClassName(...)
        if len(node.targets) == 1 and isinstance(node.targets[0], ast.Name):
            tname = node.targets[0].id
            v = node.value
            if isinstance(v, ast.Name) and v.id == "self" \
                    and u.cls is not None:
                u.var_types[tname] = ".".join(u.cls)
            elif isinstance(v, ast.Call):
                d = _dotted(v.func)
                if d is not None:
                    u.var_types[tname] = self._dotted_to_class(d)
        # attr type evidence: self.x = ClassName(...) / self.x = param
        if len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute) \
                and isinstance(node.targets[0].value, ast.Name) \
                and node.targets[0].value.id == "self" \
                and u.cls is not None and u.cls in self.classes:
            info = self.classes[u.cls]
            attr = node.targets[0].attr
            v = node.value
            t = None
            if isinstance(v, ast.Call):
                d = _dotted(v.func)
                if d is not None:
                    t = self._dotted_to_class(d)
            elif isinstance(v, ast.Name):
                t = u.var_types.get(v.id)
            if t is not None and attr not in info.attr_types:
                info.attr_types[attr] = t
        self.generic_visit(node)

    def _dotted_to_class(self, d: str) -> str:
        head = d.split(".")[0]
        if head in self.imports:
            return self.imports[head] + d[len(head):]
        return f"{self.module}.{d}" if "." not in d else d

    # known in-place mutators: calling one through a field is a write to
    # the object that field refers to
    _MUTATORS = frozenset({
        "append", "extend", "insert", "add", "discard", "remove", "pop",
        "popitem", "clear", "update", "setdefault", "sort", "appendleft",
        "popleft", "__setitem__", "__delitem__"})

    # -- calls / spawns / callbacks -----------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        u = self.unit_stack[-1]
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in self._MUTATORS \
                and u.cls is not None \
                and isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name) \
                and f.value.value.id == "self":
            self._record_access(f.value.attr, True, node.lineno)
        d = _dotted(f)
        resolved = self._dotted_to_class(d) if d else None
        if resolved in ("threading.Thread", "_thread.start_new_thread"):
            self._record_spawn(node, u)
        elif isinstance(f, ast.Attribute) \
                and f.attr in _MAIN_CALLBACK_REGS:
            idx = _MAIN_CALLBACK_REGS[f.attr]
            target = None
            if len(node.args) > idx:
                target = self._target_desc(node.args[idx])
            if target is not None:
                u.cb_targets.append(target)
        # call edges
        if isinstance(f, ast.Attribute):
            recv = f.value
            if isinstance(recv, ast.Name):
                if recv.id == "self":
                    u.calls.append(("self", f.attr))
                else:
                    u.calls.append(("var", recv.id, f.attr))
            elif isinstance(recv, ast.Attribute) \
                    and isinstance(recv.value, ast.Name) \
                    and recv.value.id == "self":
                u.calls.append(("selfattr", recv.attr, f.attr))
        elif isinstance(f, ast.Name):
            u.calls.append(("name", f.id))
        self.generic_visit(node)

    def _record_spawn(self, node: ast.Call, u: _FuncUnit) -> None:
        target = role = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = self._target_desc(kw.value)
            elif kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                role = kw.value.value
        if target is None and node.args:
            target = self._target_desc(node.args[0])
        if target is None:
            return
        if role is None:
            role = target[-1]
        u.spawns.append((target, role, node.lineno))

    def _target_desc(self, expr: ast.expr) -> Optional[tuple]:
        """Resolvable thread-target/callback shapes: a bare name (local
        def or module function), ``self.meth``, or ``functools.partial``
        of either (closures and partials both reach real entry points)."""
        if isinstance(expr, ast.Name):
            return ("name", expr.id)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            if expr.value.id == "self":
                return ("self", expr.attr)
            return ("varattr", expr.value.id, expr.attr)
        if isinstance(expr, ast.Call):
            d = _dotted(expr.func)
            if d is not None \
                    and self._dotted_to_class(d) == "functools.partial" \
                    and expr.args:
                return self._target_desc(expr.args[0])
        return None


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# ---------------------------------------------------------------------------
# the cross-file analysis
# ---------------------------------------------------------------------------

class _Analysis:
    def __init__(self, scans: List[_Scan]):
        self.scans = scans
        self.units: Dict[str, _FuncUnit] = {}
        self.classes: Dict[ClassKey, _ClassInfo] = {}
        self.owned_lines: Dict[str, Dict[int, Tuple[str, bool]]] = {}
        for s in scans:
            self.units.update(s.units)
            self.classes.update(s.classes)
            self.owned_lines[s.ctx.relpath] = s.owned_lines
        # (module, fname) -> unit, and (cls, mname) -> unit
        self.mod_fns: Dict[Tuple[str, str], _FuncUnit] = {}
        self.methods: Dict[Tuple[ClassKey, str], _FuncUnit] = {}
        for u in self.units.values():
            if u.parent is not None and u.parent.name == "<module>" \
                    and u.cls is None:
                self.mod_fns[(u.module, u.name.split(".")[-1])] = u
            if u.cls is not None and "." not in u.name:
                self.methods[(u.cls, u.name)] = u
        # methods of nested classes carry qualified names; index by tail
        for u in self.units.values():
            if u.cls is not None and "." in u.name:
                key = (u.cls, u.name.split(".")[-1])
                self.methods.setdefault(key, u)

    # -- resolution ---------------------------------------------------------
    def _class_by_dotted(self, dotted: Optional[str]) -> Optional[ClassKey]:
        if not dotted or "." not in dotted:
            return None
        mod, _, cls = dotted.rpartition(".")
        key = (mod, cls)
        return key if key in self.classes else None

    def _method(self, cls: Optional[ClassKey],
                name: str) -> Optional[_FuncUnit]:
        seen: Set[ClassKey] = set()
        while cls is not None and cls not in seen:
            seen.add(cls)
            got = self.methods.get((cls, name))
            if got is not None:
                return got
            nxt = None
            for b in self.classes[cls].bases:
                bk = self._class_by_dotted(b) \
                    or self._class_by_dotted(f"{cls[0]}.{b}")
                if bk is not None:
                    nxt = bk
                    break
            cls = nxt
        return None

    def _var_type(self, u: _FuncUnit, name: str) -> Optional[str]:
        cur: Optional[_FuncUnit] = u
        while cur is not None:
            if name in cur.var_types:
                return cur.var_types[name]
            cur = cur.parent
        return None

    def _local_fn(self, u: _FuncUnit, name: str) -> Optional[_FuncUnit]:
        cur: Optional[_FuncUnit] = u
        while cur is not None:
            if name in cur.children:
                return cur.children[name]
            cur = cur.parent
        return None

    def resolve_call(self, u: _FuncUnit, call: tuple) -> Optional[_FuncUnit]:
        kind = call[0]
        if kind == "self":
            return self._method(u.cls, call[1])
        if kind == "selfattr":
            if u.cls is None or u.cls not in self.classes:
                return None
            t = self.classes[u.cls].attr_types.get(call[1])
            return self._method(self._class_by_dotted(t), call[2])
        if kind == "var":
            vt = self._var_type(u, call[1])
            if vt is not None:
                got = self._method(self._class_by_dotted(vt), call[2])
                if got is not None:
                    return got
            # module alias: eventlog.record(...)
            scan = next(s for s in self.scans if s.module == u.module)
            dotted = scan.imports.get(call[1])
            if dotted is not None:
                return self.mod_fns.get((dotted, call[2]))
            return None
        if kind == "name":
            got = self._local_fn(u, call[1])
            if got is not None:
                return got
            got = self.mod_fns.get((u.module, call[1]))
            if got is not None:
                return got
            # from-imported function or class constructor
            scan = next(s for s in self.scans if s.module == u.module)
            dotted = scan.imports.get(call[1],
                                      f"{u.module}.{call[1]}")
            ck = self._class_by_dotted(dotted)
            if ck is not None:
                return self._method(ck, "__init__")
            mod, _, fn = dotted.rpartition(".")
            return self.mod_fns.get((mod, fn))
        if kind == "varattr":   # spawn-target shape x.meth
            vt = self._var_type(u, call[1])
            return self._method(self._class_by_dotted(vt), call[2])
        return None

    # -- reachability -------------------------------------------------------
    def _reach(self, roots: List[_FuncUnit]) -> Set[str]:
        seen: Set[str] = set()
        stack = list(roots)
        while stack:
            u = stack.pop()
            if u.uid in seen:
                continue
            seen.add(u.uid)
            for c in u.calls:
                got = self.resolve_call(u, c)
                if got is not None and got.uid not in seen:
                    stack.append(got)
        return seen

    def run(self) -> Tuple[Dict[str, Set[str]], List[tuple]]:
        """-> (unit uid -> roles, annotation problems)."""
        # 1. thread entry points
        entries: List[Tuple[_FuncUnit, str]] = []
        for u in self.units.values():
            for target, role, _ in u.spawns:
                got = self.resolve_call(u, target)
                if got is not None:
                    entries.append((got, role))
        for ck, info in self.classes.items():
            if info.is_http:
                for (cls, _m), mu in list(self.methods.items()):
                    if cls == ck:
                        entries.append((mu, "http-admin"))
        # 2. per-role reach
        role_reach: Dict[str, Set[str]] = {}
        thread_units: Set[str] = set()
        for ent, role in entries:
            r = self._reach([ent])
            role_reach.setdefault(role, set()).update(r)
            thread_units |= r
        # 3. main = everything not exclusively thread-side, plus re-rooted
        # callbacks (post_action/timers run on the crank loop)
        main_roots = [u for u in self.units.values()
                      if u.uid not in thread_units]
        for u in self.units.values():
            for t in u.cb_targets:
                got = self.resolve_call(u, t)
                if got is not None:
                    main_roots.append(got)
        role_reach[MAIN_ROLE] = self._reach(main_roots)
        roles: Dict[str, Set[str]] = {}
        for role, reach in role_reach.items():
            for uid in reach:
                roles.setdefault(uid, set()).add(role)
        return roles, entries


class ThreadSafetyRule(Rule):
    id = "thread-safety"
    description = ("instance fields reachable from >=2 thread roles must "
                   "be lock-guarded or carry an owned-by annotation")

    def finalize(self, ctxs: List[FileContext]) -> Iterator[Violation]:
        scans = [_Scan(ctx) for ctx in ctxs]
        for s in scans:
            s.visit(s.ctx.tree)
        ana = _Analysis(scans)
        roles, _entries = ana.run()

        # malformed annotations are findings of their own: an attestation
        # without a reason documents nothing
        for relpath, lines in ana.owned_lines.items():
            for line, (role, has_reason) in sorted(lines.items()):
                if not has_reason:
                    yield Violation(
                        self.id, relpath, line, 0,
                        f"owned-by={role} annotation needs a reason: "
                        "`# corelint: owned-by=<role> -- reason`")

        # field table: (cls, attr) -> access rows + owning scan
        fields: Dict[Tuple[ClassKey, str], List[tuple]] = {}
        for u in ana.units.values():
            if u.cls is None:
                continue
            # any qual segment == "__init__" covers methods of classes
            # nested in functions ("build.__init__") and closures defined
            # inside __init__ ("__init__.cb" — re-rooted to main anyway)
            in_init = "__init__" in u.name.split(".")
            u_roles = roles.get(u.uid, set())
            for attr, is_write, guarded, line in u.accesses:
                if _is_lock_name(attr):
                    continue          # the guard itself is never guarded
                fields.setdefault((u.cls, attr), []).append(
                    (u, u_roles, is_write, guarded, line, in_init))

        for (cls, attr), rows in sorted(
                fields.items(), key=lambda kv: (kv[0][0], kv[0][1])):
            # __init__ accesses contribute neither roles nor findings:
            # construction happens-before thread start (init-then-publish)
            post_init = [r for r in rows if not r[5]]
            all_roles: Set[str] = set()
            for _u, r, _w, _g, _l, _init in post_init:
                all_roles |= r
            if len(all_roles) < 2:
                continue
            if not any(w for _u, _r, w, _g, _l, _i in post_init):
                continue              # init-then-publish: immutable after __init__
            if self._is_owned(ana, cls, attr, rows):
                continue
            seen_lines: Set[Tuple[str, int]] = set()
            for u, _r, is_write, guarded, line, _i in sorted(
                    post_init,
                    key=lambda r: (r[0].relpath, r[4], not r[2])):
                if guarded:
                    continue
                # one finding per line per field (a mutator call records
                # both the container write and the binding read)
                if (u.relpath, line) in seen_lines:
                    continue
                seen_lines.add((u.relpath, line))
                yield Violation(
                    self.id, u.relpath, line, 0,
                    f"field '{cls[1]}.{attr}' is shared across thread "
                    f"roles {{{', '.join(sorted(all_roles))}}} but this "
                    f"{'write' if is_write else 'read'} holds no lock — "
                    "guard it with a make_lock/make_rlock lock or annotate "
                    "`# corelint: owned-by=<role> -- reason`")

    def _is_owned(self, ana: _Analysis, cls: ClassKey, attr: str,
                  rows: List[tuple]) -> bool:
        """An owned-by annotation on any access line of the field, or on
        its class-body declaration, attests single-thread ownership."""
        info = ana.classes.get(cls)
        lines_by_rel: Dict[str, Set[int]] = {}
        for u, _r, _w, _g, line, _i in rows:
            lines_by_rel.setdefault(u.relpath, set()).add(line)
        if info is not None:
            rel = next((s.ctx.relpath for s in ana.scans
                        if s.module == cls[0]), None)
            if rel is not None:
                lines_by_rel.setdefault(rel, set()).update(
                    info.decl_lines.get(attr, []))
        for rel, lines in lines_by_rel.items():
            owned = ana.owned_lines.get(rel, {})
            if any(ln in owned and owned[ln][1] for ln in lines):
                return True
        return False


class RawLockRule(Rule):
    id = "raw-lock"
    description = ("threading.Lock()/RLock() may only be constructed in "
                   "util/lockorder.py (make_lock keeps locks nameable, "
                   "order-traced, and lockset-visible)")

    ALLOWED = "util/lockorder.py"

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if path_is(ctx.relpath, self.ALLOWED):
            return
        imports = _resolve_imports(ctx.tree, _module_of(ctx.relpath))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None:
                continue
            head = d.split(".")[0]
            resolved = imports.get(head, head) + d[len(head):]
            if resolved in ("threading.Lock", "threading.RLock"):
                kind = resolved.split(".")[-1]
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"raw threading.{kind}() — route it through "
                    f"util.lockorder.make_{'r' if kind == 'RLock' else ''}"
                    "lock(name) so the lock is order-traced and visible "
                    "to the race sanitizer's lockset")
