"""decode-free-seam: the raw-record path never rehydrates entries.

PR 3's streaming merge pipeline guarantees O(1) merge memory by moving
packed XDR records file-to-file without ever constructing a BucketEntry.
That guarantee was enforced by a runtime monkeypatch test (forbidden
rehydrate); this rule makes it a compile-time property: inside the
raw-path scopes —

  * ``merge_buckets_raw`` in bucket/bucket.py,
  * class ``BucketStreamWriter`` in bucket/manager.py,
  * the whole native bridge module ledger/native_apply.py,

— any ``.entries`` attribute access (the lazy-rehydrate property) or any
reference to ``BucketEntry`` (constructing or re-tagging via the decoded
type) is a violation.  Re-tagging must stay a 4-byte wire splice.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Tuple

from ..core import FileContext, Rule, Violation, path_is

# (relpath suffix, scope qualname or None for whole module)
RAW_PATH_SCOPES: Tuple[Tuple[str, Optional[str]], ...] = (
    ("stellar_core_tpu/bucket/bucket.py", "merge_buckets_raw"),
    ("stellar_core_tpu/bucket/manager.py", "BucketStreamWriter"),
    ("stellar_core_tpu/ledger/native_apply.py", None),
)

FORBIDDEN_ATTRS = ("entries", "_rehydrate", "packed_entries")
FORBIDDEN_NAME = "BucketEntry"


class DecodeFreeSeamRule(Rule):
    id = "decode-free-seam"
    description = ("raw-record scopes (merge_buckets_raw, "
                   "BucketStreamWriter, the native bridge) must not "
                   "touch Bucket.entries or BucketEntry")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for suffix, scope in RAW_PATH_SCOPES:
            if not path_is(ctx.relpath, suffix):
                continue
            for node in self._scope_nodes(ctx.tree, scope):
                yield from self._scan(ctx, node, scope)

    @staticmethod
    def _scope_nodes(tree: ast.Module, scope: Optional[str]) -> List[ast.AST]:
        if scope is None:
            return [tree]
        out: List[ast.AST] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node.name == scope:
                out.append(node)
        return out

    def _scan(self, ctx: FileContext, scope_node: ast.AST,
              scope: Optional[str]) -> Iterator[Violation]:
        where = scope or "module"
        for node in ast.walk(scope_node):
            if isinstance(node, ast.Attribute) \
                    and node.attr in FORBIDDEN_ATTRS:
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f".{node.attr} rehydrates decoded entries inside the "
                    f"raw path ({where}) — stream packed records instead")
            elif isinstance(node, ast.Name) and node.id == FORBIDDEN_NAME:
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"{FORBIDDEN_NAME} referenced inside the raw path "
                    f"({where}) — records must stay packed; re-tag via "
                    f"a 4-byte splice")
