"""lock-order: the static lock-acquisition graph must stay acyclic.

Extracts every ``with <lock>:`` acquisition (names ending in ``_lock`` —
`self._lock`, `snap._lock`, module-level `_LOCK`) across the tree,
identifies each lock by its owning class/module (lock *class*, not
instance: all Histogram._lock instances are one node, the standard
deadlock-analysis granularity), and builds the held-while-acquiring
graph:

  * lexically nested ``with`` blocks, and
  * calls made while holding a lock to same-class methods / same-module
    functions that themselves acquire a lock (one call-graph level).

A cycle in that graph is a potential ABBA deadlock and fails the lint.

Receiver resolution: `self._lock` belongs to the enclosing class;
`other._lock` resolves through local type evidence (`other: Snap`
annotations, `other = Snap(...)` constructor assignments, and
`self.attr = Snap(...)` for `self.attr._lock`).  An unresolvable
receiver becomes a distinct `?name` node — never collapsed into the
enclosing class (which would silently drop the edge as a self-edge) and
never merged with other unknowns (which would fabricate cycles).  Orders
statics can't resolve are the runtime tracer's job (util/lockorder.py,
STPU_LOCK_TRACE=1): it records the *real* acquisition DAG and
fail-stops on inversion.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import FileContext, Rule, Violation


def _is_lock_name(name: str) -> bool:
    # `lock` / `_lock` / `tree_lock` / `_LOCK`, but NOT `clock`/`block`
    low = name.lower()
    return low == "lock" or low.endswith("_lock")


def _lock_expr(node: ast.expr) -> Optional[Tuple[Optional[ast.expr], str]]:
    """(receiver, attr) for a lock-ish acquisition expr, else None.
    Receiver is None for a bare Name lock (module-level)."""
    if isinstance(node, ast.Attribute) and _is_lock_name(node.attr):
        return node.value, node.attr
    if isinstance(node, ast.Name) and _is_lock_name(node.id):
        return None, node.id
    return None


def _call_class_name(value: ast.expr, classes: Set[str]) -> Optional[str]:
    """`ClassName(...)` -> "ClassName" when ClassName is a module class."""
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
            and value.func.id in classes:
        return value.func.id
    return None


class _ModuleScan(ast.NodeVisitor):
    """Per-module pass: with-lock nestings and per-function acquisitions."""

    def __init__(self, modname: str, classes: Set[str],
                 self_attr_types: Dict[Tuple[str, str], str]):
        self.mod = modname
        self.classes = classes
        # (class, attr) -> class of `self.attr = ClassName(...)`
        self.self_attr_types = self_attr_types
        self.cls_stack: List[str] = []
        self.fn_stack: List[Tuple[str, str]] = []  # (class, func)
        self.var_types_stack: List[Dict[str, str]] = []
        # (class, func) -> set of lock nodes it directly acquires
        self.fn_acquires: Dict[Tuple[str, str], Set[str]] = {}
        # edges observed lexically: (held, acquired, lineno)
        self.edges: List[Tuple[str, str, int]] = []
        # calls made while holding: (held_lock, class, callee, lineno)
        self.held_calls: List[Tuple[str, str, str, int]] = []
        self.held: List[str] = []

    # -- receiver resolution -------------------------------------------------
    def _infer_var_types(self, fn) -> Dict[str, str]:
        """name -> class for params annotated with a module class and
        locals assigned from a module-class constructor."""
        out: Dict[str, str] = {}
        args = fn.args
        for a in list(args.args) + list(args.kwonlyargs) \
                + ([args.vararg] if args.vararg else []) \
                + ([args.kwarg] if args.kwarg else []):
            if a.annotation is not None \
                    and isinstance(a.annotation, ast.Name) \
                    and a.annotation.id in self.classes:
                out[a.arg] = a.annotation.id
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                cls = _call_class_name(node.value, self.classes)
                if cls:
                    out[node.targets[0].id] = cls
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Name) \
                    and isinstance(node.annotation, ast.Name) \
                    and node.annotation.id in self.classes:
                out[node.target.id] = node.annotation.id
        return out

    def _owner_for(self, recv: Optional[ast.expr]) -> str:
        here = self.cls_stack[-1] if self.cls_stack else "<module>"
        if recv is None:
            return "<module>"
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                return here
            for scope in reversed(self.var_types_stack):
                if recv.id in scope:
                    return scope[recv.id]
            return self._unknown(recv.id)
        if isinstance(recv, ast.Attribute) \
                and isinstance(recv.value, ast.Name) \
                and recv.value.id == "self":
            t = self.self_attr_types.get((here, recv.attr))
            return t if t else self._unknown(f"self.{recv.attr}")
        # complex receiver: a distinct per-expression unknown node
        return self._unknown(ast.unparse(recv))

    def _unknown(self, label: str) -> str:
        """Unknown-receiver node scoped to the current function: the same
        name in one function plausibly means one object (intra-function
        cycles stay detectable), but across functions it must NOT merge —
        unrelated objects sharing a parameter name would otherwise
        fabricate cycles."""
        cls, fn = self.fn_stack[-1] if self.fn_stack \
            else ("<module>", "<module>")
        return f"?{cls}.{fn}.{label}"

    def _lock_node(self, recv: Optional[ast.expr], attr: str) -> str:
        return f"{self.mod}.{self._owner_for(recv)}.{attr}"

    # -- structure visitors --------------------------------------------------
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self.cls_stack.append(node.name)
        self.generic_visit(node)
        self.cls_stack.pop()

    def _visit_fn(self, node) -> None:
        key = (self.cls_stack[-1] if self.cls_stack else "<module>",
               node.name)
        self.fn_stack.append(key)
        self.fn_acquires.setdefault(key, set())
        self.var_types_stack.append(self._infer_var_types(node))
        outer_held = self.held
        self.held = []  # held set does not cross function boundaries
        self.generic_visit(node)
        self.held = outer_held
        self.var_types_stack.pop()
        self.fn_stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a lambda body runs LATER, lock-free — calls inside it are not
        # "calls made while holding"
        outer_held = self.held
        self.held = []
        self.generic_visit(node)
        self.held = outer_held

    def visit_With(self, node: ast.With) -> None:
        n_acquired = 0
        for item in node.items:
            le = _lock_expr(item.context_expr)
            if le is None:
                self.visit(item.context_expr)
                continue
            ln = self._lock_node(*le)
            if self.fn_stack:
                self.fn_acquires[self.fn_stack[-1]].add(ln)
            for h in self.held:
                if h != ln:
                    self.edges.append((h, ln, node.lineno))
            # held immediately: `with a_lock, b_lock:` orders a before b
            self.held.append(ln)
            n_acquired += 1
        for st in node.body:
            self.visit(st)
        if n_acquired:
            del self.held[-n_acquired:]

    visit_AsyncWith = visit_With

    def visit_Call(self, node: ast.Call) -> None:
        if self.held:
            f = node.func
            callee = None
            cls = "<module>"
            if isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id == "self" and self.cls_stack:
                callee, cls = f.attr, self.cls_stack[-1]
            elif isinstance(f, ast.Name):
                callee = f.id
            if callee is not None:
                for h in self.held:
                    self.held_calls.append((h, cls, callee, node.lineno))
        self.generic_visit(node)


def _collect_self_attr_types(tree: ast.Module,
                             classes: Set[str]) -> Dict[Tuple[str, str], str]:
    """(class, attr) -> ClassName for every `self.attr = ClassName(...)`."""
    out: Dict[Tuple[str, str], str] = {}
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    c = _call_class_name(node.value, classes)
                    if c:
                        out[(cls.name, t.attr)] = c
    return out


class LockOrderRule(Rule):
    id = "lock-order"
    description = ("the static `with <lock>` acquisition graph (lexical "
                   "nesting + one call level) must be cycle-free")

    def finalize(self, ctxs: List[FileContext]) -> Iterator[Violation]:
        edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
        scans: List[Tuple[FileContext, _ModuleScan]] = []
        for ctx in ctxs:
            mod = os.path.splitext(ctx.relpath)[0].replace("/", ".")
            classes = {n.name for n in ast.walk(ctx.tree)
                       if isinstance(n, ast.ClassDef)}
            scan = _ModuleScan(mod, classes,
                               _collect_self_attr_types(ctx.tree, classes))
            scan.visit(ctx.tree)
            scans.append((ctx, scan))
            for held, acq, lineno in scan.edges:
                edges.setdefault((held, acq), (ctx.relpath, lineno))
        # one call-graph level: held lock -> locks acquired by the callee
        for ctx, scan in scans:
            for held, cls, callee, lineno in scan.held_calls:
                # a `self.meth()` call resolves ONLY within its class —
                # falling back to a same-named module function would
                # fabricate edges that never happen at runtime
                acq = scan.fn_acquires.get((cls, callee), set())
                for ln in acq:
                    if ln != held:
                        edges.setdefault((held, ln), (ctx.relpath, lineno))
        yield from self._report_cycles(edges)

    def _report_cycles(self, edges) -> Iterator[Violation]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        color: Dict[str, int] = {}
        stack: List[str] = []
        seen_cycles: Set[frozenset] = set()

        def dfs(u: str):
            color[u] = 1
            stack.append(u)
            for v in adj.get(u, ()):
                if color.get(v, 0) == 0:
                    yield from dfs(v)
                elif color.get(v) == 1:
                    cyc = stack[stack.index(v):] + [v]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        path, lineno = edges[(u, v)]
                        yield Violation(
                            self.id, path, lineno, 0,
                            "lock-order cycle (potential ABBA deadlock): "
                            + " -> ".join(cyc))
            stack.pop()
            color[u] = 2

        for node in sorted(adj):
            if color.get(node, 0) == 0:
                yield from dfs(node)
