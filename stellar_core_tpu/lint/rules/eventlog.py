"""eventlog-partitions: flight-event partition literals are real log
partitions.

``eventlog.record(partition, severity, msg, **fields)`` validates its
partition at runtime against ``util/logging.PARTITIONS`` — but a typo'd
literal then only explodes when that (possibly rare) lifecycle edge
actually fires, which for fail-stop paths is exactly the moment the
flight recorder must not break.  This rule moves the check to parse
time: every string literal passed as the first argument of an
``eventlog.record(...)`` call (or a bare ``record(...)`` imported from
util.eventlog) must be a member of PARTITIONS.  Dynamic partitions
(variables) are skipped — the runtime check covers those funnels.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Rule, Violation, path_is

# the recorder itself passes caller-supplied names through
EXEMPT_FILES = ("stellar_core_tpu/util/eventlog.py",)


def _partitions():
    from ...util.logging import PARTITIONS
    return frozenset(PARTITIONS)


def _imports_record_from_eventlog(tree: ast.AST) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module \
                and node.module.endswith("eventlog"):
            if any(a.name == "record" for a in node.names):
                return True
    return False


class EventlogPartitionRule(Rule):
    id = "eventlog-partitions"
    description = ("string literals passed as the partition of "
                   "eventlog.record() must be members of "
                   "util/logging.PARTITIONS")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if any(path_is(ctx.relpath, e) for e in EXEMPT_FILES):
            return
        partitions = _partitions()
        bare_record = _imports_record_from_eventlog(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            named = (isinstance(f, ast.Attribute) and f.attr == "record"
                     and isinstance(f.value, ast.Name)
                     and f.value.id == "eventlog") \
                or (bare_record and isinstance(f, ast.Name)
                    and f.id == "record")
            if not named:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                if arg.value not in partitions:
                    yield Violation(
                        self.id, ctx.relpath, arg.lineno, arg.col_offset,
                        f"eventlog partition {arg.value!r} is not in "
                        f"util/logging.PARTITIONS")
