"""exception-hygiene: no silently swallowed broad exception handlers.

`except Exception` (or bare `except:`) is allowed only when the handler
visibly deals with the failure: it re-raises, logs, or routes the error
into an explicit failure path (`self._fail(...)`, `peer.drop(...)`).
Anything else — `pass`, bare `return None`, `continue` — swallows bugs
on hot paths (ledger close, overlay receive) and must either narrow the
exception type or carry an explicit suppression with a reason:

    except Exception:  # corelint: disable=exception-hygiene -- why
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import FileContext, Rule, Violation

BROAD_TYPES = ("Exception", "BaseException")
LOG_METHODS = ("debug", "info", "warning", "error", "exception", "critical")
# failure-path sinks: methods that by convention log/record and propagate
# the failure (Work._fail fails the work machine, Peer.drop logs + closes)
FAILURE_SINKS = ("_fail", "fail", "drop")


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True  # bare except:
    if isinstance(t, ast.Name):
        return t.id in BROAD_TYPES
    if isinstance(t, ast.Tuple):
        return any(isinstance(e, ast.Name) and e.id in BROAD_TYPES
                   for e in t.elts)
    return False


def _handles(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    (f.attr in LOG_METHODS or f.attr in FAILURE_SINKS):
                return True
            if isinstance(f, ast.Name) and f.id in FAILURE_SINKS:
                return True
    return False


class ExceptionHygieneRule(Rule):
    id = "exception-hygiene"
    description = ("broad `except Exception` handlers must re-raise, "
                   "log, route to a failure path, or carry an explicit "
                   "suppression")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if _is_broad(node) and not _handles(node):
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "broad exception handler swallows errors silently — "
                    "narrow the type, log/re-raise, or suppress with a "
                    "reason")
