"""clock-discipline: VirtualClock is the only time source.

The determinism backbone (util/clock.py) requires that wall-clock reads
never leak into subsystem code: `time.time()`, `time.monotonic()` and
`datetime.now()/utcnow()/today()` are forbidden everywhere except the
clock itself, the perf/timing surface, and the bench driver.  Everything
else must go through VirtualClock (simulated time) or the blessed
real-time helpers `util.clock.monotonic_now()` / `wall_now()`.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ..core import (FileContext, Rule, Violation, dotted_name,
                    import_aliases, path_is)

FORBIDDEN = {
    "time.time",
    "time.time_ns",
    "time.monotonic",
    "time.monotonic_ns",
    "datetime.datetime.now",
    "datetime.datetime.utcnow",
    "datetime.datetime.today",
    "datetime.date.today",
}

ALLOWED_FILES = (
    "stellar_core_tpu/util/clock.py",
    "stellar_core_tpu/util/perf.py",
    "bench.py",
)


class ClockDisciplineRule(Rule):
    id = "clock-discipline"
    description = ("wall-clock reads (time.time/time.monotonic/"
                   "datetime.now) outside util/clock.py, util/perf.py "
                   "and bench.py")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if any(path_is(ctx.relpath, a) for a in ALLOWED_FILES):
            return
        aliases = import_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            head, _, tail = dn.partition(".")
            canonical = aliases.get(head)
            if canonical is None:
                continue
            resolved = canonical + ("." + tail if tail else "")
            if resolved in FORBIDDEN:
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"{resolved}() bypasses VirtualClock — use the clock "
                    f"(or util.clock.monotonic_now/wall_now for infra "
                    f"timing)")
