"""Native-C engine discipline rules (ISSUE 15).

The C engine (native/capply.c, cxdr.c, cquorum.c) computes authoritative
ledger hashes; these rules enforce its own established memory idioms
tree-wide, over the clex.py token/function representation:

  reader-discipline           all XDR consumption goes through the
                              bounds-checked rd_* helpers; raw access to
                              a reader's buffer pointer outside them fires
  memcpy-provenance           every memcpy length is a constant, sizeof-
                              derived, or provably bounded (rd_varopaque/
                              rd_take binding or a matching allocation)
  unchecked-alloc             every malloc/calloc/realloc result is
                              null-checked before first use
  handler-result-discipline   every op_* handler return path writes an op
                              result code into the result Buf (or is the
                              -1 engine-error path) — the C analogue of
                              ledger-txn-paths
  overlay-pairing             per-op / path-hop rollback-overlay pushes
                              (op_active/hop_active = 1) are popped on
                              every return path (CAP-33 sandwich code)

Suppress with ``/* corelint: disable=<rule> -- reason */`` on the
flagged line; suppressions ratchet through LINT_BASELINE.json exactly
like the Python rules.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from ..clex import CFileContext, Tok, call_args, find_calls
from ..core import Rule, Violation

# allocators whose raw result must be null-checked / may size a copy
_ALLOC_FNS = {"PyMem_Malloc", "PyMem_Calloc", "PyMem_Realloc",
              "PyMem_RawMalloc", "malloc", "calloc", "realloc"}
# bounded-buffer constructors: an argument list naming the copied length
# proves the destination was sized by the same expression
_SIZED_FNS = _ALLOC_FNS | {"rb_new", "buf_reserve"}
# op-result writers (handler-result-discipline)
_RESULT_WRITERS = {"res_inner", "res_outer", "sponsorship_error_c",
                   "tx_result_void", "tx_result_ops"}
# calls that reset every rollback-overlay flag (overlay-pairing)
_OVERLAY_RESETTERS = {"eng_rollback_tx"}
_OVERLAY_FLAGS = ("op_active", "hop_active")

_C_KEYWORDS = {
    "if", "else", "for", "while", "do", "switch", "case", "default",
    "return", "goto", "break", "continue", "sizeof", "struct", "union",
    "enum", "typedef", "static", "const", "void", "int", "char", "long",
    "short", "unsigned", "signed", "float", "double", "volatile",
    "register", "extern", "inline",
}

_CONST_PUNCT = {"+", "-", "*", "/", "%", "(", ")", "<<", ">>",
                "&", "|", "^", "~"}


class _CRule(Rule):
    """Base: dispatch only on lexed C files."""

    language = "c"


def _texts(toks: List[Tok]) -> List[str]:
    return [t.text for t in toks]


def _is_subseq(needle: List[str], hay: List[str]) -> bool:
    n = len(needle)
    if n == 0:
        return False
    return any(hay[i:i + n] == needle for i in range(len(hay) - n + 1))


def _is_member_chain(toks: List[Tok]) -> bool:
    """`x`, `x->y`, `x.y->z` — a single lvalue chain."""
    if not toks or toks[0].kind != "name":
        return False
    expect_name = False
    for t in toks[1:]:
        if expect_name:
            if t.kind != "name":
                return False
            expect_name = False
        elif t.kind == "punct" and t.text in ("->", "."):
            expect_name = True
        else:
            return False
    return not expect_name


def _is_const_expr(toks: List[Tok]) -> bool:
    """Numbers and arithmetic punctuation only (`4`, `4 + 32`, `40 + n`
    is NOT const)."""
    if not toks:
        return False
    for t in toks:
        if t.kind == "num":
            continue
        if t.kind == "punct" and t.text in _CONST_PUNCT:
            continue
        return False
    return True


def _split_ternary(toks: List[Tok]) -> Optional[Tuple[List[Tok], List[Tok]]]:
    """For a top-level `c ? a : b` return (a, b) else None."""
    depth = 0
    qpos = -1
    for i, t in enumerate(toks):
        if t.kind != "punct":
            continue
        if t.text in ("(", "["):
            depth += 1
        elif t.text in (")", "]"):
            depth -= 1
        elif t.text == "?" and depth == 0:
            qpos = i
            break
    if qpos < 0:
        return None
    depth = 0
    for i in range(qpos + 1, len(toks)):
        t = toks[i]
        if t.kind != "punct":
            continue
        if t.text in ("(", "["):
            depth += 1
        elif t.text in (")", "]"):
            depth -= 1
        elif t.text == ":" and depth == 0:
            return toks[qpos + 1:i], toks[i + 1:]
    return None


# ---------------------------------------------------------------------------
# reader-discipline
# ---------------------------------------------------------------------------

class ReaderDisciplineRule(_CRule):
    id = "reader-discipline"
    description = "XDR reader buffers consumed only via rd_* helpers " \
                  "(no raw `.p` pointer arithmetic outside them)"

    def check(self, ctx) -> Iterator[Violation]:
        if not isinstance(ctx, CFileContext):
            return
        for fn in ctx.functions:
            if fn.name.startswith("rd_"):
                continue            # the helpers ARE the blessed accessors
            rd_vars = fn.param_names_of_type("Rd") \
                | fn.local_names_of_type("Rd")
            if not rd_vars:
                continue
            body = fn.body
            for i, t in enumerate(body):
                if t.kind != "name" or t.text not in rd_vars:
                    continue
                if i + 2 < len(body) \
                        and body[i + 1].kind == "punct" \
                        and body[i + 1].text in (".", "->") \
                        and body[i + 2].kind == "name" \
                        and body[i + 2].text == "p" \
                        and (i == 0 or body[i - 1].text
                             not in (".", "->")):
                    yield Violation(
                        self.id, ctx.relpath, t.line, t.col,
                        f"raw access to XDR reader buffer "
                        f"`{t.text}{body[i + 1].text}p` in {fn.name}() — "
                        f"consume via the bounds-checked rd_take/"
                        f"rd_varopaque helpers")


# ---------------------------------------------------------------------------
# memcpy-provenance
# ---------------------------------------------------------------------------

class MemcpyProvenanceRule(_CRule):
    id = "memcpy-provenance"
    description = "memcpy lengths are constants, sizeof-derived, or " \
                  "bounded by a preceding rd_varopaque/rd_take or " \
                  "matching allocation"

    def check(self, ctx) -> Iterator[Violation]:
        if not isinstance(ctx, CFileContext):
            return
        for fn in ctx.functions:
            body = fn.body
            for idx, _name in find_calls(body, {"memcpy"}):
                args = call_args(body, idx + 1)
                if len(args) != 3:
                    continue        # macro-ish or variadic: out of scope
                length = args[2]
                if self._length_ok(length, body, idx):
                    continue
                t = body[idx]
                yield Violation(
                    self.id, ctx.relpath, t.line, t.col,
                    f"memcpy length `{' '.join(_texts(length))}` in "
                    f"{fn.name}() is neither constant, sizeof-derived, "
                    f"nor bounded by a preceding rd_varopaque/rd_take "
                    f"or same-length allocation in this function")

    def _length_ok(self, length: List[Tok], body: List[Tok],
                   call_idx: int) -> bool:
        if any(t.kind == "name" and t.text == "sizeof" for t in length):
            return True
        if _is_const_expr(length):
            return True
        arms = _split_ternary(length)
        if arms is not None and _is_const_expr(arms[0]) \
                and _is_const_expr(arms[1]):
            return True
        if not _is_member_chain(length):
            return False
        want = _texts(length)
        # provenance scan over the tokens BEFORE this memcpy
        prefix = body[:call_idx]
        for i, name in find_calls(prefix, {"rd_varopaque", "rd_take"}
                                  | _SIZED_FNS):
            args = call_args(prefix, i + 1)
            if name == "rd_varopaque":
                # rd_varopaque(r, MAX, &len): the out-param IS the bound
                if len(args) == 3 and _texts(args[2]) == ["&"] + want:
                    return True
            elif name == "rd_take":
                # rd_take(r, n) bounds n bytes of the source
                if len(args) == 2 and _texts(args[1]) == want:
                    return True
            else:
                # destination sized by the same expression
                flat: List[str] = []
                for a in args:
                    flat.extend(_texts(a))
                    flat.append(",")
                if _is_subseq(want, flat):
                    return True
        return False


# ---------------------------------------------------------------------------
# unchecked-alloc
# ---------------------------------------------------------------------------

class UncheckedAllocRule(_CRule):
    id = "unchecked-alloc"
    description = "every malloc/calloc/realloc result is null-checked " \
                  "before first use"

    def check(self, ctx) -> Iterator[Violation]:
        if not isinstance(ctx, CFileContext):
            return
        for fn in ctx.functions:
            body = fn.body
            for idx, name in find_calls(body, _ALLOC_FNS):
                t = body[idx]
                lv = self._lvalue_before(body, idx)
                if lv is None:
                    yield Violation(
                        self.id, ctx.relpath, t.line, t.col,
                        f"{name}() result in {fn.name}() is not stored "
                        f"in a checkable lvalue — assign it and "
                        f"null-check before use")
                    continue
                problem = self._first_use_unchecked(body, idx, lv)
                if problem:
                    yield Violation(
                        self.id, ctx.relpath, t.line, t.col,
                        f"{name}() result `{' '.join(lv)}` in "
                        f"{fn.name}() is {problem}")

    @staticmethod
    def _lvalue_before(body: List[Tok], idx: int) -> Optional[List[str]]:
        """For `<lvalue> = alloc(...)` return the lvalue token texts."""
        if idx == 0 or body[idx - 1].text != "=":
            return None
        j = idx - 2
        chain: List[str] = []
        while j >= 0:
            t = body[j]
            if t.kind == "name" or (t.kind == "punct"
                                    and t.text in (".", "->")):
                chain.append(t.text)
                j -= 1
                continue
            break
        chain.reverse()
        if not chain or chain[0] in ("->", "."):
            return None
        return chain

    @staticmethod
    def _first_use_unchecked(body: List[Tok], call_idx: int,
                             lv: List[str]) -> Optional[str]:
        # skip to the end of the allocation statement
        depth = 0
        i = call_idx
        while i < len(body):
            x = body[i].text
            if body[i].kind == "punct":
                if x in ("(", "[", "{"):
                    depth += 1
                elif x in (")", "]", "}"):
                    depth -= 1
                elif x == ";" and depth == 0:
                    break
            i += 1
        i += 1
        n = len(lv)
        texts = [t.text for t in body]
        while i < len(body) - n + 1:
            if texts[i:i + n] == lv:
                # a longer member chain starting with the same prefix is
                # a USE of the object, not the pointer check we need —
                # unless guarded by `!` / `== NULL` / `!= NULL`
                prev = body[i - 1].text if i > 0 else ""
                nxt = body[i + n].text if i + n < len(body) else ""
                nxt2 = body[i + n + 1].text if i + n + 1 < len(body) else ""
                if prev in (".", "->"):
                    i += 1
                    continue        # member of a different chain
                if prev == "!" and nxt not in (".", "->"):
                    return None
                if nxt in ("==", "!=") and nxt2 in ("NULL", "0"):
                    return None
                # plain truthiness guards: `if (p)`, `while (p)`,
                # `if (x || p)`, `p ? a : b` — but NOT `f(p)`, which is
                # a use (prev '(' only counts under an if/while keyword)
                prev2 = body[i - 2].text if i > 1 else ""
                if prev == "(" and prev2 in ("if", "while") \
                        and nxt not in (".", "->", "["):
                    return None
                if prev in ("&&", "||") and nxt not in (".", "->", "["):
                    return None
                if nxt == "?":
                    return None
                return "used before a null check " \
                       f"(first use at line {body[i].line})"
            i += 1
        return "never null-checked in this function"


# ---------------------------------------------------------------------------
# handler-result-discipline
# ---------------------------------------------------------------------------

class HandlerResultRule(_CRule):
    id = "handler-result-discipline"
    description = "every op_* handler return path writes an op result " \
                  "code into the result Buf (or returns -1 engine error)"

    # A "result write" is a res_* writer call OR any call that receives
    # the handler's result-Buf parameter (delegation: store_trustline,
    # apply_manage_c, convert_hop_c and the success-arm buf_* writes all
    # take `rb`).  A return path is clean when its expression contains a
    # write / a write-derived variable / is the `-1` engine-error path;
    # a bare-constant return is additionally accepted when a result
    # write appears textually earlier in the function (the success-arm
    # idiom: write the arm, then `return 1;`).  That prefix check is
    # path-INsensitive by design — a branch-local miss needs the runtime
    # differential tier; this rule catches the structural omission.

    def check(self, ctx) -> Iterator[Violation]:
        if not isinstance(ctx, CFileContext):
            return
        for fn in ctx.functions:
            if not fn.name.startswith("op_"):
                continue
            bufs = fn.param_names_of_type("Buf")
            if not bufs:
                continue            # no result buffer: not a handler
            written_vars = self._result_vars(fn.body, bufs)
            for expr, line, col, idx in self._returns(fn.body):
                if self._return_ok(expr, written_vars, bufs):
                    continue
                if self._writes_result(fn.body[:idx], bufs):
                    continue        # success-arm idiom: write, then return
                yield Violation(
                    self.id, ctx.relpath, line, col,
                    f"{fn.name}() returns `{' '.join(_texts(expr))}` "
                    f"without writing an op result — every early-return "
                    f"path must res_inner() into the result Buf or "
                    f"return -1 (engine error)")

    @staticmethod
    def _writes_result(toks: List[Tok], bufs: Set[str]) -> bool:
        """True when `toks` contain a result write: a writer-helper call
        or any call taking the result Buf as an argument."""
        for i, t in enumerate(toks):
            if t.kind != "name" or i + 1 >= len(toks) \
                    or toks[i + 1].text != "(":
                continue
            if t.text in _RESULT_WRITERS:
                return True
            for arg in call_args(toks, i + 1):
                if any(a.kind == "name" and a.text in bufs
                       and (k == 0 or arg[k - 1].text not in (".", "->"))
                       for k, a in enumerate(arg)):
                    return True
        return False

    def _result_vars(self, body: List[Tok], bufs: Set[str]) -> Set[str]:
        """Variables assigned from a result-writing expression
        (`rc = res_inner(...)`, `rc2 = payment_tl_side(e, rb, ...)`),
        one transitive hop per pass."""
        out: Set[str] = set()
        for _pass in range(3):
            grew = False
            for i, t in enumerate(body):
                if t.kind != "name" or i + 1 >= len(body) \
                        or body[i + 1].text != "=":
                    continue
                j = i + 2
                rhs: List[Tok] = []
                depth = 0
                while j < len(body):
                    x = body[j]
                    if x.kind == "punct":
                        if x.text in ("(", "[", "{"):
                            depth += 1
                        elif x.text in (")", "]", "}"):
                            depth -= 1
                        elif x.text == ";" and depth == 0:
                            break
                    rhs.append(x)
                    j += 1
                if t.text in out:
                    continue
                if any(r.kind == "name" and r.text in out for r in rhs) \
                        or self._writes_result(rhs, bufs):
                    out.add(t.text)
                    grew = True
            if not grew:
                break
        return out

    @staticmethod
    def _returns(body: List[Tok]) \
            -> Iterator[Tuple[List[Tok], int, int, int]]:
        i = 0
        while i < len(body):
            t = body[i]
            if t.kind == "name" and t.text == "return":
                j = i + 1
                expr: List[Tok] = []
                depth = 0
                while j < len(body):
                    x = body[j]
                    if x.kind == "punct":
                        if x.text in ("(", "[", "{"):
                            depth += 1
                        elif x.text in (")", "]", "}"):
                            depth -= 1
                        elif x.text == ";" and depth == 0:
                            break
                    expr.append(x)
                    j += 1
                yield expr, t.line, t.col, i
                i = j
            i += 1

    def _return_ok(self, expr: List[Tok], written_vars: Set[str],
                   bufs: Set[str]) -> bool:
        if _texts(expr) == ["-", "1"]:
            return True             # engine-error path: caller aborts tx
        for t in expr:
            if t.kind == "name" and t.text in written_vars:
                return True
        return self._writes_result(expr, bufs)


# ---------------------------------------------------------------------------
# overlay-pairing
# ---------------------------------------------------------------------------

# statement-tree nodes for the path simulation
_TERMINATORS = ("return", "goto", "break", "continue")


class OverlayPairingRule(_CRule):
    id = "overlay-pairing"
    description = "rollback-overlay pushes (op_active/hop_active = 1) " \
                  "balance with a pop on every return path"

    def check(self, ctx) -> Iterator[Violation]:
        if not isinstance(ctx, CFileContext):
            return
        for fn in ctx.functions:
            if not self._pushes_overlay(fn.body):
                continue
            try:
                nodes, _ = self._parse_block(fn.body, 0, len(fn.body))
            except IndexError:
                continue            # malformed body: lexer already errs
            found: Set[Tuple[int, int, str]] = set()
            self._eval(nodes, frozenset({(0, 0)}), found, [])
            for line, col, flag in sorted(found):
                yield Violation(
                    self.id, ctx.relpath, line, col,
                    f"{fn.name}() can return with the {flag} rollback "
                    f"overlay still pushed — every return path must "
                    f"reset {flag} = 0 (or eng_rollback_tx) first")

    @staticmethod
    def _pushes_overlay(body: List[Tok]) -> bool:
        for i, t in enumerate(body):
            if t.kind == "name" and t.text in _OVERLAY_FLAGS \
                    and i + 2 < len(body) and body[i + 1].text == "=" \
                    and body[i + 2].text == "1":
                return True
        return False

    # -- statement-tree parser ------------------------------------------

    def _parse_block(self, toks: List[Tok], i: int, end: int):
        nodes = []
        while i < end:
            node, i = self._parse_stmt(toks, i, end)
            if node is not None:
                nodes.append(node)
        return nodes, i

    def _parse_stmt(self, toks: List[Tok], i: int, end: int):
        t = toks[i]
        if t.kind == "punct" and t.text == ";":
            return None, i + 1
        if t.kind == "punct" and t.text == "{":
            close = self._match(toks, i, end)
            nodes, _ = self._parse_block(toks, i + 1, close)
            return ("block", nodes), close + 1
        if t.kind == "name":
            kw = t.text
            if kw == "if":
                cclose = self._match(toks, i + 1, end)
                then, i2 = self._parse_stmt(toks, cclose + 1, end)
                els = None
                if i2 < end and toks[i2].kind == "name" \
                        and toks[i2].text == "else":
                    els, i2 = self._parse_stmt(toks, i2 + 1, end)
                return ("if", then, els), i2
            if kw in ("for", "while"):
                cclose = self._match(toks, i + 1, end)
                body, i2 = self._parse_stmt(toks, cclose + 1, end)
                return ("loop", body), i2
            if kw == "do":
                body, i2 = self._parse_stmt(toks, i + 1, end)
                # consume `while ( ... ) ;`
                if i2 < end and toks[i2].text == "while":
                    cclose = self._match(toks, i2 + 1, end)
                    i2 = cclose + 1
                    if i2 < end and toks[i2].text == ";":
                        i2 += 1
                return ("loop", body), i2
            if kw == "switch":
                cclose = self._match(toks, i + 1, end)
                body, i2 = self._parse_stmt(toks, cclose + 1, end)
                return ("switch", body), i2
            if kw in ("case", "default"):
                j = i + 1
                depth = 0
                while j < end:
                    x = toks[j]
                    if x.kind == "punct":
                        if x.text in ("(", "["):
                            depth += 1
                        elif x.text in (")", "]"):
                            depth -= 1
                        elif x.text == ":" and depth == 0:
                            break
                    j += 1
                return None, j + 1
            if kw in _TERMINATORS:
                j = i + 1
                depth = 0
                while j < end:
                    x = toks[j]
                    if x.kind == "punct":
                        if x.text in ("(", "[", "{"):
                            depth += 1
                        elif x.text in (")", "]", "}"):
                            depth -= 1
                        elif x.text == ";" and depth == 0:
                            break
                    j += 1
                return (kw, toks[i:j], t.line, t.col), j + 1
            # label? `name :` at statement start (not `? :` ternary)
            if i + 1 < end and toks[i + 1].kind == "punct" \
                    and toks[i + 1].text == ":" \
                    and kw not in _C_KEYWORDS:
                return None, i + 2
        # simple statement: consume to ';' at depth 0
        j = i
        depth = 0
        while j < end:
            x = toks[j]
            if x.kind == "punct":
                if x.text in ("(", "[", "{"):
                    depth += 1
                elif x.text in (")", "]", "}"):
                    depth -= 1
                elif x.text == ";" and depth == 0:
                    break
            j += 1
        return ("simple", toks[i:j]), j + 1

    @staticmethod
    def _match(toks: List[Tok], open_idx: int, end: int) -> int:
        """Index of the close matching the opener at open_idx (which
        must be '(' or '{')."""
        opener = toks[open_idx].text
        close = {"(": ")", "{": "}"}[opener]
        depth = 1
        j = open_idx + 1
        while j < end:
            x = toks[j]
            if x.kind == "punct":
                if x.text == opener:
                    depth += 1
                elif x.text == close:
                    depth -= 1
                    if depth == 0:
                        return j
            j += 1
        raise IndexError("unmatched bracket")

    # -- path simulation -------------------------------------------------

    def _eval(self, nodes, state: FrozenSet[Tuple[int, int]],
              found: Set[Tuple[int, int, str]], break_stack) \
            -> Tuple[FrozenSet[Tuple[int, int]], bool]:
        """Returns (out_state, terminated)."""
        for node in nodes:
            state, term = self._eval_node(node, state, found, break_stack)
            if term:
                return state, True
        return state, False

    def _eval_node(self, node, state, found, break_stack):
        kind = node[0]
        if kind == "simple":
            return self._apply_effects(node[1], state), False
        if kind == "block":
            return self._eval(node[1], state, found, break_stack)
        if kind == "if":
            then_s, then_t = self._eval_opt(node[1], state, found,
                                            break_stack)
            else_s, else_t = self._eval_opt(node[2], state, found,
                                            break_stack)
            outs = set()
            if not then_t:
                outs |= then_s
            if not else_t:
                outs |= else_s
            if then_t and else_t:
                return state, True
            return frozenset(outs), False
        if kind in ("loop", "switch"):
            break_stack.append(set())
            s1, t1 = self._eval_opt(node[1], state, found, break_stack)
            merged = set(state)
            if not t1:
                merged |= s1
            if kind == "loop":
                s2, t2 = self._eval_opt(node[1], frozenset(merged), found,
                                        break_stack)
                if not t2:
                    merged |= s2
            merged |= break_stack.pop()
            return frozenset(merged), False
        if kind in ("return", "goto"):
            _, toks, line, col = node
            for st in state:
                for flag, val in zip(_OVERLAY_FLAGS, st):
                    if val == 1:
                        found.add((line, col, flag))
            return state, True
        if kind in ("break", "continue"):
            _, toks, line, col = node
            if break_stack:
                break_stack[-1] |= set(state)
            return state, True
        return state, False

    def _eval_opt(self, node, state, found, break_stack):
        if node is None:
            return state, False
        return self._eval_node(node, state, found, break_stack)

    @staticmethod
    def _apply_effects(toks: List[Tok],
                       state: FrozenSet[Tuple[int, int]]):
        sets: Dict[str, Optional[int]] = {}
        for i, t in enumerate(toks):
            if t.kind == "name" and t.text in _OVERLAY_RESETTERS \
                    and i + 1 < len(toks) and toks[i + 1].text == "(":
                for f in _OVERLAY_FLAGS:
                    sets[f] = 0
            if t.kind == "name" and t.text in _OVERLAY_FLAGS \
                    and i + 1 < len(toks) and toks[i + 1].kind == "punct" \
                    and toks[i + 1].text == "=":
                # chained assigns end with the final value token
                last = toks[-1]
                if last.kind == "num" and last.text in ("0", "1"):
                    sets[t.text] = int(last.text)
                else:
                    sets[t.text] = None       # unknown: both values
        if not sets:
            return state
        out = set()
        for op_v, hop_v in state:
            vals = {"op_active": [op_v], "hop_active": [hop_v]}
            for f, v in sets.items():
                vals[f] = [0, 1] if v is None else [v]
            for a in vals["op_active"]:
                for b in vals["hop_active"]:
                    out.add((a, b))
        return frozenset(out)


NATIVE_C_RULE_CLASSES = (
    ReaderDisciplineRule,
    MemcpyProvenanceRule,
    UncheckedAllocRule,
    HandlerResultRule,
    OverlayPairingRule,
)
