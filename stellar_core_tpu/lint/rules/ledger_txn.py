"""ledger-txn-paths: every constructed LedgerTxn reaches commit/rollback.

A `LedgerTxn` bound to a name (not used as a context manager) must be
closed — `x.commit()` or `x.rollback()` — on every explicit control-flow
path that leaves the enclosing function (fall-off-end, `return`,
`raise`).  The reference enforces this at runtime (LedgerTxn's
assert-on-close / sealing discipline); this rule makes the common bug —
an early `return` that forgets the rollback — a compile-time failure.

Modeled flow: if/elif/else, while/for (+ break/continue), with,
try/except/else/finally, return, raise.  Implicit exceptions (any call
can raise) are NOT modeled — demanding try/finally around every
statement would drown the tree; the nested-txn runtime assertions still
cover that class.

Recognized closers beyond direct `x.commit()` / `x.rollback()`:
  * `return x` / `self.attr = x` — ownership escapes the function;
  * `if x._open: x.rollback()`    — the guard implies closed-after;
  * calls to a nested function defined in the same scope whose body
    closes `x` (the `use_pool()`-closure pattern in offer_ops).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from ..core import FileContext, Rule, Violation

# exit kinds propagated by the abstract interpreter
FALL, RETURN, RAISE, BREAK, CONTINUE = range(5)

Exit = Tuple[int, bool, int]  # (kind, closed, lineno)


def _dedup(exits: List[Exit]) -> List[Exit]:
    """Collapse to one exit per (kind, closed), keeping the earliest
    line: the analysis carries ONE bit of state, so sequential branches
    would otherwise multiply paths 2^n and hang the gate."""
    best: dict = {}
    for kind, cl, ln in exits:
        key = (kind, cl)
        if key not in best or ln < best[key]:
            best[key] = ln
    return [(k, c, ln) for (k, c), ln in best.items()]


def _is_ledger_txn_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = f.id if isinstance(f, ast.Name) else \
        f.attr if isinstance(f, ast.Attribute) else None
    return name == "LedgerTxn"


def _is_close_call(node: ast.AST, var: str, closers: Set[str]) -> bool:
    """`var.commit()` / `var.rollback()` / `closer_fn()`."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in ("commit", "rollback") \
            and isinstance(f.value, ast.Name) and f.value.id == var:
        return True
    return isinstance(f, ast.Name) and f.id in closers


def _expr_closes(node: Optional[ast.AST], var: str,
                 closers: Set[str]) -> bool:
    """True when evaluating this expression CERTAINLY closes var: a close
    call in a position that is unconditionally evaluated.  Conditional
    positions — `ok and x.commit()`, `x.commit() if ok else None`, chained
    comparison tails, lambda/comprehension bodies (deferred) — don't
    count."""
    if node is None:
        return False
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if _is_close_call(n, var, closers):
            return True
        if isinstance(n, ast.BoolOp):
            stack.append(n.values[0])  # later operands may short-circuit
        elif isinstance(n, ast.IfExp):
            stack.append(n.test)  # only the test always evaluates
        elif isinstance(n, ast.Compare):
            stack.append(n.left)
            if n.comparators:
                stack.append(n.comparators[0])  # later ones short-circuit
        elif isinstance(n, (ast.Lambda, ast.ListComp, ast.SetComp,
                            ast.DictComp, ast.GeneratorExp)):
            pass  # deferred / possibly-zero-iteration bodies
        else:
            stack.extend(ast.iter_child_nodes(n))
    return False


def _open_guard_target(test: ast.AST) -> Optional[str]:
    """`if x._open:` -> "x" (the closed-state guard special case)."""
    if isinstance(test, ast.Attribute) and test.attr == "_open" \
            and isinstance(test.value, ast.Name):
        return test.value.id
    return None


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = t.elts if isinstance(t, ast.Tuple) else [t]
    return any(isinstance(n, ast.Name)
               and n.id in ("Exception", "BaseException") for n in names)


class _PathAnalyzer:
    """Abstract interpretation of a statement list with one bit of state:
    has the tracked txn been closed on this path."""

    def __init__(self, var: str, closers: Set[str]):
        self.var = var
        self.closers = closers

    def run(self, stmts: List[ast.stmt], closed: bool,
            entry_line: int = 0) -> List[Exit]:
        exits: List[Exit] = []
        cur: List[Exit] = [(FALL, closed, entry_line)]
        for st in stmts:
            nxt: List[Exit] = []
            for kind, cl, ln in cur:
                if kind != FALL:
                    exits.append((kind, cl, ln))
                else:
                    nxt.extend(self.stmt(st, cl))
            cur = _dedup(nxt)
            if not cur:
                break
        exits.extend(cur)
        return _dedup(exits)

    def stmt(self, st: ast.stmt, closed: bool) -> List[Exit]:
        ln = st.lineno
        if isinstance(st, ast.Return):
            if isinstance(st.value, ast.Name) and st.value.id == self.var:
                closed = True  # ownership transferred to the caller
            elif _expr_closes(st.value, self.var, self.closers):
                closed = True
            return [(RETURN, closed, ln)]
        if isinstance(st, ast.Raise):
            if _expr_closes(st.exc, self.var, self.closers):
                closed = True
            return [(RAISE, closed, ln)]
        if isinstance(st, ast.Break):
            return [(BREAK, closed, ln)]
        if isinstance(st, ast.Continue):
            return [(CONTINUE, closed, ln)]
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            return [(FALL, closed, ln)]  # a definition, not execution
        if isinstance(st, (ast.Expr, ast.Assign, ast.AugAssign,
                           ast.AnnAssign)):
            if _expr_closes(getattr(st, "value", None), self.var,
                            self.closers):
                closed = True
            if isinstance(st, ast.Assign) \
                    and isinstance(st.value, ast.Name) \
                    and st.value.id == self.var \
                    and any(isinstance(t, ast.Attribute)
                            for t in st.targets):
                closed = True  # stored into longer-lived state: escapes
                # (a plain local alias is NOT an escape — it stays
                # untracked and conservatively unclosed)
            return [(FALL, closed, ln)]
        if isinstance(st, ast.If):
            if _open_guard_target(st.test) == self.var:
                # `if x._open:` — the then-branch runs with the txn open
                # (whatever the body does is analyzed normally); the
                # else/fall-through path implies it is already closed
                outs = self.run(st.body, False, ln)
                outs += self.run(st.orelse, True, ln) if st.orelse \
                    else [(FALL, True, ln)]
                return outs
            outs = self.run(st.body, closed, ln)
            outs += self.run(st.orelse, closed, ln) if st.orelse \
                else [(FALL, closed, ln)]
            return outs
        if isinstance(st, (ast.While, ast.For, ast.AsyncFor)):
            body_exits = self.run(st.body, closed, ln)
            # return/raise escape the loop with their own state; break
            # falls through to after-loop carrying its path's state
            outs = [(k, c, l) for k, c, l in body_exits
                    if k in (RETURN, RAISE)]
            outs += [(FALL, c, l) for k, c, l in body_exits if k == BREAK]
            # zero-iteration/condition-exhausted path runs orelse then
            # falls through with the entry state — except `while True`,
            # which only ever leaves via break/return/raise
            infinite = isinstance(st, ast.While) \
                and isinstance(st.test, ast.Constant) and bool(st.test.value)
            if not infinite:
                outs += self.run(st.orelse, closed, ln) if st.orelse \
                    else [(FALL, closed, ln)]
            return outs
        if isinstance(st, (ast.With, ast.AsyncWith)):
            entry = closed
            for item in st.items:
                ce = item.context_expr
                if isinstance(ce, ast.Name) and ce.id == self.var:
                    entry = True  # `with x:` — the CM protocol closes it
                elif _expr_closes(ce, self.var, self.closers):
                    entry = True
            return self.run(st.body, entry, ln)
        if isinstance(st, ast.Try):
            return self.try_stmt(st, closed)
        return [(FALL, closed, ln)]

    def try_stmt(self, st: ast.Try, closed: bool) -> List[Exit]:
        # does the finally block unconditionally close the txn?
        fin_closes = False
        if st.finalbody:
            fexits = self.run(st.finalbody, False, st.lineno)
            falls = [c for k, c, _ in fexits if k == FALL]
            fin_closes = bool(falls) and all(falls)

        # a catch-all handler absorbs the body's explicit raises (the
        # handler paths below model what happens next); typed handlers
        # may not match, so the raise also stays a possible exit
        catch_all = any(_is_catch_all(h) for h in st.handlers)
        body_exits = self.run(st.body, closed, st.lineno)
        outs: List[Exit] = []
        for kind, cl, ln in body_exits:
            if kind == FALL and st.orelse:
                outs.extend(self.run(st.orelse, cl, ln))
            elif kind == RAISE and catch_all:
                pass  # caught: continues in a handler path
            else:
                outs.append((kind, cl, ln))
        # handlers enter with the pessimistic entry state: the exception
        # may have struck before any close in the body ran
        for h in st.handlers:
            outs.extend(self.run(h.body, closed, h.lineno))
        if fin_closes:
            outs = [(k, True, ln) for k, _, ln in outs]
        return outs


def _nested_closers(fn: ast.AST, var: str) -> Set[str]:
    """Names of nested functions whose body closes `var` (closure over
    the outer binding)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            params = {a.arg for a in node.args.args}
            if var in params:
                continue  # shadowed: operates on its own parameter
            if any(_is_close_call(sub, var, set())
                   for sub in ast.walk(node)):
                out.add(node.name)
    return out


def _direct_body_walk(fn: ast.AST) -> Iterator[Tuple[List[ast.stmt],
                                                     ast.stmt]]:
    """(containing_block, stmt) for every statement in `fn`, NOT
    descending into nested function/class definitions."""
    stack: List[List[ast.stmt]] = [fn.body]
    while stack:
        blk = stack.pop()
        for st in blk:
            yield blk, st
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue
            for fld in ("body", "orelse", "finalbody"):
                sub = getattr(st, fld, None)
                if isinstance(sub, list) and sub and \
                        isinstance(sub[0], ast.stmt):
                    stack.append(sub)
            for h in getattr(st, "handlers", []):
                stack.append(h.body)


class LedgerTxnPathsRule(Rule):
    id = "ledger-txn-paths"
    description = ("a LedgerTxn bound to a name must reach commit()/"
                   "rollback() on every control-flow path (or be used "
                   "as a context manager)")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        for fn in ast.walk(ctx.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_fn(ctx, fn)

    def _check_fn(self, ctx: FileContext, fn: ast.AST):
        for blk, st in _direct_body_walk(fn):
            if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                    and isinstance(st.targets[0], ast.Name) \
                    and _is_ledger_txn_call(st.value):
                var = st.targets[0].id
            elif isinstance(st, ast.AnnAssign) \
                    and isinstance(st.target, ast.Name) \
                    and st.value is not None \
                    and _is_ledger_txn_call(st.value):
                var = st.target.id
            else:
                continue
            closers = _nested_closers(fn, var)
            analyzer = _PathAnalyzer(var, closers)
            idx = blk.index(st)
            exits = analyzer.run(blk[idx + 1:], False, st.lineno)
            # the binding may sit inside a nested block (e.g. an if arm or
            # a try body): FALL exits then continue into the enclosing
            # flow, and RAISE exits may be caught by enclosing handlers —
            # neither is visible to this block-local analysis.  Only flag
            # exits that certainly leave the function: RETURN always, plus
            # FALL/RAISE when the block IS the function body.
            top_level = blk is fn.body
            bad = [ln for k, c, ln in exits if not c
                   and (k == RETURN
                        or (top_level and k in (FALL, RAISE)))]
            if bad:
                yield Violation(
                    self.id, ctx.relpath, st.lineno, st.col_offset,
                    f"LedgerTxn '{var}' can leave the function without "
                    f"commit()/rollback() (path exiting near line "
                    f"{min(bad)}); close it on every path or use "
                    f"`with LedgerTxn(...)`")
