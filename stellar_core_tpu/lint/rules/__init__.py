"""corelint rule catalogue — one module per repo invariant."""

from __future__ import annotations

from typing import List

from ..core import Rule
from .clock import ClockDisciplineRule
from .decode_free import DecodeFreeSeamRule
from .determinism import (FloatDisciplineRule, HashOrderRule,
                          IterationOrderRule, RngDisciplineRule)
from .eventlog import EventlogPartitionRule
from .exceptions import ExceptionHygieneRule
from .ledger_txn import LedgerTxnPathsRule
from .lock_order import LockOrderRule
from .metric_names import MetricRegistryRule
from .native_c import NATIVE_C_RULE_CLASSES
from .thread_safety import RawLockRule, ThreadSafetyRule

ALL_RULE_CLASSES = (
    ClockDisciplineRule,
    LedgerTxnPathsRule,
    DecodeFreeSeamRule,
    ExceptionHygieneRule,
    MetricRegistryRule,
    EventlogPartitionRule,
    LockOrderRule,
    ThreadSafetyRule,
    RawLockRule,
    IterationOrderRule,
    FloatDisciplineRule,
    HashOrderRule,
    RngDisciplineRule,
) + NATIVE_C_RULE_CLASSES


def all_rules() -> List[Rule]:
    return [cls() for cls in ALL_RULE_CLASSES]


def rules_by_id(ids) -> List[Rule]:
    wanted = set(ids)
    known = {cls.id: cls for cls in ALL_RULE_CLASSES}
    unknown = wanted - set(known)
    if unknown:
        raise KeyError(f"unknown rule id(s): {sorted(unknown)}")
    return [known[i]() for i in sorted(wanted)]
