"""Determinism discipline: consensus-path rules.

stellar-core is a deterministic replicated state machine — every
validator must derive bit-identical ledger hashes from the same
externalized values.  The reference bans floats, wall-clock and
unordered iteration anywhere protocol-visible; these four rules make
that ban compile-time-checkable for this repo's consensus modules:

  iteration-order   iterating a set (or hash-keyed view) whose elements
                    flow into XDR encoding, hashing, escaping list
                    construction or broadcast order must go through
                    ``sorted(...)`` or an order-documented structure
  float-discipline  no float literals, ``float()`` or true division on
                    protocol-visible values (fees/thresholds/balances/
                    close times are integer math); metric/log/trace
                    sinks are exempt
  hash-order        no builtin ``hash()`` and no ``id()``-keyed ordering
                    (both are PYTHONHASHSEED/address-sensitive) outside
                    ``__hash__`` protocol methods
  rng-discipline    ``random`` module-level functions and ``os.urandom``
                    only through an injected seeded ``random.Random``

The scope below is THE single declaration of which modules count as
consensus-path (grep CONSENSUS_SCOPE); util/detguard.py is the runtime
complement and simulation/hashseed_diff.py the differential proof.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from ..core import (FileContext, Rule, Violation, dotted_name,
                    import_aliases)

# Single source of truth for "consensus-path" modules.  A file is in
# scope when its repo-relative path contains one of these directory
# prefixes (segment-aware, robust to a --root above the repo root).
CONSENSUS_SCOPE = (
    "stellar_core_tpu/scp/",
    "stellar_core_tpu/herder/",
    "stellar_core_tpu/ledger/",
    "stellar_core_tpu/soroban/",
    "stellar_core_tpu/transactions/",
    "stellar_core_tpu/bucket/",
    "stellar_core_tpu/xdr/",
)

# rng-discipline additionally covers the deterministic simulation layer:
# chaos/loadgen seed-threading (PR 6) is a repo invariant, not a
# consensus-only one.
RNG_EXTRA_SCOPE = (
    "stellar_core_tpu/simulation/",
)


def in_consensus_scope(relpath: str,
                       extra: tuple = ()) -> bool:
    for prefix in CONSENSUS_SCOPE + extra:
        if relpath.startswith(prefix) or ("/" + prefix) in relpath:
            return True
    return False


def _parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _ancestors(node: ast.AST,
               parents: Dict[ast.AST, ast.AST]) -> Iterator[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        yield cur
        cur = parents.get(cur)


# ---------------------------------------------------------------------------
# iteration-order
# ---------------------------------------------------------------------------

# Consuming a whole unordered iterable through one of these builtins is
# order-free (commutative / re-ordering): quiet.
_ORDER_FREE_CONSUMERS = {"sorted", "set", "frozenset", "sum", "min", "max",
                         "any", "all", "len", "dict"}

# A call to a method with one of these names inside the loop body marks
# the iteration order as escaping (list construction, XDR encoding,
# hashing, broadcast).
_ORDER_SINK_ATTRS = {"append", "extend", "insert", "to_xdr", "encode",
                     "pack", "sha256", "digest", "hexdigest", "broadcast",
                     "send_message", "emit_envelope", "flood", "write"}
_ORDER_SINK_NAMES = {"to_xdr", "sha256", "encode_xdr"}


class IterationOrderRule(Rule):
    id = "iteration-order"
    description = ("iterating a set/.keys()/.values()/.items() into an "
                   "order-sensitive sink (escaping list, XDR/hash, "
                   "broadcast) without sorted() in consensus scope")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not in_consensus_scope(ctx.relpath):
            return
        parents = _parent_map(ctx.tree)
        scopes = [ctx.tree] + [
            n for n in ast.walk(ctx.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._check_scope(ctx, scope, parents)

    # -- per-scope analysis -------------------------------------------------

    def _own_nodes(self, scope: ast.AST) -> Iterator[ast.AST]:
        """Walk `scope` without descending into nested function defs
        (those are separate scopes with their own locals)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            n = stack.pop()
            yield n
            if not isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(n))

    def _check_scope(self, ctx: FileContext, scope: ast.AST,
                     parents: Dict[ast.AST, ast.AST]) -> Iterator[Violation]:
        unordered = self._unordered_locals(scope)
        sorted_sinks = self._sorted_consumed_names(scope)
        for node in self._own_nodes(scope):
            if isinstance(node, ast.For):
                why = self._unordered_reason(node.iter, unordered)
                if why is None:
                    continue
                sink = self._body_sink(node, sorted_sinks)
                if sink is None:
                    continue
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"iterating {why} into {sink} — nondeterministic "
                    f"order is protocol-visible; wrap in sorted() or "
                    f"document the ordering and suppress")
            elif isinstance(node, (ast.ListComp, ast.GeneratorExp)):
                for gen in node.generators:
                    why = self._unordered_reason(gen.iter, unordered)
                    if why is None:
                        continue
                    if self._consumed_order_free(node, parents):
                        continue
                    kind = ("list comprehension"
                            if isinstance(node, ast.ListComp)
                            else "generator expression")
                    yield Violation(
                        self.id, ctx.relpath, node.lineno, node.col_offset,
                        f"{kind} over {why} preserves nondeterministic "
                        f"order — wrap the iterable in sorted() or feed "
                        f"an order-free consumer")
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in ("list", "tuple") and node.args:
                why = self._unordered_reason(node.args[0], unordered)
                if why is None:
                    continue
                if self._consumed_order_free(node, parents):
                    continue
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"{node.func.id}() over {why} freezes nondeterministic "
                    f"order — use sorted() instead")

    def _unordered_locals(self, scope: ast.AST) -> Set[str]:
        """Names bound in this scope whose every assignment is an
        unordered (hash-ordered) expression."""
        unordered: Set[str] = set()
        poisoned: Set[str] = set()
        for _ in range(2):  # one propagation round for name = other_name
            for node in self._own_nodes(scope):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if not isinstance(tgt, ast.Name):
                        continue
                    if self._unordered_reason(node.value, unordered):
                        if tgt.id not in poisoned:
                            unordered.add(tgt.id)
                    else:
                        poisoned.add(tgt.id)
                        unordered.discard(tgt.id)
        return unordered

    def _unordered_reason(self, expr: ast.AST,
                          unordered: Set[str]) -> Optional[str]:
        """Why `expr` iterates in hash order, or None if it does not."""
        if isinstance(expr, (ast.Set, ast.SetComp)):
            return "a set literal"
        if isinstance(expr, ast.Name) and expr.id in unordered:
            return f"set-valued local '{expr.id}'"
        if isinstance(expr, ast.BinOp) and isinstance(
                expr.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
            return (self._unordered_reason(expr.left, unordered)
                    or self._unordered_reason(expr.right, unordered))
        if isinstance(expr, ast.Call):
            f = expr.func
            if isinstance(f, ast.Name) and f.id in ("set", "frozenset"):
                return f"{f.id}()"
            if isinstance(f, ast.Attribute) \
                    and f.attr in ("keys", "values", "items", "difference",
                                   "union", "intersection",
                                   "symmetric_difference"):
                # .keys()/.values()/.items() on dicts are insertion-
                # ordered, but in consensus scope that order must be
                # *documented* load-bearing — flag and let the site
                # sort or suppress with the justification.
                return f".{f.attr}() view"
        if isinstance(expr, ast.DictComp):
            for gen in expr.generators:
                why = self._unordered_reason(gen.iter, unordered)
                if why:
                    return f"a dict built over {why}"
        return None

    def _sorted_consumed_names(self, scope: ast.AST) -> Set[str]:
        """Names X for which sorted(X)/X.sort() appears in this scope:
        appends to them are order-free accumulation."""
        out: Set[str] = set()
        for node in self._own_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Name) and f.id == "sorted" and node.args \
                    and isinstance(node.args[0], ast.Name):
                out.add(node.args[0].id)
            elif isinstance(f, ast.Attribute) and f.attr == "sort" \
                    and isinstance(f.value, ast.Name):
                out.add(f.value.id)
        return out

    def _body_sink(self, loop: ast.For,
                   sorted_sinks: Set[str]) -> Optional[str]:
        for node in ast.walk(loop):
            if isinstance(node, (ast.Yield, ast.YieldFrom)):
                return "a yield (caller-visible order)"
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr in _ORDER_SINK_ATTRS:
                if f.attr in ("append", "extend", "insert") \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id in sorted_sinks:
                    continue  # accumulator is sorted afterwards
                return f".{f.attr}()"
            if isinstance(f, ast.Name) and f.id in _ORDER_SINK_NAMES:
                return f"{f.id}()"
        return None

    def _consumed_order_free(self, node: ast.AST,
                             parents: Dict[ast.AST, ast.AST]) -> bool:
        """True when `node` is a direct argument of an order-free
        consumer like sorted()/set()/sum()."""
        parent = parents.get(node)
        return isinstance(parent, ast.Call) \
            and isinstance(parent.func, ast.Name) \
            and parent.func.id in _ORDER_FREE_CONSUMERS \
            and node in parent.args


# ---------------------------------------------------------------------------
# float-discipline
# ---------------------------------------------------------------------------

# Instrument/observability sinks: a float flowing only into these is
# monitoring, not protocol state (same sink model as metric-registry).
_METRIC_SINK_ATTRS = {"inc", "mark", "update", "set_source", "observe",
                      "gauge", "weak_gauge", "timer", "histogram",
                      "debug", "info", "warning", "error", "exception",
                      "critical", "log", "record", "mark_phase", "span",
                      "snapshot", "add_row", "set_slow_threshold"}
_METRIC_SINK_NAMES = {"record", "mark_phase", "span", "clock_anchor"}


class FloatDisciplineRule(Rule):
    id = "float-discipline"
    description = ("float literals / float() / true division producing "
                   "protocol-visible values in consensus scope (metric/"
                   "log/trace sinks exempt)")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not in_consensus_scope(ctx.relpath):
            return
        parents = _parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            kind = self._float_kind(node)
            if kind is None:
                continue
            if self._observability_sink(node, parents):
                continue
            yield Violation(
                self.id, ctx.relpath, node.lineno, node.col_offset,
                f"{kind} in consensus scope — fees/thresholds/balances/"
                f"close times are integer math; use // or scaled ints "
                f"(metric/log sinks are exempt)")

    def _float_kind(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            return f"float literal {node.value!r}"
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "float":
            return "float() conversion"
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            return "true division (/)"
        return None

    def _observability_sink(self, node: ast.AST,
                            parents: Dict[ast.AST, ast.AST]) -> bool:
        for anc in _ancestors(node, parents):
            if isinstance(anc, ast.JoinedStr):
                return True  # string formatting, not protocol state
            if isinstance(anc, ast.Call):
                f = anc.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in _METRIC_SINK_ATTRS:
                    return True
                if isinstance(f, ast.Name) \
                        and f.id in _METRIC_SINK_NAMES:
                    return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False  # crossed the enclosing function: no sink
        return False


# ---------------------------------------------------------------------------
# hash-order
# ---------------------------------------------------------------------------

_ORDERING_CALLS = {"sorted", "min", "max"}


class HashOrderRule(Rule):
    id = "hash-order"
    description = ("builtin hash() or id()-keyed ordering in consensus "
                   "scope — both are PYTHONHASHSEED/address-sensitive")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not in_consensus_scope(ctx.relpath):
            return
        parents = _parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or not isinstance(node.func, ast.Name):
                continue
            if node.func.id == "hash":
                if self._inside_hash_protocol(node, parents):
                    continue
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "builtin hash() is PYTHONHASHSEED-sensitive for "
                    "str/bytes — use sha256 (crypto) or document the "
                    "process-local use and suppress")
            elif node.func.id == "id":
                if not self._is_ordering_use(node, parents):
                    continue
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "id()-keyed ordering depends on allocation addresses "
                    "— order by content or a stable position index")

    def _inside_hash_protocol(self, node: ast.AST,
                              parents: Dict[ast.AST, ast.AST]) -> bool:
        """hash() inside a __hash__ definition is the protocol itself
        (process-local by construction)."""
        for anc in _ancestors(node, parents):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc.name == "__hash__"
        return False

    def _is_ordering_use(self, node: ast.AST,
                         parents: Dict[ast.AST, ast.AST]) -> bool:
        """id() feeding sorted()/min()/max()/.sort() — but an id() used
        as a dict/lookup key (Subscript slice) is identity bookkeeping,
        not ordering."""
        prev = node
        for anc in _ancestors(node, parents):
            if isinstance(anc, ast.Subscript) and anc.slice is prev:
                return False
            if isinstance(anc, ast.Call):
                f = anc.func
                if isinstance(f, ast.Name) and f.id in _ORDERING_CALLS:
                    return True
                if isinstance(f, ast.Attribute) and f.attr == "sort":
                    return True
            if isinstance(anc, ast.Dict):
                return False  # dict key/value: identity bookkeeping
            prev = anc
        return False


# ---------------------------------------------------------------------------
# rng-discipline
# ---------------------------------------------------------------------------

_FORBIDDEN_RNG = {
    "random." + f for f in (
        "random", "randint", "randrange", "choice", "choices", "shuffle",
        "sample", "uniform", "getrandbits", "seed", "gauss",
        "normalvariate", "expovariate", "betavariate", "triangular",
        "vonmisesvariate", "paretovariate", "weibullvariate", "randbytes",
    )
} | {
    "os.urandom",
    "uuid.uuid1",
    "uuid.uuid4",
    "secrets.token_bytes",
    "secrets.token_hex",
    "secrets.token_urlsafe",
    "secrets.randbits",
    "secrets.randbelow",
    "secrets.choice",
    "random.SystemRandom",
}


class RngDisciplineRule(Rule):
    id = "rng-discipline"
    description = ("module-level random.*/os.urandom in consensus or "
                   "simulation scope — randomness must flow through an "
                   "injected seeded random.Random")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if not in_consensus_scope(ctx.relpath, extra=RNG_EXTRA_SCOPE):
            return
        aliases = import_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if dn is None:
                continue
            head, _, tail = dn.partition(".")
            canonical = aliases.get(head)
            if canonical is None:
                continue
            resolved = canonical + ("." + tail if tail else "")
            if resolved in _FORBIDDEN_RNG:
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    f"{resolved}() draws from process-global/OS entropy "
                    f"— thread a seeded random.Random instance instead")
            elif resolved == "random.Random" and not node.args:
                yield Violation(
                    self.id, ctx.relpath, node.lineno, node.col_offset,
                    "random.Random() with no seed is entropy-seeded — "
                    "pass an explicit seed")
