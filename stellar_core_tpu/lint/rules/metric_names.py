"""metric-registry: every statically-visible metric name is canonical.

The runtime lint (tests/test_observability.py) only sees names a
simulated close happens to record; this rule checks every string literal
handed to `registry().timer/meter/gauge/counter/histogram/weak_gauge`
and `perf.scoped_timer` across the whole tree at parse time: it must
match ``layer.subsystem.event`` (METRIC_NAME_RE) and appear in
CANONICAL_METRICS — or, for data-dependent families built with
f-strings, start with a CANONICAL_PREFIXES entry.  Dynamic names
(variables) are skipped; keep those funnels few.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from ..core import FileContext, Rule, Violation, path_is

REGISTRY_METHODS = ("counter", "meter", "timer", "gauge", "histogram",
                    "weak_gauge")
FREE_FUNCS = ("scoped_timer",)

# the metric surface itself and the perf shim pass caller-supplied names
EXEMPT_FILES = (
    "stellar_core_tpu/util/metrics.py",
    "stellar_core_tpu/util/perf.py",
)


def _canonical_tables():
    from ...util.metrics import (CANONICAL_METRICS, CANONICAL_PREFIXES,
                                 METRIC_NAME_RE)
    return CANONICAL_METRICS, CANONICAL_PREFIXES, METRIC_NAME_RE


def _metric_name_arg(node: ast.Call) -> Optional[ast.expr]:
    f = node.func
    named = (isinstance(f, ast.Attribute) and f.attr in REGISTRY_METHODS) \
        or (isinstance(f, ast.Name) and f.id in FREE_FUNCS) \
        or (isinstance(f, ast.Attribute) and f.attr in FREE_FUNCS)
    if not named:
        return None
    if node.args:
        return node.args[0]
    for kw in node.keywords:  # registry().timer(name="...") counts too
        if kw.arg == "name":
            return kw.value
    return None


class MetricRegistryRule(Rule):
    id = "metric-registry"
    description = ("string literals passed to metric constructors must "
                   "match layer.subsystem.event and the canonical list "
                   "in util/metrics.py")

    def check(self, ctx: FileContext) -> Iterator[Violation]:
        if any(path_is(ctx.relpath, e) for e in EXEMPT_FILES):
            return
        canon, prefixes, name_re = _canonical_tables()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            arg = _metric_name_arg(node)
            if arg is None:
                continue
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
                if not name_re.match(name):
                    yield Violation(
                        self.id, ctx.relpath, arg.lineno, arg.col_offset,
                        f"metric name {name!r} does not match "
                        f"layer.subsystem.event")
                elif name not in canon \
                        and not name.startswith(tuple(prefixes)):
                    yield Violation(
                        self.id, ctx.relpath, arg.lineno, arg.col_offset,
                        f"metric name {name!r} is not in CANONICAL_METRICS "
                        f"(util/metrics.py) — document it there and in "
                        f"README §Observability")
            elif isinstance(arg, ast.JoinedStr):
                # f-string family: the literal head must pin a canonical
                # prefix so the data-dependent tail stays namespaced
                head = ""
                if arg.values and isinstance(arg.values[0], ast.Constant):
                    head = str(arg.values[0].value)
                if not head.startswith(tuple(prefixes)):
                    yield Violation(
                        self.id, ctx.relpath, arg.lineno, arg.col_offset,
                        f"f-string metric name (head {head!r}) must start "
                        f"with a CANONICAL_PREFIXES entry")
