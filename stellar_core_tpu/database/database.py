"""sqlite-backed durable node state.

Reference: src/database/Database.{h,cpp} (schema + transactions),
src/main/PersistentState.{h,cpp} (the storestate kv), plus the
ledgerheaders / scphistory / scpquorums / publishqueue tables that
LedgerManagerImpl::loadLastKnownLedger, HerderPersistence and
HistoryManagerImpl read on startup.

The reference runs over soci with postgres or sqlite; stdlib sqlite3 is the
only durable store here.  WAL journaling + NORMAL synchronous matches the
reference's sqlite pragmas (Database::applySchemaUpgrade sets
journal_mode=WAL); every mutation happens inside an explicit transaction
committed by the caller via `commit()` (ledger close calls it once per
close, after bucket files are on disk).
"""

from __future__ import annotations

import sqlite3
from typing import Iterable, List, Optional, Tuple

from .. import xdr as X
from ..util import logging as slog

_log = slog.get("Database")

SCHEMA_VERSION = 1

_SCHEMA = """
CREATE TABLE IF NOT EXISTS storestate (
    statename TEXT PRIMARY KEY,
    state     TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS ledgerheaders (
    ledgerhash TEXT PRIMARY KEY,
    prevhash   TEXT NOT NULL,
    ledgerseq  INTEGER UNIQUE NOT NULL,
    closetime  INTEGER NOT NULL,
    data       BLOB NOT NULL);
CREATE TABLE IF NOT EXISTS scphistory (
    ledgerseq INTEGER NOT NULL,
    envelope  BLOB NOT NULL);
CREATE TABLE IF NOT EXISTS scpquorums (
    qsethash      TEXT PRIMARY KEY,
    lastledgerseq INTEGER NOT NULL,
    qset          BLOB NOT NULL);
CREATE TABLE IF NOT EXISTS publishqueue (
    ledger INTEGER PRIMARY KEY,
    state  TEXT NOT NULL);
CREATE TABLE IF NOT EXISTS storedtxsets (
    hash          TEXT PRIMARY KEY,
    lastledgerseq INTEGER NOT NULL,
    txset         BLOB NOT NULL);
CREATE TABLE IF NOT EXISTS txhistory (
    ledgerseq   INTEGER PRIMARY KEY,
    txentry     BLOB NOT NULL,
    resultentry BLOB NOT NULL);
CREATE TABLE IF NOT EXISTS peers (
    host        TEXT NOT NULL,
    port        INTEGER NOT NULL,
    numfailures INTEGER NOT NULL DEFAULT 0,
    PRIMARY KEY (host, port));
CREATE TABLE IF NOT EXISTS ban (
    nodeid TEXT PRIMARY KEY);
CREATE INDEX IF NOT EXISTS scphistory_seq ON scphistory (ledgerseq);
"""


class PersistentState:
    """storestate keys (reference: PersistentState::Entry)."""
    LAST_CLOSED_LEDGER = "lastclosedledger"
    HISTORY_ARCHIVE_STATE = "historyarchivestate"
    LAST_SCP_DATA = "lastscpdata"
    DATABASE_SCHEMA = "databaseschema"
    NETWORK_PASSPHRASE = "networkpassphrase"


class Database:
    def __init__(self, path: str = ":memory:"):
        self.path = path
        self.conn = sqlite3.connect(path)
        self.conn.execute("PRAGMA journal_mode=WAL")
        self.conn.execute("PRAGMA synchronous=NORMAL")
        self.conn.executescript(_SCHEMA)
        cur = self.get_state(PersistentState.DATABASE_SCHEMA)
        if cur is None:
            self.set_state(PersistentState.DATABASE_SCHEMA,
                           str(SCHEMA_VERSION))
            self.conn.commit()
        elif int(cur) != SCHEMA_VERSION:
            raise RuntimeError(
                f"database schema {cur} != supported {SCHEMA_VERSION}")

    def close(self) -> None:
        self.conn.close()

    def commit(self) -> None:
        self.conn.commit()

    # -- storestate kv ------------------------------------------------------
    def set_state(self, name: str, value: str) -> None:
        self.conn.execute(
            "INSERT INTO storestate (statename, state) VALUES (?, ?) "
            "ON CONFLICT(statename) DO UPDATE SET state = excluded.state",
            (name, value))

    def get_state(self, name: str) -> Optional[str]:
        row = self.conn.execute(
            "SELECT state FROM storestate WHERE statename = ?",
            (name,)).fetchone()
        return row[0] if row else None

    # -- ledger headers ------------------------------------------------------
    def store_header(self, ledger_hash: bytes,
                     header: X.LedgerHeader) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO ledgerheaders "
            "(ledgerhash, prevhash, ledgerseq, closetime, data) "
            "VALUES (?, ?, ?, ?, ?)",
            (ledger_hash.hex(), header.previousLedgerHash.hex(),
             header.ledgerSeq, header.scpValue.closeTime, header.to_xdr()))

    def load_header_by_hash(self, ledger_hash: bytes
                            ) -> Optional[X.LedgerHeader]:
        row = self.conn.execute(
            "SELECT data FROM ledgerheaders WHERE ledgerhash = ?",
            (ledger_hash.hex(),)).fetchone()
        return X.LedgerHeader.from_xdr(row[0]) if row else None

    def load_header_by_seq(self, seq: int) -> Optional[Tuple[bytes,
                                                             X.LedgerHeader]]:
        row = self.conn.execute(
            "SELECT ledgerhash, data FROM ledgerheaders WHERE ledgerseq = ?",
            (seq,)).fetchone()
        if row is None:
            return None
        return bytes.fromhex(row[0]), X.LedgerHeader.from_xdr(row[1])

    def max_header_seq(self) -> Optional[int]:
        row = self.conn.execute(
            "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()
        return row[0]

    def delete_old_headers(self, keep_from_seq: int) -> None:
        self.conn.execute("DELETE FROM ledgerheaders WHERE ledgerseq < ?",
                          (keep_from_seq,))

    # -- SCP persistence (reference: HerderPersistence::saveSCPHistory) ------
    def save_scp_history(self, ledger_seq: int,
                         envelopes: Iterable[X.SCPEnvelope],
                         qsets: Iterable[X.SCPQuorumSet]) -> None:
        from ..crypto.sha import sha256
        self.conn.execute("DELETE FROM scphistory WHERE ledgerseq = ?",
                          (ledger_seq,))
        for env in envelopes:
            self.conn.execute(
                "INSERT INTO scphistory (ledgerseq, envelope) VALUES (?, ?)",
                (ledger_seq, env.to_xdr()))
        for qs in qsets:
            blob = qs.to_xdr()
            self.conn.execute(
                "INSERT OR REPLACE INTO scpquorums "
                "(qsethash, lastledgerseq, qset) VALUES (?, ?, ?)",
                (sha256(blob).hex(), ledger_seq, blob))

    def load_scp_history(self, ledger_seq: int) -> List[X.SCPEnvelope]:
        """Corrupt rows are skipped with a warning: SCP-state restore is
        best-effort (a node that restores nothing resyncs from peers)."""
        rows = self.conn.execute(
            "SELECT envelope FROM scphistory WHERE ledgerseq = ?",
            (ledger_seq,)).fetchall()
        out = []
        for r in rows:
            try:
                out.append(X.SCPEnvelope.from_xdr(r[0]))
            except Exception:
                _log.warning("skipping undecodable scphistory row for "
                             "slot %d", ledger_seq)
        return out

    def load_scp_quorums(self) -> List[X.SCPQuorumSet]:
        rows = self.conn.execute("SELECT qset FROM scpquorums").fetchall()
        out = []
        for r in rows:
            try:
                out.append(X.SCPQuorumSet.from_xdr(r[0]))
            except Exception:
                _log.warning("skipping undecodable scpquorums row")
        return out

    def save_txset(self, txset_hash: bytes, ledger_seq: int,
                   txset_xdr: bytes) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO storedtxsets (hash, lastledgerseq, txset)"
            " VALUES (?, ?, ?)", (txset_hash.hex(), ledger_seq, txset_xdr))

    def load_txsets(self) -> List[Tuple[bytes, bytes]]:
        rows = self.conn.execute(
            "SELECT hash, txset FROM storedtxsets").fetchall()
        return [(bytes.fromhex(r[0]), r[1]) for r in rows]

    def prune_scp(self, below_seq: int) -> None:
        """Drop SCP history / tx sets for slots below `below_seq`
        (reference: HerderPersistence + MAX_SLOTS_TO_REMEMBER trimming)."""
        self.conn.execute("DELETE FROM scphistory WHERE ledgerseq < ?",
                          (below_seq,))
        self.conn.execute("DELETE FROM storedtxsets WHERE lastledgerseq < ?",
                          (below_seq,))

    # -- per-ledger history artifacts (reference: CheckpointBuilder's
    #    incremental .dirty streams; stored relationally here) --------------
    def save_tx_history(self, ledger_seq: int, tx_entry_xdr: bytes,
                        result_entry_xdr: bytes) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO txhistory "
            "(ledgerseq, txentry, resultentry) VALUES (?, ?, ?)",
            (ledger_seq, tx_entry_xdr, result_entry_xdr))

    def load_tx_history(self, from_seq: int, to_seq: int
                        ) -> List[Tuple[int, bytes, bytes]]:
        return self.conn.execute(
            "SELECT ledgerseq, txentry, resultentry FROM txhistory "
            "WHERE ledgerseq BETWEEN ? AND ? ORDER BY ledgerseq",
            (from_seq, to_seq)).fetchall()

    def prune_tx_history(self, below_seq: int) -> None:
        self.conn.execute("DELETE FROM txhistory WHERE ledgerseq < ?",
                          (below_seq,))

    # -- peer address book (reference: PeerManager's peers table) -----------
    def store_peer(self, host: str, port: int, num_failures: int) -> None:
        self.conn.execute(
            "INSERT INTO peers (host, port, numfailures) VALUES (?, ?, ?) "
            "ON CONFLICT(host, port) DO UPDATE SET "
            "numfailures = excluded.numfailures", (host, port, num_failures))

    def load_peers(self) -> List[Tuple[str, int, int]]:
        return self.conn.execute(
            "SELECT host, port, numfailures FROM peers").fetchall()

    def delete_peer(self, host: str, port: int) -> None:
        self.conn.execute("DELETE FROM peers WHERE host = ? AND port = ?",
                          (host, port))

    # -- ban list (reference: BanManagerImpl's ban table) -------------------
    def store_ban(self, node_id: bytes) -> None:
        self.conn.execute("INSERT OR IGNORE INTO ban (nodeid) VALUES (?)",
                          (node_id.hex(),))

    def delete_ban(self, node_id: bytes) -> None:
        self.conn.execute("DELETE FROM ban WHERE nodeid = ?",
                          (node_id.hex(),))

    def load_bans(self) -> List[bytes]:
        return [bytes.fromhex(r[0]) for r in
                self.conn.execute("SELECT nodeid FROM ban").fetchall()]

    # -- publish queue (reference: HistoryManagerImpl publishqueue table) ----
    def queue_publish(self, checkpoint_ledger: int, has_json: str) -> None:
        self.conn.execute(
            "INSERT OR REPLACE INTO publishqueue (ledger, state) "
            "VALUES (?, ?)", (checkpoint_ledger, has_json))

    def publish_queue(self) -> List[Tuple[int, str]]:
        return self.conn.execute(
            "SELECT ledger, state FROM publishqueue ORDER BY ledger"
        ).fetchall()

    def dequeue_publish(self, checkpoint_ledger: int) -> None:
        self.conn.execute("DELETE FROM publishqueue WHERE ledger = ?",
                          (checkpoint_ledger,))
