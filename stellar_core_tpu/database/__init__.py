"""Durable storage layer (reference: src/database/)."""

from .database import Database, PersistentState, SCHEMA_VERSION

__all__ = ["Database", "PersistentState", "SCHEMA_VERSION"]
