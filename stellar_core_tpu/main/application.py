"""Application: the composition root that turns a Config into a live node.

Reference: src/main/ApplicationImpl.{h,cpp} — owns the VirtualClock and
every subsystem (Database, BucketManager, LedgerManager, Herder,
OverlayManager, HistoryManager, CatchupManager, CommandHandler), starts
them in dependency order, and runs the crank loop.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .. import xdr as X
from ..bucket.manager import BucketDir
from ..catchup.catchup import CatchupManager
from ..database import Database, PersistentState
from ..herder.herder import Herder
from ..history.archive import FileHistoryArchive
from ..history.manager import HistoryManager
from ..invariant import InvariantManager
from ..ledger.manager import LedgerManager
from ..overlay.overlay_manager import OverlayManager
from ..overlay.tcp import TCPTransport
from ..util import logging as slog
from ..util.clock import ClockMode, VirtualClock
from .config import Config

log = slog.get("Main")

VERSION = "stellar-core-tpu 2.0.0"


def _herder_bundle(app) -> dict:
    """Herder/SCP state for crash bundles (registered via weakref: a
    torn-down node reports itself gone instead of pinning its graph)."""
    if app is None:
        return {"gone": True}
    return {
        "state": app.herder.get_state_human(),
        "tracking_ledger": app.herder.tracking_consensus_ledger_index(),
        "tx_queue_depth": app.herder.tx_queue.size,
        "buffered_slots": sorted(app.herder._buffered),
        "ledger_timespan_s": app.herder.ledger_timespan,
        "lcl": {"seq": app.lm.last_closed_ledger_seq,
                "hash": app.lm.lcl_hash.hex(),
                "close_time": app.lm.lcl_header.scpValue.closeTime},
    }


def _config_fingerprint(app) -> dict:
    """Enough config identity to tell WHICH deployment produced a crash
    bundle without leaking secrets (no seeds, no peer credentials)."""
    if app is None:
        return {"gone": True}
    cfg = app.config
    return {
        "network_passphrase": cfg.NETWORK_PASSPHRASE,
        "network_id": app.network_id.hex(),
        "node": app.node_secret.public_key.to_strkey(),
        "is_validator": cfg.NODE_IS_VALIDATOR,
        "run_standalone": cfg.RUN_STANDALONE,
        "in_memory_ledger": cfg.IN_MEMORY_LEDGER,
        "bucket_resident_levels": cfg.BUCKET_RESIDENT_LEVELS,
        "accel": cfg.ACCEL,
        "log_format": cfg.LOG_FORMAT,
        "worker_threads": cfg.WORKER_THREADS,
    }


def _timeseries_bundle(app) -> dict:
    """Trailing time-series window for crash bundles (weakref-fed)."""
    if app is None or app.timeseries is None:
        return {"gone": True}
    return app.timeseries.bundle()


def _anomaly_bundle(app) -> dict:
    """Anomaly verdicts for crash bundles (weakref-fed)."""
    if app is None or app.anomaly is None:
        return {"gone": True}
    return app.anomaly.report()


def _app_timeseries(app):
    return app.timeseries if app is not None else None


def _app_closecosts(app):
    return app.lm.close_costs if app is not None else None


class Application:
    def __init__(self, config: Config,
                 clock: Optional[VirtualClock] = None,
                 listen: bool = True):
        self.config = config
        config.apply_process_globals()
        self.clock = clock or VirtualClock(ClockMode.REAL_TIME)
        self.network_id = config.network_id()
        self.node_secret = config.node_secret()
        slog.set_level(config.LOG_LEVEL)
        slog.set_format(config.LOG_FORMAT)
        if config.NODE_NAME:
            # fleet attribution: JSON log records, flight-event exports
            # and /tracespans documents all carry this node's name
            slog.set_node_id(config.NODE_NAME)

        # incident observability: per-category status lines (reference:
        # StatusManager feeding /info), the node.health gauge behind
        # /health, and post-mortem bundle sources (herder/SCP state +
        # config fingerprint ride along in every crash bundle)
        from ..util import eventlog
        from ..util.metrics import registry as _registry
        from .status import StatusManager, health_gauge_value
        self.status = StatusManager()
        _registry().weak_gauge("node.health", self, health_gauge_value)
        eventlog.install_thread_excepthook()
        import weakref
        ref = weakref.ref(self)
        eventlog.register_bundle_source(
            "herder", lambda: _herder_bundle(ref()))
        eventlog.register_bundle_source(
            "config", lambda: _config_fingerprint(ref()))
        # always-on sampling profiler (util/sampleprof): config flag or
        # STPU_SAMPLEPROF=1; its folded stacks join every crash bundle
        from ..util import sampleprof
        if config.SAMPLEPROF:
            sampleprof.profiler().start()
        else:
            sampleprof.start_if_configured()
        # local SLO burn tracking (util/slo): evaluated on a clock timer
        # so /slo answers with per-objective burn rates; 0 cadence = off
        self.slo_tracker = None
        self._slo_timer = None
        if config.SLO_EVAL_CADENCE_S > 0:
            from ..util.slo import SLOTracker, default_objectives
            self.slo_tracker = SLOTracker(
                default_objectives(
                    close_p99_s=config.SLO_CLOSE_P99_S,
                    admission_p99_s=config.SLO_ADMISSION_P99_S,
                    catchup_rate=config.SLO_CATCHUP_RATE,
                    budget=config.SLO_BURN_BUDGET),
                source=config.NODE_NAME or "local")
            self._arm_slo_timer()

        # database + buckets ------------------------------------------------
        self.database: Optional[Database] = None
        self.bucket_dir: Optional[BucketDir] = None
        self.bucket_store = None   # BucketListDB authority when enabled
        if config.DATABASE:
            os.makedirs(os.path.dirname(config.DATABASE) or ".",
                        exist_ok=True)
            self.database = Database(config.DATABASE)
            bdir = config.BUCKET_DIR_PATH or os.path.join(
                os.path.dirname(config.DATABASE) or ".", "buckets")
            self.bucket_dir = BucketDir(bdir)
        if not config.IN_MEMORY_LEDGER:
            # BucketListDB mode: one store serves both the durable bucket
            # files (persistence) and the indexed ledger-entry reads
            from ..bucket.manager import BucketListStore
            import tempfile
            bdir = config.BUCKET_DIR_PATH or (
                self.bucket_dir.path if self.bucket_dir is not None
                else tempfile.mkdtemp(prefix="bucketlistdb-"))
            self.bucket_store = BucketListStore(bdir)
            if self.bucket_dir is not None:
                self.bucket_dir = self.bucket_store

        invariants = (InvariantManager.from_patterns(config.INVARIANT_CHECKS)
                      if config.INVARIANT_CHECKS else None)

        # worker pool (reference: Application::postOnBackgroundThread /
        # WORKER_THREADS — bucket merges run here)
        from concurrent.futures import ThreadPoolExecutor
        self.worker_pool = (ThreadPoolExecutor(
            max_workers=config.WORKER_THREADS,
            thread_name_prefix="worker")
            if config.WORKER_THREADS > 0 else None)

        # ledger ------------------------------------------------------------
        cache_size = config.BUCKETLISTDB_ENTRY_CACHE_SIZE
        resident = config.BUCKET_RESIDENT_LEVELS
        if self.database is not None and self.database.get_state(
                PersistentState.LAST_CLOSED_LEDGER) is not None:
            self.lm = LedgerManager.load_last_known_ledger(
                self.network_id, self.database, self.bucket_dir,
                invariant_manager=invariants,
                bucket_store=self.bucket_store,
                entry_cache_size=cache_size,
                resident_levels=resident)
            self.lm.bucket_list.executor = self.worker_pool
        else:
            self.lm = LedgerManager(self.network_id,
                                    invariant_manager=invariants,
                                    merge_executor=self.worker_pool,
                                    bucket_store=self.bucket_store,
                                    entry_cache_size=cache_size,
                                    resident_levels=resident)
            self.lm.start_new_ledger()
            if self.database is not None:
                self.lm.enable_persistence(self.database, self.bucket_dir)

        self.lm.soroban_parallel_apply = config.SOROBAN_PARALLEL_APPLY

        # herder + overlay --------------------------------------------------
        self.herder = Herder(self.clock, self.lm, self.node_secret,
                             config.quorum_set(),
                             is_validator=config.NODE_IS_VALIDATOR)
        if self.database is not None:
            self.herder.attach_persistence(self.database)
        if config.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING:
            self.herder.ledger_timespan = 1.0
        if config.ADMISSION:
            # batched admission verification in front of the tx-queue
            # (herder/admission.py): /tx + overlay floods accumulate into
            # accel-sized batches; back-pressure feeds overlay flow
            # control (wired below) and /health
            self.herder.enable_admission(
                accel=config.ACCEL == "tpu",
                accel_chunk=config.ACCEL_CHUNK_SIZE,
                batch_size=config.ADMISSION_BATCH_SIZE,
                flush_delay_s=config.ADMISSION_FLUSH_DELAY_S,
                max_backlog=config.ADMISSION_MAX_BACKLOG)
        self.overlay = OverlayManager(
            self.clock, self.herder, self.network_id, self.node_secret,
            listening_port=config.PEER_PORT, database=self.database,
            batching=config.OVERLAY_BATCHING,
            batch_max_messages=config.OVERLAY_BATCH_MAX_MESSAGES,
            batch_max_bytes=config.OVERLAY_BATCH_MAX_BYTES)
        if self.herder.admission is not None:
            # backlog drained -> re-grant the flow-control capacity the
            # peers earned while the valve was closed
            self.herder.admission.on_backpressure_release = \
                self.overlay.release_flood_grants
        for addr in config.KNOWN_PEERS:
            host, _, port = addr.partition(":")
            self.overlay.peer_manager.add_address(host, int(port or 11625))
        self.transport: Optional[TCPTransport] = None
        if listen:
            self.transport = TCPTransport(
                self.overlay, listen_port=config.PEER_PORT)

        # history + catchup -------------------------------------------------
        from ..history.archive import make_archive
        archives = []
        for spec in config.HISTORY:
            archives.append(make_archive(spec.get_path, spec.put_path,
                                         spec.mkdir_cmd))
        self.history = HistoryManager(self.lm, config.NETWORK_PASSPHRASE,
                                      archives, database=self.database)
        if config.METADATA_OUTPUT_STREAM:
            self.lm.meta_stream = open(config.METADATA_OUTPUT_STREAM, "ab")
        self.herder.ledger_closed_hook = self._on_ledger_closed
        # native live close (ledger/native_close.py): the C apply engine
        # drives LedgerManager.close with NATIVE_CLOSE_DIFFERENTIAL
        # spot-checks; "auto" attaches when available, "on" warns loudly
        # when it cannot be honored
        if config.NATIVE_CLOSE != "off":
            attached = self.lm.attach_native_close(
                differential=config.NATIVE_CLOSE_DIFFERENTIAL or None)
            if attached:
                self.lm.native_closer.on_degrade = \
                    lambda reason: self.status.set_status("ledger", reason)
            elif config.NATIVE_CLOSE == "on":
                log.warning(
                    "NATIVE_CLOSE=on but the native close path is "
                    "unavailable (extension not built, BucketListDB root, "
                    "or INVARIANT_CHECKS enabled) — live close runs on the "
                    "~3x slower Python engine")
        # a node that falls behind pulls recent SCP state from its peers
        # (reference: HerderImpl out-of-sync recovery → getMoreSCPState);
        # beyond the peers' slot memory, archive catchup takes over
        self.herder.out_of_sync_handler = self._on_out_of_sync
        self._catchup_work = None
        self.catchup = CatchupManager(
            self.network_id, config.NETWORK_PASSPHRASE,
            accel=config.ACCEL == "tpu",
            accel_chunk=config.ACCEL_CHUNK_SIZE,
            bucket_store=self.bucket_store,
            entry_cache_size=cache_size,
            resident_levels=resident)

        # maintenance -------------------------------------------------------
        from .maintainer import Maintainer
        self.maintainer = Maintainer(self)

        # retrospective telemetry (ISSUE 20) --------------------------------
        # time-series capture + adaptive anomaly baselines.  Both run on
        # the observability plane OUTSIDE detguard regions: a VirtualTimer
        # on the crank loop under VIRTUAL_TIME (tests drive them
        # deterministically), a wall-cadence daemon thread on real nodes.
        self.timeseries = None
        self._ts_timer = None
        self.anomaly = None
        self._anomaly_timer = None
        if config.TIMESERIES_CADENCE_S > 0:
            from ..util.timeseries import TimeSeriesStore
            self.timeseries = TimeSeriesStore(
                cadence_s=config.TIMESERIES_CADENCE_S)
            if self.clock.mode is ClockMode.VIRTUAL_TIME:
                self._arm_ts_timer()
            else:
                self.timeseries.start()
            eventlog.register_bundle_source(
                "timeseries", lambda: _timeseries_bundle(ref()))
        if config.ANOMALY_EVAL_CADENCE_S > 0:
            from ..util.anomaly import AnomalyDetector, default_tracked
            self.anomaly = AnomalyDetector(
                default_tracked(),
                timeseries=lambda: _app_timeseries(ref()),
                closecosts=lambda: _app_closecosts(ref()),
                source=config.NODE_NAME or "local")
            self._arm_anomaly_timer()
            eventlog.register_bundle_source(
                "anomaly", lambda: _anomaly_bundle(ref()))
            if self.slo_tracker is not None:
                # leading indicator: /slo reports active anomalies before
                # the burn budget trips
                self.slo_tracker.attach_anomaly_source(self.anomaly.active)

        # http admin --------------------------------------------------------
        self.http = None
        if config.HTTP_PORT:
            from .http_admin import CommandHandler
            self.http = CommandHandler(self, config.HTTP_PORT)

        self._stopped = False

    # -- lifecycle -----------------------------------------------------------
    def _on_ledger_closed(self, arts) -> None:
        self.history.ledger_closed(arts)
        self.overlay.clear_below(
            max(0, self.lm.last_closed_ledger_seq - 100))
        # recovery clears the out-of-sync status line (reference:
        # StatusManager newest-status-per-category, removed on recovery)
        from ..herder.herder import HerderState
        if self.herder.state == HerderState.TRACKING:
            self.status.clear_status("scp")

    def _on_out_of_sync(self) -> None:
        self.status.set_status(
            "scp", f"out of sync at ledger "
            f"{self.lm.last_closed_ledger_seq}; requesting SCP state")
        self.overlay.request_scp_state()
        self.maybe_start_archive_catchup()

    def maybe_start_archive_catchup(self) -> None:
        """In-place archive catchup when the gap exceeds what peers can
        replay from SCP memory (reference: HerderImpl out-of-sync →
        CatchupManager::startCatchup; the herder keeps buffering
        externalized values meanwhile and _drain_buffered applies them
        once the replay closes the gap — ApplyBufferedLedgersWork)."""
        from ..herder.herder import MAX_SLOTS_TO_REMEMBER
        if self._catchup_work is not None and not self._catchup_work.done:
            return
        if not self.history.archives:
            return
        has = self.history.archives[0].get_state()
        if has is None:
            return
        gap = has.current_ledger - self.lm.last_closed_ledger_seq
        if gap <= MAX_SLOTS_TO_REMEMBER:
            return  # peers' SCP state covers it
        from ..historywork.works import CatchupWork
        log.info("starting in-place archive catchup: lcl=%d archive=%d",
                 self.lm.last_closed_ledger_seq, has.current_ledger)
        if self.lm.native_closer is not None:
            # the replay needs Python authority over the manager state;
            # closes during the gap run on the Python engine and the
            # native closer re-imports once the replay lands (_watch)
            self.lm.native_closer.deactivate()
        self.status.set_status(
            "history-catchup",
            f"catching up from archive: lcl={self.lm.last_closed_ledger_seq}"
            f" target={has.current_ledger}")
        work = CatchupWork(self.clock, self.lm,
                           self.history.archives[0], has.current_ledger,
                           self.network_id,
                           accel=self.config.ACCEL == "tpu",
                           accel_chunk=self.config.ACCEL_CHUNK_SIZE,
                           stats=self.catchup.stats)
        self._catchup_work = work
        work.start()
        self._watch_catchup()

    def _watch_catchup(self) -> None:
        """Poll the catchup DAG from the crank loop; on completion, drain
        any live ledgers the herder buffered during the replay."""
        from ..util.clock import VirtualTimer
        if not self._catchup_work.done:
            t = VirtualTimer(self.clock)
            self._catchup_watch_timer = t
            t.expires_from_now(0.2, self._watch_catchup)
            return
        ok = self._catchup_work.succeeded
        log.info("archive catchup %s at lcl=%d",
                 "complete" if ok else "FAILED",
                 self.lm.last_closed_ledger_seq)
        if ok:
            self.status.clear_status("history-catchup")
        else:
            self.status.set_status(
                "history-catchup",
                f"archive catchup FAILED at "
                f"lcl={self.lm.last_closed_ledger_seq}")
        self._catchup_work = None
        closer = self.lm.native_closer
        if closer is not None and closer.degraded is None \
                and not closer.bridge.active:
            closer.activate()       # resume native close post-catchup
        self.herder._drain_buffered()

    def start(self) -> None:
        """Reference: ApplicationImpl::start — restore state, join
        consensus, dial peers."""
        self.herder.restore_scp_state()
        if self.http is not None:
            self.http.start()
        if self.config.RUN_STANDALONE or self.config.FORCE_SCP:
            self.herder.bootstrap()
        else:
            self.herder.start()
        self._dial_known_peers()
        self._start_reconnect_timer()
        self.maintainer.start()
        log.info("%s up: node=%s lcl=%d port=%d", VERSION,
                 self.node_secret.public_key.to_strkey()[:12],
                 self.lm.last_closed_ledger_seq,
                 self.overlay.listening_port)

    RECONNECT_INTERVAL = 2.0

    def _dial_known_peers(self) -> None:
        """Dial address-book candidates up to the target connection count
        (reference: OverlayManagerImpl::connectToMorePeers via
        RandomPeerSource)."""
        if self.transport is None:
            return
        want = self.config.TARGET_PEER_CONNECTIONS \
            - self.overlay.num_authenticated()
        if want <= 0:
            return
        exclude = self.overlay.connected_addresses()
        for host, port in self.overlay.peer_manager.dial_candidates(
                want, exclude=exclude):
            self.transport.connect(host, port)

    def _start_reconnect_timer(self) -> None:
        """Redial while under-connected (reference:
        OverlayManagerImpl::triggerPeerResolution on a timer).  Duplicate
        connections are resolved deterministically by the overlay's
        keep-smaller-dialer rule, so over-dialing is harmless."""
        from ..util.clock import VirtualTimer
        self._reconnect_timer = VirtualTimer(self.clock)

        def tick() -> None:
            self._dial_known_peers()
            self._reconnect_timer.expires_from_now(
                self.RECONNECT_INTERVAL, tick)

        self._reconnect_timer.expires_from_now(self.RECONNECT_INTERVAL, tick)

    def run(self) -> None:
        """The main crank loop (reference: ApplicationImpl::run /
        VirtualClock::crank in a loop until shutdown)."""
        import time
        while not self._stopped:
            if self.clock.crank() == 0:
                time.sleep(0.005)

    def _arm_slo_timer(self) -> None:
        """Repeating SLO evaluation on the clock loop (VirtualTimer so
        virtual-time tests crank it deterministically)."""
        from ..util.clock import VirtualTimer
        t = VirtualTimer(self.clock)

        def tick() -> None:
            if self._stopped:
                return
            self.slo_tracker.evaluate()
            t.expires_from_now(self.config.SLO_EVAL_CADENCE_S, tick)

        t.expires_from_now(self.config.SLO_EVAL_CADENCE_S, tick)
        self._slo_timer = t

    def _arm_ts_timer(self) -> None:
        """Repeating time-series capture under VIRTUAL_TIME (real nodes
        use the store's own wall-cadence daemon instead).  Capture
        stamps virtual seconds so exported curves line up with the
        simulation's close cadence."""
        from ..util.clock import VirtualTimer
        t = VirtualTimer(self.clock)

        def tick() -> None:
            if self._stopped:
                return
            self.timeseries.capture(now=self.clock.now())
            t.expires_from_now(self.config.TIMESERIES_CADENCE_S, tick)

        t.expires_from_now(self.config.TIMESERIES_CADENCE_S, tick)
        self._ts_timer = t

    def _arm_anomaly_timer(self) -> None:
        """Repeating anomaly evaluation on the clock loop (same shape as
        the SLO timer; works under both clock modes)."""
        from ..util.clock import VirtualTimer
        t = VirtualTimer(self.clock)

        def tick() -> None:
            if self._stopped:
                return
            self.anomaly.evaluate()
            t.expires_from_now(self.config.ANOMALY_EVAL_CADENCE_S, tick)

        t.expires_from_now(self.config.ANOMALY_EVAL_CADENCE_S, tick)
        self._anomaly_timer = t

    def stop(self) -> None:
        self._stopped = True
        if self._slo_timer is not None:
            self._slo_timer.cancel()
        if self._ts_timer is not None:
            self._ts_timer.cancel()
        if self._anomaly_timer is not None:
            self._anomaly_timer.cancel()
        if self.timeseries is not None:
            self.timeseries.stop()
        if self.lm.native_closer is not None:
            # move ledger authority back to Python (rebuilds buckets and,
            # with a database attached, persists the final LCL durably)
            self.lm.detach_native_close()
        if self.herder.admission is not None:
            self.herder.admission.close()
        if self.lm.meta_stream is not None \
                and not callable(self.lm.meta_stream):
            self.lm.meta_stream.close()
            self.lm.meta_stream = None
        if self.http is not None:
            self.http.stop()
        if self.transport is not None:
            self.transport.close()
        if self.worker_pool is not None:
            self.lm.bucket_list.resolve_all_merges()
            self.worker_pool.shutdown(wait=True)
        if self.database is not None:
            self.database.close()

    # -- introspection (CommandHandler backend) ------------------------------
    def info(self) -> dict:
        return {
            "build": VERSION,
            "network": self.config.NETWORK_PASSPHRASE,
            "node": self.node_secret.public_key.to_strkey(),
            "state": self.herder.get_state_human(),
            "ledger": {
                "num": self.lm.last_closed_ledger_seq,
                "hash": self.lm.lcl_hash.hex(),
                "version": self.lm.lcl_header.ledgerVersion,
                "baseFee": self.lm.lcl_header.baseFee,
                "baseReserve": self.lm.lcl_header.baseReserve,
            },
            "peers": {
                "authenticated_count": self.overlay.num_authenticated(),
                "pending_count": len(self.overlay.pending_peers),
            },
            "protocol_version": self.lm.lcl_header.ledgerVersion,
            "accel": self.config.ACCEL,
            "status": self.status.status_lines(),
        }

    def health(self) -> dict:
        """/health backend — see main/status.evaluate_health."""
        from .status import evaluate_health
        return evaluate_health(self)

    def metrics(self) -> dict:
        from ..util.metrics import registry
        return {
            "registry": registry().snapshot(),
            "overlay": dict(self.overlay.stats),
            "herder": {
                "state": self.herder.get_state_human(),
                "tx_queue_size": self.herder.tx_queue.size,
            },
            "ledger": {
                "num": self.lm.last_closed_ledger_seq,
                "entries": self.lm.root.entry_count(),
            },
        }

    def submit_tx(self, envelope_xdr: bytes) -> dict:
        """POST /tx backend (reference: CommandHandler::tx).  Malformed
        submissions surface as XDR/validation errors (XdrError IS-A
        ValueError) — the structured rejection path; anything else is a
        bug worth a loud traceback, not a silent ERROR reply.

        Thread contract (ISSUE 9 audit): MAIN THREAD ONLY.  http_admin
        marshals /tx here via _on_main, so the whole admission chain
        (recv_transaction -> AdmissionPipeline.submit -> tx_queue.try_add)
        mutates queue state on the crank loop exclusively — that is the
        ownership the tx_queue/admission `owned-by=main` annotations
        attest and `make race` proves."""
        try:
            env = X.TransactionEnvelope.from_xdr(envelope_xdr)
            frame = self.lm.make_frame(env)
        except ValueError as e:
            log.debug("rejecting submitted tx: %s", e)
            return {"status": "ERROR", "detail": f"malformed: {e}"}
        res = self.herder.recv_transaction(frame)
        out = {"status": res.code.upper()}
        if res.result is not None:
            out["result_xdr"] = res.result.to_xdr().hex()
        return out

    def quorum_info(self, transitive: bool = False) -> dict:
        qmap = self.herder.quorum_map()
        out = {
            "node_count": len(qmap),
            "nodes": {k.hex()[:16]: (v is not None) for k, v in qmap.items()},
        }
        if transitive:
            from ..herder.quorum_intersection import check_intersection
            known = {k: v for k, v in qmap.items() if v is not None}
            if known:
                res = check_intersection(known)
                out["intersection"] = {
                    "intersects": res.intersects,
                    "node_count": len(known),
                }
        return out

    # -- admin-endpoint backends (reference: CommandHandler actions) ---------
    def manual_close(self) -> dict:
        """Trigger the next consensus round immediately.  Gated exactly
        like the reference (`CommandHandler::manualClose` requires
        MANUAL_CLOSE or RUN_STANDALONE) — on a live validator an admin
        trigger would race the herder's own ledger timer for the slot."""
        if not (self.config.MANUAL_CLOSE or self.config.RUN_STANDALONE):
            return {"status": "ERROR",
                    "detail": "manualclose requires MANUAL_CLOSE or "
                              "RUN_STANDALONE"}
        seq = self.lm.last_closed_ledger_seq + 1
        self.herder.trigger_next_ledger(seq)
        return {"status": "triggered", "ledger": seq}

    def connect_to(self, host: str, port: int) -> dict:
        if self.transport is None:
            return {"status": "ERROR", "detail": "node not listening"}
        self.overlay.peer_manager.add_address(host, port)
        self.transport.connect(host, port)
        return {"status": "connecting", "peer": f"{host}:{port}"}

    def drop_peer(self, node_id: bytes) -> dict:
        peer = self.overlay.authenticated_peers.get(node_id)
        if peer is None:
            return {"status": "ERROR", "detail": "no such peer"}
        peer.drop("dropped by admin")
        return {"status": "dropped"}

    def self_check(self) -> dict:
        from .selfcheck import self_check
        return self_check(self.lm, self.database, self.bucket_dir,
                          self.history.archives)

    def survey_node(self, node_id=None) -> dict:
        """Start a time-sliced survey; with a node id, also request that
        node's topology data."""
        if self.overlay.survey._nonce is None:
            nonce = self.overlay.survey.start_survey()
        else:
            nonce = self.overlay.survey._nonce
        if node_id is not None:
            self.overlay.survey.send_request(node_id)
        return {"status": "surveying", "nonce": nonce}

    def stop_survey(self) -> dict:
        self.overlay.survey.stop_survey()
        return {"status": "stopped"}

    def get_ledger_entry(self, key_bytes: bytes) -> dict:
        """`/getledgerentry` (reference: QueryServer getledgerentry) —
        served from an immutable bucket-list snapshot."""
        snap = self.lm.bucket_list.snapshot(self.lm.last_closed_ledger_seq)
        entry = snap.load(key_bytes)
        if entry is None:
            return {"found": False, "ledger": snap.ledger_seq}
        return {"found": True, "ledger": snap.ledger_seq,
                "entry_xdr": entry.to_xdr().hex()}
