"""Node configuration: TOML file -> Config object.

Reference: src/main/Config.{h,cpp} — the stellar-core.cfg surface.  The
key names mirror the reference's where the concept exists here
(NETWORK_PASSPHRASE, NODE_SEED, NODE_IS_VALIDATOR, QUORUM_SET, KNOWN_PEERS,
PEER_PORT, HTTP_PORT, RUN_STANDALONE, DATABASE, BUCKET_DIR_PATH,
INVARIANT_CHECKS, HISTORY).  TPU-specific additions: ACCEL ("tpu"/"none")
and ACCEL_CHUNK_SIZE, the `--accel` surface BASELINE.json benchmarks flip.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

try:
    import tomllib  # stdlib since 3.11
except ModuleNotFoundError:  # 3.10 container: subset parser below
    tomllib = None

from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from .. import xdr as X


@dataclass
class HistoryArchiveConfig:
    name: str
    get_path: str = ""        # local dir, or command template with {0}/{1}
    put_path: str = ""
    mkdir_cmd: str = ""       # optional remote-mkdir template ({0} = dir)


@dataclass
class Config:
    NETWORK_PASSPHRASE: str = "Standalone TPU Network ; 2026"
    NODE_SEED: Optional[str] = None          # strkey S...
    NODE_IS_VALIDATOR: bool = True
    RUN_STANDALONE: bool = False
    FORCE_SCP: bool = False
    MANUAL_CLOSE: bool = False               # /manualclose trigger allowed

    QUORUM_SET_VALIDATORS: List[str] = field(default_factory=list)  # G...
    QUORUM_SET_THRESHOLD: int = 0            # 0 = simple majority

    PEER_PORT: int = 11625
    HTTP_PORT: int = 0                       # 0 = no admin endpoint
    KNOWN_PEERS: List[str] = field(default_factory=list)  # "host:port"
    TARGET_PEER_CONNECTIONS: int = 8

    DATABASE: str = ""                       # sqlite path; "" = in-memory
    BUCKET_DIR_PATH: str = ""
    # BucketListDB (reference: since v21 the bucket list IS the ledger-entry
    # database).  IN_MEMORY_LEDGER=false routes every ledger-entry read
    # through indexed on-disk bucket files with a bounded LRU entry cache;
    # true keeps the legacy in-memory dict root (tests/sims).
    IN_MEMORY_LEDGER: bool = True
    BUCKETLISTDB_ENTRY_CACHE_SIZE: int = 4096  # LRU entries in LedgerTxnRoot
    # BucketListDB residency depth (phase 2): bucket-list levels >= this
    # hold NO decoded entries — they are served from indexed bucket files
    # and merged by the streaming decode-free path.  Levels below it stay
    # decoded (level 0 merges synchronously inside every close).  Raising
    # it trades memory for fewer file reads; NUM_LEVELS disables eviction.
    BUCKET_RESIDENT_LEVELS: int = 2
    INVARIANT_CHECKS: List[str] = field(default_factory=list)
    HISTORY: List[HistoryArchiveConfig] = field(default_factory=list)

    ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING: bool = False
    METADATA_OUTPUT_STREAM: str = ""         # path for LedgerCloseMeta frames
    # Checkpoint cadence (reference: getCheckpointFrequency — 64 on real
    # networks, 8 under ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING so test
    # fleets publish archives within seconds).  0 = derive from the
    # accelerate flag; any explicit value is part of the archive format
    # and must match across the whole network.
    CHECKPOINT_FREQUENCY: int = 0

    ACCEL: str = "none"                      # "tpu" routes batch crypto
    ACCEL_CHUNK_SIZE: int = 8192
    # Preverify offload profile (catchup.PreverifyPipeline): "poll" (the
    # default — collect never waits on the device; a miss degrades to
    # on-demand CPU verification, so the accelerator can only ever ADD
    # throughput), "race" (the legacy bounded wait) or "sig-only" (poll
    # that never self-disables).  "" = the pipeline default.
    ACCEL_OFFLOAD_PROFILE: str = "poll"
    # Native live close (ledger/native_close.py): "auto" routes
    # LedgerManager.close through the C apply engine when the extension
    # is built, the root is in-memory and no invariants are configured;
    # "on" additionally warns loudly when that cannot be honored; "off"
    # keeps the pure-Python close.
    NATIVE_CLOSE: str = "auto"
    # Differential spot-check cadence: every Nth close also runs the
    # Python engine on a scratch copy and fail-stops with a crash bundle
    # on any divergence (results, fees, header hash, bucket hashes).
    # 0 = defer to the NATIVE_CLOSE_DIFFERENTIAL environment variable
    # (unset -> no spot-checks).  N=1 is the differential test tier.
    NATIVE_CLOSE_DIFFERENTIAL: int = 0
    # Range-parallel catchup (catchup/parallel.py): `catchup` splits a
    # complete replay into this many concurrent checkpoint ranges, each a
    # subprocess worker seeding itself via assume-state; every boundary's
    # stitch (final hash == next seed header hash) is proven before the
    # node adopts the last range's state.  1 = classic single stream.
    CATCHUP_PARALLEL_WORKERS: int = 1
    # Device-per-range mesh (catchup/parallel.py + accel/mesh.py): > 0
    # pins each range worker to one accelerator device round-robin via
    # per-worker visible-device env, so N ranges × N devices multiply
    # instead of contending for chip 0.  0 = no pinning.
    CATCHUP_MESH_DEVICES: int = 0
    # Checkpoint-granular work stealing: a finished range worker re-seeds
    # via assume-state at a later boundary and adopts half the slowest
    # range's remaining checkpoints (the stitch proof covers the dynamic
    # seam).  false = static ranges only.
    CATCHUP_WORK_STEALING: bool = True
    # Batched authenticated transport (overlay/peer.py): negotiate
    # AUTH_FLAG_BATCH per link and coalesce batch-eligible sends into
    # one-MAC BATCHED_AUTH frames.  Negotiation falls back to classic
    # per-message frames against peers that don't advertise the flag, so
    # the knob only ever changes this node's own links.  The caps bound
    # one coalescing run (messages / encoded bytes) before a flush.
    OVERLAY_BATCHING: bool = True
    OVERLAY_BATCH_MAX_MESSAGES: int = 64
    OVERLAY_BATCH_MAX_BYTES: int = 131072
    # Batched admission (herder/admission.py): /tx + overlay TRANSACTION
    # intake accumulates into accel-sized verification batches with
    # back-pressure wired to overlay flow control and surge pricing.
    # false = legacy inline single-sig intake.
    ADMISSION: bool = True
    ADMISSION_BATCH_SIZE: int = 256          # flush at this many sigs
    ADMISSION_FLUSH_DELAY_S: float = 0.05    # deadline flush, partial batch
    ADMISSION_MAX_BACKLOG: int = 4096        # then: try-again-later
    LOG_LEVEL: str = "INFO"
    # "json" = one-JSON-object-per-line structured records carrying the
    # current span id (trace correlation); runtime-switchable via
    # /ll?format=.  "text" = the classic human stream.
    LOG_FORMAT: str = "text"
    WORKER_THREADS: int = 4                  # background bucket merges
    # Fleet observability plane (ISSUE 16).  NODE_NAME stamps every JSON
    # log record, flight-event export and /tracespans document with this
    # node's identity (simulation/fleet provisions "node-N" per node);
    # "" = unattributed single-node run.
    NODE_NAME: str = ""
    # Always-on sampling profiler (util/sampleprof): true starts the
    # ~67 Hz stack sampler at boot ($STPU_SAMPLEPROF=1 overrides to on).
    SAMPLEPROF: bool = False
    # Local SLO burn tracking (util/slo): evaluate the default
    # objectives against this node's own registry every
    # SLO_EVAL_CADENCE_S seconds and serve /slo.  0 = off.
    SLO_EVAL_CADENCE_S: float = 0.0
    SLO_CLOSE_P99_S: float = 2.0             # close-latency objective
    SLO_ADMISSION_P99_S: float = 0.5         # admission-latency objective
    SLO_CATCHUP_RATE: float = 20.0           # ledgers/s replay objective
    SLO_BURN_BUDGET: float = 0.10            # breach fraction allowed
    # Retrospective telemetry (ISSUE 20).  The in-process time-series
    # store (util/timeseries) snapshots the metric registry every
    # TIMESERIES_CADENCE_S seconds — a VirtualTimer under VIRTUAL_TIME
    # (tests crank it), a wall-cadence daemon on real nodes — and serves
    # /timeseries + tsdump.  0 = off.
    TIMESERIES_CADENCE_S: float = 0.0
    # Adaptive anomaly baselines (util/anomaly): EWMA+MAD regression
    # watch over close p99 / admission latency / merge stall / cache hit
    # rate, evaluated every ANOMALY_EVAL_CADENCE_S seconds.  0 = off.
    ANOMALY_EVAL_CADENCE_S: float = 0.0
    # Soroban execution subsystem (ISSUE 17).  These override the
    # process-wide SorobanNetworkConfig (soroban/config.py) — resource
    # limits live OFF-ledger here, so enabling them never perturbs
    # genesis or classic ledger hashes.  0 = keep the compiled default.
    SOROBAN_PARALLEL_APPLY: bool = True      # footprint-clustered apply
    SOROBAN_TX_MAX_INSTRUCTIONS: int = 0
    SOROBAN_TX_MAX_MEMORY_BYTES: int = 0
    SOROBAN_LEDGER_MAX_TX_COUNT: int = 0
    SOROBAN_LEDGER_MAX_INSTRUCTIONS: int = 0

    # -- derived -------------------------------------------------------------
    def network_id(self) -> bytes:
        return sha256(self.NETWORK_PASSPHRASE.encode())

    def node_secret(self) -> SecretKey:
        if self.NODE_SEED:
            return SecretKey.from_strkey_seed(self.NODE_SEED)
        # deterministic-from-passphrase dev key, like the reference's
        # standalone default
        return SecretKey(sha256(b"node seed " + self.network_id()))

    def checkpoint_frequency(self) -> int:
        """Effective checkpoint cadence (reference:
        HistoryManager::getCheckpointFrequency): an explicit
        CHECKPOINT_FREQUENCY wins, else 8 under the accelerate-time flag,
        else the real-network 64."""
        if self.CHECKPOINT_FREQUENCY:
            return self.CHECKPOINT_FREQUENCY
        return 8 if self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING else 64

    def apply_process_globals(self) -> None:
        """Install the config's process-wide knobs (today: the checkpoint
        cadence).  Called by the CLI config loader and Application so every
        code path that does checkpoint arithmetic — publishing, catchup,
        maintenance — agrees with the network this config describes."""
        from ..history.archive import set_checkpoint_frequency
        set_checkpoint_frequency(self.checkpoint_frequency())
        overrides = {}
        if self.SOROBAN_TX_MAX_INSTRUCTIONS:
            overrides["tx_max_instructions"] = self.SOROBAN_TX_MAX_INSTRUCTIONS
        if self.SOROBAN_TX_MAX_MEMORY_BYTES:
            overrides["tx_max_memory_bytes"] = self.SOROBAN_TX_MAX_MEMORY_BYTES
        if self.SOROBAN_LEDGER_MAX_TX_COUNT:
            overrides["ledger_max_tx_count"] = self.SOROBAN_LEDGER_MAX_TX_COUNT
        if self.SOROBAN_LEDGER_MAX_INSTRUCTIONS:
            overrides["ledger_max_instructions"] = \
                self.SOROBAN_LEDGER_MAX_INSTRUCTIONS
        if overrides:
            from ..soroban import network_config, set_network_config
            from dataclasses import replace
            set_network_config(replace(network_config(), **overrides))

    def quorum_set(self) -> X.SCPQuorumSet:
        from ..crypto.keys import PublicKey
        validators = [PublicKey.from_strkey(v).ed25519
                      for v in self.QUORUM_SET_VALIDATORS]
        if not validators:
            validators = [self.node_secret().public_key.ed25519]
        threshold = self.QUORUM_SET_THRESHOLD or (len(validators) // 2 + 1)
        return X.SCPQuorumSet(
            threshold=threshold,
            validators=[X.NodeID.ed25519(v) for v in validators],
            innerSets=[])

    @staticmethod
    def from_toml(path: str) -> "Config":
        if tomllib is not None:
            with open(path, "rb") as f:
                raw = tomllib.load(f)
        else:
            # TOML mandates UTF-8; the locale default on a py3.10
            # container is often C/ASCII
            with open(path, "r", encoding="utf-8") as f:
                raw = _parse_toml_subset(f.read())
        return Config.from_dict(raw)

    @staticmethod
    def from_dict(raw: dict) -> "Config":
        cfg = Config()
        simple = {
            "NETWORK_PASSPHRASE", "NODE_SEED", "NODE_IS_VALIDATOR",
            "RUN_STANDALONE", "FORCE_SCP", "MANUAL_CLOSE",
            "PEER_PORT", "HTTP_PORT",
            "KNOWN_PEERS", "TARGET_PEER_CONNECTIONS", "DATABASE",
            "BUCKET_DIR_PATH", "IN_MEMORY_LEDGER",
            "BUCKETLISTDB_ENTRY_CACHE_SIZE", "BUCKET_RESIDENT_LEVELS",
            "INVARIANT_CHECKS", "ACCEL",
            "ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING",
            "METADATA_OUTPUT_STREAM",
            "ACCEL_CHUNK_SIZE", "ACCEL_OFFLOAD_PROFILE",
            "CATCHUP_PARALLEL_WORKERS", "CATCHUP_MESH_DEVICES",
            "CATCHUP_WORK_STEALING",
            "CHECKPOINT_FREQUENCY",
            "NATIVE_CLOSE", "NATIVE_CLOSE_DIFFERENTIAL",
            "LOG_LEVEL", "LOG_FORMAT", "WORKER_THREADS",
            "ADMISSION", "ADMISSION_BATCH_SIZE", "ADMISSION_FLUSH_DELAY_S",
            "ADMISSION_MAX_BACKLOG",
            "OVERLAY_BATCHING", "OVERLAY_BATCH_MAX_MESSAGES",
            "OVERLAY_BATCH_MAX_BYTES",
            "NODE_NAME", "SAMPLEPROF", "SLO_EVAL_CADENCE_S",
            "SLO_CLOSE_P99_S", "SLO_ADMISSION_P99_S", "SLO_CATCHUP_RATE",
            "SLO_BURN_BUDGET",
            "TIMESERIES_CADENCE_S", "ANOMALY_EVAL_CADENCE_S",
            "SOROBAN_PARALLEL_APPLY", "SOROBAN_TX_MAX_INSTRUCTIONS",
            "SOROBAN_TX_MAX_MEMORY_BYTES", "SOROBAN_LEDGER_MAX_TX_COUNT",
            "SOROBAN_LEDGER_MAX_INSTRUCTIONS",
        }
        for key, val in raw.items():
            if key in simple:
                setattr(cfg, key, val)
            elif key == "QUORUM_SET":
                cfg.QUORUM_SET_VALIDATORS = list(val.get("VALIDATORS", []))
                cfg.QUORUM_SET_THRESHOLD = int(val.get("THRESHOLD", 0))
            elif key == "HISTORY":
                for name, spec in val.items():
                    cfg.HISTORY.append(HistoryArchiveConfig(
                        name=name, get_path=spec.get("get", ""),
                        put_path=spec.get("put", ""),
                        mkdir_cmd=spec.get("mkdir", "")))
            # unknown keys are tolerated (reference warns; we ignore)
        return cfg


def _parse_toml_subset(text: str) -> dict:
    """Minimal TOML-subset parser for Python < 3.11 (no stdlib tomllib):
    `[dotted.section]` tables plus `KEY = value` pairs whose values are
    JSON-compatible TOML (basic strings, integers, floats, booleans,
    single-line arrays) — exactly the node.cfg surface this repo's docs
    and tests use."""
    def strip_comment(line: str) -> str:
        # an unquoted '#' starts a comment; '#' inside a basic string
        # does not (the subset's strings are JSON-style double-quoted).
        # Escape state is tracked, not peeked: a string ending in an
        # escaped backslash ("x\\") must still close on its quote.
        in_str = escaped = False
        for i, c in enumerate(line):
            if in_str:
                if escaped:
                    escaped = False
                elif c == "\\":
                    escaped = True
                elif c == '"':
                    in_str = False
            elif c == '"':
                in_str = True
            elif c == "#":
                return line[:i]
        return line

    root: dict = {}
    table = root
    for lineno, raw_line in enumerate(text.splitlines(), 1):
        line = strip_comment(raw_line).strip()
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            table = root
            for part in line[1:-1].strip().split("."):
                nxt = table.setdefault(part.strip(), {})
                if not isinstance(nxt, dict):
                    raise ValueError(
                        f"config line {lineno}: section {line} collides "
                        f"with key {part.strip()!r}")
                table = nxt
            continue
        key, sep, val = line.partition("=")
        if not sep:
            raise ValueError(f"config line {lineno}: expected KEY = value")
        try:
            table[key.strip()] = json.loads(val.strip())
        except ValueError as e:
            raise ValueError(
                f"config line {lineno}: unsupported TOML value "
                f"{val.strip()!r} (full TOML needs Python 3.11+)") from e
    return root
