"""Command line (reference: src/main/CommandLine.{h,cpp}).

Subcommands: run / new-db / new-hist / catchup / publish /
check-quorum-intersection / self-check / verify-checkpoints /
report-last-history-checkpoint / offline-info / print-xdr / dump-xdr /
dump-ledger / encode-asset / sign-transaction / convert-id / http-command /
health / fleet / fuzz / gen-fuzz / apply-load / test / sec-to-pub /
gen-seed / version.
"""

from __future__ import annotations

import argparse
import json
import sys

from .config import Config


def _load_config(args) -> Config:
    cfg = Config.from_toml(args.conf)
    cfg.apply_process_globals()
    return cfg


def cmd_version(args) -> int:
    from .application import VERSION
    print(VERSION)
    return 0


def cmd_sec_to_pub(args) -> int:
    from ..crypto.keys import SecretKey
    seed = sys.stdin.readline().strip() if args.seed == "-" else args.seed
    print(SecretKey.from_strkey_seed(seed).public_key.to_strkey())
    return 0


def cmd_gen_seed(args) -> int:
    from ..crypto.keys import SecretKey
    sk = SecretKey.random()
    print(json.dumps({"secret": sk.to_strkey_seed(),
                      "public": sk.public_key.to_strkey()}))
    return 0


def cmd_new_db(args) -> int:
    """Initialize a fresh database at the config's DATABASE path
    (reference: `stellar-core new-db`)."""
    cfg = _load_config(args)
    if not cfg.DATABASE:
        print("config has no DATABASE path", file=sys.stderr)
        return 1
    import os
    for path in (cfg.DATABASE, cfg.DATABASE + "-wal", cfg.DATABASE + "-shm"):
        if os.path.exists(path):
            os.unlink(path)
    from .application import Application
    app = Application(cfg, listen=False)
    print(f"new database at {cfg.DATABASE}, genesis ledger "
          f"{app.lm.last_closed_ledger_seq} hash {app.lm.lcl_hash.hex()}")
    app.stop()
    return 0


def cmd_run(args) -> int:
    """Run the node (reference: `stellar-core run`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg)
    import signal

    def shutdown(signum, frame):
        app.stop()

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)
    app.start()
    app.run()
    return 0


def _resolve_catchup_target(args):
    """Shared --at/--to resolution for the single-stream and parallel
    catchup routes (one copy, or the two would drift).  Returns
    (error_message, target); exactly one is None."""
    target = None
    if args.at and args.at != "current":
        try:
            target = int(args.at)
        except ValueError:
            return (f"--at must be a ledger number or 'current', "
                    f"got {args.at!r}"), None
    if target is not None and args.to is not None and target != args.to:
        return "--at and --to conflict; give one", None
    return None, (target if target is not None else args.to)


def cmd_catchup(args) -> int:
    """Catch up from a history archive (reference: `stellar-core catchup`);
    `--parallel N` splits the replay into N concurrent checkpoint ranges
    stitched by assume-state (catchup/parallel.py)."""
    cfg = _load_config(args)
    from ..history.archive import make_archive

    if args.archive:
        archive_spec = args.archive
        archive = make_archive(args.archive)
    elif cfg.HISTORY:
        spec = cfg.HISTORY[0]
        archive_spec = spec.get_path
        archive = make_archive(spec.get_path, spec.put_path, spec.mkdir_cmd)
    else:
        print("no archive configured or given", file=sys.stderr)
        return 1
    workers = args.parallel if args.parallel else cfg.CATCHUP_PARALLEL_WORKERS
    if args.mode == "minimal" or args.count is not None:
        # ranges seed themselves via assume-state already; a minimal or
        # recent-N plan has at most one replay segment to parallelize.
        # Only an EXPLICIT --parallel is an error — config-driven workers
        # (CATCHUP_PARALLEL_WORKERS in node.cfg) must not break commands
        # that were valid before the key was added; they fall back to the
        # single stream.
        if args.parallel > 1:
            print("--parallel applies to complete catchup only (omit "
                  "--mode/--count)", file=sys.stderr)
            return 1
    elif workers > 1:
        return _cmd_catchup_parallel(args, cfg, archive_spec, workers)
    from ..catchup.catchup import CatchupManager
    from ..invariant.invariants import InvariantManager
    inv = (InvariantManager.from_patterns(cfg.INVARIANT_CHECKS)
           if cfg.INVARIANT_CHECKS else None)
    store = None
    if not cfg.IN_MEMORY_LEDGER:
        # BucketListDB catchup: assumed/replayed state lives in indexed
        # bucket files instead of an in-memory dict
        import os
        import tempfile
        from ..bucket.manager import BucketListStore
        bdir = cfg.BUCKET_DIR_PATH or (
            os.path.join(os.path.dirname(cfg.DATABASE) or ".", "buckets")
            if cfg.DATABASE else tempfile.mkdtemp(prefix="bucketlistdb-"))
        store = BucketListStore(bdir)
    cm = CatchupManager(cfg.network_id(), cfg.NETWORK_PASSPHRASE,
                        accel=cfg.ACCEL == "tpu",
                        accel_chunk=cfg.ACCEL_CHUNK_SIZE,
                        invariant_manager=inv,
                        bucket_store=store,
                        entry_cache_size=cfg.BUCKETLISTDB_ENTRY_CACHE_SIZE,
                        resident_levels=cfg.BUCKET_RESIDENT_LEVELS,
                        accel_profile=cfg.ACCEL_OFFLOAD_PROFILE or None)
    err, at = _resolve_catchup_target(args)
    if err:
        print(err, file=sys.stderr)
        return 1
    if args.mode == "minimal":
        if args.count is not None:
            # --count asks for CATCHUP_RECENT (bucket-apply + replay of the
            # last N); an explicit minimal mode would silently drop it
            print("--count conflicts with --mode minimal; omit --mode for "
                  "recent-N catchup", file=sys.stderr)
            return 1
        lm = cm.catchup_minimal(archive, checkpoint=at)
    elif args.count is not None:
        # reference: `catchup --at X --count N` — buckets to the nearest
        # boundary, replay the last N ledgers
        lm = cm.catchup_recent(archive, count=args.count, to_ledger=at)
    else:
        lm = cm.catchup_complete(archive, to_ledger=at)
    print(f"caught up to ledger {lm.last_closed_ledger_seq} "
          f"hash {lm.lcl_hash.hex()}")
    if cfg.DATABASE:
        from ..bucket.manager import BucketDir
        from ..database import Database
        import os
        os.makedirs(os.path.dirname(cfg.DATABASE) or ".", exist_ok=True)
        db = Database(cfg.DATABASE)
        bdir = BucketDir(cfg.BUCKET_DIR_PATH or os.path.join(
            os.path.dirname(cfg.DATABASE) or ".", "buckets"))
        lm.enable_persistence(db, bdir)
        db.close()
        print(f"state persisted to {cfg.DATABASE}")
    return 0


def _cmd_catchup_parallel(args, cfg, archive_spec: str, workers: int) -> int:
    """Range-parallel complete catchup: subprocess workers replay N
    contiguous checkpoint ranges, every boundary's stitch is proven, and
    the last range's verified state is adopted as the node's ledger."""
    import os
    from ..catchup.catchup import CatchupError
    from ..catchup.parallel import ParallelCatchup

    err, target = _resolve_catchup_target(args)
    if err:
        print(err, file=sys.stderr)
        return 1
    mesh_devices = (args.mesh_devices if args.mesh_devices >= 0
                    else cfg.CATCHUP_MESH_DEVICES)
    pc = ParallelCatchup(archive_spec, cfg.NETWORK_PASSPHRASE,
                         workers=workers,
                         accel=cfg.ACCEL == "tpu",
                         accel_chunk=cfg.ACCEL_CHUNK_SIZE,
                         invariant_checks=cfg.INVARIANT_CHECKS,
                         in_memory=cfg.IN_MEMORY_LEDGER,
                         entry_cache_size=cfg.BUCKETLISTDB_ENTRY_CACHE_SIZE,
                         resident_levels=cfg.BUCKET_RESIDENT_LEVELS,
                         steal=(cfg.CATCHUP_WORK_STEALING
                                and not args.no_steal),
                         mesh_devices=mesh_devices,
                         accel_profile=cfg.ACCEL_OFFLOAD_PROFILE or None)
    try:
        report = pc.run(target=target)
    except CatchupError as e:
        print(f"parallel catchup FAILED: {e}", file=sys.stderr)
        pc.cleanup()
        return 1
    print(f"caught up to ledger {report['final_ledger_seq']} "
          f"hash {report['final_hash']} "
          f"({len(report['ranges'])} ranges, "
          f"{report['stitches_verified']} stitches verified, "
          f"{report['ledgers_per_s']} ledgers/s)")
    if cfg.DATABASE:
        bdir = cfg.BUCKET_DIR_PATH or os.path.join(
            os.path.dirname(cfg.DATABASE) or ".", "buckets")
        pc.adopt_into(cfg.DATABASE, bdir)
        print(f"state persisted to {cfg.DATABASE}")
    pc.cleanup()
    return 0


def cmd_catchup_range(args) -> int:
    """One range worker of a parallel catchup (spawned by
    catchup/parallel.py; useful standalone for debugging a range).  Writes
    a JSON stitch record to --result — on failure the record carries an
    "error" key and the exit code is non-zero, so the orchestrator can
    retry with backoff."""
    import os
    from ..catchup.catchup import CatchupError
    from ..catchup.parallel import RangeSpec, run_range
    from ..crypto.sha import sha256
    from ..history.archive import make_archive, set_checkpoint_frequency

    if args.checkpoint_frequency:
        # the orchestrator's cadence is part of the archive format; a
        # worker planning seams at the default 64 against an accelerated
        # fleet's archive would mis-stitch every boundary
        set_checkpoint_frequency(args.checkpoint_frequency)
    archive = make_archive(args.archive)
    seed = (None if args.seed_checkpoint in ("", "genesis")
            else int(args.seed_checkpoint))
    spec = RangeSpec(index=args.index, seed_checkpoint=seed,
                     replay_to=args.to)
    os.makedirs(args.workdir, exist_ok=True)
    native = {"auto": None, "on": True, "off": False}[args.native]
    inv = None
    if args.invariant:
        from ..invariant.invariants import InvariantManager
        inv = InvariantManager.from_patterns(args.invariant)

    def write(doc: dict) -> None:
        tmp = args.result + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, args.result)

    try:
        result = run_range(
            archive, spec, sha256(args.passphrase.encode()),
            args.passphrase,
            accel=args.accel == "tpu", accel_chunk=args.accel_chunk,
            native=native, invariant_manager=inv,
            bucket_dir=(None if args.in_memory
                        else os.path.join(args.workdir, "bucketlistdb")),
            entry_cache_size=args.entry_cache_size or None,
            resident_levels=(args.resident_levels
                             if args.resident_levels >= 0 else None),
            persist_dir=(args.workdir
                         if args.persist or args.persist_target else None),
            persist_target=args.persist_target or None,
            ctl_dir=args.ctl_dir or None,
            accel_profile=args.accel_profile or None)
    except (CatchupError, RuntimeError, ValueError, OSError) as e:
        write({"index": spec.index, "error": str(e)})
        print(f"range {spec.index} FAILED: {e}", file=sys.stderr)
        return 1
    write(result)
    print(f"range {spec.index}: replayed {result['ledgers_replayed']} "
          f"ledgers to {result['final_ledger_seq']} "
          f"({result['ledgers_per_s']} ledgers/s)")
    return 0


def cmd_publish(args) -> int:
    """Force-publish the current checkpoint window to the configured
    archives (reference: `stellar-core publish`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg, listen=False)
    n = app.history.publish_queued_history()
    print(f"published {n} queued checkpoint(s)")
    app.stop()
    return 0


def cmd_check_quorum_intersection(args) -> int:
    """Check quorum intersection of a quorum-map JSON file (reference:
    `stellar-core check-quorum-intersection`)."""
    from ..herder.quorum_intersection import check_intersection
    from ..crypto.keys import PublicKey
    from .. import xdr as X

    with open(args.json_path) as f:
        raw = json.load(f)
    qmap = {}
    for node, spec in raw.items():
        nid = PublicKey.from_strkey(node).ed25519
        qmap[nid] = X.SCPQuorumSet(
            threshold=spec["threshold"],
            validators=[X.NodeID.ed25519(PublicKey.from_strkey(v).ed25519)
                        for v in spec["validators"]],
            innerSets=[])
    res = check_intersection(qmap)
    print("Network enjoys quorum intersection"
          if res.intersects
          else "Network DOES NOT enjoy quorum intersection")
    return 0 if res.intersects else 2


def cmd_new_hist(args) -> int:
    """Initialize the configured history archives with a genesis HAS
    (reference: `stellar-core new-hist`)."""
    cfg = _load_config(args)
    if not cfg.HISTORY:
        print("config has no HISTORY archives", file=sys.stderr)
        return 1
    from .application import Application
    app = Application(cfg, listen=False)
    for archive in app.history.archives:
        from ..history.archive import HistoryArchiveState
        has = HistoryArchiveState.from_bucket_list(
            app.lm.last_closed_ledger_seq, cfg.NETWORK_PASSPHRASE,
            app.lm.bucket_list)
        archive.put_state(has)
        print("initialized archive at "
              f"{getattr(archive, 'root', '(command transport)')}")
    app.stop()
    return 0


def cmd_self_check(args) -> int:
    """Verify durable state integrity (reference: `stellar-core
    self-check`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg, listen=False)
    report = app.self_check()
    print(json.dumps(report, indent=1))
    app.stop()
    return 0 if report["ok"] else 1


def cmd_verify_checkpoints(args) -> int:
    """Verify the header hash chain of an archive (reference:
    `stellar-core verify-checkpoints`)."""
    from ..catchup.catchup import CatchupManager, CatchupError
    from ..history.archive import make_archive
    cfg = _load_config(args) if args.conf else None
    archive = make_archive(args.archive)
    has = archive.get_state()
    if has is None:
        print("archive has no HAS", file=sys.stderr)
        return 1
    nid = cfg.network_id() if cfg else b"\x00" * 32
    cm = CatchupManager(nid, cfg.NETWORK_PASSPHRASE if cfg else "")
    try:
        headers = cm._read_headers(archive, has.current_ledger)
        from ..catchup.catchup import verify_ledger_chain
        verify_ledger_chain(headers)
    except CatchupError as e:
        print(f"verification FAILED: {e}", file=sys.stderr)
        return 1
    print(f"verified {len(headers)} headers through checkpoint "
          f"{has.current_ledger}; tip hash {headers[-1].hash.hex()}")
    return 0


def cmd_report_last_history_checkpoint(args) -> int:
    from ..history.archive import make_archive
    archive = make_archive(args.archive)
    has = archive.get_state()
    if has is None:
        print("archive has no HAS", file=sys.stderr)
        return 1
    print(has.to_json())
    return 0


def cmd_offline_info(args) -> int:
    """Info from durable state without joining the network (reference:
    `stellar-core offline-info`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg, listen=False)
    print(json.dumps({"info": app.info()}, indent=1))
    app.stop()
    return 0


_XDR_TYPES = {
    "tx-envelope": "TransactionEnvelope",
    "tx-result": "TransactionResult",
    "ledger-header": "LedgerHeader",
    "ledger-entry": "LedgerEntry",
    "scp-envelope": "SCPEnvelope",
    "stellar-message": "StellarMessage",
    "bucket-entry": "BucketEntry",
}


def _xdr_to_jsonable(val):
    """Structural dump of any decoded XDR value (reference: XDRCereal
    XDR→JSON printing)."""
    import enum as _enum
    from ..xdr import codec as C
    if isinstance(val, bytes):
        return val.hex()
    if isinstance(val, _enum.IntEnum):
        return val.name
    if isinstance(val, (int, str, bool)) or val is None:
        return val
    if isinstance(val, list):
        return [_xdr_to_jsonable(v) for v in val]
    if hasattr(val, "_spec"):   # struct
        return {f: _xdr_to_jsonable(getattr(val, f))
                for f, _ in val._spec}
    if hasattr(val, "switch"):  # union
        return {"type": _xdr_to_jsonable(val.switch),
                "value": _xdr_to_jsonable(val.value)}
    return repr(val)


def cmd_print_xdr(args) -> int:
    """Decode one XDR value from a file (reference: `stellar-core
    print-xdr`)."""
    from .. import xdr as X
    cls = getattr(X, _XDR_TYPES[args.filetype])
    with open(args.path, "rb") as f:
        data = f.read()
    if args.base64:
        import base64
        data = base64.b64decode(data)
    val = cls.from_xdr(data)
    print(json.dumps(_xdr_to_jsonable(val), indent=1))
    return 0


def cmd_dump_xdr(args) -> int:
    """Decode a stream of length-prefixed XDR records (an archive .xdr
    file) (reference: `stellar-core dump-xdr`)."""
    import gzip
    from .. import xdr as X
    from ..history.archive import unpack_xdr_stream
    cls = getattr(X, _XDR_TYPES[args.filetype])
    adapter = cls._xdr_adapter()
    with open(args.path, "rb") as f:
        data = f.read()
    if args.path.endswith(".gz"):
        data = gzip.decompress(data)
    n = 0
    for rec in unpack_xdr_stream(data):
        val = adapter.unpack(rec)
        print(json.dumps(_xdr_to_jsonable(val)))
        n += 1
    print(f"# {n} records", file=sys.stderr)
    return 0


def cmd_tsdump(args) -> int:
    """Summarize a persisted time-series dump (util/timeseries crash
    artifact): per-series point counts and last values, or the raw
    points of one series with --metric."""
    from ..util.timeseries import load_dump
    try:
        doc = load_dump(args.path)
    except (OSError, ValueError) as exc:
        print(f"tsdump: {exc}", file=sys.stderr)
        return 1
    series = doc["series"]
    if args.metric:
        points = series.get(args.metric)
        if points is None:
            print(f"tsdump: no series {args.metric!r} in dump "
                  f"(has {len(series)})", file=sys.stderr)
            return 1
        for p in points:
            if p["seq"] > args.since:
                print(json.dumps(p))
        return 0
    rows = []
    for name in sorted(series):
        points = [p for p in series[name] if p["seq"] > args.since]
        if not points:
            continue
        last = points[-1]
        rows.append({"metric": name, "points": len(points),
                     "first_seq": points[0]["seq"],
                     "last_seq": last["seq"], "last": last["v"]})
    print(json.dumps({"kind": doc.get("kind"),
                      "reason": doc.get("reason"),
                      "cadence_s": doc.get("cadence_s"),
                      "next_since": doc.get("next_since"),
                      "series": rows}, indent=2))
    return 0


def cmd_diag_bucket_stats(args) -> int:
    """Per-level bucket statistics (reference: `stellar-core
    diag-bucket-stats` — entry counts by type and size per level)."""
    cfg = _load_config(args)
    from .. import xdr as X
    from .application import Application
    app = Application(cfg, listen=False)
    bl = app.lm.bucket_list
    bl.resolve_all_merges()
    out = []
    totals = {"entries": 0, "bytes": 0}
    for i, lvl in enumerate(bl.levels):
        row = {"level": i}
        for attr in ("curr", "snap"):
            b = getattr(lvl, attr)
            by_type: dict = {}
            for be in b.entries:
                if be.switch == X.BucketEntryType.DEADENTRY:
                    name = "DEAD"
                else:
                    name = be.value.data.switch.name
                by_type[name] = by_type.get(name, 0) + 1
            blob = b.serialize()
            row[attr] = {
                "hash": b.hash().hex(),
                "entries": len(b.entries),
                "bytes": len(blob),
                "by_type": by_type,
            }
            totals["entries"] += len(b.entries)
            totals["bytes"] += len(blob)
        out.append(row)
    print(json.dumps({"ledger": app.lm.last_closed_ledger_seq,
                      "levels": out, "totals": totals}, indent=2))
    app.stop()
    return 0


def cmd_dump_ledger(args) -> int:
    """Dump live ledger entries from durable state (reference:
    `stellar-core dump-ledger`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg, listen=False)
    snap = app.lm.bucket_list.snapshot(app.lm.last_closed_ledger_seq)
    n = 0
    for entry in snap.scan():
        print(json.dumps(_xdr_to_jsonable(entry)))
        n += 1
        if args.limit and n >= args.limit:
            break
    print(f"# {n} entries at ledger {snap.ledger_seq}", file=sys.stderr)
    app.stop()
    return 0


def cmd_encode_asset(args) -> int:
    """Print the XDR of an asset (reference: `stellar-core encode-asset`)."""
    from .. import xdr as X
    from ..crypto.keys import PublicKey
    if args.code is None:
        asset = X.Asset.native()
    else:
        if not args.issuer:
            print("--issuer is required with --code", file=sys.stderr)
            return 1
        from ..testutils import make_asset
        issuer = X.AccountID.ed25519(
            PublicKey.from_strkey(args.issuer).ed25519)
        asset = make_asset(args.code, issuer)
    print(asset.to_xdr().hex())
    return 0


def cmd_sign_transaction(args) -> int:
    """Add a signature to a transaction-envelope XDR file; the seed comes
    from stdin (reference: `stellar-core sign-transaction`)."""
    from .. import xdr as X
    from ..crypto.keys import SecretKey
    from ..crypto.sha import sha256
    from ..transactions.frame import TransactionFrame
    with open(args.path, "rb") as f:
        env = X.TransactionEnvelope.from_xdr(f.read())
    seed = sys.stdin.readline().strip()
    sk = SecretKey.from_strkey_seed(seed)
    nid = sha256(args.netid.encode())
    frame = TransactionFrame(nid, env)
    env.value.signatures.append(X.DecoratedSignature(
        hint=sk.public_key.hint(),
        signature=sk.sign(frame.content_hash())))
    out = env.to_xdr()
    if args.output:
        with open(args.output, "wb") as f:
            f.write(out)
    else:
        print(out.hex())
    return 0


def cmd_convert_id(args) -> int:
    """Print every representation of a node/account id (reference:
    `stellar-core convert-id`)."""
    from ..crypto.keys import PublicKey
    ident = args.ident
    raw = None
    if ident.startswith("G") and len(ident) == 56:
        raw = PublicKey.from_strkey(ident).ed25519
    else:
        try:
            raw = bytes.fromhex(ident)
        except ValueError:
            pass
    if raw is None or len(raw) != 32:
        print("unrecognized id (want G... strkey or 64 hex chars)",
              file=sys.stderr)
        return 1
    print(json.dumps({
        "hex": raw.hex(),
        "strkey": PublicKey(raw).to_strkey(),
    }, indent=1))
    return 0


def cmd_http_command(args) -> int:
    """Send a command to a running node's admin port (reference:
    `stellar-core http-command`)."""
    import urllib.request
    cfg = _load_config(args)
    cmd = args.cmd if args.cmd.startswith("/") else "/" + args.cmd
    url = f"http://127.0.0.1:{cfg.HTTP_PORT}{cmd}"
    with urllib.request.urlopen(url, timeout=10) as resp:
        print(resp.read().decode())
    return 0


def cmd_health(args) -> int:
    """Probe a running node's /health; exit 0 when ok, 1 when degraded
    or unreachable — the CLI form of the load-balancer probe (wire it
    into systemd watchdogs / container healthchecks).

    ``--retries N --interval S`` turns the one-shot probe into a
    poll-to-readiness loop: up to N re-probes, S seconds apart, exiting 0
    the first time the node answers healthy.  This is how the fleet
    harness (and an operator's deploy script) waits for a booting or
    rejoining node instead of hand-rolling sleep loops."""
    import time as _t
    import urllib.error
    import urllib.request
    cfg = _load_config(args)
    url = f"http://127.0.0.1:{cfg.HTTP_PORT}/health"
    body, code = "", 0
    for attempt in range(args.retries + 1):
        if attempt:
            _t.sleep(args.interval)
        try:
            with urllib.request.urlopen(url, timeout=args.timeout) as resp:
                body = resp.read().decode()
                code = resp.status
        except urllib.error.HTTPError as e:
            body = e.read().decode()
            code = e.code
        except (urllib.error.URLError, OSError) as e:
            body = json.dumps({"status": "unreachable", "detail": str(e)})
            code = 0
        if code == 200:
            break
    print(body)
    return 0 if code == 200 else 1


def cmd_fuzz(args) -> int:
    """Run a deterministic fuzz campaign (reference: `stellar-core fuzz`
    over FuzzerImpl)."""
    from ..fuzz import OverlayFuzzer, TransactionFuzzer, fuzz_xdr_roundtrip
    if args.mode == "tx":
        crashes = TransactionFuzzer(seed=args.seed).run(args.iters)
    elif args.mode == "overlay":
        crashes = OverlayFuzzer(seed=args.seed).run(args.iters)
    else:
        crashes = fuzz_xdr_roundtrip(seed=args.seed, iters=args.iters)
    print(f"{args.mode} fuzz: {args.iters} cases, {len(crashes)} findings")
    for c in crashes[:20]:
        print(f"  {c}")
    return 1 if crashes else 0


def cmd_gen_fuzz(args) -> int:
    """Write a seed corpus of random XDR inputs (reference: `stellar-core
    gen-fuzz`)."""
    import os
    import random as _random
    from .. import xdr as X
    from ..fuzz import random_xdr_value
    os.makedirs(args.output, exist_ok=True)
    rng = _random.Random(args.seed)
    cls = {"tx": X.TransactionEnvelope,
           "overlay": X.StellarMessage}[args.mode]
    n = 0
    for i in range(args.count):
        val = random_xdr_value(cls, rng)
        try:
            blob = val.to_xdr()
        except Exception:  # corelint: disable=exception-hygiene -- unencodable fuzz variants are skipped by design
            continue
        with open(os.path.join(args.output,
                               f"fuzz-{args.mode}-{i:04d}.xdr"), "wb") as f:
            f.write(blob)
        n += 1
    print(f"wrote {n} corpus files to {args.output}")
    return 0


def cmd_apply_load(args) -> int:
    """Max-TPS apply benchmark without consensus (reference:
    `stellar-core apply-load` / ApplyLoad)."""
    from ..simulation.apply_load import ApplyLoad
    al = ApplyLoad(n_accounts=args.accounts)
    report = al.run(n_ledgers=args.ledgers, txs_per_ledger=args.txs)
    print(json.dumps(report, indent=1))
    return 0


def cmd_fleet(args) -> int:
    """Multi-process TCP network soak (simulation/fleet.py): provision N
    real nodes, drive surge-priced traffic through real /tx, execute the
    production-event schedule (kill + `catchup --parallel` rejoin,
    partition + heal, rolling config change) and assert the SLOs.
    Prints the fleet report as one JSON document; exit 0 only when every
    SLO held.  ``--schedule`` takes a JSON event file (see README §Fleet
    soak for the format); without it the standard acceptance script
    runs."""
    import tempfile
    from ..simulation.fleet import (FleetSLOs, run_fleet_soak,
                                    standard_schedule)
    workdir = args.workdir or tempfile.mkdtemp(prefix="fleet-")
    schedule = None
    if args.schedule:
        with open(args.schedule) as f:
            schedule = json.load(f)
        if args.traffic != 25.0:
            # an explicit schedule owns its own `traffic` events
            print("note: --traffic is ignored with --schedule (the "
                  "file's traffic events govern the offered rate)",
                  file=sys.stderr)
    slos = FleetSLOs()
    if args.max_retracking_s is not None:
        slos.max_retracking_s = args.max_retracking_s
    report = run_fleet_soak(
        workdir, n_nodes=args.nodes, schedule=schedule,
        traffic_rate=args.traffic, n_accounts=args.accounts, slos=slos,
        native_close_differential=args.native_differential,
        timeout_s=args.timeout)
    print(json.dumps(report, indent=1))
    return 0 if report["passed"] else 2


def cmd_test(args) -> int:
    """Run the test suite (reference: `stellar-core test` — Catch2 in the
    binary; here it delegates to pytest on the repo's tests/)."""
    import subprocess
    cmd = [sys.executable, "-m", "pytest"] + (args.pytest_args or ["-q"])
    return subprocess.call(cmd)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="stellar-core-tpu",
        description="TPU-native stellar-core node")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("run", help="run the node")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_run)

    s = sub.add_parser("new-db", help="initialize a fresh database")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_new_db)

    s = sub.add_parser("catchup", help="catch up from a history archive")
    s.add_argument("--conf", required=True)
    s.add_argument("--archive", default="")
    s.add_argument("--to", type=int, default=None,
                   help="alias of --at as a plain ledger number")
    s.add_argument("--at", default="",
                   help="target ledger, or 'current' for the archive tip")
    s.add_argument("--count", type=int, default=None,
                   help="replay only the last COUNT ledgers; buckets "
                        "cover the rest (CATCHUP_RECENT)")
    s.add_argument("--mode", choices=["complete", "minimal"],
                   default="complete")
    s.add_argument("--parallel", type=int, default=0, metavar="N",
                   help="replay as N concurrent checkpoint ranges stitched "
                        "by assume-state (0 = config "
                        "CATCHUP_PARALLEL_WORKERS)")
    s.add_argument("--mesh-devices", type=int, default=-1, metavar="D",
                   help="pin range workers round-robin to D accelerator "
                        "devices via per-worker visible-device env "
                        "(-1 = config CATCHUP_MESH_DEVICES; 0 = off)")
    s.add_argument("--no-steal", action="store_true",
                   help="disable checkpoint-granular work stealing "
                        "between range workers")
    s.set_defaults(fn=cmd_catchup)

    s = sub.add_parser("catchup-range",
                       help="one range worker of a parallel catchup "
                            "(writes a JSON stitch record)")
    s.add_argument("--archive", required=True)
    s.add_argument("--passphrase", required=True)
    s.add_argument("--to", type=int, required=True,
                   help="last ledger of the range")
    s.add_argument("--seed-checkpoint", default="genesis",
                   help="checkpoint boundary to assume-state from, or "
                        "'genesis'")
    s.add_argument("--workdir", required=True,
                   help="range-private dir (BucketListDB store + persisted "
                        "state)")
    s.add_argument("--result", required=True,
                   help="path for the JSON stitch record")
    s.add_argument("--index", type=int, default=0)
    s.add_argument("--persist", action="store_true",
                   help="durably persist the final state into --workdir")
    s.add_argument("--persist-target", type=int, default=0,
                   help="persist only when the replay actually ends at "
                        "this ledger (work stealing: whichever worker "
                        "reaches the catchup target owns the adoptable "
                        "state)")
    s.add_argument("--ctl-dir", default="",
                   help="control dir for progress heartbeats + steal "
                        "limit/ack handshake (survives retry wipes of "
                        "--workdir)")
    s.add_argument("--accel", choices=["tpu", "none"], default="none")
    s.add_argument("--accel-chunk", type=int, default=8192)
    s.add_argument("--accel-profile",
                   choices=["poll", "race", "sig-only"], default="",
                   help="preverify offload profile (default: poll — the "
                        "device is never waited on)")
    s.add_argument("--native", choices=["auto", "on", "off"],
                   default="auto")
    s.add_argument("--invariant", action="append", default=[],
                   help="INVARIANT_CHECKS pattern (repeatable); forces "
                        "the Python apply path like the single stream")
    s.add_argument("--in-memory", action="store_true",
                   help="IN_MEMORY_LEDGER mode (no range-private "
                        "BucketListDB store)")
    s.add_argument("--entry-cache-size", type=int, default=0,
                   help="BUCKETLISTDB_ENTRY_CACHE_SIZE (0 = default)")
    s.add_argument("--resident-levels", type=int, default=-1,
                   help="BUCKET_RESIDENT_LEVELS (-1 = default)")
    s.add_argument("--checkpoint-frequency", type=int, default=0,
                   help="checkpoint cadence of the archive's network "
                        "(0 = the default 64)")
    s.set_defaults(fn=cmd_catchup_range)

    s = sub.add_parser("publish", help="publish queued checkpoints")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_publish)

    s = sub.add_parser("check-quorum-intersection",
                       help="check a quorum map JSON for intersection")
    s.add_argument("json_path")
    s.set_defaults(fn=cmd_check_quorum_intersection)

    s = sub.add_parser("new-hist", help="initialize history archives")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_new_hist)

    s = sub.add_parser("self-check", help="verify durable state integrity")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_self_check)

    s = sub.add_parser("verify-checkpoints",
                       help="verify an archive's header hash chain")
    s.add_argument("--archive", required=True)
    s.add_argument("--conf", default="")
    s.set_defaults(fn=cmd_verify_checkpoints)

    s = sub.add_parser("report-last-history-checkpoint",
                       help="print an archive's HAS")
    s.add_argument("--archive", required=True)
    s.set_defaults(fn=cmd_report_last_history_checkpoint)

    s = sub.add_parser("offline-info", help="node info from durable state")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_offline_info)

    s = sub.add_parser("print-xdr", help="decode one XDR value from a file")
    s.add_argument("path")
    s.add_argument("--filetype", choices=sorted(_XDR_TYPES),
                   default="tx-envelope")
    s.add_argument("--base64", action="store_true")
    s.set_defaults(fn=cmd_print_xdr)

    s = sub.add_parser("dump-xdr", help="decode an XDR stream file")
    s.add_argument("path")
    s.add_argument("--filetype", choices=sorted(_XDR_TYPES),
                   default="ledger-header")
    s.set_defaults(fn=cmd_dump_xdr)

    s = sub.add_parser("tsdump", help="summarize a time-series dump file")
    s.add_argument("path")
    s.add_argument("--metric", default="",
                   help="print the raw points of ONE series")
    s.add_argument("--since", type=int, default=0,
                   help="only points with capture seq > SINCE")
    s.set_defaults(fn=cmd_tsdump)

    s = sub.add_parser("dump-ledger", help="dump live ledger entries")
    s.add_argument("--conf", required=True)
    s.add_argument("--limit", type=int, default=0)
    s.set_defaults(fn=cmd_dump_ledger)

    s = sub.add_parser("diag-bucket-stats",
                       help="per-level bucket entry/size statistics")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_diag_bucket_stats)

    s = sub.add_parser("encode-asset", help="print an asset's XDR")
    s.add_argument("--code", default=None)
    s.add_argument("--issuer", default=None)
    s.set_defaults(fn=cmd_encode_asset)

    s = sub.add_parser("sign-transaction",
                       help="sign a tx-envelope XDR file (seed on stdin)")
    s.add_argument("path")
    s.add_argument("--netid", required=True,
                   help="network passphrase")
    s.add_argument("--output", default="")
    s.set_defaults(fn=cmd_sign_transaction)

    s = sub.add_parser("convert-id", help="print id representations")
    s.add_argument("ident")
    s.set_defaults(fn=cmd_convert_id)

    s = sub.add_parser("http-command",
                       help="send a command to a running node")
    s.add_argument("cmd")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_http_command)

    s = sub.add_parser("health",
                       help="probe a running node's /health (exit 0=ok)")
    s.add_argument("--conf", required=True)
    s.add_argument("--timeout", type=float, default=5.0)
    s.add_argument("--retries", type=int, default=0,
                   help="re-probe up to N times until healthy (poll a "
                        "booting node to readiness)")
    s.add_argument("--interval", type=float, default=1.0,
                   help="seconds between probes with --retries")
    s.set_defaults(fn=cmd_health)

    s = sub.add_parser("fuzz", help="run a deterministic fuzz campaign")
    s.add_argument("--mode", choices=["tx", "overlay", "xdr"], default="tx")
    s.add_argument("--seed", type=int, default=0)
    s.add_argument("--iters", type=int, default=500)
    s.set_defaults(fn=cmd_fuzz)

    s = sub.add_parser("gen-fuzz", help="write a fuzz seed corpus")
    s.add_argument("--mode", choices=["tx", "overlay"], default="tx")
    s.add_argument("--output", required=True)
    s.add_argument("--count", type=int, default=100)
    s.add_argument("--seed", type=int, default=0)
    s.set_defaults(fn=cmd_gen_fuzz)

    s = sub.add_parser("apply-load",
                       help="max-TPS apply benchmark (no consensus)")
    s.add_argument("--accounts", type=int, default=1000)
    s.add_argument("--ledgers", type=int, default=20)
    s.add_argument("--txs", type=int, default=200)
    s.set_defaults(fn=cmd_apply_load)

    s = sub.add_parser("fleet",
                       help="multi-process TCP network soak with SLO "
                            "assertions")
    s.add_argument("--nodes", type=int, default=5)
    s.add_argument("--workdir", default="",
                   help="artifact dir (default: fresh temp dir; holds "
                        "per-node logs/configs + fleet-report.json)")
    s.add_argument("--schedule", default="",
                   help="JSON event-schedule file (default: the standard "
                        "kill/rejoin + partition/heal + rolling-config "
                        "script)")
    s.add_argument("--traffic", type=float, default=25.0,
                   help="offered tx/s across the fleet")
    s.add_argument("--accounts", type=int, default=60,
                   help="seed-derived traffic accounts")
    s.add_argument("--timeout", type=float, default=600.0,
                   help="hard wall-clock bound for the schedule")
    s.add_argument("--max-retracking-s", type=float, default=None,
                   help="SLO: kill -> tracking-again budget")
    s.add_argument("--native-differential", type=int, default=8,
                   help="NATIVE_CLOSE_DIFFERENTIAL cadence provisioned "
                        "into every node: each Nth live close is "
                        "spot-checked against the Python oracle "
                        "(0 = off)")
    s.set_defaults(fn=cmd_fleet)

    s = sub.add_parser("test", help="run the test suite (pytest)")
    s.add_argument("pytest_args", nargs="*")
    s.set_defaults(fn=cmd_test)

    s = sub.add_parser("sec-to-pub", help="seed strkey -> public strkey")
    s.add_argument("seed", help="S... seed, or - to read from stdin")
    s.set_defaults(fn=cmd_sec_to_pub)

    s = sub.add_parser("gen-seed", help="generate a random node seed")
    s.set_defaults(fn=cmd_gen_seed)

    s = sub.add_parser("version", help="print version")
    s.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # stdout piped into a consumer that closed early (| head) — exit
        # quietly like any well-behaved unix tool
        import os
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
