"""Command line: run / new-db / catchup / publish /
check-quorum-intersection / sec-to-pub / version.

Reference: src/main/CommandLine.{h,cpp} — the stellar-core subcommand
surface, minus the ones whose subsystems don't exist here yet.
"""

from __future__ import annotations

import argparse
import json
import sys

from .config import Config


def _load_config(args) -> Config:
    return Config.from_toml(args.conf)


def cmd_version(args) -> int:
    from .application import VERSION
    print(VERSION)
    return 0


def cmd_sec_to_pub(args) -> int:
    from ..crypto.keys import SecretKey
    seed = sys.stdin.readline().strip() if args.seed == "-" else args.seed
    print(SecretKey.from_strkey_seed(seed).public_key.to_strkey())
    return 0


def cmd_gen_seed(args) -> int:
    from ..crypto.keys import SecretKey
    sk = SecretKey.random()
    print(json.dumps({"secret": sk.to_strkey_seed(),
                      "public": sk.public_key.to_strkey()}))
    return 0


def cmd_new_db(args) -> int:
    """Initialize a fresh database at the config's DATABASE path
    (reference: `stellar-core new-db`)."""
    cfg = _load_config(args)
    if not cfg.DATABASE:
        print("config has no DATABASE path", file=sys.stderr)
        return 1
    import os
    for path in (cfg.DATABASE, cfg.DATABASE + "-wal", cfg.DATABASE + "-shm"):
        if os.path.exists(path):
            os.unlink(path)
    from .application import Application
    app = Application(cfg, listen=False)
    print(f"new database at {cfg.DATABASE}, genesis ledger "
          f"{app.lm.last_closed_ledger_seq} hash {app.lm.lcl_hash.hex()}")
    app.stop()
    return 0


def cmd_run(args) -> int:
    """Run the node (reference: `stellar-core run`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg)
    import signal

    def shutdown(signum, frame):
        app.stop()

    signal.signal(signal.SIGINT, shutdown)
    signal.signal(signal.SIGTERM, shutdown)
    app.start()
    app.run()
    return 0


def cmd_catchup(args) -> int:
    """Catch up from a history archive (reference: `stellar-core catchup`)."""
    cfg = _load_config(args)
    from ..history.archive import FileHistoryArchive
    from .application import Application

    archive_path = args.archive
    if not archive_path:
        if not cfg.HISTORY:
            print("no archive configured or given", file=sys.stderr)
            return 1
        archive_path = cfg.HISTORY[0].get_path or cfg.HISTORY[0].put_path
    archive = FileHistoryArchive(archive_path)
    from ..catchup.catchup import CatchupManager
    cm = CatchupManager(cfg.network_id(), cfg.NETWORK_PASSPHRASE,
                        accel=cfg.ACCEL == "tpu",
                        accel_chunk=cfg.ACCEL_CHUNK_SIZE)
    if args.mode == "minimal":
        lm = cm.catchup_minimal(archive)
    else:
        lm = cm.catchup_complete(archive, to_ledger=args.to)
    print(f"caught up to ledger {lm.last_closed_ledger_seq} "
          f"hash {lm.lcl_hash.hex()}")
    if cfg.DATABASE:
        from ..bucket.manager import BucketDir
        from ..database import Database
        import os
        os.makedirs(os.path.dirname(cfg.DATABASE) or ".", exist_ok=True)
        db = Database(cfg.DATABASE)
        bdir = BucketDir(cfg.BUCKET_DIR_PATH or os.path.join(
            os.path.dirname(cfg.DATABASE) or ".", "buckets"))
        lm.enable_persistence(db, bdir)
        db.close()
        print(f"state persisted to {cfg.DATABASE}")
    return 0


def cmd_publish(args) -> int:
    """Force-publish the current checkpoint window to the configured
    archives (reference: `stellar-core publish`)."""
    cfg = _load_config(args)
    from .application import Application
    app = Application(cfg, listen=False)
    n = app.history.publish_queued_history()
    print(f"published {n} queued checkpoint(s)")
    app.stop()
    return 0


def cmd_check_quorum_intersection(args) -> int:
    """Check quorum intersection of a quorum-map JSON file (reference:
    `stellar-core check-quorum-intersection`)."""
    from ..herder.quorum_intersection import check_intersection
    from ..crypto.keys import PublicKey
    from .. import xdr as X

    with open(args.json_path) as f:
        raw = json.load(f)
    qmap = {}
    for node, spec in raw.items():
        nid = PublicKey.from_strkey(node).ed25519
        qmap[nid] = X.SCPQuorumSet(
            threshold=spec["threshold"],
            validators=[X.NodeID.ed25519(PublicKey.from_strkey(v).ed25519)
                        for v in spec["validators"]],
            innerSets=[])
    res = check_intersection(qmap)
    print("Network enjoys quorum intersection"
          if res.intersects
          else "Network DOES NOT enjoy quorum intersection")
    return 0 if res.intersects else 2


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="stellar-core-tpu",
        description="TPU-native stellar-core node")
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("run", help="run the node")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_run)

    s = sub.add_parser("new-db", help="initialize a fresh database")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_new_db)

    s = sub.add_parser("catchup", help="catch up from a history archive")
    s.add_argument("--conf", required=True)
    s.add_argument("--archive", default="")
    s.add_argument("--to", type=int, default=None)
    s.add_argument("--mode", choices=["complete", "minimal"],
                   default="complete")
    s.set_defaults(fn=cmd_catchup)

    s = sub.add_parser("publish", help="publish queued checkpoints")
    s.add_argument("--conf", required=True)
    s.set_defaults(fn=cmd_publish)

    s = sub.add_parser("check-quorum-intersection",
                       help="check a quorum map JSON for intersection")
    s.add_argument("json_path")
    s.set_defaults(fn=cmd_check_quorum_intersection)

    s = sub.add_parser("sec-to-pub", help="seed strkey -> public strkey")
    s.add_argument("seed", help="S... seed, or - to read from stdin")
    s.set_defaults(fn=cmd_sec_to_pub)

    s = sub.add_parser("gen-seed", help="generate a random node seed")
    s.set_defaults(fn=cmd_gen_seed)

    s = sub.add_parser("version", help="print version")
    s.set_defaults(fn=cmd_version)

    args = p.parse_args(argv)
    return args.fn(args)
