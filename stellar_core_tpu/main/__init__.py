"""Application layer (reference: src/main/)."""

from .config import Config, HistoryArchiveConfig

__all__ = ["Config", "HistoryArchiveConfig"]
