"""Maintainer: periodic garbage collection of old node data.

Reference: src/main/Maintainer.{h,cpp} — on a timer (and via the
`/maintenance` endpoint) deletes aged-out rows (scphistory, txhistory,
superseded headers) outside the retention window, and forgets unreferenced
bucket files.  The publish queue bounds how much may be deleted: history not
yet published must be retained.
"""

from __future__ import annotations

from ..history.archive import checkpoint_frequency
from ..util import logging as slog

log = slog.get("Main")

# reference default: AUTOMATIC_MAINTENANCE_PERIOD=359s / COUNT=400 rows;
# here maintenance is small, so a per-checkpoint cadence is enough
DEFAULT_PERIOD = 300.0
RETAIN_CHECKPOINTS = 2


class Maintainer:
    def __init__(self, app, period: float = DEFAULT_PERIOD):
        self.app = app
        self.period = period
        self._timer = None

    def start(self) -> None:
        from ..util.clock import VirtualTimer
        self._timer = VirtualTimer(self.app.clock)

        def tick() -> None:
            try:
                self.perform_maintenance()
            except Exception as e:  # GC must never take the node down
                log.error("maintenance failed: %s", e)
            self._timer.expires_from_now(self.period, tick)

        self._timer.expires_from_now(self.period, tick)

    def perform_maintenance(self) -> dict:
        """One GC round; returns what was done (also the `/maintenance`
        response payload)."""
        app = self.app
        out = {"removed_buckets": 0, "pruned_below": None}
        if app.database is None:
            return out
        lcl = app.lm.last_closed_ledger_seq
        # never prune past the oldest unpublished checkpoint
        queued = [seq for seq, _ in app.database.publish_queue()]
        floor = min(queued) if queued else lcl
        keep_from = max(2, min(floor, lcl)
                        - RETAIN_CHECKPOINTS * checkpoint_frequency())
        app.database.prune_scp(keep_from)
        app.database.prune_tx_history(keep_from)
        app.database.delete_old_headers(keep_from)
        app.database.commit()
        out["pruned_below"] = keep_from
        if app.bucket_dir is not None:
            out["removed_buckets"] = app.bucket_dir.gc(
                app.lm.bucket_list.referenced_hashes())
        log.info("maintenance: pruned below %d, removed %d bucket files",
                 keep_from, out["removed_buckets"])
        return out
